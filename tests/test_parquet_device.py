"""Device-side Parquet page decode (io/parquet_device.py, VERDICT r4
item 4): the device path must produce exactly what the Arrow host path
produces — values, validity, dtypes — across PLAIN and dictionary
encodings, nullable columns, multiple pages, and every fixed-width
physical type; unsupported shapes must fall back, never corrupt."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.io.parquet import scan_parquet


def _write(tmp_path, table, **kw):
    p = str(tmp_path / "t.parquet")
    pq.write_table(table, p, **kw)
    return p


def _collect(path, **kw):
    return list(scan_parquet(path, **kw))


def _assert_tables_match(a, b):
    assert a.names == b.names
    assert a.row_count == b.row_count
    for name in a.names:
        ca, cb = a[name], b[name]
        assert ca.dtype == cb.dtype, name
        va = (
            np.ones(a.row_count, bool)
            if ca.validity is None
            else np.asarray(ca.validity)
        )
        vb = (
            np.ones(b.row_count, bool)
            if cb.validity is None
            else np.asarray(cb.validity)
        )
        np.testing.assert_array_equal(va, vb, err_msg=f"{name} validity")
        da = np.asarray(ca.data)[va]
        db = np.asarray(cb.data)[vb]
        np.testing.assert_array_equal(da, db, err_msg=f"{name} values")


def _roundtrip_check(tmp_path, atbl, **write_kw):
    p = _write(tmp_path, atbl, **write_kw)
    host = _collect(p)
    dev = _collect(p, device_decode=True)
    assert len(host) == len(dev)
    for h, d in zip(host, dev):
        _assert_tables_match(h, d)


def test_plain_fixed_width_all_types(tmp_path):
    rng = np.random.default_rng(0)
    n = 5000
    _roundtrip_check(
        tmp_path,
        pa.table({
            "i32": rng.integers(-(2**31), 2**31, n).astype(np.int32),
            "i64": rng.integers(-(2**62), 2**62, n),
            "f32": rng.standard_normal(n).astype(np.float32),
            "f64": rng.standard_normal(n),
        }),
        use_dictionary=False,
        compression="NONE",
    )


def test_dictionary_encoded_with_snappy(tmp_path):
    rng = np.random.default_rng(1)
    n = 20_000
    _roundtrip_check(
        tmp_path,
        pa.table({
            "k": rng.integers(0, 500, n),      # dict-friendly
            "v": rng.integers(0, 100, n).astype(np.int32),
        }),
        compression="SNAPPY",
    )


def test_nullable_columns(tmp_path):
    rng = np.random.default_rng(2)
    n = 10_000
    vals = rng.integers(0, 1000, n)
    mask = rng.random(n) < 0.2
    _roundtrip_check(
        tmp_path,
        pa.table({
            "x": pa.array(vals, mask=mask),
            "y": pa.array(rng.standard_normal(n),
                          mask=rng.random(n) < 0.05),
        }),
    )


def test_multiple_pages_and_row_groups(tmp_path):
    rng = np.random.default_rng(3)
    n = 200_000
    _roundtrip_check(
        tmp_path,
        pa.table({"a": rng.integers(0, 50, n),
                  "b": rng.standard_normal(n)}),
        row_group_size=60_000,
        data_page_size=8_000,  # forces many pages per chunk
    )


def test_nullable_dictionary_takes_device_path(tmp_path):
    """Nullable dict columns (the common Spark FK shape) must decode on
    the DEVICE path, not via silent Arrow fallback: the index stream
    holds only defined values, sized by the def-level popcount (r4
    review finding)."""
    import pyarrow.parquet as pqm

    from spark_rapids_jni_tpu.io import parquet_device as pdev

    rng = np.random.default_rng(7)
    n = 15_000
    vals = rng.integers(0, 300, n)
    mask = rng.random(n) < 0.2
    p = _write(tmp_path, pa.table({"x": pa.array(vals, mask=mask)}))
    pf = pqm.ParquetFile(p)
    decoded, fallback = pdev.decode_row_group(p, pf, 0, ["x"])
    assert "x" in decoded and not fallback
    got = np.asarray(decoded["x"].data)
    validity = np.asarray(decoded["x"].validity)
    np.testing.assert_array_equal(validity, ~mask)
    np.testing.assert_array_equal(got[~mask], vals[~mask])


def test_string_column_falls_back(tmp_path):
    """Strings aren't in the device scope: must fall back AND match."""
    rng = np.random.default_rng(4)
    n = 3000
    _roundtrip_check(
        tmp_path,
        pa.table({
            "s": pa.array([f"row{int(i)}" for i in rng.integers(0, 100, n)]),
            "v": rng.integers(0, 10, n),
        }),
    )


def test_decimal_int32_backed(tmp_path):
    """DECIMAL(7,2) stored as parquet INT32 takes the device path."""
    rng = np.random.default_rng(5)
    n = 4000
    cents = rng.integers(0, 10_000, n)
    arr = pa.array(cents / 100.0).cast(pa.decimal128(7, 2))
    _roundtrip_check(
        tmp_path,
        pa.table({"m": arr, "v": rng.integers(0, 9, n)}),
        store_decimal_as_integer=True,
    )


def test_predicate_filter_composes(tmp_path):
    from spark_rapids_jni_tpu.io.predicates import col as C

    rng = np.random.default_rng(6)
    n = 50_000
    p = _write(
        tmp_path,
        pa.table({"q": rng.integers(0, 100, n),
                  "v": rng.standard_normal(n)}),
        row_group_size=10_000,
    )
    pred = C("q") > 60
    host = list(scan_parquet(p, filters=pred))
    dev = list(scan_parquet(p, filters=pred, device_decode=True))
    th = sum(t.row_count for t in host)
    td = sum(t.row_count for t in dev)
    assert th == td
    sh = sum(float(np.asarray(t["v"].to_numpy()).sum()) for t in host)
    sd = sum(float(np.asarray(t["v"].to_numpy()).sum()) for t in dev)
    assert np.isclose(sh, sd)


def test_fuzz_random_schemas_match_host(tmp_path):
    """Randomized tables (dtypes x nulls x compression x page/row-group
    sizes x dict on/off): the device path must byte-match the Arrow
    path on every one — parser robustness for a NEW binary-format
    reader, where a silent one-byte drift corrupts data (the thrift
    skip bug this module already survived). Trial count balances
    coverage against suite wall-clock on the 1-core CI box."""
    rng = np.random.default_rng(2026)
    makers = [
        lambda n: rng.integers(-1000, 1000, n).astype(np.int32),
        lambda n: rng.integers(-(2**60), 2**60, n),
        lambda n: rng.integers(0, 8, n),            # tiny-cardinality dict
        lambda n: rng.standard_normal(n).astype(np.float32),
        lambda n: rng.standard_normal(n),
        lambda n: np.full(n, 7, np.int64),          # single-value RLE runs
    ]
    for trial in range(12):
        n = int(rng.integers(50, 30_000))
        ncols = int(rng.integers(1, 4))
        cols = {}
        for c in range(ncols):
            vals = makers[int(rng.integers(0, len(makers)))](n)
            if rng.random() < 0.5:
                mask = rng.random(n) < float(rng.random()) * 0.5
                cols[f"c{c}"] = pa.array(vals, mask=mask)
            else:
                cols[f"c{c}"] = pa.array(vals)
        kw = {
            "compression": ["NONE", "SNAPPY", "ZSTD"][int(rng.integers(0, 3))],
            "use_dictionary": bool(rng.integers(0, 2)),
            "row_group_size": int(rng.integers(40, max(n, 41))),
            "data_page_size": int(rng.integers(512, 64_000)),
        }
        p = str(tmp_path / f"fuzz{trial}.parquet")
        pq.write_table(pa.table(cols), p, **kw)
        host = _collect(p)
        dev = _collect(p, device_decode=True)
        assert len(host) == len(dev), (trial, kw)
        for h, d in zip(host, dev):
            try:
                _assert_tables_match(h, d)
            except AssertionError as e:
                raise AssertionError(f"trial {trial} {kw}: {e}") from e
