"""Build/CI machinery tests (SURVEY.md C8-C15 parity layer).

The reference's build chain is itself a component (Maven -> Ant ->
CMake, provenance script, submodule guard, CI entry scripts, sync bot).
These tests execute the executable parts and structurally validate the
rest, so the build layer can't rot silently in an image with no
maven/JDK.
"""

import os
import stat
import subprocess
import xml.etree.ElementTree as ET

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, **kw):
    return subprocess.run(
        cmd, capture_output=True, text=True, cwd=REPO, **kw
    )


def test_build_info_emits_provenance():
    out = _run(["bash", "build/build-info", "1.2.3", REPO, "extra=1"])
    assert out.returncode == 0, out.stderr
    props = dict(
        line.split("=", 1) for line in out.stdout.strip().splitlines()
    )
    for key in ["version", "user", "revision", "branch", "date", "url"]:
        assert key in props, f"missing {key}"
    assert props["version"] == "1.2.3"
    assert props["extra"] == "1"
    assert len(props["revision"]) == 40  # a real git sha


def test_build_info_usage_error():
    assert _run(["bash", "build/build-info", "1.2.3"]).returncode == 2


def test_dependency_check_passes_on_pinned_env():
    out = _run(["bash", "build/dependency-check"])
    if out.returncode == 1 and "drifted" in out.stdout:
        # the CHECK works (drift detected and reported) — the container
        # simply doesn't ship the pinned versions. That is an
        # environment gap, not a code bug: skip with the missing
        # dependencies named instead of failing every tier-1 run.
        drifted = "; ".join(
            line.strip()
            for line in out.stdout.splitlines()
            if ": pinned" in line
        )
        pytest.skip(
            "environment drifted from env/requirements-pin.txt "
            f"(pinned versions not installed: {drifted})"
        )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_dependency_check_fails_on_drift(tmp_path):
    bad = tmp_path / "pins.txt"
    bad.write_text("jax==0.0.0\n")
    out = _run(["bash", "build/dependency-check", str(bad)])
    assert out.returncode == 1
    assert "drifted" in out.stdout


def test_dependency_check_skippable(tmp_path):
    bad = tmp_path / "pins.txt"
    bad.write_text("jax==0.0.0\n")
    out = _run(
        ["bash", "build/dependency-check", str(bad)],
        env={**os.environ, "DEPENDENCY_CHECK_SKIP": "true"},
    )
    assert out.returncode == 0


def test_pin_file_covers_core_stack():
    with open(os.path.join(REPO, "env", "requirements-pin.txt")) as f:
        pins = {
            line.split("==")[0]
            for line in f
            if line.strip() and not line.startswith("#")
        }
    assert {"jax", "jaxlib", "numpy", "pyarrow"} <= pins


def test_poms_are_wellformed_and_linked():
    root = ET.parse(os.path.join(REPO, "pom.xml")).getroot()
    ns = {"m": "http://maven.apache.org/POM/4.0.0"}
    modules = [m.text for m in root.findall("m:modules/m:module", ns)]
    assert modules == ["spark-rapids-tpu-runtime", "spark-rapids-tpu-jni"]
    version = root.find("m:version", ns).text
    for mod in modules:
        mroot = ET.parse(os.path.join(REPO, mod, "pom.xml")).getroot()
        parent_ver = mroot.find("m:parent/m:version", ns).text
        assert parent_ver == version, f"{mod}: parent version mismatch"
    # flag plane single source of truth
    props = root.find("m:properties", ns)
    names = {p.tag.split("}")[1] for p in props}
    assert {"CPP_PARALLEL_LEVEL", "SRT_WERROR", "TPU_PLATFORM",
            "native.build.configure", "dependency.check.skip"} <= names


def test_ci_settings_xml_wellformed():
    ET.parse(os.path.join(REPO, "ci", "settings.xml"))


def test_shell_scripts_parse_and_are_executable():
    scripts = [
        "build/build-info",
        "build/dependency-check",
        "spark-rapids-tpu-runtime/build-native.sh",
        "ci/premerge-build.sh",
        "ci/nightly-build.sh",
        "ci/deploy.sh",
        "ci/dependency-sync.sh",
    ]
    for s in scripts:
        path = os.path.join(REPO, s)
        assert os.path.exists(path), f"missing {s}"
        out = _run(["bash", "-n", path])
        assert out.returncode == 0, f"{s}: syntax error: {out.stderr}"


def test_workflows_parse():
    yaml = pytest.importorskip("yaml")
    wf_dir = os.path.join(REPO, ".github", "workflows")
    names = set(os.listdir(wf_dir))
    assert {"premerge.yml", "dependency-sync.yml", "auto-merge.yml",
            "signoff-check.yml"} <= names
    for f in names:
        with open(os.path.join(wf_dir, f)) as fh:
            doc = yaml.safe_load(fh)
        assert "jobs" in doc, f"{f}: no jobs"


def test_configure_once_discipline():
    """build-native.sh must not reconfigure when CMakeCache.txt exists
    (the build-libcudf.xml:23-30 behavior) — checked by running it
    against the existing build tree and asserting no configure ran."""
    cache = os.path.join(REPO, "build", "CMakeCache.txt")
    if not os.path.exists(cache):
        pytest.skip("no configured build tree")
    before = os.path.getmtime(cache)
    out = _run(["bash", "spark-rapids-tpu-runtime/build-native.sh"])
    assert out.returncode == 0, out.stderr
    assert os.path.getmtime(cache) == before, "reconfigured needlessly"
