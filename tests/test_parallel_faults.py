"""Chaos matrix for distributed fault tolerance (ISSUE 15).

The contract under test, on the 8-device CPU mesh: seeded
``shuffle``/``collective``/``mesh``-site faults provoke transient,
permanent, and dead-slice failures inside the exchange launches, and the
plane recovers with results BYTE-IDENTICAL to a faults-off run —
lineage replay re-runs only the failed exchange, donated inputs are
at-most-once (zero retries, a ``shuffle.giveups`` bump), and persistent
collective failure walks the ``MeshRunner`` ladder down to the
surviving device count (8 -> 4 -> 2 -> 1) with parity preserved at
every rung because row-local mesh plans are mesh-size independent. At
the floor a typed ``Degraded`` falls the plan back to the single-device
exact path — a mesh-backed serving session degrades, it does not shed
the tenant. The disabled injection gate stays under 5 µs per call.
"""

import time

import jax
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plan as plan_mod
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu import parallel
from spark_rapids_jni_tpu.column import Table
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
from spark_rapids_jni_tpu.utils import config, faults, metrics

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

BOUNDARY_SIZES = (1023, 1024, 1025)

# row-local chain: the mesh path shards it as contiguous row blocks
ROW_LOCAL_CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
]

# ends in a global op: the mesh path must decline it (MeshUnsupported)
GLOBAL_CHAIN = [
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]

# exchange boundary mid-chain (ISSUE 17): the mesh path must run
# scan-side chain -> counts pass -> ragged all-to-all -> merge-side
# chain as ONE replayable stage
PARTITION_CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "partition", "kind": "hash", "keys": [0], "num": 16},
    {"op": "cast", "column": 0, "type_id": F64},
]

CHAOS_FLAGS = (
    "FAULTS", "RETRY_MAX", "RETRY_BASE_MS", "MESH_PROBE_S",
    "SKEW_SPLIT", "SKEW_SPLIT_FACTOR",
)


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    for name in CHAOS_FLAGS + ("BUCKETS", "METRICS"):
        config.clear_flag(name)


@pytest.fixture
def mesh():
    return parallel.make_mesh(8)


def _plan_table(n: int, seed: int = 0) -> Table:
    rng = np.random.default_rng(n + seed)
    return Table.from_pydict({
        "x": rng.integers(-50, 50, n, dtype=np.int64),
        "m": rng.integers(0, 3, n, dtype=np.int64) > 0,
    })


def _tbl(t: Table):
    """Byte-comparable logical view. The exact path may hand back a
    padded table carrying ``logical_rows`` (the wire layer slices it);
    the mesh path gathers the exact prefix — compare logical content."""
    n = int(t.logical_row_count)
    cols = []
    for c in t.columns:
        data = np.asarray(c.data)
        cols.append((
            str(data.dtype),
            data[:n].tolist(),
            None if c.validity is None
            else np.asarray(c.validity)[:n].tolist(),
        ))
    return (n, cols)


def _shuffle_multiset(out, occ):
    """Order-free content of a shuffled table: (k, v) multiset."""
    occ_np = np.asarray(occ)
    got_k = np.asarray(out["k"].data)[occ_np]
    got_v = np.asarray(out["v"].data)[occ_np]
    return sorted(zip(got_k.tolist(), got_v.tolist()))


def _counter(name: str) -> int:
    return int(metrics.snapshot()["counters"].get(name, 0))


# ---------------------------------------------------------------------------
# site registration + the disabled-path cost gate
# ---------------------------------------------------------------------------


class TestChaosSites:
    def test_distributed_sites_registered(self):
        assert {"shuffle", "collective", "mesh"} <= set(faults.SITES)

    def test_disabled_inject_under_five_microseconds(self):
        iters = 20_000
        for site in ("shuffle", "collective", "mesh"):
            faults.inject(site)  # warm the gate
            t0 = time.perf_counter()
            for _ in range(iters):
                faults.inject(site)
            per = (time.perf_counter() - t0) / iters
            assert per < 5e-6, f"{site}: {per * 1e6:.2f}us per call"


# ---------------------------------------------------------------------------
# satellite: overflow errors flow through the taxonomy as permanent
# ---------------------------------------------------------------------------


class TestOverflowClassification:
    @pytest.mark.parametrize("exc_cls", (
        parallel.ShuffleOverflowError,
        parallel.JoinOverflowError,
        parallel.GroupOverflowError,
    ))
    def test_overflow_is_typed_permanent(self, exc_cls):
        e = exc_cls("capacity 16 overflowed")
        # still a RuntimeError for pre-taxonomy callers
        assert isinstance(e, RuntimeError)
        assert isinstance(e, faults.PermanentError)
        assert not faults.retryable_class(faults.classify(e))

    def test_undersized_shuffle_not_retried(self, mesh):
        config.set_flag("METRICS", "1")
        before = _counter("shuffle.retries")
        t = Table.from_pydict({"k": np.full(128, 7, dtype=np.int64),
                               "v": np.arange(128, dtype=np.int64)})
        with pytest.raises(parallel.ShuffleOverflowError):
            parallel.shuffle_table(t, ["k"], mesh, capacity=8)
        assert _counter("shuffle.retries") == before


# ---------------------------------------------------------------------------
# satellite: loud-fail validation in mesh construction + sharding
# ---------------------------------------------------------------------------


class TestLoudFailValidation:
    def test_make_mesh_names_shape_and_remedy(self):
        with pytest.raises(ValueError) as ei:
            parallel.make_mesh(1024)
        msg = str(ei.value)
        assert "1024" in msg and "XLA_FLAGS" in msg

    def test_make_mesh_rejects_zero(self):
        with pytest.raises(ValueError):
            parallel.make_mesh(0)

    def test_shard_table_names_axis_and_remedy(self, mesh):
        t = Table.from_pydict({"k": np.arange(13, dtype=np.int64)})
        with pytest.raises(ValueError) as ei:
            parallel.shard_table(t, mesh)
        msg = str(ei.value)
        assert "shuffle" in msg and "divisible" in msg and "13" in msg


# ---------------------------------------------------------------------------
# seeded chaos on the shuffle exchange: replay parity, typed permanents,
# at-most-once for donated inputs
# ---------------------------------------------------------------------------


class TestShuffleChaos:
    def test_transient_replays_to_parity(self, mesh):
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        n = 1024
        rng = np.random.default_rng(7)
        t = Table.from_pydict({
            "k": rng.integers(0, 60, n, dtype=np.int64),
            "v": rng.integers(-100, 100, n, dtype=np.int64),
        })
        out, occ, _ = parallel.shuffle_table(t, ["k"], mesh, capacity=n)
        want = _shuffle_multiset(out, occ)
        before = _counter("shuffle.retries")
        config.set_flag("FAULTS", "seed=11,shuffle:transient:1:2")
        out, occ, _ = parallel.shuffle_table(t, ["k"], mesh, capacity=n)
        assert _shuffle_multiset(out, occ) == want
        assert faults.injection_stats()["shuffle:transient"]["injected"] == 2
        assert _counter("shuffle.retries") - before >= 2

    def test_permanent_surfaces_typed_without_retry(self, mesh):
        config.set_flag("METRICS", "1")
        before = _counter("shuffle.retries")
        config.set_flag("FAULTS", "shuffle:permanent:1:1")
        t = Table.from_pydict({"k": np.arange(256, dtype=np.int64),
                               "v": np.arange(256, dtype=np.int64)})
        with pytest.raises(faults.PermanentError):
            parallel.shuffle_table(t, ["k"], mesh, capacity=256)
        assert _counter("shuffle.retries") == before

    def test_donated_input_is_at_most_once(self, mesh):
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        n = 512
        t = Table.from_pydict({"k": np.arange(n, dtype=np.int64),
                               "v": np.arange(n, dtype=np.int64)})
        retries = _counter("shuffle.retries")
        giveups = _counter("shuffle.giveups")
        config.set_flag("FAULTS", "seed=1,shuffle:transient:1:1")
        with pytest.raises(faults.TransientDeviceError):
            parallel.shuffle_table(
                t, ["k"], mesh, capacity=n, donate_input=True
            )
        # the first transient surfaced: ZERO replays of consumed buffers
        assert _counter("shuffle.retries") == retries
        assert _counter("shuffle.giveups") - giveups >= 1
        # fault-free donated run still works and stays lossless
        config.set_flag("FAULTS", "")
        out, occ, overflow = parallel.shuffle_table(
            t, ["k"], mesh, capacity=n, donate_input=True
        )
        assert int(np.asarray(overflow).max()) <= 0
        assert int(np.asarray(occ).sum()) == n

    def test_collective_faults_inside_groupby_recover(self, mesh):
        config.set_flag("RETRY_BASE_MS", "1")
        config.set_flag("FAULTS", "seed=13,collective:transient:1:2")
        n = 1024
        rng = np.random.default_rng(13)
        k = rng.integers(0, 40, n, dtype=np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        t = Table.from_pydict({"k": k, "v": v})
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("v", "sum")], mesh,
        )
        assert int(np.asarray(overflow).max()) <= 0
        got = {}
        ks = np.asarray(agg["k"].data).reshape(8, -1)
        sums = np.asarray(agg["sum_v"].data).reshape(8, -1)
        counts = np.asarray(ngroups)
        for d in range(8):
            for i in range(counts[d]):
                got[int(ks[d, i])] = int(sums[d, i])
        want = {int(u): int(v[k == u].sum()) for u in np.unique(k)}
        assert got == want
        assert faults.injection_stats()["collective:transient"][
            "injected"] == 2


# ---------------------------------------------------------------------------
# mesh degradation ladder: halve, probe, replay; typed Degraded at floor
# ---------------------------------------------------------------------------


class TestMeshDegradation:
    def test_ladder_halves_probes_and_replays(self):
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        degraded_before = _counter("mesh.degraded")
        runner = parallel.MeshRunner(8)
        sizes = []

        def stage(mesh):
            size = int(mesh.shape["shuffle"])
            sizes.append(size)
            if size > 2:
                raise faults.TransientDeviceError(
                    f"UNAVAILABLE: slice lost at {size}"
                )
            return "ok"

        assert runner.run_stage("chaos.stage", stage) == "ok"
        assert sizes == [8, 4, 2]  # 8 -> 4 -> 2, success at 2
        doc = runner.to_doc()
        assert doc["degraded"] is True
        assert doc["devices"] == 2 and doc["requested_devices"] == 8
        assert doc["replays"] == 2 and doc["degradations"] == 2
        assert _counter("mesh.degraded") - degraded_before == 2

    def test_floor_raises_typed_degraded(self):
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        runner = parallel.MeshRunner(2, min_devices=2)

        def stage(mesh):
            raise faults.TransientDeviceError("UNAVAILABLE: dead slice")

        with pytest.raises(faults.Degraded) as ei:
            runner.run_stage("chaos.floor", stage)
        assert "2-device floor" in str(ei.value)
        assert _counter("mesh.exhausted") >= 1

    def test_health_probe_answers_on_live_mesh(self, mesh):
        assert parallel.MeshHealth().probe(mesh) is True

    def test_health_probe_fails_on_injected_mesh_fault(self, mesh):
        config.set_flag("METRICS", "1")
        before = _counter("mesh.probe_failures")
        config.set_flag("FAULTS", "mesh:transient:1:1")
        assert parallel.MeshHealth().probe(mesh) is False
        assert _counter("mesh.probe_failures") - before == 1

    def test_make_mesh_is_an_injection_site(self):
        config.set_flag("FAULTS", "mesh:permanent:1:1")
        with pytest.raises(faults.PermanentError):
            parallel.make_mesh(8)


# ---------------------------------------------------------------------------
# mesh-backed plans: parity at bucket edges, parity through degradation,
# exact-path fallback at the floor, declines for unsupported chains
# ---------------------------------------------------------------------------


class TestPlanMesh:
    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_parity_at_bucket_edges(self, n):
        config.set_flag("BUCKETS", "")
        t = _plan_table(n)
        want = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t))
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t,
                                     mesh_runner=runner))
        assert got == want
        assert runner.to_doc()["degraded"] is False

    def test_parity_through_full_ladder(self):
        """Three dead-slice events walk the mesh 8 -> 4 -> 2 -> 1; the
        replay on each smaller mesh stays byte-identical because
        row-local plans are mesh-size independent."""
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        n = 1024
        t = _plan_table(n)
        want = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t))
        config.set_flag("FAULTS", "seed=2,collective:transient:1:3")
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t,
                                     mesh_runner=runner))
        assert got == want
        doc = runner.to_doc()
        assert doc["degraded"] is True and doc["devices"] == 1
        assert doc["replays"] == 3

    def test_floor_falls_back_to_exact_path(self):
        """Unbounded collective failure exhausts the ladder; the plan
        degrades to the single-device exact path instead of failing."""
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        n = 1023
        t = _plan_table(n)
        want = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t))
        fallbacks = _counter("plan.mesh_fallbacks")
        config.set_flag("FAULTS", "collective:transient:1")
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(ROW_LOCAL_CHAIN, t,
                                     mesh_runner=runner))
        config.set_flag("FAULTS", "")
        assert got == want
        assert _counter("plan.mesh_fallbacks") - fallbacks == 1
        assert _counter("mesh.exhausted") >= 1

    def test_global_chain_declined_to_exact(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", "1")
        t = _plan_table(512)
        want = _tbl(plan_mod.run_plan(GLOBAL_CHAIN, t))
        declined = _counter("plan.mesh_declined")
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(GLOBAL_CHAIN, t, mesh_runner=runner))
        assert got == want
        assert _counter("plan.mesh_declined") - declined == 1


# ---------------------------------------------------------------------------
# partition-op plans under chaos (ISSUE 17): the exchange boundary
# replays losslessly at every ladder rung, and the salted skew-split
# exchange recovers byte-identical under seeded shuffle faults
# ---------------------------------------------------------------------------


def _skewed_table(n: int = 20_000, seed: int = 7):
    """~80% of rows carry ONE key: a single destination sees far past
    SKEW_SPLIT_FACTOR x the mean, so the adaptive splitter must engage."""
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 1000, n, dtype=np.int64)
    k[rng.random(n) < 0.8] = 1
    v = rng.integers(-100, 100, n, dtype=np.int64)
    return Table.from_pydict({"k": k, "v": v}), k, v


def _agg_dict(agg, ngroups):
    """Placement-free groupby content: key -> (sum, count). Works at any
    mesh size (the ladder moves placement, never content)."""
    counts = np.asarray(ngroups)
    ndev = len(counts)
    ks = np.asarray(agg["k"].data).reshape(ndev, -1)
    sums = np.asarray(agg["sum_v"].data).reshape(ndev, -1)
    cnts = np.asarray(agg["count_v"].data).reshape(ndev, -1)
    got = {}
    for d in range(ndev):
        for i in range(int(counts[d])):
            got[int(ks[d, i])] = (int(sums[d, i]), int(cnts[d, i]))
    return got


@pytest.mark.slow
class TestPartitionPlanChaos:
    """Slow tier: ~4.5 min of partition-stage compiles across mesh
    sizes (the quick tier is near its premerge budget; premerge covers
    the exchange parity + skew-split paths via ci/smoke-skew.sh)."""

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_partition_parity_at_bucket_edges(self, n):
        config.set_flag("BUCKETS", "")
        t = _plan_table(n)
        want = _tbl(plan_mod.run_plan(PARTITION_CHAIN, t))
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(PARTITION_CHAIN, t,
                                     mesh_runner=runner))
        assert got == want
        assert runner.to_doc()["degraded"] is False

    def test_partition_parity_through_full_ladder(self):
        """Three dead-slice events walk the mesh 8 -> 4 -> 2 -> 1 with a
        partition boundary mid-plan; every replay re-derives shard
        layout, counts pass, and exchange capacity at the smaller size
        and stays byte-identical — the exchange is mesh-size
        independent by construction (dest device = pid*size//num)."""
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        t = _plan_table(1024)
        want = _tbl(plan_mod.run_plan(PARTITION_CHAIN, t))
        config.set_flag("FAULTS", "seed=2,collective:transient:1:3")
        runner = parallel.MeshRunner(8)
        got = _tbl(plan_mod.run_plan(PARTITION_CHAIN, t,
                                     mesh_runner=runner))
        assert got == want
        doc = runner.to_doc()
        assert doc["degraded"] is True and doc["devices"] == 1
        assert doc["replays"] == 3

    def test_shuffle_faults_in_skew_split_replay_lossless(self, mesh):
        """Seeded shuffle-site faults land inside the salted two-phase
        exchange launches; lineage replay re-runs only the failed
        launch and the merged result stays byte-identical."""
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        t, k, v = _skewed_table()
        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
        splits0 = _counter("shuffle.skew_splits")
        agg, ng, ov = parallel.distributed_groupby(t, ["k"], aggs, mesh)
        assert int(np.asarray(ov).max()) <= 0
        want = _agg_dict(agg, ng)
        # the splitter must actually have engaged on this shape
        assert _counter("shuffle.skew_splits") > splits0
        # and produced exactly the numpy oracle
        oracle = {
            int(u): (int(v[k == u].sum()), int((k == u).sum()))
            for u in np.unique(k)
        }
        assert want == oracle
        retries = _counter("shuffle.retries")
        config.set_flag("FAULTS", "seed=11,shuffle:transient:1:2")
        agg, ng, ov = parallel.distributed_groupby(t, ["k"], aggs, mesh)
        assert int(np.asarray(ov).max()) <= 0
        assert _agg_dict(agg, ng) == want
        assert faults.injection_stats()["shuffle:transient"][
            "injected"] == 2
        assert _counter("shuffle.retries") - retries >= 2

    def test_salted_exchange_mid_degradation_parity(self):
        """A persistent fault during the salted exchange walks the
        runner's ladder 8 -> 4; the replay re-plans the split at the
        surviving size and the merged groups stay byte-identical."""
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_MAX", "0")
        t, k, v = _skewed_table(seed=3)
        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
        mesh8 = parallel.make_mesh(8)
        agg, ng, ov = parallel.distributed_groupby(t, ["k"], aggs, mesh8)
        want = _agg_dict(agg, ng)
        config.set_flag("FAULTS", "seed=2,shuffle:transient:1:1")
        runner = parallel.MeshRunner(8)
        agg, ng, ov = runner.run_stage(
            "chaos.skew_groupby",
            lambda mesh: parallel.distributed_groupby(
                t, ["k"], aggs, mesh
            ),
        )
        config.set_flag("FAULTS", "")
        assert int(np.asarray(ov).max()) <= 0
        doc = runner.to_doc()
        assert doc["degraded"] is True and doc["devices"] == 4
        assert _agg_dict(agg, ng) == want


# ---------------------------------------------------------------------------
# serving: a mesh-backed session serves byte-identical streams, and
# degrades to the exact path under chaos instead of shedding the tenant
# ---------------------------------------------------------------------------


def _wire_cols(n: int, seed: int = 0):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-50, 50, n, dtype=np.int64)
    mask = (rng.integers(0, 3, n, dtype=np.int64) > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), mask.tobytes()],
            [None, None], n)


def _norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


class TestServingMesh:
    def test_mesh_session_streams_byte_identical(self):
        config.set_flag("BUCKETS", "")
        batches = [_wire_cols(1023), _wire_cols(1024)]
        with serving.serve() as srv:
            with serving.Client(srv.port, name="plain") as c:
                want = [_norm(r) for r in c.stream(ROW_LOCAL_CHAIN,
                                                   batches)]
            with serving.Client(srv.port, name="meshed", mesh=8) as c:
                got = [_norm(r) for r in c.stream(ROW_LOCAL_CHAIN,
                                                  batches)]
            assert got == want
            docs = srv.stats()["mesh"]
            assert docs and docs[0]["requested_devices"] == 8
        assert rb.leak_report() == []

    def test_mesh_session_degrades_not_sheds(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_MAX", "0")
        config.set_flag("METRICS", "1")
        batch = _wire_cols(1024)
        with serving.serve() as srv:
            with serving.Client(srv.port, name="plain") as c:
                want = [_norm(r) for r in c.stream(ROW_LOCAL_CHAIN,
                                                   [batch])]
            fallbacks = _counter("plan.mesh_fallbacks")
            config.set_flag("FAULTS", "collective:transient:1")
            with serving.Client(srv.port, name="meshed", mesh=8) as c:
                got = [_norm(r) for r in c.stream(ROW_LOCAL_CHAIN,
                                                  [batch])]
            config.set_flag("FAULTS", "")
            assert got == want  # served exactly, not shed
            assert _counter("plan.mesh_fallbacks") - fallbacks == 1
        assert rb.leak_report() == []

    def test_impossible_mesh_count_is_typed_at_hello(self):
        with serving.serve() as srv:
            with pytest.raises(serving.ServingError) as ei:
                serving.Client(srv.port, mesh=1024).connect()
            assert ei.value.type == "bad_request"
            assert "XLA_FLAGS" in str(ei.value)
            with pytest.raises(serving.ServingError) as ei:
                serving.Client(srv.port, mesh=-4).connect()
            assert ei.value.type == "bad_request"
