"""Fault-tolerant execution plane: taxonomy, injection, retry, cancel.

The ISSUE-10 contract under test: a deterministic, seeded fault plan
(``SPARK_RAPIDS_TPU_FAULTS``) can provoke every failure kind at every
registered injection site on CPU, and the execution plane recovers
with results BYTE-IDENTICAL to a faults-off run at bucket-boundary row
counts (1023/1024/1025) — transient faults retry with backoff, OOM
faults degrade to half-batch chunks (row-local segments) or the exact
path, permanent faults surface typed. Retry is at-most-once for
donated work (a consumed input is never replayed), cancellation and
deadlines abort between segments with a clean ``leak_report()``, the
serving circuit breaker walks open -> half-open -> closed, and the
whole plane costs one int compare per checkpoint when off (< 5 µs/op,
the metrics-gate overhead class).
"""

import json
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plan as plan_mod
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.utils import buckets, config, faults, metrics

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)

BOUNDARY_SIZES = (1023, 1024, 1025)

# all ops row-local: OOM degradation may chunk this chain
ROW_LOCAL_CHAIN = [
    {"op": "filter", "mask": 1},
    {"op": "cast", "column": 0, "type_id": F64},
]

# ends in a global op: OOM degradation must NOT chunk this chain
GLOBAL_CHAIN = [
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
]

FAULT_FLAGS = (
    "FAULTS", "RETRY_MAX", "RETRY_BASE_MS", "DEADLINE_DEFAULT_S",
    "BREAKER_THRESHOLD", "BREAKER_PROBE_S",
)


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    for name in FAULT_FLAGS + ("BUCKETS", "METRICS", "PIPELINE"):
        config.clear_flag(name)


def _cols(n: int, seed: int = 0):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-50, 50, n, dtype=np.int64)
    mask = (k > 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), mask.tobytes()],
            [None, None])


def _run(chain, n, seed=0):
    return rb.table_plan_wire(json.dumps(chain), *_cols(n, seed), n)


def _norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


# ---------------------------------------------------------------------------
# spec parsing: loud-fail naming the env var
# ---------------------------------------------------------------------------


class TestSpecParsing:
    @pytest.mark.parametrize("bad,needle", [
        ("bogus:transient:1", "unknown site"),
        ("dispatch:meteor:1", "unknown kind"),
        ("dispatch:transient:nope", "bad probability"),
        ("dispatch:transient:1.5", "must be in [0, 1]"),
        ("dispatch:transient:1:x", "bad count"),
        ("dispatch:transient:1:-2", "count must be >= 0"),
        ("seed=pi,dispatch:transient:1", "bad seed"),
        ("dispatch:transient", "site:kind:prob"),
    ])
    def test_bad_spec_names_env_var(self, bad, needle):
        with pytest.raises(ValueError) as ei:
            faults.parse_spec(bad)
        assert "SPARK_RAPIDS_TPU_FAULTS" in str(ei.value)
        assert needle in str(ei.value)

    def test_bad_env_value_fails_loudly(self, monkeypatch):
        monkeypatch.setenv("SPARK_RAPIDS_TPU_FAULTS", "junk")
        with pytest.raises(ValueError) as ei:
            config.get_flag("FAULTS")
        assert "SPARK_RAPIDS_TPU_FAULTS" in str(ei.value)

    @pytest.mark.parametrize("name,bad", [
        ("RETRY_MAX", "-1"),
        ("RETRY_BASE_MS", "0"),
        ("DEADLINE_DEFAULT_S", "-3"),
        ("BREAKER_THRESHOLD", "0"),
        ("BREAKER_PROBE_S", "-1"),
    ])
    def test_knob_env_fails_loudly(self, monkeypatch, name, bad):
        monkeypatch.setenv(f"SPARK_RAPIDS_TPU_{name}", bad)
        with pytest.raises(ValueError) as ei:
            config.get_flag(name)
        assert name in str(ei.value)  # loud-fail names the knob

    def test_good_spec_round_trips(self):
        p = faults.parse_spec(
            "seed=9,dispatch:transient:0.5:3,serde:oom:1"
        )
        assert p.seed == 9
        assert set(p.stats()) == {"dispatch:transient", "serde:oom"}


# ---------------------------------------------------------------------------
# classifier
# ---------------------------------------------------------------------------


class TestClassifier:
    @pytest.mark.parametrize("type_name,msg,want", [
        ("XlaRuntimeError", "UNAVAILABLE: socket closed",
         faults.TransientDeviceError),
        ("RuntimeError", "failed to connect to coordination service",
         faults.TransientDeviceError),
        ("DeviceUnreachable", "anything", faults.TransientDeviceError),
        ("TimeoutExpired", "probe", faults.TransientDeviceError),
        ("XlaRuntimeError", "RESOURCE_EXHAUSTED: out of memory "
         "allocating 1GB", faults.ResourceExhausted),
        ("MemoryError", "failed to allocate", faults.ResourceExhausted),
        ("RuntimeError", "operation was cancelled", faults.Cancelled),
        ("ValueError", "unknown op 'zorp'", faults.PermanentError),
        ("KeyError", "table id 7", faults.PermanentError),
    ])
    def test_classify_text(self, type_name, msg, want):
        assert faults.classify_text(type_name, msg) is want

    def test_typed_errors_classify_as_themselves(self):
        for cls in (faults.TransientDeviceError, faults.PermanentError,
                    faults.ResourceExhausted, faults.Cancelled,
                    faults.DeadlineExceeded, faults.Degraded):
            assert faults.classify(cls("x")) is cls

    def test_retryable_classes(self):
        assert faults.retryable_class(faults.TransientDeviceError)
        assert faults.retryable_class(faults.ResourceExhausted)
        assert not faults.retryable_class(faults.PermanentError)
        assert not faults.retryable_class(faults.Cancelled)
        assert not faults.retryable_class(faults.DeadlineExceeded)
        assert not faults.retryable_class(faults.Degraded)


# ---------------------------------------------------------------------------
# deterministic injection
# ---------------------------------------------------------------------------


def _decisions(spec, site, calls):
    plan = faults.parse_spec(spec)
    out = []
    for _ in range(calls):
        try:
            plan.fire(site)
            out.append(False)
        except faults.FaultError:
            out.append(True)
    return out


class TestInjectionDeterminism:
    def test_same_seed_same_decisions(self):
        spec = "seed=11,dispatch:transient:0.5"
        a = _decisions(spec, "dispatch", 64)
        b = _decisions(spec, "dispatch", 64)
        assert a == b
        assert any(a) and not all(a)  # prob 0.5 actually mixes

    def test_different_seed_different_decisions(self):
        a = _decisions("seed=1,dispatch:transient:0.5", "dispatch", 64)
        b = _decisions("seed=2,dispatch:transient:0.5", "dispatch", 64)
        assert a != b

    def test_count_limits_injections(self):
        hits = _decisions("dispatch:oom:1:2", "dispatch", 10)
        assert sum(hits) == 2
        assert hits[:2] == [True, True]  # prob 1: the first two calls

    def test_unregistered_site_is_silent(self):
        plan = faults.parse_spec("dispatch:oom:1")
        plan.fire("serde")  # no rule armed there: no-op

    def test_kinds_raise_their_taxonomy_class(self):
        for kind, cls in (
            ("transient", faults.TransientDeviceError),
            ("oom", faults.ResourceExhausted),
            ("permanent", faults.PermanentError),
        ):
            plan = faults.parse_spec(f"serde:{kind}:1:1")
            with pytest.raises(cls):
                plan.fire("serde")


# ---------------------------------------------------------------------------
# retry with backoff
# ---------------------------------------------------------------------------


class TestRetry:
    def test_transient_recovers_within_budget(self):
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("UNAVAILABLE: connection reset")
            return "ok"

        assert faults.run_with_retry(flaky, "t") == "ok"
        assert calls["n"] == 3
        c = metrics.snapshot()["counters"]
        assert c.get("retry.attempts", 0) >= 2

    def test_permanent_raw_error_surfaces_unchanged(self):
        err = ValueError("unknown op 'zorp'")

        def bad():
            raise err

        with pytest.raises(ValueError) as ei:
            faults.run_with_retry(bad, "t")
        assert ei.value is err  # exact object: type AND message pinned

    def test_exhaustion_raises_typed_chained(self):
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_MAX", "2")
        config.set_flag("RETRY_BASE_MS", "0.1")

        def always():
            raise RuntimeError("UNAVAILABLE: socket closed")

        with pytest.raises(faults.TransientDeviceError) as ei:
            faults.run_with_retry(always, "t")
        assert "retries exhausted" in str(ei.value)
        assert isinstance(ei.value.__cause__, RuntimeError)
        c = metrics.snapshot()["counters"]
        assert c.get("retry.giveups", 0) >= 1

    def test_backoff_is_deterministic_and_grows(self):
        a = faults.backoff_ms(1, "site")
        assert a == faults.backoff_ms(1, "site")
        # jitter is [0.5x, 1.0x): attempt 3's floor (2x base) beats
        # attempt 1's ceiling (1x base)
        assert faults.backoff_ms(3, "site") > a


# ---------------------------------------------------------------------------
# chaos matrix: every site x recoverable kind, byte parity afterwards
# ---------------------------------------------------------------------------


MATRIX_SITES = ("dispatch", "compile", "serde")


class TestChaosMatrix:
    @pytest.mark.parametrize("kind", ("transient", "oom"))
    @pytest.mark.parametrize("site", MATRIX_SITES)
    def test_recoverable_kind_byte_parity(self, site, kind):
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_BASE_MS", "1")
        n = 1024
        # fault-armed run FIRST, against a cold executable cache, so
        # the compile site genuinely fires (it only arms on a miss)
        buckets.cache_clear()
        config.set_flag("FAULTS", f"seed=5,{site}:{kind}:1:1")
        got = _norm(_run(ROW_LOCAL_CHAIN, n))
        stats = faults.injection_stats()
        assert stats[f"{site}:{kind}"]["injected"] == 1
        config.set_flag("FAULTS", "")
        want = _norm(_run(ROW_LOCAL_CHAIN, n))
        assert got == want

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_transient_parity_at_bucket_boundaries(self, n):
        config.set_flag("BUCKETS", "")
        config.set_flag("RETRY_BASE_MS", "1")
        config.set_flag("FAULTS", "seed=7,dispatch:transient:1:2")
        got = _norm(_run(ROW_LOCAL_CHAIN, n))
        config.set_flag("FAULTS", "")
        assert got == _norm(_run(ROW_LOCAL_CHAIN, n))

    def test_oom_chunks_row_local_segment(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        n = 1025
        config.set_flag("FAULTS", "seed=3,dispatch:oom:1:1")
        got = _norm(_run(ROW_LOCAL_CHAIN, n))
        c = metrics.snapshot()["counters"]
        assert c.get("plan.chunked_segments", 0) >= 1
        config.set_flag("FAULTS", "")
        assert got == _norm(_run(ROW_LOCAL_CHAIN, n))

    def test_oom_on_global_segment_never_chunks(self):
        # sort is not row-local: degradation must NOT split the batch
        # (a chunked sort would be locally-sorted garbage); recovery
        # belongs to retry/the exact path and parity still holds
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        n = 1024
        before = metrics.snapshot()["counters"].get(
            "plan.chunked_segments", 0
        )
        config.set_flag("FAULTS", "seed=3,dispatch:oom:1:1")
        got = _norm(_run(GLOBAL_CHAIN, n))
        c = metrics.snapshot()["counters"]
        assert c.get("plan.chunked_segments", 0) == before
        config.set_flag("FAULTS", "")
        assert got == _norm(_run(GLOBAL_CHAIN, n))

    def test_permanent_fault_surfaces_typed(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("FAULTS", "dispatch:permanent:1")
        with pytest.raises(faults.PermanentError):
            _run(ROW_LOCAL_CHAIN, 256)

    def test_injection_is_metered(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        config.set_flag("FAULTS", "seed=5,serde:transient:1:1")
        _run(ROW_LOCAL_CHAIN, 512)
        c = metrics.snapshot()["counters"]
        assert c.get("faults.injected", 0) >= 1
        assert c.get("faults.injected.serde.transient", 0) >= 1
        assert c.get("retry.attempts", 0) >= 1


# ---------------------------------------------------------------------------
# at-most-once for donated work
# ---------------------------------------------------------------------------


def test_consumed_segment_is_never_retried(monkeypatch):
    # CPU jax never actually deletes donated buffers, so the consumed
    # state is simulated: _input_consumed answers True, exactly what a
    # donated executable that launched before dying leaves behind
    config.set_flag("BUCKETS", "")
    config.set_flag("METRICS", "1")
    calls = {"n": 0}

    def launch_then_die(seg_ops, table, donate=False):
        calls["n"] += 1
        raise RuntimeError("UNAVAILABLE: device lost after launch")

    monkeypatch.setattr(plan_mod, "_run_fused", launch_then_die)
    monkeypatch.setattr(plan_mod, "_input_consumed", lambda t: True)
    before = metrics.snapshot()["counters"].get("retry.attempts", 0)
    # at-most-once: the transient failure must surface as-is — one
    # attempt, no retry, no per-op replay against buffers the device
    # already owns
    with pytest.raises(RuntimeError) as ei:
        _run(ROW_LOCAL_CHAIN, 1024)
    assert "device lost after launch" in str(ei.value)
    assert calls["n"] == 1
    c = metrics.snapshot()["counters"]
    assert c.get("retry.attempts", 0) == before


# ---------------------------------------------------------------------------
# deadlines + cancellation
# ---------------------------------------------------------------------------


class TestCancellation:
    def test_cancelled_token_aborts_with_clean_leak_report(self):
        config.set_flag("BUCKETS", "")
        tok = faults.CancelToken()
        tok.cancel("test says stop")
        with faults.scoped_token(tok):
            with pytest.raises(faults.Cancelled) as ei:
                _run(ROW_LOCAL_CHAIN, 1024)
        assert "test says stop" in str(ei.value)
        assert rb.leak_report() == []

    def test_expired_deadline_aborts_with_clean_leak_report(self):
        config.set_flag("BUCKETS", "")
        tok = faults.CancelToken(deadline_s=1e-6)
        time.sleep(0.005)
        with faults.scoped_token(tok):
            with pytest.raises(faults.DeadlineExceeded):
                _run(ROW_LOCAL_CHAIN, 1024)
        assert rb.leak_report() == []

    def test_expired_token_never_sleeps_in_backoff(self):
        config.set_flag("RETRY_BASE_MS", "10000")
        tok = faults.CancelToken(deadline_s=1e-6)
        time.sleep(0.005)
        with faults.scoped_token(tok):
            t0 = time.perf_counter()
            with pytest.raises(faults.DeadlineExceeded):
                faults.sleep_backoff(1, "t")
            assert time.perf_counter() - t0 < 1.0

    def test_token_scope_restores_previous(self):
        outer = faults.CancelToken()
        with faults.scoped_token(outer):
            with faults.scoped_token(faults.CancelToken()):
                assert faults.current_token() is not outer
            assert faults.current_token() is outer
        assert faults.current_token() is None

    def test_no_token_is_noop(self):
        faults.check_cancel()  # must not raise
        assert faults.current_token() is None


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _mk(self, threshold=3, interval=10.0):
        clock = {"t": 0.0}
        b = faults.CircuitBreaker(
            threshold=threshold, probe_interval_s=interval,
            clock=lambda: clock["t"], name="test",
        )
        return b, clock

    def test_opens_after_threshold_consecutive_transients(self):
        b, _ = self._mk(threshold=3)
        err = faults.TransientDeviceError("x")
        assert not b.note_failure(err)
        assert not b.note_failure(err)
        assert b.note_failure(err)  # third one trips
        assert b.state == faults.OPEN
        with pytest.raises(faults.Degraded) as ei:
            b.allow()
        assert "next probe" in str(ei.value)

    def test_success_resets_the_count(self):
        b, _ = self._mk(threshold=2)
        err = faults.TransientDeviceError("x")
        b.note_failure(err)
        b.note_success()
        assert not b.note_failure(err)  # count restarted
        assert b.state == faults.CLOSED

    def test_non_transient_failures_neither_count_nor_reset(self):
        b, _ = self._mk(threshold=2)
        b.note_failure(faults.TransientDeviceError("x"))
        b.note_failure(ValueError("bad request"))
        b.note_failure(faults.ResourceExhausted("oom"))
        assert b.state == faults.CLOSED
        # the next transient is the SECOND consecutive one: trips
        assert b.note_failure(faults.TransientDeviceError("x"))

    def test_half_open_probe_then_close(self):
        b, clock = self._mk(threshold=1, interval=5.0)
        b.note_failure(faults.TransientDeviceError("x"))
        assert b.state == faults.OPEN
        clock["t"] = 6.0
        assert b.allow() is True  # this caller is the probe
        assert b.state == faults.HALF_OPEN
        with pytest.raises(faults.Degraded):
            b.allow()  # everyone else sheds during the trial
        b.note_success()
        assert b.state == faults.CLOSED
        assert b.allow() is False

    def test_half_open_failure_reopens_and_rearms(self):
        b, clock = self._mk(threshold=1, interval=5.0)
        b.note_failure(faults.TransientDeviceError("x"))
        clock["t"] = 6.0
        assert b.allow() is True
        assert b.note_failure(faults.TransientDeviceError("y"))
        assert b.state == faults.OPEN
        clock["t"] = 10.0  # re-armed at t=6: not yet probe time
        with pytest.raises(faults.Degraded):
            b.allow()
        clock["t"] = 11.5
        assert b.allow() is True

    def test_to_doc_shape(self):
        b, _ = self._mk()
        doc = b.to_doc()
        assert doc["state"] == faults.CLOSED
        assert doc["threshold"] == 3
        assert doc["opens"] == 0


# ---------------------------------------------------------------------------
# serving integration: typed wire errors, breaker, hbm_admit site
# ---------------------------------------------------------------------------


def _wait_until(cond, timeout=30.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def _small_batch(n=256):
    return (*_cols(n, seed=1), n)


class TestServingFaults:
    def test_breaker_opens_sheds_typed_and_recovers(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("BREAKER_THRESHOLD", "2")
        config.set_flag("BREAKER_PROBE_S", "0.5")
        b = _small_batch()
        want = _norm(rb.table_plan_wire(json.dumps(ROW_LOCAL_CHAIN), *b))
        config.set_flag("FAULTS", "serve_accept:transient:1")
        with serving.serve() as srv:
            with serving.Client(srv.port, name="chaos") as c:
                for _ in range(2):  # trip the breaker
                    with pytest.raises(serving.ServingTransientError):
                        c.stream(ROW_LOCAL_CHAIN, [b])
                with pytest.raises(serving.ServingDegraded) as ei:
                    c.stream(ROW_LOCAL_CHAIN, [b])
                assert "circuit breaker" in str(ei.value)
                assert srv.stats()["breaker"]["state"] == faults.OPEN
                # device "recovers": the background probe must close
                # the breaker with no client traffic at all
                config.set_flag("FAULTS", "")
                assert _wait_until(
                    lambda: srv.breaker.state == faults.CLOSED,
                    timeout=30,
                )
                got = c.stream(ROW_LOCAL_CHAIN, [b])
                assert _norm(got[0]) == want

    def test_hbm_admit_fault_is_typed_then_recovers(self):
        config.set_flag("BUCKETS", "")
        config.set_flag("FAULTS", "hbm_admit:oom:1:1")
        b = _small_batch()
        want = _norm(rb.table_plan_wire(json.dumps(ROW_LOCAL_CHAIN), *b))
        with serving.serve() as srv:
            with serving.Client(srv.port, name="oomy") as c:
                with pytest.raises(serving.ServingResourceExhausted):
                    c.stream(ROW_LOCAL_CHAIN, [b])
                got = c.stream(ROW_LOCAL_CHAIN, [b])  # client retry
                assert _norm(got[0]) == want
        assert rb.leak_report() == []

    def test_stream_deadline_exceeded_is_typed(self):
        config.set_flag("BUCKETS", "")
        b = _small_batch()
        want = _norm(rb.table_plan_wire(json.dumps(ROW_LOCAL_CHAIN), *b))
        with serving.serve() as srv:
            with serving.Client(srv.port, name="late") as c:
                with pytest.raises(serving.ServingDeadlineExceeded):
                    c.stream(ROW_LOCAL_CHAIN, [b], deadline_s=1e-9)
                # no deadline: same session still works
                got = c.stream(ROW_LOCAL_CHAIN, [b])
                assert _norm(got[0]) == want
        assert rb.leak_report() == []


# ---------------------------------------------------------------------------
# disabled-path overhead: the metrics-gate class
# ---------------------------------------------------------------------------


class TestDisabledOverhead:
    def test_inject_disabled_cost_within_budget(self):
        assert not faults.active()
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            faults.inject("dispatch")
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"disabled inject costs {per * 1e6:.2f}us"

    def test_check_cancel_disabled_cost_within_budget(self):
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            faults.check_cancel()
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"disabled check_cancel {per * 1e6:.2f}us"
