"""TPC-DS real-data path (benchmarks/tpcds.py, round-4 VERDICT item 6):
seeded Parquet star schema -> streamed scan/join/agg pipelines vs
pandas oracles, plus the mesh-distributed variant fed from the same
files."""

import numpy as np
import pytest

from benchmarks import tpcds


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds")
    m = tpcds.generate_parquet(str(d), scale=0.002, seed=3)
    assert m["store_sales"] >= 1000
    return str(d)


def test_generated_schema_has_nulls_decimals_strings(data_dir):
    import pyarrow.parquet as pq

    ss = pq.read_table(data_dir + "/store_sales.parquet")
    assert ss["customer_sk"].null_count > 0  # dbgen-like null FKs
    assert str(ss.schema.field("sales_price").type) == "decimal128(7, 2)"
    item = pq.read_table(data_dir + "/item.parquet")
    assert item["i_category"].type == "string"
    cust = pq.read_table(data_dir + "/customer.parquet")
    assert cust["c_first_name"].null_count > 0


def test_streamed_queries_match_pandas_oracles(data_dir):
    results = tpcds.run_all(data_dir, prefetch=1)
    assert [r["name"] for r in results] == [
        "tpcds_q5_stream", "tpcds_q23_stream", "tpcds_q64_stream"
    ]
    for r in results:
        assert r["oracle_match"], r
        assert r["groups"] > 0


def test_distributed_variant_runs_from_parquet(data_dir):
    out = tpcds.run_distributed(data_dir, devices=2)
    assert len(out) == 3
    for e in out:
        assert e["seconds"] > 0


def test_generation_is_seeded(tmp_path):
    import pyarrow.parquet as pq

    a = tmp_path / "a"
    b = tmp_path / "b"
    tpcds.generate_parquet(str(a), scale=0.002, seed=9)
    tpcds.generate_parquet(str(b), scale=0.002, seed=9)
    ta = pq.read_table(str(a / "store_sales.parquet"))
    tb = pq.read_table(str(b / "store_sales.parquet"))
    assert ta.equals(tb)
