"""Tiered memory hierarchy: HBM -> host -> disk spill (utils/spill.py).

The ISSUE-11 contract under test: every resident table has a residency
state (device | host | disk) with transparent repage-on-access, LRU
eviction under pressure, and graceful degradation instead of death —
a working set larger than the (shrunk) HBM budget completes
BYTE-IDENTICAL to the unconstrained run at bucket-boundary row counts
(1023/1024/1025); the serving tier spills cold tables instead of
shedding with OverBudget; the plan OOM ladder's first rung spills and
retries at the same shape; pins / pipelined readers / active wire
downloads always beat eviction; freeing or reclaiming a spilled table
releases its host/disk backing (zero leftover spill files); chaos on
the ``spill`` injection site is survived; and the disabled path costs
one cached generation compare (< 5 µs/op).
"""

import glob
import json
import os
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.serving.session import Session
from spark_rapids_jni_tpu.utils import config, faults, hbm, metrics, spill

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)

BOUNDARY_SIZES = (1023, 1024, 1025)

# ~20 KiB usable budget: a handful of KiB-scale tables overflows it
TINY_BUDGET_GB = 3e-5

SPILL_FLAGS = (
    "SPILL", "SPILL_DIR", "HOST_SPILL_BUDGET_GB", "HBM_BUDGET_GB",
    "METRICS", "FAULTS", "RETRY_MAX", "RETRY_BASE_MS", "BUCKETS",
    "PIPELINE",
)


@pytest.fixture(autouse=True)
def _clean():
    yield
    for name in SPILL_FLAGS:
        config.clear_flag(name)
    for tid in list(rb._RESIDENT):
        try:
            rb.table_reclaim(tid)
        except Exception:
            pass
    spill.reset()
    metrics.reset()


def _wire(n: int, seed: int = 0):
    rng = np.random.default_rng(n + seed)
    k = rng.integers(-1000, 1000, n, dtype=np.int64)
    mask = (k % 2 == 0).astype(np.uint8)
    return ([I64, B8], [0, 0], [k.tobytes(), mask.tobytes()],
            [None, None], n)


def _norm(w):
    t, s, d, v, n = w
    return (
        list(t), list(s),
        [bytes(x) if x is not None else None for x in d],
        [bytes(x) if x is not None else None for x in v],
        int(n),
    )


def _free_all(ids):
    for t in ids:
        rb.table_free(t)


class TestSpillParity:
    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_boundary_parity_host_tier(self, n):
        """Working set past a tiny budget: uploads spill, downloads
        repage, bytes identical to the unconstrained run."""
        config.set_flag("METRICS", "1")
        ref_ids = [rb.table_upload_wire(*_wire(n, s)) for s in range(5)]
        refs = [_norm(rb.table_download_wire(t)) for t in ref_ids]
        _free_all(ref_ids)
        config.set_flag("SPILL", "on")
        config.set_flag("HBM_BUDGET_GB", TINY_BUDGET_GB)
        ids = [rb.table_upload_wire(*_wire(n, s)) for s in range(5)]
        assert spill.stats_doc()["host_bytes"] > 0, "nothing spilled"
        got = [_norm(rb.table_download_wire(t)) for t in ids]
        assert got == refs
        snap = metrics.snapshot()
        assert snap["counters"].get("spill.evictions", 0) > 0
        assert snap["counters"].get("spill.repages", 0) > 0
        assert snap["bytes"].get("spill.bytes_out", 0) > 0
        assert snap["bytes"].get("spill.bytes_in", 0) > 0
        _free_all(ids)
        assert rb.resident_table_count() == 0
        doc = spill.stats_doc()
        assert doc["host_bytes"] == 0 and doc["disk_bytes"] == 0

    def test_disk_tier_roundtrip(self, tmp_path):
        """HOST_SPILL_BUDGET_GB=0 demotes straight to disk: .npz files
        exist while spilled, vanish on repage, bytes identical."""
        config.set_flag("SPILL", "on")
        config.set_flag("HBM_BUDGET_GB", TINY_BUDGET_GB)
        config.set_flag("HOST_SPILL_BUDGET_GB", 0)
        config.set_flag("SPILL_DIR", str(tmp_path))
        n = 1024
        ids = [rb.table_upload_wire(*_wire(n, s)) for s in range(5)]
        doc = spill.stats_doc()
        assert doc["disk_bytes"] > 0 and doc["files"] > 0
        pipeline.drain_io()  # demotion writes ride the async IO lane
        assert glob.glob(str(tmp_path / "*.npz"))
        got = [_norm(rb.table_download_wire(t)) for t in ids]
        _free_all(ids)
        assert glob.glob(str(tmp_path / "*.npz")) == []
        config.clear_flag("SPILL")
        config.clear_flag("HBM_BUDGET_GB")
        ref_ids = [rb.table_upload_wire(*_wire(n, s)) for s in range(5)]
        refs = [_norm(rb.table_download_wire(t)) for t in ref_ids]
        _free_all(ref_ids)
        assert got == refs

    def test_plan_over_spilled_input_repages(self):
        """A resident plan over a spilled input repages it transparently
        and matches the unspilled run."""
        chain = [
            {"op": "filter", "mask": 1},
            {"op": "sort_by", "keys": [{"column": 0}]},
        ]
        n = 1023
        tid = rb.table_upload_wire(*_wire(n))
        res = rb.table_plan_resident(json.dumps(chain), [tid])
        ref = _norm(rb.table_download_wire(res))
        rb.table_free(res)
        config.set_flag("SPILL", "on")
        # spill the input by hand (no pressure needed for the check)
        assert spill.request_headroom(1 << 40) > 0
        assert isinstance(
            rb._RESIDENT[tid], spill.SpilledTable
        ), "input did not spill"
        res = rb.table_plan_resident(json.dumps(chain), [tid])
        assert _norm(rb.table_download_wire(res)) == ref
        _free_all([tid, res])


class TestEvictionPolicy:
    def _resident(self, n=1024, seed=0):
        return rb.table_upload_wire(*_wire(n, seed))

    def test_pin_wins(self):
        config.set_flag("SPILL", "on")
        a, b = self._resident(seed=1), self._resident(seed=2)
        spill.pin_ids([a])
        spill.request_headroom(1 << 40)
        with rb._RESIDENT_LOCK:
            assert not isinstance(rb._RESIDENT[a], spill.SpilledTable)
            assert isinstance(rb._RESIDENT[b], spill.SpilledTable)
        spill.unpin_ids([a])
        spill.request_headroom(1 << 40)
        with rb._RESIDENT_LOCK:
            assert isinstance(rb._RESIDENT[a], spill.SpilledTable)
        _free_all([a, b])

    def test_live_pipelined_reader_blocks_eviction(self):
        """The donate-barrier accounting doubles as the spill guard: a
        not-yet-done reader Pending keeps its input on device."""
        config.set_flag("SPILL", "on")
        a = self._resident(seed=3)
        reader = pipeline.Pending(lambda: None, "test_reader")
        with rb._RESIDENT_LOCK:
            rb._RESIDENT_READERS.setdefault(a, []).append(reader)
        spill.request_headroom(1 << 40)
        with rb._RESIDENT_LOCK:
            assert not isinstance(rb._RESIDENT[a], spill.SpilledTable)
        reader._run()  # what the pool thread would do; done() flips True
        spill.request_headroom(1 << 40)
        with rb._RESIDENT_LOCK:
            assert isinstance(rb._RESIDENT[a], spill.SpilledTable)
        rb.table_free(a)

    def test_active_wire_download_blocks_eviction(self):
        config.set_flag("SPILL", "on")
        a = self._resident(seed=4)
        with rb._RESIDENT_LOCK:
            rb._RESIDENT_ACTIVE_READS[a] = 1
        try:
            spill.request_headroom(1 << 40)
            with rb._RESIDENT_LOCK:
                assert not isinstance(rb._RESIDENT[a], spill.SpilledTable)
        finally:
            with rb._RESIDENT_LOCK:
                rb._RESIDENT_ACTIVE_READS.pop(a, None)
        rb.table_free(a)

    def test_lru_order(self):
        """The coldest (least recently touched) table spills first."""
        config.set_flag("SPILL", "on")
        a, b = self._resident(seed=5), self._resident(seed=6)
        rb.table_num_rows(a)  # touch a: b is now the coldest
        nbytes = hbm.table_bytes(rb._RESIDENT[b])
        spill.request_headroom(max(nbytes - 1, 1))
        with rb._RESIDENT_LOCK:
            assert isinstance(rb._RESIDENT[b], spill.SpilledTable)
            assert not isinstance(rb._RESIDENT[a], spill.SpilledTable)
        _free_all([a, b])

    def test_sync_dispatch_pins_inputs(self):
        """A synchronous op's inputs cannot be evicted mid-dispatch:
        _capture_inputs(pin=True) holds them until the op returns."""
        config.set_flag("SPILL", "on")
        a = self._resident(seed=7)
        # the pin count is balanced after the call (try/finally unpin)
        rb.table_free(rb.table_op_resident(
            json.dumps({"op": "sort_by", "keys": [{"column": 0}]}), [a]
        ))
        with rb._RESIDENT_LOCK:
            assert not spill._PINS.get(a)
        rb.table_free(a)


class TestLifecycle:
    def test_free_spilled_releases_backing(self, tmp_path):
        config.set_flag("SPILL", "on")
        config.set_flag("HOST_SPILL_BUDGET_GB", 0)
        config.set_flag("SPILL_DIR", str(tmp_path))
        tid = rb.table_upload_wire(*_wire(1024))
        spill.request_headroom(1 << 40)
        pipeline.drain_io()
        assert glob.glob(str(tmp_path / "*.npz"))
        rb.table_free(tid)
        assert glob.glob(str(tmp_path / "*.npz")) == []
        assert spill.spill_file_count() == 0

    def test_reclaim_spilled_credits_bytes(self):
        config.set_flag("SPILL", "on")
        tid = rb.table_upload_wire(*_wire(1024))
        nbytes = hbm.table_bytes(rb._RESIDENT[tid])
        spill.request_headroom(1 << 40)
        got = rb.table_reclaim(tid)
        assert got == nbytes
        assert rb.resident_table_count() == 0
        doc = spill.stats_doc()
        assert doc["host_bytes"] == 0 and doc["disk_bytes"] == 0

    def test_leak_report_names_residency_tier(self):
        config.set_flag("SPILL", "on")
        tid = rb.table_upload_wire(*_wire(1023))
        nbytes = hbm.table_bytes(rb._RESIDENT[tid])
        spill.request_headroom(1 << 40)
        rec = [r for r in rb.leak_report() if r["table_id"] == tid]
        assert rec and rec[0]["residency"] == "host"
        assert rec[0]["approx_bytes"] == nbytes
        assert rec[0]["rows"] == 1023
        assert rec[0]["columns"] == 2
        rb.table_free(tid)

    def test_donate_consume_of_spilled_input(self):
        """Donating a spilled input repages it first (the executable
        needs device buffers) and drops its tracking on consume."""
        config.set_flag("SPILL", "on")
        tid = rb.table_upload_wire(*_wire(1024))
        spill.request_headroom(1 << 40)
        res = rb.table_op_resident(
            json.dumps({"op": "sort_by", "keys": [{"column": 0}]}),
            [tid], donate=True,
        )
        out = rb.table_download_wire(res)
        assert out[4] == 1024
        rb.table_free(res)
        assert rb.resident_table_count() == 0
        assert spill.stats_doc()["host_bytes"] == 0


class TestServingSpill:
    def test_admission_spills_instead_of_shedding(self):
        """Two tenants whose combined cold tables exceed the admitting
        session's headroom: admission demotes the coldest instead of
        raising OverBudget — zero sheds for a host-fitting workload."""
        config.set_flag("SPILL", "on")
        config.set_flag("METRICS", "1")
        a = Session("sa", "tenant-a", 1.0, budget_bytes=20_000)
        b = Session("sb", "tenant-b", 1.0, budget_bytes=20_000)
        ids = []
        for sess, seed in ((a, 1), (a, 2), (b, 3)):
            tid = rb.table_upload_wire(*_wire(1024, seed))
            nb = hbm.table_bytes(rb._RESIDENT[tid])
            ids.append((sess, sess.put_table(tid, nb), tid))
        # tenant-a is nearly full (2 x ~9 KiB resident of 20 KB):
        # a 12 KB request must spill, not shed
        charge = a.admit(12_000)
        doc_a, doc_b = a.to_doc(), b.to_doc()
        assert doc_a["over_budget"] == 0 and doc_b["over_budget"] == 0
        assert doc_a["spilled_bytes"] + doc_b["spilled_bytes"] > 0
        assert metrics.snapshot()["counters"].get(
            "serving.admit_spills", 0
        ) > 0
        a.release(charge)
        # repage-on-access re-charges the owner transparently
        for sess, local, tid in ids:
            assert rb.table_download_wire(tid)[4] == 1024
        assert a.to_doc()["spilled_bytes"] == 0
        assert b.to_doc()["spilled_bytes"] == 0
        a.teardown()
        b.teardown()
        assert rb.resident_table_count() == 0
        assert spill.spill_file_count() == 0

    def test_teardown_of_spilled_tables_reclaims_backing(self, tmp_path):
        config.set_flag("SPILL", "on")
        config.set_flag("HOST_SPILL_BUDGET_GB", 0)
        config.set_flag("SPILL_DIR", str(tmp_path))
        s = Session("sc", "tenant-c", 1.0, budget_bytes=1 << 30)
        tid = rb.table_upload_wire(*_wire(1024))
        s.put_table(tid, hbm.table_bytes(rb._RESIDENT[tid]))
        spill.request_headroom(1 << 40)
        pipeline.drain_io()
        assert glob.glob(str(tmp_path / "*.npz"))
        assert s.to_doc()["spilled_bytes"] > 0
        s.teardown()
        assert rb.resident_table_count() == 0
        assert glob.glob(str(tmp_path / "*.npz")) == []


class TestOOMLadder:
    def test_oom_rung_spills_and_retries_same_shape(self):
        """Rung 1 of the OOM ladder: an injected ResourceExhausted with
        a cold resident table available spills it and retries the SAME
        fused launch — no half-batch chunking, parity preserved."""
        chain = [
            {"op": "filter", "mask": 1},
            {"op": "cast", "column": 0,
             "type_id": int(dt.TypeId.FLOAT64)},
        ]
        n = 1024
        ref = _norm(rb.table_plan_wire(json.dumps(chain), *_wire(n)))
        cold = rb.table_upload_wire(*_wire(n, seed=9))
        config.set_flag("SPILL", "on")
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        config.set_flag("FAULTS", "seed=3,dispatch:oom:1:1")
        got = _norm(rb.table_plan_wire(json.dumps(chain), *_wire(n)))
        config.set_flag("FAULTS", "")
        assert got == ref
        ctr = metrics.snapshot()["counters"]
        assert ctr.get("plan.oom_spill_retries", 0) == 1
        assert ctr.get("spill.evictions", 0) >= 1
        assert ctr.get("plan.chunked_segments", 0) == 0
        with rb._RESIDENT_LOCK:
            assert isinstance(rb._RESIDENT[cold], spill.SpilledTable)
        rb.table_free(cold)

    def test_oom_rung_falls_through_when_nothing_spillable(self):
        """No cold resident tables: the rung frees nothing and the
        ladder degrades to half-batch chunking as before."""
        chain = [
            {"op": "filter", "mask": 1},
            {"op": "cast", "column": 0,
             "type_id": int(dt.TypeId.FLOAT64)},
        ]
        n = 1024
        ref = _norm(rb.table_plan_wire(json.dumps(chain), *_wire(n)))
        config.set_flag("SPILL", "on")
        config.set_flag("METRICS", "1")
        config.set_flag("RETRY_BASE_MS", "1")
        config.set_flag("FAULTS", "seed=3,dispatch:oom:1:1")
        got = _norm(rb.table_plan_wire(json.dumps(chain), *_wire(n)))
        config.set_flag("FAULTS", "")
        assert got == ref
        ctr = metrics.snapshot()["counters"]
        assert ctr.get("plan.oom_spill_retries", 0) == 0
        assert ctr.get("plan.chunked_segments", 0) == 1


class TestSpillChaos:
    def test_eviction_fault_skips_victim(self):
        """A chaos fault mid-eviction costs that victim, not the
        headroom request: the next candidate spills."""
        config.set_flag("SPILL", "on")
        config.set_flag("METRICS", "1")
        a = rb.table_upload_wire(*_wire(1024, 1))
        b = rb.table_upload_wire(*_wire(1024, 2))
        config.set_flag("FAULTS", "seed=5,spill:transient:1:1")
        freed = spill.request_headroom(1)
        config.set_flag("FAULTS", "")
        assert freed > 0
        ctr = metrics.snapshot()["counters"]
        assert ctr.get("spill.errors", 0) == 1
        assert ctr.get("spill.evictions", 0) == 1
        _free_all([a, b])

    def test_repage_fault_retries(self):
        """Backing is only dropped after a successful upload, so an
        injected repage failure retries and still round-trips."""
        config.set_flag("SPILL", "on")
        config.set_flag("METRICS", "1")
        ref = _norm(rb.table_download_wire(rb.table_upload_wire(*_wire(1023))))
        tid = rb.table_upload_wire(*_wire(1023))
        spill.request_headroom(1 << 40)
        config.set_flag("RETRY_BASE_MS", "0.1")
        config.set_flag("FAULTS", "seed=5,spill:transient:1:1")
        got = _norm(rb.table_download_wire(tid))
        config.set_flag("FAULTS", "")
        assert got == ref
        assert metrics.snapshot()["counters"].get("retry.attempts", 0) >= 1
        rb.table_free(tid)


class TestFlagsAndOverhead:
    def test_host_budget_parse_fails_loudly(self, monkeypatch):
        monkeypatch.setenv(
            "SPARK_RAPIDS_TPU_HOST_SPILL_BUDGET_GB", "banana"
        )
        with pytest.raises(ValueError, match="HOST_SPILL_BUDGET_GB"):
            config.get_flag("HOST_SPILL_BUDGET_GB")
        monkeypatch.setenv(
            "SPARK_RAPIDS_TPU_HOST_SPILL_BUDGET_GB", "-1"
        )
        with pytest.raises(ValueError, match="HOST_SPILL_BUDGET_GB"):
            config.get_flag("HOST_SPILL_BUDGET_GB")

    def test_disabled_path_overhead(self):
        """SPILL off: touch/note_put cost one cached generation compare
        (the metrics-gate overhead class, < 5 µs/op)."""
        config.set_flag("SPILL", False)
        spill.touch(1)  # prime the generation cache
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            spill.touch(1)
            spill.enabled()
        per_op = (time.perf_counter() - t0) / (2 * n)
        assert per_op < 5e-6, f"disabled spill path costs {per_op*1e6:.2f}µs/op"

    def test_stats_doc_shape(self):
        doc = spill.stats_doc()
        for key in ("enabled", "device_bytes", "host_bytes",
                    "disk_bytes", "host_bytes_hw", "disk_bytes_hw",
                    "files", "pending_events"):
            assert key in doc
