"""Packed-key batched join (ops/join_packed.py) vs the general join
oracle: randomized equivalence, out-of-range probe keys, chunked
probing, eligibility fallbacks."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.join import inner_join
from spark_rapids_jni_tpu.ops.join_packed import (
    inner_join_batched_packed,
    packed_join_supported,
)


def _pairs(t):
    cols = [c.to_pylist() for c in t.columns]
    return sorted(zip(*cols))


class TestEquivalence:
    @pytest.mark.parametrize("seed,probe_rows", [(0, 1 << 20), (1, 97), (2, 256)])
    def test_randomized(self, seed, probe_rows):
        rng = np.random.default_rng(seed)
        nl, nr = 700, 500
        # probe keys deliberately extend BELOW and ABOVE the build range
        kl = rng.integers(-50, 120, nl, dtype=np.int64)
        kr = rng.integers(0, 90, nr, dtype=np.int64)
        left = Table(
            [Column.from_numpy(kl),
             Column.from_numpy(np.arange(nl, dtype=np.int64))],
            ["k", "lv"],
        )
        right = Table(
            [Column.from_numpy(kr),
             Column.from_numpy(np.arange(nr, dtype=np.int64))],
            ["k", "rv"],
        )
        got = inner_join_batched_packed(
            left, right, ["k"], probe_rows=probe_rows
        )
        assert got is not None
        want = inner_join(left, right, ["k"])
        assert got.names == want.names
        assert _pairs(got) == _pairs(want)

    def test_zero_matches_keeps_schema(self):
        left = Table(
            [Column.from_numpy(np.array([1, 2], np.int64)),
             Column.from_numpy(np.array([9, 9], np.int64))],
            ["k", "lv"],
        )
        right = Table(
            [Column.from_numpy(np.array([5, 6], np.int64)),
             Column.from_numpy(np.array([7, 7], np.int64))],
            ["k", "rv"],
        )
        got = inner_join_batched_packed(left, right, ["k"])
        assert got is not None
        assert got.row_count == 0
        assert got.names == inner_join(left, right, ["k"]).names

    def test_negative_and_timestamp_like_keys(self):
        rng = np.random.default_rng(3)
        kl = rng.integers(-(1 << 40), 1 << 40, 400, dtype=np.int64)
        kr = np.concatenate([kl[:100], rng.integers(-(1 << 40), 1 << 40, 200, dtype=np.int64)])
        left = Table([Column.from_numpy(kl)], ["k"])
        right = Table([Column.from_numpy(kr)], ["k"])
        got = inner_join_batched_packed(left, right, ["k"], probe_rows=128)
        assert got is not None
        want = inner_join(left, right, ["k"])
        assert _pairs(got) == _pairs(want)


class TestEligibility:
    def test_wide_span_declines(self):
        kl = np.array([0, 1 << 62], np.int64)
        left = Table([Column.from_numpy(kl)], ["k"])
        right = Table([Column.from_numpy(np.arange(8, dtype=np.int64))], ["k"])
        assert inner_join_batched_packed(left, right, ["k"]) is None

    def test_null_key_declines(self):
        k = np.arange(8, dtype=np.int64)
        v = np.ones(8, bool)
        v[0] = False
        left = Table([Column.from_numpy(k, validity=v)], ["k"])
        right = Table([Column.from_numpy(k)], ["k"])
        assert not packed_join_supported(left, right, ["k"], ["k"])

    def test_multi_key_supported(self):
        # multi-key joins pack as composite fields since round 5
        k = np.arange(8, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(k)], ["a", "b"])
        assert packed_join_supported(t, t, ["a", "b"], ["a", "b"])

    def test_mismatched_key_count_declines(self):
        k = np.arange(8, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(k)], ["a", "b"])
        assert not packed_join_supported(t, t, ["a", "b"], ["a"])


def test_probe_rows_zero_raises():
    k = np.arange(8, dtype=np.int64)
    t = Table([Column.from_numpy(k)], ["k"])
    with pytest.raises(ValueError, match="probe_rows"):
        inner_join_batched_packed(t, t, ["k"], probe_rows=0)


def test_heavy_hitter_resplits(monkeypatch):
    # one build key duplicated heavily: the chunk output budget must
    # force span re-splitting instead of one giant materialization —
    # and the re-split pieces must come back in exact row order
    from spark_rapids_jni_tpu.ops import join as join_mod

    # shrink the budget floor so cap * out_row_bytes really exceeds it
    monkeypatch.setattr(join_mod, "MIN_CHUNK_OUT_BYTES", 1 << 10)
    nl = 8192
    left = Table(
        [Column.from_numpy(np.zeros(nl, np.int64)),
         Column.from_numpy(np.arange(nl, dtype=np.int64))],
        ["k", "lv"],
    )
    right = Table(
        [Column.from_numpy(np.zeros(64, np.int64)),
         Column.from_numpy(np.arange(64, dtype=np.int64))],
        ["k", "rv"],
    )
    got = inner_join_batched_packed(left, right, ["k"], probe_rows=nl)
    assert got is not None
    assert got.row_count == nl * 64
    # exact sequence (not just multiset): probe-row-major like the
    # fused single-shot join
    want = inner_join(left, right, ["k"])
    assert got["lv"].to_pylist() == want["lv"].to_pylist()
    assert got["rv"].to_pylist() == want["rv"].to_pylist()



class TestMultiKeyJoin:
    @pytest.mark.parametrize("seed,probe_rows", [(0, 1 << 20), (1, 111)])
    def test_two_keys_randomized(self, seed, probe_rows):
        rng = np.random.default_rng(seed)
        nl, nr = 600, 500
        la = rng.integers(-20, 20, nl, dtype=np.int64)
        lb = rng.integers(0, 15, nl, dtype=np.int64)
        ra = rng.integers(-20, 20, nr, dtype=np.int64)
        rb = rng.integers(0, 15, nr, dtype=np.int64)
        left = Table(
            [Column.from_numpy(la), Column.from_numpy(lb),
             Column.from_numpy(np.arange(nl, dtype=np.int64))],
            ["a", "b", "lv"],
        )
        right = Table(
            [Column.from_numpy(ra), Column.from_numpy(rb),
             Column.from_numpy(np.arange(nr, dtype=np.int64))],
            ["a", "b", "rv"],
        )
        got = inner_join_batched_packed(
            left, right, ["a", "b"], probe_rows=probe_rows
        )
        assert got is not None
        want = inner_join(left, right, ["a", "b"])
        assert got.names == want.names
        assert _pairs(got) == _pairs(want)

    def test_q64_join_shape(self):
        # (item_sk, ticket_number): the q64 self-join key pair
        rng = np.random.default_rng(5)
        n = 2000
        item = rng.integers(1, 300, n, dtype=np.int64)
        ticket = rng.integers(1, 500, n, dtype=np.int64)
        left = Table(
            [Column.from_numpy(item), Column.from_numpy(ticket),
             Column.from_numpy(np.arange(n, dtype=np.int64))],
            ["item_sk", "ticket", "lv"],
        )
        right = Table(
            [Column.from_numpy(item[::-1].copy()),
             Column.from_numpy(ticket[::-1].copy()),
             Column.from_numpy(np.arange(n, dtype=np.int64))],
            ["item_sk", "ticket", "rv"],
        )
        got = inner_join_batched_packed(
            left, right, ["item_sk", "ticket"]
        )
        assert got is not None
        want = inner_join(left, right, ["item_sk", "ticket"])
        assert _pairs(got) == _pairs(want)
