"""Tests for the arithmetic f64 <-> bits codec (utils/ieee754.py).

Contract under test: exact for normals/zeros/infs; subnormals flush to zero
(XLA DAZ/FTZ); NaN canonicalized. FLOAT64 *storage* never uses this codec.
"""

import jax
import numpy as np
import pytest

from spark_rapids_jni_tpu.utils import ieee754


NORMAL_EDGE_VALUES = np.array(
    [
        0.0,
        -0.0,
        1.0,
        -1.0,
        1.5,
        1.1,
        0.1,
        2.0**-52 + 1.0,  # 1 + eps
        np.nextafter(1.0, 2.0),
        np.nextafter(1.0, 0.0),
        2.0**-1022,  # smallest normal
        1.7976931348623157e308,  # max finite
        np.inf,
        -np.inf,
        np.pi,
        123456789.123456789,
        -3e-308,
    ]
)


def test_bits_match_numpy_view():
    got = np.asarray(jax.jit(ieee754.f64_to_bits)(NORMAL_EDGE_VALUES))
    want = NORMAL_EDGE_VALUES.view(np.uint64)
    np.testing.assert_array_equal(got, want)


def test_roundtrip_exact():
    bits = NORMAL_EDGE_VALUES.view(np.uint64)
    back = np.asarray(jax.jit(ieee754.bits_to_f64)(bits))
    np.testing.assert_array_equal(back.view(np.uint64), bits)


def test_subnormals_flush_to_zero():
    subs = np.array([5e-324, -2.5e-310, np.nextafter(2.0**-1022, 0.0)])
    got = np.asarray(jax.jit(ieee754.f64_to_bits)(subs))
    # sign preserved, magnitude flushed (DAZ) — documented contract
    assert got[0] == 0
    assert got[1] == np.uint64(1) << np.uint64(63)
    back = np.asarray(jax.jit(ieee754.bits_to_f64)(subs.view(np.uint64)))
    np.testing.assert_array_equal(np.abs(back), 0.0)


def test_nan_canonicalized():
    vals = np.array([np.nan, -np.nan])
    got = np.asarray(jax.jit(ieee754.f64_to_bits)(vals))
    assert (got == np.uint64(0x7FF8000000000000)).all()
    back = np.asarray(jax.jit(ieee754.bits_to_f64)(got))
    assert np.isnan(back).all()


def test_random_roundtrip(rng):
    exps = rng.integers(-1000, 1000, 10_000)
    vals = np.ldexp(rng.standard_normal(10_000), exps)
    vals = vals[np.isfinite(vals) & (np.abs(vals) >= 2.0**-1022)]
    got = np.asarray(jax.jit(ieee754.f64_to_bits)(vals))
    np.testing.assert_array_equal(got, vals.view(np.uint64))
    back = np.asarray(jax.jit(ieee754.bits_to_f64)(got))
    np.testing.assert_array_equal(back, vals)


def test_dispatch_helpers_cpu_exact():
    vals = np.array([1.1, 5e-324, np.pi])  # bitcast path: subnormals exact too
    bits = np.asarray(jax.jit(ieee754.float_to_bits)(vals))
    np.testing.assert_array_equal(bits, vals.view(np.uint64))
    back = np.asarray(jax.jit(ieee754.bits_to_float)(bits))
    np.testing.assert_array_equal(back, vals)
