"""Cross-variant join fuzz vs a pandas oracle.

One randomized sweep over every join type x key configuration the
surface supports — single/multi integer keys, string keys, nullable
keys, nullable values — checking full multiset equality of the result
rows against ``pandas.merge`` with Spark null semantics (null keys
match nothing; outer sides still emit their unmatched rows). The
round-4 advisor found a silently-wrong mixed-dtype corner in exactly
this surface, so the fuzz holds every variant to the same oracle.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.join import (
    anti_join,
    full_join,
    inner_join,
    left_join,
    right_join,
    semi_join,
)


def _mk_table(rng, n, key_kind, null_keys, null_vals):
    kvalid = rng.random(n) > 0.15 if null_keys else None
    if key_kind == "str":
        kidx = rng.integers(0, 12, n)
        keys = [f"sku{int(v):03d}" for v in kidx]
        kcols = [
            Column.from_strings(keys)
            if kvalid is None
            else Column.from_strings(
                [k if ok else None
                 for k, ok in zip(keys, kvalid)]
            )
        ]
        knames = ["k"]
        pdk = {"k": keys}
    elif key_kind == "multi":
        a = rng.integers(-5, 5, n, dtype=np.int64)
        b = rng.integers(0, 4, n, dtype=np.int64)
        kcols = [
            Column.from_numpy(a, validity=kvalid),
            Column.from_numpy(b),
        ]
        knames = ["a", "b"]
        pdk = {"a": a, "b": b}
    else:
        k = rng.integers(-8, 8, n, dtype=np.int64)
        kcols = [Column.from_numpy(k, validity=kvalid)]
        knames = ["k"]
        pdk = {"k": k}
    v = rng.integers(0, 1000, n, dtype=np.int64)
    vvalid = rng.random(n) > 0.1 if null_vals else None
    vcol = Column.from_numpy(v, validity=vvalid)
    t = Table(kcols + [vcol], knames + ["v"])
    pdf = pd.DataFrame(pdk)
    if kvalid is not None:
        nk = knames[0]
        if key_kind != "str":
            pdf[nk] = pdf[nk].astype("Int64")
        pdf[nk] = pdf[nk].astype("object") if key_kind == "str" else pdf[nk]
        pdf.loc[~kvalid, nk] = pd.NA
    pdf["v"] = pd.array(v, dtype="Int64")
    if vvalid is not None:
        pdf.loc[~vvalid, "v"] = pd.NA
    return t, pdf, knames


def _rows(t: Table):
    cols = [c.to_pylist() for c in t.columns]
    return sorted(
        zip(*cols), key=lambda r: tuple((x is None, x) for x in r)
    )


def _pd_rows(df):
    out = []
    for row in df.itertuples(index=False):
        out.append(
            tuple(None if pd.isna(x) else x for x in row)
        )
    return sorted(out, key=lambda r: tuple((x is None, x) for x in r))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize(
    "key_kind,null_keys", [("int", False), ("int", True),
                           ("multi", False), ("multi", True),
                           ("str", False), ("str", True)]
)
def test_join_variants_vs_pandas(seed, key_kind, null_keys):
    kind_salt = {"int": 0, "multi": 1, "str": 2}[key_kind]
    rng = np.random.default_rng(seed * 7 + kind_salt)
    left, lpdf, on = _mk_table(rng, 60, key_kind, null_keys, True)
    right, rpdf, _ = _mk_table(rng, 45, key_kind, null_keys, False)
    rpdf = rpdf.rename(columns={"v": "rv"})
    right = Table(right.columns, on + ["rv"])

    # pandas: null keys match nothing <=> drop null-key rows before the
    # inner part and re-add for the outer sides
    l_nn = lpdf.dropna(subset=on)
    r_nn = rpdf.dropna(subset=on)
    inner_pd = l_nn.merge(r_nn, on=on, how="inner")

    got = inner_join(left, right, on)
    assert _rows(got) == _pd_rows(inner_pd[list(got.names)]), "inner"

    got = left_join(left, right, on)
    matched = l_nn.merge(r_nn, on=on, how="left")
    unmatched_null = lpdf[lpdf[on].isna().any(axis=1)].copy()
    unmatched_null["rv"] = pd.NA
    left_pd = pd.concat([matched, unmatched_null], ignore_index=True)
    assert _rows(got) == _pd_rows(left_pd[list(got.names)]), "left"

    got = semi_join(left, right, on)
    keys_r = set(map(tuple, r_nn[on].itertuples(index=False)))
    semi_pd = l_nn[
        l_nn[on].apply(tuple, axis=1).isin(keys_r)
    ]
    assert _rows(got) == _pd_rows(semi_pd[list(got.names)]), "semi"

    got = anti_join(left, right, on)
    anti_nn = l_nn[~l_nn[on].apply(tuple, axis=1).isin(keys_r)]
    anti_pd = pd.concat(
        [anti_nn, lpdf[lpdf[on].isna().any(axis=1)]],
        ignore_index=True,
    )
    assert _rows(got) == _pd_rows(anti_pd[list(got.names)]), "anti"

    got = right_join(left, right, on)
    matched_r = l_nn.merge(r_nn, on=on, how="right")
    right_null = rpdf[rpdf[on].isna().any(axis=1)].copy()
    right_null["v"] = pd.NA
    right_pd = pd.concat([matched_r, right_null], ignore_index=True)
    assert _rows(got) == _pd_rows(right_pd[list(got.names)]), "right"

    got = full_join(left, right, on)
    matched_f = l_nn.merge(r_nn, on=on, how="outer")
    full_pd = pd.concat(
        [matched_f, unmatched_null, right_null], ignore_index=True
    )
    assert _rows(got) == _pd_rows(full_pd[list(got.names)]), "full"
