"""Kernel tier tests (kernels/registry.py): predicate matrix, byte
parity against the bucketed/exact path at the bucket edges, fallback
discipline under injected kernel faults, the <5 µs disabled-path gate,
and independent compile caching for kernel vs non-kernel callables.

Everything runs with ``interpret=True`` on the CPU tier — the same
kernel code the TPU compiles through Mosaic (kernels/__init__.py
``default_interpret``)."""

import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plancheck as pc
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.kernels import registry
from spark_rapids_jni_tpu.utils import buckets, config, metrics

# the acceptance bucket edges: below / at / above a pow2 bucket
EDGES = (1023, 1024, 1025)


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    for f in ("KERNELS", "FAULTS", "METRICS", "BUCKETS"):
        config.clear_flag(f)
    metrics.reset()


def _table(n, *, seed=0, null_vals=True, key_nulls=False, neg=True):
    """Two-column (k int64, v int64) table; v optionally nullable."""
    rng = np.random.default_rng(seed)
    lo = -1000 if neg else 0
    k = rng.integers(lo, 1000, n, dtype=np.int64)
    v = rng.integers(-50, 50, n, dtype=np.int64)
    kv = rng.random(n) > 0.2 if key_nulls else None
    vv = rng.random(n) > 0.3 if null_vals else None
    return Table(
        [Column.from_numpy(k, validity=kv),
         Column.from_numpy(v, validity=vv)],
        ["k", "v"],
    )


def _wire(t):
    """The padding-stripped wire 5-tuple — the byte-parity comparator
    (logical rows only; the bucket-padding region is free)."""
    return rb._table_to_wire(t)


def _ab(op, table, rest=()):
    """Dispatch once with kernels ON and once OFF; assert byte parity
    and return the ON-side wire tuple + the kernel counters."""
    config.set_flag("METRICS", "1")
    config.set_flag("KERNELS", "off")
    off = _wire(rb._dispatch(op, table, rest))
    metrics.reset()
    config.set_flag("KERNELS", "on")
    on = _wire(rb._dispatch(op, table, rest))
    ctr = dict(metrics.snapshot().get("counters", {}))
    assert on == off, f"kernel tier changed bytes for {op}"
    return on, ctr


def _launched(ctr):
    return int(ctr.get("kernel.launches", 0))


# ---------------------------------------------------------------------------
# predicate matrix
# ---------------------------------------------------------------------------


class TestPredicates:
    def test_registry_names_match_specs(self):
        assert registry.KERNEL_NAMES == frozenset(registry._REGISTRY)
        for name, spec in registry._REGISTRY.items():
            assert spec.name == name
            assert spec.ops, name
            assert callable(spec.applicable) and callable(spec.runner)

    def test_registry_matches_plancheck_rules(self):
        # the SRT012 parity triple, dynamically
        assert registry.KERNEL_NAMES == frozenset(pc._KERNEL_RULES)
        for kname, (opname, _) in pc._KERNEL_RULES.items():
            assert opname in registry._REGISTRY[kname].ops

    def test_sort_predicate(self):
        t = _table(100)
        ok = {"op": "sort_by", "keys": [{"column": 0}]}
        assert registry._a_packed_sort(ok, t, ()) is None
        multi = {"op": "sort_by",
                 "keys": [{"column": 0}, {"column": 1}]}
        assert "multi-key" in registry._a_packed_sort(multi, t, ())
        nk = _table(100, key_nulls=True)
        assert "nullable key" in registry._a_packed_sort(ok, nk, ())
        # oversized bucket: past SORT_MAX_ROWS the predicate declines
        # without building anything
        big = Table(
            [Column.from_numpy(
                np.zeros(registry.SORT_MAX_ROWS * 2, np.int64))],
            ["k"],
        )
        assert "VMEM" in registry._a_packed_sort(ok, big, ())

    def test_groupby_predicate(self):
        t = _table(100)
        ok = {"op": "groupby", "by": [0],
              "aggs": [{"column": 1, "agg": "sum"}]}
        assert registry._a_hash_groupby(ok, t, ()) is None
        bad_agg = {"op": "groupby", "by": [0],
                   "aggs": [{"column": 1, "agg": "collect_list"}]}
        assert "non-decomposable" in registry._a_hash_groupby(
            bad_agg, t, ())
        multi = {"op": "groupby", "by": [0, 1],
                 "aggs": [{"column": 1, "agg": "sum"}]}
        assert "multi-column" in registry._a_hash_groupby(multi, t, ())
        ft = Table(
            [Column.from_numpy(np.arange(8, dtype=np.int64)),
             Column.from_numpy(np.ones(8, np.float64))], ["k", "v"])
        assert "order-sensitive" in registry._a_hash_groupby(ok, ft, ())

    def test_join_predicate(self):
        l, r = _table(64), _table(32, seed=1)
        ok = {"op": "join", "on": [0], "how": "inner"}
        assert registry._a_hash_join(ok, l, [r]) is None
        left = {"op": "join", "on": [0], "how": "left"}
        assert "exact machinery" in registry._a_hash_join(left, l, [r])
        assert "missing build-side" in registry._a_hash_join(ok, l, [])
        nk = _table(32, seed=1, key_nulls=True)
        assert "build side" in registry._a_hash_join(ok, l, [nk])

    def test_rows_predicates(self):
        t = _table(16)
        assert registry._a_row_pack({"op": "to_rows"}, t, ()) is None
        st = Table([Column.from_strings(["a", "b"])])
        assert "no fixed-width" in registry._a_row_pack(
            {"op": "to_rows"}, st, ())
        packed = rb._dispatch({"op": "to_rows"}, t, ())
        unp = {"op": "from_rows",
               "type_ids": [int(dt.TypeId.INT64)] * 2, "scales": [0, 0]}
        assert registry._a_row_unpack(unp, packed, ()) is None
        assert "legacy flat" in registry._a_row_unpack(unp, t, ())

    def test_plancheck_tags_and_kernel_ops(self):
        sch = [pc.ColType(dt.TypeId.INT64), pc.ColType(dt.TypeId.INT64)]
        rep = pc.analyze(
            [{"op": "sort_by", "keys": [{"column": 0}]},
             {"op": "groupby", "by": [0],
              "aggs": [{"column": 1, "agg": "sum"}]},
             {"op": "to_rows"}],
            schema=sch, rows=500,
        )
        tags = [e["kernel"] for e in rep["ops"]]
        assert tags == ["packed_sort", "hash_groupby", "row_pack"]
        assert rep["kernel_ops"] == [0, 1, 2]
        txt = pc.render_report(rep)
        assert "~kernel:packed_sort" in txt
        # a string key is statically ineligible, and stays untagged
        rep2 = pc.analyze(
            [{"op": "sort_by", "keys": [{"column": 0}]}],
            schema=[pc.ColType(dt.TypeId.STRING)], rows=10,
        )
        assert rep2["ops"][0]["kernel"] is None
        assert rep2["kernel_ops"] == []


# ---------------------------------------------------------------------------
# byte parity at the bucket edges
# ---------------------------------------------------------------------------


class TestParity:
    @pytest.mark.parametrize("n", EDGES)
    def test_sort_parity(self, n):
        t = _table(n, seed=n)
        op = {"op": "sort_by",
              "keys": [{"column": 0, "ascending": False}]}
        _, ctr = _ab(op, t)
        assert _launched(ctr) == 1
        assert int(ctr.get("kernel.fallbacks", 0)) == 0

    @pytest.mark.parametrize("n", EDGES)
    def test_groupby_parity(self, n):
        t = _table(n, seed=n + 7)
        op = {"op": "groupby", "by": [0],
              "aggs": [{"column": 1, "agg": "sum"},
                       {"column": 1, "agg": "count"},
                       {"column": 1, "agg": "min"},
                       {"column": 1, "agg": "max"}]}
        _, ctr = _ab(op, t)
        assert _launched(ctr) == 1

    @pytest.mark.parametrize("how", ["inner", "semi", "anti"])
    def test_join_parity(self, how):
        rng = np.random.default_rng(5)
        # unique build keys (duplicates decline the inner kernel)
        bk = rng.permutation(4096)[:1000].astype(np.int64)
        r = Table([Column.from_numpy(bk),
                   Column.from_numpy(
                       rng.integers(0, 9, 1000, dtype=np.int64))],
                  ["k", "p"])
        l = _table(1023, seed=11, neg=False)
        op = {"op": "join", "on": [0], "how": how}
        _, ctr = _ab(op, l, [r])
        assert _launched(ctr) == 1

    @pytest.mark.parametrize("n", EDGES)
    def test_rows_round_trip_parity(self, n):
        t = _table(n, seed=n + 3)
        _, ctr = _ab({"op": "to_rows"}, t)
        assert _launched(ctr) == 1
        config.set_flag("KERNELS", "off")
        packed = rb._dispatch({"op": "to_rows"}, t, ())
        op = {"op": "from_rows",
              "type_ids": [int(dt.TypeId.INT64)] * 2, "scales": [0, 0]}
        _, ctr = _ab(op, packed)
        assert _launched(ctr) == 1

    def test_fuzz_small_buckets(self):
        """Many sizes across a shrunken bucket ladder: padding/occupancy
        masks exercised at every edge."""
        config.set_flag("BUCKETS", "8,64,512,2048")
        try:
            for n in (1, 7, 8, 9, 63, 65, 511, 513, 700):
                t = _table(n, seed=n)
                _ab({"op": "sort_by", "keys": [{"column": 0}]}, t)
                _ab({"op": "groupby", "by": [0],
                     "aggs": [{"column": 1, "agg": "max"}]}, t)
        finally:
            config.clear_flag("BUCKETS")
            buckets.cache_clear()

    def test_decline_adds_no_counters_for_uncovered_op(self):
        t = _table(64)
        config.set_flag("METRICS", "1")
        config.set_flag("KERNELS", "on")
        metrics.reset()
        rb._dispatch({"op": "filter", "mask": 1}, Table(
            [t.columns[0],
             Column.from_numpy(np.ones(64, dtype=np.bool_))]), ())
        ctr = metrics.snapshot().get("counters", {})
        assert not any(k.startswith("kernel.") for k in ctr)


# ---------------------------------------------------------------------------
# fallback discipline (chaos site "kernel")
# ---------------------------------------------------------------------------


class TestFallback:
    def test_injected_fault_falls_back_byte_identical(self):
        t = _table(1024, seed=2)
        op = {"op": "sort_by", "keys": [{"column": 0}]}
        config.set_flag("KERNELS", "off")
        want = _wire(rb._dispatch(op, t, ()))
        config.set_flag("METRICS", "1")
        config.set_flag("KERNELS", "on")
        config.set_flag("FAULTS", "seed=3,kernel:permanent:1:1")
        live_before = len(rb._RESIDENT)
        metrics.reset()
        got = _wire(rb._dispatch(op, t, ()))
        ctr = metrics.snapshot().get("counters", {})
        assert got == want
        assert int(ctr.get("kernel.fallbacks", 0)) == 1
        assert int(ctr.get("kernel.launches", 0)) == 0
        # no leaked resident tables from the failed launch
        assert len(rb._RESIDENT) == live_before
        # the one-shot rule is spent: the next dispatch launches
        got2 = _wire(rb._dispatch(op, t, ()))
        assert got2 == want
        assert int(metrics.snapshot()["counters"].get(
            "kernel.launches", 0)) == 1

    def test_cancellation_propagates(self):
        from spark_rapids_jni_tpu.utils import faults

        t = _table(256, seed=4)
        config.set_flag("KERNELS", "on")
        # a permanent fault is swallowed into a fallback; Cancelled
        # must NOT be (cooperative cancellation wins over fallback)
        assert registry.dispatch_kernel(
            {"op": "sort_by", "keys": [{"column": 0}]}, t, (), "sort_by"
        ) is not None
        with pytest.raises(faults.Cancelled):
            spec = registry._REGISTRY["packed_sort"]

            def boom(op, table, rest):
                raise faults.Cancelled("stop")

            object.__setattr__(spec, "runner", boom)
            try:
                registry.dispatch_kernel(
                    {"op": "sort_by", "keys": [{"column": 0}]},
                    t, (), "sort_by",
                )
            finally:
                object.__setattr__(
                    spec, "runner", registry._r_packed_sort)


# ---------------------------------------------------------------------------
# gates: disabled-path cost + independent compile caching
# ---------------------------------------------------------------------------


class TestGates:
    def test_disabled_path_under_5us(self):
        config.set_flag("KERNELS", "off")
        t = _table(64)
        op = {"op": "sort_by", "keys": [{"column": 0}]}
        registry.dispatch_kernel(op, t, (), "sort_by")  # warm the gate
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            registry.dispatch_kernel(op, t, (), "sort_by")
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"disabled path {per_call * 1e6:.2f}µs"

    def test_kernel_and_exact_callables_cache_independently(self):
        config.set_flag("METRICS", "1")
        t = _table(1024, seed=9)
        op = {"op": "sort_by", "keys": [{"column": 0}]}
        buckets.cache_clear()
        config.set_flag("KERNELS", "off")
        rb._dispatch(op, t, ())
        metrics.reset()
        config.set_flag("KERNELS", "on")
        rb._dispatch(op, t, ())
        ctr = metrics.snapshot()["counters"]
        # the kernel callable is its own cache entry: first ON dispatch
        # misses even though the OFF path already compiled this shape
        assert int(ctr.get("compile_cache.miss", 0)) >= 1
        metrics.reset()
        rb._dispatch(op, t, ())
        ctr = metrics.snapshot()["counters"]
        # second ON dispatch is a pure hit — no recompile
        assert int(ctr.get("compile_cache.miss", 0)) == 0
        assert int(ctr.get("compile_cache.hit", 0)) >= 1
        metrics.reset()
        config.set_flag("KERNELS", "off")
        rb._dispatch(op, t, ())
        ctr = metrics.snapshot()["counters"]
        # ...and flipping back OFF still hits the original entry
        assert int(ctr.get("compile_cache.miss", 0)) == 0
