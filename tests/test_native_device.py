"""Native -> device compute path tests (VERDICT r1 item 1).

The reference's whole purpose is foreign callers reaching device
kernels through the native library (RowConversionJni.cpp:24-66). These
tests drive that path here: the C ABI's embedded JAX runtime
(src/cpp/jax_runtime.cpp) dispatching table ops to the XLA backend —
once through ctypes (the library JOINS this interpreter: identical
native code to a JVM call, minus startup), and once as a PURE NATIVE
process (build/native_demo, C++ with no Python until the library hosts
one — the RowConversionTest.java analog for the native->TPU stack).
"""

import json
import os
import subprocess

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available() or not native.jax_runtime_available(),
    reason="native library with embedded JAX runtime not built",
)


def _wire(arr: np.ndarray) -> int:
    return native.buffer_create(arr.tobytes(), "test-in")


class TestCtypesDeviceDispatch:
    def test_init_and_platform(self):
        native.jax_init()
        assert native.jax_platform() in ("cpu", "tpu", "axon")

    def test_groupby_on_device_matches_oracle(self):
        rng = np.random.default_rng(11)
        n = 500
        k = rng.integers(0, 20, n).astype(np.int64)
        v = rng.standard_normal(n)
        hk, hv = _wire(k), _wire(v)
        try:
            op = json.dumps(
                {
                    "op": "groupby",
                    "by": [0],
                    "aggs": [
                        {"column": 1, "agg": "sum"},
                        {"column": 1, "agg": "count"},
                    ],
                }
            )
            ids = [dt.TypeId.INT64.value, dt.TypeId.FLOAT64.value]
            out_ids, out_s, out_d, out_v, out_n = native.jax_table_op(
                op, ids, [0, 0], [hk, hv], [None, None], n
            )
            assert out_n == len(np.unique(k))
            keys = np.frombuffer(
                native.buffer_bytes(out_d[0]), np.int64, out_n
            )
            sums = np.frombuffer(
                native.buffer_bytes(out_d[1]), np.float64, out_n
            )
            got = dict(zip(keys.tolist(), sums.tolist()))
            want = {int(u): float(v[k == u].sum()) for u in np.unique(k)}
            assert set(got) == set(want)
            for u in want:
                assert got[u] == pytest.approx(want[u], rel=1e-12)
        finally:
            for h in [hk, hv, *out_d, *[x for x in out_v if x]]:
                native.buffer_release(h)

    def test_row_roundtrip_through_device(self):
        """to_rows on device -> from_rows on device -> original columns,
        all initiated through the C ABI. The packed rows travel as a
        true LIST<UINT8> wire column (offsets + child, the reference's
        output type) rather than the old flat-UINT8 workaround."""
        n = 96
        a = np.arange(n, dtype=np.int64) * 3 - 7
        b = (np.arange(n) % 2).astype(np.int32)
        bv = (np.arange(n) % 5 != 0).astype(np.uint8)
        ids = [dt.TypeId.INT64.value, dt.TypeId.INT32.value]
        ha, hb, hbv = _wire(a), _wire(b), _wire(bv)
        handles = [ha, hb, hbv]
        try:
            out_ids0, out_s0, rd, rv, rrows = native.jax_table_op(
                json.dumps({"op": "to_rows"}),
                ids,
                [0, 0],
                [ha, hb],
                [None, hbv],
                n,
            )
            handles += [rd[0], *[x for x in rv if x]]
            assert out_ids0[0] == dt.TypeId.LIST.value
            assert out_s0[0] == dt.TypeId.UINT8.value  # child type id
            assert rrows == n
            # wire layout: int32 offsets[n+1] then the child bytes; the
            # offsets must be the arithmetic row_size sequence
            raw = native.buffer_bytes(rd[0])
            offs = np.frombuffer(raw, np.int32, n + 1)
            row_size = offs[1] - offs[0]
            np.testing.assert_array_equal(
                offs, np.arange(n + 1, dtype=np.int32) * row_size
            )
            back_op = json.dumps(
                {
                    "op": "from_rows",
                    "type_ids": ids,
                    "scales": [0, 0],
                    "num_rows": n,
                }
            )
            out_ids, _, od, ov, on = native.jax_table_op(
                back_op,
                [dt.TypeId.LIST.value],
                [dt.TypeId.UINT8.value],
                [rd[0]],
                [None],
                n,
            )
            handles += [*od, *[x for x in ov if x]]
            assert on == n and out_ids == ids
            aa = np.frombuffer(native.buffer_bytes(od[0]), np.int64, n)
            bb = np.frombuffer(native.buffer_bytes(od[1]), np.int32, n)
            np.testing.assert_array_equal(aa, a)
            vb = np.frombuffer(native.buffer_bytes(ov[1]), np.uint8, n)
            np.testing.assert_array_equal(vb, bv)
            np.testing.assert_array_equal(bb[vb == 1], b[bv == 1])
        finally:
            for h in handles:
                native.buffer_release(h)

    def test_sort_on_device(self):
        rng = np.random.default_rng(5)
        x = rng.permutation(200).astype(np.int64)
        hx = _wire(x)
        try:
            _, _, od, ov, on = native.jax_table_op(
                json.dumps(
                    {"op": "sort_by", "keys": [{"column": 0}]}
                ),
                [dt.TypeId.INT64.value],
                [0],
                [hx],
                [None],
                200,
            )
            got = np.frombuffer(native.buffer_bytes(od[0]), np.int64, on)
            np.testing.assert_array_equal(got, np.sort(x))
        finally:
            for h in [hx, *od, *[v for v in ov if v]]:
                native.buffer_release(h)

    def test_bad_op_reports_error(self):
        hx = _wire(np.arange(4, dtype=np.int64))
        try:
            with pytest.raises(RuntimeError, match="unknown table op"):
                native.jax_table_op(
                    json.dumps({"op": "nonsense"}),
                    [dt.TypeId.INT64.value],
                    [0],
                    [hx],
                    [None],
                    4,
                )
        finally:
            native.buffer_release(hx)


class TestPureNativeCaller:
    def test_native_demo_binary(self):
        """C++ process with no Python: the library hosts the interpreter
        and runs groupby + device row transpose on the XLA backend."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        demo = os.path.join(repo, "build", "native_demo")
        if not os.path.exists(demo):
            pytest.skip("native_demo not built")
        env = dict(os.environ)
        env["SRT_PYTHONPATH"] = repo
        # the subprocess owns its interpreter; keep it on the CPU backend
        # (tiny shapes, no TPU contention from the test tier). The env
        # var JAX_PLATFORMS alone is ineffective against the axon
        # plugin; runtime_bridge honors SRT_JAX_PLATFORMS via the
        # config API.
        env["JAX_PLATFORMS"] = "cpu"
        env["SRT_JAX_PLATFORMS"] = "cpu"
        res = subprocess.run(
            [demo],
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        assert "native_demo: ok" in res.stdout


class TestJniBridgeExecution:
    def test_jni_harness_binary(self):
        """Round-3 VERDICT item 3: the REAL JNI bridge entry points
        (Java_com_nvidia_spark_rapids_jni_*) executed against the mock
        JNIEnv — groupby + row round-trip + error/cleanup paths + zero
        leaked handles, with no JDK in the image."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        harness = os.path.join(repo, "build", "jni_harness")
        if not os.path.exists(harness):
            pytest.skip("jni_harness not built")
        env = dict(os.environ)
        env["SRT_PYTHONPATH"] = repo
        env["JAX_PLATFORMS"] = "cpu"
        env["SRT_JAX_PLATFORMS"] = "cpu"
        res = subprocess.run(
            [harness],
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        assert "jni_harness: ok" in res.stdout


class TestResidentTableChaining:
    """Round-3 VERDICT item 4: device-resident handle chaining — ops
    chain over resident table ids with host bytes crossing the boundary
    only at upload/download (the reference's device-pointer model,
    RowConversionJni.cpp:31,54)."""

    def test_chain_filter_join_groupby(self, rng):
        n = 600
        item = rng.integers(0, 20, n).astype(np.int64)
        qty = rng.integers(1, 10, n).astype(np.int64)
        dim_item = np.arange(20, dtype=np.int64)
        dim_cat = rng.integers(0, 4, 20).astype(np.int64)

        h = [_wire(item), _wire(qty), _wire(dim_item), _wire(dim_cat)]
        i64 = dt.TypeId.INT64.value
        out_handles = []
        try:
            sales = native.jax_table_upload(
                [i64, i64], [0, 0], [h[0], h[1]], [None, None], n
            )
            items = native.jax_table_upload(
                [i64, i64], [0, 0], [h[2], h[3]], [None, None], 20
            )
            # filter qty > 5: append a mask column then filter op
            mask = (qty > 5).astype(np.uint8)
            hm = _wire(mask)
            h.append(hm)
            with_mask = native.jax_table_upload(
                [i64, i64, dt.TypeId.BOOL8.value], [0, 0, 0],
                [h[0], h[1], hm], [None, None, None], n,
            )
            filtered = native.jax_table_op_resident(
                json.dumps({"op": "filter", "mask": 2}), [with_mask]
            )
            joined = native.jax_table_op_resident(
                json.dumps({"op": "join", "on": [0]}), [filtered, items]
            )
            agg = native.jax_table_op_resident(
                json.dumps({
                    "op": "groupby", "by": [2],
                    "aggs": [{"column": 1, "agg": "sum"}],
                }),
                [joined],
            )
            ids, scales, od, ov, rows = native.jax_table_download(agg)
            out_handles = [*od, *[v for v in ov if v]]

            cat_of = dict(zip(dim_item.tolist(), dim_cat.tolist()))
            keep = qty > 5
            want = {}
            for it, q in zip(item[keep], qty[keep]):
                want[cat_of[int(it)]] = want.get(cat_of[int(it)], 0) + int(q)
            got_k = np.frombuffer(native.buffer_bytes(od[0]), np.int64, rows)
            got_s = np.frombuffer(native.buffer_bytes(od[1]), np.int64, rows)
            assert dict(zip(got_k.tolist(), got_s.tolist())) == want

            for t in (sales, items, with_mask, filtered, joined, agg):
                native.jax_table_free(t)
            assert native.jax_resident_table_count() == 0
        finally:
            for hh in h + out_handles:
                try:
                    native.buffer_release(hh)
                except RuntimeError:
                    pass

    def test_unknown_table_id_raises(self):
        with pytest.raises(RuntimeError, match="unknown device table"):
            native.jax_table_num_rows(999_999)
        with pytest.raises(RuntimeError, match="unknown device table"):
            native.jax_table_free(999_999)

    def test_num_rows_and_free(self, rng):
        a = rng.integers(0, 5, 40).astype(np.int64)
        ha = _wire(a)
        try:
            t = native.jax_table_upload(
                [dt.TypeId.INT64.value], [0], [ha], [None], 40
            )
            assert native.jax_table_num_rows(t) == 40
            native.jax_table_free(t)
            with pytest.raises(RuntimeError, match="unknown device table"):
                native.jax_table_num_rows(t)
        finally:
            native.buffer_release(ha)
