"""Native runtime tests: row codec golden vs the XLA path + handle registry.

The reference's only repo-local test is the row round trip through the
real JNI -> CUDA stack (RowConversionTest.java:28-59). Here the native
host codec is additionally pinned byte-for-byte against the device (XLA)
implementation — two independent implementations of the normative row
format spec (RowConversion.java:43-102) must agree exactly.
"""

import subprocess

import numpy as np
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import rows
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import native


@pytest.fixture(scope="session", autouse=True)
def built_native():
    """Build the native lib once (configure-once discipline, the
    build-libcudf.xml:23-30 analog); skip the module if no toolchain."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build = os.path.join(repo, "build")
    lib = os.path.join(build, "libspark_rapids_tpu.so")
    if not os.path.exists(lib):
        try:
            subprocess.run(
                ["cmake", "-S", os.path.join(repo, "src"), "-B", build],
                check=True,
                capture_output=True,
            )
            subprocess.run(
                ["cmake", "--build", build],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            pytest.skip(f"cannot build native library: {e}")
    native.reset_for_tests()
    if not native.available():
        pytest.skip("native library unavailable")
    yield


def _host_buffers(table: Table):
    """Device table -> the host-side buffers the C ABI consumes."""
    type_ids = [int(c.dtype.id) for c in table.columns]
    col_data = []
    col_valid = []
    for c in table.columns:
        arr = np.asarray(c.data)
        if c.dtype.is_boolean:
            arr = arr.astype(np.uint8)  # BOOL8 = 1 byte in the row format
        col_data.append(np.ascontiguousarray(arr))
        col_valid.append(
            None if c.validity is None else np.asarray(c.validity)
        )
    return type_ids, col_data, col_valid


def _mixed_table(rng, n=257):
    return Table(
        [
            Column.from_numpy(rng.integers(-(2**60), 2**60, n)),
            Column.from_numpy(rng.standard_normal(n)),
            Column.from_numpy(
                rng.integers(-(2**28), 2**28, n).astype(np.int32),
                validity=rng.random(n) > 0.3,
            ),
            Column.from_numpy(rng.random(n) > 0.5),
            Column.from_numpy(rng.standard_normal(n).astype(np.float32)),
            Column.from_numpy(
                rng.integers(-100, 100, n).astype(np.int8),
                validity=rng.random(n) > 0.1,
            ),
            Column.from_numpy(
                rng.integers(-(2**25), 2**25, n).astype(np.int32),
                dtype=dt.decimal32(-3),
            ),
            Column.from_numpy(
                rng.integers(-(2**50), 2**50, n),
                validity=rng.random(n) > 0.5,
                dtype=dt.decimal64(-8),
            ),
        ],
        list("abcdefgh"),
    )


class TestLayoutParity:
    def test_layout_matches_python(self, rng):
        t = _mixed_table(rng, n=8)
        type_ids = [int(c.dtype.id) for c in t.columns]
        offs, widths, voff, vbytes, row_size = native.compute_row_layout(
            type_ids
        )
        pylayout = rows.compute_fixed_width_layout(t.dtypes())
        assert tuple(offs) == pylayout.column_offsets
        assert tuple(widths) == pylayout.column_widths
        assert voff == pylayout.validity_offset
        assert vbytes == pylayout.validity_bytes
        assert row_size == pylayout.row_size

    def test_max_rows_per_batch_parity(self):
        lib = native.load()
        for row_size in (8, 24, 64, 1000):
            assert lib.srt_max_rows_per_batch(
                row_size
            ) == rows.max_rows_per_batch(row_size)

    def test_rejects_string(self):
        with pytest.raises(RuntimeError, match="non-fixed-width"):
            native.compute_row_layout([int(dt.TypeId.STRING)])


class TestCodecGolden:
    def test_pack_matches_xla(self, rng):
        t = _mixed_table(rng)
        type_ids, col_data, col_valid = _host_buffers(t)
        got = native.pack_rows(type_ids, col_data, col_valid)
        want = rows.to_rows(t)[0].to_numpy()
        assert got.shape == want.shape
        assert np.array_equal(got, want)

    def test_unpack_round_trip(self, rng):
        t = _mixed_table(rng)
        type_ids, col_data, col_valid = _host_buffers(t)
        packed = native.pack_rows(type_ids, col_data, col_valid)
        widths = [c.dtype.itemsize for c in t.columns]
        data_out, valid_out = native.unpack_rows(type_ids, packed, widths)
        for c, dbytes, vbytes_arr in zip(t.columns, data_out, valid_out):
            orig = np.asarray(c.data)
            if c.dtype.is_boolean:
                orig = orig.astype(np.uint8)
            assert dbytes.tobytes() == orig.tobytes()
            want_valid = (
                np.ones(c.row_count, dtype=np.uint8)
                if c.validity is None
                else np.asarray(c.validity).astype(np.uint8)
            )
            assert np.array_equal(vbytes_arr, want_valid)

    def test_unpack_feeds_device_from_rows(self, rng):
        # native-packed bytes must be readable by the device-side decoder
        t = _mixed_table(rng, n=64)
        type_ids, col_data, col_valid = _host_buffers(t)
        packed = native.pack_rows(type_ids, col_data, col_valid)
        pr = rows.packed_rows_from_numpy(packed, t.dtypes())
        back = rows.from_rows(pr, t.dtypes(), names=t.names)
        assert back.to_pydict() == t.to_pydict()

    def test_empty_table(self):
        type_ids = [int(dt.TypeId.INT64)]
        out = native.pack_rows(
            type_ids, [np.zeros(0, dtype=np.int64)], [None]
        )
        assert out.shape == (0, 16)


class TestJniBridgeCompiles:
    def test_jni_sources_typecheck(self):
        """No JDK in this image, so the real JNI build is gated off
        (src/CMakeLists.txt find_package(JNI)); compile-check the bridge
        against a minimal jni.h stub so signature typos still fail."""
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        stub = os.path.join(repo, "tests", "data", "jni_stub")
        for src in ("RowConversionJni.cpp", "HostBufferJni.cpp"):
            proc = subprocess.run(
                [
                    "g++",
                    "-std=c++17",
                    "-fsyntax-only",
                    "-Wall",
                    "-Wextra",
                    "-Werror",
                    "-DSRT_HAVE_JNI=1",
                    "-I",
                    stub,
                    "-I",
                    os.path.join(repo, "src", "include"),
                    os.path.join(repo, "src", "jni", src),
                ],
                capture_output=True,
                text=True,
            )
            assert proc.returncode == 0, f"{src}: {proc.stderr}"


class TestHandleRegistry:
    def test_create_read_release(self):
        h = native.buffer_create(b"hello world", tag="t1")
        assert native.buffer_bytes(h) == b"hello world"
        native.buffer_release(h)
        with pytest.raises(RuntimeError, match="unknown handle"):
            native.buffer_bytes(h)

    def test_refcount(self):
        h = native.buffer_create(b"x" * 16, tag="rc")
        native.buffer_retain(h)
        native.buffer_release(h)
        assert native.buffer_bytes(h) == b"x" * 16  # still alive
        native.buffer_release(h)
        with pytest.raises(RuntimeError):
            native.buffer_release(h)  # double release is an error, not UB

    def test_leak_report(self):
        before = native.live_handle_count()
        h = native.buffer_create(b"leak-me", tag="leaky")
        assert native.live_handle_count() == before + 1
        report = native.leak_report()
        assert "leaky" in report and "refcount=1" in report
        native.buffer_release(h)
        assert native.live_handle_count() == before

    def test_version(self):
        assert "spark-rapids-tpu" in native.version()
