"""Factory/utility coverage (cudf factories surface, SURVEY.md §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import factories as fct
from spark_rapids_jni_tpu.column import Column, Table


class TestConstructors:
    def test_sequence(self):
        c = fct.sequence(5, start=10, step=3, dtype=dt.INT64)
        assert c.to_pylist() == [10, 13, 16, 19, 22]
        assert c.dtype == dt.INT64

    def test_sequence_float64_storage(self):
        c = fct.sequence(4, start=0.5, step=0.25, dtype=dt.FLOAT64)
        assert c.to_pylist() == [0.5, 0.75, 1.0, 1.25]
        assert c.data.dtype == jnp.uint64  # IEEE bit storage

    def test_full(self):
        assert fct.full(3, 7, dt.INT32).to_pylist() == [7, 7, 7]
        assert fct.full(2, "ab", dt.STRING).to_pylist() == ["ab", "ab"]

    def test_full_null(self):
        c = fct.full_null(4, dt.INT64)
        assert c.to_pylist() == [None] * 4
        s = fct.full_null(3, dt.STRING)
        assert s.to_pylist() == [None] * 3

    def test_empty_like(self):
        base = Column.from_strings(["abc", "de"])
        e = fct.empty_like(base, n=5)
        assert e.row_count == 5 and e.pad_width == base.pad_width


class TestCopying:
    def test_concatenate_with_nulls(self):
        a = Column.from_numpy(np.array([1, 2], dtype=np.int64))
        b = Column.from_numpy(
            np.array([3, 4], dtype=np.int64),
            validity=np.array([True, False]),
        )
        out = fct.concatenate([a, b])
        assert out.to_pylist() == [1, 2, 3, None]

    def test_concatenate_strings_mixed_pad(self):
        a = Column.from_strings(["a", "bb"])
        b = Column.from_strings(["cccc", None])
        out = fct.concatenate([a, b])
        assert out.to_pylist() == ["a", "bb", "cccc", None]

    def test_concatenate_dtype_mismatch(self):
        a = Column.from_numpy(np.array([1], dtype=np.int64))
        b = Column.from_numpy(np.array([1], dtype=np.int32))
        with pytest.raises(TypeError):
            fct.concatenate([a, b])

    def test_concatenate_tables(self):
        t1 = Table.from_pydict({"x": np.array([1, 2]), "s": ["a", "b"]})
        t2 = Table.from_pydict({"x": np.array([3]), "s": ["c"]})
        out = fct.concatenate_tables([t1, t2])
        assert out.to_pydict() == {"x": [1, 2, 3], "s": ["a", "b", "c"]}

    def test_slice_split(self):
        t = Table.from_pydict({"x": np.arange(10)})
        parts = fct.split_table(t, [3, 7])
        assert [p.row_count for p in parts] == [3, 4, 3]
        assert parts[1]["x"].to_pylist() == [3, 4, 5, 6]

    def test_interleave(self):
        a = Column.from_numpy(np.array([1, 2], dtype=np.int32))
        b = Column.from_numpy(
            np.array([10, 20], dtype=np.int32),
            validity=np.array([True, False]),
        )
        out = fct.interleave_columns([a, b])
        assert out.to_pylist() == [1, 10, 2, None]

    def test_copy_if_else(self):
        l = Column.from_numpy(np.array([1, 2, 3], dtype=np.int64))
        r = Column.from_numpy(np.array([10, 20, 30], dtype=np.int64))
        m = Column.from_numpy(
            np.array([True, False, True]),
            validity=np.array([True, True, False]),
        )
        out = fct.copy_if_else(l, r, m)
        # null mask row selects rhs (Spark CASE WHEN semantics)
        assert out.to_pylist() == [1, 20, 30]

    def test_copy_if_else_strings(self):
        l = Column.from_strings(["aa", "bb"])
        r = Column.from_strings(["xxxx", "y"])
        m = Column.from_numpy(np.array([True, False]))
        assert fct.copy_if_else(l, r, m).to_pylist() == ["aa", "y"]


class TestBitmask:
    @pytest.mark.parametrize("n", [1, 7, 8, 9, 31, 32, 33, 100])
    def test_pack_unpack_round_trip(self, n, rng):
        valid = jnp.asarray(rng.random(n) > 0.4)
        packed = fct.pack_bitmask(valid)
        assert packed.shape[0] == (n + 7) // 8
        back = fct.unpack_bitmask(packed, n)
        assert np.array_equal(np.asarray(back), np.asarray(valid))

    def test_matches_arrow_packing(self, rng):
        # device packing must agree with Arrow's LSB-first wire format
        from spark_rapids_jni_tpu.interop import pack_validity

        n = 50
        valid = rng.random(n) > 0.5
        ours = bytes(np.asarray(fct.pack_bitmask(jnp.asarray(valid))))
        arrow = pack_validity(valid)
        assert ours == arrow

    def test_jittable(self):
        f = jax.jit(fct.pack_bitmask)
        v = jnp.asarray(np.array([True] * 9))
        assert np.asarray(fct.unpack_bitmask(f(v), 9)).all()
