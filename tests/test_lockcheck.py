"""Dynamic lock-order detector: cycles, sanctioned order, overhead.

The contract (the compute-sanitizer --tool racecheck analog of this
repo's CI discipline): under ``SPARK_RAPIDS_TPU_LOCKCHECK=on`` every
tracked package lock records per-thread held sets and a global
acquisition-order graph; cycles and inversions of the sanctioned
``registry -> session -> scheduler -> spill`` order are reported
through the flight/metrics exit planes; and with the flag off an
acquisition costs one cached generation compare (< 5 µs, the
metrics-gate overhead class).
"""

import threading
import time

import pytest

from spark_rapids_jni_tpu.utils import config, flight, lockcheck


@pytest.fixture
def lockcheck_on():
    config.set_flag("LOCKCHECK", "1")
    lockcheck.reset()
    try:
        yield
    finally:
        config.clear_flag("LOCKCHECK")
        lockcheck.reset()


class TestCycleDetection:
    def test_two_thread_opposite_order_cycle(self, lockcheck_on):
        """The canonical deadlock shape: thread 1 takes A then B,
        thread 2 takes B then A. Serialized by an event so the test
        never actually deadlocks — the GRAPH still shows the cycle."""
        a = lockcheck.make_lock("alpha.a")
        b = lockcheck.make_lock("beta.b")
        first_done = threading.Event()

        def t1():
            with a:
                with b:
                    pass
            first_done.set()

        def t2():
            first_done.wait(5)
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start(), th2.start()
        th1.join(5), th2.join(5)

        doc = lockcheck.report()
        assert "alpha.a->beta.b" in doc["edges"]
        assert "beta.b->alpha.a" in doc["edges"]
        assert doc["cycles"], doc
        with pytest.raises(AssertionError, match="cycles"):
            lockcheck.assert_clean()

    def test_consistent_order_no_cycle(self, lockcheck_on):
        a = lockcheck.make_lock("alpha.a")
        b = lockcheck.make_lock("beta.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        doc = lockcheck.assert_clean()
        assert doc["edges"]["alpha.a->beta.b"]["count"] == 3
        assert doc["cycles"] == []


class TestSanctionedOrder:
    def test_inversion_reported(self, lockcheck_on):
        spill = lockcheck.make_lock("spill.events")
        registry = lockcheck.make_lock("registry.resident")
        with spill:
            with registry:  # spill (rank 3) held while taking rank 0
                pass
        doc = lockcheck.report()
        assert len(doc["order_violations"]) == 1
        v = doc["order_violations"][0]
        assert v["held"] == "spill.events"
        assert v["acquiring"] == "registry.resident"
        assert v["order"] == "registry->session->scheduler->spill"
        with pytest.raises(AssertionError, match="order_violations"):
            lockcheck.assert_clean()

    def test_sanctioned_direction_clean(self, lockcheck_on):
        registry = lockcheck.make_lock("registry.resident")
        session = lockcheck.make_lock("session.state")
        sched = lockcheck.make_lock("scheduler.queues")
        spill = lockcheck.make_lock("spill.events")
        with registry, session, sched, spill:
            pass
        doc = lockcheck.assert_clean()
        assert doc["order_violations"] == []

    def test_unranked_names_never_inversions(self, lockcheck_on):
        # names outside LOCK_ORDER contribute edges (cycle detection)
        # but no rank facts
        z = lockcheck.make_lock("zeta.z")
        registry = lockcheck.make_lock("registry.r")
        with z:
            with registry:
                pass
        assert lockcheck.report()["order_violations"] == []

    def test_same_name_instances_not_an_order_fact(self, lockcheck_on):
        # two sessions each have a session.state lock; holding one
        # while taking the other is instance layering, not lock order
        s1 = lockcheck.make_lock("session.state")
        s2 = lockcheck.make_lock("session.state")
        with s1:
            with s2:
                pass
        doc = lockcheck.report()
        assert doc["edges"] == {}


class TestPrimitives:
    def test_rlock_reentry_no_self_edge(self, lockcheck_on):
        rl = lockcheck.make_rlock("registry.resident")
        with rl:
            with rl:  # re-entry: no edge, no violation
                pass
        doc = lockcheck.assert_clean()
        assert doc["edges"] == {}

    def test_held_set_balanced_after_condition_wait(self, lockcheck_on):
        """A timed-out wait must re-add exactly one held entry — an
        unbalanced held set would fabricate edges from the CV lock to
        everything the thread touches afterwards."""
        lk = lockcheck.make_lock("session.state")
        cv = lockcheck.make_condition(lk)
        other = lockcheck.make_lock("alpha.x")
        with cv:
            cv.wait(0.01)  # times out; held entry released + re-added
        with other:
            pass
        doc = lockcheck.report()
        assert "session.state->alpha.x" not in doc["edges"]

    def test_condition_wait_for_wakes(self, lockcheck_on):
        lk = lockcheck.make_lock("session.state")
        cv = lockcheck.make_condition(lk)
        ready = []

        def waker():
            time.sleep(0.02)
            with cv:
                ready.append(1)
                cv.notify_all()

        th = threading.Thread(target=waker)
        th.start()
        with cv:
            assert cv.wait_for(lambda: ready, timeout=5)
        th.join(5)
        lockcheck.assert_clean()

    def test_condition_over_rlock(self, lockcheck_on):
        rl = lockcheck.make_rlock("registry.resident")
        cv = lockcheck.make_condition(rl)
        other = lockcheck.make_lock("alpha.x")
        with cv:
            cv.wait(0.01)
        with other:
            pass
        doc = lockcheck.report()
        assert "registry.resident->alpha.x" not in doc["edges"]

    def test_make_condition_rejects_raw_locks(self):
        with pytest.raises(TypeError, match="tracked"):
            lockcheck.make_condition(threading.Lock())

    def test_try_acquire_nonblocking(self, lockcheck_on):
        lk = lockcheck.make_lock("alpha.a")
        assert lk.acquire(blocking=False)
        assert not lk.acquire(blocking=False)
        lk.release()


class TestBlocking:
    def test_lock_held_across_dispatch_reported(self, lockcheck_on):
        registry = lockcheck.make_lock("registry.resident")
        with registry:
            lockcheck.note_blocking("device_dispatch")
        doc = lockcheck.report()
        assert len(doc["held_across_blocking"]) == 1
        v = doc["held_across_blocking"][0]
        assert v["kind"] == "device_dispatch"
        assert v["held"] == ["registry.resident"]
        # informational by default (the repage-under-registry-lock path
        # is deliberate) — strict mode fails on it
        lockcheck.assert_clean()
        with pytest.raises(AssertionError, match="held_across_blocking"):
            lockcheck.assert_clean(strict_blocking=True)

    def test_no_held_locks_no_report(self, lockcheck_on):
        lockcheck.note_blocking("device_dispatch")
        assert lockcheck.report()["held_across_blocking"] == []


class TestOverheadAndGating:
    def test_disabled_acquisition_under_5us(self):
        """The acceptance bound: flag off, an acquisition is one cached
        generation compare — budget < 5 µs each (measured as an
        acquire+release pair to keep the clock read out of the loop)."""
        assert not lockcheck.enabled()
        lk = lockcheck.make_lock("alpha.bench")
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            lk.acquire()
            lk.release()
        per_acquisition = (time.perf_counter() - t0) / (2 * n)
        assert per_acquisition < 5e-6, f"{per_acquisition * 1e6:.2f}us"

    def test_disabled_records_nothing(self):
        lockcheck.reset()
        a = lockcheck.make_lock("spill.x")
        b = lockcheck.make_lock("registry.y")
        with a:
            with b:  # would be an inversion if recording
                pass
        doc = lockcheck.report()
        assert doc["edges"] == {} and doc["order_violations"] == []

    def test_flag_flip_takes_effect_via_generation(self, lockcheck_on):
        lk = lockcheck.make_lock("alpha.a")
        with lk:
            pass
        assert lockcheck.report()["acquisitions"] >= 1
        config.clear_flag("LOCKCHECK")
        lockcheck.reset()
        with lk:
            pass
        assert lockcheck.report()["acquisitions"] == 0


class TestReporting:
    def test_exit_section_rides_flight_dump(self, lockcheck_on):
        config.set_flag("FLIGHT", True)
        try:
            with lockcheck.make_lock("alpha.a"):
                pass
            snap = flight.snapshot()
        finally:
            config.clear_flag("FLIGHT")
        sec = snap["sections"]["lockcheck"]
        assert sec["enabled"] is True
        assert sec["acquisitions"] >= 1

    def test_summary_line_shape(self, lockcheck_on):
        with lockcheck.make_lock("alpha.a"):
            pass
        line = lockcheck.summary_line()
        assert line.startswith("lockcheck:")
        assert "cycles" in line and "order violations" in line

    def test_report_folds_lock_metrics(self, lockcheck_on):
        from spark_rapids_jni_tpu.utils import metrics

        config.set_flag("METRICS", "1")
        try:
            s = lockcheck.make_lock("spill.s")
            r = lockcheck.make_lock("registry.r")
            with s:
                with r:
                    pass
            lockcheck.report()
            snap = metrics.snapshot()
        finally:
            config.clear_flag("METRICS")
        assert snap["counters"].get("lock.order_violations") == 1
        assert snap["gauges"]["lock.tracked_edges"]["value"] == 1


class TestRealModuleWiring:
    """The conversions satellite: the runtime's own locks are tracked
    under their sanctioned dotted names."""

    def test_registry_and_serving_locks_are_tracked(self):
        from spark_rapids_jni_tpu import runtime_bridge as rb
        from spark_rapids_jni_tpu.serving import scheduler as sched_mod

        assert isinstance(rb._RESIDENT_LOCK, lockcheck.TrackedRLock)
        assert rb._RESIDENT_LOCK.name == "registry.resident"
        s = sched_mod.FairScheduler(workers=1)
        assert isinstance(s._lock, lockcheck.TrackedLock)
        assert s._lock.name == "scheduler.queues"

    def test_session_lock_named(self):
        from spark_rapids_jni_tpu.serving.session import Session

        sess = Session("sid", "t", 1.0, 1 << 20)
        assert sess._lock.name == "session.state"
