"""Numeric cast-lattice fuzz vs a Spark non-ANSI oracle.

Random values through int-width narrowing (two's-complement wrap),
float->int truncation, int->float, bool conversions, and
decimal<->int rescales — null passthrough everywhere."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops.cast import cast

_INTS = [
    (dt.INT8, np.int8), (dt.INT16, np.int16),
    (dt.INT32, np.int32), (dt.INT64, np.int64),
]


@pytest.mark.parametrize("src_dt,src_np", _INTS)
@pytest.mark.parametrize("dst_dt,dst_np", _INTS)
def test_int_width_lattice_wraps(src_dt, src_np, dst_dt, dst_np):
    rng = np.random.default_rng(1)
    info = np.iinfo(src_np)
    v = rng.integers(
        info.min, int(info.max) + 1, 300, dtype=np.int64
    ).astype(src_np)
    valid = rng.random(300) > 0.15
    col = Column.from_numpy(v, validity=valid)
    got = cast(col, dst_dt).to_pylist()
    want = [
        int(x.astype(dst_np)) if ok else None
        for x, ok in zip(v, valid)
    ]
    assert got == want


def test_float_to_int_truncates_toward_zero():
    v = np.array([1.9, -1.9, 0.5, -0.5, 2.0, -2.0, 1e9 + 0.7])
    col = Column.from_numpy(v)
    got = cast(col, dt.INT64).to_pylist()
    assert got == [1, -1, 0, 0, 2, -2, 1000000000]


def test_int_to_float_and_back():
    rng = np.random.default_rng(2)
    v = rng.integers(-(2 ** 50), 2 ** 50, 200, dtype=np.int64)
    col = Column.from_numpy(v)
    f = cast(col, dt.FLOAT64)
    back = cast(f, dt.INT64).to_pylist()
    # within 2^53, float64 round-trips ints exactly
    assert back == [int(x) for x in v]


def test_bool_conversions():
    v = np.array([0, 1, -3, 7, 0], dtype=np.int64)
    col = Column.from_numpy(v)
    got = cast(col, dt.BOOL8).to_pylist()
    assert got == [False, True, True, True, False]
    b = Column.from_numpy(np.array([True, False, True]))
    assert cast(b, dt.INT32).to_pylist() == [1, 0, 1]
    assert cast(b, dt.FLOAT64).to_pylist() == [1.0, 0.0, 1.0]


def test_decimal_int_rescales():
    d2 = dt.DType(dt.TypeId.DECIMAL64, -2)
    v = np.array([150, -375, 0, 999], dtype=np.int64)  # 1.50 -3.75 0 9.99
    col = Column.from_numpy(v, dtype=d2)
    # decimal -> wider scale decimal
    d1 = dt.DType(dt.TypeId.DECIMAL64, -1)
    # cudf fixed_point rescale truncates toward zero: -3.75 -> -3.7
    assert np.asarray(cast(col, d1).data).tolist() == [15, -37, 0, 99]
    # int -> decimal and back
    i = Column.from_numpy(np.array([7, -3], dtype=np.int64))
    dec = cast(i, d2)
    assert np.asarray(dec.data).tolist() == [700, -300]
    assert cast(dec, dt.INT64).to_pylist() == [7, -3]
