"""The 64-bit join-fault fence (round-4 VERDICT item 3).

The fused single-shot join graph kills the TPU worker at >= 32M rows
(tools/xla_join_fault_repro.py), so above ``FUSED_PROBE_MAX_ROWS`` the
eager join APIs must route through chunk-probed graphs automatically —
the reference never lets callers choose safety (its 2 GB batch split is
automatic, row_conversion.cu:476-479,505-511). These tests lower the
threshold and fake an accelerator backend to pin (a) that the routing
fires and (b) that the fenced results equal the fused-path oracle.
"""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import join as join_mod


@pytest.fixture
def fenced(monkeypatch):
    """Force the fence on: tiny threshold + pretend accelerator."""
    monkeypatch.setattr(join_mod, "FUSED_PROBE_MAX_ROWS", 7)
    monkeypatch.setattr(join_mod, "_on_accelerator", lambda: True)


def _tables(n_left=50, n_right=40, seed=0):
    rng = np.random.default_rng(seed)
    left = Table(
        [
            Column.from_numpy(rng.integers(0, 12, n_left, dtype=np.int64)),
            Column.from_numpy(np.arange(n_left, dtype=np.int64)),
        ],
        ["k", "lv"],
    )
    right = Table(
        [
            Column.from_numpy(rng.integers(0, 12, n_right, dtype=np.int64)),
            Column.from_numpy(np.arange(n_right, dtype=np.int64) * 10),
        ],
        ["k", "rv"],
    )
    return left, right


def _sorted_rows(t: Table):
    cols = [np.asarray(c.to_numpy()) for c in t.columns]
    rows = sorted(zip(*cols))
    return rows


def test_inner_join_routes_to_batched(fenced, monkeypatch):
    left, right = _tables()
    calls = {}
    real = join_mod.inner_join_batched

    def spy(*a, **k):
        calls["hit"] = True
        return real(*a, **k)

    monkeypatch.setattr(join_mod, "inner_join_batched", spy)
    out = join_mod.inner_join(left, right, ["k"])
    assert calls.get("hit"), "fence did not route inner_join to batched"
    # oracle: the fused path with the fence off
    monkeypatch.setattr(join_mod, "_on_accelerator", lambda: False)
    oracle = join_mod.inner_join(left, right, ["k"])
    assert out.names == oracle.names
    assert _sorted_rows(out) == _sorted_rows(oracle)


def test_small_tables_keep_fused_path(fenced, monkeypatch):
    left, right = _tables(n_left=5, n_right=5)

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("small join must not take the batched path")

    monkeypatch.setattr(join_mod, "inner_join_batched", boom)
    join_mod.inner_join(left, right, ["k"])


@pytest.mark.parametrize(
    "api", ["left_join", "right_join", "full_join", "semi_join", "anti_join"]
)
def test_fenced_joins_match_fused_oracle(fenced, monkeypatch, api):
    left, right = _tables(seed=3)
    out = getattr(join_mod, api)(left, right, ["k"])
    monkeypatch.setattr(join_mod, "_on_accelerator", lambda: False)
    oracle = getattr(join_mod, api)(left, right, ["k"])
    assert out.names == oracle.names
    assert _sorted_rows(out) == _sorted_rows(oracle)


def test_fenced_counts_match(fenced, monkeypatch):
    left, right = _tables(seed=4)
    got_inner = int(join_mod.inner_join_count(left, right, ["k"]))
    got_left = int(join_mod.left_join_count(left, right, ["k"]))
    got_mask = np.asarray(join_mod.membership_mask(left, right, ["k"]))
    monkeypatch.setattr(join_mod, "_on_accelerator", lambda: False)
    assert got_inner == int(join_mod.inner_join_count(left, right, ["k"]))
    assert got_left == int(join_mod.left_join_count(left, right, ["k"]))
    np.testing.assert_array_equal(
        got_mask, np.asarray(join_mod.membership_mask(left, right, ["k"]))
    )


def test_fence_inert_under_jit(fenced, monkeypatch):
    """Tracers must fall through to the fused graph: the chunked probe
    helper raising under trace proves the fence never fired there."""
    import jax

    left, right = _tables(seed=5, n_left=53, n_right=41)  # fresh shapes
    oracle = int(join_mod.inner_join_count(left, right, ["k"]))

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("chunked probe must not fire under jit")

    monkeypatch.setattr(join_mod, "_chunk_ranges_fn", boom)
    fn = jax.jit(lambda l, r: join_mod.inner_join_count(l, r, ["k"]))
    assert int(fn(left, right)) == oracle


def test_fenced_masked_count_matches(fenced, monkeypatch):
    """Occupancy masks ride the chunked probe (no fence bypass)."""
    import jax.numpy as jnp

    left, right = _tables(seed=6)
    lv = jnp.asarray(np.arange(50) % 3 != 0)
    rv = jnp.asarray(np.arange(40) % 4 != 0)
    got = int(
        join_mod.inner_join_count(
            left, right, ["k"], left_valid=lv, right_valid=rv
        )
    )
    monkeypatch.setattr(join_mod, "_on_accelerator", lambda: False)
    assert got == int(
        join_mod.inner_join_count(
            left, right, ["k"], left_valid=lv, right_valid=rv
        )
    )


def test_streaming_join_batches_match_batched(monkeypatch):
    """inner_join_batches yields per-chunk pieces whose concatenation
    equals inner_join_batched (which is now defined by it)."""
    import numpy as np

    from spark_rapids_jni_tpu.ops.copying import concatenate

    left, right = _tables(n_left=300, n_right=200, seed=9)
    pieces = list(
        join_mod.inner_join_batches(left, right, ["k"], probe_rows=64)
    )
    assert len(pieces) >= 4  # genuinely streamed
    whole = join_mod.inner_join_batched(
        left, right, ["k"], probe_rows=64
    )
    got = concatenate(pieces)
    assert got.row_count == whole.row_count
    assert _sorted_rows(got) == _sorted_rows(whole)


def test_streaming_join_empty_sides():
    left, right = _tables(n_left=10, n_right=0)
    assert list(join_mod.inner_join_batches(left, right, ["k"])) == []


def test_batched_string_join_mismatched_pads():
    """String keys with different pad widths between sides must still
    match through the chunk-probed path (pre-r4 this returned 0 rows:
    positional word compare silently truncated to the narrower side)."""
    lvals = ["apple", "pear", "fig", "apple"]
    rvals = ["apple", "a-very-long-string-key", "fig"]
    left = Table(
        [Column.from_strings(lvals),
         Column.from_numpy(np.arange(4, dtype=np.int64))],
        ["k", "lv"],
    )
    right = Table(
        [Column.from_strings(rvals),
         Column.from_numpy(np.arange(3, dtype=np.int64))],
        ["k", "rv"],
    )
    assert left["k"].data.shape[1] != right["k"].data.shape[1]
    direct = join_mod.inner_join(left, right, ["k"])
    batched = join_mod.inner_join_batched(
        left, right, ["k"], probe_rows=2
    )
    assert batched.row_count == direct.row_count == 3

    def rows(t):
        return sorted(
            zip(
                t["k"].to_pylist(),
                np.asarray(t["lv"].to_numpy()).tolist(),
                np.asarray(t["rv"].to_numpy()).tolist(),
            )
        )

    assert rows(batched) == rows(direct)
    # the eager chunked-ranges path (outer joins, counts) too
    got = int(join_mod.inner_join_count(left, right, ["k"]))
    assert got == 3


def test_mixed_key_dtypes_rejected_both_paths():
    """ADVICE r4: the chunked eager path must reject STRING vs
    non-STRING key pairs like the fused path does, not silently zip-
    truncate the word comparison."""
    from spark_rapids_jni_tpu import dtype as dt
    import jax.numpy as jnp

    smat = jnp.asarray(
        np.frombuffer(b"abcdefgh", np.uint8).reshape(2, 4)
    )
    str_t = Table(
        [Column(smat, dt.STRING, None, jnp.full((2,), 4, jnp.int32))],
        ["k"],
    )
    int_t = Table(
        [Column.from_numpy(np.array([1, 2], dtype=np.int64))], ["k"]
    )
    with pytest.raises(TypeError, match="STRING vs non-STRING"):
        join_mod._equalize_string_key_pads(str_t, int_t, ["k"], ["k"])
    with pytest.raises(TypeError, match="STRING vs non-STRING"):
        # generator wrapper: must raise at CALL time, not first next()
        join_mod.inner_join_batches(str_t, int_t, ["k"], probe_rows=8)


def test_inner_join_batches_validates_at_call_time(fenced):
    left, right = _tables()
    with pytest.raises(ValueError, match="probe_rows"):
        join_mod.inner_join_batches(left, right, [0], probe_rows=0)
