"""Plan-level fused dispatch: segmentation, parity, and compile bounds.

The contract under test (the ISSUE-4 tentpole): ``table_plan_wire`` /
``table_plan_resident`` compile each maximal run of fusable ops into
ONE cached executable and return results BYTE-IDENTICAL to the per-op
wire path (which tests/test_buckets.py pins byte-identical to the
exact path) — null counts, sort stability, group counts included — at
bucket-boundary row counts (1023/1024/1025). The recompile-regression
half pins the launch/compile economics: an 8-size ragged stream
through a 4-op fusable plan compiles at most ``#buckets`` fused
executables, double-sourced from the cache counters and from
``jax.log_compiles`` output filtered to ``srt_fused_plan`` (the
test_buckets_recompile.py discipline).
"""

import json
import logging

import jax
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plan as plan_mod
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.utils import buckets, config, metrics

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)
STR = int(dt.TypeId.STRING)


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    config.clear_flag("BUCKETS")
    config.clear_flag("METRICS")


# ---------------------------------------------------------------------------
# segmentation
# ---------------------------------------------------------------------------


CAST = {"op": "cast", "column": 0, "type_id": F64}
SORT = {"op": "sort_by", "keys": [{"column": 0}]}
GROUP = {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]}
JOIN = {"op": "join", "on": [0]}


class TestSegmentation:
    def test_fusable_run_is_one_segment(self):
        segs = plan_mod.segment_plan([CAST, SORT, GROUP])
        assert segs == [("fused", [CAST, SORT, GROUP])]

    def test_groupby_is_tail_only(self):
        segs = plan_mod.segment_plan([CAST, GROUP, SORT, CAST])
        assert segs == [
            ("fused", [CAST, GROUP]),
            ("fused", [SORT, CAST]),
        ]

    def test_non_fusable_is_a_boundary(self):
        segs = plan_mod.segment_plan([CAST, SORT, JOIN, CAST, SORT])
        assert segs == [
            ("fused", [CAST, SORT]),
            ("exact", [JOIN]),
            ("fused", [CAST, SORT]),
        ]

    def test_single_op_runs_stay_exact(self):
        # a 1-op run gains nothing from a separate plan cache entry:
        # the per-op bucketed runner already caches it under its own key
        segs = plan_mod.segment_plan([CAST, JOIN, SORT])
        assert segs == [
            ("exact", [CAST]),
            ("exact", [JOIN]),
            ("exact", [SORT]),
        ]

    def test_collect_groupby_not_fusable(self):
        collect = {
            "op": "groupby", "by": [0],
            "aggs": [{"column": 1, "agg": "collect_list"}],
        }
        assert not plan_mod.op_fusable(collect)
        assert plan_mod.segment_plan([CAST, SORT, collect]) == [
            ("fused", [CAST, SORT]),
            ("exact", [collect]),
        ]

    def test_negative_slice_not_fusable(self):
        # negative bounds must raise from the exact path
        assert not plan_mod.op_fusable({"op": "slice", "start": -1})
        assert plan_mod.op_fusable({"op": "slice", "start": 1, "stop": 9})


# ---------------------------------------------------------------------------
# fused-vs-per-op parity at bucket boundaries
# ---------------------------------------------------------------------------


def _string_wire(strings):
    """List of python strings -> Arrow offsets+payload wire bytes."""
    payload = b"".join(s.encode() for s in strings)
    offs = np.zeros(len(strings) + 1, np.int32)
    np.cumsum([len(s.encode()) for s in strings], out=offs[1:])
    return offs.tobytes() + payload


def _cols(n: int):
    """Shared parity-table columns: int64 key, int64 value with nulls,
    BOOL8 mask, and a low-cardinality STRING column."""
    rng = np.random.default_rng(n)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % 7 != 0).astype(np.uint8)
    mask = (v > 0).astype(np.uint8)
    strs = [f"w{int(x) % 5}ord" for x in k]
    return [
        (I64, 0, k.tobytes(), None),
        (I64, 0, v.tobytes(), valid.tobytes()),
        (B8, 0, mask.tobytes(), None),
        (STR, 0, _string_wire(strs), None),
    ]


# >= 5 multi-op chains over the shared 4-column table. Column indices
# track the per-op semantics (filter drops its mask column).
CHAINS = {
    "filter_cast_sort_groupby": [
        {"op": "filter", "mask": 2},
        {"op": "cast", "column": 1, "type_id": F64},
        {"op": "sort_by", "keys": [{"column": 0}]},
        {"op": "groupby", "by": [0],
         "aggs": [{"column": 1, "agg": "sum"},
                  {"column": 1, "agg": "count"}]},
    ],
    "rlike_cast_sort": [
        {"op": "rlike", "column": 3, "pattern": "w[0-2]o"},
        {"op": "cast", "column": 1, "type_id": F64},
        {"op": "sort_by", "keys": [{"column": 0}]},
    ],
    "distinct_sort_slice": [
        {"op": "distinct", "keys": [0, 1]},
        {"op": "sort_by",
         "keys": [{"column": 0}, {"column": 1, "ascending": False}]},
        {"op": "slice", "start": 3, "stop": 77},
    ],
    "cast_cast_sort_distinct_groupby": [
        {"op": "cast", "column": 1, "type_id": F64},
        {"op": "cast", "column": 0, "type_id": int(dt.TypeId.INT32)},
        {"op": "sort_by", "keys": [{"column": 1}]},
        {"op": "distinct", "keys": [0]},
        {"op": "groupby", "by": [0],
         "aggs": [{"column": 1, "agg": "max"}]},
    ],
    "slice_filter_sort": [
        {"op": "slice", "start": 0, "stop": 999_999},  # stop clamps to n
        {"op": "filter", "mask": 2},
        {"op": "sort_by", "keys": [{"column": 1}, {"column": 0}]},
    ],
}

BOUNDARY_SIZES = (1023, 1024, 1025)


def _run_plan_wire(chain, cols, n):
    return rb.table_plan_wire(
        json.dumps(chain),
        [c[0] for c in cols], [c[1] for c in cols],
        [c[2] for c in cols], [c[3] for c in cols], n,
    )


def _run_per_op_wire(chain, cols, n):
    cur = (
        [c[0] for c in cols], [c[1] for c in cols],
        [c[2] for c in cols], [c[3] for c in cols], n,
    )
    for op in chain:
        cur = rb.table_op_wire(json.dumps(op), *cur)
    return cur


class TestFusedParity:
    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    @pytest.mark.parametrize("chain", sorted(CHAINS))
    def test_fused_equals_per_op_and_exact(self, chain, n):
        cols = _cols(n)
        ops = CHAINS[chain]
        config.set_flag("BUCKETS", "")
        fused = _run_plan_wire(ops, cols, n)
        per_op = _run_per_op_wire(ops, cols, n)
        config.set_flag("BUCKETS", "off")
        exact = _run_per_op_wire(ops, cols, n)
        # byte-identical 5-tuples: type ids, scales, data bytes
        # (values, sort order, group sums), validity bytes (null
        # counts) and row counts all included
        assert fused == per_op
        assert fused == exact

    def test_fused_actually_fused(self):
        # the parity above is meaningless if everything silently fell
        # back: the 4-op chain must run as ONE fused segment
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", True)
        metrics.reset()
        _run_plan_wire(
            CHAINS["filter_cast_sort_groupby"], _cols(1024), 1024
        )
        c = metrics.snapshot()["counters"]
        assert c["plan.segments"] == 1
        assert c["plan.fused_segments"] == 1
        assert c["plan.fused_ops"] == 4
        assert c.get("plan.fallbacks", 0) == 0
        assert c.get("plan.exact_ops", 0) == 0

    def test_resident_plan_matches_wire_plan(self):
        n = 1025
        cols = _cols(n)
        ops = CHAINS["filter_cast_sort_groupby"]
        config.set_flag("BUCKETS", "")
        fused = _run_plan_wire(ops, cols, n)
        tid = rb.table_upload_wire(
            [c[0] for c in cols], [c[1] for c in cols],
            [c[2] for c in cols], [c[3] for c in cols], n,
        )
        out_id = rb.table_plan_resident(json.dumps(ops), [tid])
        got = rb.table_download_wire(out_id)
        rb.table_free(tid)
        rb.table_free(out_id)
        assert got == fused

    def test_plan_with_join_boundary(self):
        # a non-fusable multi-table op splits segments and consumes a
        # rest table; the whole plan still matches per-op dispatch
        n = 600
        rng = np.random.default_rng(5)
        k = rng.integers(0, 50, n, dtype=np.int64)
        v = rng.integers(-9, 9, n, dtype=np.int64)
        rk = np.arange(0, 50, dtype=np.int64)
        rv = rng.integers(0, 5, 50, dtype=np.int64)
        up = lambda *arrs: rb.table_upload_wire(
            [I64] * len(arrs), [0] * len(arrs),
            [a.tobytes() for a in arrs], [None] * len(arrs),
            len(arrs[0]),
        )
        plan = [
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "cast", "column": 1, "type_id": F64},
            {"op": "join", "on": [0]},
            {"op": "sort_by", "keys": [{"column": 0}, {"column": 1}]},
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 2, "agg": "sum"}]},
        ]
        lt, rt = up(k, v), up(rk, rv)
        out_id = rb.table_plan_resident(json.dumps(plan), [lt, rt])
        got = rb.table_download_wire(out_id)
        for t in (lt, rt, out_id):
            rb.table_free(t)

        cur = up(k, v)
        for op in plan:
            ids = [cur, up(rk, rv)] if op["op"] == "join" else [cur]
            nxt = rb.table_op_resident(json.dumps(op), ids)
            for t in ids:
                rb.table_free(t)
            cur = nxt
        want = rb.table_download_wire(cur)
        rb.table_free(cur)
        assert got == want

    def test_fused_failure_replays_per_op(self, monkeypatch):
        # a broken fused builder must not change results — the segment
        # replays per-op and the failure is counted + WARN'd once
        def boom(op, t, n, rv):
            raise RuntimeError("injected fused failure")

        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", True)
        n = 1024
        cols = _cols(n)
        ops = CHAINS["filter_cast_sort_groupby"]
        want = _run_per_op_wire(ops, cols, n)
        monkeypatch.setattr(plan_mod, "_FUSED",
                            dict(plan_mod._FUSED, cast=boom))
        # a warm cache would launch the previously compiled segment
        # without ever reaching the patched builder
        buckets.cache_clear()
        metrics.reset()
        got = _run_plan_wire(ops, cols, n)
        assert got == want
        c = metrics.snapshot()["counters"]
        assert c["plan.fallbacks"] == 1
        assert c["plan.exact_ops"] == 4
        assert c.get("plan.fused_segments", 0) == 0

    def test_huge_slice_bound_stays_fused(self):
        # a valid stop past int32 range clamps (like the exact path)
        # instead of overflowing the traced int32 conversion into a
        # permanent per-call fallback
        config.set_flag("BUCKETS", "")
        config.set_flag("METRICS", True)
        n = 1024
        cols = _cols(n)
        ops = [
            {"op": "cast", "column": 1, "type_id": F64},
            {"op": "slice", "start": 1, "stop": 2 ** 31},
        ]
        want = _run_per_op_wire(ops, cols, n)
        buckets.cache_clear()
        metrics.reset()
        got = _run_plan_wire(ops, cols, n)
        assert got == want and got[4] == n - 1
        c = metrics.snapshot()["counters"]
        assert c.get("plan.fallbacks", 0) == 0
        assert c["plan.fused_segments"] == 1

    def test_op_error_surfaces_from_exact_path(self):
        config.set_flag("BUCKETS", "")
        n = 1024
        cols = _cols(n)
        bad = [
            {"op": "cast", "column": 1, "type_id": F64},
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "unknown_op"},
        ]
        with pytest.raises(ValueError, match="unknown table op"):
            _run_plan_wire(bad, cols, n)

    def test_malformed_plan_rejected(self):
        cols = _cols(8)
        with pytest.raises(TypeError, match="JSON list"):
            _run_plan_wire({"op": "cast"}, cols, 8)
        with pytest.raises(ValueError, match="op objects"):
            rb.table_plan_wire(
                json.dumps(["cast"]),
                [c[0] for c in cols], [c[1] for c in cols],
                [c[2] for c in cols], [c[3] for c in cols], 8,
            )


class TestFactoriesEntry:
    def test_run_plan_matches_wire_plan(self):
        from spark_rapids_jni_tpu import factories
        from spark_rapids_jni_tpu.column import Column, Table

        config.set_flag("BUCKETS", "")
        n = 1023
        rng = np.random.default_rng(2)
        k = rng.integers(0, 9, n, dtype=np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        m = v > 0
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(v),
             Column.from_numpy(m, dtype=dt.BOOL8)],
            ["k", "v", "m"],
        )
        ops = [
            {"op": "filter", "mask": 2},
            {"op": "sort_by", "keys": [{"column": 0}, {"column": 1}]},
            {"op": "distinct", "keys": [0]},
        ]
        got = factories.run_plan(ops, t)
        assert got.logical_rows is None  # exact by default
        padded = factories.run_plan(ops, t, unpad=False)
        assert padded.logical_rows == got.row_count
        # oracle: the per-op wire path on the same bytes
        want = _run_per_op_wire(
            ops,
            [(I64, 0, k.tobytes(), None), (I64, 0, v.tobytes(), None),
             (B8, 0, m.astype(np.uint8).tobytes(), None)],
            n,
        )
        assert got.row_count == want[4]
        assert np.asarray(got.columns[0].data).tobytes() == want[2][0]
        assert np.asarray(got.columns[1].data).tobytes() == want[2][1]


# ---------------------------------------------------------------------------
# recompile regression: one executable per segment per bucket
# ---------------------------------------------------------------------------


# 8 ragged sizes spanning exactly TWO buckets of the 1024 x2 ladder
# (the test_buckets_recompile.py stream shape)
SIZES = (911, 977, 1013, 1024, 1031, 1499, 1777, 2047)
N_BUCKETS = 2

PLAN_4OP = [
    {"op": "filter", "mask": 2},
    {"op": "cast", "column": 1, "type_id": F64},
    {"op": "sort_by", "keys": [{"column": 0}]},
    {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]},
]


class _CompileLog(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


def _plan_stream():
    for n in SIZES:
        rng = np.random.default_rng(n)
        k = rng.integers(0, 7, n, dtype=np.int64)
        v = rng.integers(-5, 5, n, dtype=np.int64)
        m = (v > 0).astype(np.uint8)
        out = rb.table_plan_wire(
            json.dumps(PLAN_4OP), [I64, I64, B8], [0, 0, 0],
            [k.tobytes(), v.tobytes(), m.tobytes()],
            [None, None, None], n,
        )
        assert out[4] > 0


def _captured_plan_stream():
    handler = _CompileLog()
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(handler)
    try:
        with jax.log_compiles():
            _plan_stream()
    finally:
        jax_logger.removeHandler(handler)
    return [m for m in handler.messages if m.startswith("Compiling ")]


class TestPlanRecompile:
    def test_ragged_stream_compiles_at_most_buckets_executables(self):
        config.set_flag("BUCKETS", "1024:2")
        config.set_flag("METRICS", True)
        jax.clear_caches()
        buckets.cache_clear()
        metrics.reset()
        compiles = _captured_plan_stream()

        snap = metrics.snapshot()
        misses = snap["counters"]["compile_cache.miss"]
        hits = snap["counters"].get("compile_cache.hit", 0)
        # ONE segment per plan call -> at most one executable per
        # bucket across the whole ragged stream; every further call is
        # a cache hit == one launch of the cached fused executable
        assert misses <= N_BUCKETS, f"{misses} compiles for {N_BUCKETS}"
        assert hits == len(SIZES) - misses
        assert snap["counters"]["plan.fused_ops"] == len(SIZES) * 4
        assert snap["counters"]["plan.segments"] == len(SIZES)
        # cross-check against the ACTUAL XLA compile log
        fused = [m for m in compiles if "srt_fused_plan" in m]
        assert len(fused) <= N_BUCKETS, fused
        # and nothing leaked onto the per-op bucketed path
        assert not [m for m in compiles if "srt_bucketed" in m]

    def test_second_stream_is_all_hits(self):
        config.set_flag("BUCKETS", "1024:2")
        config.set_flag("METRICS", True)
        jax.clear_caches()
        buckets.cache_clear()
        _plan_stream()  # warm
        metrics.reset()
        compiles = _captured_plan_stream()
        snap = metrics.snapshot()
        assert not [m for m in compiles if "srt_fused_plan" in m]
        assert snap["counters"].get("compile_cache.miss", 0) == 0
        assert snap["counters"]["compile_cache.hit"] == len(SIZES)


# ---------------------------------------------------------------------------
# wire-serialize satellite: mask-buffer reuse counter
# ---------------------------------------------------------------------------


class TestSerializeSavedBytes:
    def test_saved_bytes_counted_for_repeated_string_shapes(self):
        config.set_flag("METRICS", True)
        n = 64
        strs = _string_wire([f"s{i % 3}" for i in range(n)])
        metrics.reset()
        out = rb.table_op_wire(
            json.dumps({"op": "slice", "start": 0, "stop": n}),
            [STR, STR, I64], [0, 0, 0],
            [strs, strs,
             np.arange(n, dtype=np.int64).tobytes()],
            [None, None, None], n,
        )
        assert out[4] == n
        snap = metrics.snapshot()
        # both STRING columns are constant-width (every "sN" is 2
        # bytes, pad=2), so each takes the ISSUE-5 serialize fast path:
        # the (n, pad) row mask is never built at all — counted as one
        # saved (n, pad) buffer per column
        assert snap["bytes"]["wire.serialize.saved_bytes"] == 2 * n * 2

    def test_saved_bytes_mask_reuse_for_ragged_strings(self):
        # ragged lengths force the mask path; the second same-shape
        # column reuses the first one's mask buffer (the pre-ISSUE-5
        # saving, still live for non-constant-width payloads)
        config.set_flag("METRICS", True)
        n = 64
        strs = _string_wire(
            [("s" * ((i % 3) + 1)) for i in range(n)]
        )
        metrics.reset()
        out = rb.table_op_wire(
            json.dumps({"op": "slice", "start": 0, "stop": n}),
            [STR, STR, I64], [0, 0, 0],
            [strs, strs,
             np.arange(n, dtype=np.int64).tobytes()],
            [None, None, None], n,
        )
        assert out[4] == n
        snap = metrics.snapshot()
        # one reuse of an (n, pad=3) bool mask buffer
        assert snap["bytes"]["wire.serialize.saved_bytes"] == n * 3
