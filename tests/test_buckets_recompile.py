"""Recompile-regression: ragged streams compile O(#buckets), not O(N).

The tentpole claim of the shape-bucket plane, pinned with real compile
counts: dispatching a stream of 8 ragged-row-count batches through 3
representative ops (cast, sort_by, groupby) compiles at most
``#buckets x #ops`` executables with bucketing ON (every further call
is a ``compile_cache.hit``), while the exact-shape path compiles fresh
programs for every distinct batch size.

Compile counting is double-sourced: the cache's own hit/miss counters
(a miss == one ``jax.jit`` build, keyed so each key sees exactly one
shape signature) AND ``jax.log_compiles`` output filtered to the
``srt_bucketed_*`` executables the cache names.
"""

import json
import logging

import jax
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.utils import buckets, config, metrics

I64 = int(dt.TypeId.INT64)

# 8 ragged sizes spanning exactly TWO buckets of the 1024 x2 ladder
SIZES = (911, 977, 1013, 1024, 1031, 1499, 1777, 2047)
N_BUCKETS = 2

OPS = (
    {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
    {"op": "sort_by", "keys": [{"column": 0}]},
    {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]},
)


@pytest.fixture(autouse=True)
def _clean():
    config.set_flag("METRICS", True)
    yield
    config.clear_flag("BUCKETS")
    config.clear_flag("METRICS")


class _CompileLog(logging.Handler):
    """Captures the WARNING-level compile lines jax.log_compiles emits."""

    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


def _stream():
    for n in SIZES:
        rng = np.random.default_rng(n)
        k = rng.integers(0, 7, n, dtype=np.int64)
        v = rng.integers(-5, 5, n, dtype=np.int64)
        for op in OPS:
            out = rb.table_op_wire(
                json.dumps(op), [I64, I64], [0, 0],
                [k.tobytes(), v.tobytes()], [None, None], n,
            )
            assert out[4] > 0


def _captured_stream():
    handler = _CompileLog()
    jax_logger = logging.getLogger("jax")
    jax_logger.addHandler(handler)
    try:
        with jax.log_compiles():
            _stream()
    finally:
        jax_logger.removeHandler(handler)
    # one "Compiling <name> with global shapes..." line per executable
    return [m for m in handler.messages if m.startswith("Compiling ")]


def test_bucketed_stream_compiles_at_most_buckets_executables():
    config.set_flag("BUCKETS", "1024:2")
    jax.clear_caches()
    buckets.cache_clear()
    metrics.reset()
    compiles = _captured_stream()

    snap = metrics.snapshot()
    misses = snap["counters"]["compile_cache.miss"]
    hits = snap["counters"].get("compile_cache.hit", 0)
    total_calls = len(SIZES) * len(OPS)
    budget = N_BUCKETS * len(OPS)
    # the acceptance bound: <= #buckets executables per op across the
    # whole ragged stream, every other dispatch a cache hit
    assert misses <= budget, f"{misses} compiles for {budget} budget"
    assert hits == total_calls - misses
    # cross-check against the ACTUAL XLA compile log
    bucketed = [m for m in compiles if "srt_bucketed" in m]
    assert len(bucketed) <= budget, bucketed
    # pad-waste accounting rode along
    assert snap["bytes"]["bucket.pad_waste_bytes"] > 0


def test_exact_stream_compiles_per_size():
    # the counterfactual: bucketing OFF compiles fresh programs for
    # every distinct batch size — at least one executable per size,
    # and none of them from the bucket plane
    config.set_flag("BUCKETS", "off")
    jax.clear_caches()
    buckets.cache_clear()
    metrics.reset()
    compiles = _captured_stream()

    assert len(compiles) >= len(SIZES)
    assert not [m for m in compiles if "srt_bucketed" in m]
    snap = metrics.snapshot()
    assert "compile_cache.miss" not in snap["counters"]


def test_second_stream_is_all_hits():
    # a second identical stream through a warm cache compiles NOTHING
    config.set_flag("BUCKETS", "1024:2")
    jax.clear_caches()
    buckets.cache_clear()
    _stream()  # warm
    metrics.reset()
    compiles = _captured_stream()
    snap = metrics.snapshot()
    assert not [m for m in compiles if "srt_bucketed" in m]
    assert snap["counters"].get("compile_cache.miss", 0) == 0
    assert snap["counters"]["compile_cache.hit"] == len(SIZES) * len(OPS)
