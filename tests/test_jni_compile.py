"""Syntax/type-check tier for the JNI bridge (VERDICT r1 item 2, adapted).

No JDK exists in this image, so the JNI sources cannot link — but they
CAN be fully typechecked: `g++ -fsyntax-only` against a minimal
clean-room JNI ABI stub (src/jni/jni_stub/jni.h) catches everything a
compiler would short of codegen. This turns the L3 bridge from
"untested text" into "compiles against the JNI ABI surface"; the real
premerge job with a JDK does the link + JUnit run (ci/premerge-build.sh
analog of the reference's GPU-gated suite).
"""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JNI_DIR = os.path.join(REPO, "src", "jni")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


def _jni_sources():
    return sorted(
        os.path.join(JNI_DIR, f)
        for f in os.listdir(JNI_DIR)
        if f.endswith(".cpp")
    )


@pytest.mark.parametrize(
    "src", _jni_sources(), ids=lambda p: os.path.basename(p)
)
def test_jni_source_typechecks(src):
    res = subprocess.run(
        [
            "g++",
            "-std=c++17",
            "-fsyntax-only",
            "-Wall",
            "-Wextra",
            "-Werror",
            "-DSRT_HAVE_JNI=1",
            "-I",
            os.path.join(JNI_DIR, "jni_stub"),
            "-I",
            os.path.join(REPO, "src", "include"),
            src,
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr


def test_stub_never_used_in_real_build():
    """The stub may back the mock-JNIEnv TEST harness (jni_harness
    executable) but must never reach the shipped library's include
    path: every target_include_directories mentioning jni_stub must
    target jni_harness."""
    import re

    cml = open(os.path.join(REPO, "src", "CMakeLists.txt")).read()
    for m in re.finditer(
        r"target_include_directories\(\s*(\w+)([^)]*)\)", cml
    ):
        target, args = m.group(1), m.group(2)
        if "jni_stub" in args:
            assert target == "jni_harness", (
                f"jni_stub on include path of {target}"
            )
    # and the library target itself never sees it anywhere
    lib_lines = [
        line for line in cml.splitlines()
        if "spark_rapids_tpu" in line and "jni_stub" in line
    ]
    assert lib_lines == []
