"""Two-level chunked groupby (ops/groupby_chunked.py, round-4 headline).

Oracle-checked against pandas on randomized data with nulls, plus the
capacity/fallback protocol and the eager router. Exact aggregations
(int sums, counts, min/max, first/last) must match bit-for-bit; float
means re-associate like any parallel reduction (documented), so they
compare at tight rtol.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import groupby as groupby_mod
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg, groupby_aggregate
from spark_rapids_jni_tpu.ops.groupby_chunked import (
    chunked_groupby_supported,
    groupby_aggregate_capped_chunked,
    groupby_aggregate_chunked,
)


def _table(n=40_000, n_keys=300, seed=0, null_frac=0.15):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, n_keys, n).astype(np.int64)
    v = rng.integers(-1000, 1000, n).astype(np.int64)
    f = rng.standard_normal(n)
    vv = rng.random(n) > null_frac
    t = Table(
        [
            Column.from_numpy(k),
            Column.from_numpy(v, validity=vv),
            Column.from_numpy(f),
        ],
        ["k", "v", "f"],
    )
    df = pd.DataFrame({"k": k, "v": np.where(vv, v, np.nan), "f": f})
    return t, df


_AGGS = [
    GroupbyAgg("v", "sum"),
    GroupbyAgg("v", "count"),
    GroupbyAgg("v", "min"),
    GroupbyAgg("v", "max"),
    GroupbyAgg("f", "mean"),
    GroupbyAgg("v", "first"),
    GroupbyAgg("v", "last"),
]


def _oracle(df):
    return (
        df.groupby("k")
        .agg(
            sum_v=("v", "sum"),
            count_v=("v", "count"),
            min_v=("v", "min"),
            max_v=("v", "max"),
            mean_f=("f", "mean"),
            first_v=("v", "first"),
            last_v=("v", "last"),
        )
        .sort_index()
    )


def _check(out, df):
    g = _oracle(df)
    assert out.row_count == len(g)
    order = np.argsort(np.asarray(out["k"].to_numpy()))
    for name in ("sum_v", "count_v", "min_v", "max_v", "first_v", "last_v"):
        got = np.asarray(out[name].to_numpy(), dtype=np.float64)[order]
        np.testing.assert_array_equal(got, g[name].to_numpy(np.float64), err_msg=name)
    np.testing.assert_allclose(
        np.asarray(out["mean_f"].to_numpy())[order],
        g["mean_f"].to_numpy(),
        rtol=1e-9,
    )


def test_chunked_matches_pandas():
    t, df = _table()
    out = groupby_aggregate_chunked(t, ["k"], _AGGS, chunk_rows=1 << 13)
    assert out is not None
    _check(out, df)


def test_chunked_matches_single_pass_exactly():
    """Integer aggregations must be bit-identical to the one-pass path."""
    t, df = _table(seed=7)
    chunked = groupby_aggregate_chunked(
        t, ["k"], _AGGS[:4], chunk_rows=1 << 12
    )
    direct = groupby_aggregate(t, ["k"], _AGGS[:4])
    for name in chunked.names:
        np.testing.assert_array_equal(
            np.asarray(chunked[name].to_numpy(), np.float64),
            np.asarray(direct[name].to_numpy(), np.float64),
            err_msg=name,
        )


def test_capped_chunked_reports_overflow():
    """max per-chunk group count > chunk_segments flags truncation."""
    t, _ = _table(n=4096, n_keys=4000, seed=1)
    _, _, max_chunk = groupby_aggregate_capped_chunked(
        t, ["k"], [GroupbyAgg("v", "sum")],
        num_segments=4096, chunk_rows=1024, chunk_segments=64,
    )
    assert int(max_chunk) > 64  # proof the caller CAN detect it


def test_eager_falls_back_on_high_cardinality():
    """Near-distinct keys: chunking can't win; wrapper must defer."""
    rng = np.random.default_rng(3)
    n = 20_000
    k = rng.permutation(n).astype(np.int64)  # all distinct
    t = Table([Column.from_numpy(k), Column.from_numpy(k)], ["k", "v"])
    out = groupby_aggregate_chunked(
        t, ["k"], [GroupbyAgg("v", "sum")], chunk_rows=1 << 12
    )
    assert out is None
    # ... and the public API still answers correctly via single-pass
    full = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "sum")])
    assert full.row_count == n


def test_router_uses_chunked_path(monkeypatch):
    """With the chunked formulation opted in (round 5 made "single"
    the measured default), the public eager API takes the new path
    above CHUNKED_MIN_ROWS."""
    t, df = _table(n=30_000, seed=5)
    monkeypatch.setattr(groupby_mod, "CHUNKED_MIN_ROWS", 10_000)
    monkeypatch.setenv(
        "SPARK_RAPIDS_TPU_GROUPBY_FORMULATION", "chunked"
    )
    calls = {}

    import spark_rapids_jni_tpu.ops.groupby_chunked as gc
    real = gc.groupby_aggregate_chunked

    def spy(*a, **k):
        calls["hit"] = True
        return real(*a, **k)

    monkeypatch.setattr(gc, "groupby_aggregate_chunked", spy)
    out = groupby_aggregate(t, ["k"], _AGGS)
    assert calls.get("hit"), "router did not take the chunked path"
    _check(out, df)


def test_router_keeps_single_pass_for_nondecomposable(monkeypatch):
    t, _ = _table(n=30_000, seed=6)
    monkeypatch.setattr(groupby_mod, "CHUNKED_MIN_ROWS", 10_000)
    assert not chunked_groupby_supported(
        t, [GroupbyAgg("v", "variance")]
    )
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "variance")])
    g = pd.DataFrame(
        {"k": np.asarray(t["k"].to_numpy())}
    )  # row count only; variance itself is covered in test_ops
    assert out.row_count == g.k.nunique()


def test_multi_key_and_string_free_path():
    """Two int keys; exactness across chunk boundaries."""
    rng = np.random.default_rng(11)
    n = 25_000
    k1 = rng.integers(0, 40, n).astype(np.int64)
    k2 = rng.integers(0, 25, n).astype(np.int32)
    v = rng.integers(-50, 50, n).astype(np.int64)
    t = Table(
        [Column.from_numpy(k1), Column.from_numpy(k2), Column.from_numpy(v)],
        ["a", "b", "v"],
    )
    out = groupby_aggregate_chunked(
        t, ["a", "b"], [GroupbyAgg("v", "sum")], chunk_rows=1 << 12
    )
    assert out is not None
    df = (
        pd.DataFrame({"a": k1, "b": k2, "v": v})
        .groupby(["a", "b"])
        .v.sum()
        .reset_index()
    )
    assert out.row_count == len(df)
    got = pd.DataFrame(
        {
            "a": np.asarray(out["a"].to_numpy()),
            "b": np.asarray(out["b"].to_numpy()),
            "v": np.asarray(out["sum_v"].to_numpy()),
        }
    ).sort_values(["a", "b"]).reset_index(drop=True)
    want = df.sort_values(["a", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(got.v.to_numpy(), want.v.to_numpy())


def test_null_keys_form_one_group():
    rng = np.random.default_rng(13)
    n = 12_000
    k = rng.integers(0, 50, n).astype(np.int64)
    kv = rng.random(n) > 0.1  # 10% null keys
    v = rng.integers(0, 100, n).astype(np.int64)
    t = Table(
        [Column.from_numpy(k, validity=kv), Column.from_numpy(v)],
        ["k", "v"],
    )
    out = groupby_aggregate_chunked(
        t, ["k"], [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
        chunk_rows=1 << 11,
    )
    assert out is not None
    df = pd.DataFrame({"k": np.where(kv, k, np.nan), "v": v})
    g = df.groupby("k", dropna=False).v.agg(["sum", "count"])
    assert out.row_count == len(g)
    # the null-key group's total
    kvalid = np.asarray(out["k"].validity) if out["k"].validity is not None else None
    null_rows = np.where(~kvalid)[0] if kvalid is not None else []
    assert len(null_rows) == 1
    got_null_sum = int(np.asarray(out["sum_v"].to_numpy())[null_rows[0]])
    assert got_null_sum == int(df[df.k.isna()].v.sum())


# 15 randomized trials compile a fresh shape each — minutes of XLA CPU
# compile; the exact equivalence tests above keep premerge coverage
@pytest.mark.slow
def test_fuzz_chunked_equals_single_pass():
    """Randomized equivalence: chunked vs single-pass groupby across
    dtypes, null fractions, key counts, cardinalities and chunk sizes.
    Exact aggregations must match bit-for-bit; float means at 1e-9."""
    rng = np.random.default_rng(424242)
    for trial in range(15):
        n = int(rng.integers(3000, 30_000))
        nkeys = int(rng.integers(2, 4))
        card = int(rng.integers(2, 500))
        cols, names = [], []
        for i in range(nkeys - 1):
            kv = rng.integers(0, card, n).astype(
                [np.int64, np.int32][int(rng.integers(0, 2))]
            )
            kvalid = (
                rng.random(n) > 0.1 if rng.random() < 0.3 else None
            )
            cols.append(Column.from_numpy(kv, validity=kvalid))
            names.append(f"k{i}")
        vv = rng.random(n) > float(rng.random()) * 0.3
        vals = rng.integers(-10_000, 10_000, n)
        cols.append(Column.from_numpy(vals, validity=vv))
        names.append("v")
        fcol = rng.standard_normal(n)
        cols.append(Column.from_numpy(fcol))
        names.append("f")
        t = Table(cols, names)
        by = names[: nkeys - 1]
        aggs = [
            GroupbyAgg("v", "sum"),
            GroupbyAgg("v", "count"),
            GroupbyAgg("v", "min"),
            GroupbyAgg("v", "max"),
            GroupbyAgg("v", "first"),
            GroupbyAgg("v", "last"),
            GroupbyAgg("f", "mean"),
        ]
        chunk_rows = 1 << int(rng.integers(10, 13))
        chunked = groupby_aggregate_chunked(
            t, by, aggs, chunk_rows=chunk_rows
        )
        if chunked is None:  # high cardinality fallback: fine
            continue
        direct = groupby_aggregate(t, by, aggs)
        assert chunked.row_count == direct.row_count, trial
        # align on key order words (both come out key-sorted already,
        # but padding-null keys make a tuple sort simplest)
        def keymat(tbl):
            out = []
            for kn in by:
                c = tbl[kn]
                v = np.asarray(c.data, dtype=np.int64)
                m = (
                    np.ones(len(v), bool)
                    if c.validity is None
                    else np.asarray(c.validity)
                )
                out.append(np.where(m, v, np.iinfo(np.int64).min))
            return np.lexsort(out[::-1])
        oc = keymat(chunked)
        od = keymat(direct)
        for name in ("sum_v", "count_v", "min_v", "max_v",
                     "first_v", "last_v"):
            a = np.asarray(chunked[name].to_numpy(), np.float64)[oc]
            b = np.asarray(direct[name].to_numpy(), np.float64)[od]
            np.testing.assert_array_equal(a, b, err_msg=f"t{trial} {name}")
        np.testing.assert_allclose(
            np.asarray(chunked["mean_f"].to_numpy())[oc],
            np.asarray(direct["mean_f"].to_numpy())[od],
            rtol=1e-9,
            err_msg=f"t{trial} mean",
        )
