"""Pallas kernel tier: the hand-written TPU kernels, interpreted on CPU.

The reference's only hand-written kernel pair is the row transpose
(row_conversion.cu:48-304); its test is a golden round-trip through the
real device stack (RowConversionTest.java:28-59). Same shape here, plus a
cross-backend check the reference can't do: the Pallas kernels must emit
byte-identical results to the XLA-fusion backend. On CPU these run under
``interpret=True`` (tests/conftest.py pins the cpu platform); the same
calls compile through Mosaic when the suite runs on a TPU
(SPARK_RAPIDS_TPU_TEST_PLATFORM=axon).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import rows
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.kernels import hashing as khash
from spark_rapids_jni_tpu.ops import hashing as xhash


def _mixed_table(rng, n, with_nulls=True):
    t = Table.from_pydict(
        {
            "i64": rng.integers(-(2**62), 2**62, n).astype(np.int64),
            "f64": rng.standard_normal(n),
            "i32": rng.integers(-(2**31), 2**31, n).astype(np.int32),
            "i16": rng.integers(-(2**15), 2**15, n).astype(np.int16),
            "i8": rng.integers(-128, 128, n).astype(np.int8),
            "f32": rng.standard_normal(n).astype(np.float32),
            "b": rng.random(n) > 0.5,
        }
    )
    if with_nulls:
        for c in t.columns[::2]:
            c.validity = jnp.asarray(rng.random(n) > 0.25)
    return t


@pytest.mark.parametrize("n", [7, 513, 4096])
def test_pack_matches_xla(rng, n):
    t = _mixed_table(rng, n)
    ref = rows.to_rows(t, backend="xla")
    got = rows.to_rows(t, backend="pallas")
    assert len(ref) == len(got) == 1
    np.testing.assert_array_equal(
        np.asarray(ref[0].data), np.asarray(got[0].data)
    )


@pytest.mark.parametrize("n", [7, 513, 4096])
def test_roundtrip_pallas(rng, n):
    t = _mixed_table(rng, n)
    packed = rows.to_rows(t, backend="pallas")
    back = rows.from_rows(packed, backend="pallas", names=t.names)
    for a, b in zip(t.columns, back.columns):
        np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
        av = (
            np.ones(n, bool)
            if a.validity is None
            else np.asarray(a.validity)
        )
        bv = (
            np.ones(n, bool)
            if b.validity is None
            else np.asarray(b.validity)
        )
        np.testing.assert_array_equal(av, bv)


def test_cross_backend_roundtrip(rng):
    """pallas-packed bytes unpack on the XLA backend and vice versa."""
    t = _mixed_table(rng, 1000)
    a = rows.from_rows(rows.to_rows(t, backend="pallas"), backend="xla")
    b = rows.from_rows(rows.to_rows(t, backend="xla"), backend="pallas")
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(
            np.asarray(ca.data), np.asarray(cb.data)
        )


def test_single_column_narrow(rng):
    """1-column schema: validity byte matmul with a width-1 output."""
    t = Table.from_pydict({"x": rng.integers(0, 100, 100).astype(np.int64)})
    t.columns[0].validity = jnp.asarray(rng.random(100) > 0.5)
    packed = rows.to_rows(t, backend="pallas")
    back = rows.from_rows(packed, backend="pallas")
    np.testing.assert_array_equal(
        np.asarray(back.columns[0].data), np.asarray(t.columns[0].data)
    )
    np.testing.assert_array_equal(
        np.asarray(back.columns[0].validity),
        np.asarray(t.columns[0].validity),
    )


def test_wide_schema_validity_bytes(rng):
    """>8 columns: multiple validity bytes per row."""
    n = 257
    cols = {
        f"c{i}": rng.integers(0, 100, n).astype(np.int32) for i in range(13)
    }
    t = Table.from_pydict(cols)
    for i, c in enumerate(t.columns):
        if i % 3 == 0:
            c.validity = jnp.asarray(rng.random(n) > 0.3)
    ref = rows.to_rows(t, backend="xla")[0]
    got = rows.to_rows(t, backend="pallas")[0]
    np.testing.assert_array_equal(np.asarray(ref.data), np.asarray(got.data))
    back = rows.from_rows(got, backend="pallas")
    for a, b in zip(t.columns, back.columns):
        av = (
            np.ones(n, bool) if a.validity is None else np.asarray(a.validity)
        )
        bv = (
            np.ones(n, bool) if b.validity is None else np.asarray(b.validity)
        )
        np.testing.assert_array_equal(av, bv)


def test_fused_hash_matches_xla(rng):
    t = _mixed_table(rng, 3000)
    ref = np.asarray(xhash.murmur3_table(t).data)
    got = np.asarray(khash.murmur3_table_fused(t).data)
    np.testing.assert_array_equal(ref, got)


def test_fused_hash_subset_and_seed(rng):
    t = _mixed_table(rng, 500)
    ref = np.asarray(xhash.murmur3_table(t, ["i64", "i32"], seed=7).data)
    got = np.asarray(
        khash.murmur3_table_fused(t, ["i64", "i32"], seed=7).data
    )
    np.testing.assert_array_equal(ref, got)


def test_fused_hash_string_fallback(rng):
    """String keys take the XLA path transparently."""
    import pyarrow as pa

    from spark_rapids_jni_tpu import interop

    t = interop.table_from_arrow(
        pa.table({"s": ["a", "bb", None, "dddd"], "v": [1, 2, 3, 4]})
    )
    ref = np.asarray(xhash.murmur3_table(t).data)
    got = np.asarray(khash.murmur3_table_fused(t).data)
    np.testing.assert_array_equal(ref, got)


def test_spark_golden_hash_values():
    """Known Spark Murmur3Hash(seed=42) outputs still hold on the fused
    kernel (same vectors as the XLA-path golden test)."""
    t = Table.from_pydict({"x": np.array([0, 1, -1], dtype=np.int64)})
    got = np.asarray(khash.murmur3_table_fused(t).data)
    # org.apache.spark.sql.catalyst.expressions.Murmur3HashFunction(long),
    # seed 42 — literals pinned from the independent python oracle
    # (test_ops.spark_hash_long), NOT recomputed through the library.
    expect = np.array([-1670924195, -1712319331, -939490007], np.int32)
    np.testing.assert_array_equal(got, expect)
