"""Randomized containment fuzz of the regex DFA vs Python ``re``.

``rlike``/``contains_re`` decides LANGUAGE MEMBERSHIP ("does any
substring match"), which is independent of leftmost-first vs
leftmost-longest strategy — so random patterns drawn from the engine's
full supported grammar (including alternation) can be checked against
``re.search`` with ``re.ASCII`` on random subjects without tripping
the documented divergent-span corners. Span semantics (extract /
replace) stay pinned by the directed tests in test_regex.py."""

import random
import re

import pytest

from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import regex as R

_LITERALS = list("abcxyz019 _-")
_CLASSES = [r"\d", r"\w", r"\s", r"\D", r"\S", "[abc]", "[^ab]",
            "[a-f]", "[0-9x]", "."]
_QUANTS = ["", "?", "*", "+", "{2}", "{1,3}", "{2,}"]


def _rand_atom(rng):
    r = rng.random()
    if r < 0.45:
        return re.escape(rng.choice(_LITERALS))
    if r < 0.8:
        return rng.choice(_CLASSES)
    # group of two atoms, possibly alternated
    a = re.escape(rng.choice(_LITERALS))
    b = rng.choice(_CLASSES)
    sep = "|" if rng.random() < 0.5 else ""
    return f"(?:{a}{sep}{b})"


def _rand_pattern(rng):
    n = rng.randint(1, 5)
    body = "".join(
        _rand_atom(rng) + rng.choice(_QUANTS) for _ in range(n)
    )
    if rng.random() < 0.2:
        body = "^" + body
    if rng.random() < 0.2:
        body = body + "$"
    return body


def _rand_subject(rng):
    n = rng.randint(0, 12)
    return "".join(
        rng.choice("abcxyz019 _-AB.?") for _ in range(n)
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_contains_fuzz_vs_python_re(seed):
    rng = random.Random(seed)
    subjects = [_rand_subject(rng) for _ in range(150)]
    col = Column.from_strings(subjects)
    tried = 0
    for _ in range(60):
        pat = _rand_pattern(rng)
        try:
            cre = re.compile(pat, re.ASCII)
        except re.error:
            continue
        try:
            got = R.contains_re(col, pat).to_pylist()
        except (ValueError, NotImplementedError):
            continue  # outside the documented subset
        tried += 1
        want = [bool(cre.search(s)) for s in subjects]
        assert got == want, (pat, [
            (s, g, w) for s, g, w in zip(subjects, got, want) if g != w
        ][:5])
    assert tried >= 30, "fuzz generated too few supported patterns"
