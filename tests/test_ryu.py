"""Device Ryu float->string (ops/ryu.py + strings._format_float).

Oracles: Python repr IS shortest-round-trip for f64 (same contract as
Ryu), so digit/exponent agreement is exact; for f32 numpy's
``format_float_scientific(unique=True)`` provides the shortest f32
significand. The formatted-string layer is checked against the host
formatter (f64, byte-identical) and against round-trip + Java
placement properties (f32, where the old host fallback formatted the
promoted double and was simply wider than Java's Float.toString)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings as S
from spark_rapids_jni_tpu.ops.ryu import (
    shortest_decimal32,
    shortest_decimal64,
)

EDGE64 = np.array(
    [0.0, -0.0, 1.0, -1.0, 0.5, 0.1, 0.3, 1e-3, 9.999e-4, 1e7,
     9999999.5, 123456.789, 5e-324, -5e-324, 2.2250738585072014e-308,
     1.7976931348623157e308, 1 / 3, 2 / 3, 1e22, 1e23, 8e9, 3.14159,
     100.0, 4.0, float("nan"), float("inf"), float("-inf")]
)


def _repr_digits(v):
    s = repr(float(v))
    if "e" in s:
        m, e = s.split("e")
        e = int(e)
    else:
        m, e = s, 0
    m = m.lstrip("-")
    ip, _, fp = m.partition(".")
    digs = (ip + fp).lstrip("0")
    exp10 = e - len(fp)
    d2 = digs.rstrip("0")
    exp10 += len(digs) - len(d2)
    return int(d2 or "0"), exp10


def test_f64_digits_match_python_repr():
    rng = np.random.default_rng(7)
    bits = rng.integers(0, 1 << 64, 30000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals) & (vals != 0)][:15000]
    sign, digits, exp10, *_ = jax.jit(shortest_decimal64)(
        jnp.asarray(vals.view(np.uint64))
    )
    digits = np.asarray(digits)
    exp10 = np.asarray(exp10)
    sign = np.asarray(sign)
    for k in range(len(vals)):
        dw, ew = _repr_digits(abs(vals[k]))
        assert (int(digits[k]), int(exp10[k])) == (dw, ew), vals[k].hex()
        assert bool(sign[k]) == (vals[k] < 0)


def test_f32_digits_shortest_roundtrip():
    rng = np.random.default_rng(8)
    bits = rng.integers(0, 1 << 32, 30000, dtype=np.uint64).astype(
        np.uint32
    )
    vals = bits.view(np.float32)
    vals = vals[np.isfinite(vals) & (vals != 0)][:15000]
    sign, digits, exp10, *_ = jax.jit(shortest_decimal32)(
        jnp.asarray(vals.view(np.uint32))
    )
    digits = np.asarray(digits)
    exp10 = np.asarray(exp10)
    for k in range(len(vals)):
        s = np.format_float_scientific(
            np.float32(abs(vals[k])), unique=True, trim="-"
        )
        m, e = s.split("e")
        m = m.replace(".", "")
        digs = m.lstrip("0").rstrip("0") or "0"
        got = str(int(digits[k]))
        # same significand digits (shortest + correctly rounded)
        assert got == digs, (vals[k], got, digs)
    # bitwise round-trip via the decimal string
    col = Column.from_numpy(vals)
    strs = S.cast(col, dt.STRING).to_pylist()
    back = np.array([np.float32(s) for s in strs], dtype=np.float32)
    np.testing.assert_array_equal(
        back.view(np.uint32), vals.view(np.uint32)
    )


def test_f64_format_matches_host_formatter():
    rng = np.random.default_rng(9)
    bits = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals)][:10000]
    vals = np.concatenate([vals, EDGE64])
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    want = S._format_host(col).to_pylist()
    assert got == want


def test_f64_format_java_examples():
    vals = np.array(
        [4.0, 0.001, 5e-4, 1e7, 1234.5678, float("nan"), float("inf"),
         float("-inf"), 0.0, -0.0, 1e-3, 123456.78]
    )
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    assert got == [
        "4.0", "0.001", "5.0E-4", "1.0E7", "1234.5678", "NaN",
        "Infinity", "-Infinity", "0.0", "-0.0", "0.001", "123456.78",
    ]


def test_f32_format_java_examples():
    vals = np.array(
        [0.1, 4.0, 5e-4, 3.4028235e38, 1.4e-45, 0.0, -2.5],
        dtype=np.float32,
    )
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    # note 1.0E-45 for FLOAT_MIN_SUBNORMAL: the true shortest
    # round-trip (Ryu / cudf contract) — legacy Java printed the
    # longer "1.4E-45"
    assert got == [
        "0.1", "4.0", "5.0E-4", "3.4028235E38", "1.0E-45", "0.0",
        "-2.5",
    ]


def test_f64_roundtrip_bitexact():
    rng = np.random.default_rng(10)
    bits = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals)][:10000]
    col = Column.from_numpy(vals)
    strs = S.cast(col, dt.STRING).to_pylist()
    back = np.array([float(s) for s in strs])
    np.testing.assert_array_equal(
        back.view(np.uint64), vals.view(np.uint64)
    )


def test_nulls_preserved():
    from spark_rapids_jni_tpu.column import Table

    t = Table.from_pydict({"a": [1.5, None, float("nan")]})
    got = S.cast(t["a"], dt.STRING).to_pylist()
    assert got == ["1.5", None, "NaN"]
