"""Device Ryu float->string (ops/ryu.py + strings._format_float).

Oracles: Python repr IS shortest-round-trip for f64 (same contract as
Ryu), so digit/exponent agreement is exact; for f32 numpy's
``format_float_scientific(unique=True)`` provides the shortest f32
significand. The formatted-string layer is checked against the host
formatter (f64, byte-identical) and against round-trip + Java
placement properties (f32, where the old host fallback formatted the
promoted double and was simply wider than Java's Float.toString)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings as S
from spark_rapids_jni_tpu.ops.ryu import (
    shortest_decimal32,
    shortest_decimal64,
)

EDGE64 = np.array(
    [0.0, -0.0, 1.0, -1.0, 0.5, 0.1, 0.3, 1e-3, 9.999e-4, 1e7,
     9999999.5, 123456.789, 5e-324, -5e-324, 2.2250738585072014e-308,
     1.7976931348623157e308, 1 / 3, 2 / 3, 1e22, 1e23, 8e9, 3.14159,
     100.0, 4.0, float("nan"), float("inf"), float("-inf"),
     # exact-halfway mantissas: vr == vm boundary in the trim loop
     # (review catch: requires comparing against the TRIMMED vm)
     2.0 ** -24, -(2.0 ** -24), 2.0 ** -96, 5.986310706507379e51,
     2.0 ** 122, 2.0 ** -120]
)


def _repr_digits(v):
    s = repr(float(v))
    if "e" in s:
        m, e = s.split("e")
        e = int(e)
    else:
        m, e = s, 0
    m = m.lstrip("-")
    ip, _, fp = m.partition(".")
    digs = (ip + fp).lstrip("0")
    exp10 = e - len(fp)
    d2 = digs.rstrip("0")
    exp10 += len(digs) - len(d2)
    return int(d2 or "0"), exp10


def test_f64_digits_match_python_repr():
    rng = np.random.default_rng(7)
    bits = rng.integers(0, 1 << 64, 30000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals) & (vals != 0)][:15000]
    sign, digits, exp10, *_ = jax.jit(shortest_decimal64)(
        jnp.asarray(vals.view(np.uint64))
    )
    digits = np.asarray(digits)
    exp10 = np.asarray(exp10)
    sign = np.asarray(sign)
    for k in range(len(vals)):
        dw, ew = _repr_digits(abs(vals[k]))
        assert (int(digits[k]), int(exp10[k])) == (dw, ew), vals[k].hex()
        assert bool(sign[k]) == (vals[k] < 0)


def test_f32_digits_shortest_roundtrip():
    rng = np.random.default_rng(8)
    bits = rng.integers(0, 1 << 32, 30000, dtype=np.uint64).astype(
        np.uint32
    )
    vals = bits.view(np.float32)
    vals = vals[np.isfinite(vals) & (vals != 0)][:15000]
    sign, digits, exp10, *_ = jax.jit(shortest_decimal32)(
        jnp.asarray(vals.view(np.uint32))
    )
    digits = np.asarray(digits)
    exp10 = np.asarray(exp10)
    for k in range(len(vals)):
        s = np.format_float_scientific(
            np.float32(abs(vals[k])), unique=True, trim="-"
        )
        m, e = s.split("e")
        m = m.replace(".", "")
        digs = m.lstrip("0").rstrip("0") or "0"
        got = str(int(digits[k]))
        # same significand digits (shortest + correctly rounded)
        assert got == digs, (vals[k], got, digs)
    # bitwise round-trip via the decimal string
    col = Column.from_numpy(vals)
    strs = S.cast(col, dt.STRING).to_pylist()
    back = np.array([np.float32(s) for s in strs], dtype=np.float32)
    np.testing.assert_array_equal(
        back.view(np.uint32), vals.view(np.uint32)
    )


def test_f64_format_matches_host_formatter():
    rng = np.random.default_rng(9)
    bits = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals)][:10000]
    vals = np.concatenate([vals, EDGE64])
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    want = S._format_host(col).to_pylist()
    assert got == want


def test_f64_format_java_examples():
    vals = np.array(
        [4.0, 0.001, 5e-4, 1e7, 1234.5678, float("nan"), float("inf"),
         float("-inf"), 0.0, -0.0, 1e-3, 123456.78]
    )
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    assert got == [
        "4.0", "0.001", "5.0E-4", "1.0E7", "1234.5678", "NaN",
        "Infinity", "-Infinity", "0.0", "-0.0", "0.001", "123456.78",
    ]


def test_f32_format_java_examples():
    vals = np.array(
        [0.1, 4.0, 5e-4, 3.4028235e38, 1.4e-45, 0.0, -2.5],
        dtype=np.float32,
    )
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    # note 1.0E-45 for FLOAT_MIN_SUBNORMAL: the true shortest
    # round-trip (Ryu / cudf contract) — legacy Java printed the
    # longer "1.4E-45"
    assert got == [
        "0.1", "4.0", "5.0E-4", "3.4028235E38", "1.0E-45", "0.0",
        "-2.5",
    ]


def test_f64_roundtrip_bitexact():
    rng = np.random.default_rng(10)
    bits = rng.integers(0, 1 << 64, 20000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals)][:10000]
    col = Column.from_numpy(vals)
    strs = S.cast(col, dt.STRING).to_pylist()
    back = np.array([float(s) for s in strs])
    np.testing.assert_array_equal(
        back.view(np.uint64), vals.view(np.uint64)
    )


def test_nulls_preserved():
    from spark_rapids_jni_tpu.column import Table

    t = Table.from_pydict({"a": [1.5, None, float("nan")]})
    got = S.cast(t["a"], dt.STRING).to_pylist()
    assert got == ["1.5", None, "NaN"]


# ---------------------------------------------------------------------------
# Eisel-Lemire parse direction
# ---------------------------------------------------------------------------


def test_el_random_wq_vs_python():
    from spark_rapids_jni_tpu.ops.ryu import decimal_to_bits

    rng = np.random.default_rng(12)
    w = rng.integers(1, 10 ** 19, 5000, dtype=np.uint64)
    q = rng.integers(-340, 300, 5000, dtype=np.int64).astype(np.int32)
    got = np.asarray(
        jax.jit(lambda w, q: decimal_to_bits(w, q, bits64=True))(
            jnp.asarray(w), jnp.asarray(q)
        )
    )
    for k in range(len(w)):
        want = np.float64(float(f"{int(w[k])}e{int(q[k])}"))
        assert got[k] == want.view(np.uint64), (int(w[k]), int(q[k]))


def test_el_edges():
    from spark_rapids_jni_tpu.ops.ryu import decimal_to_bits

    cases = [
        (1, 0), (5, -1), (25, -2),
        (9007199254740993, 0), (9007199254740995, 0),  # ties at 2^53
        (17976931348623157, 292),  # DBL_MAX
        (2, 308), (1, 309),  # overflow line
        (49406564584124654, -340),  # min subnormal
        (22250738585072014, -324),  # min normal boundary
        (1, -400), (123456789012345678, -390),  # deep underflow
    ]
    w = np.array([c[0] for c in cases], dtype=np.uint64)
    q = np.array([c[1] for c in cases], dtype=np.int32)
    got = np.asarray(
        decimal_to_bits(jnp.asarray(w), jnp.asarray(q), bits64=True)
    )
    for k in range(len(w)):
        want = np.float64(float(f"{int(w[k])}e{int(q[k])}"))
        assert got[k] == want.view(np.uint64), cases[k]


def test_parse_format_roundtrip_bitexact_f64():
    rng = np.random.default_rng(13)
    bits = rng.integers(0, 1 << 64, 16000, dtype=np.uint64)
    vals = bits.view(np.float64)
    vals = vals[np.isfinite(vals)][:8000]
    s = S.cast(Column.from_numpy(vals), dt.STRING)
    back = S.cast(s, dt.FLOAT64)
    np.testing.assert_array_equal(
        np.asarray(back.data).view(np.uint64), vals.view(np.uint64)
    )


def test_parse_format_roundtrip_bitexact_f32_subnormals():
    # includes the f32 subnormal band that XLA's CPU backend flushes in
    # f32->f64 conversions (the parse path must stay in bits)
    rng = np.random.default_rng(14)
    bits = rng.integers(0, 1 << 32, 16000, dtype=np.uint64).astype(
        np.uint32
    )
    sub = rng.integers(1, 1 << 23, 500, dtype=np.uint64).astype(
        np.uint32
    )  # raw subnormal patterns
    bits = np.concatenate([bits, sub])
    vals = bits.view(np.float32)
    vals = vals[np.isfinite(vals)][:8000]
    s = S.cast(Column.from_numpy(vals), dt.STRING)
    back = S.cast(s, dt.FLOAT32)
    np.testing.assert_array_equal(
        np.asarray(back.data).view(np.uint32), vals.view(np.uint32)
    )


def test_parse_long_mantissa_and_leading_zeros():
    from spark_rapids_jni_tpu.column import Table

    strs = [
        "0.00054881343708050815",      # leading zeros + 17 sig digits
        "123456789012345678901234567890",  # >19 digits (top-19 window)
        "0.000000000000000000000001",  # 1e-24
        "10000000000000000000000",     # 1e22 exact
    ]
    t = Table.from_pydict({"s": strs})
    got = S.cast(t["s"], dt.FLOAT64).to_pylist()
    want = [float(x) for x in strs]
    assert got == want


def test_pow2_boundary_sweep():
    """Powers of two sit on vr == vm boundaries after trimming — the
    class the random-bits tests almost never sample."""
    vals = np.array([2.0 ** k for k in range(-250, 250, 3)])
    col = Column.from_numpy(vals)
    got = S.cast(col, dt.STRING).to_pylist()
    for v, g in zip(vals, got):
        assert float(g) == v
        # digits must equal Python repr's (both shortest + nearest)
        assert _repr_digits(v) == _repr_digits(float(g))
    got32 = S.cast(
        Column.from_numpy(np.array(
            [np.float32(2.0 ** k) for k in range(-140, 120, 3)],
            dtype=np.float32,
        )),
        dt.STRING,
    ).to_pylist()
    for k, g in zip(range(-140, 120, 3), got32):
        assert np.float32(g) == np.float32(2.0 ** k)
