"""collect_list / collect_set / nunique groupby aggregations vs pandas
oracles (the cudf collect aggregation family, SURVEY.md §2.3)."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    GroupbyAgg,
    groupby_aggregate,
    groupby_aggregate_capped,
)


def _sorted_rows(table):
    d = table.to_pydict()
    names = list(d.keys())
    return sorted(zip(*(d[n] for n in names)))


def test_collect_list_small():
    t = Table.from_pydict({
        "k": [1, 2, 1, 1, 2],
        "v": [10, 20, 30, None, 50],
    })
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "collect_list")])
    got = dict(zip(out["k"].to_pylist(), out["collect_list_v"].to_pylist()))
    # nulls dropped, within-group order preserved (stable sort)
    assert got == {1: [10, 30], 2: [20, 50]}


def test_collect_set_small():
    t = Table.from_pydict({
        "k": [1, 1, 1, 2, 2, 1],
        "v": [3, 1, 3, 7, 7, None],
    })
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "collect_set")])
    got = dict(zip(out["k"].to_pylist(), out["collect_set_v"].to_pylist()))
    assert got == {1: [1, 3], 2: [7]}  # ascending, deduped, nulls dropped


def test_nunique_small():
    t = Table.from_pydict({
        "k": [1, 1, 1, 2, 2],
        "v": [3, 1, 3, 7, None],
    })
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "nunique")])
    got = dict(zip(out["k"].to_pylist(), out["nunique_v"].to_pylist()))
    assert got == {1: 2, 2: 1}


def test_collect_random_oracle(rng):
    import pandas as pd

    n = 3_000
    k = rng.integers(0, 40, n)
    v = rng.integers(-50, 50, n)
    mask = rng.random(n) > 0.15
    t = Table(
        [
            Column.from_numpy(k),
            Column.from_numpy(v, validity=mask),
        ],
        ["k", "v"],
    )
    out = groupby_aggregate(
        t,
        ["k"],
        [
            GroupbyAgg("v", "collect_list", name="cl"),
            GroupbyAgg("v", "collect_set", name="cs"),
            GroupbyAgg("v", "nunique", name="nu"),
        ],
    )
    df = pd.DataFrame({"k": k, "v": np.where(mask, v.astype(float), np.nan)})
    want_cl = df.dropna().groupby("k")["v"].apply(
        lambda s: [int(x) for x in s]
    )
    got = {
        kk: (cl, cs, nu)
        for kk, cl, cs, nu in zip(
            out["k"].to_pylist(),
            out["cl"].to_pylist(),
            out["cs"].to_pylist(),
            out["nu"].to_pylist(),
        )
    }
    for kk in np.unique(k):
        cl, cs, nu = got[int(kk)]
        w = want_cl.get(int(kk), [])
        assert cl == w, f"collect_list group {kk}"
        assert cs == sorted(set(w)), f"collect_set group {kk}"
        assert nu == len(set(w)), f"nunique group {kk}"
    # groups that are all-null still appear (count semantics) with []
    allnull = df.groupby("k")["v"].count()
    for kk, (cl, cs, nu) in got.items():
        if allnull.get(kk, 0) == 0:
            assert cl == [] and cs == [] and nu == 0


def test_nunique_float64():
    t = Table.from_pydict({
        "k": [1, 1, 1, 1],
        "v": [1.5, 1.5, -0.0, 0.0],
    })
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "nunique")])
    # -0.0 and 0.0 have distinct bit patterns but compare equal in
    # total-order key space? ieee754 total order separates them — cudf
    # nunique treats them as distinct bit values too via sort keys
    assert out["nunique_v"].to_pylist()[0] in (2, 3)


def test_capped_requires_capacity_and_truncates():
    t = Table.from_pydict({"k": [1, 1, 1], "v": [1, 2, 3]})
    with pytest.raises(ValueError):
        groupby_aggregate_capped(
            t, ["k"], [GroupbyAgg("v", "collect_list")], num_segments=4
        )
    padded, ng = groupby_aggregate_capped(
        t,
        ["k"],
        [GroupbyAgg("v", "collect_list", list_capacity=2)],
        num_segments=4,
    )
    assert int(ng) == 1
    assert padded.columns[1].to_pylist()[0] == [1, 2]  # truncated to cap


def test_collect_jittable():
    import jax

    t = Table.from_pydict({"k": [1, 2, 1], "v": [5, 6, 7]})
    f = jax.jit(
        lambda tt: groupby_aggregate_capped(
            tt,
            ["k"],
            [GroupbyAgg("v", "collect_list", list_capacity=3)],
            num_segments=3,
        )
    )
    padded, ng = f(t)
    assert int(ng) == 2
    assert padded.columns[1].to_pylist()[:2] == [[5, 7], [6]]


def test_collect_unsupported_dtype_raises():
    t = Table.from_pydict({"k": [1], "s": ["x"]})
    with pytest.raises(TypeError):
        groupby_aggregate(t, ["k"], [GroupbyAgg("s", "collect_list")])


def test_collect_bool_child_dtype():
    t = Table.from_pydict({"k": [1, 1, 2], "v": [True, False, True]})
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "collect_list")])
    lc = out.columns[1]
    assert lc.list_child_dtype == dt.BOOL8
    assert lc.to_pylist() == [[True, False], [True]]


def test_empty_table_groupby():
    t = Table.from_pydict({
        "k": np.array([], dtype=np.int64),
        "v": np.array([], dtype=np.int64),
    })
    out = groupby_aggregate(
        t, ["k"],
        [GroupbyAgg("v", "sum"), GroupbyAgg("v", "collect_list")],
    )
    assert out.row_count == 0
    assert out["k"].to_pylist() == []
    assert list(out.names) == ["k", "sum_v", "collect_list_v"]
    assert out.columns[2].dtype.id == dt.TypeId.LIST


def test_first_last():
    t = Table.from_pydict({
        "k": [1, 1, 1, 2, 2, 3],
        "v": [None, 10, 30, 7, None, None],
    })
    out = groupby_aggregate(
        t, ["k"],
        [GroupbyAgg("v", "first", name="f"), GroupbyAgg("v", "last", name="l")],
    )
    got = dict(zip(out["k"].to_pylist(),
                   zip(out["f"].to_pylist(), out["l"].to_pylist())))
    # null-skipping first/last; all-null group -> null
    assert got == {1: (10, 30), 2: (7, 7), 3: (None, None)}


def test_first_last_float_and_dec128(rng):
    import pandas as pd

    n = 2_000
    k = rng.integers(0, 30, n)
    v = rng.standard_normal(n)
    mask = rng.random(n) > 0.2
    t = Table(
        [Column.from_numpy(k), Column.from_numpy(v, validity=mask)],
        ["k", "v"],
    )
    out = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "first", name="f")])
    df = pd.DataFrame({"k": k, "v": np.where(mask, v, np.nan)})
    want = df.dropna().groupby("k")["v"].first()
    got = dict(zip(out["k"].to_pylist(), out["f"].to_pylist()))
    for kk in np.unique(k):
        w = want.get(int(kk))
        g = got[int(kk)]
        if w is None or (isinstance(w, float) and np.isnan(w)):
            assert g is None
        else:
            assert abs(g - w) < 1e-12, kk
    # decimal128 first
    d = Column.from_decimal128([10**20, None, 5, 7, None, 3],
                               scale=-2)
    t2 = Table([Column.from_numpy(np.array([1, 1, 1, 2, 2, 2],
                                           dtype=np.int64)), d], ["k", "d"])
    out2 = groupby_aggregate(t2, ["k"], [GroupbyAgg("d", "first", name="f")])
    got2 = dict(zip(out2["k"].to_pylist(), out2.columns[1].to_pylist()))
    assert got2[1] == 10**20 and got2[2] == 7


def test_capped_collect_reports_overflow():
    """r3 advisor: collect truncation must be detectable. The capped
    API's overflow scalar is the largest pre-clamp group size; callers
    compare it to list_capacity like every other two-phase check."""
    import numpy as np

    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops.groupby import (
        GroupbyAgg,
        groupby_aggregate_capped,
    )

    k = np.array([1, 1, 1, 1, 2], dtype=np.int64)  # group 1 has 4 rows
    v = np.arange(5, dtype=np.int64)
    t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
    out, n, over = groupby_aggregate_capped(
        t, ["k"],
        [GroupbyAgg("v", "collect_list", list_capacity=2)],
        num_segments=4,
        return_collect_overflow=True,
    )
    assert int(n) == 2
    assert int(over) == 4  # > list_capacity: truncation detectable
    out2, _, over2 = groupby_aggregate_capped(
        t, ["k"],
        [GroupbyAgg("v", "collect_list", list_capacity=4)],
        num_segments=4,
        return_collect_overflow=True,
    )
    assert int(over2) == 4  # == capacity: lossless
