"""Pallas batched bitonic sort (kernels/bitonic_sort.py) vs lax.sort.

Runs in interpreter mode on the CPU tier (the kernels package
convention); the TPU A/B lives in bench.py (``chunk_sort_ab``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu.kernels.bitonic_sort import (
    batched_sort_u32,
    batched_sort_u64,
)


def _ref_sort(key, *payloads):
    """Oracle: stable variadic lax.sort with an iota tiebreaker."""
    c, t = key.shape
    iota = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (c, t))
    out = jax.lax.sort((key, iota) + payloads, num_keys=1, is_stable=True)
    return out[0], out[1], *out[2:]


@pytest.mark.parametrize("t", [8, 64, 256])
def test_matches_stable_lax_sort(t):
    rng = np.random.default_rng(7)
    c = 5
    key = jnp.asarray(
        rng.integers(0, 50, (c, t)).astype(np.uint64)  # many duplicates
    )
    v64 = jnp.asarray(rng.integers(-(2**60), 2**60, (c, t)))
    v32 = jnp.asarray(rng.integers(0, 2, (c, t)).astype(np.int32))
    got_k, got_p, got_v64, got_v32 = batched_sort_u64(key, v64, v32)
    ref_k, ref_p, ref_v64, ref_v32 = _ref_sort(key, v64, v32)
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(ref_k))
    # index tiebreaker == stability: full permutation must agree
    np.testing.assert_array_equal(np.asarray(got_p), np.asarray(ref_p))
    np.testing.assert_array_equal(np.asarray(got_v64), np.asarray(ref_v64))
    np.testing.assert_array_equal(np.asarray(got_v32), np.asarray(ref_v32))


def test_extreme_u64_keys():
    key = jnp.asarray(
        np.array(
            [[0, 2**64 - 1, 2**63, 1, 2**32, 2**32 - 1, 5, 2**63 - 1]],
            dtype=np.uint64,
        )
    )
    got_k, got_p = batched_sort_u64(key)[:2]
    np.testing.assert_array_equal(
        np.asarray(got_k)[0], np.sort(np.asarray(key)[0])
    )


def test_rejects_non_pow2():
    key = jnp.zeros((2, 12), jnp.uint64)
    with pytest.raises(ValueError):
        batched_sort_u64(key)


def test_float32_payload_bit_preserved():
    """ADVICE r4: 4-byte payloads must ride as bits, not values — the
    old astype widening truncated float32 (1.5 -> 1.0)."""
    rng = np.random.default_rng(5)
    key = jnp.asarray(rng.integers(0, 1 << 40, (2, 16)).astype(np.uint64))
    pay = jnp.asarray(rng.standard_normal((2, 16)).astype(np.float32))
    sk, perm, sp = batched_sort_u64(key, pay, interpret=True)
    rk, rp, rpay = _ref_sort(key, pay)
    np.testing.assert_array_equal(np.asarray(sk), np.asarray(rk))
    np.testing.assert_array_equal(np.asarray(sp), np.asarray(rpay))


def test_narrow_float_payload_rejected():
    key = jnp.zeros((1, 8), jnp.uint64)
    pay = jnp.zeros((1, 8), jnp.float16)
    with pytest.raises(TypeError, match="narrow float payload"):
        batched_sort_u64(key, pay, interpret=True)


def test_int16_payload_round_trips():
    rng = np.random.default_rng(6)
    key = jnp.asarray(rng.integers(0, 1 << 20, (2, 16)).astype(np.uint64))
    pay = jnp.asarray(
        rng.integers(-(1 << 15), 1 << 15, (2, 16), dtype=np.int64)
        .astype(np.int16)
    )
    sk, perm, sp = batched_sort_u64(key, pay, interpret=True)
    rk, rp, rpay = _ref_sort(key, pay.astype(jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(sp), np.asarray(rpay).astype(np.int16)
    )


def test_non_multiple_of_8_chunk_count():
    """Mosaic wants (8, T) blocks; a 3-chunk batch must pad + strip."""
    rng = np.random.default_rng(8)
    key = jnp.asarray(rng.integers(0, 1 << 40, (3, 128)).astype(np.uint64))
    got_k, got_p = batched_sort_u64(key, interpret=True)[:2]
    assert got_k.shape == (3, 128)
    np.testing.assert_array_equal(
        np.asarray(got_k), np.sort(np.asarray(key), axis=1)
    )


@pytest.mark.parametrize("t", [128, 512])
def test_u32_single_word_matches_argsort(t):
    """Distinct keys per row (the packed-iota contract): full agreement
    with np.argsort, payloads riding bit-exactly."""
    rng = np.random.default_rng(9)
    c = 11  # deliberately not a multiple of 8
    key = np.stack(
        [rng.permutation(1 << 20)[:t].astype(np.uint32) for _ in range(c)]
    )
    pay_f = rng.standard_normal((c, t)).astype(np.float32)
    pay_i = rng.integers(-100, 100, (c, t), dtype=np.int64).astype(np.int16)
    sk, sf, si = batched_sort_u32(
        jnp.asarray(key), jnp.asarray(pay_f), jnp.asarray(pay_i),
        interpret=True,
    )
    order = np.argsort(key, axis=1)
    np.testing.assert_array_equal(
        np.asarray(sk), np.take_along_axis(key, order, axis=1)
    )
    np.testing.assert_array_equal(
        np.asarray(sf), np.take_along_axis(pay_f, order, axis=1)
    )
    np.testing.assert_array_equal(
        np.asarray(si), np.take_along_axis(pay_i, order, axis=1)
    )


def test_u32_rejects_wide_payload_and_key():
    key = jnp.zeros((1, 8), jnp.uint32)
    with pytest.raises(TypeError, match="u32 network payload"):
        batched_sort_u32(key, jnp.zeros((1, 8), jnp.int64), interpret=True)
    with pytest.raises(TypeError, match="key must be uint32"):
        batched_sort_u32(jnp.zeros((1, 8), jnp.uint64), interpret=True)
