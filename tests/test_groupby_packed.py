"""Packed-key groupby (ops/groupby_packed.py) vs the single-pass
oracle: randomized equivalence across dtypes/aggs, capacity/overflow
protocol, router integration."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import groupby as groupby_mod
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg, groupby_aggregate
from spark_rapids_jni_tpu.ops.groupby_packed import (
    groupby_aggregate_packed,
    groupby_aggregate_packed_chunked,
    packed_groupby_supported,
)


def _to_dict(t, n_keys=1):
    keys = list(zip(*(t.columns[i].to_pylist() for i in range(n_keys))))
    out = {}
    for i, k in enumerate(keys):
        out[k] = tuple(
            t.columns[j].to_pylist()[i]
            for j in range(n_keys, len(t.columns))
        )
    return out


def _assert_equal(got, want):
    gd, wd = _to_dict(got), _to_dict(want)
    assert gd.keys() == wd.keys()
    for k in wd:
        for g, w in zip(gd[k], wd[k]):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-9), k
            else:
                assert g == w, k


AGGS = [
    GroupbyAgg("v", "sum"),
    GroupbyAgg("v", "count"),
    GroupbyAgg("v", "min"),
    GroupbyAgg("v", "max"),
    GroupbyAgg("v", "first"),
    GroupbyAgg("v", "last"),
    GroupbyAgg("v", "mean"),
]


class TestPackedEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_int_keys_randomized(self, seed):
        rng = np.random.default_rng(seed)
        n = 5000
        k = rng.integers(-300, 300, n, dtype=np.int64)
        v = rng.integers(-1000, 1000, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"], AGGS, num_segments=1024, chunk_rows=512,
            chunk_segments=1024,
        )
        assert not bool(ov)
        assert int(mc) <= 1024
        g = int(ng)
        got = Table(
            [Column(c.data[:g], c.dtype, None) for c in got.columns],
            got.names,
        )
        want = groupby_aggregate(t, ["k"], AGGS)
        _assert_equal(got, want)

    def test_float_values(self):
        rng = np.random.default_rng(3)
        n = 4000
        k = rng.integers(0, 50, n, dtype=np.int64)
        v = rng.standard_normal(n)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got = groupby_aggregate_packed(
            t, ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "min"),
             GroupbyAgg("v", "max"), GroupbyAgg("v", "mean")],
            chunk_rows=256,
        )
        assert got is not None
        want = groupby_aggregate(t, ["k"], [
            GroupbyAgg("v", "sum"), GroupbyAgg("v", "min"),
            GroupbyAgg("v", "max"), GroupbyAgg("v", "mean"),
        ])
        _assert_equal(got, want)

    def test_timestamp_key(self):
        rng = np.random.default_rng(4)
        n = 2000
        k = rng.integers(0, 40, n).astype(np.int32)
        v = rng.integers(0, 100, n, dtype=np.int64)
        t = Table(
            [
                Column(
                    __import__("jax.numpy", fromlist=["asarray"]).asarray(k),
                    dt.TIMESTAMP_DAYS,
                    None,
                ),
                Column.from_numpy(v),
            ],
            ["d", "v"],
        )
        got = groupby_aggregate_packed(
            t, ["d"], [GroupbyAgg("v", "sum")], chunk_rows=256
        )
        assert got is not None
        want = groupby_aggregate(t, ["d"], [GroupbyAgg("v", "sum")])
        _assert_equal(got, want)

    def test_first_last_semantics(self):
        # chunk-major order must preserve global first/last
        k = np.array([7, 3, 7, 3, 7, 3, 7, 3], np.int64)
        v = np.array([1, 2, 3, 4, 5, 6, 7, 8], np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"],
            [GroupbyAgg("v", "first"), GroupbyAgg("v", "last")],
            num_segments=8, chunk_rows=4, chunk_segments=4,
        )
        assert not bool(ov)
        g = int(ng)
        d = {
            int(np.asarray(got["k"].data)[i]): (
                int(np.asarray(got["first_v"].data)[i]),
                int(np.asarray(got["last_v"].data)[i]),
            )
            for i in range(g)
        }
        assert d == {3: (2, 8), 7: (1, 7)}


class TestProtocol:
    def test_overflow_flag_on_wide_range(self):
        # key span needs more bits than 64 - iota_bits: flagged, never
        # silently wrong
        k = np.array([0, 1 << 50, 5, 1 << 50, 9], np.int64)
        v = np.ones(5, np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        _, _, _, ov = groupby_aggregate_packed_chunked(
            t, ["k"], [GroupbyAgg("v", "sum")], num_segments=8,
            chunk_rows=1 << 18, chunk_segments=8,
        )
        assert bool(ov)

    def test_eager_declines_wide_range(self):
        rng = np.random.default_rng(5)
        n = 1000
        k = rng.integers(0, 1 << 62, n, dtype=np.int64)
        v = np.ones(n, np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        assert (
            groupby_aggregate_packed(t, ["k"], [GroupbyAgg("v", "sum")],
                                     chunk_rows=256)
            is None
        )

    def test_ineligible_shapes(self):
        n = 100
        k = np.arange(n, dtype=np.int64)
        v = np.ones(n, np.int64)
        valid = np.ones(n, bool)
        valid[3] = False
        t_null_key = Table(
            [Column.from_numpy(k, validity=valid), Column.from_numpy(v)],
            ["k", "v"],
        )
        assert not packed_groupby_supported(
            t_null_key, ["k"], [GroupbyAgg("v", "sum")]
        )
        t_two_keys = Table(
            [Column.from_numpy(k), Column.from_numpy(k), Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        assert not packed_groupby_supported(
            t_two_keys, ["a", "b"], [GroupbyAgg("v", "sum")]
        )
        t_float_key = Table(
            [Column.from_numpy(k.astype(np.float64)), Column.from_numpy(v)],
            ["k", "v"],
        )
        assert not packed_groupby_supported(
            t_float_key, ["k"], [GroupbyAgg("v", "sum")]
        )

    def test_router_uses_packed(self, monkeypatch):
        # shrink the routing threshold; the packed path must produce the
        # exact result through the public eager API
        monkeypatch.setattr(groupby_mod, "CHUNKED_MIN_ROWS", 512)
        rng = np.random.default_rng(6)
        n = 4096
        k = rng.integers(0, 64, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "sum")])
        want = {}
        for kk, vv in zip(k.tolist(), v.tolist()):
            want[kk] = want.get(kk, 0) + vv
        gd = dict(
            zip(got["k"].to_pylist(), got["sum_v"].to_pylist())
        )
        assert gd == want


class TestBoundary:
    def test_padding_never_merges_at_full_chunk_capacity(self):
        # review r5 scenario: last chunk has max_chunk == chunk_segments
        # real groups PLUS padding; padding must land in the dedicated
        # garbage slot, not the last real segment
        k = np.array([0, 0, 1, 1, 2, 3], np.int64)
        v = np.array([5, 5, 7, 7, -9, -9], np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"],
            [GroupbyAgg("v", "count"), GroupbyAgg("v", "min"),
             GroupbyAgg("v", "last")],
            num_segments=8, chunk_rows=4, chunk_segments=2,
        )
        assert not bool(ov)
        assert int(mc) == 2  # == chunk_segments: documented-exact edge
        g = int(ng)
        assert g == 4
        rows = {
            int(np.asarray(got["k"].data)[i]): (
                int(np.asarray(got["count_v"].data)[i]),
                int(np.asarray(got["min_v"].data)[i]),
                int(np.asarray(got["last_v"].data)[i]),
            )
            for i in range(g)
        }
        assert rows == {
            0: (2, 5, 5), 1: (2, 7, 7), 2: (1, -9, -9), 3: (1, -9, -9)
        }

    def test_schema_parity_with_single_pass(self):
        # the router swaps paths by key range: dtypes must be identical
        rng = np.random.default_rng(8)
        n = 3000
        k = rng.integers(0, 40, n, dtype=np.int64)
        v32 = rng.standard_normal(n).astype(np.float32)
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(v32)], ["k", "v"]
        )
        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
        packed = groupby_aggregate_packed(t, ["k"], aggs, chunk_rows=256)
        single = groupby_aggregate(t, ["k"], aggs)
        assert packed is not None
        for pc, sc in zip(packed.columns, single.columns):
            assert pc.dtype.id == sc.dtype.id, (pc.dtype, sc.dtype)
