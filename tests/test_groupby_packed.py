"""Packed-key groupby (ops/groupby_packed.py) vs the single-pass
oracle: randomized equivalence across dtypes/aggs, capacity/overflow
protocol, router integration."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import groupby as groupby_mod
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg, groupby_aggregate
from spark_rapids_jni_tpu.ops.groupby_packed import (
    groupby_aggregate_packed,
    groupby_aggregate_packed_chunked,
    packed_groupby_supported,
)


def _to_dict(t, n_keys=1):
    keys = list(zip(*(t.columns[i].to_pylist() for i in range(n_keys))))
    out = {}
    for i, k in enumerate(keys):
        out[k] = tuple(
            t.columns[j].to_pylist()[i]
            for j in range(n_keys, len(t.columns))
        )
    return out


def _assert_equal(got, want):
    gd, wd = _to_dict(got), _to_dict(want)
    assert gd.keys() == wd.keys()
    for k in wd:
        for g, w in zip(gd[k], wd[k]):
            if isinstance(w, float):
                assert g == pytest.approx(w, rel=1e-9), k
            else:
                assert g == w, k


AGGS = [
    GroupbyAgg("v", "sum"),
    GroupbyAgg("v", "count"),
    GroupbyAgg("v", "min"),
    GroupbyAgg("v", "max"),
    GroupbyAgg("v", "first"),
    GroupbyAgg("v", "last"),
    GroupbyAgg("v", "mean"),
]


class TestPackedEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_int_keys_randomized(self, seed):
        rng = np.random.default_rng(seed)
        n = 5000
        k = rng.integers(-300, 300, n, dtype=np.int64)
        v = rng.integers(-1000, 1000, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"], AGGS, num_segments=1024, chunk_rows=512,
            chunk_segments=1024,
        )
        assert not bool(ov)
        assert int(mc) <= 1024
        g = int(ng)
        got = Table(
            [Column(c.data[:g], c.dtype, None) for c in got.columns],
            got.names,
        )
        want = groupby_aggregate(t, ["k"], AGGS)
        _assert_equal(got, want)

    def test_float_values(self):
        rng = np.random.default_rng(3)
        n = 4000
        k = rng.integers(0, 50, n, dtype=np.int64)
        v = rng.standard_normal(n)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got = groupby_aggregate_packed(
            t, ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "min"),
             GroupbyAgg("v", "max"), GroupbyAgg("v", "mean")],
            chunk_rows=256,
        )
        assert got is not None
        want = groupby_aggregate(t, ["k"], [
            GroupbyAgg("v", "sum"), GroupbyAgg("v", "min"),
            GroupbyAgg("v", "max"), GroupbyAgg("v", "mean"),
        ])
        _assert_equal(got, want)

    def test_timestamp_key(self):
        rng = np.random.default_rng(4)
        n = 2000
        k = rng.integers(0, 40, n).astype(np.int32)
        v = rng.integers(0, 100, n, dtype=np.int64)
        t = Table(
            [
                Column(
                    __import__("jax.numpy", fromlist=["asarray"]).asarray(k),
                    dt.TIMESTAMP_DAYS,
                    None,
                ),
                Column.from_numpy(v),
            ],
            ["d", "v"],
        )
        got = groupby_aggregate_packed(
            t, ["d"], [GroupbyAgg("v", "sum")], chunk_rows=256
        )
        assert got is not None
        want = groupby_aggregate(t, ["d"], [GroupbyAgg("v", "sum")])
        _assert_equal(got, want)

    def test_first_last_semantics(self):
        # chunk-major order must preserve global first/last
        k = np.array([7, 3, 7, 3, 7, 3, 7, 3], np.int64)
        v = np.array([1, 2, 3, 4, 5, 6, 7, 8], np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"],
            [GroupbyAgg("v", "first"), GroupbyAgg("v", "last")],
            num_segments=8, chunk_rows=4, chunk_segments=4,
        )
        assert not bool(ov)
        g = int(ng)
        d = {
            int(np.asarray(got["k"].data)[i]): (
                int(np.asarray(got["first_v"].data)[i]),
                int(np.asarray(got["last_v"].data)[i]),
            )
            for i in range(g)
        }
        assert d == {3: (2, 8), 7: (1, 7)}


class TestProtocol:
    def test_overflow_flag_on_wide_range(self):
        # key span needs more bits than 64 - iota_bits: flagged, never
        # silently wrong
        k = np.array([0, 1 << 50, 5, 1 << 50, 9], np.int64)
        v = np.ones(5, np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        _, _, _, ov = groupby_aggregate_packed_chunked(
            t, ["k"], [GroupbyAgg("v", "sum")], num_segments=8,
            chunk_rows=1 << 18, chunk_segments=8,
        )
        assert bool(ov)

    def test_eager_declines_wide_range(self):
        rng = np.random.default_rng(5)
        n = 1000
        k = rng.integers(0, 1 << 62, n, dtype=np.int64)
        v = np.ones(n, np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        assert (
            groupby_aggregate_packed(t, ["k"], [GroupbyAgg("v", "sum")],
                                     chunk_rows=256)
            is None
        )

    def test_ineligible_shapes(self):
        n = 100
        k = np.arange(n, dtype=np.int64)
        v = np.ones(n, np.int64)
        valid = np.ones(n, bool)
        valid[3] = False
        t_null_key = Table(
            [Column.from_numpy(k, validity=valid), Column.from_numpy(v)],
            ["k", "v"],
        )
        assert not packed_groupby_supported(
            t_null_key, ["k"], [GroupbyAgg("v", "sum")]
        )
        # multi-key INT shapes are eligible since the composite-field
        # generalization (see TestMultiKey)
        t_two_keys = Table(
            [Column.from_numpy(k), Column.from_numpy(k), Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        assert packed_groupby_supported(
            t_two_keys, ["a", "b"], [GroupbyAgg("v", "sum")]
        )
        t_float_key = Table(
            [Column.from_numpy(k.astype(np.float64)), Column.from_numpy(v)],
            ["k", "v"],
        )
        assert not packed_groupby_supported(
            t_float_key, ["k"], [GroupbyAgg("v", "sum")]
        )

    def test_router_uses_packed(self, monkeypatch):
        # shrink the routing threshold and opt into the packed
        # formulation (round 5 made "single" the measured default);
        # the packed path must produce the exact result through the
        # public eager API
        monkeypatch.setattr(groupby_mod, "CHUNKED_MIN_ROWS", 512)
        monkeypatch.setenv(
            "SPARK_RAPIDS_TPU_GROUPBY_FORMULATION", "packed"
        )
        rng = np.random.default_rng(6)
        n = 4096
        k = rng.integers(0, 64, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got = groupby_aggregate(t, ["k"], [GroupbyAgg("v", "sum")])
        want = {}
        for kk, vv in zip(k.tolist(), v.tolist()):
            want[kk] = want.get(kk, 0) + vv
        gd = dict(
            zip(got["k"].to_pylist(), got["sum_v"].to_pylist())
        )
        assert gd == want


class TestBoundary:
    def test_padding_never_merges_at_full_chunk_capacity(self):
        # review r5 scenario: last chunk has max_chunk == chunk_segments
        # real groups PLUS padding; padding must land in the dedicated
        # garbage slot, not the last real segment
        k = np.array([0, 0, 1, 1, 2, 3], np.int64)
        v = np.array([5, 5, 7, 7, -9, -9], np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"],
            [GroupbyAgg("v", "count"), GroupbyAgg("v", "min"),
             GroupbyAgg("v", "last")],
            num_segments=8, chunk_rows=4, chunk_segments=2,
        )
        assert not bool(ov)
        assert int(mc) == 2  # == chunk_segments: documented-exact edge
        g = int(ng)
        assert g == 4
        rows = {
            int(np.asarray(got["k"].data)[i]): (
                int(np.asarray(got["count_v"].data)[i]),
                int(np.asarray(got["min_v"].data)[i]),
                int(np.asarray(got["last_v"].data)[i]),
            )
            for i in range(g)
        }
        assert rows == {
            0: (2, 5, 5), 1: (2, 7, 7), 2: (1, -9, -9), 3: (1, -9, -9)
        }

    def test_schema_parity_with_single_pass(self):
        # the router swaps paths by key range: dtypes must be identical
        rng = np.random.default_rng(8)
        n = 3000
        k = rng.integers(0, 40, n, dtype=np.int64)
        v32 = rng.standard_normal(n).astype(np.float32)
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(v32)], ["k", "v"]
        )
        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
        packed = groupby_aggregate_packed(t, ["k"], aggs, chunk_rows=256)
        single = groupby_aggregate(t, ["k"], aggs)
        assert packed is not None
        for pc, sc in zip(packed.columns, single.columns):
            assert pc.dtype.id == sc.dtype.id, (pc.dtype, sc.dtype)


class TestMultiKey:
    """Composite bit-field packing: several narrow keys in one word."""

    def _to_dict2(self, t, nk):
        return _to_dict(t, n_keys=nk)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_two_keys_randomized(self, seed):
        rng = np.random.default_rng(seed)
        n = 4000
        # span product must stay below the router's chunking-wins bail
        a = rng.integers(-4, 4, n, dtype=np.int64)
        b = rng.integers(0, 6, n, dtype=np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        t = Table(
            [Column.from_numpy(a), Column.from_numpy(b),
             Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count"),
                GroupbyAgg("v", "min")]
        got = groupby_aggregate_packed(t, ["a", "b"], aggs, chunk_rows=256)
        assert got is not None
        want = groupby_aggregate(t, ["a", "b"], aggs)
        gd = self._to_dict2(got, 2)
        wd = self._to_dict2(want, 2)
        assert gd == wd

    def test_three_keys_tpcds_q64_shape(self):
        # (brand, state, year): the q64 grouping key
        rng = np.random.default_rng(7)
        n = 6000
        brand = rng.integers(1, 40, n, dtype=np.int64)
        state = rng.integers(0, 8, n, dtype=np.int32)
        year = rng.integers(1998, 2003, n, dtype=np.int64)
        rev = rng.standard_normal(n)
        import jax.numpy as jnp

        t = Table(
            [Column.from_numpy(brand),
             Column(jnp.asarray(state), dt.INT32, None),
             Column.from_numpy(year), Column.from_numpy(rev)],
            ["brand", "state", "year", "rev"],
        )
        aggs = [GroupbyAgg("rev", "sum"), GroupbyAgg("rev", "count")]
        got = groupby_aggregate_packed(
            t, ["brand", "state", "year"], aggs, chunk_rows=1024
        )
        assert got is not None
        want = groupby_aggregate(t, ["brand", "state", "year"], aggs)
        gd = self._to_dict2(got, 3)
        wd = self._to_dict2(want, 3)
        assert gd.keys() == wd.keys()
        for k in wd:
            assert gd[k][1] == wd[k][1]
            assert gd[k][0] == pytest.approx(wd[k][0], rel=1e-9)

    def test_field_overflow_flagged(self):
        # declared field too narrow for the data: traced flag, not
        # silent corruption
        k1 = np.array([0, 300, 5, 300], np.int64)  # needs 9 bits
        k2 = np.array([0, 1, 2, 3], np.int64)
        v = np.ones(4, np.int64)
        t = Table(
            [Column.from_numpy(k1), Column.from_numpy(k2),
             Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        _, _, _, ov = groupby_aggregate_packed_chunked(
            t, ["a", "b"], [GroupbyAgg("v", "sum")], num_segments=8,
            chunk_rows=4, chunk_segments=8, field_bits=(4, 2),
        )
        assert bool(ov)

    def test_wide_multi_key_declines(self):
        rng = np.random.default_rng(9)
        n = 1000
        a = rng.integers(0, 1 << 40, n, dtype=np.int64)
        b = rng.integers(0, 1 << 40, n, dtype=np.int64)
        t = Table(
            [Column.from_numpy(a), Column.from_numpy(b),
             Column.from_numpy(np.ones(n, np.int64))],
            ["a", "b", "v"],
        )
        assert (
            groupby_aggregate_packed(
                t, ["a", "b"], [GroupbyAgg("v", "sum")], chunk_rows=256
            )
            is None
        )


class TestFlatVariant:
    """Single-level packed groupby: the high-cardinality arm."""

    # 7-agg sweep at 4096 segments is minutes of XLA CPU compile; the
    # faster flat-arm tests below keep premerge coverage, nightly runs all
    @pytest.mark.slow
    def test_matches_single_pass(self):
        from spark_rapids_jni_tpu.ops.groupby_packed import (
            groupby_aggregate_packed_flat,
        )

        rng = np.random.default_rng(11)
        n = 5000
        k = rng.integers(-2000, 2000, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        out, ng, ov = groupby_aggregate_packed_flat(
            t, ["k"], AGGS, num_segments=4096
        )
        assert not bool(ov)
        g = int(ng)
        got = Table(
            [Column(c.data[:g], c.dtype, None) for c in out.columns],
            out.names,
        )
        want = groupby_aggregate(t, ["k"], AGGS)
        _assert_equal(got, want)

    def test_multi_key_flat(self):
        from spark_rapids_jni_tpu.ops.groupby_packed import (
            groupby_aggregate_packed_flat,
        )

        rng = np.random.default_rng(12)
        n = 3000
        a = rng.integers(0, 300, n, dtype=np.int64)
        b = rng.integers(-20, 20, n, dtype=np.int64)
        v = rng.integers(0, 9, n, dtype=np.int64)
        t = Table(
            [Column.from_numpy(a), Column.from_numpy(b),
             Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        out, ng, ov = groupby_aggregate_packed_flat(
            t, ["a", "b"], [GroupbyAgg("v", "sum")], num_segments=n,
            field_bits=(9, 6),
        )
        assert not bool(ov)
        g = int(ng)
        got = {}
        aa = np.asarray(out["a"].data)[:g]
        bb = np.asarray(out["b"].data)[:g]
        ss = np.asarray(out["sum_v"].data)[:g]
        for i in range(g):
            got[(int(aa[i]), int(bb[i]))] = int(ss[i])
        want = {}
        for x, y, z in zip(a.tolist(), b.tolist(), v.tolist()):
            want[(x, y)] = want.get((x, y), 0) + z
        assert got == want

    def test_gather_arm_matches_sort_arm(self):
        from spark_rapids_jni_tpu.ops.groupby_packed import (
            groupby_aggregate_packed_flat,
        )

        rng = np.random.default_rng(13)
        n = 4000
        k = rng.integers(0, 500, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        a, ng_a, ov_a = groupby_aggregate_packed_flat(
            t, ["k"], AGGS, num_segments=512, values_via="sort"
        )
        b, ng_b, ov_b = groupby_aggregate_packed_flat(
            t, ["k"], AGGS, num_segments=512, values_via="gather"
        )
        assert not bool(ov_a) and not bool(ov_b)
        assert int(ng_a) == int(ng_b)
        g = int(ng_a)
        for ca, cb in zip(a.columns, b.columns):
            np.testing.assert_array_equal(
                np.asarray(ca.data)[:g], np.asarray(cb.data)[:g]
            )
        with pytest.raises(ValueError, match="values_via"):
            groupby_aggregate_packed_flat(
                t, ["k"], AGGS, num_segments=512, values_via="scatter"
            )

    def test_capacity_overflow_flagged(self):
        from spark_rapids_jni_tpu.ops.groupby_packed import (
            groupby_aggregate_packed_flat,
        )

        k = np.arange(100, dtype=np.int64)
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(k)], ["k", "v"]
        )
        _, _, ov = groupby_aggregate_packed_flat(
            t, ["k"], [GroupbyAgg("v", "sum")], num_segments=10
        )
        assert bool(ov)

    def test_router_takes_flat_for_high_cardinality(self):
        rng = np.random.default_rng(13)
        n = 60_000
        k = rng.integers(0, 50_000, n, dtype=np.int64)
        v = rng.integers(-5, 5, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        got = groupby_aggregate_packed(
            t, ["k"], [GroupbyAgg("v", "sum")], chunk_rows=2048
        )
        assert got is not None
        wd = {}
        for kk, vv in zip(k.tolist(), v.tolist()):
            wd[kk] = wd.get(kk, 0) + vv
        gd = dict(zip(got["k"].to_pylist(), got["sum_v"].to_pylist()))
        assert gd == wd


class TestPallasEngines:
    """The VMEM bitonic phase-1 engines must agree exactly with the
    lax.sort engine (values follow the word sort by gather)."""

    @pytest.mark.parametrize("engine", ["pallas", "pallas32"])
    def test_engine_equivalence(self, engine):
        rng = np.random.default_rng(17)
        n = 2000
        k = rng.integers(-40, 40, n, dtype=np.int64)
        v = rng.integers(-1000, 1000, n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        kwargs = dict(
            num_segments=128, chunk_rows=256, chunk_segments=128,
        )
        want, ng0, mc0, ov0 = groupby_aggregate_packed_chunked(
            t, ["k"], AGGS, **kwargs
        )
        got, ng, mc, ov = groupby_aggregate_packed_chunked(
            t, ["k"], AGGS, engine=engine, **kwargs
        )
        assert not bool(ov) and not bool(ov0)
        assert int(ng) == int(ng0)
        g = int(ng)
        for a, b in zip(got.columns, want.columns):
            np.testing.assert_array_equal(
                np.asarray(a.data)[:g], np.asarray(b.data)[:g]
            )

    def test_pallas32_overflow_flagged_not_silent(self):
        # key range wider than 32 - iota_bits: the u32 narrowing would
        # corrupt words, so the traced overflow flag must fire
        n = 512
        k = (np.arange(n, dtype=np.int64) * (1 << 22))  # span ~2^31
        v = np.ones(n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        _, _, _, ov = groupby_aggregate_packed_chunked(
            t, ["k"], [GroupbyAgg("v", "sum")], num_segments=512,
            chunk_rows=256, chunk_segments=512, engine="pallas32",
        )
        assert bool(ov)

    def test_pallas32_all_ones_word_reserved(self):
        # a REAL packed word equal to 0xFFFFFFFF would alias the u32
        # padding sentinel after narrowing: the fit check must reserve
        # it (flag overflow), not silently corrupt that row's key
        chunk_rows = 256  # iota_bits = 8
        n = chunk_rows
        k = np.zeros(n, dtype=np.int64)
        k[-1] = (1 << 24) - 1  # rel<<8 | iota 255 == 0xFFFFFFFF
        v = np.ones(n, dtype=np.int64)
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        _, _, _, ov = groupby_aggregate_packed_chunked(
            t, ["k"], [GroupbyAgg("v", "sum")], num_segments=n,
            chunk_rows=chunk_rows, chunk_segments=n, engine="pallas32",
        )
        assert bool(ov)

    def test_unknown_engine_rejected(self):
        t = Table(
            [
                Column.from_numpy(np.zeros(8, dtype=np.int64)),
                Column.from_numpy(np.zeros(8, dtype=np.int64)),
            ],
            ["k", "v"],
        )
        with pytest.raises(ValueError, match="engine"):
            groupby_aggregate_packed_chunked(
                t, ["k"], [GroupbyAgg("v", "sum")], num_segments=8,
                chunk_rows=8, chunk_segments=8, engine="cuda",
            )


class TestCappedGatherArm:
    def test_gather_matches_sort_arm(self):
        from spark_rapids_jni_tpu.ops.groupby import (
            groupby_aggregate_capped,
        )

        rng = np.random.default_rng(29)
        n = 4000
        k = rng.integers(0, 200, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        # with nulls + row_valid: the gather arm must route the
        # validity payload identically
        import jax.numpy as jnp

        kv = np.ones(n, dtype=bool)
        kv[::17] = False
        t = Table(
            [Column.from_numpy(k),
             Column.from_numpy(v, validity=kv)],
            ["k", "v"],
        )
        rv = jnp.asarray(np.arange(n) < (n - 100))
        a, ng_a = groupby_aggregate_capped(
            t, ["k"], AGGS, num_segments=256, row_valid=rv
        )
        b, ng_b = groupby_aggregate_capped(
            t, ["k"], AGGS, num_segments=256, row_valid=rv,
            values_via="gather",
        )
        assert int(ng_a) == int(ng_b)
        g = int(ng_a)
        for ca, cb in zip(a.columns, b.columns):
            np.testing.assert_array_equal(
                np.asarray(ca.data)[:g], np.asarray(cb.data)[:g]
            )
            if ca.validity is not None or cb.validity is not None:
                np.testing.assert_array_equal(
                    np.asarray(ca.validity)[:g],
                    np.asarray(cb.validity)[:g],
                )
