"""bench.py exit-clean + fast-fail guards (ISSUE 2 satellites).

Two consecutive rounds ended ``rc=124, parsed=null``: the driver's
timeout killed the ladder between a progress line and the next emit.
These tests pin the repair surface: structured skip records, the
unreachable-failure classifier behind the fast-fail ladder, and the
last-emitted-line guarantee the SIGTERM handler re-prints.
"""

import json

import bench


class TestFailureRecords:
    def test_skipped_flag(self):
        e = bench._failure_record(
            "groupby100m", "skipped: budget 3300s exhausted",
            exc_type="BudgetExceeded", elapsed_s=3301.2, skipped=True,
        )
        assert e["failure"]["type"] == "BudgetExceeded"
        assert e["failure"]["skipped"] is True
        assert e["failure"]["elapsed_s"] == 3301.2
        # old readers still see the flat error string
        assert "budget" in e["error"]

    def test_default_not_skipped(self):
        e = bench._failure_record("join", ValueError("boom"))
        assert e["failure"]["skipped"] is False
        assert e["failure"]["type"] == "ValueError"


class TestUnreachableClassifier:
    def test_unreachable_markers(self):
        for msg in (
            "device unreachable",
            "UNAVAILABLE: socket closed",
            "DEADLINE_EXCEEDED while fetching",
            "failed to connect to tunnel",
            "Failed to connect to remote host",  # capitalized gRPC text
            "Socket closed",
        ):
            e = bench._failure_record("cfg", msg, exc_type="SubprocessFailed")
            assert bench._unreachable_failure(e), msg

    def test_timeout_type_counts_as_unreachable(self):
        e = bench._failure_record(
            "cfg", "timeout 1800s", exc_type="TimeoutExpired"
        )
        assert bench._unreachable_failure(e)

    def test_genuine_crash_is_not_unreachable(self):
        e = bench._failure_record(
            "cfg", "assertion failed: groupby-sum mismatch vs numpy",
            exc_type="SubprocessFailed",
        )
        assert not bench._unreachable_failure(e)

    def test_tolerates_old_records_without_failure_block(self):
        assert not bench._unreachable_failure({"name": "x", "error": "boom"})
        assert bench._unreachable_failure(
            {"name": "x", "error": "device unreachable"}
        )


class TestEmitGuarantee:
    def test_emit_stores_last_line_parseable(self, capsys):
        bench._emit([{"name": "x", "error": "boom",
                      "failure": {"type": "Error", "message": "boom",
                                  "elapsed_s": None, "retries": 0,
                                  "skipped": False}}], "cpu")
        out = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(out)
        assert doc["metric"] == "groupby_sum_100M_int64"
        # the SIGTERM handler re-prints exactly this line
        assert bench._LAST_LINE == out
        assert json.loads(bench._LAST_LINE)["configs"][0]["name"] == "x"
