"""bench.py exit-clean + fast-fail guards (ISSUE 2 satellites) and the
SIGTERM telemetry-flush integration (ISSUE 3 satellite).

Two consecutive rounds ended ``rc=124, parsed=null``: the driver's
timeout killed the ladder between a progress line and the next emit.
These tests pin the repair surface: structured skip records, the
unreachable-failure classifier behind the fast-fail ladder, the
last-emitted-line guarantee the SIGTERM handler re-prints — and, since
the flight-recorder PR, that the same handler flushes the METRICS_DUMP
and FLIGHT_DUMP artifacts before ``os._exit`` (atexit never runs past
it), so an rc=124 run still leaves its telemetry behind.
"""

import json
import os
import subprocess
import sys
import textwrap

import bench

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFailureRecords:
    def test_skipped_flag(self):
        e = bench._failure_record(
            "groupby100m", "skipped: budget 3300s exhausted",
            exc_type="BudgetExceeded", elapsed_s=3301.2, skipped=True,
        )
        assert e["failure"]["type"] == "BudgetExceeded"
        assert e["failure"]["skipped"] is True
        assert e["failure"]["elapsed_s"] == 3301.2
        # old readers still see the flat error string
        assert "budget" in e["error"]

    def test_default_not_skipped(self):
        e = bench._failure_record("join", ValueError("boom"))
        assert e["failure"]["skipped"] is False
        assert e["failure"]["type"] == "ValueError"


class TestUnreachableClassifier:
    def test_unreachable_markers(self):
        for msg in (
            "device unreachable",
            "UNAVAILABLE: socket closed",
            "DEADLINE_EXCEEDED while fetching",
            "failed to connect to tunnel",
            "Failed to connect to remote host",  # capitalized gRPC text
            "Socket closed",
        ):
            e = bench._failure_record("cfg", msg, exc_type="SubprocessFailed")
            assert bench._unreachable_failure(e), msg

    def test_timeout_type_counts_as_unreachable(self):
        e = bench._failure_record(
            "cfg", "timeout 1800s", exc_type="TimeoutExpired"
        )
        assert bench._unreachable_failure(e)

    def test_structured_timeout_record_counts_as_unreachable(self):
        # the per-arm {type:"timeout"} record (an arm overrunning its
        # wall-clock slice) classifies transient like TimeoutExpired
        e = bench._failure_record("cfg", "timeout 900s", exc_type="timeout")
        assert e["failure"]["type"] == "timeout"
        assert bench._unreachable_failure(e)

    def test_genuine_crash_is_not_unreachable(self):
        e = bench._failure_record(
            "cfg", "assertion failed: groupby-sum mismatch vs numpy",
            exc_type="SubprocessFailed",
        )
        assert not bench._unreachable_failure(e)

    def test_tolerates_old_records_without_failure_block(self):
        assert not bench._unreachable_failure({"name": "x", "error": "boom"})
        assert bench._unreachable_failure(
            {"name": "x", "error": "device unreachable"}
        )


class TestSigtermTelemetryFlush:
    def test_sigterm_flushes_metrics_and_flight_dumps(self, tmp_path):
        """A SIGTERM'd bench process must leave BOTH dump files behind
        and still print the headline JSON as its final stdout line —
        the rc=124 postmortem contract. The span is deliberately left
        open when the signal lands: that is exactly the state a killed
        run dies in, and the flight tail must show it."""
        mdump = tmp_path / "metrics.json"
        fdump = tmp_path / "flight.json"
        script = textwrap.dedent(
            f"""
            import os, signal, sys, time
            sys.path.insert(0, {_ROOT!r})
            import bench
            bench._install_exit_handlers()
            bench._metrics_enable()
            from spark_rapids_jni_tpu.utils import flight, metrics
            bench._LAST_LINE = '{{"metric": "sigterm-test"}}'
            with metrics.span("cfg.doomed"):
                flight.record("I", "tunnel.probe_retry")
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(30)
                sys.exit(3)  # handler never fired
            """
        )
        env = dict(os.environ)
        env.update({
            "SPARK_RAPIDS_TPU_METRICS_DUMP": str(mdump),
            "SPARK_RAPIDS_TPU_FLIGHT_DUMP": str(fdump),
            "JAX_PLATFORMS": "cpu",
            "SRT_JAX_PLATFORMS": "cpu",
        })
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=300, env=env, cwd=_ROOT,
        )
        assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
        # the final stdout line is the re-printed headline JSON
        last = proc.stdout.strip().splitlines()[-1]
        assert json.loads(last)["metric"] == "sigterm-test"
        # metrics snapshot flushed by the handler (atexit never ran)
        snap = json.loads(mdump.read_text())
        assert "counters" in snap
        # flight tail flushed too: the open span's B, the instant, and
        # the handler's own sigterm marker
        doc = json.loads(fdump.read_text())
        names = [e["name"] for e in doc["events"]]
        assert "cfg.doomed" in names
        assert "tunnel.probe_retry" in names
        assert names[-1] == "bench.sigterm"
        # the span never closed — no E event for it (the crash shape
        # tools/trace2chrome.py renders as an unterminated X)
        assert not any(
            e["ph"] == "E" and e["name"] == "cfg.doomed"
            for e in doc["events"]
        )


class TestBudgetExhaustedRun:
    def test_zero_budget_run_exits_clean_with_parseable_headline(self):
        """A fully budget-starved run must still exit 0 with the
        headline JSON as the final stdout line, every ladder arm
        recorded as a skipped BudgetExceeded, and the mesh/Arrow tail
        skipped by its floors instead of starting unbounded work —
        the repair for the rc=124, parsed=null rounds."""
        env = dict(os.environ)
        env.update({
            "SRT_BENCH_BUDGET_S": "0",
            "JAX_PLATFORMS": "cpu",
            "SRT_JAX_PLATFORMS": "cpu",
        })
        proc = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True,
            text=True, timeout=280, env=env, cwd=_ROOT,
        )
        assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
        last = proc.stdout.strip().splitlines()[-1]
        doc = json.loads(last)
        assert doc["metric"] == "groupby_sum_100M_int64"
        by_name = {c["name"]: c for c in doc["configs"]}
        # every budgeted ladder arm is present as a structured skip
        assert set(bench._LADDER) <= set(by_name)
        for arm in bench._LADDER:
            c = by_name[arm]
            assert c["failure"]["type"] == "BudgetExceeded"
            assert c["failure"]["skipped"] is True
        # the mesh tail arms likewise carry typed skip records instead
        # of vanishing into a progress line: the skew arm is
        # budget-starved, the TPC-DS-from-parquet arm is opt-in
        skew = by_name[
            "config 4: distributed zipf skew, 8-device CPU mesh"
        ]
        assert skew["failure"]["type"] == "BudgetExceeded"
        assert skew["failure"]["skipped"] is True
        tpcds = by_name[
            "config 4: TPC-DS q5/q23/q64 from parquet, 8-dev mesh"
        ]
        assert tpcds["failure"]["type"] == "OptInSkipped"
        assert tpcds["failure"]["skipped"] is True
        # the tail floors declined to start the unbounded stages
        assert "skipping arrow baseline" in proc.stderr

    def test_walk_reserves_a_tail_window(self):
        # the walk must end early enough that both mesh stages and the
        # Arrow baseline can still start inside the budget deadline
        assert bench._TAIL_RESERVE_S > (
            2 * bench._MESH_STAGE_FLOOR_S + bench._ARROW_FLOOR_S
        )

    def test_superseded_slow_arms_are_manual(self):
        # losers of the packed/chunked A/Bs no longer walk: each alone
        # could eat the whole tail window
        for arm in (
            "groupby16m_packed_pallas32",
            "groupby100m_packed_pallas32",
            "groupby100m_packed",
            "groupby100m_chunked",
        ):
            assert bench._ARM_TIERS[arm] == "manual"
            assert arm not in bench._LADDER
            # still runnable one-off
            assert arm in bench._SUBPROCESS_CONFIGS


class TestEmitGuarantee:
    def test_emit_stores_last_line_parseable(self, capsys):
        bench._emit([{"name": "x", "error": "boom",
                      "failure": {"type": "Error", "message": "boom",
                                  "elapsed_s": None, "retries": 0,
                                  "skipped": False}}], "cpu")
        out = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(out)
        assert doc["metric"] == "groupby_sum_100M_int64"
        # the SIGTERM handler re-prints exactly this line
        assert bench._LAST_LINE == out
        assert json.loads(bench._LAST_LINE)["configs"][0]["name"] == "x"
