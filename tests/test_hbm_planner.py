"""HBM footprint planner (utils/hbm.py, round-4 VERDICT item 7).

The planner must (a) estimate resident bytes accurately for columns and
tables, (b) size join probe chunks from the budget instead of fixed
constants, and (c) make the batched join re-split skewed chunks whose
output would blow the planned footprint — all verified here against a
pandas oracle so safety never changes answers.
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import join as join_mod
from spark_rapids_jni_tpu.utils import config, hbm


@pytest.fixture(autouse=True)
def _clear_flags():
    yield
    config.clear_flag("HBM_BUDGET_GB")


def test_column_and_table_bytes_exact():
    n = 1000
    c1 = Column.from_numpy(np.arange(n, dtype=np.int64))           # 8000
    c2 = Column.from_numpy(
        np.arange(n, dtype=np.int32), validity=np.ones(n, bool)
    )  # 4000 + 1000
    t = Table([c1, c2])
    assert hbm.column_bytes(c1) == 8 * n
    assert hbm.column_bytes(c2) == 5 * n
    assert hbm.table_bytes(t) == 13 * n
    assert hbm.row_bytes(t) == 13


def test_string_key_word_count():
    c = Column.from_strings(["abcdefgh" * 2, "x"])  # pad 16
    # pad/8 = 2 words + length word; nullable adds one more
    assert hbm.key_word_count([c]) == 3


def test_budget_flag_and_reserve():
    config.set_flag("HBM_BUDGET_GB", 2.0)
    b = hbm.budget_bytes()
    assert b == int(2.0 * hbm.GIB * (1 - hbm.RESERVE_FRACTION))
    config.set_flag("HBM_BUDGET_GB", 4.0)
    assert hbm.budget_bytes() == 2 * b


def _tables(n=6000, seed=0, hot=None):
    rng = np.random.default_rng(seed)
    kl = rng.integers(0, 500, n).astype(np.int64)
    kr = rng.integers(0, 500, n).astype(np.int64)
    if hot is not None:
        kl[: n // 3] = hot  # skew a third of the probe side onto one key
        kr[: n // 3] = hot
    left = Table(
        [Column.from_numpy(kl), Column.from_numpy(np.arange(n, dtype=np.int64))],
        ["k", "lv"],
    )
    right = Table(
        [Column.from_numpy(kr), Column.from_numpy(np.arange(n, dtype=np.int64) * 3)],
        ["k", "rv"],
    )
    return left, right, kl, kr


def test_join_plan_scales_with_budget():
    left, right, _, _ = _tables()
    config.set_flag("HBM_BUDGET_GB", 1.0)
    small = hbm.join_plan(left, right, ["k"], ["k"])
    config.set_flag("HBM_BUDGET_GB", 8.0)
    big = hbm.join_plan(left, right, ["k"], ["k"])
    assert big["probe_rows"] > small["probe_rows"]
    assert small["fits"] and big["fits"]
    # at 100M-row scale the plan must stay under the fault fence anyway
    assert small["probe_rows"] >= 1024


def test_batched_join_resplits_skewed_chunks(monkeypatch):
    """A hot key whose fan-out blows the chunk output budget must force
    a re-split (more probe calls), with identical results."""
    left, right, kl, kr = _tables(n=6000, seed=2, hot=7)
    oracle = pd.DataFrame({"k": kl, "lv": np.arange(6000)}).merge(
        pd.DataFrame({"k": kr, "rv": np.arange(6000) * 3}), on="k"
    )

    calls = {"n": 0}
    real = join_mod._chunk_ranges_fn

    def counting(on, with_valid):
        fn = real(on, with_valid)

        def wrapped(*a, **k):
            calls["n"] += 1
            return fn(*a, **k)

        return wrapped

    monkeypatch.setattr(join_mod, "_chunk_ranges_fn", counting)
    out = join_mod.inner_join_batched(
        left, right, ["k"], probe_rows=4096
    )
    base_calls = calls["n"]  # ceil(6000/4096) = 2 probes, no splits
    assert out.row_count == len(oracle)

    # shrink the output-budget floor so the skewed chunk (hot key:
    # fan-out >> 2x) exceeds it and MUST re-split down to 1024-row
    # spans before materializing; 4096-row chunks satisfy the
    # `stop - start > 1024` split guard
    calls["n"] = 0
    monkeypatch.setattr(join_mod, "MIN_CHUNK_OUT_BYTES", 1 << 15)
    out2 = join_mod.inner_join_batched(left, right, ["k"], probe_rows=4096)
    assert calls["n"] > base_calls, "oversized chunks did not re-split"
    assert out2.row_count == len(oracle)
    got = np.asarray(out2["lv"].to_numpy(), np.int64).sum() + np.asarray(
        out2["rv"].to_numpy(), np.int64
    ).sum()
    assert int(got) == int(oracle.lv.sum() + oracle.rv.sum())


def test_sort_and_groupby_plans_report_fit():
    left, right, _, _ = _tables(n=4000)
    sp = hbm.sort_plan(left, n_key_words=2)
    assert sp["fits"] and sp["total_bytes"] > 0
    gp = hbm.groupby_plan(left, ["k"], num_segments=1000)
    assert gp["fits"] and gp["total_bytes"] > 0
    # a 100M-row x 50-col monster must NOT claim to fit in 1 GiB
    config.set_flag("HBM_BUDGET_GB", 1.0)
    big = Table(
        [Column.from_numpy(np.zeros(100, np.int64)) for _ in range(3)],
        ["a", "b", "c"],
    )

    class Fake:
        row_count = 100_000_000
        columns = big.columns

        def column(self, c):
            return big.columns[0]

    fake = Fake()
    import unittest.mock as mock

    with mock.patch.object(hbm, "table_bytes", return_value=100_000_000 * 24):
        assert not hbm.sort_plan(fake, n_key_words=2)["fits"]


def test_distributed_recv_capacity_warns_over_budget(monkeypatch):
    """r3 weak item 6: capacity plans must check HBM fit. A tiny forced
    budget makes the planned receive buffer 'exceed' the chip and the
    exchange must warn (real chips would OOM mid-collective)."""
    import warnings

    import jax.numpy as jnp

    from spark_rapids_jni_tpu.parallel import distributed as dist

    t = Table(
        [Column.from_numpy(np.arange(64, dtype=np.int64)),
         Column.from_numpy(np.arange(64, dtype=np.int64))],
        ["k", "v"],
    )
    config.set_flag("HBM_BUDGET_GB", 1e-9)  # ~1 byte budget
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dist._warn_if_recv_exceeds_hbm(64, t, "groupby")
    assert any("receive capacity" in str(x.message) for x in w)
    config.clear_flag("HBM_BUDGET_GB")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dist._warn_if_recv_exceeds_hbm(64, t, "groupby")
    assert not w
