"""Pipelined dispatch plane: parity, concurrency, donation, errors.

The ISSUE-5 contract under test: with ``SPARK_RAPIDS_TPU_PIPELINE`` on,
resident dispatch enqueues and the blocking points
(``table_download_wire`` / ``table_num_rows``) return results
BYTE-IDENTICAL to the synchronous path at bucket-edge row counts
(1023/1024/1025) — from single callers, from multi-threaded producers
at depths {1, 2, 8}, and through the one-call ``table_stream_wire``
driver. Worker failures replay synchronously and surface the
originating op's own error; ``=off`` is byte-identical to today's sync
path; donation consumes the input id, reports ``hbm.donated_bytes``
and changes nothing downloaded; unknown/double-freed table ids raise
the labeled KeyError naming the id and live count.
"""

import json
import threading

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.utils import config, metrics

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)
STR = int(dt.TypeId.STRING)

BOUNDARY_SIZES = (1023, 1024, 1025)

CHAIN = [
    {"op": "filter", "mask": 2},
    {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
    {"op": "sort_by", "keys": [{"column": 0}]},
]


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    pipeline.drain()
    config.clear_flag("PIPELINE")
    config.clear_flag("BUCKETS")
    config.clear_flag("METRICS")
    pipeline.depth()  # flag now off: tears the worker pool down


def _string_wire(strings):
    payload = b"".join(s.encode() for s in strings)
    offs = np.zeros(len(strings) + 1, np.int32)
    np.cumsum([len(s.encode()) for s in strings], out=offs[1:])
    return offs.tobytes() + payload


def _batch(n: int):
    """One wire batch: int64 key, int64 value (with nulls), BOOL8 mask,
    ragged STRING payload."""
    rng = np.random.default_rng(n)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % 5 != 0).astype(np.uint8)
    m = (v > 0).astype(np.uint8)
    strs = [("s" * (int(x) % 3 + 1)) for x in k]
    return (
        [I64, I64, B8, STR], [0, 0, 0, 0],
        [k.tobytes(), v.tobytes(), m.tobytes(), _string_wire(strs)],
        [None, valid.tobytes(), None, None], n,
    )


def _sync_want(n):
    config.set_flag("PIPELINE", "off")
    b = _batch(n)
    want = rb.table_plan_wire(json.dumps(CHAIN), *b)
    config.clear_flag("PIPELINE")
    return b, want


def _resident_chain(b, donate=False):
    cur = rb.table_upload_wire(*b)
    for op in CHAIN:
        nxt = rb.table_op_resident(json.dumps(op), [cur], donate=donate)
        if not donate:
            rb.table_free(cur)
        cur = nxt
    out = rb.table_download_wire(cur)
    rb.table_free(cur)
    return out


class TestDepthSpec:
    def test_off_values(self):
        for v in ("", "off", "none", "0", "false"):
            config.set_flag("PIPELINE", v)
            assert not pipeline.enabled(), v

    def test_depths(self):
        config.set_flag("PIPELINE", "3")
        assert pipeline.depth() == 3
        config.set_flag("PIPELINE", "on")
        assert pipeline.depth() == pipeline.DEFAULT_DEPTH

    def test_invalid_spec_fails_loudly(self):
        config.set_flag("PIPELINE", "fast")
        with pytest.raises(ValueError, match="PIPELINE"):
            pipeline.depth()
        config.set_flag("PIPELINE", "-2")
        with pytest.raises(ValueError, match="0..64"):
            pipeline.depth()
        config.set_flag("PIPELINE", str(pipeline.MAX_DEPTH + 1))
        with pytest.raises(ValueError, match="0..64"):
            pipeline.depth()  # silently clamping would mislabel runs

    def test_pool_tears_down_when_flag_goes_off(self):
        import sys as _sys
        import time as _time

        before = _sys.getswitchinterval()
        b, want = _sync_want(1023)
        config.set_flag("PIPELINE", "2")
        assert rb.table_stream_wire(json.dumps(CHAIN), [b]) == [want]
        assert any(
            t.name.startswith("srt-pipeline") for t in threading.enumerate()
        )
        pipeline.drain()
        config.set_flag("PIPELINE", "off")
        pipeline.depth()  # observes the flag change -> shutdown
        assert _sys.getswitchinterval() == before  # interval restored
        deadline = _time.time() + 10
        while _time.time() < deadline and any(
            t.name.startswith("srt-pipeline") for t in threading.enumerate()
        ):
            _time.sleep(0.02)
        assert not any(
            t.name.startswith("srt-pipeline") for t in threading.enumerate()
        ), "worker threads survived PIPELINE=off"


class TestParity:
    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_async_resident_chain_matches_sync(self, n):
        b, want = _sync_want(n)
        config.set_flag("PIPELINE", "off")
        sync_out = _resident_chain(b)
        assert sync_out == want
        config.set_flag("PIPELINE", "2")
        assert _resident_chain(b) == want

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_stream_matches_sync_and_off(self, n):
        b, want = _sync_want(n)
        pj = json.dumps(CHAIN)
        config.set_flag("PIPELINE", "off")
        off = rb.table_stream_wire(pj, [b, b])
        assert off == [want, want]  # =off IS today's sync path
        config.set_flag("PIPELINE", "2")
        on = rb.table_stream_wire(pj, [b] * 5)
        assert on == [want] * 5  # ordered completion, byte parity

    def test_blocking_points_resolve_pending(self):
        b, want = _sync_want(1024)
        config.set_flag("PIPELINE", "1")
        tid = rb.table_upload_wire(*b)
        out = rb.table_plan_resident(json.dumps(CHAIN), [tid])
        assert rb.table_num_rows(out) == want[4]
        assert rb.table_download_wire(out) == want
        rb.table_free(tid)
        rb.table_free(out)


class TestConcurrentProducers:
    @pytest.mark.parametrize("depth", (1, 2, 8))
    def test_threaded_chains_byte_parity(self, depth):
        # one sync oracle per boundary size, then N producer threads
        # each driving its own chain through the shared pipeline
        oracle = {n: _sync_want(n) for n in BOUNDARY_SIZES}
        config.set_flag("PIPELINE", str(depth))
        live_before = rb.resident_table_count()
        errors = []

        def producer(tid_):
            try:
                for rep in range(2):
                    n = BOUNDARY_SIZES[(tid_ + rep) % len(BOUNDARY_SIZES)]
                    b, want = oracle[n]
                    got = _resident_chain(b)
                    if got != want:
                        errors.append((tid_, n, "parity mismatch"))
            except Exception as e:  # pragma: no cover - failure detail
                errors.append((tid_, repr(e)))

        threads = [
            threading.Thread(target=producer, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "producer hung"
        assert errors == []
        pipeline.drain()
        assert rb.resident_table_count() == live_before  # no leaks


class TestWorkerFailureReplay:
    def test_transient_worker_failure_replays_sync(self, monkeypatch):
        # fail ONLY on pipeline worker threads: the sync replay on the
        # resolving thread then succeeds — pipelining healed a flake
        # without changing results
        b, want = _sync_want(1024)
        real = rb._dispatch

        def flaky(op, table, rest=()):
            if threading.current_thread().name.startswith("srt-pipeline"):
                raise RuntimeError("injected worker failure")
            return real(op, table, rest)

        monkeypatch.setattr(rb, "_dispatch", flaky)
        config.set_flag("METRICS", True)
        config.set_flag("PIPELINE", "2")
        metrics.reset()
        got = _resident_chain(b)
        assert got == want
        c = metrics.snapshot()["counters"]
        assert c.get("pipeline.replays", 0) >= 1

    def test_genuine_op_error_surfaces_at_blocking_point(self):
        # a broken op enqueues fine; the blocking point replays it
        # synchronously and raises the op's OWN error (same type and
        # message as the sync path)
        b, _ = _sync_want(1024)
        config.set_flag("PIPELINE", "2")
        tid = rb.table_upload_wire(*b)
        out = rb.table_op_resident(json.dumps({"op": "explode_wrong"}),
                                   [tid])
        with pytest.raises(ValueError, match="unknown table op"):
            rb.table_download_wire(out)
        # the terminal error is sticky: a second blocking point raises
        # it again instead of replaying twice
        with pytest.raises(ValueError, match="unknown table op"):
            rb.table_num_rows(out)
        rb.table_free(tid)
        rb.table_free(out)  # freeing the failed handle must not raise

    def test_unknown_input_id_raises_synchronously(self):
        config.set_flag("PIPELINE", "2")
        with pytest.raises(KeyError, match="999999"):
            rb.table_op_resident(json.dumps(CHAIN[0]), [999999])


class TestDonation:
    def test_donated_plan_chain_same_bytes_nonzero_donation(self):
        b, want = _sync_want(1025)
        config.set_flag("METRICS", True)
        metrics.reset()
        # table_plan_wire consumes its upload by construction: the
        # fused chain donates, the downloaded bytes must not change
        got = rb.table_plan_wire(json.dumps(CHAIN), *b)
        assert got == want
        snap = metrics.snapshot()
        assert snap["bytes"].get("hbm.donated_bytes", 0) > 0
        assert snap["counters"].get("hbm.donations", 0) >= 1

    def test_donate_consumes_resident_input_id(self):
        b, want = _sync_want(1024)
        config.set_flag("PIPELINE", "off")
        tid = rb.table_upload_wire(*b)
        out = rb.table_op_resident(
            json.dumps(CHAIN[0]), [tid], donate=True
        )
        # the input id was consumed at op time — the labeled KeyError
        # names the id and the live count
        with pytest.raises(KeyError, match=rf"{tid}.*\d+ table\(s\) live"):
            rb.table_download_wire(tid)
        got = rb.table_download_wire(out)
        rb.table_free(out)
        config.set_flag("PIPELINE", "2")
        tid2 = rb.table_upload_wire(*b)
        out2 = rb.table_op_resident(
            json.dumps(CHAIN[0]), [tid2], donate=True
        )
        assert rb.table_download_wire(out2) == got
        rb.table_free(out2)


class TestDonationSafety:
    def test_aliasing_boundary_segment_never_donates_caller_buffers(self):
        # a single-table concat is an identity-aliasing exact boundary
        # (jnp.concatenate([x]) returns x's buffer): the fused segment
        # after it must NOT donate buffers the caller still owns —
        # 1024 rows == the bucket, so no pad copy breaks the alias
        from spark_rapids_jni_tpu import plan as plan_mod
        from spark_rapids_jni_tpu.column import Column, Table

        n = 1024
        rng = np.random.default_rng(3)
        k = rng.integers(0, 9, n, dtype=np.int64)
        v = rng.integers(-50, 50, n, dtype=np.int64)
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"]
        )
        plan = [
            {"op": "concat"},
            {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
            {"op": "cast", "column": 0, "type_id": int(dt.TypeId.INT32)},
        ]
        out = plan_mod.run_plan(plan, t, donate_input=False)
        assert int(out.logical_row_count) == n
        # the caller's buffers must still be alive and byte-identical
        assert not t.columns[0].data.is_deleted()
        assert np.asarray(t.columns[0].data).tobytes() == k.tobytes()
        assert np.asarray(t.columns[1].data).tobytes() == v.tobytes()

    def test_bad_rest_id_leaves_donated_input_intact(self):
        # the labeled KeyError for a bad rest id must fire BEFORE the
        # donated input is consumed: the call fails, the input survives
        b, _ = _sync_want(1024)
        tid = rb.table_upload_wire(*b)
        with pytest.raises(KeyError, match="31337"):
            rb.table_op_resident(
                json.dumps({"op": "join", "on": [0]}), [tid, 31337],
                donate=True,
            )
        assert rb.table_num_rows(tid) == 1024  # still alive
        rb.table_free(tid)

    def test_donate_waits_for_inflight_readers_of_same_id(self, monkeypatch):
        # op1 reads A (slowed down on the worker); op2 donate-consumes
        # A right after: the donate barrier must keep A's buffers alive
        # until op1's dispatch is done — without it, op2's executable
        # deletes them mid-read and op1 dies with a deleted-array error
        # the synchronous ordering can never produce
        import time as _time

        sort_op = {"op": "sort_by", "keys": [{"column": 0}]}
        b, _ = _sync_want(1024)  # 1024 == the bucket: no pad copy
        config.set_flag("PIPELINE", "off")
        a0 = rb.table_upload_wire(*b)
        w1 = rb.table_op_resident(json.dumps(sort_op), [a0])
        want1 = rb.table_download_wire(w1)
        w2 = rb.table_op_resident(json.dumps(CHAIN[0]), [a0], donate=True)
        want2 = rb.table_download_wire(w2)
        for t in (w1, w2):
            rb.table_free(t)

        real = rb._dispatch

        def slow(op, table, rest=()):
            if (
                threading.current_thread().name.startswith("srt-pipeline")
                and op.get("op") == "sort_by"
            ):
                _time.sleep(0.3)
            return real(op, table, rest)

        monkeypatch.setattr(rb, "_dispatch", slow)
        config.set_flag("PIPELINE", "2")
        A = rb.table_upload_wire(*b)
        r1 = rb.table_op_resident(json.dumps(sort_op), [A])
        r2 = rb.table_op_resident(json.dumps(CHAIN[0]), [A], donate=True)
        assert rb.table_download_wire(r1) == want1  # reader unharmed
        assert rb.table_download_wire(r2) == want2
        for t in (r1, r2):
            rb.table_free(t)

    def test_donated_async_failure_surfaces_op_error(self, monkeypatch):
        # non-replayable donated work: the worker's own (genuine) op
        # error is what the blocking point raises — no deleted-buffer
        # error from a doomed replay. The fault must be injected
        # mid-flight: a statically-bad plan never reaches the worker —
        # plancheck rejects it at submit and the donated input survives
        from spark_rapids_jni_tpu import plan as plan_mod

        b, _ = _sync_want(1024)
        config.set_flag("PIPELINE", "2")
        tid = rb.table_upload_wire(*b)
        plan = [
            {"op": "filter", "mask": 2},
            {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
        ]
        with pytest.raises(ValueError, match="plancheck: op\\[2\\]"):
            rb.table_plan_resident(
                json.dumps(plan + [{"op": "nope_not_an_op"}]), [tid],
                donate=True,
            )
        assert rb.table_num_rows(tid) == 1024  # static reject kept it

        real = plan_mod.run_plan

        def boom(ops, table, rest=(), **kw):
            if threading.current_thread().name.startswith("srt-pipeline"):
                raise ValueError("unknown table op (injected mid-flight)")
            return real(ops, table, rest, **kw)

        monkeypatch.setattr(plan_mod, "run_plan", boom)
        out = rb.table_plan_resident(json.dumps(plan), [tid], donate=True)
        with pytest.raises(ValueError, match="unknown table op"):
            rb.table_download_wire(out)
        rb.table_free(out)


class TestLabeledKeyErrors:
    def test_unknown_and_double_free(self):
        b, _ = _sync_want(1023)
        tid = rb.table_upload_wire(*b)
        live = rb.resident_table_count()
        with pytest.raises(
            KeyError, match=rf"424242.*{live} table\(s\) live"
        ):
            rb.table_download_wire(424242)
        rb.table_free(tid)
        with pytest.raises(KeyError, match=str(tid)):
            rb.table_free(tid)  # double free names the freed id
        with pytest.raises(KeyError, match="unknown or already-freed"):
            rb.table_num_rows(tid)


class TestStageSpansOnWorkerTids:
    def test_worker_stages_record_on_worker_threads(self):
        # the Chrome-trace overlap story: decode/encode stage spans
        # must land on pipeline worker tids, not the caller's
        from spark_rapids_jni_tpu.utils import flight

        b, want = _sync_want(1024)
        config.set_flag("METRICS", True)
        config.set_flag("FLIGHT", "on")
        config.set_flag("PIPELINE", "2")
        got = rb.table_stream_wire(json.dumps(CHAIN), [b] * 4)
        assert got == [want] * 4
        pipeline.drain()
        evs = flight.tail_records()
        stage_tids = {
            e["tid"] for e in evs
            if e["ph"] == "B"
            and e["name"].split("/")[-1] in ("pipeline.decode",
                                             "pipeline.encode")
        }
        assert stage_tids, "no stage spans recorded"
        assert threading.get_ident() not in stage_tids
        config.clear_flag("FLIGHT")
