"""Trace-context plane (ISSUE 18 tentpole): per-request identity.

The contract under test: ``new_context`` is THE id mint (32-hex trace
id + 16-hex hop span id, W3C-traceparent wire header); ``activate``
binds the ambient context with exception-safe restore; malformed peer
headers degrade to None, never to a failed request; scheduler tickets
and pipeline pendings capture the submitter's context at submit time
and re-activate it on the worker — and a lineage replay (pipeline sync
replay, the mesh degradation ladder) stays in the ORIGINAL request's
trace, never minting a fresh id. Instants emitted by code that never
heard of tracing (``mesh.replay``, ``mesh.degraded``,
``shuffle.giveup``) are attributed to the enclosing trace-tagged span
by ``assign_trace_ids``. The tail-sampled slow-request log keeps span
detail only for SLO breaches and typed errors, bounded to TRACE_TOPK.
Acceptance: the disabled ``span_begin``/``span_end`` pair stays within
2x of one disabled ``flight.record()`` call.
"""

import threading
import time

import jax
import pytest

from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import parallel
from spark_rapids_jni_tpu.serving import scheduler as sched_mod
from spark_rapids_jni_tpu.serving import session as session_mod
from spark_rapids_jni_tpu.utils import config, faults, flight, metrics
from spark_rapids_jni_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _trace_isolated(monkeypatch):
    for env in ("SPARK_RAPIDS_TPU_FLIGHT", "SPARK_RAPIDS_TPU_FLIGHT_DUMP",
                "SPARK_RAPIDS_TPU_METRICS", "SPARK_RAPIDS_TPU_TRACE"):
        monkeypatch.delenv(env, raising=False)
    flight.reset()
    metrics.reset()
    tracing.reset_requests()
    yield
    pipeline.drain()
    for f in ("FLIGHT", "FLIGHT_DUMP", "METRICS", "TRACE",
              "TRACE_SLO_MS", "TRACE_TOPK", "PIPELINE", "FAULTS",
              "RETRY_MAX"):
        config.clear_flag(f)
    pipeline.depth()  # PIPELINE now off: tears the worker pool down
    flight.reset()
    metrics.reset()
    tracing.reset_requests()


# ---------------------------------------------------------------------------
# context identity + the ambient binding
# ---------------------------------------------------------------------------


class TestContext:
    def test_mint_shapes(self):
        ctx = tracing.new_context()
        assert len(ctx.trace_id) == 32
        assert len(ctx.span_id) == 16
        int(ctx.trace_id, 16)
        int(ctx.span_id, 16)
        assert ctx.header == f"00-{ctx.trace_id}-{ctx.span_id}-01"

    def test_mints_are_distinct(self):
        ids = {tracing.new_context().trace_id for _ in range(32)}
        assert len(ids) == 32

    def test_child_keeps_trace_changes_span(self):
        parent = tracing.new_context()
        child = tracing.child_context(parent)
        assert child.trace_id == parent.trace_id
        assert child.span_id != parent.span_id

    def test_ambient_activate_restores(self):
        assert tracing.current() is None
        ctx = tracing.new_context()
        with tracing.activate(ctx):
            assert tracing.current() is ctx
            assert tracing.current_traceparent() == ctx.header
            assert tracing.current_trace_id() == ctx.trace_id
            inner = tracing.new_context()
            with tracing.activate(inner):
                assert tracing.current() is inner
            assert tracing.current() is ctx
        assert tracing.current() is None
        assert tracing.current_traceparent() is None
        assert tracing.current_trace_id() is None

    def test_activate_none_is_noop(self):
        ctx = tracing.new_context()
        with tracing.activate(ctx):
            with tracing.activate(None):
                assert tracing.current() is ctx

    def test_activate_restores_on_exception(self):
        ctx = tracing.new_context()
        with pytest.raises(RuntimeError):
            with tracing.activate(ctx):
                raise RuntimeError("boom")
        assert tracing.current() is None


class TestTraceparentWire:
    def test_roundtrip(self):
        ctx = tracing.new_context()
        back = tracing.parse_traceparent(tracing.format_traceparent(ctx))
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    def test_case_and_whitespace_tolerated(self):
        ctx = tracing.new_context()
        back = tracing.parse_traceparent("  " + ctx.header.upper() + " ")
        assert back is not None and back.trace_id == ctx.trace_id

    def test_future_version_accepted(self):
        ctx = tracing.new_context()
        assert tracing.parse_traceparent("01" + ctx.header[2:]) is not None

    @pytest.mark.parametrize("bad", [
        None,
        42,
        "",
        "garbage",
        "00-abc-def-01",                                  # wrong widths
        "00-" + "g" * 32 + "-" + "1" * 16 + "-01",        # non-hex
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",        # zero trace
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",        # zero span
        "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",        # reserved ver
        "00-" + "1" * 32 + "-" + "2" * 16,                # missing flags
    ])
    def test_malformed_degrades_to_none(self, bad):
        assert tracing.parse_traceparent(bad) is None


class TestEnsureContext:
    def test_valid_header_joins_trace(self):
        peer = tracing.new_context()
        ctx = tracing.ensure_context(peer.header)
        assert ctx is not None
        assert ctx.trace_id == peer.trace_id
        assert ctx.span_id != peer.span_id  # fresh hop

    def test_disabled_plane_no_header_yields_none(self):
        assert not tracing.context_enabled()
        assert tracing.ensure_context(None) is None

    def test_trace_flag_mints(self):
        config.set_flag("TRACE", True)
        assert tracing.context_enabled()
        ctx = tracing.ensure_context(None)
        assert ctx is not None and len(ctx.trace_id) == 32

    def test_flight_ring_enables_plane(self):
        config.set_flag("FLIGHT", True)
        assert tracing.context_enabled()
        assert tracing.ensure_context(None) is not None

    def test_malformed_header_mints_fresh(self):
        config.set_flag("TRACE", True)
        ctx = tracing.ensure_context("00-zzz-bad-01")
        assert ctx is not None and len(ctx.trace_id) == 32

    def test_gate_follows_config_generation(self):
        assert not tracing.context_enabled()
        config.set_flag("TRACE", True)
        assert tracing.context_enabled()
        config.clear_flag("TRACE")
        assert not tracing.context_enabled()


# ---------------------------------------------------------------------------
# span records on the flight ring + post-hoc trace attribution
# ---------------------------------------------------------------------------


class TestSpans:
    def test_disabled_ring_yields_none_token(self):
        tok = tracing.span_begin("plan.segment")
        assert tok is None
        tracing.span_end(tok)  # no-op, no crash
        assert flight.tail_records() == []

    def test_span_carries_traceparent_as_b_arg(self):
        config.set_flag("FLIGHT", True)
        ctx = tracing.new_context()
        with tracing.activate(ctx):
            tok = tracing.span_begin("plan.segment")
            tracing.span_end(tok)
        evs = flight.tail_records()
        begins = [e for e in evs if e["ph"] == "B"]
        ends = [e for e in evs if e["ph"] == "E"]
        assert begins and begins[0]["arg"] == ctx.header
        assert ends and ends[0]["name"] == "plan.segment"

    def test_span_without_ambient_context_untagged(self):
        config.set_flag("FLIGHT", True)
        tok = tracing.span_begin("plan.segment")
        tracing.span_end(tok)
        begins = [e for e in flight.tail_records() if e["ph"] == "B"]
        assert begins and begins[0].get("arg") is None

    def test_span_end_error_rides_e_arg(self):
        config.set_flag("FLIGHT", True)
        tok = tracing.span_begin("mesh.stage")
        tracing.span_end(tok, error="Degraded")
        ends = [e for e in flight.tail_records() if e["ph"] == "E"]
        assert ends and ends[0]["arg"] == "Degraded"

    def test_assign_trace_ids_scope_inheritance(self):
        config.set_flag("FLIGHT", True)
        flight.record("I", "before.scope")  # outside: stays untagged
        ctx = tracing.new_context()
        with tracing.activate(ctx):
            tok = tracing.span_begin("serving.stream")
            flight.record("I", "mesh.replay", "stage-0")
            inner = tracing.span_begin("plan.segment")
            flight.record("I", "compile_cache.miss", "k")
            tracing.span_end(inner)
            tracing.span_end(tok)
        flight.record("I", "after.scope")
        tagged = tracing.assign_trace_ids(flight.tail_records())
        by_name = {e["name"]: e for e in tagged if e["ph"] == "I"}
        assert by_name["mesh.replay"]["trace_id"] == ctx.trace_id
        assert by_name["compile_cache.miss"]["trace_id"] == ctx.trace_id
        assert "trace_id" not in by_name["before.scope"]
        assert "trace_id" not in by_name["after.scope"]

    def test_assign_trace_ids_per_tid_isolation(self):
        # synthetic events: two threads, one traced, one not — the
        # per-tid stack walk must not leak the scope across tids
        ctx = tracing.new_context()
        events = [
            {"seq": 0, "t_ns": 10, "tid": 1, "ph": "B",
             "name": "serving.stream", "arg": ctx.header},
            {"seq": 1, "t_ns": 20, "tid": 2, "ph": "I",
             "name": "other.thread", "arg": None},
            {"seq": 2, "t_ns": 30, "tid": 1, "ph": "I",
             "name": "mesh.replay", "arg": "s"},
            {"seq": 3, "t_ns": 40, "tid": 1, "ph": "E",
             "name": "serving.stream", "arg": None},
            {"seq": 4, "t_ns": 50, "tid": 1, "ph": "I",
             "name": "after", "arg": None},
            "not-a-dict",  # older/partial dumps pass through the walk
        ]
        tagged = {
            e["name"]: e for e in tracing.assign_trace_ids(events)
        }
        assert tagged["mesh.replay"]["trace_id"] == ctx.trace_id
        assert tagged["serving.stream"]["trace_id"] == ctx.trace_id
        assert "trace_id" not in tagged["other.thread"]
        assert "trace_id" not in tagged["after"]

    def test_trace_span_records_shapes(self):
        ctx = tracing.new_context()
        events = [
            {"seq": 0, "t_ns": 1_000_000, "tid": 1, "ph": "B",
             "name": "serving.stream", "arg": ctx.header},
            {"seq": 1, "t_ns": 1_500_000, "tid": 1, "ph": "I",
             "name": "mesh.degraded", "arg": "stage:4"},
            {"seq": 2, "t_ns": 3_000_000, "tid": 1, "ph": "E",
             "name": "serving.stream", "arg": "Degraded"},
            {"seq": 3, "t_ns": 4_000_000, "tid": 1, "ph": "B",
             "name": "wire.upload", "arg": ctx.header},
            # no E for wire.upload: the kill-mid-stage case
        ]
        recs = tracing.trace_span_records(events, ctx.trace_id)
        by_name = {r["name"]: r for r in recs}
        stream = by_name["serving.stream"]
        assert stream["dur_ms"] == 2.0
        assert stream["error"] == "Degraded"
        inst = by_name["mesh.degraded"]
        assert inst["instant"] is True and inst["arg"] == "stage:4"
        assert by_name["wire.upload"]["unterminated"] is True
        # a foreign trace id matches nothing
        assert tracing.trace_span_records(events, "f" * 32) == []


# ---------------------------------------------------------------------------
# tail-sampled slow-request log (the `trace` command's data)
# ---------------------------------------------------------------------------


class TestSlowRequestLog:
    def test_disabled_plane_drops(self):
        tracing.note_request("serving.stream", 999.0)
        assert tracing.slow_requests() == []

    def test_below_slo_keeps_record_drops_span_detail(self):
        config.set_flag("TRACE", True)
        evaluated = []

        def spans():
            evaluated.append(1)
            return [{"name": "x"}]

        tracing.note_request("serving.stream", 1.0, trace_id="a" * 32,
                             session="tenant", spans=spans)
        recs = tracing.slow_requests()
        assert len(recs) == 1
        assert recs[0]["label"] == "serving.stream"
        assert recs[0]["trace_id"] == "a" * 32
        assert recs[0]["session"] == "tenant"
        assert "spans" not in recs[0]
        assert not evaluated  # tail sampling: the callable never ran

    def test_slo_breach_samples_span_detail(self):
        config.set_flag("TRACE", True)
        config.set_flag("TRACE_SLO_MS", "5")
        tracing.note_request(
            "serving.stream", 6.0,
            spans=lambda: [{"name": "mesh.stage", "dur_ms": 5.5}],
        )
        recs = tracing.slow_requests()
        assert recs[0]["spans"] == [{"name": "mesh.stage", "dur_ms": 5.5}]

    def test_typed_error_samples_below_slo(self):
        config.set_flag("TRACE", True)
        tracing.note_request(
            "serving.stream", 0.5, error="Degraded",
            spans=lambda: [{"name": "mesh.stage"}],
        )
        recs = tracing.slow_requests()
        assert recs[0]["error"] == "Degraded"
        assert recs[0]["spans"] == [{"name": "mesh.stage"}]

    def test_topk_bound_keeps_slowest_first(self):
        config.set_flag("TRACE", True)
        config.set_flag("TRACE_TOPK", "4")
        for ms in (7.0, 3.0, 9.0, 1.0, 5.0, 8.0, 2.0, 6.0):
            tracing.note_request("serving.stream", ms)
        recs = tracing.slow_requests()
        assert [r["ms"] for r in recs] == [9.0, 8.0, 7.0, 6.0]

    def test_reset_drops_log(self):
        config.set_flag("TRACE", True)
        tracing.note_request("serving.stream", 1.0)
        assert tracing.slow_requests()
        tracing.reset_requests()
        assert tracing.slow_requests() == []


class TestPrometheusText:
    def test_renders_registry_families(self):
        config.set_flag("METRICS", True)
        metrics.counter_add("shuffle.retries", 3)
        metrics.gauge_set("mesh.devices", 4)
        metrics.hist_observe("serving.queue_wait_ms", 1.5,
                             bounds=metrics.SPAN_MS_BOUNDS)
        text = metrics.prometheus_text()
        assert "# TYPE srt_shuffle_retries_total counter" in text
        assert "srt_shuffle_retries_total 3" in text
        assert "# TYPE srt_mesh_devices gauge" in text
        assert 'srt_serving_queue_wait_ms_bucket{le="' in text

    def test_explicit_snapshot_renders_without_flag(self):
        snap = {"counters": {"plan.mesh_fallbacks": 2}}
        text = metrics.prometheus_text(snap)
        assert "srt_plan_mesh_fallbacks_total 2" in text

    def test_empty_snapshot_empty_exposition(self):
        # METRICS off: the snapshot is empty and so is the exposition —
        # the serving `trace` smoke sets METRICS=1 for exactly this
        assert metrics.prometheus_text({}) == ""


# ---------------------------------------------------------------------------
# propagation across thread hops: scheduler tickets, pipeline pendings
# ---------------------------------------------------------------------------


class TestSchedulerPropagation:
    def test_ticket_captures_and_worker_reactivates(self):
        config.set_flag("FLIGHT", True)
        sched = sched_mod.FairScheduler(workers=1).start()
        sess = session_mod.Session("s", "tenant", 1.0, 1 << 40)
        sched.register(sess)
        try:
            ctx = tracing.new_context()
            with tracing.activate(ctx):
                t = sched.submit(
                    sess, tracing.current_trace_id, label="probe"
                )
            assert t.ctx is ctx  # captured at SUBMIT, not at run
            assert t.result() == ctx.trace_id  # worker re-activated it
            bare = sched.submit(
                sess, tracing.current_trace_id, label="probe"
            )
            assert bare.ctx is None and bare.result() is None
        finally:
            sched.unregister(sess)
            sched.stop()
        # the retroactive queue-wait span rides the request's trace
        waits = [
            e for e in flight.tail_records()
            if e["ph"] == "B" and e["name"] == "serving.queue_wait"
        ]
        assert any(e["arg"] == ctx.header for e in waits), waits


class TestPipelinePropagation:
    def test_pending_captures_and_worker_reactivates(self):
        config.set_flag("PIPELINE", "2")
        assert pipeline.enabled()
        ctx = tracing.new_context()
        with tracing.activate(ctx):
            p = pipeline.submit(tracing.current_trace_id, "probe")
        assert p.ctx is ctx
        assert p.resolve() == ctx.trace_id

    def test_sync_replay_keeps_original_trace(self):
        # the worker run fails; the sync replay runs on a thread with
        # NO ambient context — it must re-activate the captured one,
        # never mint a fresh trace
        config.set_flag("PIPELINE", "2")
        calls = []

        def work():
            calls.append(tracing.current_trace_id())
            if len(calls) == 1:
                raise faults.TransientDeviceError("UNAVAILABLE: flake")
            return tracing.current_trace_id()

        ctx = tracing.new_context()
        with tracing.activate(ctx):
            p = pipeline.submit(work, "probe")
        assert tracing.current() is None
        assert p.resolve() == ctx.trace_id
        assert calls == [ctx.trace_id, ctx.trace_id]


# ---------------------------------------------------------------------------
# chaos attribution (satellite): replay/degradation instants keep the
# ORIGINAL request's trace id — a replay never mints a fresh trace
# ---------------------------------------------------------------------------


def _trace_of(events, name):
    tagged = [
        e for e in tracing.assign_trace_ids(events)
        if e.get("name") == name and e.get("ph") == "I"
    ]
    assert tagged, f"no {name!r} instant on the ring"
    return {e.get("trace_id") for e in tagged}


class TestChaosTraceAttribution:
    def test_shuffle_giveup_donated_inherits_trace(self):
        config.set_flag("FLIGHT", True)

        def launch():
            raise faults.TransientDeviceError("UNAVAILABLE: mid-donate")

        ctx = tracing.new_context()
        with tracing.activate(ctx):
            with pytest.raises(faults.TransientDeviceError):
                parallel.run_collective(
                    "shuffle.all_to_all", launch, donated=True
                )
        evs = flight.tail_records()
        assert _trace_of(evs, "shuffle.giveup") == {ctx.trace_id}
        # the exchange span itself closed with the error class
        ends = [e for e in evs if e["ph"] == "E"
                and e["name"] == "shuffle.all_to_all"]
        assert ends and ends[0]["arg"] == "TransientDeviceError"

    def test_shuffle_giveup_exhausted_inherits_trace(self):
        config.set_flag("FLIGHT", True)
        config.set_flag("RETRY_BASE_MS", "0")

        def launch():
            raise faults.TransientDeviceError("UNAVAILABLE: persistent")

        ctx = tracing.new_context()
        with tracing.activate(ctx):
            with pytest.raises(faults.TransientDeviceError):
                parallel.run_collective(
                    "shuffle.exchange", launch, max_retries=1
                )
        assert _trace_of(
            flight.tail_records(), "shuffle.giveup"
        ) == {ctx.trace_id}

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
    )
    def test_mesh_ladder_instants_inherit_trace(self):
        config.set_flag("FLIGHT", True)
        config.set_flag("RETRY_MAX", "0")
        runner = parallel.MeshRunner(8)

        def stage(mesh):
            if int(mesh.shape["shuffle"]) > 2:
                raise faults.TransientDeviceError("UNAVAILABLE: slice")
            return "ok"

        ctx = tracing.new_context()
        with tracing.activate(ctx):
            assert runner.run_stage("chaos.stage", stage) == "ok"
        evs = flight.tail_records()
        # 8 -> 4 -> 2: two replays, two degradations, ONE trace
        assert _trace_of(evs, "mesh.replay") == {ctx.trace_id}
        assert _trace_of(evs, "mesh.degraded") == {ctx.trace_id}
        tagged = tracing.assign_trace_ids(evs)
        ids = {e["trace_id"] for e in tagged if "trace_id" in e}
        assert ids == {ctx.trace_id}, ids  # the ladder minted nothing
        stages = [e for e in tagged if e.get("name") == "mesh.stage"
                  and e.get("ph") == "B"]
        assert stages and stages[0]["arg"] == ctx.header


# ---------------------------------------------------------------------------
# acceptance: the disabled span pair stays in record()'s cost class
# ---------------------------------------------------------------------------


class TestOverhead:
    def test_disabled_span_pair_within_2x_of_disabled_record(self):
        assert not flight.enabled()
        iters = 100_000

        def best_of(fn, reps=5):
            fn()  # warm the cached gate
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(iters):
                    fn()
                best = min(best, time.perf_counter() - t0)
            return best / iters

        record_s = best_of(lambda: flight.record("I", "overhead.probe"))

        def pair():
            tracing.span_end(tracing.span_begin("overhead.probe"))

        pair_s = best_of(pair)
        assert pair_s <= 2.0 * record_s + 200e-9, (
            f"disabled span_begin/span_end pair costs {pair_s * 1e9:.0f}"
            f"ns/op vs {record_s * 1e9:.0f}ns/op for disabled "
            "flight.record() — the trace layer broke the disabled-path "
            "cost class (<= 2x record + 200ns slack)"
        )
