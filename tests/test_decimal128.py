"""DECIMAL128 device columns (round-3 VERDICT item 6): two-u64-limb
representation with order keys, binaryop, row format, wire, sort and
groupby — oracle-tested with Python ints across scales -38..0.

Reference surface: decimal128 round-trips in the vendored cudf Java
tests (spark-rapids-cudf/pom.xml:207-217); the (typeId=27, scale) wire
convention of RowConversionJni.cpp:56-61.
"""

import json

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops, rows
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import int128
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg


def _rand_ints(rng, n, bits=100):
    """Random signed ints spanning well past 64 bits."""
    lo = rng.integers(0, 2**63, n, dtype=np.uint64).astype(object)
    hi = rng.integers(0, 2 ** (bits - 63), n).astype(object)
    sign = rng.choice([-1, 1], n).astype(object)
    return [int(s * ((h << 63) | l)) for s, h, l in zip(sign, hi, lo)]


class TestLimbs:
    def test_py_round_trip(self, rng):
        vals = _rand_ints(rng, 50) + [0, 1, -1, 2**127 - 1, -(2**127)]
        limbs = int128.from_py_ints(vals)
        assert int128.to_py_ints(limbs) == vals

    def test_add_sub_oracle(self, rng):
        import jax.numpy as jnp

        a = _rand_ints(rng, 64, bits=120)
        b = _rand_ints(rng, 64, bits=120)
        la = jnp.asarray(int128.from_py_ints(a))
        lb = jnp.asarray(int128.from_py_ints(b))
        slo, shi = int128.add(la[:, 0], la[:, 1], lb[:, 0], lb[:, 1])
        got = int128.to_py_ints(np.stack([slo, shi], axis=1))
        mod = 1 << 128
        want = [
            ((x + y + (mod >> 1)) % mod) - (mod >> 1) for x, y in zip(a, b)
        ]
        assert got == want
        dlo, dhi = int128.sub(la[:, 0], la[:, 1], lb[:, 0], lb[:, 1])
        got = int128.to_py_ints(np.stack([dlo, dhi], axis=1))
        want = [
            ((x - y + (mod >> 1)) % mod) - (mod >> 1) for x, y in zip(a, b)
        ]
        assert got == want

    def test_rescale_divide_truncates(self):
        import jax.numpy as jnp

        vals = [12345, -12345, 10**30 + 7, -(10**30 + 7)]
        limbs = jnp.asarray(int128.from_py_ints(vals))
        lo, hi = int128.rescale(limbs[:, 0], limbs[:, 1], -3, 0)
        got = int128.to_py_ints(np.stack([lo, hi], axis=1))
        # truncation toward zero, cudf fixed_point convention
        want = [12, -12, (10**30 + 7) // 1000, -((10**30 + 7) // 1000)]
        assert got == want

    def test_rescale_multiply_exact(self):
        import jax.numpy as jnp

        vals = [7, -7, 10**10]
        limbs = jnp.asarray(int128.from_py_ints(vals))
        lo, hi = int128.rescale(limbs[:, 0], limbs[:, 1], 0, -25)
        got = int128.to_py_ints(np.stack([lo, hi], axis=1))
        assert got == [v * 10**25 for v in vals]


class TestColumn:
    def test_from_to_pylist(self, rng):
        vals = _rand_ints(rng, 40) + [None, 0, None]
        col = Column.from_decimal128(vals, scale=-10)
        assert col.dtype == dt.decimal128(-10)
        assert col.to_pylist() == vals

    def test_rows_round_trip_mixed_schema(self, rng):
        """Packed-row round trip with decimal128 beside narrower types —
        the RowConversionTest shape with a 16-byte column added."""
        n = 96
        d128 = _rand_ints(rng, n)
        cols = [
            Column.from_numpy(
                rng.integers(-100, 100, n, dtype=np.int64)
            ),
            Column.from_decimal128(d128, scale=-38),
            Column.from_numpy(
                rng.integers(0, 2, n).astype(np.bool_)
            ),
        ]
        t = Table(cols, ["a", "d", "b"])
        schema = t.dtypes()
        packed = rows.to_rows(t, split=False)
        back = rows.from_rows(packed, schema)
        assert back.columns[1].to_pylist() == d128
        np.testing.assert_array_equal(
            np.asarray(back.columns[0].data), np.asarray(cols[0].data)
        )

    def test_rows_byte_exact_vs_host_codec(self, rng):
        """Device packing of a decimal128 column matches the C host codec
        (src/cpp/row_format.cpp width-16 path) byte for byte."""
        from spark_rapids_jni_tpu.utils import native

        if not native.available():
            pytest.skip("native library not built")
        n = 64
        vals = _rand_ints(rng, n)
        col = Column.from_decimal128(vals, scale=0)
        t = Table([col])
        dev = np.asarray(rows.to_rows(t, split=False)[0].data)
        limbs = int128.from_py_ints(vals)
        got = native.pack_rows(
            [int(dt.TypeId.DECIMAL128)], [limbs], [None]
        )
        assert dev.tobytes() == np.asarray(got).tobytes()


class TestOps:
    def test_sort_oracle(self, rng):
        vals = _rand_ints(rng, 200)
        col = Column.from_decimal128(vals, scale=-5)
        out = ops.sort_table(Table([col], ["d"]), ["d"])
        assert out["d"].to_pylist() == sorted(vals)

    def test_binaryop_add_sub_cmp(self, rng):
        a = _rand_ints(rng, 100, bits=110)
        b = _rand_ints(rng, 100, bits=110)
        ca = Column.from_decimal128(a, scale=-2)
        cb = Column.from_decimal128(b, scale=-2)
        got = ops.binary_op("add", ca, cb).to_pylist()
        assert got == [x + y for x, y in zip(a, b)]
        got = ops.binary_op("sub", ca, cb).to_pylist()
        assert got == [x - y for x, y in zip(a, b)]
        got = ops.binary_op("lt", ca, cb).to_pylist()
        assert got == [x < y for x, y in zip(a, b)]
        got = ops.binary_op("eq", ca, ca).to_pylist()
        assert all(got)

    def test_binaryop_mixed_scale_rescales(self):
        ca = Column.from_decimal128([5], scale=-1)   # 0.5
        cb = Column.from_decimal128([25], scale=-2)  # 0.25
        out = ops.binary_op("add", ca, cb)
        assert out.dtype.scale == -2
        assert out.to_pylist() == [75]  # 0.75 at scale -2

    def test_cast_widen_and_narrow(self):
        c64 = Column.from_numpy(
            np.asarray([123, -456], dtype=np.int64),
            dtype=dt.decimal64(-3),
        )
        wide = ops.cast(c64, dt.decimal128(-3))
        assert wide.to_pylist() == [123, -456]
        back = ops.cast(wide, dt.decimal64(-3))
        assert back.to_pylist() == [123, -456]
        f = ops.cast(wide, dt.FLOAT64)
        assert f.to_pylist() == pytest.approx([0.123, -0.456])

    @pytest.mark.parametrize("scale", [-38, -20, -5, 0])
    def test_groupby_sum_min_max_count(self, rng, scale):
        n = 400
        keys = rng.integers(0, 12, n, dtype=np.int64)
        vals = _rand_ints(rng, n, bits=90)
        t = Table(
            [
                Column.from_numpy(keys),
                Column.from_decimal128(vals, scale=scale),
            ],
            ["k", "d"],
        )
        out = ops.groupby_aggregate(
            t,
            ["k"],
            [
                GroupbyAgg("d", "sum"),
                GroupbyAgg("d", "min"),
                GroupbyAgg("d", "max"),
                GroupbyAgg("d", "count"),
            ],
        )
        got = {
            k: (s, mn, mx, c)
            for k, s, mn, mx, c in zip(
                out["k"].to_pylist(),
                out["sum_d"].to_pylist(),
                out["min_d"].to_pylist(),
                out["max_d"].to_pylist(),
                out["count_d"].to_pylist(),
            )
        }
        varr = np.array(vals, dtype=object)
        for u in np.unique(keys):
            vs = [int(x) for x in varr[keys == u]]
            assert got[int(u)] == (sum(vs), min(vs), max(vs), len(vs)), (
                f"group {u} at scale {scale}"
            )

    def test_join_on_decimal128_keys(self, rng):
        kvals = [10**25 + i for i in range(8)]
        lk = [kvals[i % 8] for i in range(24)]
        rk = [kvals[i % 4] for i in range(12)]
        left = Table(
            [
                Column.from_decimal128(lk, scale=-9),
                Column.from_numpy(np.arange(24, dtype=np.int64)),
            ],
            ["k", "lv"],
        )
        right = Table(
            [
                Column.from_decimal128(rk, scale=-9),
                Column.from_numpy(np.arange(12, dtype=np.int64)),
            ],
            ["k", "rv"],
        )
        out = ops.inner_join(left, right, ["k"])
        want = sorted(
            (k1, i, j)
            for i, k1 in enumerate(lk)
            for j, k2 in enumerate(rk)
            if k1 == k2
        )
        got = sorted(
            zip(
                out["k"].to_pylist(),
                out["lv"].to_pylist(),
                out["rv"].to_pylist(),
            )
        )
        assert got == want


class TestWire:
    def test_runtime_bridge_accepts_decimal128(self, rng):
        """The native wire path (runtime_bridge.table_op_wire) round-trips
        decimal128 columns through a device op."""
        from spark_rapids_jni_tpu import runtime_bridge

        n = 60
        vals = _rand_ints(rng, n)
        limbs = int128.from_py_ints(vals)
        keys = rng.integers(0, 5, n, dtype=np.int64)
        op = json.dumps(
            {"op": "sort_by", "keys": [{"column": 0}]}
        )
        out_ids, out_scales, out_d, out_v, out_n = (
            runtime_bridge.table_op_wire(
                op,
                [int(dt.TypeId.DECIMAL128), int(dt.TypeId.INT64)],
                [-7, 0],
                [limbs.tobytes(), keys.tobytes()],
                [None, None],
                n,
            )
        )
        assert out_n == n
        assert out_ids[0] == int(dt.TypeId.DECIMAL128)
        assert out_scales[0] == -7
        got = int128.to_py_ints(
            np.frombuffer(out_d[0], np.uint64).reshape(n, 2)
        )
        assert got == sorted(vals)


class TestArrowInterop:
    def test_arrow_decimal128_round_trip(self, rng):
        pa = pytest.importorskip("pyarrow")
        import decimal as _dec

        from spark_rapids_jni_tpu import interop

        vals = _rand_ints(rng, 40) + [None]
        scale = 10
        # localcontext(prec=...) kwargs need Python 3.11+
        with _dec.localcontext() as ctx:
            ctx.prec = 50
            py = [
                None if v is None else _dec.Decimal(v).scaleb(-scale)
                for v in vals
            ]
        arr = pa.array(py, type=pa.decimal128(38, scale))
        col = interop.column_from_arrow(arr)
        assert col.dtype == dt.decimal128(-scale)
        assert col.to_pylist() == vals
        back = interop.column_to_arrow(col)
        assert back.to_pylist() == arr.to_pylist()
