"""UTF-8 string tier (ops/strings_utf8.py, round-4 VERDICT item 9) vs
Python/PyArrow oracles on non-ASCII data."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings_utf8 as u8

CORPUS = [
    "",
    "ascii only",
    "café résumé",            # Latin-1 supplement (2-byte)
    "ΑΒΓ αβγ Ωμέγα",          # Greek
    "Привет МИР",             # Cyrillic
    "naïve ĆĘŻA łódź",        # Latin Extended-A
    "日本語テキスト",            # CJK (no case)
    "mixed Ångström π≈3.14",
    "ＦＵＬＬｗｉｄｔｈ",          # full-width forms (3-byte, cased)
    "emoji 🎉 four-byte 🚀",   # supplementary plane
    None,
]


@pytest.fixture
def col():
    return Column.from_strings(CORPUS)


def test_char_length_matches_python(col):
    got = np.asarray(u8.char_length(col).data)
    for i, s in enumerate(CORPUS):
        if s is not None:
            assert got[i] == len(s), s


def test_utf8_substring_matches_python(col):
    for start, length in [(0, 4), (2, 3), (1, None), (5, 100), (-3, None),
                          (-5, 2), (0, 0)]:
        out = u8.utf8_substring(col, start, length)
        vals = out.to_pylist()
        for s, g in zip(CORPUS, vals):
            if s is None:
                continue
            want = s[start:] if length is None else (
                s[max(len(s) + start, 0):][:length] if start < 0
                else s[start: start + length]
            )
            assert g == want, (s, start, length, g, want)


def test_case_mapping_matches_pyarrow_in_scope(col):
    """Within the documented 1:1 length-preserving scope the result
    must equal pyarrow's utf8_upper/lower exactly."""
    import pyarrow.compute as pc
    import pyarrow as pa

    src = [s for s in CORPUS if s is not None]
    c = Column.from_strings(src)
    got_up = u8.utf8_upper(c).to_pylist()
    got_lo = u8.utf8_lower(c).to_pylist()
    want_up = pc.utf8_upper(pa.array(src)).to_pylist()
    want_lo = pc.utf8_lower(pa.array(src)).to_pylist()
    assert got_up == want_up
    assert got_lo == want_lo


def test_documented_divergence_length_changing_maps():
    """ß->SS changes byte length: documented pass-through, pinned so
    the limitation is enforced-as-stated rather than silent."""
    c = Column.from_strings(["straße", "İstanbul"])
    up = u8.utf8_upper(c).to_pylist()
    assert up[0] == "STRAßE"  # ß unchanged (1:2 mapping out of scope)
    # U+0130 lowercases to i + combining dot (1:2): unchanged
    lo = u8.utf8_lower(c).to_pylist()
    assert lo[1] == "İstanbul".replace("İ", "İ")


def test_four_byte_chars_pass_through():
    c = Column.from_strings(["𝐀𝐁 plain ascii"])
    up = u8.utf8_upper(c).to_pylist()
    # mathematical bold capitals are supplementary plane: untouched;
    # the ASCII tail still uppercases
    assert up[0] == "𝐀𝐁 PLAIN ASCII"


def test_full_corpus_round_trip_bytes_stable(col):
    """lower(upper(x)) byte length never changes (the scope contract)."""
    up = u8.utf8_upper(col)
    lo = u8.utf8_lower(up)
    assert np.array_equal(
        np.asarray(col.lengths), np.asarray(lo.lengths)
    )
