"""Binary-arithmetic fuzz vs a Spark-semantics Python oracle.

Random operand pairs across the integer/float dtype lattice with
nulls and zero divisors, through add/sub/mul/div/floor_div/mod/pmod —
checked element-for-element against Spark SQL non-ANSI semantics
(int/0 -> null, float/0 -> IEEE, Java-sign mod, positive pmod)."""

import math

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops.binaryop import binary_op

_INT_T = [np.int8, np.int16, np.int32, np.int64]
_FLT_T = [np.float32, np.float64]


def _java_mod(a, b):
    r = math.fmod(a, b)
    return r


def _pmod(a, b):
    r = math.fmod(a, b)
    if r < 0:
        r = math.fmod(r + b, b)
    return r


def _oracle(op, a, b, is_float):
    if a is None or b is None:
        return None
    if op == "add":
        return a + b
    if op == "sub":
        return a - b
    if op == "mul":
        return a * b
    if op in ("div", "true_div"):
        if not is_float and b == 0:
            return None
        if is_float:
            if b == 0:
                return math.inf if a > 0 else (-math.inf if a < 0 else math.nan)
            return a / b
        # Spark IntegralDivide: truncation toward zero (Java int div)
        q = abs(a) // abs(b)
        return q if (a >= 0) == (b >= 0) else -q
    if op == "floor_div":
        if b == 0:
            if not is_float:
                return None
            return math.inf if a > 0 else (-math.inf if a < 0 else math.nan)
        return math.floor(a / b)
    if op == "mod":
        if b == 0:
            return None if not is_float else math.nan
        return _java_mod(a, b)
    if op == "pmod":
        if b == 0:
            return None if not is_float else math.nan
        return _pmod(a, b)
    raise ValueError(op)


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("op", ["add", "sub", "mul", "div",
                                "floor_div", "mod", "pmod"])
def test_int64_ops_vs_oracle(op, seed):
    rng = np.random.default_rng(seed)
    n = 400
    a = rng.integers(-50, 50, n, dtype=np.int64)
    b = rng.integers(-6, 6, n, dtype=np.int64)  # zeros included
    av = rng.random(n) > 0.1
    ca = Column.from_numpy(a, validity=av)
    cb = Column.from_numpy(b)
    got = binary_op(op, ca, cb).to_pylist()
    for i in range(n):
        aa = int(a[i]) if av[i] else None
        want = _oracle(op, aa, int(b[i]), False)
        g = got[i]
        if want is None:
            assert g is None, (op, aa, int(b[i]), g)
        elif isinstance(want, float):
            assert g == pytest.approx(want, rel=1e-12), (op, aa, int(b[i]))
        else:
            assert g == want, (op, aa, int(b[i]), g)


@pytest.mark.parametrize("op", ["add", "mul", "div", "mod", "pmod"])
def test_float64_ops_vs_oracle(op):
    rng = np.random.default_rng(7)
    n = 400
    a = np.round(rng.standard_normal(n) * 10, 3)
    b = np.round(rng.standard_normal(n) * 4, 3)
    b[::13] = 0.0  # IEEE corners
    ca = Column.from_numpy(a)
    cb = Column.from_numpy(b)
    got = binary_op(op, ca, cb).to_pylist()
    for i in range(n):
        want = _oracle(op, float(a[i]), float(b[i]), True)
        g = got[i]
        if want is None or (isinstance(want, float) and math.isnan(want)):
            assert g is None or math.isnan(g), (op, a[i], b[i], g)
        elif math.isinf(want):
            assert g == want, (op, a[i], b[i], g)
        else:
            assert g == pytest.approx(want, rel=1e-9), (op, a[i], b[i], g)


@pytest.mark.parametrize("ta", [np.int16, np.int32])
@pytest.mark.parametrize("tb", [np.int8, np.int64])
def test_mixed_width_promotion(ta, tb):
    rng = np.random.default_rng(3)
    n = 300
    a = rng.integers(-100, 100, n).astype(ta)
    b = rng.integers(-100, 100, n).astype(tb)
    got = binary_op("add", Column.from_numpy(a), Column.from_numpy(b))
    assert got.to_pylist() == [
        int(x) + int(y) for x, y in zip(a, b)
    ]


def test_decimal_div_scale_contract():
    """a / b at the promoted output scale, truncated toward zero —
    7.50 / 2.00 must be 3.75, not 0.03 (review catch)."""
    from decimal import Decimal

    d2 = dt.DType(dt.TypeId.DECIMAL64, -2)
    a = Column.from_numpy(
        np.array([750, -750, 100, 999], dtype=np.int64), dtype=d2
    )
    b = Column.from_numpy(
        np.array([200, 200, 50, 300], dtype=np.int64), dtype=d2
    )
    out = binary_op("div", a, b)
    assert out.dtype.scale == -2
    got = [int(x) for x in np.asarray(out.data)]
    assert got == [375, -375, 200, 333]  # 3.75, -3.75, 2.00, 3.33

    # mixed scales: 3 (scale 0) / 0.50 (scale -2) = 6.00 at scale -2
    d0 = dt.DType(dt.TypeId.DECIMAL64, 0)
    a2 = Column.from_numpy(np.array([3], dtype=np.int64), dtype=d0)
    b2 = Column.from_numpy(np.array([50], dtype=np.int64), dtype=d2)
    out2 = binary_op("div", a2, b2)
    assert out2.dtype.scale == -2
    assert int(np.asarray(out2.data)[0]) == 600
