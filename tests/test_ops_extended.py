"""Tests for the extended op families (round, datetime, copying,
replace, search, scan, compaction) — the remaining rows of the cudf
capability surface (SURVEY.md §2.3), each checked against an
independent numpy/python oracle."""

import datetime as pydt

import numpy as np
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table


def col(values, dtype=None):
    return Column.from_numpy(np.asarray(values, dtype=dtype))


# ---------------------------------------------------------------------------
# round
# ---------------------------------------------------------------------------

class TestRound:
    def test_float_half_up(self):
        c = col([2.5, 3.5, -2.5, 1.234, -1.235], np.float64)
        got = ops.round_column(c, 0, "half_up").to_pylist()
        assert got[:3] == [3.0, 4.0, -3.0]

    def test_float_half_even(self):
        c = col([2.5, 3.5, 4.5, -2.5], np.float64)
        got = ops.round_column(c, 0, "half_even").to_pylist()
        assert got == [2.0, 4.0, 4.0, -2.0]

    def test_float_places(self):
        c = col([1.25, 1.351, -9.875], np.float64)
        got = ops.round_column(c, 1, "half_up").to_pylist()
        np.testing.assert_allclose(got, [1.3, 1.4, -9.9], atol=1e-9)

    def test_int_negative_places(self):
        c = col([149, 150, -150, -151, 1250], np.int64)
        up = ops.round_column(c, -2, "half_up").to_pylist()
        assert up == [100, 200, -200, -200, 1300]
        even = ops.round_column(c, -2, "half_even").to_pylist()
        assert even == [100, 200, -200, -200, 1200]

    def test_decimal_exact(self):
        # DECIMAL64 scale -3: unscaled 1500 = 1.500
        c = Column(np.array([1500, 2500, -1500], np.int64), dt.DType(dt.TypeId.DECIMAL64, -3), None)
        got = ops.round_column(c, 0, "half_up")
        assert got.dtype == c.dtype
        assert [int(v) for v in np.asarray(got.data)] == [2000, 3000, -2000]

    def test_nulls_pass_through(self):
        c = Column.from_numpy(np.array([1.5, 2.5]), validity=np.array([True, False]))
        got = ops.round_column(c, 0, "half_up")
        assert got.to_pylist() == [2.0, None]


# ---------------------------------------------------------------------------
# datetime
# ---------------------------------------------------------------------------

class TestDatetime:
    def _ts_col(self, dates, unit=dt.TypeId.TIMESTAMP_SECONDS):
        epoch = pydt.datetime(1970, 1, 1)
        secs = np.array(
            [int((d - epoch).total_seconds()) for d in dates], np.int64
        )
        return Column(secs, dt.DType(unit), None), dates

    def test_ymd_fields(self):
        dates = [
            pydt.datetime(2000, 2, 29, 13, 45, 56),
            pydt.datetime(1969, 12, 31, 23, 59, 59),
            pydt.datetime(2024, 1, 1, 0, 0, 0),
            pydt.datetime(1900, 3, 1, 6, 30, 15),
        ]
        c, ds = self._ts_col(dates)
        assert ops.datetime.year(c).to_pylist() == [d.year for d in ds]
        assert ops.datetime.month(c).to_pylist() == [d.month for d in ds]
        assert ops.datetime.day(c).to_pylist() == [d.day for d in ds]
        assert ops.datetime.hour(c).to_pylist() == [d.hour for d in ds]
        assert ops.datetime.minute(c).to_pylist() == [d.minute for d in ds]
        assert ops.datetime.second(c).to_pylist() == [d.second for d in ds]

    def test_weekday_iso(self):
        dates = [
            pydt.datetime(2024, 7, 29) + pydt.timedelta(days=i)
            for i in range(7)
        ]  # Mon..Sun
        c, ds = self._ts_col(dates)
        assert ops.datetime.weekday(c).to_pylist() == [
            d.isoweekday() for d in ds
        ]

    def test_day_of_year(self):
        dates = [pydt.datetime(2024, 3, 1), pydt.datetime(2023, 3, 1)]
        c, ds = self._ts_col(dates)
        assert ops.datetime.day_of_year(c).to_pylist() == [
            d.timetuple().tm_yday for d in ds
        ]

    def test_last_day_of_month(self):
        days = np.array(
            [
                (pydt.date(2024, 2, 5) - pydt.date(1970, 1, 1)).days,
                (pydt.date(2023, 2, 5) - pydt.date(1970, 1, 1)).days,
            ],
            np.int32,
        )
        c = Column(days, dt.TIMESTAMP_DAYS, None)
        got = ops.datetime.last_day_of_month(c)
        want = [
            (pydt.date(2024, 2, 29) - pydt.date(1970, 1, 1)).days,
            (pydt.date(2023, 2, 28) - pydt.date(1970, 1, 1)).days,
        ]
        assert [int(v) for v in np.asarray(got.data)] == want

    def test_add_months_clamps(self):
        days = np.array(
            [(pydt.date(2024, 1, 31) - pydt.date(1970, 1, 1)).days], np.int32
        )
        c = Column(days, dt.TIMESTAMP_DAYS, None)
        got = ops.datetime.add_calendrical_months(c, 1)
        want = (pydt.date(2024, 2, 29) - pydt.date(1970, 1, 1)).days
        assert int(np.asarray(got.data)[0]) == want

    def test_random_roundtrip_vs_numpy(self):
        rng = np.random.default_rng(7)
        days = rng.integers(-40000, 40000, 200).astype(np.int64)
        secs = days * 86400 + rng.integers(0, 86400, 200)
        c = Column(secs, dt.DType(dt.TypeId.TIMESTAMP_SECONDS), None)
        as_np = secs.astype("datetime64[s]")
        y = as_np.astype("datetime64[Y]").astype(int) + 1970
        assert ops.datetime.year(c).to_pylist() == list(y)


# ---------------------------------------------------------------------------
# copying
# ---------------------------------------------------------------------------

class TestCopying:
    def test_concatenate_tables(self):
        t1 = Table.from_pydict({"a": [1, 2], "b": [1.0, 2.0]})
        t2 = Table.from_pydict({"a": [3, None], "b": [3.0, 4.0]})
        out = ops.concatenate([t1, t2])
        assert out["a"].to_pylist() == [1, 2, 3, None]
        assert out["b"].to_pylist() == [1.0, 2.0, 3.0, 4.0]

    def test_concatenate_strings(self):
        t1 = Table.from_pydict({"s": ["a", "bb"]})
        t2 = Table.from_pydict({"s": ["cccc", None]})
        out = ops.concatenate([t1, t2])
        assert out["s"].to_pylist() == ["a", "bb", "cccc", None]

    def test_interleave(self):
        t = Table.from_pydict({"a": [1, 2], "b": [10, 20]})
        out = ops.interleave_columns(t)
        assert out.to_pylist() == [1, 10, 2, 20]

    def test_copy_if_else_columns(self):
        mask = Column(np.array([True, False, True]), dt.BOOL8, np.array([True, True, False]))
        lhs = col([1, 2, 3], np.int64)
        rhs = col([10, 20, 30], np.int64)
        out = ops.copy_if_else(mask, lhs, rhs)
        # null mask row selects rhs
        assert out.to_pylist() == [1, 20, 30]

    def test_copy_if_else_scalar(self):
        mask = Column(np.array([True, False]), dt.BOOL8, None)
        rhs = col([5, 6], np.int64)
        out = ops.copy_if_else(mask, 0, rhs)
        assert out.to_pylist() == [0, 6]

    def test_sequence(self):
        out = ops.sequence(5, start=10, step=3, dtype=dt.INT64)
        assert out.to_pylist() == [10, 13, 16, 19, 22]


# ---------------------------------------------------------------------------
# replace
# ---------------------------------------------------------------------------

class TestReplace:
    def test_replace_nulls_scalar(self):
        c = Column.from_numpy(
            np.array([1, 2, 3], np.int64), validity=np.array([True, False, True])
        )
        out = ops.replace_nulls(c, 99)
        assert out.to_pylist() == [1, 99, 3]
        assert out.validity is None

    def test_replace_nulls_column(self):
        c = Column.from_numpy(
            np.array([1, 2, 3], np.int64), validity=np.array([False, True, False])
        )
        fill = col([10, 20, 30], np.int64)
        assert ops.replace_nulls(c, fill).to_pylist() == [10, 2, 30]

    def test_fill_preceding_following(self):
        c = Column.from_numpy(
            np.array([0, 1, 0, 0, 4], np.int64),
            validity=np.array([False, True, False, False, True]),
        )
        fwd = ops.replace_nulls_policy(c, ops.replace.PRECEDING)
        assert fwd.to_pylist() == [None, 1, 1, 1, 4]
        bwd = ops.replace_nulls_policy(c, ops.replace.FOLLOWING)
        assert bwd.to_pylist() == [1, 1, 4, 4, 4]

    def test_replace_nulls_strings(self):
        c = Column.from_strings(["aa", None, "cccc"])
        out = ops.replace_nulls(c, "xx")
        assert out.to_pylist() == ["aa", "xx", "cccc"]
        fill = Column.from_strings(["1", "22", "333"])
        out2 = ops.replace_nulls(c, fill)
        assert out2.to_pylist() == ["aa", "22", "cccc"]

    def test_nans_to_nulls(self):
        c = col([1.0, np.nan, 3.0], np.float64)
        out = ops.nans_to_nulls(c)
        assert out.to_pylist() == [1.0, None, 3.0]

    def test_find_and_replace(self):
        c = col([1, 2, 3, 2], np.int64)
        out = ops.find_and_replace(c, [2, 3], [20, 30])
        assert out.to_pylist() == [1, 20, 30, 20]

    def test_clamp(self):
        c = col([-5, 0, 5, 10], np.int64)
        assert ops.clamp(c, 0, 6).to_pylist() == [0, 0, 5, 6]
        assert ops.clamp(c, lo=0).to_pylist() == [0, 0, 5, 10]


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

class TestSearch:
    def test_bounds_single_key(self):
        hay = Table.from_pydict({"k": [10, 20, 20, 30]})
        ndl = Table.from_pydict({"k": [5, 20, 35]})
        lo = ops.lower_bound(hay, ndl).to_pylist()
        hi = ops.upper_bound(hay, ndl).to_pylist()
        assert lo == [0, 1, 4]
        assert hi == [0, 3, 4]

    def test_bounds_multi_key(self):
        hay = Table.from_pydict({"a": [1, 1, 2, 2], "b": [1.0, 5.0, 1.0, 5.0]})
        ndl = Table.from_pydict({"a": [1, 2], "b": [5.0, 0.5]})
        assert ops.lower_bound(hay, ndl).to_pylist() == [1, 2]
        assert ops.upper_bound(hay, ndl).to_pylist() == [2, 2]

    def test_contains(self):
        hay = col([1, 3, 5], np.int64)
        ndl = col([0, 3, 5, 7], np.int64)
        assert ops.contains_column(hay, ndl).to_pylist() == [
            False, True, True, False,
        ]

    def test_contains_null_haystack_never_matches(self):
        hay = Column.from_numpy(
            np.array([1, 999], np.int64), validity=np.array([True, False])
        )
        ndl = col([999, 1], np.int64)
        assert ops.contains_column(hay, ndl).to_pylist() == [False, True]

    def test_contains_strings(self):
        hay = Column.from_strings(["apple", "pear"])
        ndl = Column.from_strings(["pear", "plum"])
        assert ops.contains_column(hay, ndl).to_pylist() == [True, False]


# ---------------------------------------------------------------------------
# scan
# ---------------------------------------------------------------------------

class TestScan:
    def test_cumsum(self):
        c = col([1, 2, 3, 4], np.int64)
        assert ops.scan(c, "sum").to_pylist() == [1, 3, 6, 10]
        assert ops.scan(c, "sum", inclusive=False).to_pylist() == [0, 1, 3, 6]

    def test_cummin_max_product(self):
        c = col([3, 1, 4, 1], np.int64)
        assert ops.scan(c, "min").to_pylist() == [3, 1, 1, 1]
        assert ops.scan(c, "max").to_pylist() == [3, 3, 4, 4]
        assert ops.scan(c, "product").to_pylist() == [3, 3, 12, 12]

    def test_scan_skips_nulls(self):
        c = Column.from_numpy(
            np.array([1, 5, 2], np.int64),
            validity=np.array([True, False, True]),
        )
        # null emits null; running sum carries past it
        assert ops.scan(c, "sum").to_pylist() == [1, None, 3]

    def test_scan_bool_min_max(self):
        c = Column(np.array([True, False, True]), dt.BOOL8, None)
        assert ops.scan(c, "min").to_pylist() == [True, False, False]
        assert ops.scan(c, "max").to_pylist() == [True, True, True]

    def test_scan_float(self):
        c = col([0.5, 0.25, 0.125], np.float64)
        np.testing.assert_allclose(
            ops.scan(c, "sum").to_pylist(), [0.5, 0.75, 0.875]
        )


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------

class TestCompaction:
    def test_distinct_preserves_first(self):
        t = Table.from_pydict({"k": [3, 1, 3, 2, 1], "v": [0, 1, 2, 3, 4]})
        out = ops.distinct(t, ["k"])
        assert out["k"].to_pylist() == [3, 1, 2]
        assert out["v"].to_pylist() == [0, 1, 3]

    def test_distinct_count(self):
        t = Table.from_pydict({"k": [1, 1, 2, None, None]})
        assert int(ops.distinct_count(t)) == 3  # 1, 2, null

    def test_distinct_null_group_ignores_payload_bytes(self):
        # two nulls over different underlying bytes are ONE group
        c = Column.from_numpy(
            np.array([7, 8], np.int64), validity=np.array([False, False])
        )
        assert int(ops.distinct_count(Table([c], ["c"]))) == 1

    def test_distinct_capped_jits(self):
        import jax

        t = Table.from_pydict({"k": [1, 2, 1, 2, 3]})
        fn = jax.jit(lambda t: ops.distinct_capped(t, ["k"], capacity=5))
        out, count = fn(t)
        assert int(count) == 3

    def test_distinct_multi_key_vs_python(self):
        rng = np.random.default_rng(3)
        a = rng.integers(0, 5, 100)
        b = rng.integers(0, 4, 100)
        t = Table.from_pydict({"a": a, "b": b})
        want = len({(x, y) for x, y in zip(a, b)})
        assert int(ops.distinct_count(t)) == want


class TestMergeSorted:
    def test_merge_matches_oracle(self, rng):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import is_sorted, merge_sorted, SortKey

        parts = []
        host = []
        for _ in range(3):
            k = np.sort(rng.integers(0, 1000, 500))
            v = rng.integers(-10, 10, 500)
            host.append((k, v))
            parts.append(Table(
                [Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"]
            ))
        out = merge_sorted(parts, [SortKey("k")])
        allk = np.concatenate([h[0] for h in host])
        np.testing.assert_array_equal(
            out["k"].to_numpy(), np.sort(allk, kind="stable")
        )
        assert bool(is_sorted(out, [SortKey("k")]))
        # stability: equal keys keep input-table order
        a = Table([Column.from_numpy(np.array([5, 5], dtype=np.int64)),
                   Column.from_numpy(np.array([0, 1], dtype=np.int64))],
                  ["k", "tag"])
        b = Table([Column.from_numpy(np.array([5], dtype=np.int64)),
                   Column.from_numpy(np.array([2], dtype=np.int64))],
                  ["k", "tag"])
        m = merge_sorted([a, b], [SortKey("k")])
        assert m["tag"].to_pylist() == [0, 1, 2]

    def test_is_sorted(self, rng):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import is_sorted, SortKey

        k = np.array([3, 1, 2], dtype=np.int64)
        t = Table([Column.from_numpy(k)], ["k"])
        assert not bool(is_sorted(t, [SortKey("k")]))
        assert bool(is_sorted(t, [SortKey("k")])) is False
        ts = Table([Column.from_numpy(np.sort(k))], ["k"])
        assert bool(is_sorted(ts, [SortKey("k")]))
        # descending + nulls-first placement
        kd = Column.from_numpy(
            np.array([9, 7, 7, 1], dtype=np.int64),
            validity=np.array([False, True, True, True]),
        )
        td = Table([kd], ["k"])
        assert bool(
            is_sorted(td, [SortKey("k", ascending=False,
                                    nulls_first=True)])
        )


class TestTableCopyOps:
    def test_cross_join(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import cross_join

        l = Table.from_pydict({"a": [1, 2]})
        r = Table.from_pydict({"b": [10, 20, 30]})
        out = cross_join(l, r)
        assert out["a"].to_pylist() == [1, 1, 1, 2, 2, 2]
        assert out["b"].to_pylist() == [10, 20, 30, 10, 20, 30]

    def test_cross_join_jit(self):
        import jax
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import cross_join

        l = Table.from_pydict({"a": [1, 2]})
        r = Table.from_pydict({"b": [5, 6]})
        f = jax.jit(cross_join)
        out = f(l, r)
        assert out["a"].to_pylist() == [1, 1, 2, 2]

    def test_scatter(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import scatter

        tgt = Table.from_pydict({"v": [0, 0, 0, 0, 0],
                                 "s": ["a", "b", "c", "d", "e"]})
        src = Table.from_pydict({"v": [7, None], "s": ["XX", "Y"]})
        out = scatter(src, np.array([3, 0]), tgt)
        assert out["v"].to_pylist() == [None, 0, 0, 7, 0]
        assert out["s"].to_pylist() == ["Y", "b", "c", "XX", "e"]

    def test_split(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import split

        t = Table.from_pydict({"v": list(range(10))})
        parts = split(t, [3, 7])
        assert [p.row_count for p in parts] == [3, 4, 3]
        assert parts[1]["v"].to_pylist() == [3, 4, 5, 6]
        import pytest as _pytest
        with _pytest.raises(ValueError):
            split(t, [7, 3])

    def test_sample(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import sample

        t = Table.from_pydict({"v": list(range(100))})
        s1 = sample(t, 10, seed=1)
        s2 = sample(t, 10, seed=1)
        assert s1["v"].to_pylist() == s2["v"].to_pylist()  # deterministic
        assert len(set(s1["v"].to_pylist())) == 10  # no replacement
        sr = sample(t, 200, seed=2, replacement=True)
        assert sr.row_count == 200
        import pytest as _pytest
        with _pytest.raises(ValueError):
            sample(t, 101)


class TestBatchedJoin:
    def test_matches_single_shot(self, rng):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import inner_join, inner_join_batched

        n = 10_000
        kl = rng.integers(0, 3_000, n, dtype=np.int64)
        kr = rng.integers(0, 3_000, n, dtype=np.int64)
        vl = rng.integers(-9, 9, n, dtype=np.int64)
        vr = rng.integers(-9, 9, n, dtype=np.int64)
        lv = rng.random(n) > 0.05
        left = Table(
            [Column.from_numpy(kl, validity=lv), Column.from_numpy(vl)],
            ["k", "lv"],
        )
        right = Table(
            [Column.from_numpy(kr), Column.from_numpy(vr)], ["k", "rv"]
        )
        whole = inner_join(left, right, ["k"])
        batched = inner_join_batched(left, right, ["k"], probe_rows=1024)
        def rows(t):
            return sorted(zip(t["k"].to_pylist(), t["lv"].to_pylist(),
                              t["rv"].to_pylist()))
        assert rows(batched) == rows(whole)
        assert batched.row_count == whole.row_count

    def test_no_matches_and_empty(self, rng):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import inner_join_batched

        left = Table.from_pydict({"k": [1, 2, 3]})
        right = Table.from_pydict({"k": [9, 8]})
        out = inner_join_batched(left, right, ["k"], probe_rows=2)
        assert out.row_count == 0
        empty = Table.from_pydict({"k": np.array([], dtype=np.int64)})
        out2 = inner_join_batched(empty, right, ["k"])
        assert out2.row_count == 0


def test_batched_join_rejects_bad_probe_rows():
    from spark_rapids_jni_tpu.column import Table
    from spark_rapids_jni_tpu.ops import inner_join_batched
    import pytest as _pytest

    l = Table.from_pydict({"k": [1]})
    r = Table.from_pydict({"k": [1]})
    with _pytest.raises(ValueError):
        inner_join_batched(l, r, ["k"], probe_rows=-1)
    with _pytest.raises(ValueError):
        inner_join_batched(l, r, ["k"], probe_rows=0)


def test_batched_join_schema_parity_with_single_shot():
    from spark_rapids_jni_tpu.column import Table
    from spark_rapids_jni_tpu.ops import inner_join, inner_join_batched

    l = Table.from_pydict({"k": [1, 2], "lv": [7, 8]})
    r = Table.from_pydict({"k": [1, 2], "rv": [5, 6]})
    a = inner_join(l, r, ["k"])
    b = inner_join_batched(l, r, ["k"], probe_rows=1)
    for ca, cb in zip(a.columns, b.columns):
        assert (ca.validity is None) == (cb.validity is None)


class TestModAndRepeat:
    def test_mod_spark_semantics(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import binary_op

        t = Table.from_pydict({
            "a": np.array([-7, 7, -7, 7, 5], dtype=np.int64),
            "b": np.array([3, 3, -3, -3, 0], dtype=np.int64),
        })
        m = binary_op("mod", t["a"], t["b"])
        # Java/Spark %: sign of the dividend; x % 0 is null
        assert m.to_pylist() == [-1, 1, -1, 1, None]
        p = binary_op("pmod", t["a"], t["b"])
        # Spark Pmod corrects only NEGATIVE remainders: pmod(-7,3)=2,
        # pmod(7,-3)=1 (r=1 kept as-is), pmod(-7,-3)=-1
        assert p.to_pylist() == [2, 1, -1, 1, None]

    def test_shiftright_unsigned(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import binary_op

        t = Table.from_pydict({
            "a": np.array([-8, 16], dtype=np.int64),
            "s": np.array([1, 2], dtype=np.int64),
        })
        sru = binary_op("shiftright_unsigned", t["a"], t["s"])
        assert sru.to_pylist() == [(-8 % (1 << 64)) >> 1, 4]
        sr = binary_op("shiftright", t["a"], t["s"])
        assert sr.to_pylist() == [-4, 4]

    def test_repeat(self):
        import numpy as np
        import pytest as _pytest

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import repeat

        t = Table.from_pydict({"v": [10, 20, 30], "s": ["a", "b", "c"]})
        r = repeat(t, 2)
        assert r["v"].to_pylist() == [10, 10, 20, 20, 30, 30]
        r2 = repeat(t, np.array([0, 3, 1]))
        assert r2["v"].to_pylist() == [20, 20, 20, 30]
        assert r2["s"].to_pylist() == ["b", "b", "b", "c"]
        assert repeat(t, np.array([0, 0, 0])).row_count == 0
        with _pytest.raises(ValueError):
            repeat(t, np.array([1, -1, 0]))

    def test_unary_logs(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import unary_op

        t = Table.from_pydict({"v": [1.0, 8.0, 100.0]})
        np.testing.assert_allclose(
            unary_op("log2", t["v"]).to_numpy(), [0.0, 3.0, np.log2(100)]
        )
        np.testing.assert_allclose(
            unary_op("log10", t["v"]).to_numpy(), [0.0, np.log10(8), 2.0]
        )
        np.testing.assert_allclose(
            unary_op("log1p", t["v"]).to_numpy(), np.log1p([1.0, 8.0, 100.0])
        )
        np.testing.assert_allclose(
            unary_op("expm1", t["v"]).to_numpy(), np.expm1([1.0, 8.0, 100.0])
        )

    def test_shiftright_unsigned_narrow_widths(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import binary_op

        a = Column.from_numpy(np.array([-8, 16], dtype=np.int16))
        s = Column.from_numpy(np.array([1, 2], dtype=np.int16))
        out = binary_op("shiftright_unsigned", a, s)
        # logical shift at 16 bits: 0xFFF8 >> 1 = 0x7FFC = 32764
        assert out.to_pylist() == [32764, 4]

    def test_pmod_float(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import binary_op

        t = Table.from_pydict({
            "a": [-7.0, 7.0, -7.5],
            "b": [3.0, -3.0, 2.0],
        })
        out = binary_op("pmod", t["a"], t["b"]).to_numpy()
        np.testing.assert_allclose(out, [2.0, 1.0, 0.5])

    def test_shift_amount_masked_like_java(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import binary_op

        t = Table.from_pydict({
            "a": np.array([5, 5, -8], dtype=np.int64),
            "s": np.array([64, 65, 64], dtype=np.int64),
        })
        # Java masks int64 shifts to amount & 63: x << 64 == x
        assert binary_op("shiftleft", t["a"], t["s"]).to_pylist() == [5, 10, -8]
        assert binary_op("shiftright", t["a"], t["s"]).to_pylist() == [5, 2, -8]
        assert binary_op(
            "shiftright_unsigned", t["a"], t["s"]
        ).to_pylist() == [5, 2, -8]


class TestDateTrunc:
    def test_truncate_vs_python(self):
        import datetime as _dt

        import numpy as np

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops import datetime as sdt

        stamps = [
            _dt.datetime(2024, 7, 30, 13, 45, 56),
            _dt.datetime(1969, 5, 14, 23, 59, 59),
            _dt.datetime(2000, 1, 1, 0, 0, 0),
            _dt.datetime(1987, 11, 9, 6, 30, 15),
        ]
        epoch = _dt.datetime(1970, 1, 1)
        secs = np.array(
            [int((d - epoch).total_seconds()) for d in stamps], np.int64
        )
        c = Column(secs, dt.DType(dt.TypeId.TIMESTAMP_SECONDS), None)

        def back(out):
            return [
                epoch + _dt.timedelta(seconds=int(v))
                for v in np.asarray(out.data)
            ]

        assert back(sdt.truncate(c, "day")) == [
            d.replace(hour=0, minute=0, second=0) for d in stamps
        ]
        assert back(sdt.truncate(c, "month")) == [
            d.replace(day=1, hour=0, minute=0, second=0) for d in stamps
        ]
        assert back(sdt.truncate(c, "year")) == [
            d.replace(month=1, day=1, hour=0, minute=0, second=0)
            for d in stamps
        ]
        assert back(sdt.truncate(c, "hour")) == [
            d.replace(minute=0, second=0) for d in stamps
        ]
        # ISO week: Monday 00:00 on or before the stamp
        assert back(sdt.truncate(c, "week")) == [
            (d - _dt.timedelta(days=d.weekday())).replace(
                hour=0, minute=0, second=0
            )
            for d in stamps
        ]
        assert back(sdt.truncate(c, "quarter")) == [
            d.replace(
                month=((d.month - 1) // 3) * 3 + 1, day=1,
                hour=0, minute=0, second=0,
            )
            for d in stamps
        ]

    def test_quarter(self):
        import datetime as _dt

        import numpy as np

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops import datetime as sdt

        days = np.array(
            [
                (_dt.date(2024, m, 15) - _dt.date(1970, 1, 1)).days
                for m in range(1, 13)
            ],
            np.int32,
        )
        c = Column(days, dt.TIMESTAMP_DAYS, None)
        got = sdt.quarter(c).to_pylist()
        assert got == [1, 1, 1, 2, 2, 2, 3, 3, 3, 4, 4, 4]


class TestSortVariadicPayload:
    def test_matches_argsort_gather(self, rng):
        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import SortKey, sort_table
        from spark_rapids_jni_tpu.ops.gather import gather_table
        from spark_rapids_jni_tpu.ops.sort import argsort_table

        n = 5_000
        t = Table(
            [
                Column.from_numpy(
                    rng.integers(0, 100, n),
                    validity=rng.random(n) > 0.1,
                ),
                Column.from_numpy(rng.standard_normal(n)),
                Column.from_strings(
                    ["s%d" % i for i in rng.integers(0, 50, n)]
                ),
                Column.from_decimal128(
                    [
                        int(a) * (10**10) + int(b)
                        for a, b in zip(
                            rng.integers(-(10**9), 10**9, n),
                            rng.integers(0, 10**9, n),
                        )
                    ]
                ),
            ],
            ["k", "f", "s", "d"],
        )
        keys = [SortKey("k"), SortKey("f", ascending=False)]
        fast = sort_table(t, keys)
        ref = gather_table(t, argsort_table(t, keys))
        assert fast.to_pydict() == ref.to_pydict()

    def test_stability(self):
        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import SortKey, sort_table

        t = Table.from_pydict({
            "k": [1, 0, 1, 0, 1],
            "tag": [0, 1, 2, 3, 4],
        })
        out = sort_table(t, [SortKey("k")])
        assert out["tag"].to_pylist() == [1, 3, 0, 2, 4]

    def test_payload_table(self, rng):
        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import SortKey, sort_table

        keys = Table.from_pydict({"k": [3, 1, 2]})
        payload = Table.from_pydict({"v": [30, 10, 20]})
        out = sort_table(keys, [SortKey("k")], payload=payload)
        assert out["v"].to_pylist() == [10, 20, 30]


class TestSubsecondDatetime:
    def test_fractions(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops import datetime as sdt

        # 1.234567891 seconds past epoch, in ns resolution
        ns = np.array([1_234_567_891, -1_500_000_000], np.int64)
        c = Column(ns, dt.DType(dt.TypeId.TIMESTAMP_NANOSECONDS), None)
        assert sdt.millisecond_fraction(c).to_pylist() == [234, 500]
        assert sdt.microsecond_fraction(c).to_pylist() == [567, 0]
        assert sdt.nanosecond_fraction(c).to_pylist() == [891, 0]
        # second-resolution columns have zero fractions
        cs = Column(
            np.array([5], np.int64),
            dt.DType(dt.TypeId.TIMESTAMP_SECONDS), None,
        )
        assert sdt.millisecond_fraction(cs).to_pylist() == [0]

    def test_day_of_week_sunday(self):
        import datetime as _dt

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops import datetime as sdt

        days = np.array(
            [
                (_dt.date(2024, 7, 28) - _dt.date(1970, 1, 1)).days + i
                for i in range(7)
            ],
            np.int32,
        )  # 2024-07-28 is a Sunday
        c = Column(days, dt.TIMESTAMP_DAYS, None)
        assert sdt.day_of_week_sunday(c).to_pylist() == [
            1, 2, 3, 4, 5, 6, 7,
        ]

    def test_fraction_type_guard(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops import datetime as sdt

        bad = Column.from_numpy(np.array([1, 2], np.int64))
        for fn in (sdt.millisecond_fraction, sdt.microsecond_fraction,
                   sdt.nanosecond_fraction):
            with pytest.raises(TypeError):
                fn(bad)


class TestDropNullsAndExtremeBy:
    def test_drop_nulls(self):
        t = Table(
            [
                Column.from_numpy(
                    np.array([1, 2, 3, 4], np.int64),
                    validity=np.array([True, False, True, True]),
                ),
                Column.from_numpy(
                    np.array([9, 8, 7, 6], np.int64),
                    validity=np.array([True, True, False, True]),
                ),
            ],
            ["a", "b"],
        )
        out = ops.drop_nulls(t)
        assert out["a"].to_pylist() == [1, 4]
        only_a = ops.drop_nulls(t, keys=["a"])
        assert only_a["a"].to_pylist() == [1, 3, 4]
        thresh = ops.drop_nulls(t, keep_threshold=1)
        assert thresh.row_count == 4  # every row has >=1 valid value

    def test_arg_extreme_and_extreme_by(self):
        by = Column.from_numpy(
            np.array([5, 1, 9, 1], np.int64),
            validity=np.array([True, True, True, False]),
        )
        val = Column.from_strings(["a", "b", "c", "d"])
        assert ops.arg_extreme(by, "argmin").to_pylist() == [1]
        assert ops.arg_extreme(by, "argmax").to_pylist() == [2]
        assert ops.extreme_by(val, by, "min_by").to_pylist() == ["b"]
        assert ops.extreme_by(val, by, "max_by").to_pylist() == ["c"]
        # all-null by column -> null result
        allnull = Column.from_numpy(
            np.array([1, 2], np.int64),
            validity=np.array([False, False]),
        )
        assert ops.arg_extreme(allnull, "argmin").to_pylist() == [None]

    def test_arg_extreme_sentinel_collision(self):
        # a valid INT64_MAX must win argmin ties against null rows
        by = Column.from_numpy(
            np.array([0, np.iinfo(np.int64).max], np.int64),
            validity=np.array([False, True]),
        )
        assert ops.arg_extreme(by, "argmin").to_pylist() == [1]
        byf = Column.from_numpy(
            np.array([0.0, -np.inf], np.float64),
            validity=np.array([False, True]),
        )
        assert ops.arg_extreme(byf, "argmax").to_pylist() == [1]
        with pytest.raises(TypeError):
            ops.arg_extreme(Column.from_strings(["a"]), "argmin")
