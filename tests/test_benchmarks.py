"""Correctness of the TPC-DS-shaped benchmark queries at tiny scale:
single-chip results against a pure-python oracle, distributed results
against single-chip (the 8-device virtual mesh from conftest)."""

from collections import defaultdict

import numpy as np
import pytest

from benchmarks import datagen, queries
from spark_rapids_jni_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def tables():
    return datagen.generate(2000, seed=11)


def _oracle_q5(tables, lo=100, hi=200):
    out = defaultdict(lambda: [0.0, 0.0])
    item_cat = dict(
        zip(
            tables["item"]["item_sk"].to_pylist(),
            tables["item"]["category_id"].to_pylist(),
        )
    )
    for t in (tables["store_sales"], tables["web_sales"]):
        d = t.to_pydict()
        for i in range(len(d["item_sk"])):
            if not (lo <= d["date_sk"][i] < hi):
                continue
            cat = item_cat[d["item_sk"][i]]
            out[cat][0] += d["quantity"][i] * d["sales_price"][i]
            out[cat][1] += d["net_profit"][i]
    return out


def test_q5_vs_oracle(tables):
    got = queries.q5(tables)
    want = _oracle_q5(tables)
    cats = got["category_id"].to_pylist()
    sums = got["sum_revenue"].to_pylist()
    profs = got["sum_net_profit"].to_pylist()
    assert sorted(cats) == sorted(want.keys())
    for c, s, p in zip(cats, sums, profs):
        assert s == pytest.approx(want[c][0], rel=1e-6), f"cat {c} revenue"
        assert p == pytest.approx(want[c][1], rel=1e-6), f"cat {c} profit"


def _oracle_q23(tables, min_count=4):
    d = tables["store_sales"].to_pydict()
    counts = defaultdict(int)
    for sk in d["item_sk"]:
        counts[sk] += 1
    hot = {k for k, v in counts.items() if v >= min_count}
    spend = defaultdict(float)
    for i in range(len(d["item_sk"])):
        if d["item_sk"][i] in hot:
            spend[d["customer_sk"][i]] += d["quantity"][i] * d["sales_price"][i]
    return spend


def test_q23_vs_oracle(tables):
    got = queries.q23(tables)
    want = _oracle_q23(tables)
    custs = got["customer_sk"].to_pylist()
    sums = got["sum_spend"].to_pylist()
    assert sorted(custs) == sorted(want.keys())
    for c, s in zip(custs, sums):
        assert s == pytest.approx(want[c], rel=1e-6)


def _oracle_q64(tables, max_price=150.0):
    item = tables["item"].to_pydict()
    # current_price is decimal: to_pydict yields unscaled values
    price_scale = tables["item"]["current_price"].dtype.scale
    cutoff = max_price * (10 ** -price_scale)
    cheap_brand = {
        item["item_sk"][i]: item["brand_id"][i]
        for i in range(len(item["item_sk"]))
        if item["current_price"][i] <= cutoff
    }
    cust = tables["customer"].to_pydict()
    state = dict(zip(cust["customer_sk"], cust["state_id"]))
    dates = tables["date_dim"].to_pydict()
    year = dict(zip(dates["date_sk"], dates["year"]))
    d = tables["store_sales"].to_pydict()
    out = defaultdict(lambda: [0.0, 0])
    for i in range(len(d["item_sk"])):
        if d["item_sk"][i] not in cheap_brand:
            continue
        key = (
            cheap_brand[d["item_sk"][i]],
            state[d["customer_sk"][i]],
            year[d["date_sk"][i]],
        )
        out[key][0] += d["quantity"][i] * d["sales_price"][i]
        out[key][1] += 1
    return out


def test_q64_vs_oracle(tables):
    got = queries.q64(tables)
    want = _oracle_q64(tables)
    keys = list(
        zip(
            got["brand_id"].to_pylist(),
            got["state_id"].to_pylist(),
            got["year"].to_pylist(),
        )
    )
    assert sorted(keys) == sorted(want.keys())
    sums = got["sum_revenue"].to_pylist()
    cnts = got["count_revenue"].to_pylist()
    for k, s, c in zip(keys, sums, cnts):
        assert s == pytest.approx(want[k][0], rel=1e-6), f"key {k}"
        assert c == want[k][1], f"key {k} count"


# ---------------------------------------------------------------------------
# distributed == single-chip (virtual 8-device mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _groupby_to_dict(table, key_names, val_names):
    keys = list(zip(*[table[k].to_pylist() for k in key_names]))
    vals = {v: table[v].to_pylist() for v in val_names}
    return {
        k: tuple(vals[v][i] for v in val_names) for i, k in enumerate(keys)
    }


def test_q5_distributed_matches(tables, mesh):
    single = queries.q5(tables)
    padded, counts, overflow = queries.q5_distributed(tables, mesh)
    assert int(np.asarray(overflow).max()) <= 0  # no dropped rows
    dist = queries._unpad_groupby(padded, counts)
    s = _groupby_to_dict(single, ["category_id"], ["sum_revenue"])
    d = _groupby_to_dict(dist, ["category_id"], ["sum_revenue"])
    assert set(s) == set(d)
    for k in s:
        assert d[k][0] == pytest.approx(s[k][0], rel=1e-6)


def test_q23_distributed_matches(tables, mesh):
    single = queries.q23(tables)
    padded, counts, overflow = queries.q23_distributed(tables, mesh)
    assert int(np.asarray(overflow).max()) <= 0  # no dropped rows
    dist = queries._unpad_groupby(padded, counts)
    s = _groupby_to_dict(single, ["customer_sk"], ["sum_spend"])
    d = _groupby_to_dict(dist, ["customer_sk"], ["sum_spend"])
    assert s.keys() == d.keys()
    for k in s:
        assert d[k][0] == pytest.approx(s[k][0], rel=1e-6)


def test_q64_distributed_matches(tables, mesh):
    single = queries.q64(tables)
    dist = queries.q64_distributed(tables, mesh)
    keys = ["brand_id", "state_id", "year"]
    s = _groupby_to_dict(single, keys, ["sum_revenue", "count_revenue"])
    d = _groupby_to_dict(dist, keys, ["sum_revenue", "count_revenue"])
    assert s.keys() == d.keys()
    for k in s:
        assert d[k][1] == s[k][1]
        assert d[k][0] == pytest.approx(s[k][0], rel=1e-6)


def test_bench_main_emits_parseable_line_when_unreachable(monkeypatch, tmp_path):
    """Round-4 postmortem regression: a dead tunnel + an immediate kill
    must still leave a parseable headline line (r4 published nothing
    because main() printed only once, at the very end)."""
    import contextlib
    import io
    import json as json_mod

    import bench

    monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: False)
    monkeypatch.setattr(bench, "_stop_daemon", lambda: None)
    # isolate from any real daemon state
    monkeypatch.setattr(bench, "_STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.setenv("SRT_BENCH_DEADLINE_S", "-1")
    # pre-set the store dir so monkeypatch restores it: bench's
    # _metrics_enable exports it (setdefault) for its subprocesses
    monkeypatch.setenv(
        "SPARK_RAPIDS_TPU_PLANSTATS_DIR", str(tmp_path / "planstats")
    )
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) >= 2  # one up-front + one after the ladder walk
    for line in lines:
        doc = json_mod.loads(line)
        assert doc["metric"] == "groupby_sum_100M_int64"
    last = json_mod.loads(lines[-1])
    assert last["headline_source"].startswith("published_round")
    names = {e["name"] for e in last["configs"]}
    # every ladder arm plus the mesh tail's typed skip records
    assert set(bench._LADDER) <= names
    for e in last["configs"]:
        if e["name"] not in bench._LADDER:
            assert e["failure"]["skipped"] is True
            assert e["failure"]["type"] in (
                "BudgetExceeded", "OptInSkipped", "DeviceUnreachable"
            )


def test_bench_emit_daemon_provenance(monkeypatch, capsys):
    """A daemon-state 100M entry must not masquerade as a this-run
    measurement: headline_source carries its capture timestamp."""
    import json as json_mod

    import bench

    entry = {
        "name": "groupby_sum_100M_chunked",
        "seconds_median": 0.5,
        "source": "daemon_retry_loop",
        "measured_at": "2026-07-30T12:00:00Z",
    }
    bench._emit([entry], "tpu")
    doc = json_mod.loads(capsys.readouterr().out.strip())
    assert doc["headline_source"] == "daemon_retry_loop(2026-07-30T12:00:00Z)"
    assert doc["value"] == pytest.approx(2e8)
