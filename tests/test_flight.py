"""The flight recorder + Chrome-trace export plane (ISSUE 3 tentpole).

Covers the ring buffer (gating, capacity parsing, wraparound, the
8-writer no-lost/no-torn stress contract), the acceptance-criterion
overhead bound on the disabled path, the dump plane
(``SPARK_RAPIDS_TPU_FLIGHT_DUMP`` + atexit + exit sections), the
Chrome-trace exporter (golden file, schema validity, nesting, the
crash-shaped unterminated/truncated span repairs), the
``tools/trace2chrome.py`` CLI, the resident-table leak report, and the
bench ``flight_tail`` failure-record field.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import config, flight, metrics, tracing

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GOLDEN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data",
    "flight_golden_trace.json",
)


@pytest.fixture(autouse=True)
def _flight_isolated(monkeypatch):
    monkeypatch.delenv("SPARK_RAPIDS_TPU_FLIGHT", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_FLIGHT_DUMP", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_METRICS", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_METRICS_DUMP", raising=False)
    flight.reset()
    metrics.reset()
    flight._WARNED_SPEC = False
    yield
    for f in ("FLIGHT", "FLIGHT_DUMP", "METRICS", "METRICS_DUMP", "TRACE"):
        config.clear_flag(f)
    flight.reset()
    metrics.reset()


class TestGate:
    def test_disabled_by_default(self):
        assert not flight.enabled()
        assert flight.capacity() == 0
        flight.record("I", "x")  # no-op, no crash
        assert flight.tail_records() == []
        assert flight.dropped() == 0

    def test_truthy_enables_default_capacity(self):
        config.set_flag("FLIGHT", True)
        assert flight.enabled()
        assert flight.capacity() == flight.DEFAULT_CAPACITY

    def test_integer_capacity_rounds_to_pow2(self):
        config.set_flag("FLIGHT", "100")
        assert flight.capacity() == 128

    def test_off_values_disable(self):
        for v in ("off", "0", "false", "none", "no"):
            config.set_flag("FLIGHT", v)
            assert not flight.enabled(), v

    def test_dump_path_implies_enabled(self, tmp_path):
        config.set_flag("FLIGHT_DUMP", str(tmp_path / "f.json"))
        assert flight.enabled()
        assert flight.capacity() == flight.DEFAULT_CAPACITY

    def test_invalid_spec_warns_once_and_defaults_on(self, capsys):
        # the log.py invalid-LOG_LEVEL discipline: a typo must not
        # silently disable the crash-telemetry plane
        config.set_flag("FLIGHT", "bogus")
        assert flight.enabled()
        assert flight.capacity() == flight.DEFAULT_CAPACITY
        config.set_flag("FLIGHT", "also-bogus")
        flight.enabled()
        err = capsys.readouterr().err
        assert err.count("[srt][flight][WARN]") == 1

    def test_huge_capacity_clamped(self):
        config.set_flag("FLIGHT", str(1 << 40))
        assert flight.capacity() == flight.MAX_CAPACITY


class TestRing:
    def test_order_and_fields(self):
        config.set_flag("FLIGHT", 64)
        flight.record("B", "spanA")
        flight.record("I", "note", 7)
        flight.record("E", "spanA")
        recs = flight.tail_records()
        assert [r["ph"] for r in recs] == ["B", "I", "E"]
        assert recs[1]["arg"] == 7
        assert "arg" not in recs[0]  # None args are omitted
        assert all(r["tid"] == threading.get_ident() for r in recs)
        # monotonic timestamps + contiguous sequence numbers
        assert recs[0]["t_ns"] <= recs[1]["t_ns"] <= recs[2]["t_ns"]
        assert [r["seq"] for r in recs] == [0, 1, 2]

    def test_wraparound_keeps_newest(self):
        config.set_flag("FLIGHT", 64)
        for i in range(100):
            flight.record("I", "e", i)
        recs = flight.tail_records()
        assert len(recs) == 64
        assert [r["arg"] for r in recs] == list(range(36, 100))
        assert flight.dropped() == 36
        assert [r["arg"] for r in flight.tail_records(10)] == list(
            range(90, 100)
        )

    def test_reset_clears(self):
        config.set_flag("FLIGHT", 64)
        flight.record("I", "x")
        flight.reset()
        assert flight.tail_records() == []


class TestThreadStress:
    def test_no_lost_or_torn_events_under_8_writers(self):
        """Satellite acceptance: 8 writer threads, every event lands
        exactly once with its own thread's payload — the lock-free
        ring's atomicity contract."""
        N, M = 8, 2000
        config.set_flag("FLIGHT", N * M)  # capacity >= total: no drops
        barrier = threading.Barrier(N)

        def writer(t):
            barrier.wait()
            for j in range(M):
                flight.record("I", f"w{t}", (t, j))

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(N)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        recs = flight.tail_records()
        assert len(recs) == N * M  # no lost events
        assert len({r["seq"] for r in recs}) == N * M  # no dupes
        per_writer: dict = {t: [] for t in range(N)}
        tid_of: dict = {}
        for r in recs:
            t, j = r["arg"]
            # no torn events: name and payload were written together
            assert r["name"] == f"w{t}"
            # one OS thread per writer, stable across its events
            assert tid_of.setdefault(t, r["tid"]) == r["tid"]
            per_writer[t].append(j)
        for t in range(N):
            # seq order preserves each writer's program order
            assert per_writer[t] == list(range(M))
        assert len(set(tid_of.values())) == N


class TestOverhead:
    def test_disabled_record_cost_within_budget(self):
        """Acceptance criterion: the disabled-path cost stays ~1us/event.
        The real cost is one cached generation compare (~0.1-0.3us);
        the 5us bound leaves generous CI-noise margin."""
        assert not flight.enabled()
        flight.record("I", "warm")  # warm the gate cache
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            flight.record("I", "x")
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"disabled flight.record costs {per * 1e6:.2f}us"

    def test_enabled_record_cost_bounded(self):
        """The enabled path is a seq fetch + timestamp + slot store —
        order O(100ns)-1us; bound it loosely so a lock or allocation
        sneaking into the hot path fails fast."""
        config.set_flag("FLIGHT", 1 << 14)
        flight.record("I", "warm")
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            flight.record("I", "x")
        per = (time.perf_counter() - t0) / n
        assert per < 5e-5, f"enabled flight.record costs {per * 1e6:.2f}us"


class TestSpansOnFlight:
    def test_flight_only_span_records_begin_end(self):
        """FLIGHT alone (METRICS off) must make spans real: the flight
        timeline is useful precisely when nothing else is on."""
        config.set_flag("FLIGHT", True)
        with metrics.span("solo"):
            pass
        recs = flight.tail_records()
        assert [(r["ph"], r["name"]) for r in recs] == [
            ("B", "solo"), ("E", "solo"),
        ]
        # the metrics registry stayed off
        assert metrics.snapshot()["timers"] == {}

    def test_nested_spans_record_qualified_names(self):
        config.set_flag("FLIGHT", True)
        with metrics.span("outer"):
            with metrics.span("inner"):
                pass
        names = [r["name"] for r in flight.tail_records()]
        assert names == [
            "outer", "outer/inner", "outer/inner", "outer",
        ]

    def test_pad_waste_counter_track_in_flight_only_mode(self):
        """The pad-waste counter track must survive FLIGHT-only mode:
        it keeps its own running total instead of piggybacking on the
        (disabled) metrics byte counter."""
        config.set_flag("FLIGHT", True)
        assert not metrics.enabled()
        n = 1500  # not a bucket size: forces padding to 2048
        k = np.arange(n, dtype=np.int64)
        i64 = int(dt.TypeId.INT64)
        op = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
        rb.table_op_wire(op, [i64], [0], [k.tobytes()], [None], n)
        cs = [
            r for r in flight.tail_records()
            if r["ph"] == "C" and r["name"] == "bucket.pad_waste_bytes"
        ]
        assert cs and cs[-1]["arg"] > 0

    def test_span_exception_records_error_arg(self):
        config.set_flag("FLIGHT", True)
        with pytest.raises(ValueError):
            with metrics.span("doomed"):
                raise ValueError("boom")
        end = flight.tail_records()[-1]
        assert end["ph"] == "E"
        assert end["arg"] == "ValueError"


class TestDump:
    def test_dump_writes_snapshot(self, tmp_path):
        path = str(tmp_path / "flight.json")
        config.set_flag("FLIGHT_DUMP", path)
        flight.record("I", "evt", 1)
        assert flight.dump() == path
        doc = json.loads(open(path).read())
        assert doc["version"] == 1
        assert doc["capacity"] == flight.DEFAULT_CAPACITY
        assert doc["dropped"] == 0
        assert doc["pid"] == os.getpid()
        assert doc["events"][-1]["name"] == "evt"
        assert "epoch_ns" in doc and "anchor_perf_ns" in doc

    def test_dump_without_path_is_noop(self):
        config.set_flag("FLIGHT", True)
        assert flight.dump() is None

    def test_dump_bad_path_warns_not_raises(self, capsys):
        config.set_flag("FLIGHT", True)
        flight.record("I", "x")
        assert flight.dump("/nonexistent-dir/x/flight.json") is None
        assert "[srt][flight][WARN]" in capsys.readouterr().err

    def test_exit_sections_ride_in_snapshot(self):
        config.set_flag("FLIGHT", True)
        flight.register_exit_section("_test_section", lambda: {"k": 1})
        flight.register_exit_section(
            "_test_broken", lambda: 1 / 0
        )
        try:
            snap = flight.snapshot()
        finally:
            flight._EXIT_SECTIONS.pop("_test_section", None)
            flight._EXIT_SECTIONS.pop("_test_broken", None)
        assert snap["sections"]["_test_section"] == {"k": 1}
        # a broken provider degrades to an error record, never raises
        assert "ZeroDivisionError" in snap["sections"]["_test_broken"]["error"]

    def test_atexit_dump_from_env(self, tmp_path):
        """SPARK_RAPIDS_TPU_FLIGHT_DUMP alone turns the recorder on and
        flushes the tail at interpreter exit — and never touches stdout
        (the bench-JSON wire protocol)."""
        dump = tmp_path / "flight.json"
        code = (
            "from spark_rapids_jni_tpu.utils import flight\n"
            "assert flight.enabled()\n"
            "flight.record('I', 'atexit-evt', 42)\n"
        )
        env = dict(os.environ)
        env.update({
            "SPARK_RAPIDS_TPU_FLIGHT_DUMP": str(dump),
            "JAX_PLATFORMS": "cpu",
            "SRT_JAX_PLATFORMS": "cpu",
        })
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=300, env=env, cwd=_ROOT,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert proc.stdout == ""
        doc = json.loads(dump.read_text())
        assert doc["events"][-1]["name"] == "atexit-evt"
        assert doc["events"][-1]["arg"] == 42


class TestChromeExport:
    _SYNTHETIC = [
        {"seq": 0, "t_ns": 1_000, "tid": 11, "ph": "E",
         "name": "wire.deserialize"},
        {"seq": 1, "t_ns": 2_000, "tid": 11, "ph": "B",
         "name": "dispatch.sort_by"},
        {"seq": 2, "t_ns": 3_000, "tid": 11, "ph": "B",
         "name": "dispatch.sort_by/bucketed.sort_by"},
        {"seq": 3, "t_ns": 3_500, "tid": 11, "ph": "I",
         "name": "compile_cache.miss", "arg": "srt_bucketed_sort"},
        {"seq": 4, "t_ns": 6_000, "tid": 11, "ph": "E",
         "name": "dispatch.sort_by/bucketed.sort_by"},
        {"seq": 5, "t_ns": 7_000, "tid": 11, "ph": "E",
         "name": "dispatch.sort_by"},
        {"seq": 6, "t_ns": 7_500, "tid": 22, "ph": "C",
         "name": "resident.live", "arg": 3},
        {"seq": 7, "t_ns": 8_000, "tid": 22, "ph": "B",
         "name": "wire.serialize"},
        {"seq": 8, "t_ns": 9_000, "tid": 22, "ph": "E",
         "name": "wire.serialize", "arg": "ValueError"},
        {"seq": 9, "t_ns": 10_000, "tid": 11, "ph": "B",
         "name": "dispatch.groupby"},
    ]

    def test_matches_golden_file(self):
        """Golden-file pin: the exporter's output for a fixed synthetic
        tail is byte-stable. Regenerate tests/data/flight_golden_trace
        .json deliberately when the schema changes."""
        got = tracing.to_chrome_trace(self._SYNTHETIC)
        want = json.loads(open(_GOLDEN).read())
        assert got == want

    def test_schema_valid(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        assert trace["displayTimeUnit"] == "ms"
        for e in trace["traceEvents"]:
            assert e["ph"] in ("X", "i", "C", "M"), e
            assert isinstance(e["pid"], int)
            assert isinstance(e["tid"], int)
            assert e["name"]
            if e["ph"] != "M":
                assert e["ts"] >= 0.0
            if e["ph"] == "X":
                assert e["dur"] >= 0.0
        # JSON-serializable end to end
        json.dumps(trace)

    def test_category_is_leaf_subsystem(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        cats = {
            e["name"]: e["cat"]
            for e in trace["traceEvents"] if e["ph"] == "X"
        }
        # a nested span is categorized by the subsystem that RAN, not
        # its outermost wrapper
        assert cats["dispatch.sort_by/bucketed.sort_by"] == "bucketed"
        assert cats["dispatch.sort_by"] == "dispatch"

    def test_nesting_preserved(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        by_name = {
            e["name"]: e for e in trace["traceEvents"]
            if e["ph"] == "X"
        }
        outer = by_name["dispatch.sort_by"]
        inner = by_name["dispatch.sort_by/bucketed.sort_by"]
        assert outer["tid"] == inner["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]

    def test_crash_shapes_are_repaired(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        # an E whose B fell off the ring starts at the origin
        trunc = [e for e in xs if e.get("args", {}).get("truncated_begin")]
        assert [e["name"] for e in trunc] == ["wire.deserialize"]
        assert trunc[0]["ts"] == 0.0
        # a B that never ended (the SIGTERM case) runs to the tail end
        unterm = [e for e in xs if e.get("args", {}).get("unterminated")]
        assert [e["name"] for e in unterm] == ["dispatch.groupby"]
        # the errored span carries its exception type
        err = [e for e in xs if e.get("args", {}).get("error")]
        assert err[0]["name"] == "wire.serialize"
        assert err[0]["args"]["error"] == "ValueError"

    def test_counter_and_instant_tracks(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert counters[0]["name"] == "resident.live"
        assert counters[0]["args"]["value"] == 3
        instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
        assert instants[0]["name"] == "compile_cache.miss"
        assert instants[0]["s"] == "t"

    def test_thread_metadata(self):
        trace = tracing.to_chrome_trace(self._SYNTHETIC)
        names = [
            e for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        ]
        assert {e["tid"] for e in names} == {11, 22}

    def test_empty_events(self):
        assert tracing.to_chrome_trace([]) == {
            "traceEvents": [], "displayTimeUnit": "ms",
        }

    def test_traceparent_b_arg_lands_in_x_args(self):
        # the trace layer rides the span's B arg (utils/tracing.py):
        # the exporter must surface it as args.traceparent on the X —
        # including the unterminated crash shape
        tp = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
        events = [
            {"seq": 0, "t_ns": 1_000, "tid": 1, "ph": "B",
             "name": "serving.stream", "arg": tp},
            {"seq": 1, "t_ns": 2_000, "tid": 1, "ph": "E",
             "name": "serving.stream"},
            {"seq": 2, "t_ns": 3_000, "tid": 1, "ph": "B",
             "name": "mesh.stage", "arg": tp},
        ]
        xs = {
            e["name"]: e
            for e in tracing.to_chrome_trace(events)["traceEvents"]
            if e["ph"] == "X"
        }
        assert xs["serving.stream"]["args"]["traceparent"] == tp
        unterm = xs["mesh.stage"]
        assert unterm["args"]["unterminated"] is True
        assert unterm["args"]["traceparent"] == tp

    def test_non_numeric_counter_degrades_to_instant(self):
        # a C sample with a string payload would break the Chrome
        # counter track — it must come back as a visible instant
        events = [
            {"seq": 0, "t_ns": 1_000, "tid": 1, "ph": "C",
             "name": "resident.live", "arg": "3 tables"},
            {"seq": 1, "t_ns": 2_000, "tid": 1, "ph": "C",
             "name": "resident.live", "arg": 3},
        ]
        out = tracing.to_chrome_trace(events)["traceEvents"]
        instants = [e for e in out if e["ph"] == "i"]
        counters = [e for e in out if e["ph"] == "C"]
        assert len(instants) == 1
        assert instants[0]["args"]["arg"] == "3 tables"
        assert len(counters) == 1
        assert counters[0]["args"]["value"] == 3

    def test_older_partial_formats_tolerated(self):
        # non-dict rows and missing seq/tid/t_ns keys (older dumps)
        # must degrade, not crash the postmortem tool
        events = [
            "junk-row",
            None,
            {"ph": "I", "name": "legacy.instant"},
            {"seq": 1, "t_ns": 2_000, "tid": 1, "ph": "B",
             "name": "legacy.span"},
            {"seq": 2, "t_ns": 3_000, "tid": 1, "ph": "E",
             "name": "legacy.span"},
        ]
        out = tracing.to_chrome_trace(events)["traceEvents"]
        assert [e["name"] for e in out if e["ph"] == "i"] == [
            "legacy.instant"
        ]
        assert [e["name"] for e in out if e["ph"] == "X"] == [
            "legacy.span"
        ]

    def test_live_dispatch_covers_three_subsystems(self):
        """Acceptance: a wire dispatch with flight on yields spans from
        >= 3 subsystems (dispatch, wire serde, bucketed) plus a counter
        track once a resident handle moves."""
        config.set_flag("FLIGHT", True)
        config.set_flag("METRICS", True)
        n = 2000
        k = np.arange(n, dtype=np.int64)[::-1].copy()
        i64 = int(dt.TypeId.INT64)
        op = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
        rb.table_op_wire(op, [i64], [0], [k.tobytes()], [None], n)
        tid = rb.table_upload_wire([i64], [0], [k.tobytes()], [None], n)
        rb.table_free(tid)
        trace = tracing.to_chrome_trace(flight.tail_records())
        cats = {
            e["cat"] for e in trace["traceEvents"] if e["ph"] == "X"
        }
        assert {"dispatch", "wire", "bucketed"} <= cats
        counter_names = {
            e["name"] for e in trace["traceEvents"] if e["ph"] == "C"
        }
        assert "resident.live" in counter_names


class TestTrace2ChromeCli:
    def test_converts_flight_dump(self, tmp_path):
        config.set_flag("FLIGHT", True)
        with metrics.span("cfg.smoke"):
            flight.record("I", "note")
        dump_path = str(tmp_path / "flight.json")
        assert flight.dump(dump_path) == dump_path
        out_path = str(tmp_path / "trace.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", "trace2chrome.py"),
             dump_path, "-o", out_path],
            capture_output=True, text=True, timeout=300, cwd=_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        trace = json.loads(open(out_path).read())
        assert any(
            e["ph"] == "X" and e["name"] == "cfg.smoke"
            for e in trace["traceEvents"]
        )
        assert "perfetto" in proc.stdout

    def test_no_events_exits_nonzero(self, tmp_path):
        p = tmp_path / "empty.json"
        p.write_text(json.dumps({"events": []}))
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", "trace2chrome.py"),
             str(p)],
            capture_output=True, text=True, timeout=300, cwd=_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert "no flight events" in proc.stderr


class TestLeakReport:
    def test_leaked_table_lists_allocating_span_stack(self):
        config.set_flag("METRICS", True)
        config.set_flag("FLIGHT", True)
        t = Table([Column.from_numpy(np.arange(64, dtype=np.int64))], ["k"])
        with metrics.span("cfg.load"):
            with metrics.span("upload"):
                tid = rb._resident_put(t)
        try:
            leaks = [
                r for r in rb.leak_report() if r["table_id"] == tid
            ]
            assert len(leaks) == 1
            rec = leaks[0]
            assert rec["rows"] == 64
            assert rec["columns"] == 1
            assert rec["allocated_under"] == ["cfg.load", "cfg.load/upload"]
            assert rec["approx_bytes"] > 0
            assert rec["age_s"] >= 0.0
            # the flight dump embeds the same report
            snap = flight.snapshot()
            ids = {
                r["table_id"]
                for r in snap["sections"]["resident_leaks"]
            }
            assert tid in ids
            json.dumps(snap)
        finally:
            rb.table_free(tid)
        assert all(
            r["table_id"] != tid for r in rb.leak_report()
        )


class TestBenchFlightTail:
    def test_failure_record_grows_flight_tail(self):
        """Satellite acceptance: 'device unreachable' is never again a
        bare string — the failure record carries the last flight events."""
        import bench

        config.set_flag("FLIGHT", True)
        flight.record("I", "tunnel.probe_failed", 1)
        flight.record("I", "tunnel.probe_retry")
        rec = bench._failure_record(
            "join", "device unreachable", exc_type="DeviceUnreachable",
        )
        tail = rec["failure"]["flight_tail"]
        assert [e["name"] for e in tail[-2:]] == [
            "tunnel.probe_failed", "tunnel.probe_retry",
        ]
        json.dumps(rec)

    def test_failure_record_without_flight_stays_lean(self):
        import bench

        assert not flight.enabled()
        rec = bench._failure_record("join", ValueError("boom"))
        assert "flight_tail" not in rec["failure"]

    def test_skip_records_stay_lean(self):
        """A fast-fail batch creates N skip records back to back — each
        embedding the same 40-event tail would multiply the headline
        JSON for zero information. Only ran-and-died records carry it."""
        import bench

        config.set_flag("FLIGHT", True)
        flight.record("I", "device.unreachable", "join")
        rec = bench._failure_record(
            "sort", "skipped: device unreachable (fast-fail after join)",
            exc_type="DeviceUnreachable", skipped=True,
        )
        assert "flight_tail" not in rec["failure"]
