"""The query-profiler plane (ISSUE 8 tentpole).

Covers the attribution invariants the EXPLAIN ANALYZE report rests on:
per-segment compile/execute/serde/stall splits sum to the session wall
time, a forced compile-cache miss shows up as compile time on exactly
the segment that launched it, multi-process merges preserve every
session and every flight event on one wall-clock-ordered timeline, the
disabled path stays in the metrics-gate overhead class, the
``(pid, host, session_id)`` stamping of flight dumps, the leak report's
``logical_rows``/bytes fields, and the ``tools/explain.py`` renderer.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import (
    buckets,
    config,
    flight,
    metrics,
    profiler,
    tracing,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)

# the bench fused_plan chain: one 4-op fused segment
CHAIN = [
    {"op": "filter", "mask": 2},
    {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
    {"op": "sort_by", "keys": [{"column": 0}]},
    {"op": "groupby", "by": [0],
     "aggs": [{"column": 1, "agg": "sum"}]},
]


@pytest.fixture(autouse=True)
def _profiler_isolated(monkeypatch):
    for env in ("PROFILE", "PROFILE_DUMP", "FLIGHT", "FLIGHT_DUMP",
                "METRICS", "METRICS_DUMP", "PLANSTATS", "PLANSTATS_DIR"):
        monkeypatch.delenv("SPARK_RAPIDS_TPU_" + env, raising=False)
        # a flag OVERRIDE leaked by an earlier module (bench helpers
        # run in-process set PROFILE/METRICS/FLIGHT/PLANSTATS_DIR)
        # beats the env
        config.clear_flag(env)
    profiler.reset()
    flight.reset()
    metrics.reset()
    yield
    for f in ("PROFILE", "PROFILE_DUMP", "FLIGHT", "FLIGHT_DUMP",
              "METRICS", "METRICS_DUMP", "PLANSTATS", "PLANSTATS_DIR"):
        config.clear_flag(f)
    profiler.reset()
    flight.reset()
    metrics.reset()


def _wire_inputs(n=2500, seed=7):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 100, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    m = (v > 0).astype(np.uint8)
    return (
        [I64, I64, B8], [0, 0, 0],
        [k.tobytes(), v.tobytes(), m.tobytes()],
        [None, None, None], n,
    )


def _run_chain(plan=None):
    return rb.table_plan_wire(json.dumps(plan or CHAIN), *_wire_inputs())


class TestGate:
    def test_disabled_by_default(self):
        assert not profiler.enabled()
        assert not profiler.session_active()
        scope = profiler.maybe_session([], label="x")
        assert scope is profiler._NULL_SCOPE
        _run_chain()
        assert profiler.sessions() == []

    def test_flag_enables_auto_sessions(self):
        config.set_flag("PROFILE", "on")
        assert profiler.enabled()
        _run_chain()
        docs = profiler.sessions()
        assert len(docs) == 1
        assert docs[0]["label"] == "plan_wire"

    def test_dump_path_implies_profile(self, tmp_path):
        config.set_flag("PROFILE_DUMP", str(tmp_path / "p.json"))
        assert profiler.enabled()

    def test_hooks_without_session_are_noops(self):
        profiler.note_cache(True)
        profiler.note_compile("x", 0.1)
        profiler.note_serde("in", 0.1, 10)
        profiler.note_stall(0.1)
        profiler.note_pad(1, 2)
        profiler.note_donation(3)
        profiler.note_fallback("fused")
        profiler.note_shuffle(4)
        assert profiler.segment_begin(0, "fused", CHAIN) is None
        profiler.segment_end(None)
        assert profiler.sessions() == []


class TestAttribution:
    def test_splits_sum_to_session_wall(self):
        """The acceptance invariant: per-segment splits + boundary +
        unattributed == session wall, by construction."""
        config.set_flag("PROFILE", "on")
        _run_chain(CHAIN + [{"op": "concat"}])
        doc = profiler.sessions()[-1]
        segs = doc["segments"]
        assert len(segs) >= 2  # the fused run + the exact boundary op
        assert {s["kind"] for s in segs} == {"fused", "exact"}
        for s in segs:
            total = (
                s["compile_s"] + s["execute_s"] + s["serde_s"]
                + s["stall_s"]
            )
            assert total == pytest.approx(s["wall_s"], abs=1e-9)
        b = doc["boundary"]
        covered = (
            sum(s["wall_s"] for s in segs)
            + b["serde_s"] + b["stall_s"] + b["compile_s"]
            + doc["unattributed_s"]
        )
        assert covered == pytest.approx(doc["wall_s"], rel=1e-6)
        # the wire upload/download happened outside any segment
        assert b["serde_bytes_in"] > 0 and b["serde_bytes_out"] > 0

    def test_forced_cache_miss_is_compile_time_on_fused_segment(self):
        config.set_flag("PROFILE", "on")
        buckets.cache_clear()
        _run_chain()
        cold = profiler.sessions()[-1]["segments"][0]
        assert cold["kind"] == "fused"
        assert cold["cache_misses"] >= 1
        assert cold["compile_s"] > 0
        # the compile dominates the cold fused segment's wall
        assert cold["compile_s"] > 0.5 * cold["wall_s"]
        # warm rerun of the SAME plan: hit, no compile attributed
        _run_chain()
        warm = profiler.sessions()[-1]["segments"][0]
        assert warm["cache_hits"] >= 1
        assert warm["cache_misses"] == 0
        assert warm["compile_s"] == 0.0

    def test_rows_and_launches_per_segment(self):
        config.set_flag("PROFILE", "on")
        out = _run_chain()
        seg = profiler.sessions()[-1]["segments"][0]
        assert seg["rows_in"] == 2500
        assert seg["rows_out"] == out[4]
        assert seg["launches"] == seg["cache_hits"] + seg["cache_misses"]
        assert seg["launches"] >= 1
        assert seg["ops"] == [op["op"] for op in CHAIN]

    def test_explicit_session_scopes_resident_plan(self):
        t = Table(
            [Column.from_numpy(np.arange(4096, dtype=np.int64))], ["k"]
        )
        with profiler.profile_session(
            [{"op": "sort_by", "keys": [{"column": 0}]}], label="manual"
        ) as prof:
            tid = rb._resident_put(t)
            res = rb.table_plan_resident(
                json.dumps([{"op": "sort_by", "keys": [{"column": 0}]}]),
                [tid],
            )
            rb.table_num_rows(res)
            rb.table_free(tid)
            rb.table_free(res)
        assert prof.session_id
        doc = profiler.sessions()[-1]
        assert doc["session_id"] == prof.session_id
        assert doc["label"] == "manual"
        assert len(doc["segments"]) >= 1

    def test_stream_session_accumulates_batches(self):
        config.set_flag("PROFILE", "on")
        plan = [{"op": "sort_by", "keys": [{"column": 0}]}]
        rng = np.random.default_rng(3)
        batches = []
        for n in (1500, 1700):
            k = rng.integers(0, 50, n, dtype=np.int64)
            batches.append(([I64], [0], [k.tobytes()], [None], n))
        outs = rb.table_stream_wire(json.dumps(plan), batches)
        assert len(outs) == 2
        doc = profiler.sessions()[-1]
        assert doc["label"] == "stream"
        assert doc["batches"] == 2
        seg = doc["segments"][0]
        assert seg["calls"] == 2
        assert seg["rows_in"] == 1500 + 1700


class TestMergeSessions:
    def _two_process_docs(self):
        config.set_flag("PROFILE", "on")
        _run_chain()
        d1 = profiler.sessions()[-1]
        # the second process: same shape, different identity, later
        d2 = json.loads(json.dumps(d1))
        d2["pid"] = d1["pid"] + 1
        d2["host"] = "otherhost"
        d2["session_id"] = "f" * 16
        d2["epoch_ns"] = d1["epoch_ns"] + 1_000_000
        return d1, d2

    def test_merge_preserves_and_orders_sessions(self):
        d1, d2 = self._two_process_docs()
        merged = profiler.merge_sessions([
            {"version": 1, "sessions": [d2]},
            {"version": 1, "sessions": [d1]},
        ])
        ids = [s["session_id"] for s in merged["sessions"]]
        assert ids == [d1["session_id"], d2["session_id"]]  # epoch order
        procs = {
            (p["host"], p["pid"]) for p in merged["processes"]
        }
        assert procs == {
            (d1["host"], d1["pid"]), ("otherhost", d1["pid"] + 1),
        }

    def test_merge_accepts_flight_dumps(self):
        d1, d2 = self._two_process_docs()
        fd = {"version": 1, "events": [],
              "sections": {"profile_sessions": [d1]}}
        merged = profiler.merge_sessions([fd, d2])
        assert len(merged["sessions"]) == 2

    def test_merged_chrome_trace_preserves_every_event(self):
        """Two single-process dumps -> ONE timeline: every event
        survives, processes get distinct pids + name metadata, and
        wall-clock alignment orders them as they actually happened."""
        d1 = {
            "pid": 100, "host": "hosta",
            "epoch_ns": 1_000_000_000, "anchor_perf_ns": 500,
            "events": [
                {"seq": 0, "t_ns": 600, "tid": 1, "ph": "I", "name": "a0"},
                {"seq": 1, "t_ns": 900, "tid": 1, "ph": "I", "name": "a1"},
            ],
        }
        d2 = {
            "pid": 100, "host": "hostb",  # pid COLLIDES across hosts
            "epoch_ns": 1_000_000_000, "anchor_perf_ns": 100,
            "events": [
                {"seq": 0, "t_ns": 350, "tid": 7, "ph": "I", "name": "b0"},
            ],
        }
        trace = tracing.merge_chrome_traces([d1, d2])
        evs = trace["traceEvents"]
        inst = {e["name"]: e for e in evs if e["ph"] == "i"}
        assert set(inst) == {"a0", "a1", "b0"}  # nothing dropped
        assert len({e["pid"] for e in evs}) == 2  # collision bumped
        names = {
            e["args"]["name"] for e in evs if e["name"] == "process_name"
        }
        assert names == {"hosta:100", "hostb:100"}
        assert any(e["name"] == "process_sort_index" for e in evs)
        # wall order: a0 @ wall 1e9+100, b0 @ 1e9+250, a1 @ 1e9+400
        assert inst["a0"]["ts"] < inst["b0"]["ts"] < inst["a1"]["ts"]

    def test_session_id_labels_merged_process_track(self):
        d = {
            "pid": 5, "host": "h", "session_id": "abcd1234ffff0000",
            "epoch_ns": 10, "anchor_perf_ns": 1,
            "events": [
                {"seq": 0, "t_ns": 2, "tid": 1, "ph": "I", "name": "x"},
            ],
        }
        trace = tracing.merge_chrome_traces([d])
        names = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e["name"] == "process_name"
        }
        assert names == {"h:5 [abcd1234]"}


class TestDisabledOverhead:
    def test_disabled_hook_cost_within_metrics_gate_class(self):
        """The acceptance bound: with no session open, a profiler hook
        costs one module-global load + branch — the metrics/flight gate
        class (same budget as test_flight's disabled record)."""
        assert not profiler.session_active()
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            profiler.note_cache(True)
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"disabled note_cache costs {per * 1e6:.2f}us"

    def test_disabled_maybe_session_cost_within_budget(self):
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            with profiler.maybe_session(None, label="x"):
                pass
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"disabled maybe_session {per * 1e6:.2f}us"


class TestFlightStamping:
    def test_snapshot_carries_pid_host_and_session_id(self):
        config.set_flag("FLIGHT", True)
        with profiler.profile_session([], label="stamp") as prof:
            flight.record("I", "inside")
            snap = flight.snapshot()
        assert snap["pid"] == os.getpid()
        assert snap["host"]
        assert snap["session_id"] == prof.session_id

    def test_sessions_ride_flight_dump_sections(self):
        config.set_flag("FLIGHT", True)
        with profiler.profile_session([], label="ride"):
            pass
        snap = flight.snapshot()
        docs = snap["sections"]["profile_sessions"]
        assert docs and docs[-1]["label"] == "ride"


class TestLeakReportBytes:
    def test_leak_report_has_logical_rows_and_bytes(self):
        config.set_flag("METRICS", True)
        t = Table(
            [Column.from_numpy(np.arange(128, dtype=np.int64))], ["k"]
        )
        tid = rb._resident_put(t)
        try:
            rec = next(
                r for r in rb.leak_report() if r["table_id"] == tid
            )
            assert rec["logical_rows"] == 128
            assert rec["rows"] == 128  # back-compat field
            assert rec["approx_bytes"] >= 128 * 8
        finally:
            rb.table_free(tid)

    def test_leak_record_names_allocating_session(self):
        config.set_flag("METRICS", True)
        t = Table(
            [Column.from_numpy(np.arange(16, dtype=np.int64))], ["k"]
        )
        with profiler.profile_session([], label="alloc") as prof:
            tid = rb._resident_put(t)
        try:
            rec = next(
                r for r in rb.leak_report() if r["table_id"] == tid
            )
            assert rec["session"] == prof.session_id
        finally:
            rb.table_free(tid)


class TestExplainRenderer:
    def _explain(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "explain", os.path.join(_ROOT, "tools", "explain.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_report_names_every_plan_op_and_splits(self):
        config.set_flag("PROFILE", "on")
        _run_chain(CHAIN + [{"op": "concat"}])
        doc = profiler.sessions()[-1]
        text = self._explain().render_session(doc)
        for op in [o["op"] for o in CHAIN] + ["concat"]:
            assert op in text
        assert "fused" in text and "exact" in text
        assert "compile" in text and "execute" in text
        assert "serde" in text and "stall" in text
        assert doc["session_id"] in text

    def test_merged_report_lists_both_processes(self):
        config.set_flag("PROFILE", "on")
        _run_chain()
        d1 = profiler.sessions()[-1]
        d2 = json.loads(json.dumps(d1))
        d2["pid"], d2["host"], d2["session_id"] = 1, "peer", "e" * 16
        mod = self._explain()
        merged = profiler.merge_sessions([d1, d2])
        text = mod.render_merged(merged)
        assert "2 process(es)" in text
        assert f"{d1['host']}:{d1['pid']}" in text
        assert "peer:1" in text

    def test_extract_sessions_from_bench_profile_block(self):
        config.set_flag("PROFILE", "on")
        _run_chain()
        doc = profiler.sessions()[-1]
        bench_doc = {
            "configs": [
                {"name": "fused_plan",
                 "profile": {"sessions": 3, "segments": [],
                             "sessions_tail": [doc]}},
            ]
        }
        got = profiler.extract_sessions(bench_doc)
        assert [s["session_id"] for s in got] == [doc["session_id"]]


class TestDumpPlane:
    def test_dump_and_reload_roundtrip(self, tmp_path):
        config.set_flag("PROFILE", "on")
        _run_chain()
        path = str(tmp_path / "profile.json")
        assert profiler.dump(path) == path
        doc = json.loads(open(path).read())
        assert doc["pid"] == os.getpid()
        got = profiler.extract_sessions(doc)
        assert len(got) == 1
        assert got[0]["segments"]

    def test_dump_bad_path_warns_not_raises(self, capsys):
        config.set_flag("PROFILE", "on")
        _run_chain()
        assert profiler.dump("/nonexistent-dir/x/p.json") is None
        assert "[srt][profiler][WARN]" in capsys.readouterr().err
