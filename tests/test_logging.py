"""The runtime observability plane (utils/log.py): the
RMM_LOGGING_LEVEL role (reference pom.xml:82) — HBM plan decisions,
live handle counts, level gating."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import config, hbm, log


@pytest.fixture(autouse=True)
def _reset_flags(monkeypatch):
    # pin a known baseline: an exported SPARK_RAPIDS_TPU_*LOG_LEVEL in
    # the developer's shell must not flip these assertions
    monkeypatch.delenv("SPARK_RAPIDS_TPU_LOG_LEVEL", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL", raising=False)
    log._WARNED_INVALID.clear()  # one-time warnings: once per TEST
    yield
    config.clear_flag("LOG_LEVEL")
    config.clear_flag("ALLOC_LOG_LEVEL")
    log._WARNED_INVALID.clear()


def _table(n=64):
    return Table(
        [
            Column.from_numpy(np.arange(n, dtype=np.int64)),
            Column.from_numpy(np.arange(n, dtype=np.int64)),
        ],
        ["k", "v"],
    )


def test_silent_by_default(capsys):
    log.log("ERROR", "general", "should not appear")
    hbm.join_plan(_table(), _table(), ["k"], ["k"])
    assert "[srt]" not in capsys.readouterr().err


def test_hbm_plan_decision_surfaces(capsys):
    config.set_flag("LOG_LEVEL", "INFO")
    hbm.join_plan(_table(), _table(), ["k"], ["k"])
    err = capsys.readouterr().err
    assert "[srt][hbm][INFO] join_plan" in err
    assert "probe_rows=" in err and "fits=" in err


def test_handle_counts_surface(capsys):
    from spark_rapids_jni_tpu import runtime_bridge as rb

    config.set_flag("ALLOC_LOG_LEVEL", "DEBUG")
    tid = rb._resident_put(_table(8))
    rb.table_free(tid)
    err = capsys.readouterr().err
    assert "[srt][handles][DEBUG] resident_put" in err
    assert "[srt][handles][DEBUG] table_free" in err
    assert "live=" in err


def test_alloc_level_overrides_only_alloc_channels(capsys):
    # ALLOC_LOG_LEVEL=DEBUG must open hbm/handles but leave the general
    # channel gated by LOG_LEVEL (still OFF)
    config.set_flag("ALLOC_LOG_LEVEL", "DEBUG")
    log.log("INFO", "general", "general-line")
    log.log("DEBUG", "hbm", "hbm-line")
    err = capsys.readouterr().err
    assert "general-line" not in err
    assert "hbm-line" in err


def test_level_ordering(capsys):
    config.set_flag("LOG_LEVEL", "WARN")
    log.log("ERROR", "tunnel", "e")
    log.log("WARN", "tunnel", "w")
    log.log("INFO", "tunnel", "i")
    err = capsys.readouterr().err
    assert "[srt][tunnel][ERROR] e" in err
    assert "[srt][tunnel][WARN] w" in err
    assert " i" not in err


def test_flag_documented():
    assert "LOG_LEVEL" in config.describe_flags()


def test_alloc_off_silences_even_under_debug(capsys):
    # the override works in the QUIET direction too
    config.set_flag("LOG_LEVEL", "DEBUG")
    config.set_flag("ALLOC_LOG_LEVEL", "OFF")
    log.log("DEBUG", "handles", "handle-line")
    log.log("DEBUG", "tunnel", "tunnel-line")
    err = capsys.readouterr().err
    assert "handle-line" not in err
    assert "tunnel-line" in err


def test_invalid_alloc_level_falls_back(capsys):
    config.set_flag("LOG_LEVEL", "INFO")
    config.set_flag("ALLOC_LOG_LEVEL", "VERBOSE")  # typo'd value
    log.log("INFO", "hbm", "hbm-line")
    assert "hbm-line" in capsys.readouterr().err


def test_invalid_log_level_warns_once_and_names_value(capsys):
    # the pre-fix behavior mapped a typo silently to OFF — the one user
    # who opted into logging got total silence with no indication why
    config.set_flag("LOG_LEVEL", "CHATTY")
    log.log("ERROR", "general", "first")
    err = capsys.readouterr().err
    assert "[srt][log][WARN]" in err
    assert "CHATTY" in err and "SPARK_RAPIDS_TPU_LOG_LEVEL" in err
    # one-time: a second gated call must not repeat the warning
    log.log("ERROR", "general", "second")
    assert "CHATTY" not in capsys.readouterr().err


def test_invalid_log_level_falls_back_to_default(capsys):
    # fallback target is the DECLARED default, not hardcoded OFF
    config.set_flag("LOG_LEVEL", "NOPE")
    assert not log.enabled("ERROR")
    assert log._resolve_level("general") == log.LEVELS[
        str(config.flag_default("LOG_LEVEL"))
    ]


def test_invalid_alloc_level_warns_once(capsys):
    config.set_flag("LOG_LEVEL", "INFO")
    config.set_flag("ALLOC_LOG_LEVEL", "VERBOSE")
    log.log("INFO", "hbm", "a")
    err = capsys.readouterr().err
    assert "SPARK_RAPIDS_TPU_ALLOC_LOG_LEVEL" in err
    assert "VERBOSE" in err
    log.log("INFO", "hbm", "b")
    assert "VERBOSE" not in capsys.readouterr().err
