"""srt-check static analyzer: every pass, pragma grammar, baseline.

Each pass gets a violating fixture and a clean fixture; the pragma and
baseline machinery get their own coverage; and the repo itself must
scan clean against the committed baseline (the CI gate this tool backs
— see ci/premerge-build.sh).
"""

import importlib.util
import json
import os
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "srt_check", os.path.join(REPO_ROOT, "tools", "srt_check.py")
)
srt = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(srt)

# package-relative paths: SRT002/SRT003 only fire inside the runtime
# package, and utils/config.py is SRT001's one sanctioned home
PKG = "spark_rapids_jni_tpu"


def scan(tmp_path, rel, src):
    full = tmp_path / rel
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text(textwrap.dedent(src))
    return srt.scan_file(str(full), str(tmp_path))


def passes_of(findings):
    return [f.pass_id for f in findings]


class TestEnvOutsideConfig:
    def test_prefixed_read_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            V = os.environ.get("SPARK_RAPIDS_TPU_FOO", "0")
        """)
        assert passes_of(got) == ["SRT001"]
        assert "SPARK_RAPIDS_TPU_FOO" in got[0].message

    def test_all_read_shapes_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            A = os.getenv("SPARK_RAPIDS_TPU_A")
            B = os.environ["SPARK_RAPIDS_TPU_B"]
            C = "SPARK_RAPIDS_TPU_C" in os.environ
        """)
        assert passes_of(got) == ["SRT001"] * 3

    def test_config_py_exempt(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/config.py", """
            import os
            V = os.environ.get("SPARK_RAPIDS_TPU_FOO")
        """)
        assert got == []

    def test_write_is_not_a_read(self, tmp_path):
        # tests and fixtures SET knobs through the environment; only
        # reads bypass the flag plane
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            os.environ["SPARK_RAPIDS_TPU_FOO"] = "1"
        """)
        assert got == []

    def test_unprefixed_module_level_read_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            HOME = os.environ.get("HOME")
        """)
        assert got == []


class TestBroadExcept:
    def test_swallow_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                except Exception:
                    return None
        """)
        assert passes_of(got) == ["SRT002"]

    def test_bare_reraise_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                except Exception:
                    cleanup()
                    raise
        """)
        assert got == []

    def test_faults_routing_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import faults
            def f():
                try:
                    g()
                except Exception as e:
                    raise faults.classify(e, "foo")
        """)
        assert got == []

    def test_breaker_feed_counts_as_routing(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f(breaker):
                try:
                    g()
                except BaseException as e:
                    breaker.note_failure(e)
        """)
        assert got == []

    def test_pragma_suppresses(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                except Exception:  # srt: allow-broad-except(best-effort cleanup)
                    return None
        """)
        assert got == []

    def test_pragma_on_line_above_suppresses(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                # srt: allow-broad-except(best-effort cleanup)
                except Exception:
                    return None
        """)
        assert got == []

    def test_outside_package_not_flagged(self, tmp_path):
        # bench.py / tools are offline drivers without the taxonomy
        got = scan(tmp_path, "tools/foo.py", """
            def f():
                try:
                    g()
                except Exception:
                    return None
        """)
        assert got == []


class TestHotEnvRead:
    def test_read_in_function_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            def hot():
                return os.environ.get("SOME_KNOB") == "1"
        """)
        assert passes_of(got) == ["SRT003"]

    def test_module_level_read_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            KNOB = os.environ.get("SOME_KNOB")
        """)
        assert got == []

    def test_prefixed_in_function_reports_srt001_once(self, tmp_path):
        # one finding per site: the sharper pass wins, no double report
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            def hot():
                return os.environ.get("SPARK_RAPIDS_TPU_FOO")
        """)
        assert passes_of(got) == ["SRT001"]


class TestWallclockInReplay:
    def test_time_time_in_faults_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/faults.py", """
            import time
            def decide():
                return time.time() % 2 == 0
        """)
        assert passes_of(got) == ["SRT004"]

    def test_random_in_buckets_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/buckets.py", """
            import random
            def pick():
                return random.random()
        """)
        assert passes_of(got) == ["SRT004"]

    def test_monotonic_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/faults.py", """
            import time
            def interval():
                return time.monotonic()
        """)
        assert got == []

    def test_other_modules_unscoped(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import time
            def now():
                return time.time()
        """)
        assert got == []


class TestRetryOnDonated:
    def test_donated_retry_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import faults
            def f(exe, table):
                return faults.run_with_retry(
                    lambda: exe(table, donate=True), site="seg"
                )
        """)
        assert passes_of(got) == ["SRT005"]

    def test_donate_false_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import faults
            def f(exe, table):
                return faults.run_with_retry(
                    lambda: exe(table, donate=False), site="seg"
                )
        """)
        assert got == []


class TestMetricNameConvention:
    def test_bad_shape_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import metrics
            def f():
                metrics.counter_add("Bad Name")
        """)
        assert passes_of(got) == ["SRT006"]

    def test_unregistered_namespace_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import metrics
            def f():
                metrics.counter_add("nonexistentns.thing")
        """)
        assert passes_of(got) == ["SRT006"]
        assert "nonexistentns" in got[0].message

    def test_registered_dotted_name_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import flight, metrics
            def f():
                metrics.counter_add("op.groupby.calls")
                metrics.bytes_add("wire.bytes_in", 4)
                flight.record("I", "spill.evict", 1)
        """)
        assert got == []

    def test_dynamic_names_skipped(self, tmp_path):
        # computed names can't be checked statically — not a finding
        got = scan(tmp_path, f"{PKG}/foo.py", """
            from .utils import metrics
            def f(name):
                metrics.counter_add("op." + name)
        """)
        assert got == []


BENCH_OK = """
    _SUBPROCESS_CONFIGS = {
        "groupby": lambda p: None,
        "join": lambda p: None,
    }
    _ARM_TIERS = {
        "groupby": "headline",
        "join": "manual",
    }
"""


class TestBenchArmTier:
    def test_missing_table_flagged(self, tmp_path):
        got = scan(tmp_path, "mybench.py", """
            _SUBPROCESS_CONFIGS = {"groupby": lambda p: None}
        """)
        assert passes_of(got) == ["SRT007"]

    def test_untiered_arm_flagged(self, tmp_path):
        got = scan(tmp_path, "mybench.py", """
            _SUBPROCESS_CONFIGS = {
                "groupby": lambda p: None,
                "join": lambda p: None,
            }
            _ARM_TIERS = {"groupby": "headline"}
        """)
        assert passes_of(got) == ["SRT007"]
        assert "join" in got[0].message

    def test_invalid_tier_and_stale_entry_flagged(self, tmp_path):
        got = scan(tmp_path, "mybench.py", """
            _SUBPROCESS_CONFIGS = {"groupby": lambda p: None}
            _ARM_TIERS = {
                "groupby": "nightly",
                "ghost": "extended",
            }
        """)
        assert sorted(passes_of(got)) == ["SRT007", "SRT007"]
        msgs = " ".join(f.message for f in got)
        assert "nightly" in msgs and "ghost" in msgs

    def test_full_table_clean(self, tmp_path):
        assert scan(tmp_path, "mybench.py", BENCH_OK) == []

    def test_non_bench_module_exempt(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            X = 1
        """)
        assert got == []


class TestStatsAppend:
    def test_raw_append_in_planstats_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/planstats.py", """
            def sneak(path):
                return open(path, "ab")
        """)
        assert "SRT010" in passes_of(got)

    def test_helper_site_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/planstats.py", """
            def _open_append(path):
                return open(path, "ab")
        """)
        assert got == []

    def test_read_mode_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/planstats.py", """
            def load(path):
                with open(path, "rb") as f:
                    return f.read()
        """)
        assert got == []

    def test_mode_keyword_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/planstats.py", """
            def sneak(path):
                return open(path, mode="a")
        """)
        assert "SRT010" in passes_of(got)

    def test_stats_path_append_elsewhere_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def dump(planstats_path, rec):
                with open(planstats_path, "a") as f:
                    f.write(rec)
        """)
        assert "SRT010" in passes_of(got)

    def test_stats_dirname_literal_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            import os
            def dump(rec):
                with open(os.path.join("/tmp/srt-planstats", "x.wal"),
                          "ab") as f:
                    f.write(rec)
        """)
        assert "SRT010" in passes_of(got)

    def test_unrelated_append_elsewhere_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def log(path, line):
                with open(path, "a") as f:
                    f.write(line)
        """)
        assert got == []

    def test_pragma_suppresses(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/utils/planstats.py", """
            def migrate(path):
                # srt: allow-stats-append(one-shot v0 store migration)
                return open(path, "ab")
        """)
        assert got == []

    def test_repo_planstats_has_one_sanctioned_site(self):
        # the shipped module must route every append through the helper
        findings = srt.scan_file(os.path.join(
            REPO_ROOT, PKG, "utils", "planstats.py"
        ))
        assert [f for f in findings if f.pass_id == "SRT010"] == []


class TestPragmaGrammar:
    def test_empty_reason_is_a_finding(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                except Exception:  # srt: allow-broad-except()
                    return None
        """)
        # the pragma doesn't suppress AND is itself flagged
        assert sorted(passes_of(got)) == ["SRT000", "SRT002"]

    def test_unknown_slug_is_a_finding(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            X = 1  # srt: allow-everything(why not)
        """)
        assert passes_of(got) == ["SRT000"]
        assert "allow-everything" in got[0].message

    def test_docstring_mention_is_not_a_pragma(self, tmp_path):
        # only real COMMENT tokens parse as pragmas: docs quoting the
        # grammar (like this tool's own docstring) are inert
        got = scan(tmp_path, f"{PKG}/foo.py", '''
            """Docs: write # srt: allow-broad-except(reason) above it."""
            MSG = "add '# srt: allow-broad-except(<reason>)' if deliberate"
        ''')
        assert got == []

    def test_wrong_slug_does_not_suppress(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/foo.py", """
            def f():
                try:
                    g()
                except Exception:  # srt: allow-wallclock(wrong pass)
                    return None
        """)
        assert "SRT002" in passes_of(got)


class TestBaseline:
    SRC = """
        import os
        V = os.environ.get("SPARK_RAPIDS_TPU_FOO")
    """

    def _write(self, tmp_path):
        full = tmp_path / PKG / "foo.py"
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(self.SRC))
        return full

    def test_new_finding_fails_baselined_passes(self, tmp_path, capsys):
        self._write(tmp_path)
        bl = tmp_path / "baseline.json"
        argv = [f"{PKG}/foo.py", "--root", str(tmp_path),
                "--baseline", str(bl)]
        assert srt.main(argv) == 1  # new finding -> gate fails
        assert srt.main(argv + ["--write-baseline"]) == 0
        assert srt.main(argv) == 0  # grandfathered -> passes
        out = capsys.readouterr().out
        assert "[baselined]" in out

    def test_fixed_finding_reports_stale_entry(self, tmp_path):
        full = self._write(tmp_path)
        bl = tmp_path / "baseline.json"
        argv = [f"{PKG}/foo.py", "--root", str(tmp_path),
                "--baseline", str(bl)]
        srt.main(argv + ["--write-baseline"])
        full.write_text("V = None\n")  # fix the violation
        findings = srt.scan_file(str(full), str(tmp_path))
        assert findings == []
        doc = json.loads(bl.read_text())
        assert len(doc["fingerprints"]) == 1  # now stale, prunable

    def test_fingerprint_survives_line_motion(self, tmp_path):
        full = self._write(tmp_path)
        before = srt.scan_file(str(full), str(tmp_path))[0].fingerprint
        full.write_text("# a comment\n\n" + textwrap.dedent(self.SRC))
        after = srt.scan_file(str(full), str(tmp_path))[0].fingerprint
        assert before == after  # content-hashed, not line-numbered

    def test_json_output_shape(self, tmp_path, capsys):
        self._write(tmp_path)
        rc = srt.main([f"{PKG}/foo.py", "--root", str(tmp_path),
                       "--baseline", str(tmp_path / "none.json"),
                       "--json"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["counts"]["new"] == 1
        f = doc["findings"][0]
        assert f["pass"] == "SRT001"
        assert f["path"].endswith("foo.py") and f["line"] >= 1


class TestRepoClean:
    def test_repo_scans_clean_against_committed_baseline(self):
        """The acceptance gate: the tree + tools/srt_check_baseline.json
        must make `python tools/srt_check.py` exit 0."""
        findings = srt.scan_repo(repo_root=REPO_ROOT)
        baseline = srt.load_baseline(srt.DEFAULT_BASELINE)
        new = [f.render() for f in findings
               if f.fingerprint not in baseline]
        assert new == []

    def test_bench_tiers_cover_every_arm(self):
        # import-light re-statement of SRT007 against the real bench.py
        findings = srt.scan_file(
            os.path.join(REPO_ROOT, "bench.py"), REPO_ROOT
        )
        assert [f for f in findings if f.pass_id == "SRT007"] == []


class TestHostSync:
    def test_item_flagged_in_hot_module(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/plan.py", """
            def f(col):
                return col.data.item()
        """)
        assert passes_of(got) == ["SRT009"]
        assert "sync" in got[0].message

    def test_int_over_device_local_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/bucketed.py", """
            import jax.numpy as jnp

            def f(a):
                count = jnp.sum(a)
                return int(count)
        """)
        assert passes_of(got) == ["SRT009"]
        assert "int()" in got[0].message

    def test_np_asarray_flagged(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/plan.py", """
            import numpy as np

            def f(x):
                return np.asarray(x)
        """)
        assert passes_of(got) == ["SRT009"]

    def test_host_attr_reads_are_clean(self, tmp_path):
        # Table/Column bookkeeping is host data — int() over it is free
        got = scan(tmp_path, f"{PKG}/plan.py", """
            def f(table):
                n = int(table.row_count)
                m = int(table.logical_row_count)
                return n + m
        """)
        assert got == []

    def test_host_call_results_are_clean(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/plan.py", """
            def f(xs, table):
                n = len(xs)
                b = int(table_bytes(table))
                return int(n) + b
        """)
        assert got == []

    def test_pragma_suppresses(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/plan.py", """
            import jax.numpy as jnp

            def f(a):
                count = jnp.sum(a)
                # srt: allow-host-sync(segment boundary: one sizing read)
                return int(count)
        """)
        assert got == []

    def test_only_hot_modules_in_scope(self, tmp_path):
        # outside plan.py/bucketed.py a sync is someone else's problem
        got = scan(tmp_path, f"{PKG}/ops/foo.py", """
            def f(col):
                return col.data.item()
        """)
        assert got == []

    def test_rebound_host_local_is_clean(self, tmp_path):
        # a name rebound from device to host drops out of the taint set
        got = scan(tmp_path, f"{PKG}/plan.py", """
            import jax.numpy as jnp

            def f(a):
                x = jnp.sum(a)
                x = len([1])
                return int(x)
        """)
        assert got == []


class TestDispatchParity:
    PLANCHECK_OK = """
        _RULES = {
            "cast": None,
            "filter": None,
        }
    """
    DISPATCH_OK = """
        DISPATCH_OPS = frozenset({"cast", "filter"})

        def _dispatch_impl(name, op):
            if name == "cast":
                return 1
            if name == "filter":
                return 2
            raise ValueError(f"unknown table op {name!r}")
    """

    def _plancheck(self, tmp_path, src=None):
        full = tmp_path / PKG / "plancheck.py"
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src or self.PLANCHECK_OK))

    def test_three_way_parity_clean(self, tmp_path):
        self._plancheck(tmp_path)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", self.DISPATCH_OK)
        assert got == []

    def test_arm_missing_from_dispatch_ops(self, tmp_path):
        self._plancheck(tmp_path)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", """
            DISPATCH_OPS = frozenset({"cast", "filter"})

            def _dispatch_impl(name, op):
                if name == "cast":
                    return 1
                if name == "filter":
                    return 2
                if name == "explode":
                    return 3
                raise ValueError(f"unknown table op {name!r}")
        """)
        msgs = [f.message for f in got]
        assert passes_of(got) == ["SRT008"]
        assert "dispatch arm 'explode' missing from DISPATCH_OPS" in msgs[0]

    def test_stale_dispatch_ops_entry(self, tmp_path):
        self._plancheck(tmp_path, """
            _RULES = {"cast": None, "filter": None, "repeat": None}
        """)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", """
            DISPATCH_OPS = frozenset({"cast", "filter", "repeat"})

            def _dispatch_impl(name, op):
                if name == "cast":
                    return 1
                if name == "filter":
                    return 2
                raise ValueError(f"unknown table op {name!r}")
        """)
        assert passes_of(got) == ["SRT008"]
        assert "stale" in got[0].message

    def test_dispatch_op_without_plancheck_rule(self, tmp_path):
        self._plancheck(tmp_path, """
            _RULES = {"cast": None}
        """)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", self.DISPATCH_OK)
        assert passes_of(got) == ["SRT008"]
        assert "no plancheck inference rule" in got[0].message

    def test_plancheck_rule_without_dispatch_arm(self, tmp_path):
        self._plancheck(tmp_path, """
            _RULES = {"cast": None, "filter": None, "ghost": None}
        """)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", self.DISPATCH_OK)
        assert passes_of(got) == ["SRT008"]
        assert "plancheck rule 'ghost' has no dispatch arm" \
            in got[0].message

    def test_missing_plancheck_module(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", self.DISPATCH_OK)
        assert passes_of(got) == ["SRT008"]
        assert "no sibling plancheck.py" in got[0].message

    def test_non_literal_dispatch_ops(self, tmp_path):
        self._plancheck(tmp_path)
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", """
            _OPS = ["cast"]
            DISPATCH_OPS = frozenset(_OPS)

            def _dispatch_impl(name, op):
                if name == "cast":
                    return 1
                raise ValueError(f"unknown table op {name!r}")
        """)
        assert passes_of(got) == ["SRT008"]
        assert "pure string-literal" in got[0].message

    def test_pragma_suppresses(self, tmp_path):
        got = scan(tmp_path, f"{PKG}/runtime_bridge.py", """
            # srt: allow-dispatch-parity(migration window: rules land next)
            DISPATCH_OPS = frozenset({"cast"})

            def _dispatch_impl(name, op):
                if name == "cast":
                    return 1
                raise ValueError(f"unknown table op {name!r}")
        """)
        assert got == []

    def test_non_dispatch_modules_exempt(self, tmp_path):
        # a module with only one of the two anchors is not the dispatch
        # plane; the pass stays quiet
        got = scan(tmp_path, f"{PKG}/other.py", """
            DISPATCH_OPS = frozenset({"cast"})
        """)
        assert got == []

    def test_real_repo_three_way_parity_holds(self):
        findings = srt.scan_file(
            os.path.join(REPO_ROOT, PKG, "runtime_bridge.py"), REPO_ROOT
        )
        assert [f for f in findings if f.pass_id == "SRT008"] == []


class TestPruneBaseline:
    def test_prune_drops_only_stale_entries(self, tmp_path, capsys):
        full = tmp_path / PKG / "foo.py"
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent("""
            import os
            A = os.environ.get("SPARK_RAPIDS_TPU_A")
            B = os.environ.get("SPARK_RAPIDS_TPU_B")
        """))
        bl = tmp_path / "baseline.json"
        argv = [f"{PKG}/foo.py", "--root", str(tmp_path),
                "--baseline", str(bl)]
        assert srt.main(argv + ["--write-baseline"]) == 0
        assert len(json.loads(bl.read_text())["fingerprints"]) == 2
        # fix ONE violation: its fingerprint goes stale
        full.write_text(textwrap.dedent("""
            import os
            A = os.environ.get("SPARK_RAPIDS_TPU_A")
        """))
        assert srt.main(argv + ["--prune-baseline"]) == 0
        out = capsys.readouterr().out
        assert "pruned 1 stale" in out
        doc = json.loads(bl.read_text())
        # the still-live grandfathered entry survives the prune
        assert len(doc["fingerprints"]) == 1
        assert srt.main(argv) == 0  # gate still green afterwards

    def test_prune_without_stale_is_a_noop(self, tmp_path):
        full = tmp_path / PKG / "foo.py"
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(
            'import os\nV = os.environ.get("SPARK_RAPIDS_TPU_V")\n'
        )
        bl = tmp_path / "baseline.json"
        argv = [f"{PKG}/foo.py", "--root", str(tmp_path),
                "--baseline", str(bl)]
        srt.main(argv + ["--write-baseline"])
        before = bl.read_text()
        assert srt.main(argv + ["--prune-baseline"]) == 0
        assert bl.read_text() == before

    def test_prune_missing_baseline_is_safe(self, tmp_path):
        assert srt.prune_baseline(str(tmp_path / "none.json"), set()) == 0
