"""Window / quantile / variance tests — validated against pure-python
sliding-window oracles and numpy statistics."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg


def _oracle_window(values, valid, preceding, following, agg, min_periods=1):
    n = len(values)
    out = []
    for i in range(n):
        lo = max(i - preceding, 0)
        hi = min(i + following + 1, n)
        frame = [values[j] for j in range(lo, hi) if valid[j]]
        if len(frame) < min_periods or not frame:
            out.append(None)
        elif agg == "sum":
            out.append(sum(frame))
        elif agg == "count":
            out.append(len(frame))
        elif agg == "mean":
            out.append(sum(frame) / len(frame))
        elif agg == "min":
            out.append(min(frame))
        elif agg == "max":
            out.append(max(frame))
    return out


class TestRolling:
    @pytest.mark.parametrize("agg", ["sum", "count", "mean", "min", "max"])
    def test_vs_oracle(self, agg):
        rng = np.random.default_rng(5)
        vals = rng.integers(-50, 50, 64).astype(np.int64)
        valid = rng.random(64) > 0.2
        col = Column.from_numpy(vals, validity=valid)
        got = ops.rolling_aggregate(col, 3, 1, agg).to_pylist()
        want = _oracle_window(list(vals), list(valid), 3, 1, agg)
        if agg == "mean":
            for g, w in zip(got, want):
                assert (g is None) == (w is None)
                if w is not None:
                    assert g == pytest.approx(w)
        else:
            assert got == want

    def test_min_periods(self):
        col = Column.from_numpy(np.arange(5, dtype=np.int64))
        got = ops.rolling_aggregate(col, 2, 0, "sum", min_periods=3)
        assert got.to_pylist() == [None, None, 3, 6, 9]

    def test_float_window(self):
        col = Column.from_numpy(np.array([1.5, -2.5, 4.0], np.float64))
        got = ops.rolling_aggregate(col, 1, 0, "max").to_pylist()
        assert got == [1.5, 1.5, 4.0]

    def test_extreme_values_not_nulled(self):
        # INT64_MAX shares its order key with the min-exile sentinel;
        # the winner must still surface as a valid value
        m = np.iinfo(np.int64)
        col = Column.from_numpy(
            np.array([m.max, m.max], np.int64),
            validity=np.array([True, False]),
        )
        assert ops.rolling_aggregate(col, 1, 0, "min").to_pylist() == [
            m.max, m.max,
        ]
        col2 = Column.from_numpy(
            np.array([m.min, m.min], np.int64),
            validity=np.array([True, False]),
        )
        assert ops.rolling_aggregate(col2, 1, 0, "max").to_pylist() == [
            m.min, m.min,
        ]

    def test_large_window_min(self):
        rng = np.random.default_rng(9)
        vals = rng.standard_normal(300)
        col = Column.from_numpy(vals)
        got = ops.rolling_aggregate(col, 100, 50, "min").to_pylist()
        want = _oracle_window(list(vals), [True] * 300, 100, 50, "min")
        np.testing.assert_allclose(got, want)


class TestGroupedWindow:
    def test_partitioned_sum_matches_python(self):
        rng = np.random.default_rng(2)
        part = rng.integers(0, 4, 50)
        order = rng.permutation(50)
        vals = rng.integers(0, 100, 50).astype(np.int64)
        t = Table.from_pydict({"p": part, "o": order, "v": vals})
        got = ops.grouped_rolling_aggregate(
            t, ["p"], ["o"], "v", preceding=2, following=0, agg="sum"
        ).to_pylist()
        # python oracle: per partition, ordered by o, window sum over
        # up-to-3 trailing rows; result in original row order
        want = [None] * 50
        for p in set(part):
            rows = sorted(
                [i for i in range(50) if part[i] == p], key=lambda i: order[i]
            )
            for j, i in enumerate(rows):
                frame = rows[max(j - 2, 0) : j + 1]
                want[i] = int(sum(vals[k] for k in frame))
        assert got == want

    def test_lead_lag(self):
        col = Column.from_numpy(np.array([10, 20, 30], np.int64))
        assert ops.lead(col).to_pylist() == [20, 30, None]
        assert ops.lag(col).to_pylist() == [None, 10, 20]

    def test_lag_partitioned(self):
        col = Column.from_numpy(np.array([1, 2, 3, 4], np.int64))
        pids = np.array([0, 0, 1, 1])
        assert ops.lag(col, 1, pids).to_pylist() == [None, 1, None, 3]

    def test_row_number(self):
        t = Table.from_pydict({"p": [1, 0, 1, 0, 1], "o": [5, 3, 1, 9, 2]})
        got = ops.row_number(t, ["p"], ["o"]).to_pylist()
        # partition 0 rows (idx 1,3): o=3 -> 1, o=9 -> 2
        # partition 1 rows (idx 0,2,4): o=5 -> 3, o=1 -> 1, o=2 -> 2
        assert got == [3, 1, 1, 2, 2]


class TestQuantile:
    def test_linear_matches_numpy(self):
        rng = np.random.default_rng(4)
        vals = rng.standard_normal(101)
        col = Column.from_numpy(vals)
        qs = [0.0, 0.25, 0.5, 0.75, 1.0]
        got = ops.quantile(col, qs).to_pylist()
        want = np.quantile(vals, qs)
        np.testing.assert_allclose(got, want, rtol=1e-12)

    @pytest.mark.parametrize(
        "interp,npinterp",
        [("lower", "lower"), ("higher", "higher"),
         ("midpoint", "midpoint"), ("nearest", "nearest")],
    )
    def test_interpolations(self, interp, npinterp):
        vals = np.array([1.0, 2.0, 3.0, 4.0])
        col = Column.from_numpy(vals)
        got = ops.quantile(col, [0.4], interp).to_pylist()
        want = np.quantile(vals, [0.4], method=npinterp)
        np.testing.assert_allclose(got, want)

    def test_nulls_excluded(self):
        col = Column.from_numpy(
            np.array([1.0, 100.0, 3.0]), validity=np.array([True, False, True])
        )
        got = ops.quantile(col, [1.0]).to_pylist()
        assert got == [3.0]

    def test_all_null_gives_null(self):
        col = Column.from_numpy(
            np.array([1.0]), validity=np.array([False])
        )
        assert ops.quantile(col, [0.5]).to_pylist() == [None]


class TestVariance:
    def test_reduce_var_std(self):
        vals = np.array([1.0, 4.0, 9.0, 16.0])
        col = Column.from_numpy(vals)
        assert ops.reduce_column(col, "variance").to_pylist()[0] == pytest.approx(
            np.var(vals, ddof=1)
        )
        assert ops.reduce_column(col, "std").to_pylist()[0] == pytest.approx(
            np.std(vals, ddof=1)
        )

    def test_groupby_variance_large_magnitude(self):
        # mean-subtracting formula: exact where E[x^2]-E[x]^2 cancels
        v = np.array([1e9, 1e9 + 1, 1e9 + 2])
        t = Table.from_pydict({"k": np.zeros(3, np.int64), "v": v})
        got = ops.groupby_aggregate(t, ["k"], [GroupbyAgg("v", "variance")])
        assert got["variance_v"].to_pylist()[0] == pytest.approx(1.0)

    def test_groupby_variance(self):
        k = np.array([0, 0, 0, 1, 1, 2])
        v = np.array([1.0, 2.0, 4.0, 10.0, 30.0, 5.0])
        t = Table.from_pydict({"k": k, "v": v})
        got = ops.groupby_aggregate(
            t, ["k"], [GroupbyAgg("v", "variance"), GroupbyAgg("v", "std")]
        )
        gk = got["k"].to_pylist()
        gv = got["variance_v"].to_pylist()
        gs = got["std_v"].to_pylist()
        want = {
            0: np.var([1.0, 2.0, 4.0], ddof=1),
            1: np.var([10.0, 30.0], ddof=1),
            2: None,  # single row -> null sample variance
        }
        for kk, vv, ss in zip(gk, gv, gs):
            if want[kk] is None:
                assert vv is None and ss is None
            else:
                assert vv == pytest.approx(want[kk])
                assert ss == pytest.approx(np.sqrt(want[kk]))


class TestRankFamily:
    def _table(self, rng, n=2_000):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table

        return Table.from_pydict({
            "p": rng.integers(0, 20, n),
            "v": rng.integers(0, 30, n),  # many ties
        })

    def test_rank_vs_pandas(self, rng):
        import numpy as np
        import pandas as pd

        from spark_rapids_jni_tpu.ops import dense_rank, rank

        t = self._table(rng)
        df = pd.DataFrame(t.to_pydict())
        got_r = np.asarray(rank(t, ["p"], ["v"]).data)
        want_r = df.groupby("p")["v"].rank(method="min").astype(int)
        np.testing.assert_array_equal(got_r, want_r.to_numpy())
        got_d = np.asarray(dense_rank(t, ["p"], ["v"]).data)
        want_d = df.groupby("p")["v"].rank(method="dense").astype(int)
        np.testing.assert_array_equal(got_d, want_d.to_numpy())

    def test_percent_rank_vs_pandas(self, rng):
        import numpy as np
        import pandas as pd

        from spark_rapids_jni_tpu.ops import percent_rank

        t = self._table(rng, n=500)
        df = pd.DataFrame(t.to_pydict())
        got = percent_rank(t, ["p"], ["v"]).to_numpy()
        # pandas pct uses rank/size; SQL percent_rank is (rank-1)/(size-1)
        r = df.groupby("p")["v"].rank(method="min")
        size = df.groupby("p")["v"].transform("size")
        want = np.where(size > 1, (r - 1) / np.maximum(size - 1, 1), 0.0)
        np.testing.assert_allclose(got, want, rtol=1e-12)

    def test_ntile(self, rng):
        import numpy as np

        from spark_rapids_jni_tpu.column import Table
        from spark_rapids_jni_tpu.ops import ntile

        # one partition of 10 rows into 4 tiles -> sizes 3,3,2,2
        t = Table.from_pydict({
            "p": [0] * 10,
            "v": list(range(10)),
        })
        got = np.asarray(ntile(t, ["p"], ["v"], 4).data)
        assert got.tolist() == [1, 1, 1, 2, 2, 2, 3, 3, 4, 4]
        # more tiles than rows: each row its own bucket
        t2 = Table.from_pydict({"p": [0] * 3, "v": [2, 0, 1]})
        got2 = np.asarray(ntile(t2, ["p"], ["v"], 8).data)
        assert got2.tolist() == [3, 1, 2]

    def test_rank_jit(self, rng):
        import jax
        import numpy as np

        from spark_rapids_jni_tpu.ops import rank

        t = self._table(rng, n=256)
        f = jax.jit(lambda tt: rank(tt, ["p"], ["v"]).data)
        got = np.asarray(f(t))
        assert got.min() == 1

    def test_rank_null_order_keys_tie(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column, Table
        from spark_rapids_jni_tpu.ops import dense_rank, rank

        # two null order keys carrying DIFFERENT garbage payloads must
        # still tie (SQL: all NULLs in the order key share a rank)
        v = Column.from_numpy(
            np.array([111, 999, 5], dtype=np.int64),
            validity=np.array([False, False, True]),
        )
        p = Column.from_numpy(np.zeros(3, dtype=np.int64))
        t = Table([p, v], ["p", "v"])
        r = np.asarray(rank(t, ["p"], ["v"]).data)
        d = np.asarray(dense_rank(t, ["p"], ["v"]).data)
        # nulls sort first (ascending default): both get rank 1
        assert r.tolist() == [1, 1, 3]
        assert d.tolist() == [1, 1, 2]


def _oracle_range_window(
    parts, order, ovalid, values, vvalid, preceding, following, agg,
    min_periods=1, ascending=True,
):
    """O(n^2) reference: for each row, scan its partition and test the
    ORDER BY value against [v-pre, v+fol] (asc) / [v-fol, v+pre] (desc);
    NULL order rows frame exactly their partition's null peers."""
    n = len(order)
    out = []
    for i in range(n):
        frame = []
        for j in range(n):
            if parts[j] != parts[i]:
                continue
            nulls_first = ascending  # Spark's default null placement
            if not ovalid[i] and not ovalid[j]:
                hit = True
            elif not ovalid[i]:
                # valid j sits after the null run when nulls are first:
                # only a positional UNBOUNDED bound reaches it
                hit = (
                    following is None if nulls_first else preceding is None
                )
            elif not ovalid[j]:
                hit = (
                    preceding is None if nulls_first else following is None
                )
            else:
                # the low VALUE edge comes from preceding when ascending
                # but from following when descending (and vice versa)
                lo_b = preceding if ascending else following
                hi_b = following if ascending else preceding
                lo = -float("inf") if lo_b is None else order[i] - lo_b
                hi = float("inf") if hi_b is None else order[i] + hi_b
                hit = lo <= order[j] <= hi
            if hit and vvalid[j]:
                frame.append(values[j])
        if len(frame) < min_periods or not frame:
            out.append(None)
        elif agg == "sum":
            out.append(sum(frame))
        elif agg == "count":
            out.append(len(frame))
        elif agg == "mean":
            out.append(sum(frame) / len(frame))
        elif agg == "min":
            out.append(min(frame))
        elif agg == "max":
            out.append(max(frame))
    return out


class TestRangeFrames:
    def _table(self, n=96, seed=7, float_order=False):
        rng = np.random.default_rng(seed)
        parts = rng.integers(0, 5, n).astype(np.int64)
        if float_order:
            order = np.round(rng.standard_normal(n) * 10, 2)
        else:
            order = rng.integers(-30, 30, n).astype(np.int64)
        ovalid = rng.random(n) > 0.15
        vals = rng.integers(-50, 50, n).astype(np.int64)
        vvalid = rng.random(n) > 0.2
        t = Table(
            [
                Column.from_numpy(parts),
                Column.from_numpy(order, validity=ovalid),
                Column.from_numpy(vals, validity=vvalid),
            ],
            ["p", "o", "v"],
        )
        return t, parts, order, ovalid, vals, vvalid

    @pytest.mark.parametrize("agg", ["sum", "count", "mean", "min", "max"])
    def test_vs_oracle(self, agg):
        t, parts, order, ovalid, vals, vvalid = self._table()
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 5, 3, agg
        ).to_pylist()
        want = _oracle_range_window(
            parts, order, ovalid, vals, vvalid, 5, 3, agg
        )
        if agg == "mean":
            for g, w in zip(got, want):
                assert (g is None) == (w is None)
                if g is not None:
                    assert g == pytest.approx(w)
        else:
            assert got == want

    @pytest.mark.parametrize(
        "pre,fol", [(None, 0), (0, None), (None, None), (2, 0), (0, 2)]
    )
    def test_unbounded_and_current(self, pre, fol):
        t, parts, order, ovalid, vals, vvalid = self._table(seed=11)
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", pre, fol, "sum"
        ).to_pylist()
        want = _oracle_range_window(
            parts, order, ovalid, vals, vvalid, pre, fol, "sum"
        )
        assert got == want

    def test_descending(self):
        t, parts, order, ovalid, vals, vvalid = self._table(seed=13)
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 4, 2, "sum", ascending=False
        ).to_pylist()
        want = _oracle_range_window(
            parts, order, ovalid, vals, vvalid, 4, 2, "sum",
            ascending=False,
        )
        assert got == want

    def test_float_order_column(self):
        t, parts, order, ovalid, vals, vvalid = self._table(
            seed=17, float_order=True
        )
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 5.0, 5.0, "count"
        ).to_pylist()
        want = _oracle_range_window(
            parts, order, ovalid, vals, vvalid, 5.0, 5.0, "count"
        )
        assert got == want

    def test_peers_share_frames(self):
        # duplicate order values: every peer must see the same frame —
        # the defining RANGE-vs-ROWS difference
        t = Table(
            [
                Column.from_numpy(np.zeros(6, np.int64)),
                Column.from_numpy(np.array([1, 1, 1, 2, 2, 9], np.int64)),
                Column.from_numpy(np.array([1, 2, 4, 8, 16, 32], np.int64)),
            ],
            ["p", "o", "v"],
        )
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 0, 0, "sum"
        ).to_pylist()
        assert got == [7, 7, 7, 24, 24, 32]

    def test_saturation_at_int64_extremes(self):
        big = np.iinfo(np.int64).max
        t = Table(
            [
                Column.from_numpy(np.zeros(3, np.int64)),
                Column.from_numpy(
                    np.array([big - 1, big, -big], np.int64)
                ),
                Column.from_numpy(np.array([1, 2, 4], np.int64)),
            ],
            ["p", "o", "v"],
        )
        # +following must clamp at INT64_MAX, not wrap below -big
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 0, 5, "sum"
        ).to_pylist()
        assert got == [3, 2, 4]

    def test_no_partition(self):
        t, parts, order, ovalid, vals, vvalid = self._table(seed=19)
        got = ops.grouped_range_rolling_aggregate(
            t, [], "o", "v", 3, 3, "sum"
        ).to_pylist()
        want = _oracle_range_window(
            np.zeros_like(parts), order, ovalid, vals, vvalid, 3, 3,
            "sum",
        )
        assert got == want

    def test_string_order_rejected(self):
        t = Table(
            [
                Column.from_numpy(np.zeros(2, np.int64)),
                Column.from_numpy(np.array([1, 2], np.int64)),
            ],
            ["p", "v"],
        )
        import jax.numpy as jnp

        smat = jnp.asarray(np.zeros((2, 4), np.uint8))
        st = Table(
            [
                t.columns[0],
                Column(smat, dt.STRING, None, jnp.full((2,), 4, jnp.int32)),
                t.columns[1],
            ],
            ["p", "s", "v"],
        )
        with pytest.raises(TypeError, match="fixed-width"):
            ops.grouped_range_rolling_aggregate(
                st, ["p"], "s", "v", 1, 1, "sum"
            )

    @pytest.mark.parametrize("pre,fol", [(None, 1), (1, None)])
    def test_unbounded_descending(self, pre, fol):
        t, parts, order, ovalid, vals, vvalid = self._table(seed=23)
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", pre, fol, "sum", ascending=False
        ).to_pylist()
        want = _oracle_range_window(
            parts, order, ovalid, vals, vvalid, pre, fol, "sum",
            ascending=False,
        )
        assert got == want

    def test_unsigned_order_column(self):
        # numpy>=2 regression: a negative delta must never be cast to
        # the (unsigned) order dtype
        t = Table(
            [
                Column.from_numpy(np.zeros(4, np.int64)),
                Column.from_numpy(
                    np.array([1, 3, 4, 2**64 - 1], np.uint64)
                ),
                Column.from_numpy(np.array([1, 2, 4, 8], np.int64)),
            ],
            ["p", "o", "v"],
        )
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 2, 0, "sum"
        ).to_pylist()
        assert got == [1, 3, 6, 8]

    def test_narrow_order_with_out_of_range_bound(self):
        t = Table(
            [
                Column.from_numpy(np.zeros(3, np.int64)),
                Column.from_numpy(np.array([-100, 0, 100], np.int8)),
                Column.from_numpy(np.array([1, 2, 4], np.int64)),
            ],
            ["p", "o", "v"],
        )
        got = ops.grouped_range_rolling_aggregate(
            t, ["p"], "o", "v", 300, 0, "sum"
        ).to_pylist()
        assert got == [1, 3, 7]
