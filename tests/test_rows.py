"""Phase-1 tests: the packed row format and row⇄columnar round trip.

The oracle below re-implements the row-format *spec* (RowConversion.java:43-102)
independently in numpy so the device path is cross-checked against a second
implementation, not against itself.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import rows
from spark_rapids_jni_tpu.column import Column, Table


def oracle_pack(arrays, valids, dtypes):
    """Reference numpy implementation of the packed row format."""
    layout = oracle_layout(dtypes)
    n = len(arrays[0])
    out = np.zeros((n, layout["row_size"]), dtype=np.uint8)
    for arr, d, off in zip(arrays, dtypes, layout["offsets"]):
        if d.is_boolean:
            b = arr.astype(np.uint8).reshape(n, 1)
        else:
            b = np.ascontiguousarray(arr).view(np.uint8).reshape(n, d.itemsize)
        out[:, off : off + b.shape[1]] = b
    # validity: 1 bit per column, LSB-first, appended after last column value
    voff = layout["validity_offset"]
    for i, v in enumerate(valids):
        byte, bit = i // 8, i % 8
        out[:, voff + byte] |= (v.astype(np.uint8) << bit)
    return out


def oracle_layout(dtypes):
    cursor = 0
    offsets = []
    for d in dtypes:
        w = d.itemsize
        cursor = (cursor + w - 1) // w * w
        offsets.append(cursor)
        cursor += w
    voff = cursor
    cursor += (len(dtypes) + 7) // 8
    row_size = (cursor + 7) // 8 * 8
    return {"offsets": offsets, "validity_offset": voff, "row_size": row_size}


def reference_test_table(rng, n=64, trailing_nulls=3):
    """The 8-column schema of RowConversionTest.java:30-39 (long, double,
    int, bool, float, byte, decimal32 scale -3, decimal64 scale -8), with
    trailing nulls in every column."""
    valid = np.ones(n, dtype=bool)
    valid[n - trailing_nulls :] = False
    cols = [
        Column.from_numpy(rng.integers(-(2**60), 2**60, n, dtype=np.int64), valid),
        Column.from_numpy(rng.standard_normal(n), valid),
        Column.from_numpy(rng.integers(-(2**31), 2**31, n, dtype=np.int32), valid),
        Column.from_numpy(rng.random(n) > 0.5, valid),
        Column.from_numpy(rng.standard_normal(n).astype(np.float32), valid),
        Column.from_numpy(rng.integers(-128, 128, n, dtype=np.int8), valid),
        Column.from_numpy(
            rng.integers(-(10**6), 10**6, n, dtype=np.int32),
            valid,
            dtype=dt.decimal32(-3),
        ),
        Column.from_numpy(
            rng.integers(-(10**15), 10**15, n, dtype=np.int64),
            valid,
            dtype=dt.decimal64(-8),
        ),
    ]
    return Table(cols, list("abcdefgh"))


class TestLayout:
    def test_reference_schema_layout(self):
        t_dtypes = [
            dt.INT64,
            dt.FLOAT64,
            dt.INT32,
            dt.BOOL8,
            dt.FLOAT32,
            dt.INT8,
            dt.decimal32(-3),
            dt.decimal64(-8),
        ]
        lay = rows.compute_fixed_width_layout(t_dtypes)
        assert lay.column_offsets == (0, 8, 16, 20, 24, 28, 32, 40)
        assert lay.validity_offset == 48
        assert lay.validity_bytes == 1
        assert lay.row_size == 56  # padded to 64-bit multiple

    def test_alignment_padding(self):
        # int8 then int64: the long must be 8-aligned.
        lay = rows.compute_fixed_width_layout([dt.INT8, dt.INT64])
        assert lay.column_offsets == (0, 8)
        assert lay.validity_offset == 16
        assert lay.row_size == 24

    def test_many_columns_validity_bytes(self):
        lay = rows.compute_fixed_width_layout([dt.INT8] * 17)
        assert lay.validity_bytes == 3
        assert lay.validity_offset == 17
        assert lay.row_size == 24

    def test_string_rejected(self):
        with pytest.raises(TypeError):
            rows.compute_fixed_width_layout([dt.INT32, dt.STRING])

    def test_max_rows_per_batch(self):
        # multiples of 32; INT_MAX cap (row_conversion.cu:476-479)
        assert rows.max_rows_per_batch(56) == (rows.INT_MAX // 56) // 32 * 32
        with pytest.raises(ValueError):
            rows.max_rows_per_batch(rows.INT_MAX // 16)


class TestRoundTrip:
    def test_reference_round_trip(self, rng):
        """The RowConversionTest.fixedWidthRowsRoundTrip analog."""
        t = reference_test_table(rng)
        packed = rows.to_rows(t)
        assert len(packed) == 1  # no 2 GB split for 64 rows
        assert packed[0].row_count == 64
        back = rows.from_rows(packed, t.dtypes(), names=t.names)
        for name in t.names:
            assert back[name].to_pylist() == t[name].to_pylist(), name

    def test_bytes_match_oracle(self, rng):
        t = reference_test_table(rng, n=37)
        got = rows.to_rows(t)[0].to_numpy()
        arrays = [np.asarray(c.data) for c in t.columns]
        valids = [c.validity_to_numpy() for c in t.columns]
        want = oracle_pack(arrays, valids, list(t.dtypes()))
        np.testing.assert_array_equal(got, want)

    def test_offsets_sequence(self, rng):
        t = reference_test_table(rng, n=5)
        p = rows.to_rows(t)[0]
        np.testing.assert_array_equal(
            p.offsets(), np.arange(6, dtype=np.int32) * p.row_size
        )

    def test_batch_splitting(self, rng):
        t = reference_test_table(rng, n=100, trailing_nulls=10)
        packed = rows.to_rows(t, batch_rows=32)
        assert [p.row_count for p in packed] == [32, 32, 32, 4]
        back = rows.from_rows(packed, t.dtypes(), names=t.names)
        assert back.row_count == 100
        for name in t.names:
            assert back[name].to_pylist() == t[name].to_pylist(), name

    def test_no_validity_all_valid(self, rng):
        t = Table(
            [
                Column.from_numpy(np.arange(10, dtype=np.int64)),
                Column.from_numpy(np.arange(10, dtype=np.int32)),
            ]
        )
        p = rows.to_rows(t)[0]
        lay = p.layout
        vb = p.to_numpy()[:, lay.validity_offset]
        np.testing.assert_array_equal(vb, np.full(10, 0b11, dtype=np.uint8))
        back = rows.from_rows(p)
        assert back[0].null_count() == 0

    def test_schema_mismatch_rejected(self, rng):
        t = reference_test_table(rng, n=8)
        p = rows.to_rows(t)
        with pytest.raises(ValueError):
            rows.from_rows(p, [dt.INT64, dt.INT8])

    def test_host_row_ingest(self, rng):
        """Rows packed by the independent oracle decode on device."""
        t = reference_test_table(rng, n=21)
        arrays = [np.asarray(c.data) for c in t.columns]
        valids = [c.validity_to_numpy() for c in t.columns]
        host_rows = oracle_pack(arrays, valids, list(t.dtypes()))
        p = rows.packed_rows_from_numpy(host_rows, t.dtypes())
        back = rows.from_rows(p, t.dtypes(), names=t.names)
        for name in t.names:
            assert back[name].to_pylist() == t[name].to_pylist(), name

    def test_single_column_byte(self, rng):
        t = Table([Column.from_numpy(np.array([1, 0, 255], dtype=np.uint8))])
        lay = rows.to_rows(t)[0].layout
        assert lay.row_size == 8  # 1 data + 1 validity -> pad to 8
        back = rows.from_rows(rows.to_rows(t))
        assert back[0].to_pylist() == [1, 0, 255]

    def test_empty_table_round_trip(self):
        t = Table([Column.from_numpy(np.array([], dtype=np.int64))])
        back = rows.from_rows(rows.to_rows(t))
        assert back.row_count == 0
        assert back[0].dtype == dt.INT64
