"""Structural tests for the Java facade (java/ tree).

No JDK exists in this image (SURVEY.md §4's GPU-gated JUnit suite maps
to the CI premerge job), so these tests pin the parts of the Java layer
that a compiler would: the JNI wire contract (every `native` method in
Java has a bridge implementation with the right mangled name), the
package/file layout, and the dtype id space shared across Java, C and
Python (one id table in three languages — a mismatch silently corrupts
the (typeId, scale) wire arrays of RowConversionJni.cpp:56-61).
"""

import os
import re

import pytest

from spark_rapids_jni_tpu import dtype as dt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JAVA_ROOT = os.path.join(REPO, "java", "src")


def _java_files():
    out = []
    for root, _, files in os.walk(JAVA_ROOT):
        for f in files:
            if f.endswith(".java"):
                out.append(os.path.join(root, f))
    return out


def _read(path):
    with open(path) as f:
        return f.read()


def test_java_tree_exists():
    files = {os.path.basename(p) for p in _java_files()}
    # L4 facade (SURVEY.md layer map) + repo-local L5 classes.
    for required in [
        "DType.java",
        "ColumnView.java",
        "ColumnVector.java",
        "Table.java",
        "NativeDepsLoader.java",
        "RowConversion.java",
        "NativeLibraryLoader.java",
        "HostBuffer.java",
        "RowConversionTest.java",
    ]:
        assert required in files, f"missing {required}"


def test_package_matches_path():
    for path in _java_files():
        src = _read(path)
        m = re.search(r"^package\s+([\w.]+);", src, re.M)
        assert m, f"{path}: no package declaration"
        expected_dir = m.group(1).replace(".", os.sep)
        assert os.path.dirname(path).endswith(expected_dir), (
            f"{path}: package {m.group(1)} does not match directory"
        )
        cls = os.path.splitext(os.path.basename(path))[0]
        assert re.search(
            rf"(class|interface|enum)\s+{cls}\b", src
        ), f"{path}: no type named {cls}"


def test_braces_balanced():
    for path in _java_files():
        src = _read(path)
        # strip string/char literals and comments before counting
        src = re.sub(r"//.*", "", src)
        src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
        src = re.sub(r'"(\\.|[^"\\])*"', '""', src)
        src = re.sub(r"'(\\.|[^'\\])'", "''", src)
        assert src.count("{") == src.count("}"), f"{path}: unbalanced braces"


def _strip_comments(src):
    src = re.sub(r"//.*", "", src)
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    return src


def _native_methods():
    """(class fqn, method name) for every `native` declaration."""
    out = []
    for path in _java_files():
        src = _read(path)
        pkg = re.search(r"^package\s+([\w.]+);", src, re.M).group(1)
        cls = os.path.splitext(os.path.basename(path))[0]
        for m in re.finditer(
            r"\bnative\s+[\w\[\]<>]+\s+(\w+)\s*\(", _strip_comments(src)
        ):
            out.append((f"{pkg}.{cls}", m.group(1)))
    return out


def _jni_mangle(fqcn, method):
    # JNI short-name mangling: dots -> underscores; '_' in names would
    # need _1 escapes, none of ours use it.
    assert "_" not in method
    return "Java_" + fqcn.replace(".", "_") + "_" + method


def test_every_native_method_has_a_bridge_symbol():
    jni_src = ""
    jni_dir = os.path.join(REPO, "src", "jni")
    for f in os.listdir(jni_dir):
        path = os.path.join(jni_dir, f)
        if os.path.isfile(path):
            jni_src += _read(path)
    natives = _native_methods()
    assert natives, "no native methods found in the Java tree"
    for fqcn, method in natives:
        sym = _jni_mangle(fqcn, method)
        # must be the full symbol (followed by its parameter list), not a
        # prefix of a longer one: `convertToRows` does not match
        # `convertToRowsNative(`
        assert re.search(
            re.escape(sym) + r"\s*\(", jni_src
        ), f"bridge missing JNI symbol {sym}"


def _compiled_jni_symbols():
    """Java_* symbols actually present in a BUILT native artifact, via
    ``nm`` — the compiler-verified ground truth the source regex above
    can't give (round-4 VERDICT item 10). Preference order: the real
    JNI .so (when a JDK was present at build time), else jni_harness,
    which compiles the same bridge sources against the stub jni.h."""
    import subprocess

    candidates = [
        (os.path.join(REPO, "build", "libspark_rapids_tpu_jni.so"), "-D"),
        (os.path.join(REPO, "build", "jni_harness"), ""),
    ]
    for path, dyn in candidates:
        if not os.path.exists(path):
            continue
        cmd = ["nm", "--defined-only"] + (["-D"] if dyn else []) + [path]
        out = subprocess.run(cmd, capture_output=True, text=True)
        if out.returncode != 0:
            continue
        syms = {
            line.split()[-1]
            for line in out.stdout.splitlines()
            if line.strip()
            and line.split()[-1].startswith("Java_")
            # gcc outlines error paths as `sym.cold` fragments — not
            # separate exports
            and "." not in line.split()[-1]
        }
        if syms:
            return syms
    return None


def test_bridge_symbols_in_built_binary_match_java_declarations():
    """Bidirectional check against the COMPILED symbol table: every
    Java `native` method must resolve to an exported Java_* symbol, and
    every exported Java_* symbol must have a Java declaration (an
    orphan either way means UnsatisfiedLinkError — or dead code — at
    first JVM run)."""
    syms = _compiled_jni_symbols()
    if syms is None:
        import pytest

        pytest.skip("no built native binary with JNI symbols (run cmake)")
    natives = _native_methods()
    declared = {_jni_mangle(fqcn, m) for fqcn, m in natives}
    missing = declared - syms
    assert not missing, f"native methods without compiled symbols: {missing}"
    orphans = syms - declared
    assert not orphans, f"compiled JNI symbols no Java class declares: {orphans}"


def test_dtype_ids_match_python():
    """The DTypeEnum table in Java must be the TypeId table in Python."""
    src = _read(
        os.path.join(JAVA_ROOT, "main", "java", "ai", "rapids", "cudf", "DType.java")
    )
    entries = re.findall(r"^\s{4}(\w+)\((\d+),\s*(\d+)\)[,;]", src, re.M)
    assert len(entries) >= 29, "DTypeEnum table truncated"
    for name, native_id, width in entries:
        tid = dt.TypeId[name]
        assert int(native_id) == int(tid), f"{name}: java id {native_id} != {int(tid)}"
        py_width = dt._WIDTHS.get(tid, 0)
        if name == "DICTIONARY32":
            continue  # java carries key width; python treats as nested
        assert int(width) == py_width, (
            f"{name}: java width {width} != python {py_width}"
        )


def test_facade_uses_wire_contract():
    """convertToRows/convertFromRows facade methods marshal the
    (typeId, scale) parallel arrays of the reference JNI."""
    src = _read(
        os.path.join(
            JAVA_ROOT, "main", "java", "com", "nvidia", "spark", "rapids",
            "jni", "RowConversion.java",
        )
    )
    assert "convertToRows(\n      ai.rapids.cudf.Table table)" in src.replace(
        "\r", ""
    ) or re.search(r"convertToRows\(\s*ai\.rapids\.cudf\.Table", src)
    assert re.search(
        r"convertFromRows\(\s*ai\.rapids\.cudf\.ColumnView.*?ai\.rapids\.cudf\.DType\.\.\.",
        src,
        re.S,
    )
    assert "getNativeId()" in src and "getScale()" in src


PLUGIN_FACADE = {
    # VERDICT r4 item 4: the plugin's real ai.rapids.cudf import
    # surface. Class -> public members a Spark plugin binds.
    "Scalar.java": [
        "fromBool", "fromInt", "fromLong", "fromDouble", "fromString",
        "fromDecimal", "nullScalar", "getType", "isValid", "close",
    ],
    "HostColumnVector.java": [
        "builder", "fromLongs", "fromStrings", "appendNull", "build",
        "getRowCount", "getNullCount", "isNull", "copyToDevice",
    ],
    "ContiguousTable.java": [
        "pack", "getBuffer", "getTable", "getMetadataDirectBuffer",
        "unpack", "getRowCount", "close",
    ],
    "Schema.java": ["builder", "column", "getTypeIds", "getScales"],
    "Rmm.java": [
        "initialize", "isInitialized", "getPoolSize", "shutdown",
    ],
}


def test_plugin_facade_surface_present():
    """Every class/member of the plugin's ai.rapids.cudf binding surface
    exists (text-level; a JVM would enforce signatures)."""
    base = os.path.join(JAVA_ROOT, "main", "java", "ai", "rapids", "cudf")
    for fname, members in PLUGIN_FACADE.items():
        path = os.path.join(base, fname)
        assert os.path.exists(path), f"missing facade class {fname}"
        src = open(path).read()
        for m in members:
            assert re.search(rf"\b{m}\s*\(", src), (
                f"{fname} lacks public member {m}"
            )


def test_set_runtime_flag_c_abi():
    """Drive srt_set_runtime_flag through the C ABI: prefix-checked
    setenv/unsetenv reaching this process's environment (the
    ai.rapids.cudf.Rmm path into the flag plane)."""
    import ctypes

    from spark_rapids_jni_tpu.utils import native

    try:
        lib = native.load()
    except OSError:
        lib = None
    if lib is None:
        pytest.skip("native library not built")
    lib.srt_set_runtime_flag.restype = ctypes.c_int
    # os.environ is a startup snapshot: read back through libc getenv,
    # which is what the embedded runtime's flag plane actually reads
    libc = ctypes.CDLL(None)
    libc.getenv.restype = ctypes.c_char_p
    name = b"SPARK_RAPIDS_TPU_TEST_FLAG_XYZ"
    assert lib.srt_set_runtime_flag(name, b"42") == 0
    assert libc.getenv(name) == b"42"
    assert lib.srt_set_runtime_flag(name, None) == 0
    assert libc.getenv(name) is None
    # outside the flag plane: rejected, env untouched
    assert lib.srt_set_runtime_flag(b"PATH", b"/tmp") != 0
    assert libc.getenv(b"PATH") != b"/tmp"
