"""Per-executor-thread dispatch (the PER_THREAD_DEFAULT_STREAM analog,
SURVEY.md §2.3 last row): Spark runs one task per executor thread, each
dispatching native calls concurrently. The reference gets isolation from
per-thread CUDA streams; here concurrent dispatch goes through the C
ABI / embedded runtime (GIL-interleaved host glue, async XLA execution)
and must be correct and leak-free under thread contention."""

import json
import threading

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available() or not native.jax_runtime_available(),
    reason="native library with embedded JAX runtime not built",
)

N_THREADS = 6
OPS_PER_THREAD = 4


def _worker_wire(tid, results, errors):
    try:
        rng = np.random.default_rng(tid)
        for it in range(OPS_PER_THREAD):
            n = 200 + 10 * tid
            k = rng.integers(0, 8, n).astype(np.int64)
            v = rng.integers(-50, 50, n).astype(np.int64)
            hk = native.buffer_create(k.tobytes(), f"t{tid}-k")
            hv = native.buffer_create(v.tobytes(), f"t{tid}-v")
            try:
                op = json.dumps({
                    "op": "groupby", "by": [0],
                    "aggs": [{"column": 1, "agg": "sum"}],
                })
                i64 = dt.TypeId.INT64.value
                _, _, od, ov, rows = native.jax_table_op(
                    op, [i64, i64], [0, 0], [hk, hv], [None, None], n
                )
                keys = np.frombuffer(
                    native.buffer_bytes(od[0]), np.int64, rows
                )
                sums = np.frombuffer(
                    native.buffer_bytes(od[1]), np.int64, rows
                )
                want = {int(u): int(v[k == u].sum()) for u in np.unique(k)}
                got = dict(zip(keys.tolist(), sums.tolist()))
                if got != want:
                    errors.append((tid, it, "oracle mismatch"))
                for h in [*od, *[x for x in ov if x]]:
                    native.buffer_release(h)
            finally:
                native.buffer_release(hk)
                native.buffer_release(hv)
        results.append(tid)
    except Exception as e:  # pragma: no cover
        errors.append((tid, repr(e)))


def _worker_resident(tid, results, errors):
    try:
        rng = np.random.default_rng(100 + tid)
        for it in range(OPS_PER_THREAD):
            n = 160
            x = rng.permutation(n).astype(np.int64)
            hx = native.buffer_create(x.tobytes(), f"t{tid}-x")
            try:
                t = native.jax_table_upload(
                    [dt.TypeId.INT64.value], [0], [hx], [None], n
                )
                s = native.jax_table_op_resident(
                    json.dumps(
                        {"op": "sort_by", "keys": [{"column": 0}]}
                    ),
                    [t],
                )
                _, _, od, ov, rows = native.jax_table_download(s)
                got = np.frombuffer(
                    native.buffer_bytes(od[0]), np.int64, rows
                )
                if got.tolist() != sorted(x.tolist()):
                    errors.append((tid, it, "sort mismatch"))
                for h in [*od, *[v for v in ov if v]]:
                    native.buffer_release(h)
                native.jax_table_free(t)
                native.jax_table_free(s)
            finally:
                native.buffer_release(hx)
        results.append(tid)
    except Exception as e:  # pragma: no cover
        errors.append((tid, repr(e)))


class TestConcurrentDispatch:
    def test_wire_ops_from_many_threads(self):
        native.jax_init()
        before = native.live_handle_count()
        results, errors = [], []
        threads = [
            threading.Thread(target=_worker_wire, args=(i, results, errors))
            for i in range(N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert errors == []
        assert sorted(results) == list(range(N_THREADS))
        assert native.live_handle_count() == before

    def test_resident_tables_from_many_threads(self):
        native.jax_init()
        before = native.live_handle_count()
        resident_before = native.jax_resident_table_count()
        results, errors = [], []
        threads = [
            threading.Thread(
                target=_worker_resident, args=(i, results, errors)
            )
            for i in range(N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert errors == []
        assert sorted(results) == list(range(N_THREADS))
        assert native.live_handle_count() == before
        assert native.jax_resident_table_count() == resident_before
