"""The op-level metrics registry + span plane (utils/metrics.py): the
``GpuMetric`` / SQL-UI-counters role of the reference stack.

Covers registry math (counters/bytes/timers/gauges/histograms), span
nesting + exception-path duration recording, thread safety under
concurrent ``_dispatch`` calls (the Python-tier sibling of
tests/test_concurrency.py), the resident-table round-trip acceptance
snapshot, stdout hygiene (LOG_LEVEL=TRACE + a metrics dump must never
touch stdout — the bench-JSON wire protocol), the bench structured
failure records, and analyze_bench's metrics summarization.
"""

import contextlib
import importlib.util
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import config, flight, log, metrics, tracing

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _metrics_isolated(monkeypatch):
    monkeypatch.delenv("SPARK_RAPIDS_TPU_METRICS", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_METRICS_DUMP", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_LOG_LEVEL", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_FLIGHT", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_FLIGHT_DUMP", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_PLANSTATS", raising=False)
    monkeypatch.delenv("SPARK_RAPIDS_TPU_PLANSTATS_DIR", raising=False)
    # flag overrides leaked by an earlier module (bench helpers run
    # in-process set METRICS/FLIGHT/PROFILE/PLANSTATS_DIR) beat the env
    for f in ("METRICS", "METRICS_DUMP", "FLIGHT", "FLIGHT_DUMP",
              "PROFILE", "PROFILE_DUMP", "PLANSTATS", "PLANSTATS_DIR"):
        config.clear_flag(f)
    metrics.reset()
    flight.reset()
    yield
    for f in ("METRICS", "METRICS_DUMP", "LOG_LEVEL", "TRACE",
              "FLIGHT", "FLIGHT_DUMP", "PROFILE", "PROFILE_DUMP",
              "PLANSTATS", "PLANSTATS_DIR"):
        config.clear_flag(f)
    metrics.reset()
    flight.reset()
    log._WARNED_INVALID.clear()


def _on():
    config.set_flag("METRICS", True)


class TestRegistryMath:
    def test_counters(self):
        _on()
        metrics.counter_add("c")
        metrics.counter_add("c", 41)
        assert metrics.snapshot()["counters"]["c"] == 42

    def test_bytes(self):
        _on()
        metrics.bytes_add("b", 100)
        metrics.bytes_add("b", 28)
        assert metrics.snapshot()["bytes"]["b"] == 128

    def test_timer_fold(self):
        _on()
        for s in (0.5, 0.1, 0.9):
            metrics.timer_record("t", s)
        t = metrics.snapshot()["timers"]["t"]
        assert t["count"] == 3
        assert t["total_s"] == pytest.approx(1.5)
        assert t["min_s"] == pytest.approx(0.1)
        assert t["max_s"] == pytest.approx(0.9)

    def test_gauge_high_water(self):
        _on()
        for v in (1, 5, 2):
            metrics.gauge_set("g", v)
        g = metrics.snapshot()["gauges"]["g"]
        assert g["value"] == 2
        assert g["high_water"] == 5

    def test_histogram_buckets(self):
        _on()
        bounds = [1, 10, 100]
        for v in (0.5, 1, 5, 100, 1000):
            metrics.hist_observe("h", v, bounds=bounds)
        h = metrics.snapshot()["histograms"]["h"]
        # inclusive upper edges: {<=1: 2, <=10: 1, <=100: 1, overflow: 1}
        assert h["bounds"] == bounds
        assert h["counts"] == [2, 1, 1, 1]
        assert h["count"] == 5
        assert h["sum"] == pytest.approx(1106.5)

    def test_snapshot_is_json_able(self):
        _on()
        metrics.counter_add("c")
        metrics.timer_record("t", 0.25)
        metrics.gauge_set("g", 3)
        metrics.hist_observe("h", 7)
        json.dumps(metrics.snapshot())  # must not raise

    def test_disabled_mutators_no_op(self):
        metrics.counter_add("c")
        metrics.bytes_add("b", 1)
        metrics.timer_record("t", 1.0)
        metrics.gauge_set("g", 1)
        metrics.hist_observe("h", 1)
        snap = metrics.snapshot()
        assert snap["counters"] == {}
        assert snap["timers"] == {}

    def test_disabled_span_is_shared_null(self):
        # the disabled hot path allocates nothing per call
        assert metrics.span("x") is metrics.NULL_SPAN
        assert metrics.span("y") is metrics.NULL_SPAN


class TestSpans:
    def test_span_records_duration(self):
        _on()
        with metrics.span("work"):
            pass
        t = metrics.snapshot()["timers"]["work"]
        assert t["count"] == 1
        assert t["total_s"] >= 0.0

    def test_span_nesting_qualified_names(self, capsys):
        _on()
        config.set_flag("LOG_LEVEL", "TRACE")
        with metrics.span("outer") as outer:
            assert metrics.span_depth() == 1
            with metrics.span("inner") as inner:
                assert metrics.span_depth() == 2
                assert inner.qualname == "outer/inner"
            assert outer.qualname == "outer"
        assert metrics.span_depth() == 0
        timers = metrics.snapshot()["timers"]
        # aggregation stays under the plain name; the qualified path is
        # the trace/log label
        assert set(timers) == {"outer", "inner"}
        err = capsys.readouterr().err
        assert "[srt][span][TRACE] outer/inner" in err

    def test_span_exception_path_records(self):
        _on()
        with pytest.raises(ValueError):
            with metrics.span("doomed"):
                raise ValueError("boom")
        snap = metrics.snapshot()
        assert snap["timers"]["doomed"]["count"] == 1
        assert snap["counters"]["span.doomed.errors"] == 1
        assert metrics.span_depth() == 0  # stack unwound

    def test_span_self_time_excludes_children(self):
        import time as _time

        _on()
        with metrics.span("outer"):
            with metrics.span("inner"):
                _time.sleep(0.02)
        snap = metrics.snapshot()
        # inner has no children: self time == its duration
        assert snap["span_self"]["inner"]["self_s"] >= 0.015
        # outer's self time excludes inner — near zero, far below its
        # total (which contains the sleep)
        assert snap["timers"]["outer"]["total_s"] >= 0.015
        assert snap["span_self"]["outer"]["self_s"] < 0.015
        # and every span feeds its duration histogram
        assert snap["histograms"]["span_ms.inner"]["count"] == 1
        assert snap["histograms"]["span_ms.outer"]["count"] == 1

    def test_traced_decorator(self):
        _on()

        @metrics.traced("deco.fn")
        def fn(x):
            return x + 1

        assert fn(1) == 2
        assert metrics.snapshot()["timers"]["deco.fn"]["count"] == 1

    def test_span_opens_trace_range_when_trace_on(self, monkeypatch):
        _on()
        config.set_flag("TRACE", True)
        opened = []

        @contextlib.contextmanager
        def fake_range(name):
            opened.append(name)
            yield

        monkeypatch.setattr(tracing, "trace_range", fake_range)
        with metrics.span("ranged"):
            pass
        assert opened == ["ranged"]


class TestThreadSafety:
    def test_registry_exact_under_contention(self):
        _on()
        N, M = 8, 1000
        barrier = threading.Barrier(N)

        def hammer():
            barrier.wait()
            for _ in range(M):
                metrics.counter_add("hot")
                metrics.timer_record("hot_t", 0.001)
                metrics.gauge_set("hot_g", 1)

        threads = [threading.Thread(target=hammer) for _ in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        snap = metrics.snapshot()
        assert snap["counters"]["hot"] == N * M
        assert snap["timers"]["hot_t"]["count"] == N * M

    def test_concurrent_dispatch_counts_exact(self):
        """The test_concurrency pattern on the pure-Python wire path:
        per-op counters must stay exact when executor threads dispatch
        concurrently."""
        _on()
        N_THREADS, OPS = 4, 3
        i64 = int(dt.TypeId.INT64)
        op = json.dumps({
            "op": "groupby", "by": [0],
            "aggs": [{"column": 1, "agg": "sum"}],
        })
        errors = []

        def worker(tid):
            try:
                rng = np.random.default_rng(tid)
                for _ in range(OPS):
                    n = 64
                    k = rng.integers(0, 8, n).astype(np.int64)
                    v = rng.integers(-50, 50, n).astype(np.int64)
                    _, _, od, _, rows = rb.table_op_wire(
                        op, [i64, i64], [0, 0],
                        [k.tobytes(), v.tobytes()], [None, None], n,
                    )
                    keys = np.frombuffer(od[0], np.int64, rows)
                    sums = np.frombuffer(od[1], np.int64, rows)
                    want = {
                        int(u): int(v[k == u].sum()) for u in np.unique(k)
                    }
                    if dict(zip(keys.tolist(), sums.tolist())) != want:
                        errors.append((tid, "oracle mismatch"))
            except Exception as e:  # pragma: no cover
                errors.append((tid, repr(e)))

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert errors == []
        snap = metrics.snapshot()
        assert snap["counters"]["op.groupby.calls"] == N_THREADS * OPS
        assert (
            snap["counters"]["op.groupby.rows_in"]
            == N_THREADS * OPS * 64
        )
        assert snap["bytes"]["wire.bytes_in"] == N_THREADS * OPS * 64 * 16
        assert snap["timers"]["dispatch.groupby"]["count"] == N_THREADS * OPS


class TestResidentRoundTrip:
    def test_snapshot_after_resident_groupby_round_trip(self):
        """Acceptance: non-zero op counts, wire bytes, and a resident
        handle high-water mark after an upload -> groupby -> download
        -> free chain."""
        _on()
        n = 128
        rng = np.random.default_rng(5)
        k = rng.integers(0, 10, n).astype(np.int64)
        v = rng.integers(-100, 100, n).astype(np.int64)
        i64 = int(dt.TypeId.INT64)
        tid = rb.table_upload_wire(
            [i64, i64], [0, 0], [k.tobytes(), v.tobytes()],
            [None, None], n,
        )
        gid = rb.table_op_resident(
            json.dumps({
                "op": "groupby", "by": [0],
                "aggs": [{"column": 1, "agg": "sum"}],
            }),
            [tid],
        )
        out = rb.table_download_wire(gid)
        rb.table_free(tid)
        rb.table_free(gid)
        assert out[4] > 0
        snap = metrics.snapshot()
        assert snap["counters"]["op.groupby.calls"] >= 1
        assert snap["bytes"]["wire.bytes_in"] >= n * 16
        assert snap["bytes"]["wire.bytes_out"] > 0
        assert snap["gauges"]["resident.live"]["high_water"] >= 2
        # the chain freed what it allocated: live back to zero but the
        # high-water mark preserves the peak (the leak-report analog)
        assert snap["gauges"]["resident.live"]["value"] == 0
        assert (
            snap["counters"]["resident.put"]
            == snap["counters"]["resident.free"]
        )
        assert snap["timers"]["wire.deserialize"]["count"] >= 1
        assert snap["timers"]["wire.serialize"]["count"] >= 1

    def test_hbm_plan_metrics(self):
        _on()
        from spark_rapids_jni_tpu.utils import hbm

        t = Table(
            [
                Column.from_numpy(np.arange(64, dtype=np.int64)),
                Column.from_numpy(np.arange(64, dtype=np.int64)),
            ],
            ["k", "v"],
        )
        hbm.join_plan(t, t, ["k"], ["k"])
        hbm.groupby_plan(t, ["k"], 16)
        snap = metrics.snapshot()
        assert snap["counters"]["hbm.plan.join"] == 1
        assert snap["counters"]["hbm.plan.groupby"] == 1
        assert snap["bytes"]["hbm.planned_bytes"] > 0
        assert snap["gauges"]["hbm.budget_bytes"]["value"] > 0


class TestStdoutHygiene:
    def test_trace_level_plus_dump_never_writes_stdout(self, tmp_path):
        """LOG_LEVEL=TRACE + METRICS + a dump path: stderr carries the
        telemetry, the dump file carries the snapshot, stdout stays
        EMPTY (it is the bench-JSON wire protocol)."""
        dump = tmp_path / "metrics.json"
        code = (
            "import json, numpy as np\n"
            "from spark_rapids_jni_tpu import dtype as dt\n"
            "from spark_rapids_jni_tpu import runtime_bridge as rb\n"
            "from spark_rapids_jni_tpu.utils import hbm\n"
            "from spark_rapids_jni_tpu.column import Column, Table\n"
            "k = np.arange(32, dtype=np.int64)[::-1].copy()\n"
            "op = json.dumps({'op': 'sort_by',"
            " 'keys': [{'column': 0}]})\n"
            "rb.table_op_wire(op, [int(dt.TypeId.INT64)], [0],"
            " [k.tobytes()], [None], 32)\n"
            "t = Table([Column.from_numpy(k)], ['k'])\n"
            "hbm.sort_plan(t, 1)\n"
            "tid = rb._resident_put(t)\n"
            "rb.table_free(tid)\n"
        )
        env = dict(os.environ)
        env.update({
            "SPARK_RAPIDS_TPU_LOG_LEVEL": "TRACE",
            "SPARK_RAPIDS_TPU_METRICS": "1",
            "SPARK_RAPIDS_TPU_METRICS_DUMP": str(dump),
            "JAX_PLATFORMS": "cpu",
            "SRT_JAX_PLATFORMS": "cpu",
        })
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=300, env=env, cwd=_ROOT,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert proc.stdout == ""
        assert "[srt]" in proc.stderr  # telemetry went to stderr
        # the atexit dump landed and parses
        snap = json.loads(dump.read_text())
        assert snap["counters"]["op.sort_by.calls"] == 1
        assert snap["bytes"]["wire.bytes_in"] > 0
        assert snap["gauges"]["resident.live"]["high_water"] >= 1

    def test_dump_helper_handles_bad_path(self, capsys):
        _on()
        config.set_flag("METRICS_DUMP", "/nonexistent-dir/x/metrics.json")
        assert metrics.dump() is None
        assert "[srt][metrics][WARN]" in capsys.readouterr().err


class TestCaptureTrace:
    def _fake_profiler(self, monkeypatch, writes=None):
        import types

        import jax

        calls = []

        @contextlib.contextmanager
        def fake_trace(log_dir):
            calls.append(log_dir)
            if writes:
                with open(os.path.join(log_dir, writes), "w") as f:
                    f.write("x")
            yield

        monkeypatch.setattr(
            jax, "profiler",
            types.SimpleNamespace(trace=fake_trace),
            raising=False,
        )
        return calls

    def test_creates_missing_dir_and_warns_when_empty(
        self, tmp_path, monkeypatch, capsys
    ):
        target = str(tmp_path / "deep" / "traces")
        calls = self._fake_profiler(monkeypatch)
        with tracing.capture_trace(target):
            pass
        assert calls == [target]
        assert os.path.isdir(target)
        assert "[srt][trace][WARN]" in capsys.readouterr().err

    def test_no_warn_when_capture_produced_files(
        self, tmp_path, monkeypatch, capsys
    ):
        target = str(tmp_path / "traces")
        self._fake_profiler(monkeypatch, writes="trace.pb")
        with tracing.capture_trace(target):
            pass
        assert "[srt][trace][WARN]" not in capsys.readouterr().err


class TestBenchFailureRecords:
    def test_failure_record_shape(self):
        import bench

        r = bench._failure_record(
            "join", ValueError("boom"), elapsed_s=1.234, retries=2
        )
        assert r["name"] == "join"
        assert r["error"] == "boom"
        assert r["failure"] == {
            "type": "ValueError",
            "message": "boom",
            "class": "PermanentError",
            "elapsed_s": 1.234,
            "retries": 2,
            "backoff_ms": 0.0,
            "skipped": False,
        }
        json.dumps(r)

    def test_unreachable_ladder_is_structured(self, monkeypatch, tmp_path):
        """Acceptance: every config entry carries either a metrics block
        or a structured failure record — no bare error strings."""
        import io

        import bench

        monkeypatch.setattr(bench, "_probe_device", lambda *a, **k: False)
        monkeypatch.setattr(bench, "_stop_daemon", lambda: None)
        monkeypatch.setattr(bench, "_STATE_PATH", str(tmp_path / "s.json"))
        monkeypatch.setenv("SRT_BENCH_DEADLINE_S", "-1")
        # pre-set the store dir so monkeypatch restores it: bench's
        # _metrics_enable exports it (setdefault) for its subprocesses
        monkeypatch.setenv(
            "SPARK_RAPIDS_TPU_PLANSTATS_DIR", str(tmp_path / "planstats")
        )
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bench.main()
        last = json.loads(buf.getvalue().strip().splitlines()[-1])
        by_name = {e["name"]: e for e in last["configs"]}
        # every ladder arm is present, plus the mesh tail's typed skip
        # records (the arms never vanish into bare progress lines)
        assert set(bench._LADDER) <= set(by_name)
        for e in last["configs"]:
            assert "metrics" in e or "failure" in e, e
        for arm in bench._LADDER:
            f = by_name[arm]["failure"]
            assert f["type"] == "DeviceUnreachable"
            assert f["message"] == "device unreachable"
            assert f["elapsed_s"] is not None
            assert f["retries"] == 1
        extra = set(by_name) - set(bench._LADDER)
        for arm in extra:
            f = by_name[arm]["failure"]
            assert f["skipped"] is True
            assert f["type"] in ("BudgetExceeded", "OptInSkipped")


def _analyze_mod():
    spec = importlib.util.spec_from_file_location(
        "analyze_bench", os.path.join(_ROOT, "tools", "analyze_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestAnalyzeBench:
    def test_merge_and_summarize_metrics(self, capsys):
        mod = _analyze_mod()
        block = {
            "timers": {"dispatch.groupby": {"count": 3, "total_s": 1.5}},
            "bytes": {"wire.bytes_in": 1_000_000},
            "counters": {"op.groupby.calls": 3},
        }
        raw = [
            {"name": "a", "seconds_median": 1.0, "metrics": block},
            # same snapshot shared by a sibling entry: folded once
            {"name": "b", "seconds_median": 2.0, "metrics": block},
            {"name": "old-entry-without-metrics", "seconds_median": 3.0},
        ]
        merged = mod._merge_metrics(raw)
        assert merged["timers"]["dispatch.groupby"]["count"] == 3
        assert merged["bytes"]["wire.bytes_in"] == 1_000_000
        mod.summarize_metrics(raw)
        out = capsys.readouterr().out
        assert "dispatch.groupby" in out
        assert "wire.bytes_in" in out
        assert "groupby" in out

    def test_tolerates_old_entries(self, capsys):
        mod = _analyze_mod()
        mod.summarize_metrics([{"name": "x", "seconds_median": 1.0}])
        assert "no metrics blocks" in capsys.readouterr().out

    def test_hist_percentile_upper_edges(self):
        mod = _analyze_mod()
        # 3 observations, one per bucket: p50 lands on the 2nd edge
        assert mod._hist_percentile([1, 10, 100], [1, 1, 1, 0], 0.5) == 10.0
        # all mass in the overflow bucket: percentile is ">max"
        assert mod._hist_percentile([1, 10], [0, 0, 5], 0.95) == float("inf")
        assert mod._hist_percentile([1], [0, 0], 0.5) is None

    def test_summarize_spans_percentiles_and_self_time(self, capsys):
        mod = _analyze_mod()
        block = {
            "timers": {
                "dispatch.sort_by": {
                    "count": 3, "total_s": 1.0, "min_s": 0.1, "max_s": 0.7,
                },
            },
            "histograms": {
                "span_ms.dispatch.sort_by": {
                    "bounds": [1, 10, 100], "counts": [1, 1, 1, 0],
                    "count": 3, "sum": 60.0,
                },
                # non-span histogram must not rank as a span
                "dispatch.rows_in": {
                    "bounds": [1], "counts": [1, 0], "count": 1, "sum": 1.0,
                },
            },
            "span_self": {
                "dispatch.sort_by": {"count": 3, "self_s": 0.4},
            },
        }
        mod.summarize_spans([{"name": "a", "metrics": block}])
        out = capsys.readouterr().out
        assert "span durations" in out
        assert "dispatch.sort_by" in out
        assert "rows_in" not in out
        assert "top 5 ops by self time" in out
        assert "40% of span" in out

    def test_summarize_spans_tolerates_old_files(self, capsys):
        mod = _analyze_mod()
        # pre-flight-recorder metrics blocks and metric-less entries
        # produce NO span section (quiet skip, not a crash)
        mod.summarize_spans([
            {"name": "x", "seconds_median": 1.0},
            {"name": "y", "metrics": {"timers": {}, "bytes": {}}},
        ])
        assert capsys.readouterr().out == ""

    def test_load_bench_file_with_failures(self, tmp_path, capsys):
        mod = _analyze_mod()
        doc = {
            "metric": "groupby_sum_100M_int64",
            "configs": [
                {"name": "groupby_sum_16M", "seconds_median": 1.0},
                {
                    "name": "join",
                    "error": "timeout 60s",
                    "failure": {
                        "type": "TimeoutExpired",
                        "message": "timeout 60s",
                        "elapsed_s": 60.0,
                        "retries": 1,
                    },
                },
            ],
        }
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(doc))
        entries, raw, drift = mod._load(str(p))
        assert "groupby_sum_16M" in entries
        assert "join" not in entries  # failures never rank in the A/B
        assert drift is None  # pre-planstats file: no drift block
        mod.summarize_failures(raw)
        out = capsys.readouterr().out
        assert "TimeoutExpired" in out and "join" in out

    def test_load_surfaces_headline_drift_block(self, tmp_path):
        mod = _analyze_mod()
        doc = {
            "metric": "groupby_sum_100M_int64",
            "drift": {"records": 6, "plans": 2,
                      "findings": {"cardinality": 1}},
            "configs": [{"name": "a", "seconds_median": 1.0}],
        }
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(doc))
        _, _, drift = mod._load(str(p))
        assert drift == {"records": 6, "plans": 2,
                         "findings": {"cardinality": 1}}

    def test_summarize_drift_with_findings(self, capsys):
        mod = _analyze_mod()
        mod.summarize_drift(
            {"records": 6, "plans": 2,
             "findings": {"cardinality": 1, "hbm": 2}}
        )
        out = capsys.readouterr().out
        assert "6 stats record(s) over 2 plan group(s)" in out
        assert "cardinality=1" in out and "hbm=2" in out
        assert "explain.py --drift" in out

    def test_summarize_drift_clean_store(self, capsys):
        mod = _analyze_mod()
        mod.summarize_drift({"records": 3, "plans": 1, "findings": {}})
        out = capsys.readouterr().out
        assert "no drift findings" in out

    def test_summarize_drift_tolerates_old_files(self, capsys):
        # pre-planstats BENCH files pass None through _load: quiet skip
        mod = _analyze_mod()
        mod.summarize_drift(None)
        assert capsys.readouterr().out == ""
