"""Scan + compaction fuzz vs the pandas oracle.

Running aggregates (cumsum/cummin/cummax/cumprod incl. exclusive
form) with null skip-and-stay-null semantics, and the distinct /
drop-nulls family (first-occurrence keep order, null keys equal to
each other), checked against pandas."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.compaction import distinct, drop_nulls
from spark_rapids_jni_tpu.ops.scan import scan

_PD_SCAN = {
    "sum": "cumsum", "min": "cummin", "max": "cummax",
    "product": "cumprod",
}


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("agg", ["sum", "min", "max", "product"])
@pytest.mark.parametrize("with_nulls", [False, True])
def test_scan_vs_pandas(seed, agg, with_nulls):
    rng = np.random.default_rng(seed)
    n = 200
    lo, hi = ((-40, 40) if agg != "product" else (1, 3))
    v = rng.integers(lo, hi, n, dtype=np.int64)
    valid = rng.random(n) > 0.2 if with_nulls else None
    col = Column.from_numpy(v, validity=valid)
    got = scan(col, agg).to_pylist()
    ser = pd.Series(v, dtype="Int64")
    if valid is not None:
        ser = ser.mask(~valid)
    want = getattr(ser, _PD_SCAN[agg])().tolist()
    want = [None if x is pd.NA else int(x) for x in want]
    # null rows stay null in both; valid rows skip nulls in the running agg
    assert got == want, (agg, [
        (i, g, w) for i, (g, w) in enumerate(zip(got, want)) if g != w
    ][:4])


def test_exclusive_scan_shifts_with_identity():
    v = np.array([3, 1, 4, 1, 5], dtype=np.int64)
    col = Column.from_numpy(v)
    got = scan(col, "sum", inclusive=False).to_pylist()
    assert got == [0, 3, 4, 8, 9]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_distinct_first_occurrence_vs_pandas(seed):
    rng = np.random.default_rng(seed)
    n = 300
    k = rng.integers(0, 12, n, dtype=np.int64)
    valid = rng.random(n) > 0.2
    v = np.arange(n, dtype=np.int64)
    t = Table(
        [Column.from_numpy(k, validity=valid), Column.from_numpy(v)],
        ["k", "v"],
    )
    got = distinct(t, ["k"])
    pdf = pd.DataFrame({"k": pd.array(k, dtype="Int64"), "v": v})
    pdf.loc[~valid, "k"] = pd.NA
    want = pdf.drop_duplicates(subset="k", keep="first")
    assert got["k"].to_pylist() == [
        None if pd.isna(x) else int(x) for x in want["k"]
    ]
    assert got["v"].to_pylist() == [int(x) for x in want["v"]]


def test_distinct_multi_key_and_full_row():
    rng = np.random.default_rng(7)
    n = 200
    a = rng.integers(0, 4, n, dtype=np.int64)
    b = rng.integers(0, 4, n, dtype=np.int64)
    t = Table([Column.from_numpy(a), Column.from_numpy(b)], ["a", "b"])
    got = distinct(t)  # all columns
    pdf = pd.DataFrame({"a": a, "b": b}).drop_duplicates(keep="first")
    assert got["a"].to_pylist() == pdf["a"].tolist()
    assert got["b"].to_pylist() == pdf["b"].tolist()


def test_drop_nulls_vs_pandas():
    rng = np.random.default_rng(8)
    n = 150
    k = rng.integers(0, 9, n, dtype=np.int64)
    valid_k = rng.random(n) > 0.25
    w = rng.standard_normal(n)
    valid_w = rng.random(n) > 0.25
    t = Table(
        [
            Column.from_numpy(k, validity=valid_k),
            Column.from_numpy(w, validity=valid_w),
        ],
        ["k", "w"],
    )
    got = drop_nulls(t, ["k"])
    keep = valid_k
    assert got["k"].to_pylist() == [int(x) for x in k[keep]]
    got_all = drop_nulls(t, ["k", "w"])
    keep_all = valid_k & valid_w
    assert got_all["k"].to_pylist() == [int(x) for x in k[keep_all]]
