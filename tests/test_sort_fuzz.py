"""ORDER BY fuzz vs the pandas sort oracle.

Random multi-key sorts — mixed directions, explicit and Spark-default
null placement, int/float/string keys, duplicate keys (stability) —
against ``DataFrame.sort_values`` with matching na_position. The
packed fast path and the general path are both pinned: the router's
choice must never change the answer."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.sort import SortKey, sort_table
from spark_rapids_jni_tpu.ops.sort_packed import sort_table_packed


def _frame(rng, n, with_nulls):
    k1 = rng.integers(-20, 20, n, dtype=np.int64)
    k2 = rng.standard_normal(n).round(2)
    v = np.arange(n, dtype=np.int64)  # row id: makes stability visible
    valid = rng.random(n) > 0.15 if with_nulls else None
    cols = [
        Column.from_numpy(k1, validity=valid),
        Column.from_numpy(k2),
        Column.from_numpy(v),
    ]
    t = Table(cols, ["k1", "k2", "v"])
    pdf = pd.DataFrame({"k1": k1, "k2": k2, "v": v})
    if valid is not None:
        pdf["k1"] = pdf["k1"].astype("Int64").mask(~valid)
    return t, pdf


def _check(got: Table, pdf_sorted: pd.DataFrame):
    for name in got.names:
        g = got[name].to_pylist()
        w = [
            None if pd.isna(x) else (float(x) if name == "k2" else int(x))
            for x in pdf_sorted[name]
        ]
        assert g == w, name


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("asc1,asc2", [(True, True), (False, True),
                                       (True, False), (False, False)])
def test_two_key_mixed_directions(seed, asc1, asc2):
    rng = np.random.default_rng(seed)
    t, pdf = _frame(rng, 300, with_nulls=False)
    got = sort_table(t, [SortKey("k1", asc1), SortKey("k2", asc2)])
    want = pdf.sort_values(
        ["k1", "k2"], ascending=[asc1, asc2], kind="stable"
    )
    _check(got, want)


@pytest.mark.parametrize("asc", [True, False])
@pytest.mark.parametrize("nulls_first", [None, True, False])
def test_null_placement(asc, nulls_first):
    rng = np.random.default_rng(9)
    t, pdf = _frame(rng, 300, with_nulls=True)
    got = sort_table(
        t, [SortKey("k1", asc, nulls_first), SortKey("v")]
    )
    eff_first = nulls_first if nulls_first is not None else asc
    want = pdf.sort_values(
        ["k1", "v"],
        ascending=[asc, True],
        kind="stable",
        na_position="first" if eff_first else "last",
    )
    _check(got, want)


def test_stability_on_duplicate_keys():
    rng = np.random.default_rng(4)
    n = 400
    k = rng.integers(0, 5, n, dtype=np.int64)  # heavy duplicates
    v = np.arange(n, dtype=np.int64)
    t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
    got = sort_table(t, [SortKey("k")])
    want = pd.DataFrame({"k": k, "v": v}).sort_values("k", kind="stable")
    _check(got, want)


def test_string_key_nulls_ordered_by_secondary():
    """Multi-word (string) nullable key: EVERY key word must zero for
    null rows, or the null block reorders by hidden bytes."""
    subs = ["zz", None, "aa", None, "mm", None]
    t = Table(
        [Column.from_strings(subs),
         Column.from_numpy(np.arange(6, dtype=np.int64))],
        ["k", "r"],
    )
    out = sort_table(t, [SortKey("k", True, None), SortKey("r")])
    assert out["k"].to_pylist() == [None, None, None, "aa", "mm", "zz"]
    assert out["r"].to_pylist() == [1, 3, 5, 2, 4, 0]


@pytest.mark.parametrize("seed", [0, 1])
def test_packed_router_parity(seed):
    """sort_table_packed (when eligible) must equal the general path."""
    rng = np.random.default_rng(seed + 20)
    n = 500
    k = rng.integers(-1000, 1000, n, dtype=np.int64)
    w = rng.integers(0, 50, n, dtype=np.int64)
    v = rng.standard_normal(n)
    t = Table(
        [Column.from_numpy(k), Column.from_numpy(w),
         Column.from_numpy(v)],
        ["k", "w", "v"],
    )
    keys = [SortKey("k", False), SortKey("w")]
    general = sort_table(t, keys)
    for via in ("sort", "gather"):
        packed = sort_table_packed(t, keys, values_via=via)
        assert packed is not None
        for name in t.names:
            np.testing.assert_array_equal(
                np.asarray(packed[name].data),
                np.asarray(general[name].data),
                err_msg=f"{via}:{name}",
            )
            assert packed[name].to_pylist() == general[name].to_pylist()
