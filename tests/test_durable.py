"""Durable serving plane: crash-safe checkpoint/restore, reconnect.

The ISSUE-14 contract under test: with ``SPARK_RAPIDS_TPU_DURABLE=on``
the daemon journals every namespace mutation (upload / plan output /
free / bye) to a per-session write-ahead log with CRC-framed fsync'd
records; a restarted daemon replays the journals into live sessions —
tables byte-identical, budgets and HBM accounting re-charged, the
idempotency window intact — BEFORE the listener accepts traffic, and
warm-starts the compile cache from the plan manifest so replayed plans
recompile nothing. Torn journal tails (crash mid-append) are truncated
and recovered; mid-file corruption quarantines that one session and
never crashes the daemon. Clients reconnect with a resume token and
replay mutating commands by request id for at-most-once application.
The disabled path (the default) costs under 5µs per mutation.
"""

import os
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.serving import durable, frames
from spark_rapids_jni_tpu.utils import config, faults, metrics, spill

I64 = int(dt.TypeId.INT64)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)
STR = int(dt.TypeId.STRING)

# one jittable op so warm-start exercises the compile cache
CAST = [{"op": "cast", "column": 1, "type_id": F64}]


@pytest.fixture(autouse=True)
def _durable_env(tmp_path):
    """Every test runs durable-on against its own checkpoint dir
    (tests that need the disabled path clear the flag themselves)."""
    config.set_flag("DURABLE", "on")
    config.set_flag("CHECKPOINT_DIR", str(tmp_path / "ckpt"))
    durable.reset()
    yield
    pipeline.drain()
    for name in ("DURABLE", "CHECKPOINT_DIR", "METRICS", "FAULTS",
                 "PIPELINE", "BUCKETS", "HBM_BUDGET_GB",
                 "SERVE_MAX_SESSIONS", "SERVE_QUEUE_DEPTH",
                 "SERVE_SESSION_HBM_FRACTION", "SERVE_PORT"):
        config.clear_flag(name)
    pipeline.depth()


def _string_wire(strings):
    payload = b"".join(s.encode() for s in strings)
    offs = np.zeros(len(strings) + 1, np.int32)
    np.cumsum([len(s.encode()) for s in strings], out=offs[1:])
    return offs.tobytes() + payload


def _batch(n: int, seed: int = 0):
    rng = np.random.default_rng(n + 7919 * seed)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % 5 != 0).astype(np.uint8)
    strs = [("s" * (int(x) % 3 + 1)) for x in k]
    return (
        [I64, I64, STR], [0, 0, 0],
        [k.tobytes(), v.tobytes(), _string_wire(strs)],
        [None, valid.tobytes(), None],
        n,
    )


def _canon(batch):
    type_ids, scales, datas, valids, n = batch
    return (
        list(type_ids), list(scales),
        [bytes(b) for b in datas],
        [None if v is None else bytes(v) for v in valids],
        int(n),
    )


# ---------------------------------------------------------------------------
# journal format: framing, torn tails, mid-file corruption
# ---------------------------------------------------------------------------
class TestJournal:
    def test_round_trip(self, tmp_path):
        p = str(tmp_path / "a.wal")
        j = durable.Journal(p)
        recs = [
            {"t": "open", "name": "s", "weight": 1.0, "budget": 9,
             "token": "x"},
            {"t": "put", "local": 1, "bytes": 10, "file": "f.npz"},
            {"t": "free", "local": 1, "bytes": 10},
        ]
        for r in recs:
            j.append(r)
        j.close()
        got, torn, _ = durable.read_journal(p)
        assert torn == 0
        assert got == recs

    def test_truncation_at_every_byte_is_a_torn_tail(self, tmp_path):
        """Crash-mid-append leaves a prefix of the file; EVERY prefix
        must replay to exactly the records whose frames fit whole —
        never an error, never a phantom record."""
        p = str(tmp_path / "a.wal")
        j = durable.Journal(p)
        ends = [j._good]  # offset after magic = 0 records
        for i in range(4):
            j.append({"t": "put", "local": i, "bytes": i * 3,
                      "file": f"f{i}.npz"})
            ends.append(j._good)
        j.close()
        blob = open(p, "rb").read()
        assert ends[-1] == len(blob)
        cut_path = str(tmp_path / "cut.wal")
        for cut in range(len(durable._MAGIC), len(blob) + 1):
            with open(cut_path, "wb") as f:
                f.write(blob[:cut])
            got, torn, good = durable.read_journal(cut_path)
            whole = max(i for i, e in enumerate(ends) if e <= cut)
            assert len(got) == whole, f"cut={cut}"
            assert good == ends[whole], f"cut={cut}"
            assert torn == (0 if cut in ends else 1), f"cut={cut}"
            for i, r in enumerate(got):
                assert r["local"] == i

    def test_magic_missing_is_corrupt(self, tmp_path):
        p = str(tmp_path / "b.wal")
        with open(p, "wb") as f:
            f.write(b"not a journal at all")
        with pytest.raises(durable.CheckpointCorrupt):
            durable.read_journal(p)

    def test_mid_file_corruption_is_corrupt_not_torn(self, tmp_path):
        """A bad CRC with MORE bytes after it is disk corruption, not
        a crash artifact: typed error, never silent truncation."""
        p = str(tmp_path / "c.wal")
        j = durable.Journal(p)
        j.append({"t": "put", "local": 1, "bytes": 4, "file": "x"})
        first_end = j._good
        j.append({"t": "free", "local": 1, "bytes": 4})
        j.close()
        blob = bytearray(open(p, "rb").read())
        flip = len(durable._MAGIC) + durable._FRAME.size + 2
        assert flip < first_end
        blob[flip] ^= 0xFF
        with open(p, "wb") as f:
            f.write(blob)
        with pytest.raises(durable.CheckpointCorrupt) as ei:
            durable.read_journal(p)
        assert "mid-journal" in str(ei.value)

    def test_append_self_heals_after_torn_write(self, tmp_path):
        """An injected torn write (chaos site ``checkpoint``) leaves a
        partial frame; the NEXT append truncates back to the last good
        offset first, so one degraded record never poisons the log."""
        p = str(tmp_path / "d.wal")
        j = durable.Journal(p)
        j.append({"t": "put", "local": 1, "bytes": 2, "file": "x"})
        config.set_flag("FAULTS", "seed=3,checkpoint:permanent:1:1")
        try:
            with pytest.raises(faults.FaultError):
                j.append({"t": "put", "local": 2, "bytes": 2, "file": "y"})
        finally:
            config.set_flag("FAULTS", "")
        assert os.path.getsize(p) > j._good  # torn bytes on disk
        j.append({"t": "put", "local": 3, "bytes": 2, "file": "z"})
        j.close()
        got, torn, _ = durable.read_journal(p)
        assert torn == 0
        assert [r["local"] for r in got] == [1, 3]

    def test_restore_scan_truncates_torn_tail(self, tmp_path):
        d = str(tmp_path / "scan")
        os.makedirs(d)
        j = durable.Journal(os.path.join(d, "s1.wal"))
        j.append({"t": "open", "name": "n", "weight": 1.0, "budget": 8,
                  "token": "t"})
        good = j._good
        j.close()
        with open(os.path.join(d, "s1.wal"), "ab") as f:
            f.write(b"\x99" * 7)  # torn partial frame
        sessions, quarantined = durable.restore_scan(d)
        assert not quarantined
        assert len(sessions) == 1 and sessions[0].sid == "s1"
        assert os.path.getsize(os.path.join(d, "s1.wal")) == good

    def test_restore_scan_quarantines_corrupt_journal(self, tmp_path):
        d = str(tmp_path / "scan2")
        os.makedirs(d)
        j = durable.Journal(os.path.join(d, "bad.wal"))
        j.append({"t": "open", "name": "n", "weight": 1.0, "budget": 8,
                  "token": "t"})
        j.append({"t": "free", "local": 1, "bytes": 0})
        j.close()
        blob = bytearray(open(os.path.join(d, "bad.wal"), "rb").read())
        blob[len(durable._MAGIC) + durable._FRAME.size] ^= 0xFF
        with open(os.path.join(d, "bad.wal"), "wb") as f:
            f.write(blob)
        sessions, quarantined = durable.restore_scan(d)
        assert sessions == []
        assert "bad" in quarantined
        assert os.path.exists(os.path.join(d, "bad.wal.quarantined"))
        assert not os.path.exists(os.path.join(d, "bad.wal"))

    def test_bye_erases_session(self, tmp_path):
        d = str(tmp_path / "bye")
        os.makedirs(d)
        dlog = durable.SessionLog("s9", d)
        dlog.log_open("n", 1.0, 8, "tok")
        dlog.log_bye()
        sessions, quarantined = durable.restore_scan(d)
        assert sessions == [] and not quarantined
        assert not os.path.exists(os.path.join(d, "s9.wal"))


# ---------------------------------------------------------------------------
# checkpoint dir knob + sweep regression
# ---------------------------------------------------------------------------
class TestCheckpointDir:
    def test_parser_rejects_whitespace(self, monkeypatch):
        config.clear_flag("CHECKPOINT_DIR")
        monkeypatch.setenv("SPARK_RAPIDS_TPU_CHECKPOINT_DIR", "   ")
        with pytest.raises(ValueError) as ei:
            config.get_flag("CHECKPOINT_DIR")
        assert "SPARK_RAPIDS_TPU_CHECKPOINT_DIR" in str(ei.value)

    def test_parser_rejects_file_path(self, tmp_path, monkeypatch):
        config.clear_flag("CHECKPOINT_DIR")
        f = tmp_path / "plain-file"
        f.write_text("x")
        monkeypatch.setenv("SPARK_RAPIDS_TPU_CHECKPOINT_DIR", str(f))
        with pytest.raises(ValueError) as ei:
            config.get_flag("CHECKPOINT_DIR")
        assert "not a directory" in str(ei.value)

    def test_sweep_spares_checkpoint_files(self, tmp_path):
        """THE sweep regression: ``spill._sweep_at_exit`` (and
        ``spill.reset``) unconditionally unlink everything registered
        in ``_FILES``. Checkpoint payloads written through the same
        ``.npz`` serde must survive a sweep — a daemon restart that
        also tears down spill must not eat its own durable state."""
        ckpt_dir = config.get_flag("CHECKPOINT_DIR")
        os.makedirs(ckpt_dir, exist_ok=True)
        keep = os.path.join(ckpt_dir, "sess-t1.npz")
        with open(keep, "wb") as f:
            f.write(b"payload")
        gone = str(tmp_path / "spilled.npz")
        with open(gone, "wb") as f:
            f.write(b"spill")
        spill._FILES.update({keep, gone})
        try:
            spill._sweep_at_exit()
            assert os.path.exists(keep), "sweep ate a checkpoint file"
            assert not os.path.exists(gone)
            assert keep not in spill._FILES
        finally:
            spill._FILES.discard(keep)
            spill._FILES.discard(gone)

    def test_reset_spares_checkpoint_files(self):
        ckpt_dir = config.get_flag("CHECKPOINT_DIR")
        os.makedirs(ckpt_dir, exist_ok=True)
        keep = os.path.join(ckpt_dir, "sess-t2.npz")
        with open(keep, "wb") as f:
            f.write(b"payload")
        spill._FILES.add(keep)
        try:
            spill.reset()
            assert os.path.exists(keep)
        finally:
            os.unlink(keep)


# ---------------------------------------------------------------------------
# table payload serde (spill .npz round trip)
# ---------------------------------------------------------------------------
class TestPayloadSerde:
    def test_round_trip_bytes(self, tmp_path):
        wire = _batch(97, seed=3)
        t = rb._table_from_wire(*wire, None)
        tid = rb._resident_put(t)
        p = str(tmp_path / "t.npz")
        n = spill.save_table_npz(p, t)
        assert n > 0 and os.path.exists(p)
        t2 = spill.load_table_npz(p)
        tid2 = rb._resident_put(t2)
        assert _canon(rb.table_download_wire(tid2)) == _canon(
            rb.table_download_wire(tid)
        )
        rb.table_free(tid)
        rb.table_free(tid2)

    def test_load_payload_wraps_read_errors(self, tmp_path):
        p = str(tmp_path / "junk.npz")
        with open(p, "wb") as f:
            f.write(b"not an npz")
        with pytest.raises(durable.CheckpointCorrupt):
            durable.load_payload(p)


# ---------------------------------------------------------------------------
# disabled path: the default must stay effectively free
# ---------------------------------------------------------------------------
class TestDisabledPath:
    def test_disabled_gate_under_5us(self):
        config.clear_flag("DURABLE")
        durable.enabled()  # prime the generation cache
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            durable.enabled()
        per = (time.perf_counter() - t0) / n
        assert durable.enabled() is False
        assert per < 5e-6, f"disabled gate {per * 1e6:.2f}us >= 5us"

    def test_disabled_server_journals_nothing(self, tmp_path):
        config.clear_flag("DURABLE")
        ckpt = config.get_flag("CHECKPOINT_DIR")
        with serving.Server(workers=1) as srv:
            with serving.Client(srv.port, name="d") as c:
                assert c.resume_token is None
                t1 = c.upload(_batch(16), req="u1")
                c.free(t1, req="f1")
        assert not os.path.exists(ckpt) or not os.listdir(ckpt)


# ---------------------------------------------------------------------------
# server restore: crash, restart, byte parity, budgets, warm start
# ---------------------------------------------------------------------------
class TestRestore:
    def test_crash_restart_recovers_sessions_bytes_and_dedup(self):
        config.set_flag("METRICS", "on")
        wire_a, wire_b = _batch(200, seed=1), _batch(64, seed=2)
        srv = serving.Server(workers=2)
        srv.start()
        ca = serving.Client(srv.port, name="a").connect()
        cb = serving.Client(srv.port, name="b").connect()
        ta1 = ca.upload(wire_a, req="a-up-1")
        ta2 = ca.plan(CAST, [ta1], req="a-plan-1")
        tb1 = cb.upload(wire_b, req="b-up-1")
        want_a = _canon(ca.download(ta2))
        want_b = _canon(cb.download(tb1))
        sid_a, tok_a = ca.session, ca.resume_token
        sid_b, tok_b = cb.session, cb.resume_token
        assert tok_a and tok_b and tok_a != tok_b
        ca.kill()
        cb.kill()
        srv.stop()  # simulated crash: no bye, files stay

        srv2 = serving.Server(workers=2)
        srv2.start()
        try:
            doc = srv2.stats()["durability"]
            assert doc["restore"]["sessions"] == 2
            assert doc["restore"]["quarantined"] == {}
            assert doc["restore"]["warm_compiles"] >= 1
            assert doc["restore"]["warm_failures"] == 0

            ca2 = serving.Client(
                srv2.port, session=sid_a, resume=tok_a).connect()
            cb2 = serving.Client(
                srv2.port, session=sid_b, resume=tok_b).connect()
            assert _canon(ca2.download(ta2)) == want_a
            assert _canon(cb2.download(tb1)) == want_b
            # the idempotency window survived the restart: a replayed
            # request id returns the original response, applies nothing
            assert ca2.upload(wire_a, req="a-up-1") == ta1
            assert ca2.plan(CAST, [ta1], req="a-plan-1") == ta2
            # replayed plans land on the warmed compile cache
            snap = metrics.snapshot()["counters"]
            miss0 = snap.get("compile_cache.miss", 0)
            t_new = ca2.plan(CAST, [ta1], req="a-plan-2")
            ca2.download(t_new)
            snap = metrics.snapshot()["counters"]
            assert snap.get("compile_cache.miss", 0) == miss0
            # budgets were re-charged, not zeroed: the restored bytes
            # count against the session
            stats = srv2.stats()
            sess_a = next(s for s in stats["sessions"]
                          if s["session"] == sid_a)
            assert sess_a["resident_bytes"] > 0
            ca2.close()
            cb2.close()
        finally:
            srv2.stop()
        # clean byes erased both sessions' durable state
        ckpt = config.get_flag("CHECKPOINT_DIR")
        left = [f for f in os.listdir(ckpt) if f != "manifest.wal"]
        assert left == []

    def test_free_is_journaled(self):
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="f").connect()
        t1 = c.upload(_batch(32), req="u1")
        t2 = c.upload(_batch(48), req="u2")
        c.free(t1, req="f1")
        sid, tok = c.session, c.resume_token
        c.kill()
        srv.stop()
        srv2 = serving.Server(workers=1)
        srv2.start()
        try:
            c2 = serving.Client(
                srv2.port, session=sid, resume=tok).connect()
            with pytest.raises(serving.ServingTableError):
                c2.download(t1)
            assert _canon(c2.download(t2)) == _canon(_batch(48))
            c2.close()
        finally:
            srv2.stop()

    def test_resume_token_enforced(self):
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="r").connect()
        sid = c.session
        c.kill()
        try:
            with pytest.raises(serving.ServingResumeDenied):
                serving.Client(
                    srv.port, session=sid, resume="wrong").connect()
            with pytest.raises(serving.ServingResumeDenied):
                serving.Client(srv.port, session=sid).connect()
        finally:
            srv.stop()

    def test_donating_plan_drops_input_payload(self):
        """A donated plan input is consumed: its checkpoint payload is
        dropped with the journal record, and a restart restores only
        the output."""
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="d").connect()
        t1 = c.upload(_batch(128, seed=5), req="u1")
        t2 = c.plan(CAST, [t1], donate=True, req="p1")
        want = _canon(c.download(t2))
        sid, tok = c.session, c.resume_token
        c.kill()
        srv.stop()
        ckpt = config.get_flag("CHECKPOINT_DIR")
        names = os.listdir(ckpt)
        assert f"{sid}-t{t1}.npz" not in names
        assert f"{sid}-t{t2}.npz" in names
        srv2 = serving.Server(workers=1)
        srv2.start()
        try:
            c2 = serving.Client(
                srv2.port, session=sid, resume=tok).connect()
            assert _canon(c2.download(t2)) == want
            with pytest.raises(serving.ServingTableError):
                c2.download(t1)
            c2.close()
        finally:
            srv2.stop()


# ---------------------------------------------------------------------------
# chaos: checkpoint faults degrade, never crash
# ---------------------------------------------------------------------------
class TestChaos:
    def test_journal_fault_degrades_not_fails_request(self):
        """A torn journal write during a live upload degrades
        durability (counted) but the request still succeeds — memory
        is authoritative."""
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="c").connect()
        config.set_flag("FAULTS", "seed=11,checkpoint:permanent:1:1")
        try:
            t1 = c.upload(_batch(16), req="u1")
        finally:
            config.set_flag("FAULTS", "")
        assert _canon(c.download(t1)) == _canon(_batch(16))
        stats = srv.stats()["durability"]
        assert stats.get("checkpoint.errors", 0) >= 1
        c.close()
        srv.stop()

    def test_restore_read_fault_quarantines_session_daemon_survives(self):
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="q").connect()
        c.upload(_batch(32), req="u1")
        sid, tok = c.session, c.resume_token
        c.kill()
        srv.stop()
        # every restore-time payload read faults: the session is
        # quarantined; the daemon starts and serves new sessions
        config.set_flag("FAULTS", "seed=2,checkpoint:permanent:1:99")
        try:
            srv2 = serving.Server(workers=1)
            srv2.start()
        finally:
            config.set_flag("FAULTS", "")
        try:
            doc = srv2.stats()["durability"]
            assert sid in doc["restore"]["quarantined"]
            assert doc["quarantined_sessions"] == 1
            with pytest.raises(serving.ServingQuarantined):
                serving.Client(
                    srv2.port, session=sid, resume=tok).connect()
            # the daemon is healthy for fresh tenants
            with serving.Client(srv2.port, name="fresh") as c2:
                t = c2.upload(_batch(8), req="u1")
                assert _canon(c2.download(t)) == _canon(_batch(8))
            ckpt = config.get_flag("CHECKPOINT_DIR")
            assert os.path.exists(
                os.path.join(ckpt, f"{sid}.wal.quarantined"))
        finally:
            srv2.stop()


# ---------------------------------------------------------------------------
# reconnect + idempotent replay across a dropped socket
# ---------------------------------------------------------------------------
class TestReconnect:
    def test_replay_after_socket_loss_applies_once(self):
        """The crash-mid-reply window: the client sends a mutating
        command, the socket dies before the reply lands, the client
        reconnects and resends the SAME request id. Exactly one
        application; byte-identical result."""
        srv = serving.Server(workers=1)
        srv.start()
        config.set_flag("METRICS", "on")
        try:
            c = serving.Client(srv.port, name="rc").connect()
            wire = _batch(77, seed=9)
            # send the upload frame, then kill the socket without
            # reading the reply — the server applies it; the client
            # cannot know
            meta, buffers = frames.batch_to_parts(wire)
            frames.send_frame(
                c._sock, {"cmd": "upload", "batch": meta, "req": "u-77"},
                buffers)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if any(s["tables"] for s in srv.stats()["sessions"]):
                    break
                time.sleep(0.01)
            c.kill()
            c2 = c.reconnect()
            t1 = c2.upload(wire, req="u-77")  # replayed, not re-applied
            assert [s["tables"] for s in srv.stats()["sessions"]] == [1]
            snap = metrics.snapshot()["counters"]
            assert snap.get("serving.idempotent_replays", 0) >= 1
            assert _canon(c2.download(t1)) == _canon(wire)
            # plan + free replay the same way
            t2 = c2.plan(CAST, [t1], req="p-77")
            assert c2.plan(CAST, [t1], req="p-77") == t2
            n = c2.free(t2, req="f-77")
            assert c2.free(t2, req="f-77") == n
            c2.close()
        finally:
            srv.stop()

    def test_dedup_window_is_bounded(self):
        from spark_rapids_jni_tpu.serving import session as session_mod
        s = session_mod.Session("x", "x", 1.0, 1 << 20)
        for i in range(durable.DEDUP_CAP + 10):
            s.dedup_put(f"r{i}", {"table": i}, cap=durable.DEDUP_CAP)
        assert s.dedup_get("r0") is None
        assert s.dedup_get(f"r{durable.DEDUP_CAP + 9}") is not None
        s.teardown()


# ---------------------------------------------------------------------------
# drain: the rolling-restart handshake
# ---------------------------------------------------------------------------
class TestDrain:
    def test_drain_rejects_new_work_then_stops(self):
        srv = serving.Server(workers=1)
        srv.start()
        c = serving.Client(srv.port, name="dr").connect()
        t1 = c.upload(_batch(24), req="u1")
        res = c.drain(deadline_s=10.0)
        assert res.get("drained") is True
        # draining (or already-stopped) daemon refuses device work
        with pytest.raises((serving.ServingDraining, OSError,
                            RuntimeError)):
            c.upload(_batch(8), req="u2")
            serving.Client(srv.port, name="late").connect()
        srv.stop()  # waits for the drain-triggered stop to finish
        # the checkpoint survived: a successor restores the session
        srv2 = serving.Server(workers=1)
        srv2.start()
        try:
            assert srv2.stats()["durability"]["restore"]["sessions"] == 1
        finally:
            srv2.stop()


# ---------------------------------------------------------------------------
# warm-start manifest
# ---------------------------------------------------------------------------
class TestManifest:
    def test_note_dedupes_and_survives_reload(self, tmp_path):
        d = str(tmp_path / "man")
        os.makedirs(d)
        t = rb._table_from_wire(*_batch(50), None)
        tid = rb._resident_put(t)
        m = durable.Manifest(d)
        for _ in range(3):
            m.note(CAST, [t], False)
        assert len(m.records()) == 1
        m.close()
        m2 = durable.Manifest(d)
        assert len(m2.records()) == 1
        compiled, failed = m2.warm_start()
        assert compiled == 1 and failed == 0
        m2.close()
        rb.table_free(tid)

    def test_corrupt_manifest_starts_fresh(self, tmp_path):
        d = str(tmp_path / "man2")
        os.makedirs(d)
        j = durable.Journal(os.path.join(d, "manifest.wal"))
        j.append({"t": "plan", "ops": [], "donate": False, "tables": []})
        j.append({"t": "plan", "ops": [1], "donate": False, "tables": []})
        j.close()
        blob = bytearray(
            open(os.path.join(d, "manifest.wal"), "rb").read())
        blob[len(durable._MAGIC) + durable._FRAME.size] ^= 0xFF
        with open(os.path.join(d, "manifest.wal"), "wb") as f:
            f.write(blob)
        m = durable.Manifest(d)  # must not raise
        assert m.records() == []
        m.close()
        assert os.path.exists(
            os.path.join(d, "manifest.wal.quarantined"))
