"""Randomized string-op sweep vs the Python str/bytes oracle.

Random ASCII subjects (embedded spaces, digits, repeats, empties)
through the byte-level op surface — length/upper/lower/strip family,
find/contains/replace with random needles, concat, reverse, pad/zfill,
slice — all checked element-for-element against Python's own string
semantics. The directed suites pin the UTF-8 tier and edge syntax;
this sweep guards the byte-path plumbing (lengths, padded matrices,
validity) across arbitrary shape mixes."""

import random

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings as S

_ALPHA = "abcXYZ019 _-=."


def _subjects(rng, n):
    out = []
    for _ in range(n):
        ln = rng.randint(0, 14)
        out.append("".join(rng.choice(_ALPHA) for _ in range(ln)))
    # guaranteed edge shapes
    out[:4] = ["", " ", "aaa", "  ab  "]
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_unary_ops_vs_python(seed):
    rng = random.Random(seed)
    subs = _subjects(rng, 200)
    col = Column.from_strings(subs)
    checks = [
        (S.length(col), [len(s) for s in subs]),
        (S.upper(col), [s.upper() for s in subs]),
        (S.lower(col), [s.lower() for s in subs]),
        (S.strip(col), [s.strip(" ") for s in subs]),
        (S.lstrip(col), [s.lstrip(" ") for s in subs]),
        (S.rstrip(col), [s.rstrip(" ") for s in subs]),
        (S.reverse(col), [s[::-1] for s in subs]),
        (S.capitalize(col), [s[:1].upper() + s[1:].lower() for s in subs]),
    ]
    for got_col, want in checks:
        got = got_col.to_pylist()
        assert got == want, (got_col, [
            (s, g, w) for s, g, w in zip(subs, got, want) if g != w
        ][:5])


@pytest.mark.parametrize("seed", [3, 4, 5])
def test_needle_ops_vs_python(seed):
    rng = random.Random(seed)
    subs = _subjects(rng, 200)
    col = Column.from_strings(subs)
    for _ in range(8):
        nl = rng.randint(1, 3)
        needle = "".join(rng.choice("abX0 ") for _ in range(nl))
        got_c = S.contains(col, needle).to_pylist()
        assert got_c == [needle in s for s in subs], needle
        got_f = S.find(col, needle).to_pylist()
        assert got_f == [s.find(needle) for s in subs], needle
        repl = "".join(rng.choice("zQ_") for _ in range(rng.randint(0, 2)))
        got_r = S.replace(col, needle, repl).to_pylist()
        assert got_r == [s.replace(needle, repl) for s in subs], (
            needle, repl,
        )


@pytest.mark.parametrize("seed", [6, 7])
def test_binary_and_width_ops_vs_python(seed):
    rng = random.Random(seed)
    subs_a = _subjects(rng, 150)
    subs_b = _subjects(rng, 150)
    a = Column.from_strings(subs_a)
    b = Column.from_strings(subs_b)
    got = S.concat(a, b).to_pylist()
    assert got == [x + y for x, y in zip(subs_a, subs_b)]
    for width in (0, 3, 9):
        # Spark lpad/rpad semantics: EXACTLY width bytes - truncate
        # when longer (unlike Python ljust/rjust, which never truncate)
        assert S.pad(a, width, "right", "*").to_pylist() == [
            s[:width].ljust(width, "*") for s in subs_a
        ]
        assert S.pad(a, width, "left", "*").to_pylist() == [
            s[:width].rjust(width, "*") for s in subs_a
        ]
    for width in (0, 3, 9):
        assert S.zfill(a, width).to_pylist() == [
            s.zfill(width) for s in subs_a
        ]


def test_nulls_propagate():
    subs = ["ab", None, "", None, "x y"]
    col = Column.from_strings(subs)
    assert S.upper(col).to_pylist() == ["AB", None, "", None, "X Y"]
    assert S.length(col).to_pylist() == [2, None, 0, None, 3]
    assert S.replace(col, "x", "z").to_pylist() == [
        "ab", None, "", None, "z y",
    ]
