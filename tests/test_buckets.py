"""Shape-bucket plane: policy, padding, and bucket-edge SEMANTICS.

The contract under test: with pad-to-bucket batching ON (the default),
every dispatch-plane op returns byte-identical wire results to the
exact-shape path (``SPARK_RAPIDS_TPU_BUCKETS=off``) — null counts,
groupby group counts, sort stability, and join cardinality included —
at bucket-boundary row counts (1023/1024/1025 around the default 1024
floor; a small explicit ladder for the cheap sweeps).
"""

import json

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import buckets, config, metrics

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    config.clear_flag("BUCKETS")
    config.clear_flag("METRICS")


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_default_ladder(self):
        assert buckets.enabled()
        assert buckets.bucket_for(1) == 1024
        assert buckets.bucket_for(1023) == 1024
        assert buckets.bucket_for(1024) == 1024
        assert buckets.bucket_for(1025) == 2048
        assert buckets.bucket_for(0) is None
        assert buckets.bucket_for(-5) is None
        # past the ladder cap: exact dispatch
        assert buckets.bucket_for((1 << 23) + 1) is None

    def test_floor_growth_spec(self):
        config.set_flag("BUCKETS", "16:4")
        assert buckets.bucket_for(10) == 16
        assert buckets.bucket_for(16) == 16
        assert buckets.bucket_for(17) == 64
        assert buckets.bucket_for(65) == 256

    def test_cap_spec(self):
        config.set_flag("BUCKETS", "16:2:64")
        assert buckets.bucket_for(64) == 64
        assert buckets.bucket_for(65) is None

    def test_explicit_list(self):
        config.set_flag("BUCKETS", "8,64,512")
        assert buckets.bucket_for(5) == 8
        assert buckets.bucket_for(8) == 8
        assert buckets.bucket_for(9) == 64
        assert buckets.bucket_for(65) == 512
        assert buckets.bucket_for(513) is None

    def test_off_values(self):
        for spec in ("off", "0", "none", "false", "disabled"):
            config.set_flag("BUCKETS", spec)
            assert not buckets.enabled()
            assert buckets.bucket_for(100) is None

    def test_invalid_spec_raises_loudly(self):
        config.set_flag("BUCKETS", "banana")
        with pytest.raises(ValueError, match="SPARK_RAPIDS_TPU_BUCKETS"):
            buckets.policy()
        config.set_flag("BUCKETS", "16:1")  # growth < 2
        with pytest.raises(ValueError):
            buckets.policy()


# ---------------------------------------------------------------------------
# pad / unpad / Table.logical_rows
# ---------------------------------------------------------------------------


def _mixed_table(n: int) -> Table:
    rng = np.random.default_rng(n)
    k = rng.integers(0, 7, n, dtype=np.int64)
    v = rng.integers(-50, 50, n, dtype=np.int64)
    valid = rng.random(n) > 0.2
    strs = [f"s{int(x) % 5}" if valid[i] else None
            for i, x in enumerate(k)]
    return Table(
        [
            Column.from_numpy(k),
            Column.from_numpy(v, validity=valid),
            Column.from_strings(strs),
        ],
        ["k", "v", "s"],
    )


class TestPadUnpad:
    def test_round_trip(self):
        t = _mixed_table(10)
        p = buckets.pad_table(t, 16)
        assert p.row_count == 16
        assert p.logical_rows == 10
        assert p.logical_row_count == 10
        assert p.is_padded
        # padded tail: zero data, False validity, zero lengths
        assert not np.asarray(p.columns[1].validity)[10:].any()
        assert not np.asarray(p.columns[2].lengths)[10:].any()
        back = buckets.unpad_table(p)
        assert back.row_count == 10
        assert not back.is_padded
        assert back.to_pydict() == t.to_pydict()

    def test_logical_rows_validation(self):
        c = Column.from_numpy(np.arange(4, dtype=np.int64))
        with pytest.raises(ValueError):
            Table([c], logical_rows=5)
        with pytest.raises(ValueError):
            Table([c], logical_rows=-1)

    def test_pad_down_rejected(self):
        t = _mixed_table(10)
        with pytest.raises(ValueError):
            buckets.pad_table(t, 4)

    def test_factories_entry_points(self):
        from spark_rapids_jni_tpu import factories

        config.set_flag("BUCKETS", "16:2")
        t = _mixed_table(10)
        p = factories.pad_to_bucket(t)
        assert p.row_count == 16 and p.logical_rows == 10
        assert factories.unpad_table(p).to_pydict() == t.to_pydict()
        config.set_flag("BUCKETS", "off")
        assert factories.pad_to_bucket(t) is t

    def test_pad_to_bucket_passes_through_larger_padded(self):
        # a capped-op output can sit at a bucket ABOVE its logical
        # count's own bucket; re-bucketing must pass it through, not
        # try to pad down
        from spark_rapids_jni_tpu import factories

        config.set_flag("BUCKETS", "16:2")
        t = _mixed_table(10)
        big = buckets.pad_table(t, 64)
        assert factories.pad_to_bucket(big) is big
        again = factories.pad_to_bucket(factories.pad_to_bucket(t))
        assert again.row_count == 16 and again.logical_rows == 10

    def test_is_bucketable_gate(self):
        from spark_rapids_jni_tpu import bucketed

        assert bucketed.is_bucketable({"op": "sort_by", "keys": []})
        assert bucketed.is_bucketable({"op": "join", "how": "semi"})
        assert bucketed.is_bucketable({"op": "join"})  # default inner
        assert not bucketed.is_bucketable({"op": "join", "how": "full"})
        assert not bucketed.is_bucketable({"op": "explode"})
        assert not bucketed.is_bucketable({"op": "concat"})
        assert bucketed.is_bucketable(
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "sum"}]}
        )
        assert not bucketed.is_bucketable(
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "collect_list"}]}
        )

    def test_padded_table_is_a_pytree(self):
        import jax

        t = buckets.pad_table(_mixed_table(10), 16)
        leaves, treedef = jax.tree.flatten(t)
        back = jax.tree.unflatten(treedef, leaves)
        assert back.logical_rows == 10
        assert back.names == ("k", "v", "s")


# ---------------------------------------------------------------------------
# bucket-edge semantics: bucketing on == off, byte for byte
# ---------------------------------------------------------------------------


def _wire(op: dict, cols, n: int):
    """Run one wire op over (dtype_id, bytes, valid_bytes|None) cols."""
    return rb.table_op_wire(
        json.dumps(op),
        [c[0] for c in cols],
        [0] * len(cols),
        [c[1] for c in cols],
        [c[2] for c in cols],
        n,
    )


def _int_cols(n: int, null_every: int = 7):
    rng = np.random.default_rng(n)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % null_every != 0).astype(np.uint8)
    return k, v, valid


def _both_arms(run):
    """Run ``run()`` with bucketing on, then off; return both results."""
    config.set_flag("BUCKETS", "")
    on = run()
    config.set_flag("BUCKETS", "off")
    off = run()
    config.clear_flag("BUCKETS")
    return on, off


BOUNDARY_SIZES = (1023, 1024, 1025)


class TestBucketEdgeSemantics:
    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_cast_preserves_null_count(self, n):
        k, v, valid = _int_cols(n)

        def run():
            out = _wire(
                {"op": "cast", "column": 1,
                 "type_id": int(dt.TypeId.FLOAT64)},
                [(I64, k.tobytes(), None), (I64, v.tobytes(), valid.tobytes())],
                n,
            )
            return out

        on, off = _both_arms(run)
        assert on == off
        assert on[4] == n
        # null count survives the bucket boundary exactly
        nulls = np.frombuffer(on[3][1], np.uint8)
        assert int((nulls == 0).sum()) == int((valid == 0).sum())

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_groupby_group_counts(self, n):
        k, v, valid = _int_cols(n)

        def run():
            return _wire(
                {"op": "groupby", "by": [0],
                 "aggs": [{"column": 1, "agg": "sum"},
                          {"column": 1, "agg": "count"}]},
                [(I64, k.tobytes(), None), (I64, v.tobytes(), valid.tobytes())],
                n,
            )

        on, off = _both_arms(run)
        assert on == off
        assert on[4] == len(np.unique(k))  # group count exact

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_sort_stability_and_null_placement(self, n):
        k, _, valid = _int_cols(n, null_every=5)
        iota = np.arange(n, dtype=np.int64)  # stability witness

        def run():
            return _wire(
                {"op": "sort_by", "keys": [{"column": 0}]},
                [(I64, k.tobytes(), valid.tobytes()),
                 (I64, iota.tobytes(), None)],
                n,
            )

        on, off = _both_arms(run)
        assert on == off
        assert on[4] == n
        # independent oracle: stable argsort with nulls first (Spark
        # ascending default), ties broken by original position
        key = np.where(valid.astype(bool), k, np.int64(-(1 << 40)))
        order = np.lexsort((iota, key))
        got = np.frombuffer(on[2][1], np.int64)
        np.testing.assert_array_equal(got, iota[order])

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    @pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
    def test_join_cardinality(self, n, how):
        k, v, valid = _int_cols(n)
        rng = np.random.default_rng(n + 1)
        kr = rng.integers(0, 5, 40, dtype=np.int64)  # keys 0-4 of 0-8
        vr = rng.integers(0, 10, 40, dtype=np.int64)

        def run():
            tidl = rb.table_upload_wire(
                [I64, I64], [0, 0], [k.tobytes(), v.tobytes()],
                [valid.tobytes(), None], n,
            )
            tidr = rb.table_upload_wire(
                [I64, I64], [0, 0], [kr.tobytes(), vr.tobytes()],
                [None, None], 40,
            )
            jid = rb.table_op_resident(
                json.dumps({"op": "join", "how": how, "on": [0]}),
                [tidl, tidr],
            )
            out = rb.table_download_wire(jid)
            for t in (tidl, tidr, jid):
                rb.table_free(t)
            return out

        on, off = _both_arms(run)
        assert on == off
        # independent cardinality oracle (null keys never match)
        kv = np.where(valid.astype(bool), k, np.int64(-1))
        matches = {key: int((kr == key).sum()) for key in range(9)}
        per_left = np.array([matches.get(int(x), 0) for x in kv])
        want = {
            "inner": int(per_left.sum()),
            "left": int(np.maximum(per_left, 1).sum()),
            "semi": int((per_left > 0).sum()),
            "anti": int((per_left == 0).sum()),
        }[how]
        assert on[4] == want

    @pytest.mark.parametrize("n", BOUNDARY_SIZES)
    def test_filter_and_distinct(self, n):
        k, v, valid = _int_cols(n)
        mask = (v > 0).astype(np.uint8)

        def run():
            f = _wire(
                {"op": "filter", "mask": 2},
                [(I64, k.tobytes(), None), (I64, v.tobytes(), None),
                 (B8, mask.tobytes(), None)],
                n,
            )
            d = _wire(
                {"op": "distinct", "keys": [0]},
                [(I64, k.tobytes(), None), (I64, v.tobytes(), None)],
                n,
            )
            return f, d

        on, off = _both_arms(run)
        assert on == off
        assert on[0][4] == int(mask.sum())
        assert on[1][4] == len(np.unique(k))

    def test_resident_chain_parity(self):
        n = 1025
        k, v, _ = _int_cols(n)
        mask = (v > 0).astype(np.uint8)

        def run():
            tid = rb.table_upload_wire(
                [I64, I64, B8], [0, 0, 0],
                [k.tobytes(), v.tobytes(), mask.tobytes()],
                [None, None, None], n,
            )
            f = rb.table_op_resident(
                json.dumps({"op": "filter", "mask": 2}), [tid]
            )
            s = rb.table_op_resident(
                json.dumps({"op": "sort_by", "keys": [{"column": 0}]}), [f]
            )
            g = rb.table_op_resident(
                json.dumps({"op": "groupby", "by": [0],
                            "aggs": [{"column": 1, "agg": "sum"}]}), [s]
            )
            rows = [rb.table_num_rows(x) for x in (tid, f, s, g)]
            out = rb.table_download_wire(g)
            for t in (tid, f, s, g):
                rb.table_free(t)
            return rows, out

        on, off = _both_arms(run)
        assert on == off
        assert on[0][0] == n  # resident row counts are LOGICAL counts

    def test_rlike_empty_matching_pattern_excludes_padding(self):
        # ".*" matches the empty string — padding rows (length-0
        # strings) must still be excluded by the occupancy gate
        n = 1000
        strs = [f"row{i}" for i in range(n)]

        def run():
            col = Column.from_strings(strs)
            out = rb._dispatch(
                {"op": "rlike", "column": 0, "pattern": ".*"},
                Table([col], ["s"]),
            )
            return out.logical_row_count

        on, off = _both_arms(run)
        assert on == off == n

    def test_nonbucketable_op_unpads_first(self):
        # slice is not bucketed: a padded resident input must be
        # unpadded before the exact path sees it
        n = 1000
        k, v, _ = _int_cols(n)

        def run():
            tid = rb.table_upload_wire(
                [I64, I64], [0, 0], [k.tobytes(), v.tobytes()],
                [None, None], n,
            )
            s = rb.table_op_resident(
                json.dumps({"op": "slice", "start": 5, "stop": 900}), [tid]
            )
            out = rb.table_download_wire(s)
            for t in (tid, s):
                rb.table_free(t)
            return out

        on, off = _both_arms(run)
        assert on == off
        assert on[4] == 895


# ---------------------------------------------------------------------------
# metrics integration
# ---------------------------------------------------------------------------


class TestBucketMetrics:
    def test_pad_waste_and_cache_counters(self):
        config.set_flag("METRICS", True)
        config.set_flag("BUCKETS", "")
        metrics.reset()
        buckets.cache_clear()
        n = 1000
        k, v, _ = _int_cols(n)
        for _ in range(2):
            _wire(
                {"op": "sort_by", "keys": [{"column": 0}]},
                [(I64, k.tobytes(), None), (I64, v.tobytes(), None)],
                n,
            )
        snap = metrics.snapshot()
        assert snap["counters"]["compile_cache.miss"] == 1
        assert snap["counters"]["compile_cache.hit"] == 1
        assert snap["counters"]["bucket.pad_tables"] >= 2
        # 24 pad rows x 16 B/row, twice
        assert snap["bytes"]["bucket.pad_waste_bytes"] >= 2 * 24 * 16
        assert "bucket.size" in snap["histograms"]
        assert "bucket.pad_rows" in snap["histograms"]
        assert snap["gauges"]["compile_cache.size"]["value"] >= 1

    def test_cache_stats_and_clear(self):
        config.set_flag("BUCKETS", "")
        buckets.cache_clear()
        n = 1000
        k, v, _ = _int_cols(n)
        _wire(
            {"op": "cast", "column": 0, "type_id": int(dt.TypeId.INT32)},
            [(I64, k.tobytes(), None), (I64, v.tobytes(), None)],
            n,
        )
        assert buckets.cache_stats()["size"] >= 1
        buckets.cache_clear()
        assert buckets.cache_stats()["size"] == 0
