"""Multi-tenant serving daemon: parity, fairness, budgets, teardown.

The ISSUE-9 contract under test: N threaded clients streaming ragged
batches through one daemon get results BYTE-IDENTICAL to serial
``table_plan_wire`` execution; session B warm-hits session A's
compiled executables (process-global ``buckets.cached_jit``); a heavy
session cannot starve a light one (weighted-deficit scheduling bounds
the light session's p95 queue wait); an over-budget request gets a
typed rejection naming the session budget; a shed request gets a typed
BUSY, never a hang; table ids are session-scoped with labeled
KeyErrors; and disconnect (graceful OR crash) mid-stream leaks zero
tables — including the satellite regression that reclaiming a table
while its ``table_download_wire`` is pending on a pipeline worker
settles via the donation-barrier path instead of deleting buffers
under the reader.
"""

import json
import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import pipeline
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu import serving
from spark_rapids_jni_tpu.serving import scheduler as sched_mod
from spark_rapids_jni_tpu.serving import session as session_mod
from spark_rapids_jni_tpu.utils import (
    buckets, config, flight, metrics, profiler, tracing,
)

I64 = int(dt.TypeId.INT64)
B8 = int(dt.TypeId.BOOL8)
STR = int(dt.TypeId.STRING)

BOUNDARY_SIZES = (1023, 1024, 1025)

CHAIN = [
    {"op": "filter", "mask": 2},
    {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
    {"op": "sort_by", "keys": [{"column": 0}]},
]


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    pipeline.drain()
    for name in ("PIPELINE", "BUCKETS", "METRICS", "HBM_BUDGET_GB",
                 "SERVE_MAX_SESSIONS", "SERVE_QUEUE_DEPTH",
                 "SERVE_SESSION_HBM_FRACTION", "SERVE_PORT",
                 "FLIGHT", "TRACE", "TRACE_SLO_MS", "TRACE_TOPK"):
        config.clear_flag(name)
    pipeline.depth()  # flag now off: tears the worker pool down
    flight.reset()
    tracing.reset_requests()


def _string_wire(strings):
    payload = b"".join(s.encode() for s in strings)
    offs = np.zeros(len(strings) + 1, np.int32)
    np.cumsum([len(s.encode()) for s in strings], out=offs[1:])
    return offs.tobytes() + payload


def _batch(n: int, seed: int = 0):
    """One ragged wire batch: int64 key, int64 value (with nulls),
    BOOL8 mask, STRING payload."""
    rng = np.random.default_rng(n + 7919 * seed)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % 5 != 0).astype(np.uint8)
    m = (v > 0).astype(np.uint8)
    strs = [("s" * (int(x) % 3 + 1)) for x in k]
    return (
        [I64, I64, B8, STR], [0, 0, 0, 0],
        [k.tobytes(), v.tobytes(), m.tobytes(), _string_wire(strs)],
        [None, valid.tobytes(), None, None], n,
    )


def _norm(wire):
    t, s, d, v, n = wire
    return (
        [int(x) for x in t], [int(x) for x in s],
        [None if x is None else bytes(x) for x in d],
        [None if x is None else bytes(x) for x in v], int(n),
    )


def _serial_want(batches):
    return [
        _norm(rb.table_plan_wire(json.dumps(CHAIN), *b)) for b in batches
    ]


# ---------------------------------------------------------------------------
# parity: threaded clients == serial execution, byte for byte
# ---------------------------------------------------------------------------


def test_single_session_stream_parity_boundary_sizes():
    batches = [_batch(n) for n in BOUNDARY_SIZES]
    want = _serial_want(batches)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="solo") as c:
            got = c.stream(CHAIN, batches)
    assert [_norm(g) for g in got] == want
    assert rb.resident_table_count() == 0


@pytest.mark.parametrize("n_clients", [2, 4])
def test_threaded_clients_byte_identical_to_serial(n_clients):
    per_client = [
        [_batch(n, seed=i) for n in BOUNDARY_SIZES]
        for i in range(n_clients)
    ]
    want = [_serial_want(bs) for bs in per_client]
    got = [None] * n_clients
    errs = []

    with serving.serve() as srv:

        def run(i):
            try:
                with serving.Client(srv.port, name=f"c{i}") as c:
                    got[i] = [
                        _norm(g) for g in c.stream(CHAIN, per_client[i])
                    ]
            except BaseException as e:  # pragma: no cover - diagnostics
                errs.append(e)

        ts = [
            threading.Thread(target=run, args=(i,))
            for i in range(n_clients)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    assert not errs, errs
    assert got == want
    assert rb.resident_table_count() == 0


def test_stream_parity_with_pipeline_enabled():
    batches = [_batch(n) for n in BOUNDARY_SIZES]
    want = _serial_want(batches)
    config.set_flag("PIPELINE", "2")
    with serving.serve() as srv:
        with serving.Client(srv.port) as c:
            got = [_norm(g) for g in c.stream(CHAIN, batches)]
    assert got == want


def test_resident_roundtrip_through_daemon():
    b = _batch(1024)
    want = _norm(rb.table_plan_wire(json.dumps(CHAIN), *b))
    with serving.serve() as srv:
        with serving.Client(srv.port) as c:
            tid = c.upload(b)
            out = c.plan(CHAIN, [tid], donate=True)
            got = _norm(c.download(out))
            assert c.free(out) >= 0
    assert got == want
    assert rb.resident_table_count() == 0


# ---------------------------------------------------------------------------
# cross-session executable-cache sharing (acceptance criterion)
# ---------------------------------------------------------------------------


def test_second_session_compile_count_near_zero():
    config.set_flag("METRICS", True)
    metrics.reset()
    buckets.cache_clear()
    batches = [_batch(n) for n in BOUNDARY_SIZES]
    with serving.serve() as srv:
        with serving.Client(srv.port, name="warm") as a:
            a.stream(CHAIN, batches)
        after_a = metrics.snapshot()["counters"]
        with serving.Client(srv.port, name="rider") as b:
            got = [_norm(g) for g in b.stream(CHAIN, batches)]
        after_b = metrics.snapshot()["counters"]
    assert got == _serial_want(batches)
    misses_b = (
        after_b.get("compile_cache.miss", 0)
        - after_a.get("compile_cache.miss", 0)
    )
    hits_b = (
        after_b.get("compile_cache.hit", 0)
        - after_a.get("compile_cache.hit", 0)
    )
    # session B replays session A's shapes: every fused-segment lookup
    # must warm-hit the process-global cache — compile count ~ 0
    assert misses_b == 0, (misses_b, after_b)
    assert hits_b > 0, after_b


# ---------------------------------------------------------------------------
# fairness: the weighted-deficit queue bounds the light session's wait
# ---------------------------------------------------------------------------


def test_fair_scheduler_heavy_cannot_starve_light():
    sched = sched_mod.FairScheduler(
        workers=1, queue_depth=64, quantum_rows=65536
    ).start()
    heavy = session_mod.Session("h", "heavy", 1.0, 1 << 40)
    light = session_mod.Session("l", "light", 1.0, 1 << 40)
    sched.register(heavy)
    sched.register(light)
    try:
        heavy_t0 = time.perf_counter()
        hts = [
            sched.submit(heavy, lambda: time.sleep(0.02), cost=65536,
                         shed=False)
            for _ in range(20)
        ]
        lts = [
            sched.submit(light, lambda: None, cost=64, shed=False)
            for _ in range(5)
        ]
        for t in hts + lts:
            t.result()
        heavy_total = time.perf_counter() - heavy_t0
    finally:
        sched.unregister(heavy)
        sched.unregister(light)
        sched.stop()
    p95 = light.wait_percentiles()["p95_ms"] / 1e3
    # DRR interleaves: each light request waits at most a couple of
    # heavy executions (~20 ms each), never the whole heavy backlog
    assert p95 < heavy_total * 0.5, (p95, heavy_total)
    assert p95 < 0.2, p95


def test_daemon_fairness_two_sessions():
    heavy_batches = [_batch(8192, seed=i) for i in range(16)]
    light_batch = [_batch(256)]
    stats_doc = {}
    with serving.serve(workers=1) as srv:
        # warm both bucket shapes first: the timed phase below must
        # measure queueing under DRR, not first-compile latency
        with serving.Client(srv.port, name="warmup") as w:
            w.stream(CHAIN, [heavy_batches[0], light_batch[0]])
        done = threading.Event()

        def heavy_run():
            with serving.Client(srv.port, name="heavy") as c:
                c.stream(CHAIN, heavy_batches)
            done.set()

        th = threading.Thread(target=heavy_run)
        t0 = time.perf_counter()
        th.start()
        with serving.Client(srv.port, name="light") as c:
            while not done.is_set():
                c.stream(CHAIN, light_batch)
            stats_doc.update({
                s["name"]: s for s in c.stats()["sessions"]
            })
        th.join(timeout=120)
        heavy_total = time.perf_counter() - t0
    light_doc = stats_doc.get("light")
    assert light_doc is not None
    assert light_doc["requests"] >= 1
    p95 = light_doc["queue_wait"]["p95_ms"] / 1e3
    # the light session's requests interleave into the heavy stream:
    # its p95 queue wait is bounded well below the heavy makespan
    # (absolute floor tolerates scheduler noise on a loaded runner)
    assert p95 < max(heavy_total * 0.6, 0.1), (p95, heavy_total)


# ---------------------------------------------------------------------------
# admission: typed over-budget rejection + typed BUSY shed
# ---------------------------------------------------------------------------


def test_over_budget_typed_rejection_names_session_budget():
    config.set_flag("HBM_BUDGET_GB", 1e-6)  # ~1 KiB device budget
    with serving.serve() as srv:
        with serving.Client(srv.port, name="greedy") as c:
            with pytest.raises(serving.ServingOverBudget) as ei:
                c.stream(CHAIN, [_batch(4096)])
            msg = str(ei.value)
            assert "greedy" in msg
            assert "budget" in msg
            assert str(c.budget_bytes) in msg
            # the session survives the rejection: a fitting request on
            # the same connection still works
            with pytest.raises(serving.ServingOverBudget):
                c.stream(CHAIN, [_batch(4096)])
    assert rb.resident_table_count() == 0


def test_busy_shed_is_typed_and_never_hangs():
    with serving.serve(queue_depth=2, workers=1) as srv:
        with serving.Client(srv.port, name="shed") as c:
            sess = srv._sessions[c.session]
            gate = threading.Event()
            # block the single executor, then fill the session queue
            blocker = srv.scheduler.submit(
                sess, gate.wait, cost=1, shed=False
            )
            fillers = [
                srv.scheduler.submit(sess, lambda: None, cost=1,
                                     shed=False)
                for _ in range(2)
            ]
            t0 = time.perf_counter()
            with pytest.raises(serving.ServingBusy) as ei:
                c.stream(CHAIN, [_batch(64)])
            assert time.perf_counter() - t0 < 30
            assert "shed" in str(ei.value)
            gate.set()
            for t in [blocker] + fillers:
                t.result()
            # queue drained: the same request now succeeds
            got = c.stream(CHAIN, [_batch(64)])
            assert len(got) == 1
            assert c.stats()["sessions"][0]["shed"] >= 1


def test_session_limit_typed_rejection():
    with serving.serve(max_sessions=1) as srv:
        with serving.Client(srv.port, name="only"):
            with pytest.raises(serving.ServingSessionLimit):
                serving.Client(srv.port, name="extra").connect()
        # the slot freed on close: a new session is admitted again
        with serving.Client(srv.port, name="next") as c:
            assert c.session


def test_donation_credits_flow_back_to_session():
    with serving.serve() as srv:
        with serving.Client(srv.port, name="donor") as c:
            c.stream(CHAIN, [_batch(2048)])
            doc = c.stats()["sessions"][0]
    # the fused chain donates its consumed input; the credit lands on
    # the tenant's budget accounting, and completion clears in-flight
    assert doc["donated_credit_bytes"] > 0
    assert doc["inflight_bytes"] == 0


# ---------------------------------------------------------------------------
# session-scoped namespaces
# ---------------------------------------------------------------------------


def test_cross_session_table_access_is_labeled_keyerror():
    with serving.serve() as srv:
        with serving.Client(srv.port, name="owner") as a, \
                serving.Client(srv.port, name="thief") as b:
            tid = a.upload(_batch(512))
            with pytest.raises(serving.ServingTableError) as ei:
                b.download(tid)
            msg = str(ei.value)
            assert "thief" in msg
            assert "session-scoped" in msg
            with pytest.raises(serving.ServingTableError):
                b.free(tid)
            # the owner still sees its table
            assert _norm(a.download(tid))[4] == 512
    assert rb.resident_table_count() == 0


def test_second_connection_attaches_to_same_session():
    with serving.serve() as srv:
        with serving.Client(srv.port, name="tenant") as a:
            tid = a.upload(_batch(256))
            with serving.Client(srv.port, session=a.session) as b:
                assert b.session == a.session
                assert _norm(b.download(tid))[4] == 256
            # detaching the second connection must NOT tear down the
            # still-attached session
            assert _norm(a.download(tid))[4] == 256
    assert rb.resident_table_count() == 0


# ---------------------------------------------------------------------------
# teardown: zero leaks on disconnect and crash
# ---------------------------------------------------------------------------


def _wait_until(cond, timeout=30.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_graceful_disconnect_reclaims_all_tables():
    with serving.serve() as srv:
        c = serving.Client(srv.port, name="tidy").connect()
        for n in (256, 512, 1024):
            c.upload(_batch(n))
        assert rb.resident_table_count() == 3
        c.close()
        assert _wait_until(lambda: rb.resident_table_count() == 0)
    assert rb.leak_report() == []


def test_crash_disconnect_mid_stream_leaks_zero_tables():
    config.set_flag("PIPELINE", "2")
    with serving.serve() as srv:
        c = serving.Client(srv.port, name="crash").connect()
        for n in (256, 512):
            c.upload(_batch(n))
        # fire a stream and kill the socket without waiting: the
        # daemon finishes or drops the in-flight work, then tears the
        # session down with full reclamation
        from spark_rapids_jni_tpu.serving import frames

        metas, buffers = frames.batches_to_parts(
            [_batch(n, seed=9) for n in BOUNDARY_SIZES]
        )
        frames.send_frame(
            c._sock, {"cmd": "stream", "plan": CHAIN, "batches": metas},
            buffers,
        )
        c.kill()
        assert _wait_until(lambda: rb.resident_table_count() == 0), (
            rb.leak_report()
        )
    assert rb.leak_report() == []


def test_crash_disconnect_cancels_inflight_stream_promptly():
    """ISSUE-10 satellite bugfix: a client crash mid-stream used to
    leave the whole request running against the dead socket — every
    queued batch still executed while holding the session's in-flight
    HBM charge. The conn thread now polls peer liveness between batch
    results, cancels the request's token (``serving.cancelled``), and
    the remaining queued batches settle WITHOUT running."""
    config.set_flag("METRICS", "1")
    # a chain/shape combination no other test compiles, so the first
    # batches are guaranteed still in flight when the kill lands
    chain = [
        {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
        {"op": "sort_by", "keys": [{"column": 1}, {"column": 0}]},
        {"op": "distinct", "keys": [0]},
    ]
    batches = [_batch(30_000, seed=s) for s in range(12)]
    with serving.serve(queue_depth=4) as srv:
        c = serving.Client(srv.port, name="crash-cancel").connect()
        from spark_rapids_jni_tpu.serving import frames

        metas, buffers = frames.batches_to_parts(batches)
        frames.send_frame(
            c._sock,
            {"cmd": "stream", "plan": chain, "batches": metas},
            buffers,
        )
        time.sleep(0.15)  # well inside the first bucket's compile
        c.kill()
        # prompt teardown: the cancelled stream must not run its 12
        # batches to completion first
        assert _wait_until(
            lambda: srv.stats()["sessions_live"] == 0, timeout=60
        )
        assert _wait_until(lambda: rb.resident_table_count() == 0)
    counters = metrics.snapshot()["counters"]
    assert counters.get("serving.cancelled", 0) >= 1
    assert rb.leak_report() == []


def test_server_stop_tears_down_live_sessions():
    srv = serving.Server().start()
    c = serving.Client(srv.port, name="leftover").connect()
    c.upload(_batch(128))
    srv.stop()  # client never said bye
    assert rb.resident_table_count() == 0
    assert rb.leak_report() == []


# ---------------------------------------------------------------------------
# satellite regression: reclaim vs in-flight readers (donation barrier)
# ---------------------------------------------------------------------------


def test_reclaim_waits_for_download_pending_on_worker(monkeypatch):
    """Freeing a table while its ``table_download_wire`` is pending on
    a pipeline worker must settle via the barrier path: the reclaim
    drains the in-flight serializer before deleting buffers, so the
    download still returns the full, correct wire bytes."""
    config.set_flag("PIPELINE", "2")
    b = _batch(1024)
    tid = rb.table_upload_wire(*b)
    want = _norm(rb.table_download_wire(tid))

    real = rb._column_to_wire
    started = threading.Event()

    def slow_column_to_wire(col, logical_rows, ctx):
        started.set()
        time.sleep(0.05)  # hold the serializer open across the reclaim
        return real(col, logical_rows, ctx)

    monkeypatch.setattr(rb, "_column_to_wire", slow_column_to_wire)
    p = pipeline.submit(lambda: rb.table_download_wire(tid), "encode")
    assert started.wait(timeout=30)
    reclaimed = rb.table_reclaim(tid)  # must wait, not delete underfoot
    monkeypatch.setattr(rb, "_column_to_wire", real)
    assert _norm(p.resolve()) == want
    assert reclaimed > 0
    with pytest.raises(KeyError, match="already-freed"):
        rb.table_download_wire(tid)
    assert rb.resident_table_count() == 0


def test_reclaim_settles_pipelined_reader_before_deleting():
    """A pipelined op still READING the table (registered in
    ``_RESIDENT_READERS``) is terminally settled by the reclaim — the
    donate barrier — so its result is correct even though the input's
    buffers are deleted right after."""
    config.set_flag("PIPELINE", "2")
    b = _batch(1024)
    tid = rb.table_upload_wire(*b)
    op = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
    out = rb.table_op_resident(op, [tid])
    rb.table_reclaim(tid)  # settles the reader, then deletes buffers
    got = _norm(rb.table_download_wire(out))
    pipeline.drain()
    config.clear_flag("PIPELINE")
    tid2 = rb.table_upload_wire(*b)
    out2 = rb.table_op_resident(op, [tid2])
    want = _norm(rb.table_download_wire(out2))
    rb.table_free(out)
    rb.table_free(tid2)
    rb.table_free(out2)
    assert got == want
    assert rb.resident_table_count() == 0


def test_reclaim_unknown_id_raises_labeled_keyerror():
    with pytest.raises(KeyError, match="table id 999999"):
        rb.table_reclaim(999999)


# ---------------------------------------------------------------------------
# observability: served streams are session-stamped profile sessions
# ---------------------------------------------------------------------------


def test_served_streams_open_labeled_profile_sessions():
    profiler.sessions(reset=True)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="alpha") as a:
            a.stream(CHAIN, [_batch(512)])
        with serving.Client(srv.port, name="beta") as b:
            b.stream(CHAIN, [_batch(512)])
    labels = {s["label"] for s in profiler.sessions(reset=True)}
    assert "serve:alpha" in labels
    assert "serve:beta" in labels


# ---------------------------------------------------------------------------
# SERVE* config knobs: centralized, loud-fail parsing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,bad,needle", [
    ("SERVE_PORT", "abc", "SERVE_PORT"),
    ("SERVE_PORT", "70000", "SERVE_PORT"),
    ("SERVE_MAX_SESSIONS", "0", "SERVE_MAX_SESSIONS"),
    ("SERVE_MAX_SESSIONS", "x", "SERVE_MAX_SESSIONS"),
    ("SERVE_QUEUE_DEPTH", "-3", "SERVE_QUEUE_DEPTH"),
    ("SERVE_SESSION_HBM_FRACTION", "2.0", "SERVE_SESSION_HBM_FRACTION"),
    ("SERVE_SESSION_HBM_FRACTION", "nope", "SERVE_SESSION_HBM_FRACTION"),
])
def test_serve_flags_fail_loudly(monkeypatch, name, bad, needle):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_" + name, bad)
    with pytest.raises(ValueError, match=needle):
        config.get_flag(name)


def test_serve_flags_defaults_and_parse(monkeypatch):
    assert config.get_flag("SERVE_PORT") == 0
    assert config.get_flag("SERVE_MAX_SESSIONS") == 8
    assert config.get_flag("SERVE_QUEUE_DEPTH") == 16
    assert config.get_flag("SERVE_SESSION_HBM_FRACTION") == 0.25
    monkeypatch.setenv("SPARK_RAPIDS_TPU_SERVE_PORT", "4242")
    monkeypatch.setenv("SPARK_RAPIDS_TPU_SERVE_QUEUE_DEPTH", "3")
    monkeypatch.setenv(
        "SPARK_RAPIDS_TPU_SERVE_SESSION_HBM_FRACTION", "0.5"
    )
    assert config.get_flag("SERVE_PORT") == 4242
    assert config.get_flag("SERVE_QUEUE_DEPTH") == 3
    assert config.get_flag("SERVE_SESSION_HBM_FRACTION") == 0.5


def test_server_reads_flags_from_config(monkeypatch):
    config.set_flag("SERVE_MAX_SESSIONS", 1)
    config.set_flag("SERVE_QUEUE_DEPTH", 5)
    srv = serving.Server()
    assert srv.max_sessions == 1
    assert srv.queue_depth == 5


# ---------------------------------------------------------------------------
# pre-admission static plan analysis (plancheck): a statically-invalid
# or malformed plan answers a typed bad_request BEFORE the scheduler
# queue, with zero uploads/compiles and the tagged report attached
# ---------------------------------------------------------------------------


def test_statically_invalid_stream_is_bad_request_before_queue():
    config.set_flag("METRICS", True)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="static") as c:
            metrics.reset()
            with pytest.raises(serving.ServingError) as ei:
                c.stream([{"op": "frobnicate"}], [_batch(64)])
            assert ei.value.type == "bad_request"
            assert "plancheck: op[0]" in str(ei.value)
            assert "unknown table op" in str(ei.value)
            # the tagged report rides the error frame back to the client
            rep = getattr(ei.value, "plan_report", None)
            assert rep is not None and rep["ok"] is False
            assert rep["ops"][0]["tier"] == "unsupported"
            # zero scheduler admissions, uploads, or compiles happened
            counters = metrics.snapshot()["counters"]
            assert counters.get("serving.requests", 0) == 0
            assert not any(
                k.startswith(("wire.", "compile_cache.")) for k in counters
            )
            # the session survives the rejection: a clean plan runs
            got = c.stream(CHAIN, [_batch(64)])
            assert len(got) == 1
    assert rb.resident_table_count() == 0


def test_wire_schema_aware_stream_rejection():
    # the check runs against the FIRST BATCH's wire schema: a filter
    # whose mask column is INT64 (not BOOL8) is statically invalid
    with serving.serve() as srv:
        with serving.Client(srv.port, name="schema") as c:
            with pytest.raises(serving.ServingError) as ei:
                c.stream([{"op": "filter", "mask": 0}], [_batch(32)])
            assert ei.value.type == "bad_request"
            assert "BOOL8" in str(ei.value)
    assert rb.resident_table_count() == 0


def test_statically_invalid_plan_cmd_is_bad_request_before_queue():
    config.set_flag("METRICS", True)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="resident") as c:
            tid = c.upload(_batch(64))
            metrics.reset()
            with pytest.raises(serving.ServingError) as ei:
                c.plan([{"op": "groupby", "by": [17],
                         "aggs": [{"column": 0, "agg": "sum"}]}], [tid])
            assert ei.value.type == "bad_request"
            assert "plancheck: op[0]" in str(ei.value)
            assert "out of range" in str(ei.value)
            counters = metrics.snapshot()["counters"]
            assert counters.get("serving.requests", 0) == 0
            c.free(tid)
    assert rb.resident_table_count() == 0


def test_malformed_plan_frame_is_typed_bad_request():
    # a raw frame whose plan is not a JSON list (the Client API cannot
    # even send this shape) must answer bad_request, not kill the conn
    from spark_rapids_jni_tpu.serving import frames

    with serving.serve() as srv:
        with serving.Client(srv.port, name="mal") as c:
            for bad in ({"op": "cast"}, "nope", 17):
                frames.send_frame(
                    c._sock,
                    {"cmd": "stream", "plan": bad, "batches": []}, [],
                )
                resp, _ = frames.recv_frame(c._sock)
                assert resp["ok"] is False
                assert resp["error"]["type"] == "bad_request"
                assert "JSON list" in resp["error"]["message"]
            # connection still usable after all three rejections
            got = c.stream(CHAIN, [_batch(32)])
            assert len(got) == 1


# ---------------------------------------------------------------------------
# ISSUE 18: the live introspection plane — the `trace` command
# ---------------------------------------------------------------------------


def test_trace_command_returns_slow_request_log_and_prometheus():
    """The daemon's ``trace`` command: a traced stream shows up in the
    tail-sampled slow-request log under the CLIENT's trace id (the
    server joins the wire traceparent, it never re-mints), with span
    detail sampled in because TRACE_SLO_MS=0 makes every request an
    SLO breach, alongside a Prometheus exposition of the registry."""
    config.set_flag("FLIGHT", True)
    config.set_flag("METRICS", True)
    config.set_flag("TRACE_SLO_MS", "0")
    with serving.serve() as srv:
        with serving.Client(srv.port, name="traced") as c:
            ctx = tracing.new_context()
            with tracing.activate(ctx):
                got = c.stream(CHAIN, [_batch(512)])
            assert len(got) == 1
            doc = c.trace()
    assert doc["slo_ms"] == 0.0
    assert doc["topk"] == int(config.get_flag("TRACE_TOPK"))
    mine = [r for r in doc["slow_requests"]
            if r.get("trace_id") == ctx.trace_id]
    assert mine, (ctx.trace_id, doc["slow_requests"])
    rec = mine[0]
    assert rec["label"] == "serving.stream"
    assert rec["session"] == "traced"
    assert rec["ms"] >= 0.0
    # span detail sampled in (SLO breach): server-side spans are
    # attributed to the CLIENT's trace id across the wire hop
    names = {s["name"] for s in rec["spans"]}
    assert "serving.queue_wait" in names, names
    assert any(n.startswith("serving.stream") for n in names), names
    prom = doc["prometheus"]
    assert "# TYPE" in prom and "srt_serving_requests_total" in prom


def test_trace_command_tail_sampling_drops_fast_request_detail():
    # default SLO (250ms): a fast healthy stream is LOGGED but its
    # span detail is not kept — that is the tail-sampling contract
    config.set_flag("FLIGHT", True)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="fast") as c:
            ctx = tracing.new_context()
            with tracing.activate(ctx):
                c.stream(CHAIN, [_batch(64)])
            doc = c.trace()
    mine = [r for r in doc["slow_requests"]
            if r.get("trace_id") == ctx.trace_id
            and r["label"] == "serving.stream"]
    assert mine and all("spans" not in r for r in mine), mine
    assert isinstance(doc["prometheus"], str)


def test_untraced_client_still_lands_in_slow_request_log():
    # no client context: the server MINTS one per request (the plane is
    # on because the flight ring records) — requests are never invisible
    config.set_flag("FLIGHT", True)
    with serving.serve() as srv:
        with serving.Client(srv.port, name="plain") as c:
            c.stream(CHAIN, [_batch(64)])
            doc = c.trace()
    streams = [r for r in doc["slow_requests"]
               if r["label"] == "serving.stream"
               and r.get("session") == "plain"]
    assert streams and all(
        len(r.get("trace_id", "")) == 32 for r in streams
    ), streams
    assert rb.resident_table_count() == 0
