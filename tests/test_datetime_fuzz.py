"""Datetime field/arithmetic fuzz vs the pandas datetime oracle.

Random timestamps across +-200 years (pre-1970 negatives, leap years,
month-end boundaries) through every field extractor, ISO weekday,
month-end, and calendrical-month arithmetic, checked against pandas'
own calendar."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import datetime as D


def _ts_col(rng, n):
    us = rng.integers(
        -6_311_520_000_000_000, 6_311_520_000_000_000, n
    )
    col = Column(
        np.asarray(us, dtype=np.int64), dt.TIMESTAMP_MICROSECONDS, None
    )
    pdt = pd.to_datetime(us, unit="us")
    return col, pdt


@pytest.mark.parametrize("seed", [0, 1])
def test_fields_vs_pandas(seed):
    rng = np.random.default_rng(seed)
    col, pdt = _ts_col(rng, 3000)
    checks = [
        (D.year, pdt.year),
        (D.month, pdt.month),
        (D.day, pdt.day),
        (D.hour, pdt.hour),
        (D.minute, pdt.minute),
        (D.second, pdt.second),
        (D.day_of_year, pdt.dayofyear),
        (D.quarter, pdt.quarter),
    ]
    for fn, want in checks:
        got = np.asarray(fn(col).data)
        np.testing.assert_array_equal(
            got, np.asarray(want), err_msg=fn.__name__
        )


def test_weekday_iso_vs_pandas():
    rng = np.random.default_rng(3)
    col, pdt = _ts_col(rng, 2000)
    got = np.asarray(D.weekday(col).data)
    # module convention: ISO Monday=1..Sunday=7; pandas dayofweek Mon=0
    np.testing.assert_array_equal(got, np.asarray(pdt.dayofweek) + 1)


@pytest.mark.parametrize("seed", [5, 6])
def test_add_months_vs_pandas(seed):
    rng = np.random.default_rng(seed)
    n = 1500
    col, pdt = _ts_col(rng, n)
    months = rng.integers(-30, 30, n, dtype=np.int64)
    got = np.asarray(
        D.add_calendrical_months(
            col, Column(np.asarray(months, dtype=np.int32), dt.INT32, None)
        ).data
    )
    want = np.array(
        [
            (t + pd.DateOffset(months=int(m))).value // 1000
            for t, m in zip(pdt, months)
        ]
    )
    np.testing.assert_array_equal(got, want)


def test_last_day_of_month_vs_pandas():
    rng = np.random.default_rng(9)
    col, pdt = _ts_col(rng, 2000)
    out = D.last_day_of_month(col)
    assert out.dtype.id == dt.TypeId.TIMESTAMP_DAYS
    got_dates = pd.to_datetime(
        np.asarray(out.data, dtype="int64"), unit="D"
    ).values.astype("datetime64[D]")
    # MonthEnd(0) maps an exact month-end midnight to itself; other
    # instants roll forward to their month's last day — same contract
    want_dates = (
        (pdt + pd.offsets.MonthEnd(0)).normalize()
        .values.astype("datetime64[D]")
    )
    np.testing.assert_array_equal(got_dates, want_dates)
