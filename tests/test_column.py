"""Phase-0 tests: DType wire format, Column/Table pytrees, Arrow interop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import interop
from spark_rapids_jni_tpu.column import Column, Table


class TestDType:
    def test_wire_roundtrip(self):
        for d in [dt.INT64, dt.FLOAT32, dt.BOOL8, dt.decimal32(-3), dt.decimal64(-8)]:
            tid, scale = d.to_wire()
            assert dt.DType.from_wire(tid, scale) == d

    def test_widths(self):
        assert dt.INT8.itemsize == 1
        assert dt.INT64.itemsize == 8
        assert dt.BOOL8.itemsize == 1
        assert dt.decimal32(-3).itemsize == 4
        assert dt.decimal64(-8).itemsize == 8
        assert dt.TIMESTAMP_DAYS.itemsize == 4

    def test_decimal_scale_gate(self):
        with pytest.raises(ValueError):
            dt.DType(dt.TypeId.INT32, scale=-2)

    def test_string_not_fixed_width(self):
        assert not dt.STRING.is_fixed_width
        with pytest.raises(TypeError):
            dt.STRING.itemsize


class TestColumn:
    def test_fixed_width_roundtrip(self, rng):
        arr = rng.integers(-100, 100, 1000, dtype=np.int64)
        col = Column.from_numpy(arr)
        assert col.dtype == dt.INT64
        assert col.row_count == 1000
        np.testing.assert_array_equal(col.to_numpy(), arr)

    def test_validity(self, rng):
        arr = rng.standard_normal(64).astype(np.float32)
        valid = rng.random(64) > 0.3
        col = Column.from_numpy(arr, validity=valid)
        assert col.null_count() == int((~valid).sum())
        got = col.to_pylist()
        for i in range(64):
            if valid[i]:
                assert got[i] == pytest.approx(float(arr[i]))
            else:
                assert got[i] is None

    def test_decimal(self):
        col = Column.from_numpy(
            np.array([1234, -5678, 0], dtype=np.int32), dtype=dt.decimal32(-3)
        )
        assert col.dtype.scale == -3
        assert col.to_pylist() == [1234, -5678, 0]

    def test_strings(self):
        col = Column.from_strings(["spark", None, "", "rapids-tpu"])
        assert col.dtype.is_string
        assert col.to_pylist() == ["spark", None, "", "rapids-tpu"]
        assert col.null_count() == 1

    def test_pytree(self, rng):
        arr = rng.integers(0, 10, 128, dtype=np.int32)
        valid = rng.random(128) > 0.5
        col = Column.from_numpy(arr, validity=valid)
        leaves, treedef = jax.tree_util.tree_flatten(col)
        col2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert col2.dtype == col.dtype
        np.testing.assert_array_equal(col2.to_numpy(), arr)

    def test_jit_through(self, rng):
        col = Column.from_numpy(rng.integers(0, 10, 64, dtype=np.int64))

        @jax.jit
        def double(c: Column) -> Column:
            return Column(data=c.data * 2, dtype=c.dtype, validity=c.validity)

        out = double(col)
        np.testing.assert_array_equal(out.to_numpy(), col.to_numpy() * 2)

    def test_timestamps(self):
        ts = np.array(["2026-01-01", "2026-07-29"], dtype="datetime64[D]")
        col = Column.from_numpy(ts)
        assert col.dtype == dt.TIMESTAMP_DAYS
        np.testing.assert_array_equal(col.to_numpy(), ts)


class TestTable:
    def test_basic(self, rng):
        t = Table.from_pydict(
            {
                "a": rng.integers(0, 5, 100, dtype=np.int64),
                "b": rng.standard_normal(100),
                "s": ["x", "yy", None, "zzz"] * 25,
            }
        )
        assert t.num_columns == 3
        assert t.row_count == 100
        assert t["a"].dtype == dt.INT64
        assert t["s"].dtype.is_string
        assert t.select(["b", "a"]).names == ("b", "a")

    def test_schema_wire(self):
        t = Table.from_pydict(
            {"a": np.array([1], dtype=np.int64)},
            dtypes=None,
        )
        ids, scales = t.schema_wire()
        assert ids == [int(dt.TypeId.INT64)]
        assert scales == [0]

    def test_length_mismatch(self):
        with pytest.raises(ValueError):
            Table(
                [
                    Column.from_numpy(np.arange(3)),
                    Column.from_numpy(np.arange(4)),
                ]
            )

    def test_pytree_through_jit(self, rng):
        t = Table.from_pydict(
            {"a": rng.integers(0, 5, 32, dtype=np.int64), "b": rng.standard_normal(32)}
        )

        @jax.jit
        def addone(tbl: Table) -> Table:
            cols = [
                Column(c.data + 1, c.dtype, c.validity) for c in tbl.columns
            ]
            return Table(cols, tbl.names)

        out = addone(t)
        np.testing.assert_array_equal(out["a"].to_numpy(), t["a"].to_numpy() + 1)
        assert out.names == t.names


class TestArrowInterop:
    def test_roundtrip_numeric_with_nulls(self, rng):
        pa = pytest.importorskip("pyarrow")
        arr = pa.array([1, None, 3, 4, None], type=pa.int64())
        col = interop.column_from_arrow(arr)
        assert col.null_count() == 2
        back = interop.column_to_arrow(col)
        assert back.to_pylist() == arr.to_pylist()

    def test_validity_bit_packing(self, rng):
        valid = rng.random(77) > 0.5
        packed = interop.pack_validity(valid)
        unpacked = interop.unpack_validity(packed, 77)
        np.testing.assert_array_equal(unpacked, valid)

    def test_table_roundtrip(self):
        pa = pytest.importorskip("pyarrow")
        tbl = pa.table(
            {
                "i": pa.array([1, 2, None], type=pa.int32()),
                "f": pa.array([1.5, None, 3.5], type=pa.float64()),
                "b": pa.array([True, False, None]),
                "s": pa.array(["a", None, "ccc"]),
            }
        )
        dev = interop.table_from_arrow(tbl)
        assert dev.row_count == 3
        back = interop.table_to_arrow(dev)
        assert back.to_pydict() == tbl.to_pydict()

    def test_decimal_roundtrip(self):
        pa = pytest.importorskip("pyarrow")
        import decimal

        arr = pa.array(
            [decimal.Decimal("1.234"), None, decimal.Decimal("-9.876")],
            type=pa.decimal128(9, 3),
        )
        col = interop.column_from_arrow(arr)
        assert col.dtype == dt.decimal32(-3)
        assert col.to_pylist() == [1234, None, -9876]
        back = interop.column_to_arrow(col)
        assert back.to_pylist() == arr.to_pylist()


class TestReviewRegressions:
    """Regressions from the phase-0 code review."""

    def test_binary_payload_lossless(self):
        pa = pytest.importorskip("pyarrow")
        arr = pa.array([b"\xff\x00ab", None], type=pa.binary())
        col = interop.column_from_arrow(arr)
        back = interop.column_to_arrow(col)
        assert back.to_pylist() == [b"\xff\x00ab", None]

    def test_sliced_decimal_ingest(self):
        pa = pytest.importorskip("pyarrow")
        import decimal

        arr = pa.array(
            [decimal.Decimal("1.234"), None, decimal.Decimal("-9.876")],
            type=pa.decimal128(9, 3),
        ).slice(1, 2)
        col = interop.column_from_arrow(arr)
        assert col.to_pylist() == [None, -9876]

    def test_duration_days_export(self):
        pa = pytest.importorskip("pyarrow")
        col = Column.from_numpy(np.array([1, 2], dtype="timedelta64[D]"))
        out = interop.column_to_arrow(col)
        assert out.to_pylist()[0].days == 1

    def test_column_eq_does_not_raise(self):
        a = Column.from_numpy(np.arange(5))
        b = Column.from_numpy(np.arange(5))
        assert (a == b) is False  # identity comparison, not elementwise
