"""STRUCT column tests: construction, field access, gather/filter/sort,
Arrow round-trip (the cudf structs surface, SURVEY.md §2.3)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.struct import (
    StructColumn,
    pack,
    struct_from_arrow,
    struct_to_arrow,
    unpack,
)

ROWS = [
    {"a": 1, "s": "x"},
    None,
    {"a": 3, "s": None},
    {"a": None, "s": "w"},
    {"a": 5, "s": "v"},
]


def test_from_pylist_round_trip():
    sc = StructColumn.from_pylist(ROWS)
    assert sc.row_count == 5
    assert sc.null_count() == 1
    assert sc.to_pylist() == ROWS


def test_field_access_folds_struct_nulls():
    sc = StructColumn.from_pylist(ROWS)
    # row 1 is a null struct: its children read as null through field()
    assert sc.field("a").to_pylist() == [1, None, 3, None, 5]
    assert sc.field("s").to_pylist() == ["x", None, None, "w", "v"]
    assert sc.field(0).to_pylist() == [1, None, 3, None, 5]


def test_gather_and_filter():
    sc = StructColumn.from_pylist(ROWS)
    import jax.numpy as jnp

    g = sc.gather(jnp.asarray([4, 0, 1]))
    assert g.to_pylist() == [ROWS[4], ROWS[0], None]
    mask = Column.from_numpy(
        np.array([True, True, False, False, True]), dtype=dt.BOOL8
    )
    f = sc.filter(mask)
    assert f.to_pylist() == [ROWS[0], None, ROWS[4]]


def test_argsort_lexicographic():
    sc = StructColumn.from_pylist(
        [
            {"a": 2, "b": 9},
            {"a": 1, "b": 5},
            {"a": 2, "b": 1},
            None,
            {"a": 1, "b": 7},
        ]
    )
    perm = np.asarray(sc.argsort())
    got = sc.gather(perm).to_pylist()
    # struct-level nulls first, then (a, b) lexicographic
    assert got == [
        None,
        {"a": 1, "b": 5},
        {"a": 1, "b": 7},
        {"a": 2, "b": 1},
        {"a": 2, "b": 9},
    ]


def test_pack_unpack():
    t = Table.from_pydict({"k": [1, 2, 3], "v": [9, None, 7]})
    sc = pack(t, ["k", "v"])
    assert sc.to_pylist() == [
        {"k": 1, "v": 9},
        {"k": 2, "v": None},
        {"k": 3, "v": 7},
    ]
    back = unpack(sc)
    assert back["k"].to_pylist() == [1, 2, 3]
    assert back["v"].to_pylist() == [9, None, 7]


def test_arrow_round_trip():
    arr = pa.array(
        ROWS,
        type=pa.struct([("a", pa.int64()), ("s", pa.string())]),
    )
    sc = struct_from_arrow(arr)
    assert sc.to_pylist() == ROWS
    back = struct_to_arrow(sc)
    assert back.to_pylist() == ROWS
    assert pa.types.is_struct(back.type)


def test_jit_pytree():
    import jax

    sc = StructColumn.from_pylist(
        [{"a": 1, "b": 2.5}, {"a": 3, "b": 4.5}]
    )

    @jax.jit
    def f(s):
        return s.field("a").data + 1

    assert np.asarray(f(sc)).tolist() == [2, 4]


def test_mismatched_children_raise():
    a = Column.from_numpy(np.array([1, 2], dtype=np.int64))
    b = Column.from_numpy(np.array([1], dtype=np.int64))
    with pytest.raises(ValueError):
        StructColumn.from_children([a, b])
    with pytest.raises(ValueError):
        StructColumn.from_children([])
