"""LIST op tests vs host oracles (explode family + element ops)."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import lists as L


def _table():
    lc = Column.from_list_of_lists(
        [[1, 2, 3], [], [7], None, [9, 10]], child_dtype=dt.INT64
    )
    k = Column.from_numpy(np.array([10, 20, 30, 40, 50], dtype=np.int64))
    s = Column.from_strings(["a", "bb", None, "dd", "e"])
    return Table([k, lc, s], ["k", "v", "s"])


def test_count_elements():
    t = _table()
    out = L.count_elements(t["v"])
    assert out.to_pylist() == [3, 0, 1, None, 2]


def test_list_contains():
    t = _table()
    out = L.list_contains(t["v"], 7)
    assert out.to_pylist() == [False, False, True, None, False]
    # zero padding must not produce false hits
    out0 = L.list_contains(t["v"], 0)
    assert out0.to_pylist() == [False, False, False, None, False]


def test_extract_list_element():
    t = _table()
    assert L.extract_list_element(t["v"], 0).to_pylist() == [
        1, None, 7, None, 9,
    ]
    assert L.extract_list_element(t["v"], -1).to_pylist() == [
        3, None, 7, None, 10,
    ]
    assert L.extract_list_element(t["v"], 2).to_pylist() == [
        3, None, None, None, None,
    ]


def test_explode():
    t = _table()
    out = L.explode(t, "v")
    assert list(out.names) == ["k", "v", "s"]
    assert out["k"].to_pylist() == [10, 10, 10, 30, 50, 50]
    assert out["v"].to_pylist() == [1, 2, 3, 7, 9, 10]
    assert out["v"].dtype == dt.INT64
    # sibling string column gathers through, including its null
    assert out["s"].to_pylist() == ["a", "a", "a", None, "e", "e"]


def test_explode_outer():
    t = _table()
    out = L.explode_outer(t, "v")
    assert out["k"].to_pylist() == [10, 10, 10, 20, 30, 40, 50, 50]
    assert out["v"].to_pylist() == [1, 2, 3, None, 7, None, 9, 10]


def test_explode_position():
    t = _table()
    out = L.explode_position(t, "v")
    assert list(out.names) == ["k", "pos", "v", "s"]
    assert out["pos"].to_pylist() == [0, 1, 2, 0, 0, 1]
    out2 = L.explode_position(t, "v", outer=True)
    assert out2["pos"].to_pylist() == [0, 1, 2, None, 0, None, 0, 1]


def test_explode_empty_result():
    lc = Column.from_list_of_lists([[], None], child_dtype=dt.INT32)
    t = Table([lc], ["v"])
    out = L.explode(t, "v")
    assert out.row_count == 0


def test_explode_random_oracle(rng):
    n = 500
    pylists = []
    for i in range(n):
        if rng.random() < 0.1:
            pylists.append(None)
        else:
            k = int(rng.integers(0, 6))
            pylists.append(rng.integers(-100, 100, k).tolist())
    keys = rng.integers(0, 1000, n)
    t = Table(
        [
            Column.from_numpy(keys),
            Column.from_list_of_lists(pylists, child_dtype=dt.INT64),
        ],
        ["k", "v"],
    )
    out = L.explode(t, "v")
    want_k, want_v = [], []
    for key, lst in zip(keys.tolist(), pylists):
        for x in lst or []:
            want_k.append(key)
            want_v.append(x)
    assert out["k"].to_pylist() == want_k
    assert out["v"].to_pylist() == want_v


def test_non_list_raises():
    t = _table()
    with pytest.raises(TypeError):
        L.explode(t, "k")


class TestSplitExplode:
    def test_basic(self):
        from spark_rapids_jni_tpu.ops import split_explode

        t = Table(
            [
                Column.from_numpy(np.array([1, 2, 3, 4], dtype=np.int64)),
                Column.from_strings(["a,b,c", "", None, "x,,y"]),
            ],
            ["k", "s"],
        )
        out = split_explode(t, "s", ",")
        # null -> no rows; "" -> one empty token; "x,,y" -> x, "", y
        assert out["k"].to_pylist() == [1, 1, 1, 2, 4, 4, 4]
        assert out["s"].to_pylist() == ["a", "b", "c", "", "x", "", "y"]

    def test_oracle(self, rng):
        from spark_rapids_jni_tpu.ops import split_explode

        words = []
        for _ in range(300):
            k = int(rng.integers(0, 5))
            words.append(
                ",".join(
                    "".join(rng.choice(list("abc"), int(rng.integers(0, 4))))
                    for _ in range(k + 1)
                )
                if rng.random() > 0.1
                else None
            )
        keys = np.arange(len(words), dtype=np.int64)
        t = Table(
            [Column.from_numpy(keys), Column.from_strings(words)],
            ["k", "s"],
        )
        out = split_explode(t, "s", ",")
        want_k, want_s = [], []
        for key, w in zip(keys.tolist(), words):
            if w is None:
                continue
            for tok in w.split(","):
                want_k.append(key)
                want_s.append(tok)
        assert out["k"].to_pylist() == want_k
        assert out["s"].to_pylist() == want_s

    def test_multibyte_delim_rejected(self):
        from spark_rapids_jni_tpu.ops import split_explode

        t = Table([Column.from_strings(["ab"])], ["s"])
        with pytest.raises(ValueError):
            split_explode(t, "s", "--")
