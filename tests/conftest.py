"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The reference's test strategy requires a physical GPU for every test
(ci/premerge-build.sh:20 gates on nvidia-smi). The TPU rebuild deliberately
does better: XLA's CPU backend plus a forced 8-device host platform gives a
no-accelerator tier that also exercises the multi-chip sharding paths
(SURVEY.md §4 implication (2)).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The environment pins JAX_PLATFORMS to the TPU platform, and the plugin
# re-appends itself even when the env var is overridden — so the platform
# must be forced through the config API before backend initialization.
# SPARK_RAPIDS_TPU_TEST_PLATFORM=axon opts a test run onto the real chip.
jax.config.update(
    "jax_platforms", os.environ.get("SPARK_RAPIDS_TPU_TEST_PLATFORM", "cpu")
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# ---------------------------------------------------------------------------
# quick/slow split (round-4 VERDICT weak item 8): the distributed tier
# runs minutes-per-file on the virtual 8-device mesh and grows with
# coverage. The premerge gate runs `-m "not slow"` plus the multichip
# dryrun (which exercises the same distributed paths end-to-end); the
# nightly tier runs everything.
# ---------------------------------------------------------------------------

_SLOW_MODULES = {
    "test_parallel",      # distributed ops over the virtual mesh
    "test_benchmarks",    # TPC-DS query DAGs incl. mesh variants
    "test_tpcds",         # parquet star schema generate + stream
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: distributed/mesh tier (premerge skips; nightly runs)"
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        if mod in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
