"""Plan-statistics store + drift layer (utils/planstats.py, ISSUE 16).

Covers the crash contract (torn tails recover silently — the
serving/durable.py WAL discipline, minus the typed quarantine: stats
are telemetry, so a reader never raises), the record hook through the
profiler, the drift checks, rotation, and the <5µs disabled-path bound
for the new dispatch hooks.
"""

import json
import os
import struct
import time
import zlib

import pytest

from spark_rapids_jni_tpu.utils import config, metrics, planstats, profiler


@pytest.fixture(autouse=True)
def _isolated_store(tmp_path, monkeypatch):
    """Every test gets its own store dir; flags and module state reset.
    Env overrides leaked by an earlier module (bench helpers run
    in-process export PLANSTATS_DIR for their subprocesses) are
    dropped so the flag below is the only knob."""
    for env in ("PLANSTATS", "PLANSTATS_DIR"):
        monkeypatch.delenv("SPARK_RAPIDS_TPU_" + env, raising=False)
    planstats.reset()
    profiler.reset()
    metrics.reset()
    config.set_flag("PLANSTATS_DIR", str(tmp_path / "stats"))
    yield
    for name in ("PLANSTATS", "PLANSTATS_DIR", "PLANSTATS_ROTATE_MB",
                 "DRIFT_ROWS_FACTOR", "DRIFT_HBM_FACTOR", "PROFILE"):
        config.clear_flag(name)
    planstats.reset()
    profiler.reset()
    metrics.reset()


STATIC = {
    "segments": [
        {"kind": "fused", "ops": [0, 1], "rows_bound": 100,
         "est_hbm_bytes": 4000},
    ],
    "rows_out_bound": 100,
    "est_hbm_peak_bytes": 4000,
}


def _run_once(rows_out=50, out_bytes=400, label="t", bucket=None,
              plan=None, static=STATIC, kind="fused"):
    """One profile session with one segment — the shape every dispatch
    entry produces."""
    with profiler.profile_session(
        plan or [{"op": "filter"}], label=label, schema="i32,i64",
        bucket=bucket, static=static,
    ):
        tok = profiler.segment_begin(
            0, kind, [{"op": "filter"}], rows_in=100
        )
        profiler.segment_end(tok, rows_out=rows_out, out_bytes=out_bytes)


class TestStoreRoundTrip:
    def test_every_session_appends_one_record(self):
        for _ in range(3):
            _run_once()
        recs = planstats.load()
        assert len(recs) == 3
        r = recs[-1]
        assert r["fp"] == planstats.plan_fingerprint([{"op": "filter"}])
        assert r["schema"] == "i32,i64"
        assert r["label"] == "t"
        seg = r["segments"][0]
        assert seg["rows_in"] == 100
        assert seg["rows_out"] == 50
        assert seg["out_bytes"] == 400
        assert r["bytes_moved"] == 400
        assert r["pred"]["segments"][0]["rows_bound"] == 100

    def test_disabled_gate_appends_nothing(self):
        config.clear_flag("PLANSTATS_DIR")
        config.set_flag("PROFILE", "on")  # sessions still open
        _run_once()
        assert planstats.record_session({"plan": None}) is None

    def test_counter_deltas_ride_the_record(self):
        base = planstats.counter_snapshot()
        metrics.counter_add("retry.attempts", 3)
        rec = planstats.record_session(
            {"plan": [{"op": "filter"}], "segments": []}, base
        )
        assert rec["counters"] == {"retry.attempts": 3}

    def test_fingerprint_is_stable_across_key_order(self):
        a = planstats.plan_fingerprint([{"op": "filter", "mask": 1}])
        b = planstats.plan_fingerprint([{"mask": 1, "op": "filter"}])
        assert a == b


class TestTornTail:
    def test_truncation_at_every_byte_recovers_complete_records(self):
        """kill -9 mid-append leaves a prefix; EVERY prefix must load
        to exactly the records whose frames fit whole — never an
        error, never a phantom record, tail dropped silently (the
        satellite-2 contract)."""
        for i in range(4):
            _run_once(rows_out=10 + i)
        (path,) = [
            os.path.join(planstats.stats_dir(), f)
            for f in os.listdir(planstats.stats_dir())
        ]
        blob = open(path, "rb").read()
        # frame ends from the framing itself
        ends = [len(planstats._MAGIC)]
        off = len(planstats._MAGIC)
        while off < len(blob):
            length, _crc = planstats._FRAME.unpack_from(blob, off)
            off += planstats._FRAME.size + length
            ends.append(off)
        assert ends[-1] == len(blob)
        cut_path = path + ".cut"
        for cut in range(len(planstats._MAGIC), len(blob) + 1):
            with open(cut_path, "wb") as f:
                f.write(blob[:cut])
            recs, torn = planstats.read_stats_file(cut_path)
            whole = max(i for i, e in enumerate(ends) if e <= cut)
            assert len(recs) == whole, f"cut={cut}"
            assert torn == (0 if cut in ends else 1), f"cut={cut}"
            for i, r in enumerate(recs):
                assert r["segments"][0]["rows_out"] == 10 + i
        os.remove(cut_path)

    def test_load_skips_torn_tail_silently(self):
        _run_once(rows_out=1)
        _run_once(rows_out=2)
        (path,) = [
            os.path.join(planstats.stats_dir(), f)
            for f in os.listdir(planstats.stats_dir())
        ]
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:-7])  # mid-record truncation
        recs = planstats.load()
        assert [r["segments"][0]["rows_out"] for r in recs] == [1]

    def test_mid_file_corruption_stops_scan_without_raising(self):
        """Unlike durable journals (client-acknowledged state, typed
        quarantine) a corrupt stats file degrades to what survived."""
        _run_once(rows_out=1)
        _run_once(rows_out=2)
        (path,) = [
            os.path.join(planstats.stats_dir(), f)
            for f in os.listdir(planstats.stats_dir())
        ]
        blob = bytearray(open(path, "rb").read())
        blob[len(planstats._MAGIC) + planstats._FRAME.size + 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        recs, torn = planstats.read_stats_file(path)
        assert recs == [] and torn == 0
        assert planstats.stats_doc().get("planstats.corrupt_files", 0) >= 1

    def test_bad_magic_is_not_fatal(self, tmp_path):
        p = str(tmp_path / "junk.wal")
        with open(p, "wb") as f:
            f.write(b"not a stats file")
        recs, torn = planstats.read_stats_file(p)
        assert recs == [] and torn == 0

    def test_append_self_heals_after_torn_write(self):
        _run_once(rows_out=1)
        w = planstats._writer()
        with w._lock:
            w._f.write(b"\x01\x02\x03")  # torn frame fragment
            w._f.flush()
        _run_once(rows_out=2)
        recs = planstats.load()
        assert [r["segments"][0]["rows_out"] for r in recs] == [1, 2]


class TestRotation:
    def test_rotation_keeps_one_old_generation(self):
        config.set_flag("PLANSTATS_ROTATE_MB", 0.0005)  # ~524 bytes
        for i in range(8):
            _run_once(rows_out=i + 1)
        files = sorted(os.listdir(planstats.stats_dir()))
        assert any(f.endswith(".wal.1") for f in files)
        assert planstats.stats_doc()["planstats.rotations"] >= 1
        # load() still reads both generations
        assert len(planstats.load()) >= 2


class TestDrift:
    def test_steady_state_raises_no_findings(self):
        for _ in range(4):
            _run_once()
        assert not planstats.stats_doc()["findings"]

    def test_history_skew_flags_cardinality(self):
        config.set_flag("DRIFT_ROWS_FACTOR", 2.0)
        for _ in range(3):
            _run_once(rows_out=50, out_bytes=400)
        _run_once(rows_out=5000, out_bytes=40000)
        last = planstats.load()[-1]
        kinds = [f["type"] for f in last["drift"]]
        assert "cardinality" in kinds
        assert planstats.stats_doc()["drift.cardinality"] >= 1

    def test_static_bound_violation_flags_cardinality(self):
        _run_once(rows_out=500)  # bound is 100
        last = planstats.load()[-1]
        assert any(
            f["type"] == "cardinality" and "static" in f["detail"]
            for f in last["drift"]
        )

    def test_hbm_overrun_flags_hbm(self):
        # proxy = rows_in*width + out_bytes with width 400 -> ~44000B
        # vs est 4000 * factor 2
        _run_once(rows_out=100, out_bytes=40000)
        last = planstats.load()[-1]
        assert any(f["type"] == "hbm" for f in last["drift"])

    def test_bucket_scales_the_hbm_estimate(self):
        # same bytes but bucket 1024 over bound 100 scales est x10.24:
        # no finding
        _run_once(rows_out=100, out_bytes=40000, bucket=1024)
        last = planstats.load()[-1]
        assert not any(
            f["type"] == "hbm" for f in last.get("drift") or []
        )

    def test_segmentation_change_flags_once(self):
        static = {
            "segments": [
                {"kind": "fused", "ops": [0], "rows_bound": 100,
                 "est_hbm_bytes": 4000},
                {"kind": "exact", "ops": [1], "rows_bound": 100,
                 "est_hbm_bytes": 4000},
            ],
            "rows_out_bound": 100,
            "est_hbm_peak_bytes": 4000,
        }
        _run_once(static=static)  # observed: ONE fused segment
        last = planstats.load()[-1]
        assert any(f["type"] == "segmentation" for f in last["drift"])

    def test_mesh_segment_is_not_segmentation_drift(self):
        _run_once(kind="mesh")
        last = planstats.load()[-1]
        assert not any(
            f["type"] == "segmentation"
            for f in last.get("drift") or []
        )

    def test_history_seeds_from_disk_across_reset(self):
        config.set_flag("DRIFT_ROWS_FACTOR", 2.0)
        for _ in range(3):
            _run_once(rows_out=50)
        planstats.reset()  # fresh process analog: in-memory history gone
        config.set_flag("PLANSTATS_DIR", planstats.stats_dir())
        _run_once(rows_out=5000, out_bytes=40000)
        last = planstats.load()[-1]
        assert any(
            f["type"] == "cardinality" and "history" in f["detail"]
            for f in last["drift"]
        )


class TestReport:
    def test_percentiles_and_pred_per_segment(self):
        for i in range(5):
            _run_once(rows_out=40 + i)
        rep = planstats.drift_report()
        assert rep["records"] == 5
        (g,) = rep["groups"]
        assert g["runs"] == 5
        (seg,) = g["segments"]
        assert seg["rows_out"]["n"] == 5
        assert seg["rows_out"]["p50"] == 42
        assert seg["rows_out"]["max"] == 44
        assert seg["pred"]["rows_bound"] == 100
        text = planstats.render_drift(rep)
        assert "rows_out p50/p95/max" in text
        assert "pred bound 100" in text

    def test_groups_key_on_fp_schema_bucket(self):
        _run_once(bucket=128)
        _run_once(bucket=256)
        _run_once(plan=[{"op": "cast"}])
        rep = planstats.drift_report()
        assert len(rep["groups"]) == 3

    def test_summary_block_shape(self):
        _run_once(rows_out=500)  # triggers a finding
        s = planstats.summary()
        assert s["records"] == 1
        assert s["plans"] == 1
        assert s["findings"].get("cardinality", 0) >= 1

    def test_summary_none_when_empty(self):
        assert planstats.summary() is None


class TestDisabledOverhead:
    def test_disabled_maybe_session_under_5us(self):
        """The acceptance bound: with everything off, the dispatch-
        plane hook (maybe_session + the planstats gate) costs <5µs."""
        config.clear_flag("PLANSTATS_DIR")
        assert not profiler.enabled()
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            with profiler.maybe_session([{"op": "filter"}]):
                pass
        per = (time.perf_counter() - t0) / n
        assert per < 5e-6, f"{per * 1e6:.2f}us"

    def test_disabled_record_session_is_none(self):
        config.clear_flag("PLANSTATS_DIR")
        assert planstats.record_session({"plan": None}) is None


class TestFraming:
    def test_frame_layout_matches_wal_discipline(self):
        """len|crc32|payload after the SRTS1 magic — the durable.py
        framing with a distinct magic, so neither reader misparses the
        other's files."""
        rec = planstats.record_session(
            {"plan": [{"op": "filter"}], "segments": []}
        )
        (path,) = [
            os.path.join(planstats.stats_dir(), f)
            for f in os.listdir(planstats.stats_dir())
        ]
        blob = open(path, "rb").read()
        assert blob.startswith(b"SRTS1\n")
        length, crc = struct.unpack_from("<II", blob, 6)
        payload = blob[6 + 8:6 + 8 + length]
        assert zlib.crc32(payload) & 0xFFFFFFFF == crc
        assert json.loads(payload.decode()) == rec
