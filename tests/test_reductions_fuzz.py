"""Reduction fuzz vs the pandas nullable-dtype oracle.

Random columns (int64/float64/bool, random null rates including
all-null and empty) through every reduction — sum/mean/min/max/count/
any/all/product/variance/std — against pandas' null-skipping
reductions, plus the null-result contract (no valid rows -> null,
variance needs two)."""

import math

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops.reductions import reduce


def _int_col(rng, n, null_rate):
    v = rng.integers(-100, 100, max(n, 1), dtype=np.int64)[:n]
    valid = rng.random(n) >= null_rate if n else np.zeros(0, bool)
    return (
        Column.from_numpy(v, validity=valid if n else None),
        pd.Series(v, dtype="Int64").mask(~valid) if n else pd.Series([], dtype="Int64"),
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("null_rate", [0.0, 0.3, 1.0])
def test_int_reductions_vs_pandas(seed, null_rate):
    rng = np.random.default_rng(seed)
    col, ser = _int_col(rng, 500, null_rate)
    for op, want in [
        ("sum", ser.sum() if ser.count() else None),
        ("count", ser.count()),
        ("min", ser.min()), ("max", ser.max()),
        ("mean", ser.mean()),
        ("variance", ser.var(ddof=1)),
        ("std", ser.std(ddof=1)),
    ]:
        got = reduce(col, op).to_pylist()[0]
        if want is None or want is pd.NA or (
            isinstance(want, float) and math.isnan(want)
        ):
            assert got is None, (op, got)
        elif isinstance(want, float) or op in ("mean", "variance", "std"):
            assert got == pytest.approx(float(want), rel=1e-9), op
        else:
            assert got == int(want), (op, got, want)


def test_float_reductions_vs_pandas():
    rng = np.random.default_rng(5)
    n = 400
    v = rng.standard_normal(n) * 10
    valid = rng.random(n) > 0.2
    col = Column.from_numpy(v, validity=valid)
    ser = pd.Series(v).mask(~valid)
    assert reduce(col, "sum").to_pylist()[0] == pytest.approx(ser.sum())
    assert reduce(col, "mean").to_pylist()[0] == pytest.approx(ser.mean())
    assert reduce(col, "min").to_pylist()[0] == pytest.approx(ser.min())
    assert reduce(col, "max").to_pylist()[0] == pytest.approx(ser.max())
    assert reduce(col, "variance").to_pylist()[0] == pytest.approx(
        ser.var(ddof=1)
    )


def test_bool_any_all_vs_pandas():
    rng = np.random.default_rng(6)
    for null_rate in (0.0, 0.4, 1.0):
        n = 60
        v = rng.random(n) > 0.5
        valid = rng.random(n) >= null_rate
        col = Column.from_numpy(v, validity=valid)
        ser = pd.Series(v, dtype="boolean").mask(~valid)
        got_any = reduce(col, "any").to_pylist()[0]
        got_all = reduce(col, "all").to_pylist()[0]
        if ser.count() == 0:
            assert got_any is None and got_all is None
        else:
            assert got_any == bool(ser.dropna().any())
            assert got_all == bool(ser.dropna().all())


def test_product_and_empty():
    rng = np.random.default_rng(8)
    v = rng.integers(1, 5, 20, dtype=np.int64)
    valid = rng.random(20) > 0.3
    col = Column.from_numpy(v, validity=valid)
    want = int(np.prod(v[valid]))
    assert reduce(col, "product").to_pylist() == [want]
    # all-null -> identity product but null result
    col2 = Column.from_numpy(v, validity=np.zeros(20, bool))
    assert reduce(col2, "product").to_pylist() == [None]
    # empty column: every reduction must be null (count 0)
    empty = Column.from_numpy(np.zeros(0, dtype=np.int64))
    assert reduce(empty, "count").to_pylist() == [0]
    for op in ("sum", "min", "max", "mean", "variance", "product"):
        assert reduce(empty, op).to_pylist() == [None], op


def test_variance_needs_two_valid():
    col = Column.from_numpy(
        np.array([5, 9], dtype=np.int64),
        validity=np.array([True, False]),
    )
    assert reduce(col, "variance").to_pylist() == [None]
    assert reduce(col, "std").to_pylist() == [None]
