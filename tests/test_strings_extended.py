"""Tests for the extended string ops (strip/find/pad/replace/split/
reverse) against python's str semantics on ASCII data."""

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import strings


@pytest.fixture
def col():
    return Column.from_strings(
        ["  hello  ", "world", "", None, "a b c", "xx"]
    )


class TestStrip:
    def test_strip(self, col):
        got = strings.strip(col).to_pylist()
        assert got == ["hello", "world", "", None, "a b c", "xx"]

    def test_lstrip_rstrip(self, col):
        assert strings.lstrip(col).to_pylist() == [
            "hello  ", "world", "", None, "a b c", "xx",
        ]
        assert strings.rstrip(col).to_pylist() == [
            "  hello", "world", "", None, "a b c", "xx",
        ]

    def test_strip_custom_chars(self):
        c = Column.from_strings(["xxabcxx", "xyx"])
        assert strings.strip(c, "x").to_pylist() == ["abc", "y"]

    def test_strip_all_stripped(self):
        c = Column.from_strings(["   ", "a"])
        assert strings.strip(c).to_pylist() == ["", "a"]


class TestFind:
    def test_find(self):
        c = Column.from_strings(["hello", "world", "ololo", ""])
        got = strings.find(c, "lo").to_pylist()
        assert got == [s.find("lo") for s in ["hello", "world", "ololo", ""]]

    def test_find_first_occurrence(self):
        c = Column.from_strings(["abcabc"])
        assert strings.find(c, "bc").to_pylist() == [1]

    def test_find_empty_pattern(self):
        c = Column.from_strings(["abc"])
        assert strings.find(c, "").to_pylist() == [0]


class TestPad:
    def test_rpad_truncates_like_spark(self):
        c = Column.from_strings(["ab", "abcdef"])
        got = strings.pad(c, 4, "right", "*").to_pylist()
        assert got == ["ab**", "abcd"]  # Spark rpad truncates to width

    def test_lpad_truncates_like_spark(self):
        c = Column.from_strings(["ab", "abcdef"])
        got = strings.pad(c, 4, "left", "0").to_pylist()
        assert got == ["00ab", "abcd"]

    def test_multichar_fill(self):
        c = Column.from_strings(["x"])
        assert strings.pad(c, 6, "left", "ab").to_pylist() == ["ababax"]
        assert strings.pad(c, 6, "right", "ab").to_pylist() == ["xababa"]

    def test_trim_space_only_default(self):
        c = Column.from_strings(["\thi\t", " hi "])
        # Spark trim removes only spaces by default
        assert strings.strip(c).to_pylist() == ["\thi\t", "hi"]


class TestReplace:
    def test_equal_width_device(self):
        c = Column.from_strings(["banana", "abcabc", "xyz"])
        got = strings.replace(c, "an", "AN").to_pylist()
        assert got == [s.replace("an", "AN") for s in ["banana", "abcabc", "xyz"]]

    def test_nonoverlapping_greedy(self):
        c = Column.from_strings(["aaaa"])
        assert strings.replace(c, "aa", "bb").to_pylist() == ["bbbb"]

    def test_width_changing_host(self):
        c = Column.from_strings(["banana", None, "x"])
        got = strings.replace(c, "na", "_").to_pylist()
        assert got == ["ba__", None, "x"]


class TestSplit:
    def test_split_get(self):
        c = Column.from_strings(["a,b,c", "one", ",x", "a,,b"])
        for i in range(3):
            got = strings.split_get(c, ",", i).to_pylist()
            want = [
                (s.split(",")[i] if i < len(s.split(",")) else "")
                for s in ["a,b,c", "one", ",x", "a,,b"]
            ]
            assert got == want, f"index {i}"


class TestReverse:
    def test_reverse(self):
        c = Column.from_strings(["abc", "", "xy", None])
        assert strings.reverse(c).to_pylist() == ["cba", "", "yx", None]


class TestStringCasts:
    """Round-3 VERDICT item 8: string<->number casts, Spark non-ANSI
    semantics (unparseable -> null), oracle-tested."""

    def test_string_to_int(self):
        col = Column.from_strings(
            ["42", "-7", "+13", "  99  ", "3.7", "-3.7", "abc", "",
             "12x", "9223372036854775807", "1e3", None, "0",
             "00000000000000000042", "9999999999999999999"]
        )
        out = ops.cast(col, dt.INT64)
        assert out.to_pylist() == [
            42, -7, 13, 99, 3, -3, None, None,
            None, 9223372036854775807, None, None, 0,
            42, None,
        ]

    def test_string_to_decimal_overflow_nulls(self):
        col = Column.from_strings(["9999999999999999", "1.5"])
        out = ops.cast(col, dt.decimal64(-3))
        # 1e16 * 1000 exceeds the 18-digit exact window -> null, never
        # a wrapped value marked valid
        assert out.to_pylist() == [None, 1500]

    def test_float_to_string_shortest(self):
        col = Column.from_numpy(np.asarray([0.0005, 1e-7, 1.25e10]))
        out = ops.cast(col, dt.STRING)
        assert out.to_pylist() == ["5.0E-4", "1.0E-7", "1.25E10"]

    def test_string_to_int_range_check(self):
        col = Column.from_strings(["127", "128", "-128", "-129"])
        out = ops.cast(col, dt.INT8)
        assert out.to_pylist() == [127, None, -128, None]

    def test_string_to_float(self):
        import math

        col = Column.from_strings(
            ["1.5", "-2.25", "1e3", "-4.5E-2", ".5", "7.", "abc",
             "NaN", "Infinity", "-Infinity", None, "0"]
        )
        out = ops.cast(col, dt.FLOAT64)
        got = out.to_pylist()
        want = [1.5, -2.25, 1000.0, -0.045, 0.5, 7.0, None,
                float("nan"), float("inf"), float("-inf"), None, 0.0]
        for g, w in zip(got, want):
            if w is None:
                assert g is None
            elif isinstance(w, float) and math.isnan(w):
                assert math.isnan(g)
            else:
                assert g == pytest.approx(w, rel=1e-12)

    def test_string_to_bool(self):
        col = Column.from_strings(
            ["true", "FALSE", "t", "no", "1", "0", "maybe", None]
        )
        out = ops.cast(col, dt.BOOL8)
        assert out.to_pylist() == [
            True, False, True, False, True, False, None, None
        ]

    def test_string_to_decimal(self):
        col = Column.from_strings(
            ["1.234", "-0.5", "10", "1.23456", "x"]
        )
        out = ops.cast(col, dt.decimal64(-3))
        # unscaled at 10^-3; excess fractional digits truncate
        assert out.to_pylist() == [1234, -500, 10000, 1234, None]

    def test_int_to_string(self, rng):
        vals = np.concatenate([
            rng.integers(-(10**17), 10**17, 200),
            np.asarray([0, 1, -1, np.iinfo(np.int64).max,
                        np.iinfo(np.int64).min]),
        ]).astype(np.int64)
        col = Column.from_numpy(vals)
        out = ops.cast(col, dt.STRING)
        assert out.to_pylist() == [str(int(v)) for v in vals]

    def test_bool_to_string(self):
        col = Column.from_numpy(np.asarray([True, False]))
        out = ops.cast(col, dt.STRING)
        assert out.to_pylist() == ["true", "false"]

    def test_float_to_string(self):
        col = Column.from_numpy(np.asarray([1.5, 0.0, -2.0, 1e10]))
        out = ops.cast(col, dt.STRING)
        assert out.to_pylist() == ["1.5", "0.0", "-2.0", "1.0E10"]

    def test_decimal_to_string(self):
        col = Column.from_numpy(
            np.asarray([1234, -500], dtype=np.int64),
            dtype=dt.decimal64(-3),
        )
        out = ops.cast(col, dt.STRING)
        assert out.to_pylist() == ["1.234", "-0.500"]

    def test_round_trip_int_string_int(self, rng):
        vals = rng.integers(-(10**12), 10**12, 300).astype(np.int64)
        col = Column.from_numpy(vals)
        back = ops.cast(ops.cast(col, dt.STRING), dt.INT64)
        assert back.to_pylist() == vals.tolist()


class TestDictionaryEncode:
    def test_encode_round_trip(self, rng):
        words = ["apple", "pear", "fig", "kiwi", "plum"]
        vals = [words[i] for i in rng.integers(0, 5, 400)]
        col = Column.from_strings(vals)
        codes, uniq = strings.dictionary_encode(col)
        u = uniq.to_pylist()
        assert sorted(u) == sorted(set(vals))
        decoded = [u[c] for c in codes.to_pylist()]
        assert decoded == vals

    def test_shared_encoding_joins_string_keys(self, rng):
        lk = ["a", "b", "c", "a", "d"]
        rk = ["b", "a", "e"]
        lcol = Column.from_strings(lk)
        rcol = Column.from_strings(rk)
        lc, rc = strings.encode_join_keys(lcol, rcol)
        left = Table(
            [lc, Column.from_numpy(np.arange(5, dtype=np.int64))],
            ["k", "lv"],
        )
        right = Table(
            [rc, Column.from_numpy(np.arange(3, dtype=np.int64))],
            ["k", "rv"],
        )
        out = ops.inner_join(left, right, ["k"])
        got = sorted(zip(out["lv"].to_pylist(), out["rv"].to_pylist()))
        want = sorted(
            (i, j)
            for i, a in enumerate(lk)
            for j, b in enumerate(rk)
            if a == b
        )
        assert got == want

    def test_codes_match_string_join(self, rng):
        """Code-based join result == direct string-key join result."""
        pool = [f"w{i}" for i in range(12)]
        lk = [pool[i] for i in rng.integers(0, 12, 60)]
        rk = [pool[i] for i in rng.integers(0, 12, 40)]
        ls = Table(
            [Column.from_strings(lk),
             Column.from_numpy(np.arange(60, dtype=np.int64))],
            ["k", "lv"],
        )
        rs = Table(
            [Column.from_strings(rk),
             Column.from_numpy(np.arange(40, dtype=np.int64))],
            ["k", "rv"],
        )
        direct = ops.inner_join(ls, rs, ["k"])
        lc, rc = strings.encode_join_keys(ls["k"], rs["k"])
        lt = Table([lc, ls["lv"]], ["k", "lv"])
        rt = Table([rc, rs["rv"]], ["k", "rv"])
        coded = ops.inner_join(lt, rt, ["k"])
        a = sorted(zip(direct["lv"].to_pylist(), direct["rv"].to_pylist()))
        b = sorted(zip(coded["lv"].to_pylist(), coded["rv"].to_pylist()))
        assert a == b


class TestCharClassPreds:
    WORDS = ["abc", "ABC", "a1", "123", "", " \t", "Hello World",
             "MiXeD", "under_score", "++", "42"]

    def _col(self):
        from spark_rapids_jni_tpu.column import Column
        return Column.from_strings(self.WORDS)

    def test_is_digit(self):
        from spark_rapids_jni_tpu.ops.strings import is_digit
        got = is_digit(self._col()).to_pylist()
        want = [w.isdigit() for w in self.WORDS]
        assert got == want

    def test_is_alpha(self):
        from spark_rapids_jni_tpu.ops.strings import is_alpha
        got = is_alpha(self._col()).to_pylist()
        want = [w.isalpha() for w in self.WORDS]
        assert got == want

    def test_is_alnum(self):
        from spark_rapids_jni_tpu.ops.strings import is_alnum
        got = is_alnum(self._col()).to_pylist()
        want = [w.isalnum() for w in self.WORDS]
        assert got == want

    def test_is_space(self):
        from spark_rapids_jni_tpu.ops.strings import is_space
        got = is_space(self._col()).to_pylist()
        want = [w.isspace() for w in self.WORDS]
        assert got == want

    def test_is_upper_lower(self):
        from spark_rapids_jni_tpu.ops.strings import is_lower, is_upper
        col = self._col()
        got_u = is_upper(col).to_pylist()
        got_l = is_lower(col).to_pylist()
        want_u = [w.isupper() for w in self.WORDS]
        want_l = [w.islower() for w in self.WORDS]
        assert got_u == want_u
        assert got_l == want_l


class TestCaseAndPad:
    def test_zfill(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import zfill
        words = ["42", "-7", "+3", "hello", "", "12345678"]
        got = zfill(Column.from_strings(words), 5).to_pylist()
        want = [w.zfill(5) for w in words]
        assert got == want

    def test_capitalize_title(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import capitalize, title
        words = ["hello world", "HELLO", "a.b c", "", "3abc"]
        col = Column.from_strings(words)
        assert capitalize(col).to_pylist() == [
            w.capitalize() for w in words
        ]
        assert title(col).to_pylist() == [w.title() for w in words]


class TestUrl:
    def test_url_encode_oracle(self):
        from urllib.parse import quote

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import url_encode
        words = ["hello world", "a/b?c=d&e", "safe-_.~ABC123", "",
                 "100%", "x y z"]
        got = url_encode(Column.from_strings(words)).to_pylist()
        want = [quote(w, safe="-_.~") for w in words]
        assert got == want

    def test_url_decode_oracle(self):
        from urllib.parse import unquote_plus

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import url_decode
        words = ["hello%20world", "a%2Fb%3Fc", "plus+sign", "100%",
                 "%zz", "", "%41%42c"]
        got = url_decode(Column.from_strings(words)).to_pylist()
        want = [unquote_plus(w) for w in words]
        assert got == want

    def test_url_round_trip(self, rng):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import url_decode, url_encode
        words = ["".join(rng.choice(list("ab /?&=%+~"), 8)) for _ in range(100)]
        col = Column.from_strings(words)
        back = url_decode(url_encode(col)).to_pylist()
        assert back == words


def test_url_encode_and_replace_re_empty_column():
    from spark_rapids_jni_tpu.column import Column
    from spark_rapids_jni_tpu.ops.regex import replace_re
    from spark_rapids_jni_tpu.ops.strings import url_encode

    col = Column.from_strings([])
    assert url_encode(col).to_pylist() == []
    assert replace_re(col, r"\d+", "#").to_pylist() == []


class TestConcatWsAndSlice:
    def test_concat_ws_skips_nulls(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import concat_ws

        a = Column.from_strings(["x", None, "p", None])
        b = Column.from_strings(["y", "m", None, None])
        c = Column.from_strings(["z", "n", "q", None])
        out = concat_ws("-", a, b, c).to_pylist()
        # Spark concat_ws skips nulls; all-null row yields ''
        assert out == ["x-y-z", "m-n", "p-q", ""]

    def test_concat_ws_multibyte_sep(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import concat_ws

        a = Column.from_strings(["a", "bb"])
        b = Column.from_strings(["c", "dd"])
        assert concat_ws(", ", a, b).to_pylist() == ["a, c", "bb, dd"]

    def test_substring_column(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import substring_column

        col = Column.from_strings(["hello", "world", "hi", None])
        starts = Column.from_numpy(np.array([1, 0, 5, 0], np.int32))
        lens = Column.from_numpy(np.array([3, 2, 4, 1], np.int32))
        out = substring_column(col, starts, lens).to_pylist()
        assert out == ["ell", "wo", "", None]

    def test_substring_column_null_offsets(self):
        import numpy as np

        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import substring_column

        col = Column.from_strings(["abcdef", "ghij"])
        starts = Column.from_numpy(
            np.array([2, 0], np.int32), validity=np.array([True, False])
        )
        lens = Column.from_numpy(np.array([2, 2], np.int32))
        out = substring_column(col, starts, lens).to_pylist()
        assert out == ["cd", None]

    def test_concat_ws_single_column_rezeroes_null_bytes(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import binary_op, concat, concat_ws

        # concat leaves real bytes under null rows; concat_ws of that
        # single column must re-zero them so '' equality holds
        a = Column.from_strings(["x", None])
        b = Column.from_strings(["y", "zz"])
        c = concat(a, b)  # row 1 null but carries 'zz' bytes
        out = concat_ws("-", c)
        assert out.to_pylist() == ["xy", ""]
        empty = Column.from_strings(["xy", ""])
        eq = binary_op("eq", out, empty)
        assert eq.to_pylist() == [True, True]


class TestTranslate:
    def test_mapping_and_deletion(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import translate

        col = Column.from_strings(["abcabc", "xyz", None, ""])
        # a->1, b->2, c deleted (to shorter than from)
        out = translate(col, "abc", "12").to_pylist()
        want = [w.translate(str.maketrans("ab", "12", "c"))
                if w is not None else None
                for w in ["abcabc", "xyz", None, ""]]
        assert out == want

    def test_pure_mapping_no_deletion(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import translate

        col = Column.from_strings(["hello world"])
        out = translate(col, "lo ", "01_").to_pylist()
        assert out == ["hello world".translate(str.maketrans("lo ", "01_"))]

    def test_first_occurrence_wins_and_ascii_guard(self):
        from spark_rapids_jni_tpu.column import Column
        from spark_rapids_jni_tpu.ops.strings import translate
        import pytest as _pytest

        col = Column.from_strings(["aaa"])
        # Spark TRANSLATE: first duplicate mapping wins
        assert translate(col, "aba", "xyz").to_pylist() == ["xxx"]
        assert translate(col, "aa", "x").to_pylist() == ["xxx"]
        with _pytest.raises(ValueError):
            translate(col, "é", "e")


class TestBitapLiteralMatching:
    """Shift-or scan formulation (round-4 VERDICT item 5): one uint64
    bitset per row, O(n*pad) work, O(1) graph — must agree with the
    unrolled window formulation and Python oracles everywhere."""

    def test_overlapping_and_boundary_matches(self):
        vals = ["aaa", "aa", "a", "", "baab", "abab", "ababab", "xaba"]
        col = Column.from_strings(vals)
        for pat in ["aa", "ab", "aba", "b", "xaba"]:
            got = np.asarray(strings.contains(col, pat).data).tolist()
            assert got == [pat in v for v in vals], pat
            gotf = np.asarray(strings.find(col, pat).data).tolist()
            assert gotf == [v.find(pat) for v in vals], pat

    def test_pattern_longer_than_bitap_bitset(self):
        """>64-byte patterns take the unrolled fallback."""
        long_pat = "x" * 70
        vals = ["y" * 80, "z" + long_pat + "z", long_pat]
        col = Column.from_strings(vals)
        got = np.asarray(strings.contains(col, long_pat).data).tolist()
        assert got == [False, True, True]
        gotf = np.asarray(strings.find(col, long_pat).data).tolist()
        assert gotf == [-1, 1, 0]

    def test_replace_greedy_scan(self):
        vals = ["aaaa", "abab", "xx", "aba"]
        col = Column.from_strings(vals)
        out = strings.replace(col, "aa", "zz")
        assert out.to_pylist() == [v.replace("aa", "zz") for v in vals]

    def test_contains_near_pad_boundary(self):
        # pattern match ending exactly at the pad edge
        col = Column.from_strings(["abcd", "abc", "dabc"])
        got = np.asarray(strings.contains(col, "abcd").data).tolist()
        assert got == [True, False, False]

    def test_string_key_capped_join_is_jittable(self):
        """Auto dictionary-encoding must not break jit (no host sync)."""
        import jax

        from spark_rapids_jni_tpu.ops.join import inner_join_capped

        left = Table(
            [Column.from_strings(["a", "bb", "c", "bb"]),
             Column.from_numpy(np.arange(4, dtype=np.int64))],
            ["k", "lv"],
        )
        right = Table(
            [Column.from_strings(["bb", "d", "a"]),
             Column.from_numpy(np.arange(3, dtype=np.int64) * 10)],
            ["k", "rv"],
        )
        fn = jax.jit(
            lambda l, r: inner_join_capped(l, r, ["k"], capacity=8)
        )
        out, cnt = fn(left, right)
        assert int(cnt) == 3  # a->a, bb->bb (x2)


class TestDeviceDecimalFormat:
    """Round-4 VERDICT weak item 7: decimal -> string now formats on
    DEVICE (the int formatter's digit machinery + point insertion);
    only float shortest-repr and the DECIMAL128/positive-scale corners
    remain host passes."""

    @pytest.mark.parametrize("scale", [0, -1, -2, -5])
    def test_matches_host_formatter(self, scale):
        from spark_rapids_jni_tpu.ops.strings import _format_host

        rng = np.random.default_rng(scale + 10)
        u = rng.integers(-(10**9), 10**9, 400)
        valid = rng.random(400) > 0.1
        col = Column.from_numpy(
            u, validity=valid,
            dtype=dt.DType(dt.TypeId.DECIMAL64, scale),
        )
        got = ops.cast(col, dt.STRING).to_pylist()
        want = _format_host(col).to_pylist()
        assert got == want

    def test_jittable(self):
        import jax

        from spark_rapids_jni_tpu.ops.strings import _format_decimal

        col = Column.from_numpy(
            np.array([1234, -5, 0], np.int64),
            dtype=dt.DType(dt.TypeId.DECIMAL64, -2),
        )
        out = jax.jit(_format_decimal)(col)
        assert out.to_pylist() == ["12.34", "-0.05", "0.00"]


S = strings


class TestDecimalFormatDevice:
    """Every decimal width and scale formats on device (round-5: the
    last _format_host corners closed — DECIMAL128 via base-10^9 limb
    division, positive scales as appended zeros)."""

    @staticmethod
    def _oracle(vals, scale):
        out = []
        for u in vals:
            sgn = "-" if u < 0 else ""
            digits = str(abs(int(u)))
            if scale > 0:
                out.append(sgn + digits + "0" * scale)
            elif scale == 0:
                out.append(sgn + digits)
            else:
                digits = digits.rjust(-scale + 1, "0")
                out.append(sgn + digits[:scale] + "." + digits[scale:])
        return out

    @pytest.mark.parametrize("scale", [0, -2, -19, -25, 3])
    def test_decimal64_all_scales(self, scale):
        rng = np.random.default_rng(21)
        v = rng.integers(-(10 ** 17), 10 ** 17, 300).astype(np.int64)
        col = Column.from_numpy(
            v, dtype=dt.DType(dt.TypeId.DECIMAL64, scale)
        )
        got = S.cast(col, dt.STRING).to_pylist()
        assert got == self._oracle(v, scale)

    @pytest.mark.parametrize("scale", [0, -10, -37, 4])
    def test_decimal128_all_scales(self, scale):
        rng = np.random.default_rng(22)
        vals = [
            int(rng.integers(-(10 ** 18), 10 ** 18))
            * int(rng.integers(1, 10 ** 18))
            for _ in range(200)
        ] + [0, 10 ** 37, -(10 ** 37), 1 << 126, (1 << 127) - 1,
             -(1 << 127)]
        col = Column.from_decimal128(vals, scale=scale)
        got = S.cast(col, dt.STRING).to_pylist()
        assert got == self._oracle(vals, scale)


class TestDecimal128Parse:
    """STRING -> DECIMAL128: exact 128-bit masked-Horner accumulation."""

    def test_vs_decimal_oracle(self):
        from decimal import Decimal, localcontext

        from spark_rapids_jni_tpu.ops.int128 import to_py_ints

        strs = [
            "1234567890123456789012345678.12", "-0.99", "0.005",
            "  -42  ", "12.3.4", "",
            "99999999999999999999999999999999999.999",
            "-12345678901234567890123456789012345.678",
            "170141183460469231731687303715884105727",  # 39 digits
            "0", "-0.0", ".5", "00001.5",
        ]
        t = Table.from_pydict({"s": strs})
        got = S.cast(t["s"], dt.DType(dt.TypeId.DECIMAL128, -3))
        vals = to_py_ints(np.asarray(got.data))
        ok = np.asarray(got.validity)
        for s_, v, o in zip(strs, vals, ok):
            want = None
            try:
                with localcontext() as ctx:
                    ctx.prec = 60
                    d = Decimal(s_.strip())
                    if "e" in s_.lower() or s_.count(".") > 1:
                        raise ValueError
                    unscaled = int(
                        d.scaleb(3).to_integral_value(rounding="ROUND_DOWN")
                    )
                    # representable: sig int digits + k <= 38
                    sig = len(str(abs(int(d))).lstrip("0"))
                    if int(d) == 0:
                        sig = 0
                    if sig + 3 <= 38:
                        want = unscaled
            except Exception:
                want = None
            got_v = int(v) if o else None
            assert got_v == want, (s_, got_v, want)

    def test_format_parse_roundtrip(self):
        from spark_rapids_jni_tpu.ops.int128 import to_py_ints

        rng = np.random.default_rng(33)
        vals = [
            int(rng.integers(-(10 ** 18), 10 ** 18))
            * int(rng.integers(1, 10 ** 17))
            for _ in range(300)
        ] + [0, 10 ** 34, -(10 ** 34)]
        col = Column.from_decimal128(vals, scale=-4)
        s = S.cast(col, dt.STRING)
        back = S.cast(s, dt.DType(dt.TypeId.DECIMAL128, -4))
        assert back.validity is None or bool(np.asarray(back.validity).all())
        got = to_py_ints(np.asarray(back.data))
        assert [int(g) for g in got] == vals


class TestPositiveScaleDecimalParse:
    @pytest.mark.parametrize("tid,width", [
        (dt.TypeId.DECIMAL64, 64), (dt.TypeId.DECIMAL128, 128),
    ])
    def test_truncates_toward_zero(self, tid, width):
        from spark_rapids_jni_tpu.ops.int128 import to_py_ints

        t = Table.from_pydict(
            {"s": ["123456", "-9876.5", "999", "1000", "-1000", "0"]}
        )
        got = S.cast(t["s"], dt.DType(tid, 3))
        if width == 128:
            vals = [int(x) for x in to_py_ints(np.asarray(got.data))]
        else:
            vals = [int(x) for x in np.asarray(got.data)]
        assert vals == [123, -9, 0, 1, -1, 0]
        # format side: round-trip of the representable values
        back = S.cast(got, dt.STRING).to_pylist()
        assert back == ["123000", "-9000", "0000", "1000", "-1000", "0000"]

    def test_wide_string_fits_after_truncation(self):
        # review catch: 20 integer digits with scale 3 has a 17-digit
        # unscaled value - representable, and must not be nulled by a
        # pre-truncation width check (the dropped digits never touch
        # the accumulator)
        from spark_rapids_jni_tpu.ops.int128 import to_py_ints

        t = Table.from_pydict(
            {"s": ["12345678901234567890", "-12345678901234567890.9"]}
        )
        got64 = S.cast(t["s"], dt.DType(dt.TypeId.DECIMAL64, 3))
        assert got64.validity is None or bool(
            np.asarray(got64.validity).all()
        )
        assert [int(x) for x in np.asarray(got64.data)] == [
            12345678901234567, -12345678901234567,
        ]
        # 40-digit integer, scale 5: 35-digit unscaled fits DECIMAL128
        wide = "1234567890" * 4
        t2 = Table.from_pydict({"s": [wide, "-" + wide]})
        got128 = S.cast(t2["s"], dt.DType(dt.TypeId.DECIMAL128, 5))
        assert got128.validity is None or bool(
            np.asarray(got128.validity).all()
        )
        want = int(wide) // 10 ** 5
        assert [int(x) for x in to_py_ints(np.asarray(got128.data))] == [
            want, -want,
        ]
