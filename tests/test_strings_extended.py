"""Tests for the extended string ops (strip/find/pad/replace/split/
reverse) against python's str semantics on ASCII data."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings


@pytest.fixture
def col():
    return Column.from_strings(
        ["  hello  ", "world", "", None, "a b c", "xx"]
    )


class TestStrip:
    def test_strip(self, col):
        got = strings.strip(col).to_pylist()
        assert got == ["hello", "world", "", None, "a b c", "xx"]

    def test_lstrip_rstrip(self, col):
        assert strings.lstrip(col).to_pylist() == [
            "hello  ", "world", "", None, "a b c", "xx",
        ]
        assert strings.rstrip(col).to_pylist() == [
            "  hello", "world", "", None, "a b c", "xx",
        ]

    def test_strip_custom_chars(self):
        c = Column.from_strings(["xxabcxx", "xyx"])
        assert strings.strip(c, "x").to_pylist() == ["abc", "y"]

    def test_strip_all_stripped(self):
        c = Column.from_strings(["   ", "a"])
        assert strings.strip(c).to_pylist() == ["", "a"]


class TestFind:
    def test_find(self):
        c = Column.from_strings(["hello", "world", "ololo", ""])
        got = strings.find(c, "lo").to_pylist()
        assert got == [s.find("lo") for s in ["hello", "world", "ololo", ""]]

    def test_find_first_occurrence(self):
        c = Column.from_strings(["abcabc"])
        assert strings.find(c, "bc").to_pylist() == [1]

    def test_find_empty_pattern(self):
        c = Column.from_strings(["abc"])
        assert strings.find(c, "").to_pylist() == [0]


class TestPad:
    def test_rpad_truncates_like_spark(self):
        c = Column.from_strings(["ab", "abcdef"])
        got = strings.pad(c, 4, "right", "*").to_pylist()
        assert got == ["ab**", "abcd"]  # Spark rpad truncates to width

    def test_lpad_truncates_like_spark(self):
        c = Column.from_strings(["ab", "abcdef"])
        got = strings.pad(c, 4, "left", "0").to_pylist()
        assert got == ["00ab", "abcd"]

    def test_multichar_fill(self):
        c = Column.from_strings(["x"])
        assert strings.pad(c, 6, "left", "ab").to_pylist() == ["ababax"]
        assert strings.pad(c, 6, "right", "ab").to_pylist() == ["xababa"]

    def test_trim_space_only_default(self):
        c = Column.from_strings(["\thi\t", " hi "])
        # Spark trim removes only spaces by default
        assert strings.strip(c).to_pylist() == ["\thi\t", "hi"]


class TestReplace:
    def test_equal_width_device(self):
        c = Column.from_strings(["banana", "abcabc", "xyz"])
        got = strings.replace(c, "an", "AN").to_pylist()
        assert got == [s.replace("an", "AN") for s in ["banana", "abcabc", "xyz"]]

    def test_nonoverlapping_greedy(self):
        c = Column.from_strings(["aaaa"])
        assert strings.replace(c, "aa", "bb").to_pylist() == ["bbbb"]

    def test_width_changing_host(self):
        c = Column.from_strings(["banana", None, "x"])
        got = strings.replace(c, "na", "_").to_pylist()
        assert got == ["ba__", None, "x"]


class TestSplit:
    def test_split_get(self):
        c = Column.from_strings(["a,b,c", "one", ",x", "a,,b"])
        for i in range(3):
            got = strings.split_get(c, ",", i).to_pylist()
            want = [
                (s.split(",")[i] if i < len(s.split(",")) else "")
                for s in ["a,b,c", "one", ",x", "a,,b"]
            ]
            assert got == want, f"index {i}"


class TestReverse:
    def test_reverse(self):
        c = Column.from_strings(["abc", "", "xy", None])
        assert strings.reverse(c).to_pylist() == ["cba", "", "yx", None]
