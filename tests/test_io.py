"""I/O layer tests: Parquet/ORC/CSV/IPC round trips + predicate pushdown.

Models the reference's I/O coverage (the cudf Java I/O tests run in-module,
SURVEY.md §4 "integration suite by inclusion") with the added pushdown
checks the TPU design introduces: row-group pruning must be *observable*
(pruned groups never decoded) and exact filtering must match a host oracle.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.io import (
    col,
    parquet_metadata,
    read_arrow_ipc,
    read_csv,
    read_orc,
    read_parquet,
    scan_orc,
    scan_parquet,
    write_arrow_ipc,
    write_csv,
    write_orc,
    write_parquet,
)
from spark_rapids_jni_tpu.io.predicates import ColumnStats, from_dnf


def _typed_table(rng, n=200):
    """A table covering the reference round-trip test's type spread
    (RowConversionTest.java:30-39) plus strings."""
    return Table.from_pydict(
        {
            "i64": rng.integers(-(2**40), 2**40, n),
            "f64": rng.standard_normal(n),
            "i32": rng.integers(-(2**20), 2**20, n).astype(np.int32),
            "b": rng.random(n) > 0.5,
            "f32": rng.standard_normal(n).astype(np.float32),
            "i8": rng.integers(-100, 100, n).astype(np.int8),
            "s": [f"row-{i}" if i % 7 else None for i in range(n)],
        }
    )


class TestParquet:
    def test_round_trip(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.parquet"
        write_parquet(t, p)
        back = read_parquet(p)
        assert back.to_pydict() == t.to_pydict()

    def test_round_trip_nulls_and_decimals(self, tmp_path):
        t = Table(
            [
                Column.from_numpy(
                    np.array([1000, -2500, 0, 99], dtype=np.int32),
                    validity=np.array([True, True, False, True]),
                    dtype=dt.decimal32(-3),
                ),
                Column.from_numpy(np.array([5.0, 6.0, 7.0, 8.0])),
            ],
            ["d", "f"],
        )
        p = tmp_path / "d.parquet"
        write_parquet(t, p)
        back = read_parquet(p)
        assert back["d"].dtype == dt.decimal32(-3)
        assert back.to_pydict() == t.to_pydict()

    def test_projection(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.parquet"
        write_parquet(t, p)
        back = read_parquet(p, columns=["i32", "s"])
        assert list(back.names) == ["i32", "s"]
        assert back.to_pydict() == t.select(["i32", "s"]).to_pydict()

    def test_row_group_pruning_observable(self, tmp_path):
        # 4 row groups of 100 rows with disjoint key ranges; a filter on one
        # range must decode exactly one group *before* exact filtering.
        n = 400
        k = np.arange(n, dtype=np.int64)
        v = (k * 3) % 17
        atbl = pa.table({"k": k, "v": v})
        p = tmp_path / "rg.parquet"
        pq.write_table(atbl, p, row_group_size=100)

        meta = parquet_metadata(p)
        assert meta["num_row_groups"] == 4
        assert meta["row_groups"][1]["stats"]["k"].min == 100

        pred = (col("k") >= 150) & (col("k") < 180)
        batches = list(scan_parquet(p, filters=pred, exact_filter=False))
        # only row group [100,200) survives pruning
        assert len(batches) == 1
        assert batches[0].row_count == 100

        exact = read_parquet(p, filters=pred)
        kk = np.asarray(exact["k"].to_numpy())
        assert kk.min() == 150 and kk.max() == 179 and len(kk) == 30

    def test_filters_dnf_and_or(self, tmp_path, rng):
        t = _typed_table(rng, n=300)
        p = tmp_path / "t.parquet"
        write_parquet(t, p, row_group_size=50)
        pred = (col("i8") > 50) | (col("i8") < -50)
        back = read_parquet(p, filters=pred)
        i8 = np.asarray(t["i8"].to_numpy())
        want = int(((i8 > 50) | (i8 < -50)).sum())
        assert back.row_count == want
        # pyarrow-style DNF spelling of the same predicate
        back2 = read_parquet(
            p, filters=[[("i8", ">", 50)], [("i8", "<", -50)]]
        )
        assert back2.to_pydict() == back.to_pydict()

    def test_filter_on_unprojected_column(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.parquet"
        write_parquet(t, p)
        back = read_parquet(p, columns=["i64"], filters=col("i8") > 0)
        assert list(back.names) == ["i64"]
        i8 = np.asarray(t["i8"].to_numpy())
        assert back.row_count == int((i8 > 0).sum())

    def test_null_predicates(self, tmp_path, rng):
        t = _typed_table(rng, n=70)
        p = tmp_path / "t.parquet"
        write_parquet(t, p)
        back = read_parquet(p, filters=col("s").is_null())
        assert back.row_count == t["s"].null_count()
        back2 = read_parquet(p, filters=col("s").is_not_null())
        assert back2.row_count == t.row_count - t["s"].null_count()

    def test_isin(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.parquet"
        write_parquet(t, p)
        back = read_parquet(p, filters=col("i8").isin([1, 2, 3]))
        i8 = np.asarray(t["i8"].to_numpy())
        assert back.row_count == int(np.isin(i8, [1, 2, 3]).sum())

    def test_multi_file(self, tmp_path, rng):
        t1 = _typed_table(rng, n=50)
        t2 = _typed_table(rng, n=60)
        p1, p2 = tmp_path / "a.parquet", tmp_path / "b.parquet"
        write_parquet(t1, p1)
        write_parquet(t2, p2)
        back = read_parquet([p1, p2])
        assert back.row_count == 110

    def test_scan_batches(self, tmp_path, rng):
        t = _typed_table(rng, n=250)
        p = tmp_path / "t.parquet"
        write_parquet(t, p, row_group_size=100)
        batches = list(scan_parquet(p))
        assert [b.row_count for b in batches] == [100, 100, 50]


class TestPruningLogic:
    def test_leaf_maybe_matches(self):
        st = {"x": ColumnStats(min=10, max=20, null_count=0, num_values=100)}
        assert (col("x") == 15).maybe_matches(st)
        assert not (col("x") == 25).maybe_matches(st)
        assert not (col("x") < 10).maybe_matches(st)
        assert (col("x") <= 10).maybe_matches(st)
        assert not (col("x") > 20).maybe_matches(st)
        assert (col("x") >= 20).maybe_matches(st)
        assert not col("x").is_null().maybe_matches(st)
        assert col("x").is_not_null().maybe_matches(st)
        assert not col("x").isin([1, 2]).maybe_matches(st)
        assert col("x").isin([1, 12]).maybe_matches(st)

    def test_all_null_group(self):
        st = {"x": ColumnStats(min=None, max=None, null_count=5, num_values=5)}
        assert col("x").is_null().maybe_matches(st)
        assert not col("x").is_not_null().maybe_matches(st)

    def test_missing_stats_never_prunes(self):
        assert (col("y") == 1).maybe_matches({})

    def test_ne_prunes_constant_group(self):
        st = {"x": ColumnStats(min=7, max=7, null_count=0, num_values=9)}
        assert not (col("x") != 7).maybe_matches(st)
        assert (col("x") != 8).maybe_matches(st)


class TestOrc:
    def test_round_trip(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.orc"
        write_orc(t, p)
        back = read_orc(p)
        assert back.to_pydict() == t.to_pydict()

    def test_filter_and_projection(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.orc"
        write_orc(t, p)
        back = read_orc(p, columns=["i64"], filters=col("i8") > 0)
        i8 = np.asarray(t["i8"].to_numpy())
        assert list(back.names) == ["i64"]
        assert back.row_count == int((i8 > 0).sum())

    def test_scan_stripes(self, tmp_path, rng):
        t = _typed_table(rng, n=120)
        p = tmp_path / "t.orc"
        write_orc(t, p)
        batches = list(scan_orc(p))
        assert sum(b.row_count for b in batches) == 120


class TestCsv:
    def test_round_trip(self, tmp_path, rng):
        n = 80
        t = Table.from_pydict(
            {
                "a": rng.integers(0, 1000, n),
                "b": rng.standard_normal(n),
                "s": [f"v{i}" for i in range(n)],
            }
        )
        p = tmp_path / "t.csv"
        write_csv(t, p)
        back = read_csv(p)
        assert np.array_equal(back["a"].to_numpy(), t["a"].to_numpy())
        assert np.allclose(back["b"].to_numpy(), t["b"].to_numpy())
        assert back["s"].to_pylist() == t["s"].to_pylist()

    def test_filters(self, tmp_path, rng):
        n = 100
        t = Table.from_pydict({"a": rng.integers(0, 10, n)})
        p = tmp_path / "t.csv"
        write_csv(t, p)
        back = read_csv(p, filters=col("a") == 3)
        a = np.asarray(t["a"].to_numpy())
        assert back.row_count == int((a == 3).sum())


class TestIpc:
    def test_round_trip(self, tmp_path, rng):
        t = _typed_table(rng)
        p = tmp_path / "t.arrow"
        write_arrow_ipc(t, p)
        back = read_arrow_ipc(p)
        assert back.to_pydict() == t.to_pydict()


def test_empty_not_in_keeps_non_null_rows(tmp_path, rng):
    t = _typed_table(rng, n=40)
    p = tmp_path / "t.parquet"
    write_parquet(t, p)
    back = read_parquet(p, filters=col("s").not_in([]))
    # SQL: x NOT IN () is true for non-null x, null for null x
    assert back.row_count == t.row_count - t["s"].null_count()
    back2 = read_parquet(p, filters=col("i8").isin([]))
    assert back2.row_count == 0


def test_pyarrow_equality_alias(tmp_path, rng):
    t = _typed_table(rng, n=30)
    p = tmp_path / "t.parquet"
    write_parquet(t, p)
    i8 = np.asarray(t["i8"].to_numpy())
    v = int(i8[0])
    want = int((i8 == v).sum())
    assert read_parquet(p, filters=[("i8", "=", v)]).row_count == want
    assert (
        read_parquet(p, filters=[("i8", "<>", v)]).row_count
        == t.row_count - want
    )


def test_csv_explicit_names_skip_header(tmp_path, rng):
    t = Table.from_pydict({"a": np.arange(5), "b": np.arange(5.0)})
    p = tmp_path / "t.csv"
    write_csv(t, p)
    back = read_csv(p, column_names=["x", "y"], header=True)
    assert back.row_count == 5
    assert np.array_equal(back["x"].to_numpy(), np.arange(5))


def test_csv_projection_with_predicate_column(tmp_path, rng):
    n = 60
    t = Table.from_pydict(
        {"a": rng.integers(0, 5, n), "b": rng.integers(0, 9, n)}
    )
    p = tmp_path / "t.csv"
    write_csv(t, p)
    back = read_csv(p, columns=["a"], filters=col("b") > 4)
    b = np.asarray(t["b"].to_numpy())
    assert list(back.names) == ["a"]
    assert back.row_count == int((b > 4).sum())


def test_from_dnf_shapes():
    p1 = from_dnf([("a", "==", 1), ("b", ">", 2)])
    assert p1.columns() == {"a", "b"}
    p2 = from_dnf([[("a", "==", 1)], [("b", ">", 2)]])
    assert p2.columns() == {"a", "b"}


class TestPrefetchOverlap:
    """Round-3 VERDICT item 10: decode row group k+1 on a host thread
    while k computes — the nvcomp/GDS async-feed role."""

    def _make_file(self, tmp_path, rng, n_groups=6, rows_per_group=1_500_000):
        pa = pytest.importorskip("pyarrow")
        pq_mod = pytest.importorskip("pyarrow.parquet")
        path = str(tmp_path / "overlap.parquet")
        n = n_groups * rows_per_group
        tbl = pa.table({
            "k": rng.integers(0, 1000, n),
            "v": rng.standard_normal(n),
            "w": rng.standard_normal(n),
            "x": rng.integers(0, 10**9, n),
        })
        pq_mod.write_table(tbl, path, row_group_size=rows_per_group)
        return path

    def test_prefetch_matches_serial(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io.parquet import scan_parquet

        path = self._make_file(tmp_path, rng, n_groups=3,
                               rows_per_group=10_000)
        serial = [
            np.asarray(t["k"].data) for t in scan_parquet(path)
        ]
        pre = [
            np.asarray(t["k"].data)
            for t in scan_parquet(path, prefetch=2)
        ]
        assert len(serial) == len(pre)
        for a, b in zip(serial, pre):
            np.testing.assert_array_equal(a, b)

    def test_prefetch_overlaps_compute(self, tmp_path, rng):
        """With sleep-dominated compute, total time with prefetch must
        approach sum(compute) + one decode instead of the serial
        sum(compute) + sum(decode)."""
        import time

        from spark_rapids_jni_tpu.io.parquet import scan_parquet

        path = self._make_file(tmp_path, rng)
        compute_s = 0.25

        def run(prefetch):
            t0 = time.perf_counter()
            n = 0
            for t in scan_parquet(path, prefetch=prefetch):
                time.sleep(compute_s)  # stands in for device compute
                n += 1
            return time.perf_counter() - t0, n

        serial_s, n_serial = run(0)
        prefetch_s, n_pre = run(2)
        assert n_serial == n_pre
        decode_total = serial_s - n_serial * compute_s
        if decode_total < 0.3:
            pytest.skip("decode too fast on this host to measure overlap")
        # generous bound: at least half the decode time must be hidden
        assert prefetch_s < serial_s - 0.5 * decode_total + 0.1, (
            serial_s, prefetch_s, decode_total
        )

    def test_prefetch_propagates_errors(self, tmp_path):
        from spark_rapids_jni_tpu.io.parquet import scan_parquet

        with pytest.raises(Exception):
            list(scan_parquet(str(tmp_path / "missing.parquet"),
                              prefetch=2))


class TestOrcPrefetch:
    def test_orc_prefetch_matches_serial(self, tmp_path, rng):
        pa = pytest.importorskip("pyarrow")
        orc = pytest.importorskip("pyarrow.orc")
        from spark_rapids_jni_tpu.io.orc import scan_orc

        path = str(tmp_path / "t.orc")
        n = 30_000
        tbl = pa.table({"k": rng.integers(0, 100, n)})
        orc.write_table(tbl, path, stripe_size=8 * 64 * 1024)
        serial = [np.asarray(t["k"].data) for t in scan_orc(path)]
        pre = [
            np.asarray(t["k"].data) for t in scan_orc(path, prefetch=2)
        ]
        assert len(serial) == len(pre) >= 1
        for a, b in zip(serial, pre):
            np.testing.assert_array_equal(a, b)


class TestCsvScan:
    def test_scan_batches_match_read(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_csv, scan_csv

        path = str(tmp_path / "t.csv")
        n = 50_000
        t = Table.from_pydict({
            "k": rng.integers(0, 100, n),
            "v": rng.integers(-1000, 1000, n),
        })
        write_csv(t, path)
        whole = read_csv(path)
        batches = list(scan_csv(path, block_size=1 << 16))
        assert len(batches) > 1  # actually streamed
        got_k = np.concatenate([np.asarray(b["k"].data) for b in batches])
        np.testing.assert_array_equal(got_k, np.asarray(whole["k"].data))
        pre = list(scan_csv(path, block_size=1 << 16, prefetch=2))
        got_pre = np.concatenate([np.asarray(b["k"].data) for b in pre])
        np.testing.assert_array_equal(got_pre, got_k)

    def test_scan_with_filter(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import scan_csv

        path = str(tmp_path / "f.csv")
        n = 20_000
        t = Table.from_pydict({"k": rng.integers(0, 100, n)})
        write_csv(t, path)
        rows = sum(
            b.row_count
            for b in scan_csv(path, filters=col("k") < 10,
                              block_size=1 << 16)
        )
        kk = np.asarray(t["k"].data)
        assert rows == int((kk < 10).sum())

    def test_scan_projection_and_pinned_dtypes(self, tmp_path):
        import pyarrow as pa

        from spark_rapids_jni_tpu.io import scan_csv

        path = str(tmp_path / "drift.csv")
        # column v looks integral for the whole first block and turns
        # float near the end: type inference from block 1 alone would
        # abort mid-stream without the dtypes pin
        n = 40_000
        with open(path, "w") as f:
            f.write("k,v,unused\n")
            for i in range(n):
                v = "2.5" if i == n - 1 else str(i % 7)
                f.write(f"{i % 100},{v},junk{i}\n")
        batches = list(
            scan_csv(path, columns=["v"], block_size=1 << 16,
                     dtypes={"v": pa.float64()})
        )
        assert len(batches) > 1
        for b in batches:
            assert list(b.names) == ["v"]
        total = sum(float(b["v"].to_numpy().sum()) for b in batches)
        want = sum(
            2.5 if i == n - 1 else float(i % 7) for i in range(n)
        )
        assert abs(total - want) < 1e-6


class TestJson:
    def test_round_trip_with_nulls(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_json, write_json

        path = str(tmp_path / "t.jsonl")
        t = Table.from_pydict({
            "k": [1, 2, None, 4],
            "s": ["a", None, "cc", "d"],
            "f": [1.5, 2.0, 3.25, None],
        })
        write_json(t, path)
        back = read_json(path)
        assert back["k"].to_pylist() == [1, 2, None, 4]
        assert back["s"].to_pylist() == ["a", None, "cc", "d"]
        assert back["f"].to_pylist() == [1.5, 2.0, 3.25, None]

    def test_projection_and_filter(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_json, write_json

        path = str(tmp_path / "f.jsonl")
        n = 5_000
        k = rng.integers(0, 100, n)
        v = rng.integers(-10, 10, n)
        write_json(Table.from_pydict({"k": k, "v": v}), path)
        out = read_json(path, columns=["v"], filters=col("k") < 10)
        assert list(out.names) == ["v"]
        np.testing.assert_array_equal(
            np.sort(out["v"].to_numpy()), np.sort(v[k < 10])
        )

    def test_scan_batches_and_prefetch(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_json, scan_json, write_json

        path = str(tmp_path / "s.jsonl")
        n = 30_000
        k = rng.integers(0, 100, n)
        write_json(Table.from_pydict({"k": k}), path)
        batches = list(scan_json(path, block_rows=1 << 13))
        assert len(batches) > 1
        got = np.concatenate([b["k"].to_numpy() for b in batches])
        np.testing.assert_array_equal(got, k)
        pre = list(scan_json(path, block_rows=1 << 13, prefetch=2))
        got_pre = np.concatenate([b["k"].to_numpy() for b in pre])
        np.testing.assert_array_equal(got_pre, k)

    def test_scan_pinned_dtypes_across_chunks(self, tmp_path):
        import pyarrow as pa

        from spark_rapids_jni_tpu.io import scan_json

        path = str(tmp_path / "drift.jsonl")
        n = 20_000
        with open(path, "w") as f:
            for i in range(n):
                v = 2.5 if i == n - 1 else i % 3
                f.write('{"v": %s}\n' % v)
        batches = list(
            scan_json(path, block_rows=1 << 12,
                      dtypes={"v": pa.float64()})
        )
        assert len(batches) > 1
        total = sum(float(b["v"].to_numpy().sum()) for b in batches)
        want = sum(2.5 if i == n - 1 else i % 3 for i in range(n))
        assert abs(total - want) < 1e-6


class TestAvro:
    def test_round_trip_null_codec(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_avro, write_avro

        path = str(tmp_path / "t.avro")
        t = Table.from_pydict({
            "i": [1, None, 3, -(2**40)],
            "f": [1.5, 2.25, None, -0.5],
            "b": [True, False, True, None],
            "s": ["x", None, "yz", ""],
        })
        write_avro(t, path)
        back = read_avro(path)
        assert back["i"].to_pylist() == [1, None, 3, -(2**40)]
        assert back["f"].to_pylist() == [1.5, 2.25, None, -0.5]
        assert back["b"].to_pylist() == [True, False, True, None]
        assert back["s"].to_pylist() == ["x", None, "yz", ""]

    def test_round_trip_deflate(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_avro, write_avro

        path = str(tmp_path / "d.avro")
        n = 5_000
        k = rng.integers(-(2**30), 2**30, n)
        t = Table.from_pydict({"k": k})
        write_avro(t, path, codec="deflate")
        back = read_avro(path)
        np.testing.assert_array_equal(back["k"].to_numpy(), k)

    def test_projection_and_filter(self, tmp_path, rng):
        from spark_rapids_jni_tpu.io import read_avro, write_avro

        path = str(tmp_path / "p.avro")
        n = 2_000
        k = rng.integers(0, 100, n)
        v = rng.integers(-5, 5, n)
        write_avro(Table.from_pydict({"k": k, "v": v}), path)
        out = read_avro(path, columns=["v"], filters=col("k") < 10)
        assert list(out.names) == ["v"]
        np.testing.assert_array_equal(
            np.sort(out["v"].to_numpy()), np.sort(v[k < 10])
        )

    def test_unsupported_schema_raises(self, tmp_path):
        from spark_rapids_jni_tpu.io.avro import (
            _MAGIC, _write_long, read_avro,
        )
        import json as _json

        path = str(tmp_path / "bad.avro")
        schema = {"type": "record", "name": "r",
                  "fields": [{"name": "m",
                              "type": {"type": "map", "values": "long"}}]}
        out = bytearray(_MAGIC)
        meta = {b"avro.schema": _json.dumps(schema).encode()}
        _write_long(out, len(meta))
        for kk, vv in meta.items():
            _write_long(out, len(kk)); out += kk
            _write_long(out, len(vv)); out += vv
        _write_long(out, 0)
        out += b"\x00" * 16
        with open(path, "wb") as f:
            f.write(bytes(out))
        with pytest.raises(TypeError):
            read_avro(path)

    def test_not_avro_raises(self, tmp_path):
        from spark_rapids_jni_tpu.io import read_avro

        path = str(tmp_path / "x.avro")
        with open(path, "wb") as f:
            f.write(b"PAR1 not avro")
        with pytest.raises(ValueError):
            read_avro(path)


class TestReviewRegressions:
    def test_avro_reversed_union_order(self, tmp_path):
        """[\"long\", \"null\"] unions are spec-legal: the null branch
        index follows declaration order, not always 0."""
        import json as _json

        from spark_rapids_jni_tpu.io import read_avro
        from spark_rapids_jni_tpu.io.avro import _MAGIC, _write_long

        schema = {"type": "record", "name": "r",
                  "fields": [{"name": "k", "type": ["long", "null"]}]}
        body = bytearray()
        # rows: 7, null, -3  (branch 0 = long value, branch 1 = null)
        _write_long(body, 0); _write_long(body, 7)
        _write_long(body, 1)
        _write_long(body, 0); _write_long(body, -3)
        sync = b"\x01" * 16
        out = bytearray(_MAGIC)
        meta = {b"avro.schema": _json.dumps(schema).encode()}
        _write_long(out, len(meta))
        for kk, vv in meta.items():
            _write_long(out, len(kk)); out += kk
            _write_long(out, len(vv)); out += vv
        _write_long(out, 0)
        out += sync
        _write_long(out, 3)
        _write_long(out, len(body))
        out += bytes(body)
        out += sync
        path = str(tmp_path / "rev.avro")
        with open(path, "wb") as f:
            f.write(bytes(out))
        back = read_avro(path)
        assert back["k"].to_pylist() == [7, None, -3]

    def test_json_nan_round_trip(self, tmp_path):
        from spark_rapids_jni_tpu.io import read_json, write_json

        path = str(tmp_path / "nan.jsonl")
        t = Table.from_pydict({"f": [1.0, float("nan"), float("inf")]})
        write_json(t, path)  # must not emit invalid JSON
        back = read_json(path)
        assert back["f"].to_pylist() == [1.0, None, None]

    def test_scan_json_sparse_keys(self, tmp_path):
        from spark_rapids_jni_tpu.io import scan_json

        path = str(tmp_path / "sparse.jsonl")
        n = 9_000
        with open(path, "w") as f:
            for i in range(n):
                if i < 5_000:
                    f.write('{"k": %d}\n' % i)
                else:
                    f.write('{"k": %d, "x": %d}\n' % (i, i * 2))
        # "x" is absent from the whole first chunk: with a dtypes pin the
        # scan null-fills it chunk-locally like read_json does file-wide
        batches = list(
            scan_json(path, columns=["x"], block_rows=1 << 12,
                      dtypes={"x": pa.int64()})
        )
        vals = [v for b in batches for v in b["x"].to_pylist()]
        want = [None if i < 5_000 else i * 2 for i in range(n)]
        assert vals == want
        # without a pin and never seen: clear error
        path2 = str(tmp_path / "never.jsonl")
        with open(path2, "w") as f:
            for i in range(100):
                f.write('{"k": %d}\n' % i)
        with pytest.raises(ValueError):
            list(scan_json(path2, columns=["zzz"], block_rows=50))

    def test_from_pydict_pad_widths(self):
        t = Table.from_pydict(
            {"s": ["ab", "c"]}, pad_widths={"s": 32}
        )
        assert t["s"].data.shape[1] == 32


class TestReviewRegressions2:
    def test_scan_json_blank_block_not_eof(self, tmp_path):
        from spark_rapids_jni_tpu.io import scan_json

        path = str(tmp_path / "blanks.jsonl")
        with open(path, "w") as f:
            for i in range(10):
                f.write('{"k": %d}\n' % i)
            f.write("\n" * 120)
            for i in range(10, 20):
                f.write('{"k": %d}\n' % i)
        got = [
            v
            for b in scan_json(path, block_rows=50)
            for v in b["k"].to_pylist()
        ]
        assert got == list(range(20))

    def test_avro_schema_types_pin_dtypes(self, tmp_path):
        from spark_rapids_jni_tpu import dtype as dt
        from spark_rapids_jni_tpu.io import read_avro, write_avro

        # empty table: dtype must come from the schema, not inference
        path = str(tmp_path / "empty.avro")
        t = Table.from_pydict({"k": np.array([], dtype=np.int64)})
        write_avro(t, path)
        back = read_avro(path)
        assert back["k"].dtype == dt.INT64
        assert back.row_count == 0
        # float32 survives the round trip (schema says "float")
        path2 = str(tmp_path / "f32.avro")
        t2 = Table.from_pydict(
            {"f": np.array([1.5, 2.5], dtype=np.float32)}
        )
        write_avro(t2, path2)
        back2 = read_avro(path2)
        assert back2["f"].dtype == dt.FLOAT32
        assert back2["f"].to_pylist() == [1.5, 2.5]

    def test_sample_empty_replacement_raises(self):
        from spark_rapids_jni_tpu.ops import sample

        t = Table.from_pydict({"v": np.array([], dtype=np.int64)})
        with pytest.raises(ValueError):
            sample(t, 3, replacement=True)
