"""LIST column MVP (round-3 VERDICT item 9): padded-matrix device layout
(offsets implicit in lengths), Arrow list round trip, and the true
LIST<UINT8> packed-rows export over the wire — the reference's own
nested output type (row_conversion.cu:389-406)."""

import json

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import interop, rows
from spark_rapids_jni_tpu.column import Column, Table


class TestListColumn:
    def test_from_to_pylist(self):
        vals = [[1, 2, 3], [], None, [7], [5, 5, 5, 5]]
        col = Column.from_list_of_lists(vals, dt.INT32)
        assert col.dtype.id == dt.TypeId.LIST
        assert col.list_child_dtype == dt.INT32
        assert col.to_pylist() == vals

    def test_arrow_round_trip(self):
        pa = pytest.importorskip("pyarrow")
        vals = [[1, -2, 3], [], None, [120, -7]]
        arr = pa.array(vals, type=pa.list_(pa.int8()))
        col = interop.column_from_arrow(arr)
        assert col.to_pylist() == vals
        back = interop.column_to_arrow(col)
        assert back.to_pylist() == vals
        assert back.type == pa.list_(pa.int8())

    def test_arrow_round_trip_int64_child(self):
        pa = pytest.importorskip("pyarrow")
        vals = [[10**12], [1, 2], None]
        arr = pa.array(vals, type=pa.list_(pa.int64()))
        col = interop.column_from_arrow(arr)
        assert col.to_pylist() == vals
        assert interop.column_to_arrow(col).to_pylist() == vals


class TestPackedRowsAsList:
    def test_to_rows_list_round_trip(self, rng):
        n = 64
        t = Table.from_pydict({
            "a": rng.integers(-100, 100, n, dtype=np.int64),
            "b": rng.standard_normal(n),
        })
        lst = rows.to_rows_list(t)
        assert lst.dtype.id == dt.TypeId.LIST
        assert lst.list_child_dtype == dt.UINT8
        layout = rows.compute_fixed_width_layout(t.dtypes())
        assert np.asarray(lst.lengths).tolist() == [layout.row_size] * n
        back = rows.from_rows_list(lst, t.dtypes())
        np.testing.assert_array_equal(
            np.asarray(back.columns[0].data), np.asarray(t["a"].data)
        )

    def test_wire_round_trip(self, rng):
        """to_rows over the wire yields a LIST column whose offsets are
        the row_size sequence; from_rows accepts it back."""
        from spark_rapids_jni_tpu import runtime_bridge as rb

        n = 48
        a = rng.integers(0, 1000, n).astype(np.int64)
        ids = [int(dt.TypeId.INT64)]
        out_t, out_s, out_d, out_v, out_n = rb.table_op_wire(
            json.dumps({"op": "to_rows"}), ids, [0],
            [a.tobytes()], [None], n,
        )
        assert out_t[0] == int(dt.TypeId.LIST)
        assert out_s[0] == int(dt.TypeId.UINT8)
        assert out_n == n
        offs = np.frombuffer(out_d[0], np.int32, n + 1)
        row_size = offs[1]
        np.testing.assert_array_equal(
            offs, np.arange(n + 1, dtype=np.int32) * row_size
        )
        back_t, _, back_d, _, back_n = rb.table_op_wire(
            json.dumps({
                "op": "from_rows", "type_ids": ids, "scales": [0],
                "num_rows": n,
            }),
            [out_t[0]], [out_s[0]], [out_d[0]], [None], n,
        )
        assert back_n == n
        np.testing.assert_array_equal(
            np.frombuffer(back_d[0], np.int64, n), a
        )
