/* Minimal jni.h stand-in for COMPILE-CHECKING src/jni/ in environments
 * without a JDK (this image has none). Declares exactly the subset of the
 * JNI surface the bridge uses, with real JNI's shapes. NOT shipped, NOT a
 * JNI implementation — tests/test_native.py points g++ -fsyntax-only at
 * this directory so signature typos in the bridge fail CI even when the
 * real JNI build is skipped (CMake gates on find_package(JNI)). */
#ifndef SRT_TEST_JNI_STUB_H
#define SRT_TEST_JNI_STUB_H

#include <cstdint>

#define JNIEXPORT __attribute__((visibility("default")))
#define JNICALL

typedef int32_t jint;
typedef int64_t jlong;
typedef int8_t jbyte;
typedef uint8_t jboolean;
typedef uint16_t jchar;
typedef int16_t jshort;
typedef float jfloat;
typedef double jdouble;
typedef jint jsize;

struct _jobject {};
typedef _jobject* jobject;
typedef jobject jclass;
typedef jobject jstring;
typedef jobject jarray;
typedef jarray jbyteArray;
typedef jarray jintArray;
typedef jarray jlongArray;
typedef jobject jthrowable;

struct JNIEnv {
  jclass FindClass(const char*);
  jint ThrowNew(jclass, const char*);
  jsize GetArrayLength(jarray);
  void GetIntArrayRegion(jintArray, jsize, jsize, jint*);
  void GetByteArrayRegion(jbyteArray, jsize, jsize, jbyte*);
  jbyteArray NewByteArray(jsize);
  void SetByteArrayRegion(jbyteArray, jsize, jsize, const jbyte*);
  jlongArray NewLongArray(jsize);
  void SetLongArrayRegion(jlongArray, jsize, jsize, const jlong*);
  const char* GetStringUTFChars(jstring, jboolean*);
  void ReleaseStringUTFChars(jstring, const char*);
};

#endif /* SRT_TEST_JNI_STUB_H */
