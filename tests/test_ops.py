"""Phase-2 tests: the columnar op library vs independent oracles."""

import numpy as np
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import strings as str_ops
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
from spark_rapids_jni_tpu.ops.sort import SortKey


# --------------------------------------------------------------------------
# Independent Spark Murmur3_x86_32 oracle (pure python, 32-bit masked)
# --------------------------------------------------------------------------
M = 0xFFFFFFFF


def _rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & M


def _mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & M
    k1 = _rotl(k1, 15)
    return (k1 * 0x1B873593) & M


def _mix_h1(h1, k1):
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & M


def _fmix(h1, n):
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M
    h1 ^= h1 >> 16
    return h1


def spark_hash_int(v, seed=42):
    return _to_i32(_fmix(_mix_h1(seed & M, _mix_k1(v & M)), 4))


def spark_hash_long(v, seed=42):
    low = v & M
    high = (v >> 32) & M
    h1 = _mix_h1(seed & M, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _to_i32(_fmix(h1, 8))


def spark_hash_bytes(data: bytes, seed=42):
    h1 = seed & M
    nblocks = len(data) // 4
    for i in range(nblocks):
        word = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        h1 = _mix_h1(h1, _mix_k1(word))
    for i in range(nblocks * 4, len(data)):
        b = data[i]
        if b >= 128:
            b -= 256  # sign-extended byte
        h1 = _mix_h1(h1, _mix_k1(b & M))
    return _to_i32(_fmix(h1, len(data)))


def _to_i32(v):
    v &= M
    return v - (1 << 32) if v >= (1 << 31) else v


class TestMurmur3:
    def test_int_longs(self, rng):
        ints = rng.integers(-(2**31), 2**31, 50, dtype=np.int32)
        got = ops.murmur3_column(Column.from_numpy(ints)).to_pylist()
        want = [spark_hash_int(int(v)) for v in ints]
        assert got == want

        longs = rng.integers(-(2**62), 2**62, 50, dtype=np.int64)
        got = ops.murmur3_column(Column.from_numpy(longs)).to_pylist()
        want = [spark_hash_long(int(v)) for v in longs]
        assert got == want

    def test_doubles_floats(self, rng):
        d = rng.standard_normal(20)
        d[0] = -0.0  # Spark normalizes to +0.0
        got = ops.murmur3_column(Column.from_numpy(d)).to_pylist()
        want = [
            spark_hash_long(
                int(np.float64(0.0 if v == 0 else v).view(np.int64))
            )
            for v in d
        ]
        assert got == want

        f = rng.standard_normal(20).astype(np.float32)
        got = ops.murmur3_column(Column.from_numpy(f)).to_pylist()
        want = [spark_hash_int(int(np.float32(v).view(np.int32))) for v in f]
        assert got == want

    def test_strings(self):
        vals = ["", "a", "ab", "abc", "abcd", "abcde", "sparkly-tpu", "\xe9\xfc"]
        col = Column.from_strings(vals)
        got = ops.murmur3_column(col).to_pylist()
        want = [
            spark_hash_bytes(v.encode("utf-8", "surrogateescape")) for v in vals
        ]
        assert got == want

    def test_null_passthrough_and_chain(self):
        t = Table.from_pydict({"a": [1, None, 3], "b": [10, 20, 30]})
        got = ops.murmur3_table(t).to_pylist()
        want = []
        for a, b in [(1, 10), (None, 20), (3, 30)]:
            h = 42
            if a is not None:
                h = spark_hash_long(a, h) & M
            h = spark_hash_long(b, h)
            want.append(h)
        assert got == want


class TestBinaryOps:
    def test_arith_nulls(self):
        a = Table.from_pydict({"x": [1, None, 3, 4]})["x"]
        b = Table.from_pydict({"x": [10, 20, None, 40]})["x"]
        assert ops.add(a, b).to_pylist() == [11, None, None, 44]
        assert ops.mul(a, b).to_pylist() == [10, None, None, 160]

    def test_int_div_by_zero_is_null(self):
        a = Column.from_numpy(np.array([10, 7, 5], dtype=np.int64))
        b = Column.from_numpy(np.array([2, 0, 0], dtype=np.int64))
        assert ops.div(a, b).to_pylist() == [5, None, None]

    def test_float_div_by_zero_is_inf(self):
        a = Column.from_numpy(np.array([1.0, -1.0]))
        b = Column.from_numpy(np.array([0.0, 0.0]))
        assert ops.div(a, b).to_pylist() == [np.inf, -np.inf]

    def test_float64_storage_roundtrip_through_op(self):
        a = Column.from_numpy(np.array([1.1, 2.2]))
        out = ops.add(a, a)
        np.testing.assert_allclose(out.to_numpy(), [2.2, 4.4])
        assert out.dtype == dt.FLOAT64
        assert out.data.dtype == np.uint64  # bit-pattern storage preserved

    def test_comparisons(self):
        a = Table.from_pydict({"x": [1, None, 3]})["x"]
        b = Table.from_pydict({"x": [2, 2, 2]})["x"]
        assert ops.lt(a, b).to_pylist() == [True, None, False]
        assert ops.binary_op("null_safe_eq", a, a).to_pylist() == [
            True,
            True,
            True,
        ]
        n1 = Table.from_pydict({"x": [None, 1]})["x"]
        n2 = Table.from_pydict({"x": [None, None]})["x"]
        assert ops.binary_op("null_safe_eq", n1, n2).to_pylist() == [
            True,
            False,
        ]

    def test_three_valued_logic(self):
        tv = Table.from_pydict({"x": [True, False, None] * 3})["x"]
        other = Table.from_pydict(
            {"x": [True, True, True, False, False, False, None, None, None]}
        )["x"]
        # Spark: F AND NULL = F, T OR NULL = T
        assert ops.binary_op("and", tv, other).to_pylist() == [
            True, False, None, False, False, False, None, False, None,
        ]
        assert ops.binary_op("or", tv, other).to_pylist() == [
            True, True, True, True, False, None, True, None, None,
        ]

    def test_decimal_add_rescale(self):
        a = Column.from_numpy(
            np.array([1234, 500], dtype=np.int32), dtype=dt.decimal32(-3)
        )  # 1.234, 0.500
        b = Column.from_numpy(
            np.array([11, 22], dtype=np.int32), dtype=dt.decimal32(-1)
        )  # 1.1, 2.2
        out = ops.add(a, b)
        assert out.dtype.scale == -3
        assert out.to_pylist() == [2334, 2700]  # 2.334, 2.700

    def test_decimal_mul(self):
        a = Column.from_numpy(
            np.array([150], dtype=np.int32), dtype=dt.decimal32(-2)
        )  # 1.50
        out = ops.mul(a, a)  # 2.25 at scale -2 -> 225... at combined scale -4 rescaled to -2
        assert out.dtype.scale == -2
        assert out.to_pylist() == [225]


class TestUnaryCast:
    def test_unary(self):
        a = Column.from_numpy(np.array([-1.5, 4.0, None or 9.0]))
        assert ops.unary_op("abs", a).to_pylist() == [1.5, 4.0, 9.0]
        assert ops.unary_op("sqrt", a).to_pylist()[1] == 2.0
        b = Table.from_pydict({"x": [1, None]})["x"]
        assert ops.is_null(b).to_pylist() == [False, True]
        assert ops.is_not_null(b).to_pylist() == [True, False]

    def test_cast(self):
        a = Column.from_numpy(np.array([1.9, -2.9]))
        assert ops.cast(a, dt.INT32).to_pylist() == [1, -2]
        b = Column.from_numpy(np.array([0, 3], dtype=np.int64))
        assert ops.cast(b, dt.BOOL8).to_pylist() == [False, True]
        d = Column.from_numpy(
            np.array([1234], dtype=np.int32), dtype=dt.decimal32(-3)
        )
        assert ops.cast(d, dt.FLOAT64).to_pylist() == [pytest.approx(1.234)]
        assert ops.cast(d, dt.decimal64(-1)).to_pylist() == [12]  # 1.2


class TestReductions:
    def test_basic(self, rng):
        vals = rng.integers(-100, 100, 1000, dtype=np.int64)
        valid = rng.random(1000) > 0.2
        col = Column.from_numpy(vals, valid)
        assert ops.reduce_column(col, "sum").to_pylist() == [
            int(vals[valid].sum())
        ]
        assert ops.reduce_column(col, "min").to_pylist() == [
            int(vals[valid].min())
        ]
        assert ops.reduce_column(col, "max").to_pylist() == [
            int(vals[valid].max())
        ]
        assert ops.reduce_column(col, "count").to_pylist() == [
            int(valid.sum())
        ]
        assert ops.reduce_column(col, "mean").to_pylist() == [
            pytest.approx(vals[valid].mean())
        ]

    def test_all_null_sum_is_null(self):
        col = Table.from_pydict({"x": [None, None]}, dtypes={"x": dt.INT64})
        # object-list with all None: force int64 dtype
        c = Column.from_numpy(
            np.array([0, 0], dtype=np.int64), np.array([False, False])
        )
        assert ops.reduce_column(c, "sum").to_pylist() == [None]
        assert ops.reduce_column(c, "count").to_pylist() == [0]


class TestFilterGatherSort:
    def test_filter(self, rng):
        n = 500
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 10, n, dtype=np.int64),
                "v": rng.standard_normal(n),
            }
        )
        mask = ops.gt(t["k"], Column.from_numpy(np.full(n, 5, dtype=np.int64)))
        out = ops.filter_table(t, mask)
        kk = np.asarray(t["k"].data)
        assert out.row_count == int((kk > 5).sum())
        np.testing.assert_array_equal(
            np.asarray(out["k"].data), kk[kk > 5]
        )

    def test_filter_capped(self, rng):
        import jax

        t = Table.from_pydict({"k": np.arange(100, dtype=np.int64)})
        mask = Column(t["k"].data % 2 == 0, dt.BOOL8, None)
        f = jax.jit(
            lambda tbl, m: ops.filter_table_capped(tbl, m, capacity=64)
        )
        out, count = f(t, mask)
        assert int(count) == 50
        np.testing.assert_array_equal(
            np.asarray(out["k"].data)[:50], np.arange(0, 100, 2)
        )

    def test_sort_multi_key_nulls(self):
        t = Table.from_pydict(
            {
                "a": [2, 1, None, 1, 2],
                "b": [1.0, 9.0, 5.0, 7.0, None],
            }
        )
        out = ops.sort_table(
            t, [SortKey("a"), SortKey("b", ascending=False)]
        )
        # default: asc nulls first for a; desc nulls last for b
        assert out["a"].to_pylist() == [None, 1, 1, 2, 2]
        assert out["b"].to_pylist() == [5.0, 9.0, 7.0, 1.0, None]

    def test_sort_float_total_order(self):
        vals = np.array([1.5, -2.0, np.nan, np.inf, -np.inf, 0.0, -0.0])
        t = Table([Column.from_numpy(vals)])
        out = ops.sort_table(t, [SortKey(0)])
        got = np.asarray(out[0].to_numpy())
        # NaN last (Spark order); -0.0 before 0.0
        assert np.isnan(got[-1])
        np.testing.assert_array_equal(
            got[:-1], np.array([-np.inf, -2.0, -0.0, 0.0, 1.5, np.inf])
        )
        assert np.signbit(got[2])

    def test_sort_strings(self):
        t = Table([Column.from_strings(["pear", "apple", "fig", None, "app"])])
        out = ops.sort_table(t, [SortKey(0, nulls_first=False)])
        assert out[0].to_pylist() == ["app", "apple", "fig", "pear", None]


class TestGroupby:
    def test_sum_count_vs_pandas(self, rng):
        pd = pytest.importorskip("pandas")
        n = 2000
        k = rng.integers(0, 50, n, dtype=np.int64)
        v = rng.standard_normal(n)
        vvalid = rng.random(n) > 0.1
        t = Table(
            [Column.from_numpy(k), Column.from_numpy(v, vvalid)], ["k", "v"]
        )
        out = ops.groupby_aggregate(
            t,
            ["k"],
            [
                GroupbyAgg("v", "sum"),
                GroupbyAgg("v", "count"),
                GroupbyAgg("v", "min"),
                GroupbyAgg("v", "max"),
                GroupbyAgg("v", "mean"),
            ],
        )
        df = pd.DataFrame({"k": k, "v": np.where(vvalid, v, np.nan)})
        want = df.groupby("k")["v"].agg(["sum", "count", "min", "max", "mean"])
        got_k = out["k"].to_pylist()
        assert got_k == sorted(set(k.tolist()))
        np.testing.assert_allclose(
            np.asarray(out["sum_v"].to_numpy()), want["sum"].values, rtol=1e-12
        )
        np.testing.assert_array_equal(
            np.asarray(out["count_v"].data), want["count"].values
        )
        np.testing.assert_allclose(
            np.asarray(out["mean_v"].to_numpy()), want["mean"].values, rtol=1e-12
        )

    def test_null_key_group(self):
        t = Table.from_pydict({"k": [1, None, 1, None], "v": [1, 2, 3, 4]})
        out = ops.groupby_aggregate(t, ["k"], [GroupbyAgg("v", "sum")])
        d = dict(zip(out["k"].to_pylist(), out["sum_v"].to_pylist()))
        assert d == {None: 6, 1: 4}

    def test_multi_key(self):
        t = Table.from_pydict(
            {
                "a": [1, 1, 2, 2, 1],
                "b": ["x", "y", "x", "x", "x"],
                "v": [10, 20, 30, 40, 50],
            }
        )
        out = ops.groupby_aggregate(t, ["a", "b"], [GroupbyAgg("v", "sum")])
        got = {
            (a, b): s
            for a, b, s in zip(
                out["a"].to_pylist(),
                out["b"].to_pylist(),
                out["sum_v"].to_pylist(),
            )
        }
        assert got == {(1, "x"): 60, (1, "y"): 20, (2, "x"): 70}


class TestJoin:
    def test_inner_vs_pandas(self, rng):
        pd = pytest.importorskip("pandas")
        nl, nr = 300, 200
        lk = rng.integers(0, 40, nl, dtype=np.int64)
        rk = rng.integers(0, 40, nr, dtype=np.int64)
        lv = rng.standard_normal(nl)
        rv = rng.standard_normal(nr)
        left = Table(
            [Column.from_numpy(lk), Column.from_numpy(lv)], ["k", "lv"]
        )
        right = Table(
            [Column.from_numpy(rk), Column.from_numpy(rv)], ["k", "rv"]
        )
        out = ops.inner_join(left, right, ["k"])
        want = pd.merge(
            pd.DataFrame({"k": lk, "lv": lv}),
            pd.DataFrame({"k": rk, "rv": rv}),
            on="k",
        )
        assert out.row_count == len(want)
        got = sorted(
            zip(
                out["k"].to_pylist(),
                out["lv"].to_pylist(),
                out["rv"].to_pylist(),
            )
        )
        expect = sorted(
            zip(want["k"].tolist(), want["lv"].tolist(), want["rv"].tolist())
        )
        for g, e in zip(got, expect):
            assert g[0] == e[0]
            assert g[1] == pytest.approx(e[1])
            assert g[2] == pytest.approx(e[2])

    def test_nulls_never_match(self):
        left = Table.from_pydict({"k": [1, None, 3]})
        right = Table.from_pydict({"k": [1, None, 1]})
        out = ops.inner_join(left, right, ["k"])
        assert out["k"].to_pylist() == [1, 1]

    def test_left_join(self):
        left = Table.from_pydict({"k": [1, 2, None], "lv": [10, 20, 30]})
        right = Table.from_pydict({"k": [1, 1], "rv": [100, 200]})
        out = ops.left_join(left, right, ["k"])
        rows = sorted(
            zip(
                out["k"].to_pylist(),
                out["lv"].to_pylist(),
                out["rv"].to_pylist(),
            ),
            key=lambda r: (r[0] is None, r),
        )
        assert rows == [
            (1, 10, 100),
            (1, 10, 200),
            (2, 20, None),
            (None, 30, None),
        ]

    def test_semi_anti(self):
        left = Table.from_pydict({"k": [1, 2, 3, None]})
        right = Table.from_pydict({"k": [2, 3]})
        assert ops.semi_join(left, right, ["k"])["k"].to_pylist() == [2, 3]
        assert ops.anti_join(left, right, ["k"])["k"].to_pylist() == [1, None]

    def test_string_key_join(self):
        left = Table.from_pydict({"k": ["apple", "fig", "pear"], "v": [1, 2, 3]})
        right = Table.from_pydict({"k": ["fig", "apple"], "w": [10, 20]})
        out = ops.inner_join(left, right, ["k"])
        got = sorted(zip(out["k"].to_pylist(), out["v"].to_pylist(), out["w"].to_pylist()))
        assert got == [("apple", 1, 20), ("fig", 2, 10)]

    def test_capped_jit(self, rng):
        import jax

        left = Table.from_pydict({"k": [1, 2, 2, 5], "v": [1, 2, 3, 4]})
        right = Table.from_pydict({"k": [2, 2, 5], "w": [7, 8, 9]})
        from spark_rapids_jni_tpu.ops.join import inner_join_capped

        f = jax.jit(
            lambda l, r: inner_join_capped(l, r, ["k"], capacity=16)
        )
        out, count = f(left, right)
        assert int(count) == 5
        rows = sorted(
            (k, v, w)
            for k, v, w, ok in zip(
                out["k"].to_pylist(),
                out["v"].to_pylist(),
                out["w"].to_pylist(),
                range(16),
            )
            if k is not None
        )
        assert rows == [(2, 2, 7), (2, 2, 8), (2, 3, 7), (2, 3, 8), (5, 4, 9)]


class TestPartition:
    def test_hash_partition_counts(self, rng):
        n = 1000
        t = Table.from_pydict(
            {"k": rng.integers(0, 1000, n, dtype=np.int64)}
        )
        out, counts = ops.hash_partition(t, ["k"], 8)
        assert int(np.asarray(counts).sum()) == n
        # partition ids must match Spark's pmod(murmur3) exactly
        part = np.array(
            [spark_hash_long(int(v)) % 8 for v in np.asarray(t["k"].data)]
        )
        part = (part + 8) % 8
        want = np.bincount(part, minlength=8)
        np.testing.assert_array_equal(np.asarray(counts), want)

    def test_round_robin(self):
        t = Table.from_pydict({"k": np.arange(10, dtype=np.int64)})
        out, counts = ops.round_robin_partition(t, 3)
        np.testing.assert_array_equal(np.asarray(counts), [4, 3, 3])


class TestStrings:
    def test_basics(self):
        c = Column.from_strings(["Hello", "WORLD", None, "tpu123"])
        assert str_ops.length(c).to_pylist() == [5, 5, None, 6]
        assert str_ops.upper(c).to_pylist() == ["HELLO", "WORLD", None, "TPU123"]
        assert str_ops.lower(c).to_pylist() == ["hello", "world", None, "tpu123"]

    def test_contains_startswith_endswith(self):
        c = Column.from_strings(["spark", "rapids", "sparkly", "park", None])
        assert str_ops.contains(c, "ark").to_pylist() == [
            True, False, True, True, None,
        ]
        assert str_ops.starts_with(c, "spark").to_pylist() == [
            True, False, True, False, None,
        ]
        assert str_ops.ends_with(c, "rk").to_pylist() == [
            True, False, False, True, None,
        ]

    def test_substring_concat(self):
        a = Column.from_strings(["hello", "ab"])
        assert str_ops.substring(a, 1, 3).to_pylist() == ["ell", "b"]
        b = Column.from_strings(["-x", "-yz"])
        assert str_ops.concat(a, b).to_pylist() == ["hello-x", "ab-yz"]

    def test_compare(self):
        a = Column.from_strings(["apple", "fig", "zz"])
        b = Column.from_strings(["apricot", "fig", "aa"])
        assert ops.binary_op("lt", a, b).to_pylist() == [True, False, False]
        assert ops.binary_op("eq", a, b).to_pylist() == [False, True, False]


class TestOuterJoins:
    """Round-3: FULL/RIGHT OUTER (VERDICT item 7), pandas oracles."""

    def _tables(self, rng, nl=300, nr=200, keyspace=40):
        lk = rng.integers(0, keyspace, nl, dtype=np.int64)
        rk = rng.integers(0, keyspace, nr, dtype=np.int64)
        lv = np.arange(nl, dtype=np.int64)
        rv = np.arange(nr, dtype=np.int64)
        left = Table(
            [Column.from_numpy(lk), Column.from_numpy(lv)], ["k", "lv"]
        )
        right = Table(
            [Column.from_numpy(rk), Column.from_numpy(rv)], ["k", "rv"]
        )
        return left, right, lk, rk, lv, rv

    @staticmethod
    def _rows(out):
        return sorted(
            zip(
                out["k"].to_pylist(),
                out["lv"].to_pylist(),
                out["rv"].to_pylist(),
            ),
            key=lambda r: tuple((x is None, x) for x in r),
        )

    @staticmethod
    def _pandas_rows(pd, lk, rk, lv, rv, how):
        want = pd.merge(
            pd.DataFrame({"k": lk, "lv": lv}),
            pd.DataFrame({"k": rk, "rv": rv}),
            on="k",
            how=how,
        )
        rows = [
            (
                None if pd.isna(k) else int(k),
                None if pd.isna(a) else int(a),
                None if pd.isna(b) else int(b),
            )
            for k, a, b in zip(want["k"], want["lv"], want["rv"])
        ]
        return sorted(
            rows, key=lambda r: tuple((x is None, x) for x in r)
        )

    def test_right_join_vs_pandas(self, rng):
        pd = pytest.importorskip("pandas")
        left, right, lk, rk, lv, rv = self._tables(rng)
        out = ops.right_join(left, right, ["k"])
        assert self._rows(out) == self._pandas_rows(pd, lk, rk, lv, rv, "right")

    def test_full_join_vs_pandas(self, rng):
        pd = pytest.importorskip("pandas")
        # disjoint-ish keyspaces so both sides have unmatched rows
        left, right, lk, rk, lv, rv = self._tables(rng, keyspace=60)
        out = ops.full_join(left, right, ["k"])
        assert self._rows(out) == self._pandas_rows(pd, lk, rk, lv, rv, "outer")

    def test_full_join_null_keys_both_sides(self):
        left = Table.from_pydict({"k": [1, None, 3], "lv": [10, 20, 30]})
        right = Table.from_pydict({"k": [1, None], "rv": [100, 200]})
        out = ops.full_join(left, right, ["k"])
        rows = self._rows(out)
        # null keys never match but still appear, one row each
        assert rows == [
            (1, 10, 100),
            (3, 30, None),
            (None, 20, None),
            (None, None, 200),
        ]

    def test_right_join_null_keys(self):
        left = Table.from_pydict({"k": [1, 2], "lv": [10, 20]})
        right = Table.from_pydict({"k": [1, None, 9], "rv": [100, 200, 300]})
        out = ops.right_join(left, right, ["k"])
        rows = self._rows(out)
        assert rows == [
            (1, 10, 100),
            (9, None, 300),
            (None, None, 200),
        ]

    def test_full_join_no_matches(self):
        left = Table.from_pydict({"k": [1, 2], "lv": [10, 20]})
        right = Table.from_pydict({"k": [8, 9], "rv": [100, 200]})
        out = ops.full_join(left, right, ["k"])
        assert out.row_count == 4
        rows = self._rows(out)
        assert rows == [
            (1, 10, None),
            (2, 20, None),
            (8, None, 100),
            (9, None, 200),
        ]
