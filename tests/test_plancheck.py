"""Plan-time static analyzer (the GpuOverrides tagging-pass analog).

The tentpole contract: ``plancheck`` walks a plan's JSON op list against
an input schema signature BEFORE any upload, compile, or scheduler
admission and produces a tagged report — per-op inferred output
schema/dtypes, a support tier with a human-readable reason, predicted
fusion segmentation, and a static HBM footprint bound. Three invariants
pin it to the runtime so the two can never drift:

* registry parity — ``plancheck._RULES`` keys == the dispatch plane's
  ``runtime_bridge.DISPATCH_OPS`` (also enforced statically by srt-check
  SRT008), and the tier tables mirror ``bucketed._RUNNERS`` /
  ``plan.op_fusable``;
* segmentation parity — ``predict_segments`` agrees exactly with
  ``plan.segment_plan`` over a fuzzed corpus, bucket edges included;
* inference parity — an analyzer-clean plan EXECUTES, and its executed
  wire schema matches the inferred one byte-for-byte (type ids and
  scale slots).

The acceptance half: a statically-invalid plan (unknown op,
dtype-mismatched cast, groupby on a missing column) is rejected at
every entry — ``table_plan_wire`` / ``table_stream_wire`` /
``table_plan_resident`` — with a typed error naming op index + reason
and ZERO uploads or compiles, asserted via the ``wire.*`` /
``compile_cache.*`` metrics counters.
"""

import json

import numpy as np
import pytest

from spark_rapids_jni_tpu import bucketed
from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import plan as plan_mod
from spark_rapids_jni_tpu import plancheck as pc
from spark_rapids_jni_tpu import runtime_bridge as rb
from spark_rapids_jni_tpu.utils import config, metrics

I64 = int(dt.TypeId.INT64)
I32 = int(dt.TypeId.INT32)
F64 = int(dt.TypeId.FLOAT64)
B8 = int(dt.TypeId.BOOL8)
STR = int(dt.TypeId.STRING)

C = pc.ColType
T = dt.TypeId


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    config.clear_flag("BUCKETS")
    config.clear_flag("METRICS")


def _string_wire(strings):
    payload = b"".join(s.encode() for s in strings)
    offs = np.zeros(len(strings) + 1, np.int32)
    np.cumsum([len(s.encode()) for s in strings], out=offs[1:])
    return offs.tobytes() + payload


def _cols(n: int):
    """The shared parity table: int64 key, int64 value with nulls, BOOL8
    mask, float64, and a low-cardinality STRING column."""
    rng = np.random.default_rng(n)
    k = rng.integers(0, 9, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    valid = (np.arange(n) % 7 != 0).astype(np.uint8)
    mask = (v > 0).astype(np.uint8)
    f = rng.normal(size=n)
    strs = [f"w{int(x) % 5}ord" for x in k]
    return [
        (I64, 0, k.tobytes(), None),
        (I64, 0, v.tobytes(), valid.tobytes()),
        (B8, 0, mask.tobytes(), None),
        (F64, 0, f.tobytes(), None),
        (STR, 0, _string_wire(strs), None),
    ]


BASE_SCHEMA = [C(T.INT64), C(T.INT64), C(T.BOOL8), C(T.FLOAT64), C(T.STRING)]


def _run_wire(ops, cols, n):
    return rb.table_plan_wire(
        json.dumps(ops),
        [c[0] for c in cols], [c[1] for c in cols],
        [c[2] for c in cols], [c[3] for c in cols], n,
    )


def _ids_scales(schema):
    return [(c.id, c.scale, c.child) for c in schema]


# ---------------------------------------------------------------------------
# schema signatures
# ---------------------------------------------------------------------------


class TestSchemaSignatures:
    def test_wire_roundtrip_splits_list_child(self):
        sch = pc.schema_from_wire([I64, int(T.LIST), STR], [0, int(T.INT32), 0])
        assert sch[0] == C(T.INT64)
        assert sch[1] == C(T.LIST, 0, T.INT32)
        assert sch[1].pretty() == "LIST<INT32>"
        assert sch[2].is_string

    def test_schema_of_live_table(self):
        n = 16
        cols = _cols(n)
        tid = rb.table_upload_wire(
            [c[0] for c in cols], [c[1] for c in cols],
            [c[2] for c in cols], [c[3] for c in cols], n,
        )
        try:
            sch = pc.schema_of_table(rb._resident_get(tid))
        finally:
            rb.table_free(tid)
        assert sch == BASE_SCHEMA

    def test_to_json_is_wire_shaped(self):
        d = C(T.DECIMAL64, -2).to_json()
        assert d == {
            "type_id": int(T.DECIMAL64), "scale": -2, "child": None,
            "pretty": "DECIMAL64(scale=-2)",
        }


# ---------------------------------------------------------------------------
# per-op inference rules
# ---------------------------------------------------------------------------


def _one(ops, schema=BASE_SCHEMA, rows=100, **kw):
    return pc.analyze(ops, schema=schema, rows=rows, **kw)


class TestInferenceRules:
    def test_cast_rewrites_column(self):
        rep = _one([{"op": "cast", "column": 1, "type_id": F64}])
        assert rep["ok"]
        out = rep["ops"][0]["out_schema"]
        assert out[1]["type_id"] == F64
        assert out[0]["type_id"] == I64

    def test_cast_float_to_decimal128_rejected(self):
        rep = _one([{"op": "cast", "column": 3,
                     "type_id": int(T.DECIMAL128)}])
        assert not rep["ok"]
        assert "DECIMAL128" in rep["ops"][0]["reason"]

    def test_cast_string_paths(self):
        ok = _one([{"op": "cast", "column": 4, "type_id": I64}])
        assert ok["ok"]
        ok = _one([{"op": "cast", "column": 0, "type_id": STR}])
        assert ok["ok"] and ok["ops"][0]["out_schema"][0]["type_id"] == STR

    def test_filter_drops_mask_column(self):
        rep = _one([{"op": "filter", "mask": 2}])
        assert rep["ok"]
        out = rep["ops"][0]["out_schema"]
        assert [c["type_id"] for c in out] == [I64, I64, F64, STR]

    def test_filter_non_bool_mask_rejected(self):
        rep = _one([{"op": "filter", "mask": 0}])
        assert not rep["ok"]
        assert "BOOL8" in rep["ops"][0]["reason"]

    def test_filter_zero_column_result_rejected(self):
        rep = _one([{"op": "filter", "mask": 0}], schema=[C(T.BOOL8)])
        assert not rep["ok"]
        assert "zero-column" in rep["ops"][0]["reason"]

    def test_groupby_agg_output_dtypes(self):
        rep = _one([{
            "op": "groupby", "by": [0],
            "aggs": [
                {"column": 1, "agg": "sum"},
                {"column": 1, "agg": "count"},
                {"column": 3, "agg": "sum"},
                {"column": 3, "agg": "mean"},
                {"column": 1, "agg": "min"},
                {"column": 1, "agg": "collect_list"},
            ],
        }])
        assert rep["ok"], rep["ops"][0]["reason"]
        out = rep["ops"][0]["out_schema"]
        # key, then: int sum->I64, count->I64, float sum->F64, mean->F64,
        # min->input, collect_list->LIST<INT64>
        assert [c["type_id"] for c in out[:6]] == [I64, I64, I64, F64, F64,
                                                   I64]
        assert out[6]["type_id"] == int(T.LIST)
        assert out[6]["child"] == I64

    def test_groupby_sum_on_string_rejected(self):
        rep = _one([{"op": "groupby", "by": [0],
                     "aggs": [{"column": 4, "agg": "sum"}]}])
        assert not rep["ok"]
        assert "STRING" in rep["ops"][0]["reason"]

    def test_groupby_collect_float64_rejected(self):
        # FLOAT64 is not a supported LIST child on the wire
        rep = _one([{"op": "groupby", "by": [0],
                     "aggs": [{"column": 3, "agg": "collect_list"}]}])
        assert not rep["ok"]
        assert "collect_list" in rep["ops"][0]["reason"]

    def test_groupby_missing_column_rejected(self):
        rep = _one([{"op": "groupby", "by": [17],
                     "aggs": [{"column": 0, "agg": "sum"}]}])
        assert not rep["ok"]
        assert "out of range" in rep["ops"][0]["reason"]

    def test_join_using_semantics(self):
        right = ([C(T.INT64), C(T.FLOAT64)], 10)
        rep = _one([{"op": "join", "on": [0], "how": "inner"}],
                   rest=[right])
        assert rep["ok"]
        out = rep["ops"][0]["out_schema"]
        # left cols + right cols minus the right join key
        assert [c["type_id"] for c in out] == [I64, I64, B8, F64, STR, F64]
        assert rep["ops"][0]["rows_bound"] == 100 * 10

    def test_semi_join_keeps_left_schema(self):
        rep = _one([{"op": "join", "on": [0], "how": "semi"}],
                   rest=[([C(T.INT64)], 10)])
        assert rep["ok"]
        assert len(rep["ops"][0]["out_schema"]) == len(BASE_SCHEMA)
        assert rep["ops"][0]["rows_bound"] == 100

    def test_outer_join_key_dtype_mismatch_rejected(self):
        rep = _one([{"op": "join", "on": [0], "how": "full"}],
                   rest=[([C(T.FLOAT64)], 10)])
        assert not rep["ok"]
        assert "outer-join key dtypes differ" in rep["ops"][0]["reason"]

    def test_join_without_rest_table_rejected(self):
        rep = _one([{"op": "join", "on": [0]}])
        assert not rep["ok"]
        assert "two input tables" in rep["ops"][0]["reason"]

    def test_concat_dtype_mismatch_rejected(self):
        rep = _one([{"op": "concat"}], rest=[([C(T.FLOAT64)] * 5, 10)])
        assert not rep["ok"]
        assert "dtype mismatch" in rep["ops"][0]["reason"]

    def test_concat_adds_rows(self):
        rep = _one([{"op": "concat"}], rest=[(list(BASE_SCHEMA), 10)])
        assert rep["ok"]
        assert rep["ops"][0]["rows_bound"] == 110

    def test_slice_row_clamping(self):
        rep = _one([{"op": "slice", "start": 10, "stop": 2000}])
        assert rep["ok"]
        assert rep["ops"][0]["rows_bound"] == 90

    def test_negative_slice_rejected(self):
        rep = _one([{"op": "slice", "start": -1}])
        assert not rep["ok"]
        assert "negative" in rep["ops"][0]["reason"]

    def test_explode_requires_list(self):
        rep = _one([{"op": "explode", "column": 0}])
        assert not rep["ok"]
        assert "LIST" in rep["ops"][0]["reason"]
        ok = _one([{"op": "explode", "column": 0}],
                  schema=[C(T.LIST, 0, T.INT32)])
        assert ok["ok"]
        assert ok["ops"][0]["out_schema"][0]["type_id"] == I32
        assert ok["ops"][0]["rows_bound"] is None  # data-dependent

    def test_rlike_requires_string(self):
        rep = _one([{"op": "rlike", "column": 0, "pattern": "x"}])
        assert not rep["ok"]
        assert "STRING" in rep["ops"][0]["reason"]

    def test_partition_schema_passthrough(self):
        rep = _one([{"op": "partition", "kind": "hash", "keys": [0],
                     "num": 8}])
        assert rep["ok"], rep["ops"][0]["reason"]
        out = rep["ops"][0]["out_schema"]
        # pure row redistribution: schema and rows pass through unchanged
        assert [c["type_id"] for c in out] == [I64, I64, B8, F64, STR]
        assert rep["ops"][0]["rows_bound"] == 100
        assert rep["ops"][0]["tier"] == "exact-only"
        assert "exchange boundary" in rep["ops"][0]["reason"]

    def test_partition_bad_kind_rejected(self):
        rep = _one([{"op": "partition", "kind": "zorder", "num": 8}])
        assert not rep["ok"]
        assert "unknown partition kind" in rep["ops"][0]["reason"]

    def test_partition_bad_num_rejected(self):
        for num in (0, -3, True, "8", None):
            rep = _one([{"op": "partition", "kind": "hash", "keys": [0],
                         "num": num}])
            assert not rep["ok"], num
            assert "partition num" in rep["ops"][0]["reason"]

    def test_partition_range_needs_keys(self):
        rep = _one([{"op": "partition", "kind": "range", "num": 8}])
        assert not rep["ok"]
        assert "non-empty 'keys'" in rep["ops"][0]["reason"]

    def test_partition_missing_key_rejected(self):
        rep = _one([{"op": "partition", "kind": "hash", "keys": [17],
                     "num": 8}])
        assert not rep["ok"]
        assert "out of range" in rep["ops"][0]["reason"]

    def test_to_rows_from_rows_roundtrip_schema(self):
        rep = _one([
            {"op": "to_rows"},
            {"op": "from_rows", "type_ids": [I64, I64], "scales": [0, 0]},
        ], schema=[C(T.INT64), C(T.INT64)])
        assert rep["ok"], rep["ops"]
        assert rep["ops"][0]["out_schema"][0]["pretty"] == "LIST<UINT8>"
        assert [c["type_id"] for c in rep["out_schema"]] == [I64, I64]

    def test_to_rows_refuses_strings(self):
        rep = _one([{"op": "to_rows"}])
        assert not rep["ok"]
        assert "fixed-width" in rep["ops"][0]["reason"]

    def test_unknown_op_mirrors_dispatch_message(self):
        rep = _one([{"op": "frobnicate"}])
        assert not rep["ok"]
        assert rep["ops"][0]["reason"] == "unknown table op 'frobnicate'"

    def test_schema_unknowable_downstream_of_reject(self):
        rep = _one([{"op": "frobnicate"},
                    {"op": "cast", "column": 99, "type_id": F64}])
        assert not rep["ok"]
        # the cast after the rejected op cannot be range-checked
        assert rep["ops"][1]["out_schema"] is None

    def test_structural_walk_without_schema(self):
        # schema=None degrades to structural validation: shape errors
        # still reject, dtype questions stay open
        rep = pc.analyze([{"op": "cast", "column": 5, "type_id": F64},
                          {"op": "groupby", "by": []}])
        assert not rep["ok"]
        assert "non-empty 'by' list" in rep["ops"][1]["reason"]
        ok = pc.analyze([{"op": "filter", "mask": 3},
                         {"op": "sort_by", "keys": [{"column": 0}]}])
        assert ok["ok"]

    def test_non_list_plan(self):
        rep = pc.analyze("nope")
        assert not rep["ok"]
        assert "JSON list" in rep["ops"][0]["reason"]

    def test_footprint_bound_is_populated(self):
        rep = _one([{"op": "filter", "mask": 2},
                    {"op": "sort_by", "keys": [{"column": 0}]}])
        assert rep["ok"]
        assert rep["est_hbm_peak_bytes"] is not None
        assert rep["est_hbm_peak_bytes"] > 0
        for seg in rep["segments"]:
            assert seg["est_hbm_bytes"] <= rep["est_hbm_peak_bytes"]

    def test_render_report_tags(self):
        txt = pc.render_report(_one([{"op": "cast", "column": 1,
                                      "type_id": F64},
                                     {"op": "frobnicate"}]))
        assert "REJECTED" in txt
        assert "unknown table op" in txt
        assert "* op[0]" in txt  # fusable glyph
        assert "! op[1]" in txt  # unsupported glyph


# ---------------------------------------------------------------------------
# registry + tier parity with the runtime (the SRT008 pair, dynamically)
# ---------------------------------------------------------------------------


OPS_CORPUS = [
    {"op": "cast", "column": 0, "type_id": F64},
    {"op": "filter", "mask": 2},
    {"op": "rlike", "column": 4, "pattern": "a+"},
    {"op": "distinct"},
    {"op": "distinct", "keys": [0, 1]},
    {"op": "sort_by", "keys": [{"column": 0}]},
    {"op": "slice", "start": 0, "stop": 10},
    {"op": "slice", "start": -1},
    {"op": "slice", "start": "x"},
    {"op": "slice"},
    {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]},
    {"op": "groupby", "by": [0],
     "aggs": [{"column": 1, "agg": "collect_list"}]},
    {"op": "groupby", "by": [0],
     "aggs": [{"column": 1, "agg": "collect_set"}]},
    {"op": "join", "on": [0]},
    {"op": "join", "on": [0], "how": "full"},
    {"op": "cross_join"},
    {"op": "concat"},
    {"op": "explode", "column": 0},
    {"op": "repeat", "count": 2},
    {"op": "sample", "n": 5},
    {"op": "to_rows"},
    {"op": "from_rows", "type_ids": [I64], "scales": [0]},
    {"op": "frobnicate"},
    {"notanop": 1},
]


class TestRegistryParity:
    def test_rule_table_matches_dispatch_ops(self):
        assert set(pc._RULES) == rb.DISPATCH_OPS

    def test_bucketed_tier_tables_match_runtime(self):
        assert pc._BUCKETED_OPS == frozenset(bucketed._RUNNERS)
        assert pc._BUCKETED_JOIN_HOWS == bucketed._BUCKETED_JOIN_HOWS

    def test_op_fusable_mirror_matches_plan(self):
        for op in OPS_CORPUS:
            assert pc._op_fusable(op) == plan_mod.op_fusable(op), op

    def test_every_dispatch_op_gets_a_tier_and_reason(self):
        for name in sorted(rb.DISPATCH_OPS):
            tier, reason = pc._tier({"op": name})
            assert tier in ("fusable", "per-op", "exact-only"), name
            assert reason

    def test_tier_reflects_bucketed_join_hows(self):
        assert pc._tier({"op": "join", "how": "inner"})[0] == "per-op"
        assert pc._tier({"op": "join", "how": "full"})[0] == "exact-only"

    def test_collect_groupby_is_exact_only(self):
        op = {"op": "groupby", "by": [0],
              "aggs": [{"column": 1, "agg": "collect_list"}]}
        assert pc._tier(op)[0] == "exact-only"
        plain = {"op": "groupby", "by": [0],
                 "aggs": [{"column": 1, "agg": "sum"}]}
        assert pc._tier(plain)[0] == "fusable"


# ---------------------------------------------------------------------------
# segmentation-parity fuzz
# ---------------------------------------------------------------------------


def _assert_seg_parity(ops):
    pred = pc.predict_segments(ops)
    real = plan_mod.segment_plan(ops)
    assert [k for k, _ in pred] == [k for k, _ in real], ops
    assert [[ops[i] for i in idxs] for _, idxs in pred] == [
        seg for _, seg in real
    ], ops


def _rand_valid_op(rng, schema):
    """One candidate op valid against ``schema`` (fixed-width keys only,
    so every generated plan also EXECUTES on the CPU dispatch plane)."""
    fixed = [i for i, c in enumerate(schema) if c.is_fixed_width]
    bools = [i for i, c in enumerate(schema) if c.is_boolean]
    strs = [i for i, c in enumerate(schema) if c.is_string]
    ints = [i for i, c in enumerate(schema)
            if c.is_integer or c.is_floating]
    choices = [
        {"op": "slice", "start": int(rng.integers(0, 3)),
         "stop": int(rng.integers(8, 64))},
        {"op": "sort_by",
         "keys": [{"column": int(rng.choice(fixed))}]},
        {"op": "distinct", "keys": [int(rng.choice(fixed))]},
    ]
    if ints:
        tgt = int(rng.choice([F64, I64, I32]))
        choices.append(
            {"op": "cast", "column": int(rng.choice(ints)), "type_id": tgt}
        )
        choices.append({
            "op": "groupby", "by": [int(rng.choice(ints))],
            "aggs": [{
                "column": int(rng.choice(ints)),
                "agg": str(rng.choice(["sum", "count", "min", "max"])),
            }],
        })
    if bools and len(schema) > 1:
        choices.append({"op": "filter", "mask": int(rng.choice(bools))})
    if strs:
        choices.append(
            {"op": "rlike", "column": int(rng.choice(strs)),
             "pattern": "w[0-2]o"}
        )
    return choices[int(rng.integers(0, len(choices)))]


def _rand_plan(rng, max_len=6):
    """Random analyzer-clean plan over BASE_SCHEMA (accept-filtered: a
    candidate the analyzer rejects is discarded and redrawn)."""
    ops = []
    schema = list(BASE_SCHEMA)
    for _ in range(int(rng.integers(1, max_len + 1))):
        for _try in range(8):
            cand = _rand_valid_op(rng, schema)
            rep = pc.analyze(ops + [cand], schema=BASE_SCHEMA, rows=100)
            if rep["ok"]:
                ops.append(cand)
                out = rep["ops"][-1]["out_schema"]
                schema = [
                    pc.ColType(
                        dt.TypeId(c["type_id"]), c["scale"],
                        dt.TypeId(c["child"]) if c["child"] is not None
                        else None,
                    )
                    for c in out
                ]
                break
    return ops


class TestSegmentationFuzz:
    def test_200_random_plans_segment_identically(self):
        rng = np.random.default_rng(1234)
        wild = list(OPS_CORPUS)
        for trial in range(200):
            if trial % 2:
                # analyzer-clean plans
                ops = _rand_plan(rng)
            else:
                # unconstrained soup, malformed entries included —
                # segmentation must still agree op-for-op
                k = int(rng.integers(1, 8))
                ops = [wild[int(i)] for i in rng.integers(0, len(wild), k)]
            _assert_seg_parity(ops)

    def test_predicted_segments_match_report(self):
        ops = [
            {"op": "cast", "column": 1, "type_id": F64},
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "join", "on": [0]},
        ]
        rep = pc.analyze(ops, schema=BASE_SCHEMA, rows=10,
                         rest=[([C(T.INT64)], 5)])
        assert [(s["kind"], s["ops"]) for s in rep["segments"]] == [
            ("fused", [0, 1]), ("exact", [2]),
        ]


# ---------------------------------------------------------------------------
# inference-vs-execution fuzz: analyzer-clean plans run, and the wire
# result's (type_ids, scales) match the inferred schema byte-for-byte
# ---------------------------------------------------------------------------


def _assert_executes_as_inferred(ops, n):
    cols = _cols(n)
    rep = pc.analyze(ops, schema=BASE_SCHEMA, rows=n)
    assert rep["ok"], (ops, [e["reason"] for e in rep["ops"]])
    _assert_seg_parity(ops)
    type_ids, scales, _datas, _valids, out_rows = _run_wire(ops, cols, n)
    inferred = rep["out_schema"]
    assert len(inferred) == len(type_ids), ops
    for got_tid, got_scale, want in zip(type_ids, scales, inferred):
        assert int(got_tid) == want["type_id"], ops
        # LIST wire convention: scale slot carries the child type id
        want_scale = (
            want["child"] if want["type_id"] == int(T.LIST)
            else want["scale"]
        )
        assert int(got_scale) == want_scale, ops
    if rep["rows_out_bound"] is not None:
        assert out_rows <= rep["rows_out_bound"], ops


class TestExecutionParityFuzz:
    def test_random_clean_plans_execute_with_inferred_schema(self):
        rng = np.random.default_rng(77)
        config.set_flag("BUCKETS", "off")  # eager exact: cheap fuzz path
        for _ in range(20):
            ops = _rand_plan(rng, max_len=4)
            _assert_executes_as_inferred(ops, n=48)

    @pytest.mark.parametrize("n", (1023, 1024, 1025))
    def test_bucket_edges_with_buckets_on(self, n):
        # the same chain test_plan.py pins byte-identical across paths,
        # now cross-checked against the static inference with the
        # bucketed plan path live at the 1024 bucket edges
        config.set_flag("BUCKETS", "")
        ops = [
            {"op": "filter", "mask": 2},
            {"op": "cast", "column": 1, "type_id": F64},
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "sum"},
                      {"column": 1, "agg": "count"}]},
        ]
        # BASE_SCHEMA here is the 5-col table; the test_plan chain uses
        # its 4-col cousin — drop the F64 column to match its shape
        cols = _cols(n)
        del cols[3]
        schema = [c for i, c in enumerate(BASE_SCHEMA) if i != 3]
        rep = pc.analyze(ops, schema=schema, rows=n)
        assert rep["ok"]
        _assert_seg_parity(ops)
        got = rb.table_plan_wire(
            json.dumps(ops),
            [c[0] for c in cols], [c[1] for c in cols],
            [c[2] for c in cols], [c[3] for c in cols], n,
        )
        type_ids, scales, _d, _v, out_rows = got
        assert [int(t) for t in type_ids] == [
            c["type_id"] for c in rep["out_schema"]
        ]
        assert [int(s) for s in scales] == [
            c["scale"] for c in rep["out_schema"]
        ]
        assert out_rows <= rep["rows_out_bound"]


# ---------------------------------------------------------------------------
# acceptance: invalid plans die at every entry with ZERO device work
# ---------------------------------------------------------------------------


INVALID_PLANS = {
    "unknown_op": (
        [{"op": "frobnicate"}], "unknown table op 'frobnicate'"),
    "dtype_mismatched_cast": (
        [{"op": "cast", "column": 3, "type_id": int(T.DECIMAL128)}],
        "DECIMAL128"),
    "groupby_missing_column": (
        [{"op": "groupby", "by": [17],
          "aggs": [{"column": 0, "agg": "sum"}]}],
        "out of range"),
}


def _work_counters(snap=None):
    c = (snap or metrics.snapshot())["counters"]
    return {
        k: v for k, v in c.items()
        if k.startswith(("wire.", "compile_cache.", "serving.", "resident."))
    }


class TestRejectionZeroWork:
    @pytest.mark.parametrize("case", sorted(INVALID_PLANS))
    def test_wire_entry_rejects_before_any_upload(self, case):
        ops, needle = INVALID_PLANS[case]
        n = 32
        cols = _cols(n)
        config.set_flag("METRICS", True)
        metrics.reset()
        with pytest.raises(pc.PlanCheckError) as exc:
            _run_wire(ops, cols, n)
        assert "plancheck: op[0]" in str(exc.value)
        assert needle in str(exc.value)
        assert exc.value.index == 0
        assert exc.value.plan_report["ok"] is False
        assert _work_counters() == {}  # no upload, no compile

    @pytest.mark.parametrize("case", sorted(INVALID_PLANS))
    def test_stream_entry_rejects_before_any_upload(self, case):
        ops, needle = INVALID_PLANS[case]
        n = 32
        cols = _cols(n)
        batch = (
            [c[0] for c in cols], [c[1] for c in cols],
            [c[2] for c in cols], [c[3] for c in cols], n,
        )
        config.set_flag("METRICS", True)
        metrics.reset()
        with pytest.raises(pc.PlanCheckError, match="plancheck: op\\[0\\]"):
            rb.table_stream_wire(json.dumps(ops), [batch, batch])
        assert _work_counters() == {}

    @pytest.mark.parametrize("case", sorted(INVALID_PLANS))
    def test_resident_entry_rejects_before_any_dispatch(self, case):
        ops, needle = INVALID_PLANS[case]
        n = 32
        cols = _cols(n)
        tid = rb.table_upload_wire(
            [c[0] for c in cols], [c[1] for c in cols],
            [c[2] for c in cols], [c[3] for c in cols], n,
        )
        try:
            config.set_flag("METRICS", True)
            metrics.reset()
            with pytest.raises(pc.PlanCheckError) as exc:
                rb.table_plan_resident(json.dumps(ops), [tid])
            assert needle in str(exc.value)
            assert _work_counters() == {}
        finally:
            config.clear_flag("METRICS")
            rb.table_free(tid)

    def test_legacy_error_texts_still_reach_callers(self):
        # pre-existing callers match these substrings THROUGH the wire
        # entries; the static reject must carry the same text
        n = 8
        cols = _cols(n)
        with pytest.raises(ValueError, match="unknown table op"):
            _run_wire([{"op": "nope"}], cols, n)
        with pytest.raises(TypeError, match="JSON list"):
            _run_wire({"op": "nope"}, cols, n)
        with pytest.raises(ValueError, match="op objects"):
            _run_wire(["nope"], cols, n)

    def test_valid_plan_passes_through_unchanged(self):
        n = 64
        cols = _cols(n)
        config.set_flag("BUCKETS", "off")
        out = _run_wire(
            [{"op": "filter", "mask": 2},
             {"op": "sort_by", "keys": [{"column": 0}]}], cols, n,
        )
        assert out[4] <= n
        assert len(out[0]) == 4  # mask dropped

    def test_check_plan_returns_report_when_clean(self):
        rep = pc.check_plan(
            [{"op": "cast", "column": 0, "type_id": F64}],
            schema=BASE_SCHEMA, rows=10,
        )
        assert rep["ok"]
        assert rep["out_schema"][0]["type_id"] == F64
