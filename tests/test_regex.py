"""Regex ops vs the Python ``re`` oracle.

The engine advertises leftmost-longest (POSIX) span semantics over a
documented syntax subset; every test pattern here is one where Python's
backtracking ``re`` agrees, so ``re`` serves as the oracle (the same role
the cudf Java suite's host comparisons play, SURVEY.md §4)."""

import re

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import regex as rx


WORDS = [
    "", "a", "ab", "abc", "aabbb", "id=123", "id=", "x1y2z3",
    "hello world", "2024-01-31", "not-a-date", "foo.txt", "foo_txt",
    "  padded  ", "aaaa", "abab", "cabbage", "12.5", "-7", "+e",
    "tail123", "123head", "a|b", "[x]", "line\nbreak", "CAPS", "MiXeD",
]


def _col(values):
    return Column.from_strings(values)


def _rand_strings(rng, n=200, alphabet="abc01 .-", max_len=12):
    out = []
    for _ in range(n):
        k = int(rng.integers(0, max_len))
        out.append("".join(rng.choice(list(alphabet), k)))
    return out


CONTAINS_PATTERNS = [
    r"abc",
    r"a+b",
    r"\d+",
    r"[a-c]{2}",
    r"a.c",
    r"(ab)+",
    r"a|0",
    r"^a",
    r"c$",
    r"^[a-z0-9]*$",
    r"\s",
    r"\.",
    r"[^a-z]",
    r"b{2,3}",
    r"-?\d+\.\d+",
]


@pytest.mark.parametrize("pattern", CONTAINS_PATTERNS)
def test_contains_re_fixed_corpus(pattern):
    col = _col(WORDS)
    got = np.asarray(rx.contains_re(col, pattern).data)
    want = [re.search(pattern, w) is not None for w in WORDS]
    assert got.tolist() == want, pattern


@pytest.mark.parametrize("pattern", CONTAINS_PATTERNS)
def test_contains_re_random(rng, pattern):
    words = _rand_strings(rng)
    col = _col(words)
    got = np.asarray(rx.contains_re(col, pattern).data)
    want = [re.search(pattern, w) is not None for w in words]
    assert got.tolist() == want, pattern


def test_contains_re_jit():
    import jax

    col = _col(WORDS)
    f = jax.jit(lambda c: rx.contains_re(c, r"\d+").data)
    got = np.asarray(f(col))
    want = [re.search(r"\d+", w) is not None for w in WORDS]
    assert got.tolist() == want


@pytest.mark.parametrize(
    "pattern",
    [r"[a-z]+", r"\d{4}-\d{2}-\d{2}", r"a*", r"a.*c", r"(?:ab|ba)+"],
)
def test_matches_re(pattern):
    col = _col(WORDS)
    got = np.asarray(rx.matches_re(col, pattern).data)
    want = [re.fullmatch(pattern, w) is not None for w in WORDS]
    assert got.tolist() == want, pattern


@pytest.mark.parametrize(
    "pattern", [r"\d+", r"[bc]+", r"ab", r"^a+", r"c$", r"a.c"]
)
def test_find_re(pattern):
    col = _col(WORDS)
    got = np.asarray(rx.find_re(col, pattern).data)
    for w, g in zip(WORDS, got.tolist()):
        m = re.search(pattern, w)
        assert g == (m.start() if m else -1), (pattern, w)


@pytest.mark.parametrize(
    "pattern,group_re",
    [
        (r"id=(\d+)", r"id=(\d+)"),
        (r"(\d+)", r"(\d+)"),
        (r"^(\w+)\.txt$", r"^(\w+)\.txt$"),
        (r"-(\d{2})-", r"-(\d{2})-"),
    ],
)
def test_extract_re(pattern, group_re):
    col = _col(WORDS)
    out = rx.extract_re(col, pattern)
    vals = out.to_pylist()
    for w, got in zip(WORDS, vals):
        m = re.search(group_re, w)
        assert got == (m.group(1) if m else None), (pattern, w)


def test_extract_re_rejects_variable_context():
    col = _col(WORDS)
    with pytest.raises(ValueError):
        rx.extract_re(col, r"a*(\d+)")
    with pytest.raises(ValueError):
        rx.extract_re(col, r"(\d+)(\w+)")


@pytest.mark.parametrize(
    "pattern,repl",
    [
        (r"\d+", "#"),
        (r"[aeiou]", ""),
        (r"ab", "xyz"),
        (r"\s+", "_"),
        (r"a.c", "QQ"),
    ],
)
def test_replace_re(pattern, repl):
    col = _col(WORDS)
    out = rx.replace_re(col, pattern, repl).to_pylist()
    want = [re.sub(pattern, repl, w) for w in WORDS]
    assert out == want, (pattern, repl)


def test_replace_re_random(rng):
    words = _rand_strings(rng, n=300)
    col = _col(words)
    out = rx.replace_re(col, r"[ab]+", "<>").to_pylist()
    want = [re.sub(r"[ab]+", "<>", w) for w in words]
    assert out == want


@pytest.mark.parametrize("pattern", [r"\d+", r"a", r"[bc]{2}", r"ab"])
def test_count_re(pattern):
    col = _col(WORDS)
    got = np.asarray(rx.count_re(col, pattern).data)
    want = [len(re.findall(pattern, w)) for w in WORDS]
    assert got.tolist() == want, pattern


def test_null_propagation():
    col = _col(["abc", None, "123"])
    out = rx.contains_re(col, r"\d")
    assert np.asarray(out.validity).tolist() == [True, False, True]
    ext = rx.extract_re(col, r"(\d+)")
    # null input stays null; no-match row becomes null (cudf convention)
    assert ext.to_pylist() == [None, None, "123"]


def test_anchors_and_empty():
    col = _col(["", "a", "ba"])
    assert np.asarray(rx.contains_re(col, r"^a").data).tolist() == [
        False, True, False,
    ]
    assert np.asarray(rx.contains_re(col, r"a$").data).tolist() == [
        False, True, True,
    ]
    # empty-matching pattern contains-matches everything
    assert np.asarray(rx.contains_re(col, r"z*").data).tolist() == [
        True, True, True,
    ]
    # but full-match only where the whole string fits
    assert np.asarray(rx.matches_re(col, r"a*").data).tolist() == [
        True, True, False,
    ]


def test_unsupported_syntax_raises():
    col = _col(["x"])
    for bad in [r"a(?=b)", r"(a", r"a{1,999}", r"a\k", r"mid^dle"]:
        with pytest.raises(ValueError):
            rx.contains_re(col, bad)


def test_dfa_state_cap():
    # exponential-subset pattern: (a|b)*a(a|b){n} needs ~2^n DFA states
    with pytest.raises(ValueError):
        rx.compile_re(r"(?:a|b)*a(?:a|b){12}")


# ---------------------------------------------------------------------------
# round 4: anchor scoping over alternation (ADVICE r3 medium) + typed
# errors + generated differential corpus (VERDICT r3 item 8)
# ---------------------------------------------------------------------------


def test_anchor_binds_one_branch():
    """Java/Spark semantics: '^a|b' is '(^a)|b', NOT '^(a|b)'."""
    col = _col(["zb", "az", "za", "b", "a", ""])
    for pattern in [r"^a|b", r"b|^a", r"a$|b", r"^a|b$", r"a$|^b"]:
        got = np.asarray(rx.contains_re(col, pattern).data).tolist()
        want = [re.search(pattern, w) is not None for w in col.to_pylist()]
        assert got == want, pattern


def test_matches_re_alternation_per_branch():
    """Full match succeeds iff ANY branch full-matches."""
    col = _col(["a", "b", "ab", "ba", ""])
    for pattern in [r"a|b", r"a+|b", r"^a|b", r"a|"]:
        got = np.asarray(rx.matches_re(col, pattern).data).tolist()
        want = [
            re.fullmatch(f"(?:{pattern})", w) is not None
            for w in col.to_pylist()
        ]
        assert got == want, pattern


def test_typed_unsupported_pattern_error():
    col = _col(["x"])
    for bad in [r"(a", r"a{1,999}", r"mid^dle"]:
        with pytest.raises(rx.UnsupportedPatternError):
            rx.contains_re(col, bad)
    with pytest.raises(rx.UnsupportedPatternError):
        rx.compile_re(r"(?:a|b)*a(?:a|b){12}")  # DFA state overflow
    # span ops can't distribute anchors: typed error, not wrong results
    with pytest.raises(rx.UnsupportedPatternError):
        rx.replace_re(col, r"^a|b", "X")


def _gen_pattern(rng):
    """Random pattern clamped to the documented subset."""
    atoms = [
        "a", "b", "0", "_", ".", r"\d", r"\w", r"\s", "[ab]", "[^a]",
        "[a-c]", r"\.",
    ]
    quants = ["", "", "", "*", "+", "?", "{2}", "{1,3}"]

    def branch():
        k = int(rng.integers(1, 5))
        out = []
        for _ in range(k):
            a = atoms[int(rng.integers(0, len(atoms)))]
            q = quants[int(rng.integers(0, len(quants)))]
            if q and int(rng.integers(0, 4)) == 0:
                a = f"(?:{a}{atoms[int(rng.integers(0, len(atoms)))]})"
            out.append(a + q)
        return "".join(out)

    nb = int(rng.integers(1, 4))
    branches = [branch() for _ in range(nb)]
    # per-branch anchors, like Java scopes them
    branches = [
        ("^" if int(rng.integers(0, 5)) == 0 else "")
        + b
        + ("$" if int(rng.integers(0, 5)) == 0 else "")
        for b in branches
    ]
    return "|".join(branches)


def test_differential_corpus_vs_python_re():
    """200 generated patterns x 60 random strings: the DFA engine must
    agree with Python re.search on every (pattern, string) pair inside
    the documented subset. No '\\n' in the corpus: Python's '$' matches
    before a trailing newline, ours means hard string end."""
    rng = np.random.default_rng(20260730)
    strings = _rand_strings(rng, n=60, alphabet="ab01 _.", max_len=10)
    col = _col(strings)
    checked = 0
    for _ in range(200):
        pattern = _gen_pattern(rng)
        try:
            got = np.asarray(rx.contains_re(col, pattern).data).tolist()
        except rx.UnsupportedPatternError:
            continue  # outside the enforced subset: allowed to refuse
        want = [re.search(pattern, s) is not None for s in strings]
        assert got == want, f"divergence for {pattern!r}"
        checked += 1
    assert checked > 150  # the subset must actually cover the grammar
