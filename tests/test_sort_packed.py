"""Packed ORDER BY (ops/sort_packed.py) vs sort_table: randomized
equivalence incl. stability, descending, string payloads, fallbacks."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.sort import SortKey, sort_table
from spark_rapids_jni_tpu.ops.sort_packed import sort_table_packed


def _cols(t):
    return [c.to_pylist() for c in t.columns]


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("ascending", [True, False])
def test_randomized_equivalence(seed, ascending):
    rng = np.random.default_rng(seed)
    n = 3000
    k = rng.integers(-500, 500, n, dtype=np.int64)
    v = rng.integers(-9, 9, n, dtype=np.int64)
    vv = rng.random(n) > 0.2
    s = ["s%d" % (x % 13) for x in rng.integers(0, 100, n)]
    t = Table(
        [
            Column.from_numpy(k),
            Column.from_numpy(v, validity=vv),
            Column.from_strings(s),
        ],
        ["k", "v", "s"],
    )
    key = [SortKey("k", ascending=ascending)]
    got = sort_table_packed(t, key)
    assert got is not None
    want = sort_table(t, key)
    assert got.names == want.names
    # full equality, column by column — duplicates keys make this a
    # STABILITY check too (both must keep original order within ties)
    assert _cols(got) == _cols(want)


def test_timestamp_key_and_reconstruction():
    from spark_rapids_jni_tpu import dtype as dt
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    n = 500
    days = rng.integers(0, 20_000, n).astype(np.int32)
    t = Table(
        [
            Column(jnp.asarray(days), dt.TIMESTAMP_DAYS, None),
            Column.from_numpy(np.arange(n, dtype=np.int64)),
        ],
        ["d", "v"],
    )
    got = sort_table_packed(t, [SortKey("d")])
    assert got is not None
    want = sort_table(t, [SortKey("d")])
    assert _cols(got) == _cols(want)
    assert got.columns[0].dtype.id == dt.TypeId.TIMESTAMP_DAYS


def test_declines():
    n = 64
    k = np.arange(n, dtype=np.int64)
    valid = np.ones(n, bool)
    valid[0] = False
    t_null = Table([Column.from_numpy(k, validity=valid)], ["k"])
    assert sort_table_packed(t_null, [SortKey("k")]) is None
    t_wide = Table(
        [Column.from_numpy(np.array([0, 1 << 62] * 32, np.int64))], ["k"]
    )
    assert sort_table_packed(t_wide, [SortKey("k")]) is None
    # multi-key shapes are SUPPORTED since the composite-field
    # generalization (TestMultiKey); only duplicate columns decline
    t2 = Table(
        [Column.from_numpy(k),
         Column.from_numpy((k * 3 % 7).astype(np.int64))],
        ["a", "b"],
    )
    got = sort_table_packed(t2, [SortKey("a"), SortKey("b")])
    assert got is not None
    assert _cols(got) == _cols(sort_table(t2, [SortKey("a"), SortKey("b")]))


class TestMultiKey:
    @pytest.mark.parametrize(
        "dirs", [(True, True), (True, False), (False, True)]
    )
    def test_two_keys_mixed_directions(self, dirs):
        rng = np.random.default_rng(21)
        n = 2500
        a = rng.integers(-30, 30, n, dtype=np.int64)
        b = rng.integers(0, 100, n, dtype=np.int64)
        v = rng.integers(-9, 9, n, dtype=np.int64)
        t = Table(
            [Column.from_numpy(a), Column.from_numpy(b),
             Column.from_numpy(v)],
            ["a", "b", "v"],
        )
        keys = [SortKey("a", ascending=dirs[0]),
                SortKey("b", ascending=dirs[1])]
        got = sort_table_packed(t, keys)
        assert got is not None
        want = sort_table(t, keys)
        assert _cols(got) == _cols(want)

    def test_three_keys_with_string_payload(self):
        rng = np.random.default_rng(22)
        n = 1200
        t = Table(
            [
                Column.from_numpy(rng.integers(0, 12, n, dtype=np.int64)),
                Column.from_numpy(rng.integers(-5, 5, n, dtype=np.int64)),
                Column.from_numpy(rng.integers(0, 40, n, dtype=np.int64)),
                Column.from_strings(
                    ["p%d" % x for x in rng.integers(0, 30, n)]
                ),
            ],
            ["a", "b", "c", "s"],
        )
        keys = [SortKey("a"), SortKey("b", ascending=False), SortKey("c")]
        got = sort_table_packed(t, keys)
        assert got is not None
        want = sort_table(t, keys)
        assert _cols(got) == _cols(want)

    def test_duplicate_key_column_declines(self):
        k = np.arange(32, dtype=np.int64)
        t = Table([Column.from_numpy(k)], ["k"])
        assert sort_table_packed(
            t, [SortKey("k"), SortKey("k", ascending=False)]
        ) is None


def test_gather_arm_matches_sort_arm():
    from spark_rapids_jni_tpu.ops.sort_packed import sort_table_packed

    rng = np.random.default_rng(41)
    n = 3000
    k = rng.integers(-500, 500, n, dtype=np.int64)
    v = rng.standard_normal(n)
    w = rng.integers(0, 9, n, dtype=np.int64)
    kv = np.ones(n, dtype=bool)
    kv[::13] = False
    t = Table(
        [
            Column.from_numpy(k),
            Column.from_numpy(v, validity=kv),
            Column.from_numpy(w),
        ],
        ["k", "v", "w"],
    )
    a = sort_table_packed(t, [SortKey("k")])
    b = sort_table_packed(t, [SortKey("k")], values_via="gather")
    assert a is not None and b is not None
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(
            np.asarray(ca.data), np.asarray(cb.data)
        )
        if ca.validity is not None:
            np.testing.assert_array_equal(
                np.asarray(ca.validity), np.asarray(cb.validity)
            )
