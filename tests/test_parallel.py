"""Phase-3 tests: shuffle exchange + distributed ops on the 8-device CPU mesh."""

import jax
import numpy as np
import pytest

import spark_rapids_jni_tpu as srt
from spark_rapids_jni_tpu import ops, parallel
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


@pytest.fixture
def mesh():
    return parallel.make_mesh(8)


class TestSharding:
    def test_shard_and_replicate(self, mesh, rng):
        t = Table.from_pydict(
            {"k": rng.integers(0, 100, 800, dtype=np.int64)}
        )
        sh = parallel.shard_table(t, mesh)
        assert parallel.local_shards(sh) == 8
        rep = parallel.replicate_table(t, mesh)
        np.testing.assert_array_equal(
            np.asarray(rep["k"].data), np.asarray(t["k"].data)
        )

    def test_uneven_rejected(self, mesh):
        t = Table.from_pydict({"k": np.arange(13, dtype=np.int64)})
        with pytest.raises(ValueError):
            parallel.shard_table(t, mesh)


class TestShuffle:
    def test_all_rows_arrive_at_hash_owner(self, mesh, rng):
        n = 1600
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 1000, n, dtype=np.int64),
                "v": rng.standard_normal(n),
            }
        )
        out, occ, overflow = parallel.shuffle_table(
            t, ["k"], mesh, capacity=n
        )
        assert int(np.asarray(overflow).max()) <= 0
        occ_np = np.asarray(occ)
        assert occ_np.sum() == n  # every row arrived exactly once
        # rows on each device hash to that device
        got_k = np.asarray(out["k"].data)[occ_np]
        got_v = np.asarray(out["v"].to_numpy())[occ_np]
        # multiset equality with the input
        src = sorted(zip(np.asarray(t["k"].data).tolist(),
                         np.asarray(t["v"].to_numpy()).tolist()))
        dst = sorted(zip(got_k.tolist(), got_v.tolist()))
        assert src == dst
        # placement: every received row sits on the device its key hashes to
        from spark_rapids_jni_tpu.ops.partition import partition_ids_hash

        part_of_key = {
            int(k): int(p)
            for k, p in zip(
                np.asarray(t["k"].data),
                np.asarray(partition_ids_hash(t, ["k"], 8)),
            )
        }
        occ_dev = occ_np.reshape(8, -1)
        keys_dev = np.asarray(out["k"].data).reshape(8, -1)
        for dev in range(8):
            for k in keys_dev[dev][occ_dev[dev]]:
                assert part_of_key[int(k)] == dev

    def test_placement_matches_spark_hash(self, mesh, rng):
        from spark_rapids_jni_tpu.ops.partition import partition_ids_hash

        n = 800
        t = Table.from_pydict({"k": rng.integers(0, 50, n, dtype=np.int64)})
        out, occ, _ = parallel.shuffle_table(t, ["k"], mesh, capacity=n)
        occ_np = np.asarray(occ).reshape(8, -1)
        keys = np.asarray(out["k"].data).reshape(8, -1)
        want_part = np.asarray(partition_ids_hash(t, ["k"], 8))
        for dev in range(8):
            ks = keys[dev][occ_np[dev]]
            for k in ks:
                # this key's Spark partition must be this device
                idx = np.asarray(t["k"].data) == k
                assert (want_part[idx] == dev).all()


class TestLosslessShuffle:
    """VERDICT r1 item 4: no silent row loss, ever."""

    def test_undersized_capacity_raises(self, mesh, rng):
        n = 800
        # every row carries the same key -> one (src, dst) pair gets all
        # 100 rows of each source; capacity 16 is hopeless
        t = Table.from_pydict(
            {"k": np.full(n, 7, dtype=np.int64)}
        )
        with pytest.raises(parallel.ShuffleOverflowError):
            parallel.shuffle_table(t, ["k"], mesh, capacity=16)

    def test_auto_planned_capacity_is_exact(self, mesh, rng):
        n = 800
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 50, n, dtype=np.int64),
                "v": rng.integers(-100, 100, n, dtype=np.int64),
            }
        )
        out, occ, overflow = parallel.shuffle_table(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        assert int(np.asarray(occ).sum()) == n

    def test_max_skew_single_key_lossless(self, mesh, rng):
        """Maximal skew: every row hashes to ONE partition; the planned
        exchange still delivers every row and the groupby is exact."""
        n = 1600
        t = Table.from_pydict(
            {
                "k": np.full(n, 3, dtype=np.int64),
                "v": rng.integers(-100, 100, n, dtype=np.int64),
            }
        )
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            mesh,
        )
        assert int(np.asarray(overflow).max()) <= 0
        counts = np.asarray(ngroups)
        assert counts.sum() == 1  # one global group
        d = int(np.argmax(counts))
        sums = np.asarray(agg["sum_v"].data).reshape(8, -1)
        cnts = np.asarray(agg["count_v"].data).reshape(8, -1)
        assert int(sums[d, 0]) == int(np.asarray(t["v"].data).sum())
        assert int(cnts[d, 0]) == n

    def test_zipf_skew_groupby_lossless(self, mesh, rng):
        """Heavy-tailed keys (zipf): planning must absorb the hot key."""
        n = 4000
        k = np.minimum(rng.zipf(1.3, n), 1000).astype(np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        t = Table.from_pydict({"k": k, "v": v})
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("v", "sum")], mesh,
        )
        assert int(np.asarray(overflow).max()) <= 0
        got = {}
        ks = np.asarray(agg["k"].data).reshape(8, -1)
        sums = np.asarray(agg["sum_v"].data).reshape(8, -1)
        counts = np.asarray(ngroups)
        for d in range(8):
            for i in range(counts[d]):
                got[int(ks[d, i])] = int(sums[d, i])
        want = {int(u): int(v[k == u].sum()) for u in np.unique(k)}
        assert got == want

    def test_join_auto_sized_output(self, mesh, rng):
        """out_capacity=None two-phase sizing yields the exact join."""
        pd = pytest.importorskip("pandas")
        nl, nr = 320, 320
        lk = rng.integers(0, 10, nl, dtype=np.int64)
        rk = rng.integers(0, 10, nr, dtype=np.int64)
        left = Table.from_pydict(
            {"k": lk, "lv": np.arange(nl, dtype=np.int64)}
        )
        right = Table.from_pydict(
            {"k": rk, "rv": np.arange(nr, dtype=np.int64)}
        )
        out, counts, lov, rov = parallel.distributed_inner_join(
            left, right, ["k"], mesh,
        )
        want = pd.merge(
            pd.DataFrame({"k": lk, "lv": np.arange(nl)}),
            pd.DataFrame({"k": rk, "rv": np.arange(nr)}),
            on="k",
        )
        assert int(np.asarray(counts).sum()) == len(want)
        kcol = np.asarray(out["k"].data)
        kval = np.asarray(out["k"].validity)
        lv = np.asarray(out["lv"].data)
        rv = np.asarray(out["rv"].data)
        got = sorted(
            (int(kcol[i]), int(lv[i]), int(rv[i]))
            for i in range(len(kcol))
            if kval[i]
        )
        expect = sorted(
            zip(want["k"].tolist(), want["lv"].tolist(), want["rv"].tolist())
        )
        assert got == expect

    def test_undersized_groups_per_device_raises(self, mesh, rng):
        n = 800
        # ~100 distinct keys all hashing across devices; 2 segments is
        # hopeless on whichever device owns the most keys
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 100, n, dtype=np.int64),
                "v": rng.integers(-10, 10, n, dtype=np.int64),
            }
        )
        with pytest.raises(parallel.GroupOverflowError):
            parallel.distributed_groupby(
                t, ["k"], [GroupbyAgg("v", "sum")], mesh,
                groups_per_device=2,
            )

    def test_bad_on_overflow_rejected(self, mesh, rng):
        t = Table.from_pydict({"k": np.arange(80, dtype=np.int64)})
        with pytest.raises(ValueError):
            parallel.shuffle_table(t, ["k"], mesh, on_overflow="allowed")

    def test_join_undersized_output_raises(self, mesh, rng):
        nl = nr = 320
        left = Table.from_pydict(
            {"k": np.full(nl, 1, dtype=np.int64),
             "lv": np.arange(nl, dtype=np.int64)}
        )
        right = Table.from_pydict(
            {"k": np.full(nr, 1, dtype=np.int64),
             "rv": np.arange(nr, dtype=np.int64)}
        )
        # 320*320 = 102400 matches on one device; ocap 64 is hopeless
        with pytest.raises(parallel.JoinOverflowError):
            parallel.distributed_inner_join(
                left, right, ["k"], mesh, out_capacity=64,
            )


class TestDistributedOps:
    def test_distributed_groupby_matches_local(self, mesh, rng):
        n = 1600
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 30, n, dtype=np.int64),
                "v": rng.integers(-100, 100, n, dtype=np.int64),
            }
        )
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            mesh, capacity=n,
        )
        assert int(np.asarray(overflow).max()) <= 0
        counts = np.asarray(ngroups)
        # collect per-device groups
        got = {}
        ks = np.asarray(agg["k"].data).reshape(8, -1)
        kvalid = np.asarray(agg["k"].validity).reshape(8, -1)
        sums = np.asarray(agg["sum_v"].data).reshape(8, -1)
        cnts = np.asarray(agg["count_v"].data).reshape(8, -1)
        for d in range(8):
            g = counts[d]
            for i in range(g):
                assert kvalid[d, i]
                got[int(ks[d, i])] = (int(sums[d, i]), int(cnts[d, i]))
        kk = np.asarray(t["k"].data)
        vv = np.asarray(t["v"].data)
        want = {
            int(u): (int(vv[kk == u].sum()), int((kk == u).sum()))
            for u in np.unique(kk)
        }
        assert got == want

    def test_distributed_join_matches_local(self, mesh, rng):
        pd = pytest.importorskip("pandas")
        nl, nr = 800, 640
        lk = rng.integers(0, 40, nl, dtype=np.int64)
        rk = rng.integers(0, 40, nr, dtype=np.int64)
        left = Table(
            [
                Column.from_numpy(lk),
                Column.from_numpy(np.arange(nl, dtype=np.int64)),
            ],
            ["k", "lv"],
        )
        right = Table(
            [
                Column.from_numpy(rk),
                Column.from_numpy(np.arange(nr, dtype=np.int64)),
            ],
            ["k", "rv"],
        )
        out, counts, lov, rov = parallel.distributed_inner_join(
            left, right, ["k"], mesh, capacity=nl + nr,
            out_capacity=8 * (nl + nr),
        )
        assert int(np.asarray(lov).max()) <= 0
        assert int(np.asarray(rov).max()) <= 0
        want = pd.merge(
            pd.DataFrame({"k": lk, "lv": np.arange(nl)}),
            pd.DataFrame({"k": rk, "rv": np.arange(nr)}),
            on="k",
        )
        total = int(np.asarray(counts).sum())
        assert total == len(want)
        # collect valid rows across devices
        kcol = np.asarray(out["k"].data)
        kval = np.asarray(out["k"].validity)
        lv = np.asarray(out["lv"].data)
        rv = np.asarray(out["rv"].data)
        got = sorted(
            (int(kcol[i]), int(lv[i]), int(rv[i]))
            for i in range(len(kcol))
            if kval[i]
        )
        expect = sorted(
            zip(want["k"].tolist(), want["lv"].tolist(), want["rv"].tolist())
        )
        assert got == expect


class TestCompactShuffle:
    """Round-3: ragged-compact exchange — received buffers scale with the
    REAL per-destination row totals, not P x the hottest (src,dst) pair."""

    def _per_partition_keys(self, num=8):
        """One key value per partition (found by probing the Spark hash)."""
        from spark_rapids_jni_tpu.ops.partition import partition_ids_hash

        found = {}
        k = 0
        while len(found) < num and k < 10_000:
            t = Table.from_pydict({"k": np.asarray([k], dtype=np.int64)})
            p = int(np.asarray(partition_ids_hash(t, ["k"], num))[0])
            found.setdefault(p, k)
            k += 1
        assert len(found) == num
        return found

    def test_correlated_skew_buffer_is_compact(self, mesh, rng):
        """Sorted/correlated input: each source's rows all hash to ONE
        destination. Per-pair max = n_local, so the dense exchange would
        materialize 8 * n_local rows per device; the compact exchange
        must stay at ~n_local."""
        from spark_rapids_jni_tpu.parallel.shuffle import _round_capacity

        key_of = self._per_partition_keys(8)
        n_local = 200
        ks = np.concatenate(
            [np.full(n_local, key_of[d], dtype=np.int64) for d in range(8)]
        )
        t = Table.from_pydict(
            {"k": ks, "v": np.arange(len(ks), dtype=np.int64)}
        )
        out, occ, overflow = parallel.shuffle_table_compact(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        per_dev = out["k"].data.shape[0] // 8
        # the whole point: buffer ∝ actual received rows, not 8x
        assert per_dev <= _round_capacity(n_local)
        occ_np = np.asarray(occ)
        assert occ_np.sum() == len(ks)  # lossless

    def test_compact_multiset_and_placement(self, mesh, rng):
        from spark_rapids_jni_tpu.ops.partition import partition_ids_hash

        n = 1600
        t = Table.from_pydict(
            {
                "k": rng.integers(0, 1000, n, dtype=np.int64),
                "v": rng.standard_normal(n),
            }
        )
        out, occ, overflow = parallel.shuffle_table_compact(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        occ_np = np.asarray(occ)
        assert occ_np.sum() == n
        got_k = np.asarray(out["k"].data)[occ_np]
        got_v = np.asarray(out["v"].to_numpy())[occ_np]
        src = sorted(zip(np.asarray(t["k"].data).tolist(),
                         np.asarray(t["v"].to_numpy()).tolist()))
        dst = sorted(zip(got_k.tolist(), got_v.tolist()))
        assert src == dst
        part_of_key = {
            int(k): int(p)
            for k, p in zip(
                np.asarray(t["k"].data),
                np.asarray(partition_ids_hash(t, ["k"], 8)),
            )
        }
        per_dev = out["k"].data.shape[0] // 8
        occ_dev = occ_np.reshape(8, per_dev)
        keys_dev = np.asarray(out["k"].data).reshape(8, per_dev)
        for dev in range(8):
            for k in keys_dev[dev][occ_dev[dev]]:
                assert part_of_key[int(k)] == dev

    def test_compact_undersized_raises(self, mesh):
        n = 800
        t = Table.from_pydict({"k": np.full(n, 7, dtype=np.int64)})
        with pytest.raises(parallel.ShuffleOverflowError):
            parallel.shuffle_table_compact(t, ["k"], mesh, out_size=16)

    def test_ragged_impl_lowers_on_mesh(self, mesh, rng):
        """XLA:CPU cannot EXECUTE ragged-all-to-all, but the TPU impl
        must at least trace+lower on the virtual mesh so the real-chip
        path is structurally exercised in the no-accelerator tier."""
        import jax
        from jax.sharding import PartitionSpec as P
        from spark_rapids_jni_tpu.parallel.mesh import shard_map
        from spark_rapids_jni_tpu.parallel.shuffle import (
            exchange_ragged_by_hash,
            partition_counts,
        )

        n = 800
        t = Table.from_pydict(
            {"k": rng.integers(0, 50, n, dtype=np.int64)}
        )
        sh = parallel.shard_table(t, mesh)
        counts = parallel.partition_counts(sh, ["k"], mesh)

        def run(local, C):
            out, occ, ov = exchange_ragged_by_hash(
                local, ["k"], C, 256, impl="ragged"
            )
            return out, occ, ov[None]

        fn = shard_map(
            run, mesh=mesh, in_specs=(P("shuffle"), P()),
            out_specs=P("shuffle"), check_vma=False,
        )
        jax.jit(fn).lower(sh, counts)  # must not raise

    def test_distributed_groupby_compact_zipf(self, mesh, rng):
        """The r2 OOM shape: zipf skew through the NEW compact path."""
        n = 4000
        k = np.minimum(rng.zipf(1.3, n), 1000).astype(np.int64)
        v = rng.integers(-100, 100, n, dtype=np.int64)
        t = Table.from_pydict({"k": k, "v": v})
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("v", "sum")], mesh,
        )
        assert int(np.asarray(overflow).max()) <= 0
        got = {}
        per_dev = agg["k"].data.shape[0] // 8
        ks = np.asarray(agg["k"].data).reshape(8, per_dev)
        sums = np.asarray(agg["sum_v"].data).reshape(8, per_dev)
        counts = np.asarray(ngroups)
        for d in range(8):
            for i in range(counts[d]):
                got[int(ks[d, i])] = int(sums[d, i])
        want = {int(u): int(v[k == u].sum()) for u in np.unique(k)}
        assert got == want


class TestDistributedSort:
    """Round-3: distributed global ORDER BY (sample -> range partition ->
    local sort); reading devices in mesh order yields the total order."""

    def _collect(self, out, occ, col="k"):
        per_dev = out[col].data.shape[0] // 8
        vals = np.asarray(out[col].data).reshape(8, per_dev)
        occ_np = np.asarray(occ).reshape(8, per_dev)
        flat = []
        for d in range(8):
            flat.extend(vals[d][occ_np[d]].tolist())
        return flat

    def test_total_order_ints(self, mesh, rng):
        n = 1600
        k = rng.integers(-1000, 1000, n, dtype=np.int64)
        t = Table.from_pydict({"k": k, "v": np.arange(n, dtype=np.int64)})
        out, occ, overflow = parallel.distributed_sort(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        got = self._collect(out, occ)
        assert got == sorted(k.tolist())

    def test_total_order_descending(self, mesh, rng):
        from spark_rapids_jni_tpu.ops.sort import SortKey

        n = 800
        k = rng.integers(0, 500, n, dtype=np.int64)
        t = Table.from_pydict({"k": k})
        out, occ, overflow = parallel.distributed_sort(
            t, [SortKey("k", ascending=False)], mesh
        )
        got = self._collect(out, occ)
        assert got == sorted(k.tolist(), reverse=True)

    def test_skewed_distribution(self, mesh, rng):
        """Heavy duplication: range partitioning must still deliver every
        row (compact buffers absorb the hot range)."""
        n = 2400
        k = np.concatenate([
            np.full(n // 2, 7, dtype=np.int64),
            rng.integers(-100, 100, n - n // 2).astype(np.int64),
        ])
        t = Table.from_pydict({"k": k})
        out, occ, overflow = parallel.distributed_sort(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        got = self._collect(out, occ)
        assert got == sorted(k.tolist())

    def test_payload_rides_along(self, mesh, rng):
        n = 800
        k = rng.permutation(n).astype(np.int64)
        t = Table.from_pydict({"k": k, "v": k * 10})
        out, occ, _ = parallel.distributed_sort(t, ["k"], mesh)
        ks = self._collect(out, occ, "k")
        vs = self._collect(out, occ, "v")
        assert vs == [x * 10 for x in ks]


class TestDistributedDecimal128:
    def test_distributed_groupby_decimal128(self, mesh, rng):
        """Two-u64-limb columns ((n, 2) buffers) ride the ragged-compact
        exchange and the exact mod-2^128 segment sums end-to-end."""
        from spark_rapids_jni_tpu.ops.int128 import from_py_ints

        n = 800
        k = rng.integers(0, 20, n, dtype=np.int64)
        vals = [int(v) * 10**25 for v in rng.integers(-50, 50, n)]
        from spark_rapids_jni_tpu import dtype as dt

        t = Table(
            [
                Column.from_numpy(k),
                Column.from_numpy(
                    from_py_ints(vals), dtype=dt.decimal128(-30)
                ),
            ],
            ["k", "d"],
        )
        agg, ngroups, overflow = parallel.distributed_groupby(
            t, ["k"], [GroupbyAgg("d", "sum")], mesh
        )
        assert int(np.asarray(overflow).max()) <= 0
        per_dev = agg["k"].data.shape[0] // 8
        counts = np.asarray(ngroups)
        got = {}
        ks = np.asarray(agg["k"].data).reshape(8, per_dev)
        from spark_rapids_jni_tpu.ops.int128 import to_py_ints

        sums_limbs = np.asarray(agg["sum_d"].data).reshape(8, per_dev, 2)
        for d in range(8):
            sums = to_py_ints(sums_limbs[d])
            for i in range(counts[d]):
                got[int(ks[d, i])] = sums[i]
        want = {}
        for key, v in zip(k.tolist(), vals):
            want[key] = want.get(key, 0) + v
        assert got == want


class TestDistributedSortStrings:
    def test_total_order_string_keys(self, mesh, rng):
        """Multi-word order keys (padded byte matrix + length tiebreak)
        through the sample -> range-partition -> local-sort pipeline."""
        words = [f"w{i:03d}" for i in range(40)]
        n = 800
        vals = [words[i] for i in rng.integers(0, 40, n)]
        t = Table(
            [
                Column.from_strings(vals),
                Column.from_numpy(np.arange(n, dtype=np.int64)),
            ],
            ["k", "v"],
        )
        out, occ, overflow = parallel.distributed_sort(t, ["k"], mesh)
        assert int(np.asarray(overflow).max()) <= 0
        per_dev = out["k"].data.shape[0] // 8
        occ_np = np.asarray(occ).reshape(8, per_dev)
        mats = np.asarray(out["k"].data).reshape(8, per_dev, -1)
        lens = np.asarray(out["k"].lengths).reshape(8, per_dev)
        got = []
        for d in range(8):
            for i in range(per_dev):
                if occ_np[d, i]:
                    got.append(
                        bytes(mats[d, i, : lens[d, i]]).decode()
                    )
        assert got == sorted(vals)


class TestBroadcastJoin:
    def test_matches_host_oracle(self, mesh, rng):
        import pandas as pd

        n_fact, n_dim = 4_000, 64
        fk = rng.integers(0, 100, n_fact, dtype=np.int64)
        fv = rng.integers(-10, 10, n_fact, dtype=np.int64)
        dk = rng.permutation(100)[:n_dim].astype(np.int64)
        dv = rng.integers(0, 5, n_dim, dtype=np.int64)
        fact = Table(
            [Column.from_numpy(fk), Column.from_numpy(fv)], ["k", "fv"]
        )
        dim = Table(
            [Column.from_numpy(dk), Column.from_numpy(dv)], ["k", "dv"]
        )
        out, counts = parallel.broadcast_inner_join(
            fact, dim, ["k"], mesh
        )
        # collect valid rows from each device's prefix
        per_dev = np.asarray(counts)
        k_all = np.asarray(out["k"].data)
        fv_all = np.asarray(out["fv"].data)
        dv_all = np.asarray(out["dv"].data)
        cap = k_all.shape[0] // 8
        got = []
        for d in range(8):
            c = int(per_dev[d])
            s = d * cap
            got.extend(
                zip(k_all[s : s + c], fv_all[s : s + c], dv_all[s : s + c])
            )
        want_df = pd.merge(
            pd.DataFrame({"k": fk, "fv": fv}),
            pd.DataFrame({"k": dk, "dv": dv}),
            on="k",
        )
        want = list(
            zip(want_df["k"].to_numpy(), want_df["fv"].to_numpy(),
                want_df["dv"].to_numpy())
        )
        assert sorted(got) == sorted(want)

    def test_null_keys_never_match(self, mesh):
        fk = Column.from_numpy(
            np.array([1, 2, 3, 4] * 8, dtype=np.int64),
            validity=np.array([True, False, True, True] * 8),
        )
        fact = Table([fk], ["k"])
        dim = Table.from_pydict({"k": [2, 3]})
        out, counts = parallel.broadcast_inner_join(fact, dim, ["k"], mesh)
        # valid fact keys are {1, 3, 4} (the 2s are null); dim has {2, 3},
        # so only the eight 3s match — null keys never join
        assert int(np.asarray(counts).sum()) == 8

    def test_undersized_capacity_raises(self, mesh):
        fact = Table.from_pydict({"k": [1] * 64})
        dim = Table.from_pydict({"k": [1, 1, 1]})
        with pytest.raises(parallel.distributed.JoinOverflowError):
            parallel.broadcast_inner_join(
                fact, dim, ["k"], mesh, out_capacity=2
            )


class TestDistributedOuterAndMembership:
    def _tables(self, rng, n=4_000, m=600):
        import pandas as pd

        lk = rng.integers(0, 200, n, dtype=np.int64)
        lv = rng.integers(-9, 9, n, dtype=np.int64)
        rk = rng.integers(100, 300, m, dtype=np.int64)  # partial overlap
        rv = rng.integers(0, 5, m, dtype=np.int64)
        left = Table(
            [Column.from_numpy(lk), Column.from_numpy(lv)], ["k", "lv"]
        )
        right = Table(
            [Column.from_numpy(rk), Column.from_numpy(rv)], ["k", "rv"]
        )
        ldf = pd.DataFrame({"k": lk, "lv": lv})
        rdf = pd.DataFrame({"k": rk, "rv": rv})
        return left, right, ldf, rdf

    def test_left_join_oracle(self, mesh, rng):
        import pandas as pd

        left, right, ldf, rdf = self._tables(rng)
        out, counts, lov, rov = parallel.distributed_left_join(
            left, right, ["k"], mesh
        )
        per_dev = np.asarray(counts)
        cap = out.row_count // 8
        got = []
        kk = np.asarray(out["k"].data)
        lvv = np.asarray(out["lv"].data)
        rvv = out["rv"].to_pylist()
        rvalid = (
            np.ones(out.row_count, bool)
            if out["rv"].validity is None
            else np.asarray(out["rv"].validity)
        )
        for d in range(8):
            s = d * cap
            for i in range(s, s + int(per_dev[d])):
                got.append(
                    (int(kk[i]), int(lvv[i]),
                     int(rvv[i]) if rvalid[i] else None)
                )
        want_df = ldf.merge(rdf, on="k", how="left")
        want = [
            (int(r.k), int(r.lv),
             None if pd.isna(r.rv) else int(r.rv))
            for r in want_df.itertuples()
        ]
        assert sorted(got, key=str) == sorted(want, key=str)

    def test_semi_anti_oracle(self, mesh, rng):
        left, right, ldf, rdf = self._tables(rng)
        rkeys = set(rdf["k"].tolist())
        want_semi = sorted(
            (int(k), int(v))
            for k, v in zip(ldf["k"], ldf["lv"]) if int(k) in rkeys
        )
        want_anti = sorted(
            (int(k), int(v))
            for k, v in zip(ldf["k"], ldf["lv"]) if int(k) not in rkeys
        )
        sh, occ, _, _ = parallel.distributed_semi_join(
            left, right, ["k"], mesh
        )
        occ_h = np.asarray(occ)
        got_semi = sorted(
            zip(
                np.asarray(sh["k"].data)[occ_h].tolist(),
                np.asarray(sh["lv"].data)[occ_h].tolist(),
            )
        )
        assert got_semi == want_semi
        sh2, occ2, _, _ = parallel.distributed_anti_join(
            left, right, ["k"], mesh
        )
        occ2_h = np.asarray(occ2)
        got_anti = sorted(
            zip(
                np.asarray(sh2["k"].data)[occ2_h].tolist(),
                np.asarray(sh2["lv"].data)[occ2_h].tolist(),
            )
        )
        assert got_anti == want_anti

    def test_left_join_null_keys_emit(self, mesh):
        lk = Column.from_numpy(
            np.array([1, 2] * 16, dtype=np.int64),
            validity=np.array([True, False] * 16),
        )
        left = Table([lk], ["k"])
        # exactly one right row carries the overlapping key 1
        right = Table.from_pydict({"k": [1, 30, 40, 50, 60, 70, 80, 90]})
        out, counts, _, _ = parallel.distributed_left_join(
            left, right, ["k"], mesh
        )
        # every left row emits exactly once: 16 matches + 16 null-key rows
        assert int(np.asarray(counts).sum()) == 32


class TestDistributedDistinct:
    def test_matches_host_oracle(self, mesh, rng):
        n = 1600
        k = rng.integers(0, 60, n, dtype=np.int64)
        s = ["tag%d" % (v % 7) for v in rng.integers(0, 100, n)]
        t = Table(
            [Column.from_numpy(k), Column.from_strings(s)], ["k", "s"]
        )
        out, counts, overflow = parallel.distributed_distinct(
            t, ["k", "s"], mesh
        )
        assert int(np.asarray(overflow).max()) <= 0
        per_dev = np.asarray(counts)
        got = set()
        kk = np.asarray(out["k"].data)
        ss = out["s"].to_pylist()
        cap = out.row_count // 8
        for d in range(8):
            base = d * cap
            for i in range(base, base + int(per_dev[d])):
                got.add((int(kk[i]), ss[i]))
        want = set(zip(k.tolist(), s))
        assert got == want
