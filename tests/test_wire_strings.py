"""STRING columns + new ops over the native wire (runtime_bridge).

The cudf JNI marshals string columns as Arrow offsets+bytes; the TPU
wire uses the same layout (runtime_bridge._column_from_wire). These
tests drive the exact byte-level path a native/JNI caller uses."""

import json

import numpy as np
import pytest

from spark_rapids_jni_tpu import dtype as dt
from spark_rapids_jni_tpu import runtime_bridge as rb


def _string_wire(values):
    """(data bytes, valid bytes | None) in the Arrow offsets+bytes wire."""
    raw = [
        (v.encode() if isinstance(v, str) else b"") for v in values
    ]
    offs = np.zeros(len(values) + 1, np.int32)
    np.cumsum([len(r) for r in raw], out=offs[1:])
    data = offs.tobytes() + b"".join(raw)
    if any(v is None for v in values):
        valid = bytes(0 if v is None else 1 for v in values)
    else:
        valid = None
    return data, valid


def _decode_strings(data, valid, n):
    offs = np.frombuffer(data, np.int32, n + 1)
    raw = data[4 * (n + 1):]
    out = []
    vmask = (
        [True] * n if valid is None else [b == 1 for b in valid]
    )
    for i in range(n):
        out.append(
            raw[offs[i]:offs[i + 1]].decode() if vmask[i] else None
        )
    return out


S = int(dt.TypeId.STRING)
I64 = int(dt.TypeId.INT64)


def test_string_round_trip_via_sort():
    values = ["pear", None, "apple", "fig", ""]
    data, valid = _string_wire(values)
    op = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
    out_t, out_s, out_d, out_v, n = rb.table_op_wire(
        op, [S], [0], [data], [valid], len(values)
    )
    assert out_t == [S] and n == 5
    got = _decode_strings(out_d[0], out_v[0], n)
    # nulls first (Spark ascending default), then byte order
    assert got == [None, "", "apple", "fig", "pear"]


def test_rlike_filter_over_wire():
    values = ["id=42", "nope", "id=7x", None, "xid=9"]
    data, valid = _string_wire(values)
    k = np.arange(5, dtype=np.int64)
    op = json.dumps({"op": "rlike", "column": 1, "pattern": r"^id=\d+"})
    out_t, out_s, out_d, out_v, n = rb.table_op_wire(
        op, [I64, S], [0, 0], [k.tobytes(), data], [None, valid], 5
    )
    assert n == 2
    keys = np.frombuffer(out_d[0], np.int64, n)
    assert keys.tolist() == [0, 2]


def test_string_cast_over_wire():
    values = ["12", "-7", "oops", None]
    data, valid = _string_wire(values)
    op = json.dumps({"op": "cast", "column": 0, "type_id": I64})
    out_t, _, out_d, out_v, n = rb.table_op_wire(
        op, [S], [0], [data], [valid], 4
    )
    assert out_t == [I64] and n == 4
    vals = np.frombuffer(out_d[0], np.int64, 4)
    vmask = list(out_v[0])
    assert vals[0] == 12 and vals[1] == -7
    assert vmask == [1, 1, 0, 0]  # unparseable and null rows are null


def test_distinct_and_cross_join_over_wire():
    k = np.array([3, 1, 3, 1, 2], dtype=np.int64)
    op = json.dumps({"op": "distinct"})
    _, _, out_d, _, n = rb.table_op_wire(
        op, [I64], [0], [k.tobytes()], [None], 5
    )
    assert n == 3
    assert sorted(np.frombuffer(out_d[0], np.int64, n)) == [1, 2, 3]


def test_explode_over_wire():
    # LIST<INT64> column in the offsets+child wire convention
    offs = np.array([0, 2, 2, 3], np.int32)
    child = np.array([5, 6, 9], np.int64)
    data = offs.tobytes() + child.tobytes()
    L = int(dt.TypeId.LIST)
    op = json.dumps({"op": "explode", "column": 0})
    out_t, _, out_d, _, n = rb.table_op_wire(
        op, [L], [I64], [data], [None], 3
    )
    assert out_t == [I64] and n == 3
    assert np.frombuffer(out_d[0], np.int64, 3).tolist() == [5, 6, 9]


def test_slice_repeat_sample_over_wire():
    k = np.arange(10, dtype=np.int64)
    op = json.dumps({"op": "slice", "start": 2, "stop": 5})
    _, _, out_d, _, n = rb.table_op_wire(
        op, [I64], [0], [k.tobytes()], [None], 10
    )
    assert n == 3
    assert np.frombuffer(out_d[0], np.int64, n).tolist() == [2, 3, 4]

    op2 = json.dumps({"op": "repeat", "count": 2})
    _, _, out2, _, n2 = rb.table_op_wire(
        op2, [I64], [0], [k[:3].tobytes()], [None], 3
    )
    assert n2 == 6
    assert np.frombuffer(out2[0], np.int64, n2).tolist() == [0, 0, 1, 1, 2, 2]

    op3 = json.dumps({"op": "sample", "n": 4, "seed": 7})
    _, _, out3, _, n3 = rb.table_op_wire(
        op3, [I64], [0], [k.tobytes()], [None], 10
    )
    assert n3 == 4
    vals = np.frombuffer(out3[0], np.int64, n3)
    assert len(set(vals.tolist())) == 4 and all(0 <= v < 10 for v in vals)


def test_slice_negative_bounds_raise():
    k = np.arange(4, dtype=np.int64)
    with pytest.raises(Exception):
        rb.table_op_wire(
            json.dumps({"op": "slice", "start": -2}),
            [I64], [0], [k.tobytes()], [None], 4,
        )


# ---------------------------------------------------------------------------
# corrupt wire offsets: validated loudly, never a silently wrong mask
# ---------------------------------------------------------------------------


def _sort_op():
    return json.dumps({"op": "sort_by", "keys": [{"column": 0}]})


def test_non_monotonic_offsets_raise_with_label():
    # offsets [0, 3, 1, 4]: row 1 would get length -2 — before the
    # validation this produced an all-False mask row and shifted every
    # following row's payload into the wrong slot without any error
    offs = np.array([0, 3, 1, 4], np.int32)
    data = offs.tobytes() + b"abcd"
    with pytest.raises(ValueError, match="STRING wire offsets corrupt"):
        rb.table_op_wire(_sort_op(), [S], [0], [data], [None], 3)


def test_negative_first_offset_raises():
    offs = np.array([-4, 0, 2], np.int32)
    data = offs.tobytes() + b"ab"
    with pytest.raises(ValueError, match="STRING wire offsets corrupt"):
        rb.table_op_wire(_sort_op(), [S], [0], [data], [None], 2)


def test_list_offsets_carry_list_label():
    offs = np.array([0, 2, 1], np.int32)
    payload = np.arange(2, dtype=np.int64).tobytes()
    with pytest.raises(ValueError, match="LIST wire offsets corrupt"):
        rb.table_op_wire(
            _sort_op(), [int(dt.TypeId.LIST)], [I64],
            [offs.tobytes() + payload], [None], 2,
        )


def test_truncated_offsets_block_raises():
    # buffer shorter than the offsets array itself
    data = np.array([0, 1], np.int32).tobytes()[:-2]
    with pytest.raises(ValueError, match="STRING wire buffer holds"):
        rb.table_op_wire(_sort_op(), [S], [0], [data], [None], 1)


def test_valid_offsets_still_pass():
    data, valid = _string_wire(["ab", "", "xyz"])
    out = rb.table_op_wire(_sort_op(), [S], [0], [data], [valid], 3)
    assert out[4] == 3
