"""Benchmark ladder: BASELINE.json configs 1-3 on the attached device.

Prints ONE JSON line whose primary metric is the 100M-row groupby-sum
(config 1 at scale) and whose `configs` array carries the full measured
ladder:

  config 1  hash groupby-sum at 1M / 16M / 100M int64 rows, vs CPU Arrow
  config 2  row<->columnar transpose + cast/binaryop round trip
  config 3  100M-row hash inner join (two-phase) + 100M-row sort

Methodology (hardened per round-2 review — and corrected):
  - SYNC BY HOST FETCH: on the tunneled TPU platform ("axon"),
    ``jax.block_until_ready`` returns before the computation finishes
    (measured: a 16M-row u64 sort "completes" in 30us by
    block_until_ready but takes ~60ms to produce its first byte). The
    r1/r2 headline (13.2G/11.1G rows/s, 92x/84x Arrow) timed async
    ENQUEUE, not compute — that is the real story behind the apparent
    r1->r2 "regression": both numbers were noise around dispatch
    latency. Every timed region here ends with a one-element host fetch
    that forces the computation (and pays one ~30-60ms tunnel
    round-trip, which a real Spark driver would also pay).
  - FRESH inputs per repetition where feasible (cycled tables), median +
    min + spread over all reps, not best-of-N alone.
  - every entry carries achieved bytes/s against the HBM peak
    (v5e ~819 GB/s) as a bandwidth sanity line.
  - numerical sanity asserts per config (sums match numpy oracles).
"""

import json
import statistics
import sys
import time

import numpy as np


def _progress(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _flight_tail(limit=40):
    """Last N flight-recorder events, or None when the recorder is off
    or the package is absent — the timeline of what the process was
    doing in the seconds before a failure."""
    try:
        from spark_rapids_jni_tpu.utils import flight

        if not flight.enabled():
            return None
        return flight.tail_records(limit) or None
    except Exception:
        return None


def _flight_note(name, arg=None):
    """One instant event on the flight recorder (lazy import, never
    raises): probe retries and fast-fail decisions must appear in the
    postmortem timeline next to the spans they interrupted."""
    try:
        from spark_rapids_jni_tpu.utils import flight

        flight.record("I", name, arg)
    except Exception:
        pass


def _classify_failure_text(type_name, message) -> str:
    """Taxonomy class name for a failure's (type, message) text via
    faults.classify_text — the shared classifier replacing this file's
    historical ad-hoc marker list."""
    try:
        from spark_rapids_jni_tpu.utils import faults

        return faults.classify_text(
            str(type_name or ""), str(message or "")
        ).__name__
    except Exception:
        return "PermanentError"


def _failure_record(
    name, error, exc_type=None, elapsed_s=None, retries=0, skipped=False,
    backoff_ms=0.0,
):
    """Structured failure entry: exception type, message, taxonomy
    class, elapsed time and retry/backoff counts, so a killed ladder is
    diagnosable from the JSON alone (rounds 1-5 died with bare
    '"error": "device unreachable"' strings and no telemetry). The flat
    "error" string stays for old readers; "failure" is the structured
    record. ``skipped=True`` marks a config that was never attempted
    (budget exhausted / fast-fail after the tunnel went down) as
    opposed to one that ran and died.
    When the flight recorder is on, a record for a config that actually
    RAN and died also carries ``flight_tail`` — the last events before
    the failure, the input of ``tools/trace2chrome.py`` — so "device
    unreachable" is never again a bare string. Skip records
    (``skipped=True``) stay lean: a fast-fail batch would otherwise
    embed N byte-identical tails into the headline JSON; the config
    that triggered the fast-fail carries the one that matters."""
    msg = str(error)[:300]
    tname = exc_type or (
        type(error).__name__ if isinstance(error, BaseException)
        else "Error"
    )
    failure = {
        "type": tname,
        "message": msg,
        "class": _classify_failure_text(tname, msg),
        "elapsed_s": (
            round(float(elapsed_s), 3) if elapsed_s is not None else None
        ),
        "retries": int(retries),
        "backoff_ms": round(float(backoff_ms), 2),
        "skipped": bool(skipped),
    }
    if not skipped:
        tail = _flight_tail()
        if tail:
            failure["flight_tail"] = tail
    return {"name": name, "error": msg, "failure": failure}


def _unreachable_failure(entry) -> bool:
    """True when a failure entry smells like the device/tunnel died
    (vs a genuine per-config crash) — i.e. it classifies transient
    under the shared fault taxonomy (faults.classify_text subsumes the
    marker list this file used to keep by hand)."""
    f = entry.get("failure") or {}
    return _classify_failure_text(
        f.get("type", ""),
        f"{f.get('message', '')} {entry.get('error', '')}",
    ) == "TransientDeviceError"


def _metrics_enable():
    """Turn the metrics AND flight-recorder planes on for this process
    (lazy import so the bench stays runnable from a checkout without
    the package installed). The flight recorder is the crash telemetry:
    its tail rides in every structured failure record and is flushed to
    SPARK_RAPIDS_TPU_FLIGHT_DUMP from the SIGTERM handler."""
    import os
    import tempfile

    try:
        from spark_rapids_jni_tpu.utils import config as _srt_config

        _srt_config.set_flag("METRICS", True)
        _srt_config.set_flag("FLIGHT", True)
        _srt_config.set_flag("PROFILE", "on")
        # plan-stats store: a per-run directory (inherited by the
        # config subprocesses through the environment) so every arm's
        # run_plan executions land drift-comparable records the
        # headline's "drift" block summarizes
        pdir = os.path.join(
            tempfile.gettempdir(), f"srt-bench-planstats-{os.getpid()}"
        )
        # srt: allow-env-read(dir must ride env into config subprocesses)
        pdir = os.environ.setdefault(
            "SPARK_RAPIDS_TPU_PLANSTATS_DIR", pdir
        )
        _srt_config.set_flag("PLANSTATS_DIR", pdir)
    except Exception:
        pass


def _drift_block():
    """Compact drift summary from this run's plan-stats store for the
    headline JSON (record/plan counts + findings by type), or None when
    the store is absent/empty — old readers never see the key change
    shape."""
    try:
        from spark_rapids_jni_tpu.utils import planstats as _srt_planstats

        return _srt_planstats.summary()
    except Exception:
        return None


def _flush_telemetry():
    """Write the metrics snapshot and flight-recorder tail to their
    configured dump paths NOW. Called from the SIGTERM handler (which
    os._exit's, skipping atexit) so an rc=124 run still leaves its
    telemetry behind; cheap and exception-free by construction."""
    try:
        from spark_rapids_jni_tpu.utils import flight as _srt_flight
        from spark_rapids_jni_tpu.utils import metrics as _srt_metrics
        from spark_rapids_jni_tpu.utils import profiler as _srt_profiler

        _srt_metrics.dump()
        _srt_flight.dump()
        _srt_profiler.dump()
    except Exception:
        pass


def _metrics_snapshot(reset=False):
    """Current metrics snapshot, or None when the package is absent.
    ``reset=True`` clears the registry afterward so consecutive
    in-process configs get per-config blocks, not cumulative ones."""
    try:
        from spark_rapids_jni_tpu.utils import metrics as _srt_metrics

        snap = _srt_metrics.snapshot()
        if reset:
            _srt_metrics.reset()
        return snap
    except Exception:
        return None


def _profile_block(reset=False):
    """Aggregated per-segment profiler summary for this config's
    sessions (utils/profiler.summarize), or None when the package is
    absent or no session ran. ``reset=True`` clears the session
    registry afterward — the _metrics_snapshot discipline, so
    consecutive in-process configs get per-config blocks."""
    try:
        from spark_rapids_jni_tpu.utils import profiler as _srt_profiler

        docs = _srt_profiler.sessions(reset=reset)
        if not docs:
            return None
        block = _srt_profiler.summarize(docs)
        # keep the LAST few full session docs for tools/explain.py;
        # the aggregate above is the compact per-config story
        block["sessions_tail"] = docs[-3:]
        return block
    except Exception:
        return None


HBM_PEAK_GBPS = {"tpu": 819.0, "axon": 819.0}  # v5e HBM bandwidth


def _sync(out):
    """Force completion: fetch ONE element of the first array leaf.

    All outputs of a jitted call belong to one executable, so fetching
    any element of any output waits for the whole computation. A full
    np.asarray(out) would instead time the tunnel transfer of the
    entire result."""
    import jax

    leaves = [l for l in jax.tree.leaves(out) if hasattr(l, "dtype")]
    if leaves:
        np.asarray(leaves[0].ravel()[-1])
    return out


def _timeit(fn, inputs, reps_per_input=3):
    """Time fn over (cycled) inputs; returns (median, min, std, last_out)."""
    out = _sync(fn(*inputs[0]))  # compile/warmup
    times = []
    for _ in range(reps_per_input):
        for inp in inputs:
            t0 = time.perf_counter()
            out = _sync(fn(*inp))
            times.append(time.perf_counter() - t0)
    return (
        statistics.median(times),
        min(times),
        statistics.pstdev(times),
        out,
    )


def _entry(config, name, rows, med, mn, std, bytes_moved, platform):
    peak = HBM_PEAK_GBPS.get(platform)
    gbps = bytes_moved / med / 1e9
    e = {
        "config": config,
        "name": name,
        "rows": rows,
        "seconds_median": round(med, 6),
        "seconds_min": round(mn, 6),
        "spread": round(std / med, 3) if med else None,
        "rows_per_s": round(rows / med, 1),
        "achieved_gbps": round(gbps, 2),
    }
    if peak:
        e["hbm_peak_gbps"] = peak
        e["hbm_frac"] = round(gbps / peak, 4)
    return e


def _gen_groupby_inputs(n, n_inputs=2, n_keys=10_000):
    """Shared config-1 data generator: every groupby A/B rung MUST draw
    from this one (same seed, same shape) or the arms stop being
    comparable (the r3 shrink lesson)."""
    import jax

    from spark_rapids_jni_tpu.column import Column, Table

    rng = np.random.default_rng(42)
    hosts = []
    inputs = []
    for _ in range(n_inputs):
        k = rng.integers(0, n_keys, n, dtype=np.int64)
        v = rng.integers(-1000, 1000, n, dtype=np.int64)
        hosts.append((k, v))
        t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
        jax.block_until_ready(t.columns[0].data)
        inputs.append((t,))
    return hosts, inputs


def bench_groupby(platform, n, n_inputs=2, values_via="sort"):
    import jax

    from spark_rapids_jni_tpu.ops.groupby import (
        GroupbyAgg,
        groupby_aggregate_capped,
    )

    n_keys = 10_000
    hosts, inputs = _gen_groupby_inputs(n, n_inputs, n_keys)

    step = jax.jit(
        lambda t: groupby_aggregate_capped(
            t,
            ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            num_segments=n_keys,
            values_via=values_via,
        )
    )
    med, mn, std, out = _timeit(step, inputs)
    # sanity: last-run totals must match numpy on the last-cycled input
    agg, ngroups = out
    total = int(np.asarray(agg["sum_v"].data)[: int(ngroups)].sum())
    assert total == int(hosts[-1][1].sum()), "groupby-sum mismatch vs numpy"
    suffix = "" if values_via == "sort" else f"_{values_via}"
    return _entry(
        1, f"groupby_sum_{n // 1_000_000}M{suffix}", n, med, mn, std,
        n * 16, platform,
    ), med


def bench_groupby_chunked(platform, n=100_000_000, n_inputs=2):
    """Config 1 at scale via the two-level chunked design (round-4
    headline): C batched VMEM-sized sorts + a combine pass, vs the
    single giant variadic sort of ``bench_groupby``."""
    import jax

    from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
    from spark_rapids_jni_tpu.ops.groupby_chunked import (
        groupby_aggregate_capped_chunked,
    )

    n_keys = 10_000
    chunk_rows = 1 << 18
    chunk_segments = 1 << 15  # 10k keys/chunk worst case + headroom
    hosts, inputs = _gen_groupby_inputs(n, n_inputs)

    step = jax.jit(
        lambda t: groupby_aggregate_capped_chunked(
            t,
            ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            num_segments=n_keys,
            chunk_rows=chunk_rows,
            chunk_segments=chunk_segments,
        )
    )
    med, mn, std, out = _timeit(step, inputs)
    agg, ngroups, max_chunk = out
    assert int(max_chunk) <= chunk_segments, "chunk capacity overflow"
    total = int(np.asarray(agg["sum_v"].data)[: int(ngroups)].sum())
    assert total == int(hosts[-1][1].sum()), "groupby-sum mismatch vs numpy"
    return _entry(
        1, f"groupby_sum_{n // 1_000_000}M_chunked", n, med, mn, std,
        n * 16, platform,
    )


def bench_groupby_packed(platform, n=100_000_000, n_inputs=2,
                         engine="lax", chunk_rows=1 << 18,
                         chunk_segments=1 << 14):
    """Config 1 at scale via the packed-key formulation: ONE u64 sort
    word ((key-kmin)<<18 | iota) per row instead of (occupancy, key,
    iota, row_valid) — ~1.8x less sort traffic than the chunked path on
    the same shape, ties impossible so stability is free. The A/B vs
    groupby100m_chunked/groupby100m decides the headline formulation."""
    import jax

    from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
    from spark_rapids_jni_tpu.ops.groupby_packed import (
        groupby_aggregate_packed_chunked,
    )

    n_keys = 10_000
    hosts, inputs = _gen_groupby_inputs(n, n_inputs, n_keys)

    step = jax.jit(
        lambda t: groupby_aggregate_packed_chunked(
            t,
            ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            num_segments=n_keys,
            chunk_rows=chunk_rows,
            chunk_segments=chunk_segments,
            engine=engine,
        )
    )
    med, mn, std, out = _timeit(step, inputs)
    agg, ngroups, max_chunk, overflow = out
    assert not bool(overflow), "packed range overflow"
    assert int(max_chunk) <= chunk_segments, "chunk capacity overflow"
    total = int(np.asarray(agg["sum_v"].data)[: int(ngroups)].sum())
    assert total == int(hosts[-1][1].sum()), "groupby-sum mismatch vs numpy"
    suffix = "" if engine == "lax" else f"_{engine}"
    return _entry(
        1, f"groupby_sum_{n // 1_000_000}M_packed{suffix}", n, med, mn,
        std, n * 16, platform,
    )


def bench_groupby_flat(platform, n=16_000_000, values_via="sort",
                       n_inputs=2):
    """Single-level flat-packed groupby on the LOW-cardinality headline
    shape: one u64 word (key<<iota_bits | iota) through ONE full-column
    sort — no chunking, no combine. ``values_via`` A/Bs carrying values
    as sort payloads vs a word-only sort plus permutation gather."""
    import jax

    from spark_rapids_jni_tpu.ops.groupby import GroupbyAgg
    from spark_rapids_jni_tpu.ops.groupby_packed import (
        groupby_aggregate_packed_flat,
    )

    n_keys = 10_000
    hosts, inputs = _gen_groupby_inputs(n, n_inputs, n_keys)

    step = jax.jit(
        lambda t: groupby_aggregate_packed_flat(
            t,
            ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            num_segments=n_keys,
            values_via=values_via,
        )
    )
    med, mn, std, out = _timeit(step, inputs)
    agg, ngroups, overflow = out
    assert not bool(overflow), "flat packed overflow"
    total = int(np.asarray(agg["sum_v"].data)[: int(ngroups)].sum())
    assert total == int(hosts[-1][1].sum()), "groupby-sum mismatch vs numpy"
    return _entry(
        1, f"groupby_sum_{n // 1_000_000}M_flat_{values_via}", n, med,
        mn, std, n * 16, platform,
    )


def bench_groupby_highcard(platform, n=100_000_000, n_keys=50_000_000):
    """High-cardinality A/B in one config: the general single-pass
    capped groupby vs the FLAT packed formulation on the same 50M-key
    shape (per-chunk dedup can't win here; the question is whether the
    one-narrow-word sort beats the multi-word single-pass sort)."""
    import jax

    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops.groupby import (
        GroupbyAgg,
        groupby_aggregate_capped,
    )
    from spark_rapids_jni_tpu.ops.groupby_packed import (
        groupby_aggregate_packed_flat,
    )

    rng = np.random.default_rng(44)
    k = rng.integers(0, n_keys, n, dtype=np.int64)
    v = rng.integers(-1000, 1000, n, dtype=np.int64)
    t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
    jax.block_until_ready(t.columns[0].data)
    aggs = [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")]
    want_total = int(v.sum())

    single = jax.jit(
        lambda tt: groupby_aggregate_capped(
            tt, ["k"], aggs, num_segments=n_keys
        )
    )
    med_s, mn_s, std_s, out_s = _timeit(single, [(t,)], reps_per_input=2)
    agg_s, ng_s = out_s
    tot = int(np.asarray(agg_s["sum_v"].data)[: int(ng_s)].sum())
    assert tot == want_total, "single-pass highcard sum mismatch"

    flat = jax.jit(
        lambda tt: groupby_aggregate_packed_flat(
            tt, ["k"], aggs, num_segments=n_keys
        )
    )
    med_f, mn_f, std_f, out_f = _timeit(flat, [(t,)], reps_per_input=2)
    agg_f, ng_f, ov = out_f
    assert not bool(ov), "flat packed overflow"
    tot = int(np.asarray(agg_f["sum_v"].data)[: int(ng_f)].sum())
    assert tot == want_total, "flat packed highcard sum mismatch"

    e1 = _entry(1, f"groupby_highcard_{n // 1_000_000}M_single", n,
                med_s, mn_s, std_s, n * 16, platform)
    e2 = _entry(1, f"groupby_highcard_{n // 1_000_000}M_packed_flat", n,
                med_f, mn_f, std_f, n * 16, platform)
    e2["vs_single"] = round(med_s / med_f, 2)
    return [e1, e2]


def arrow_baseline(n):
    """CPU Arrow groupby throughput (rows/s) on the config-1 shape."""
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        return None
    rng = np.random.default_rng(7)
    k = rng.integers(0, 10_000, n, dtype=np.int64)
    v = rng.integers(-1000, 1000, n, dtype=np.int64)
    atbl = pa.table({"k": k, "v": v})
    atbl.group_by("k").aggregate([("v", "sum"), ("v", "count")])  # warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        atbl.group_by("k").aggregate([("v", "sum"), ("v", "count")])
        best = min(best, time.perf_counter() - t0)
    return n / best


def bench_transpose(platform, n=4_000_000, n_inputs=2, backend="xla"):
    """Config 2: to_rows -> from_rows -> cast+binaryop on the result.

    The CudfColumnVector round-trip shape: an 8-column fixed-width table
    (the reference round-trip test schema, RowConversionTest.java:30-39)
    packed to Spark UnsafeRow bytes and back, then a cast and an add to
    stand in for the CudfColumnVector compute step.
    """
    import jax

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import rows as rows_mod
    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops import binaryop
    from spark_rapids_jni_tpu.ops.cast import cast as cast_fn

    rng = np.random.default_rng(3)
    schema = [
        dt.INT64, dt.FLOAT64, dt.INT32, dt.BOOL8,
        dt.FLOAT32, dt.INT8, dt.DType(dt.TypeId.DECIMAL32, -3),
        dt.DType(dt.TypeId.DECIMAL64, -8),
    ]
    layout = rows_mod.compute_fixed_width_layout(schema)

    def make_table():
        cols = []
        for d in schema:
            npdt = np.dtype(d.storage_dtype)
            if d.is_boolean:
                arr = rng.integers(0, 2, n).astype(np.bool_)
            elif d.is_floating:
                arr = rng.standard_normal(n).astype(npdt)
            else:
                info = np.iinfo(npdt)
                arr = rng.integers(
                    info.min // 2, info.max // 2, n, dtype=npdt
                )
            valid = rng.random(n) > 0.1
            cols.append(Column.from_numpy(arr, validity=valid, dtype=d))
        t = Table(cols)
        jax.block_until_ready(t.columns[0].data)
        return t

    inputs = [(make_table(),) for _ in range(n_inputs)]

    def round_trip(t):
        batches = rows_mod.to_rows(t, split=False, backend=backend)
        back = rows_mod.from_rows(batches, schema, backend=backend)
        c = cast_fn(back.columns[0], dt.FLOAT64)
        return binaryop.add(c, back.columns[1])

    med, mn, std, out = _timeit(round_trip, inputs)
    # pack writes + unpack reads the packed bytes, plus column reads/writes
    bytes_moved = n * layout.row_size * 2
    # default arm keeps the historical unsuffixed name (BASELINE.json
    # published rows are keyed by entry name; only the new arm suffixes)
    name = (
        "transpose_cast_round_trip"
        if backend == "xla"
        else f"transpose_cast_round_trip_{backend}"
    )
    return _entry(2, name, n, med, mn, std, bytes_moved, platform)


def bench_transpose_pallas(platform, n=4_000_000, n_inputs=2):
    """Config 2 A/B arm: the explicit VMEM-tiled Pallas transpose pair
    (kernels/row_transpose.py) vs the XLA-fused default — r3 measured
    the XLA path at 1.54s/4M rows (~1 GB/s effective), far below what a
    tiled byte repack should do; this decides the default backend."""
    return bench_transpose(platform, n, n_inputs, backend="pallas")


def bench_sort(platform, n=100_000_000):
    """Config 3b: 100M-row single-chip sort (u64-normalized keys),
    payload formulation (what ``sort_table`` ships)."""
    return _bench_sort_formulation(platform, n, "payload")


def bench_sort_gather(platform, n=100_000_000):
    """Config 3b A/B arm: the argsort+gather formulation ``sort_table``
    used before 241d4b6 — measured so the payload-vs-gather switch rests
    on a direct on-chip number, not the round-3 indirect inference
    (groupby's payload sort at 1.08s vs this form's 5.71s)."""
    return _bench_sort_formulation(platform, n, "gather")


def bench_sort_packed_gather(platform, n=100_000_000):
    """Config 3b fourth arm: packed word-only sort + payload gather."""
    return _bench_sort_formulation(platform, n, "packed_gather")


def bench_sort_packed(platform, n=100_000_000):
    """Config 3b third arm: the packed formulation (sort_packed.py) —
    key word, iota AND the key column's payload in ONE u64 (16 B/row of
    operands vs the payload form's 24; bench keys span [0,1e8) < 2^37
    so the shape is eligible)."""
    return _bench_sort_formulation(platform, n, "packed")


def _bench_sort_formulation(platform, n, form):
    import jax

    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops.gather import gather_table
    from spark_rapids_jni_tpu.ops.sort import (
        SortKey,
        argsort_table,
        sort_table,
    )

    rng = np.random.default_rng(13)
    k = rng.integers(0, n, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    t = Table([Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"])
    jax.block_until_ready(t.columns[0].data)
    if form == "payload":
        sort_fn = jax.jit(lambda tt: sort_table(tt, [SortKey("k")]))
    elif form in ("packed", "packed_gather"):
        from spark_rapids_jni_tpu.ops.sort_packed import sort_table_packed

        via = "gather" if form.endswith("gather") else "sort"

        def sort_fn(tt):
            out = sort_table_packed(tt, [SortKey("k")], values_via=via)
            assert out is not None, "packed sort declined the bench shape"
            return out
    else:
        sort_fn = jax.jit(
            lambda tt: gather_table(tt, argsort_table(tt, [SortKey("k")]))
        )
    med, mn, std, out = _timeit(sort_fn, [(t,)], reps_per_input=2)
    head = np.asarray(out["k"].data[:1000])
    assert (np.diff(head) >= 0).all(), "sort output not ordered"
    return _entry(3, f"sort_{n // 1_000_000}M_int64_{form}", n, med, mn,
                  std, n * 16 * 2, platform)


def _join_inputs(n):
    """Shared config-3 join workload: both benches must measure the
    same data shape."""
    import jax

    from spark_rapids_jni_tpu.column import Column, Table

    rng = np.random.default_rng(11)
    kl = rng.integers(0, n, n, dtype=np.int64)
    kr = rng.integers(0, n, n, dtype=np.int64)
    vl = rng.integers(-100, 100, n, dtype=np.int64)
    vr = rng.integers(-100, 100, n, dtype=np.int64)
    left = Table(
        [Column.from_numpy(kl), Column.from_numpy(vl)], ["k", "lv"]
    )
    right = Table(
        [Column.from_numpy(kr), Column.from_numpy(vr)], ["k", "rv"]
    )
    jax.block_until_ready(left.columns[0].data)
    jax.block_until_ready(right.columns[0].data)
    return left, right


def bench_join(platform, n=None):
    """Config 3a: two-phase hash inner join at 100M rows (override
    via SRT_BENCH_JOIN_ROWS for crash triage)."""
    import os

    import jax

    if n is None:
        n = int(os.environ.get("SRT_BENCH_JOIN_ROWS", 100_000_000))

    from spark_rapids_jni_tpu.ops.join import (
        inner_join_capped,
        inner_join_count,
    )

    left, right = _join_inputs(n)

    count_fn = jax.jit(lambda l, r: inner_join_count(l, r, ["k"]))
    total = int(count_fn(left, right))
    # exact capacity rounded to 32 rows, not pow2: at ~100M matches the
    # pow2 rounding wastes ~2.5 GB of HBM across the 3 output columns,
    # which is the difference between fitting and crashing the worker
    cap = max(32, (total + 31) // 32 * 32)
    join_fn = jax.jit(
        lambda l, r: inner_join_capped(l, r, ["k"], capacity=cap)
    )

    def two_phase(l, r):
        c = int(count_fn(l, r))  # phase 1 + the real host sync it implies
        out, cnt = join_fn(l, r)
        return out

    med, mn, std, out = _timeit(
        two_phase, [(left, right)], reps_per_input=2
    )
    # both sides read (16B/row each) + output written (3 int64 cols)
    bytes_moved = 2 * n * 16 + total * 24
    e1 = _entry(
        3, f"inner_join_{n // 1_000_000}M_two_phase", 2 * n, med, mn,
        std, bytes_moved, platform,
    )
    e1["matches"] = total
    return e1


def bench_join_batched(platform, n=None):
    """Config 3a at 100M via the batched probe path. The single-shot
    two-phase join graph (lexsort + lex-searchsorted fused in one jit)
    hits a TPU worker kernel fault at >=32M rows with 64-bit keys
    (reproduced standalone; 16M probes and 100M sorts are fine), so the
    supported 100M path sorts the build side once and probes in 16M
    chunks — the reference's split discipline applied to joins."""
    import os

    from spark_rapids_jni_tpu.ops.join import inner_join_batched

    if n is None:
        n = int(os.environ.get("SRT_BENCH_JOIN_ROWS", 100_000_000))
    left, right = _join_inputs(n)

    def run(l, r):
        return inner_join_batched(l, r, ["k"], probe_rows=16_000_000)

    med, mn, std, out = _timeit(run, [(left, right)], reps_per_input=2)
    matches = out.row_count
    bytes_moved = 2 * n * 16 + matches * 24
    e = _entry(
        3, f"inner_join_{n // 1_000_000}M_batched_probe", 2 * n, med,
        mn, std, bytes_moved, platform,
    )
    e["matches"] = matches
    return e


def bench_join_batched_packed(platform, n=None):
    """Config 3a A/B arm: the packed-key batched join (join_packed.py)
    — one-u64-word build sort (8 B/row vs 20) with the permutation in
    the low bits, native searchsorted probe. Eligible because the bench
    keys span [0, n) and n < 2^37."""
    import os

    from spark_rapids_jni_tpu.ops.join_packed import (
        inner_join_batched_packed,
    )

    if n is None:
        n = int(os.environ.get("SRT_BENCH_JOIN_ROWS", 100_000_000))
    left, right = _join_inputs(n)

    def run(l, r):
        out = inner_join_batched_packed(l, r, ["k"], probe_rows=16_000_000)
        assert out is not None, "packed join declined the bench shape"
        return out

    med, mn, std, out = _timeit(run, [(left, right)], reps_per_input=2)
    matches = out.row_count
    bytes_moved = 2 * n * 16 + matches * 24
    e = _entry(
        3, f"inner_join_{n // 1_000_000}M_batched_packed", 2 * n, med,
        mn, std, bytes_moved, platform,
    )
    e["matches"] = matches
    return e


def bench_bucketed_stream(platform, n_batches=12):
    """Shape-bucket dispatch bench: a ragged stream of ColumnarBatch-
    shaped wire calls (filter -> sort -> groupby per batch, every batch
    a different row count) with pad-to-bucket batching + the compiled-
    executable cache ON vs OFF. COLD timings are the story: the exact
    path compiles every op for every distinct size, the bucketed path
    compiles once per (op, bucket) and then streams on cache hits."""
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu.utils import buckets as buckets_mod
    from spark_rapids_jni_tpu.utils import config as srt_config
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics

    _metrics_enable()  # the cache/pad counters ARE this config's story
    rng = np.random.default_rng(31)
    sizes = sorted(
        int(s) for s in rng.integers(50_000, 140_000, n_batches)
    )
    i64 = int(dt.TypeId.INT64)
    b8 = int(dt.TypeId.BOOL8)
    op_filter = json.dumps({"op": "filter", "mask": 2})
    op_sort = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
    op_group = json.dumps(
        {"op": "groupby", "by": [0], "aggs": [{"column": 1, "agg": "sum"}]}
    )
    batches = []
    for nn in sizes:
        kk = rng.integers(0, 1000, nn, dtype=np.int64)
        vv = rng.integers(-100, 100, nn, dtype=np.int64)
        mm = (vv > 0).astype(np.uint8)
        batches.append((nn, kk.tobytes(), vv.tobytes(), mm.tobytes()))

    def stream():
        t0 = _time.perf_counter()
        total = 0
        for nn, kb, vb, mb in batches:
            t1 = rb.table_op_wire(
                op_filter, [i64, i64, b8], [0, 0, 0], [kb, vb, mb],
                [None, None, None], nn,
            )
            t2 = rb.table_op_wire(op_sort, t1[0], t1[1], t1[2], t1[3], t1[4])
            t3 = rb.table_op_wire(op_group, t2[0], t2[1], t2[2], t2[3], t2[4])
            total += t3[4]
        return _time.perf_counter() - t0, total

    try:
        srt_config.set_flag("BUCKETS", "off")
        exact_cold_s, exact_total = stream()
        exact_warm_s, _ = stream()
        srt_config.set_flag("BUCKETS", "")
        buckets_mod.cache_clear()
        srt_metrics.reset()  # the entry's metrics block = the ON arm
        on_cold_s, on_total = stream()
        on_warm_s, _ = stream()
    finally:
        srt_config.clear_flag("BUCKETS")
    assert exact_total == on_total, "bucketed stream changed results"
    snap = _metrics_snapshot() or {}
    ctr = snap.get("counters", {})
    hits = int(ctr.get("compile_cache.hit", 0))
    misses = int(ctr.get("compile_cache.miss", 0))
    rows = sum(s[0] for s in batches)
    return {
        "config": "dispatch",
        "name": f"bucketed_dispatch_stream_{n_batches}x3op",
        "rows": rows,
        "distinct_batch_sizes": len(set(sizes)),
        "exact_cold_seconds": round(exact_cold_s, 4),
        "exact_warm_seconds": round(exact_warm_s, 4),
        "bucketed_cold_seconds": round(on_cold_s, 4),
        "bucketed_warm_seconds": round(on_warm_s, 4),
        "cold_speedup": round(exact_cold_s / on_cold_s, 2),
        "compile_cache_hits": hits,
        "compile_cache_misses": misses,
        "pad_waste_bytes": int(
            snap.get("bytes", {}).get("bucket.pad_waste_bytes", 0)
        ),
        "platform": platform,
    }


def bench_fused_plan(platform, n_batches=16):
    """Plan-fusion bench (ISSUE 4 tentpole): the SAME 4-op chain
    (filter -> cast -> sort_by -> groupby) over a ragged stream of
    device-resident tables, dispatched per-op (four executable launches
    + three materialized intermediate tables per batch) vs through
    ``table_plan_resident`` (ONE fused executable launch per batch once
    the cache is warm). Launch counts come from the compile cache's
    hit+miss counters — every cached_jit call is one executable launch
    — and the ``plan.*`` counters ride along in a structured ``fusion``
    block. SRT_BENCH_PLAN_ROWS shrinks the shape for smoke runs
    (ci/smoke-observability.sh drives this config)."""
    import os as _os
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu.utils import buckets as buckets_mod
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics

    _metrics_enable()  # the launch/fusion counters ARE this config's story
    # default shape sits in the launch-overhead-sensitive regime (the
    # regime fusion targets — many small ragged ColumnarBatches);
    # SRT_BENCH_PLAN_ROWS scales it up/down
    base = int(_os.environ.get("SRT_BENCH_PLAN_ROWS", 8_000))
    rng = np.random.default_rng(37)
    sizes = sorted(
        int(s)
        for s in rng.integers(base // 2, base * 3 // 2 + 2, n_batches)
    )
    i64 = int(dt.TypeId.INT64)
    b8 = int(dt.TypeId.BOOL8)
    chain = [
        {"op": "filter", "mask": 2},
        {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
        {"op": "sort_by", "keys": [{"column": 0}]},
        {"op": "groupby", "by": [0],
         "aggs": [{"column": 1, "agg": "sum"},
                  {"column": 1, "agg": "count"}]},
    ]
    batches = []
    for nn in sizes:
        kk = rng.integers(0, 1000, nn, dtype=np.int64)
        vv = rng.integers(-100, 100, nn, dtype=np.int64)
        mm = (vv > 0).astype(np.uint8)
        batches.append((nn, kk.tobytes(), vv.tobytes(), mm.tobytes()))

    def upload(nn, kb, vb, mb):
        return rb.table_upload_wire(
            [i64, i64, b8], [0, 0, 0], [kb, vb, mb],
            [None, None, None], nn,
        )

    def per_op_stream():
        t0 = _time.perf_counter()
        total = 0
        for nn, kb, vb, mb in batches:
            cur = upload(nn, kb, vb, mb)
            for op in chain:
                nxt = rb.table_op_resident(json.dumps(op), [cur])
                rb.table_free(cur)
                cur = nxt
            out = rb.table_download_wire(cur)
            rb.table_free(cur)
            total += out[4]
        return _time.perf_counter() - t0, total

    def fused_stream():
        t0 = _time.perf_counter()
        total = 0
        for nn, kb, vb, mb in batches:
            tid = upload(nn, kb, vb, mb)
            res = rb.table_plan_resident(json.dumps(chain), [tid])
            rb.table_free(tid)
            out = rb.table_download_wire(res)
            rb.table_free(res)
            total += out[4]
        return _time.perf_counter() - t0, total

    def launches(snap):
        c = (snap or {}).get("counters", {})
        return int(c.get("compile_cache.hit", 0)) + int(
            c.get("compile_cache.miss", 0)
        )

    warm_reps = 3  # best-of: one warm pass is scheduler-noise-bound

    buckets_mod.cache_clear()
    srt_metrics.reset()
    per_cold_s, per_total = per_op_stream()
    srt_metrics.reset()
    per_warm_s, _ = per_op_stream()
    per_launches = launches(_metrics_snapshot())
    for _ in range(warm_reps - 1):
        per_warm_s = min(per_warm_s, per_op_stream()[0])
    buckets_mod.cache_clear()
    srt_metrics.reset()
    fused_cold_s, fused_total = fused_stream()
    # reset so the launch count and the entry's metrics block cover
    # only WARM fused passes (no compile-phase noise)
    srt_metrics.reset()
    fused_warm_s, _ = fused_stream()
    snap = _metrics_snapshot() or {}
    fused_launches = launches(snap)
    for _ in range(warm_reps - 1):
        fused_warm_s = min(fused_warm_s, fused_stream()[0])
    ctr = snap.get("counters", {})
    assert per_total == fused_total, "fused plan changed results"
    return {
        "config": "dispatch",
        "name": f"fused_plan_{n_batches}x{len(chain)}op",
        "rows": sum(s[0] for s in batches),
        "distinct_batch_sizes": len(set(sizes)),
        "per_op_cold_seconds": round(per_cold_s, 4),
        "per_op_warm_seconds": round(per_warm_s, 4),
        "fused_cold_seconds": round(fused_cold_s, 4),
        "fused_warm_seconds": round(fused_warm_s, 4),
        "cold_speedup": round(per_cold_s / fused_cold_s, 2),
        "warm_speedup": round(per_warm_s / fused_warm_s, 2),
        "fusion": {
            "chain_ops": len(chain),
            "batches": n_batches,
            "plan_calls": int(ctr.get("plan.calls", 0)),
            "segments": int(ctr.get("plan.segments", 0)),
            "fused_segments": int(ctr.get("plan.fused_segments", 0)),
            "fused_ops": int(ctr.get("plan.fused_ops", 0)),
            "exact_ops": int(ctr.get("plan.exact_ops", 0)),
            "fallbacks": int(ctr.get("plan.fallbacks", 0)),
            "fused_launches": fused_launches,
            "per_op_launches": per_launches,
            "launches_saved": per_launches - fused_launches,
        },
        "platform": platform,
    }


def bench_pipelined_stream(platform, n_batches=12, depth=None):
    """Pipelined-dispatch bench (ISSUE 5 tentpole): the SAME fusable
    3-op chain (filter -> cast -> cast, one fused segment, donation
    eligible) over a ragged stream of wire batches, three ways:

      sync per-op   the repo's SYNCHRONOUS resident-stream idiom
                    (bench_resident_chain / the fused_plan bench's
                    per-op arm): upload -> one ``table_op_resident``
                    per op, each blocking, registry round-trips
                    between ops -> download. The baseline the
                    ``warm_speedup`` headline is measured against.
      sync plan     the PR-4 fused flavor of the same synchronous
                    stream (upload -> ``table_plan_resident`` ->
                    download), reported as ``sync_plan_warm_seconds``
                    / ``vs_plan_sync`` so the fusion and pipelining
                    contributions stay separable.
      pipelined     one ``table_stream_wire`` call with the pipeline
                    on: batch N+1's wire decode and batch N-1's wire
                    encode on background workers while batch N's fused
                    executable (input donated) runs on the caller.

    WARM throughput is the story (every arm reuses cached
    executables); byte parity across all three arms is asserted. The
    structured ``pipeline`` block carries the overlap fraction, stall
    totals and donated bytes. A wide STRING payload column gives the
    serde stages the weight they have on real ColumnarBatches (the
    chain deliberately has no multi-operand sort: serde and compute
    are then comparable, the regime pipelining targets — a
    compute-bound stream pins its ceiling at the compute time either
    way). NOTE on single-core hosts the pipelined margin over the
    PLAN-sync arm is bounded by the amortized per-batch overhead, not
    by overlap — there is no second core to overlap onto; the
    ``host_cpus`` field records what the numbers mean.
    SRT_BENCH_STREAM_ROWS / SRT_BENCH_PIPELINE_DEPTH shrink/tune it
    for smoke runs (ci/smoke-observability.sh drives this config)."""
    import os as _os
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import pipeline as pipeline_mod
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu.utils import config as srt_config
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics

    _metrics_enable()  # the overlap/stall/donation counters ARE the story
    if depth is None:
        depth = int(_os.environ.get("SRT_BENCH_PIPELINE_DEPTH", 2))
    base = int(_os.environ.get("SRT_BENCH_STREAM_ROWS", 120_000))
    rng = np.random.default_rng(41)
    sizes = sorted(
        int(s)
        for s in rng.integers(base // 2, base * 3 // 2 + 2, n_batches)
    )
    i64 = int(dt.TypeId.INT64)
    b8 = int(dt.TypeId.BOOL8)
    s_t = int(dt.TypeId.STRING)
    chain = [
        {"op": "filter", "mask": 2},
        {"op": "cast", "column": 1, "type_id": int(dt.TypeId.FLOAT64)},
        {"op": "cast", "column": 0, "type_id": int(dt.TypeId.INT32)},
    ]
    plan_json = json.dumps(chain)
    op_jsons = [json.dumps(op) for op in chain]
    str_width = 24

    def string_wire(ids):
        # constant-width payload rows, vectorized (python-str loops
        # would dominate setup at bench scale)
        mat = np.full((ids.size, str_width), ord("x"), np.uint8)
        mat[:, 1] = ord("0") + (ids % 8)
        offs = np.arange(ids.size + 1, dtype=np.int32) * str_width
        return offs.tobytes() + mat.tobytes()

    batches = []
    for nn in sizes:
        kk = rng.integers(0, 1000, nn, dtype=np.int64)
        vv = rng.integers(-100, 100, nn, dtype=np.int64)
        mm = (vv > 0).astype(np.uint8)
        batches.append((
            [i64, i64, b8, s_t], [0, 0, 0, 0],
            [kk.tobytes(), vv.tobytes(), mm.tobytes(), string_wire(kk)],
            [None, None, None, None], nn,
        ))

    def per_op_stream():
        t0 = _time.perf_counter()
        outs = []
        for b in batches:
            cur = rb.table_upload_wire(*b)
            for oj in op_jsons:
                nxt = rb.table_op_resident(oj, [cur])
                rb.table_free(cur)
                cur = nxt
            outs.append(rb.table_download_wire(cur))
            rb.table_free(cur)
        return _time.perf_counter() - t0, outs

    def plan_stream():
        t0 = _time.perf_counter()
        outs = []
        for b in batches:
            tid = rb.table_upload_wire(*b)
            res = rb.table_plan_resident(plan_json, [tid])
            rb.table_free(tid)
            outs.append(rb.table_download_wire(res))
            rb.table_free(res)
        return _time.perf_counter() - t0, outs

    def piped_stream():
        t0 = _time.perf_counter()
        outs = rb.table_stream_wire(plan_json, batches)
        return _time.perf_counter() - t0, outs

    warm_reps = 3  # best-of: one warm pass is scheduler-noise-bound
    try:
        srt_config.set_flag("PIPELINE", "off")
        sync_cold_s, sync_outs = per_op_stream()
        sync_warm_s = min(per_op_stream()[0] for _ in range(warm_reps))
        plan_stream()
        plan_warm_s = min(plan_stream()[0] for _ in range(warm_reps))
        off_outs = piped_stream()[1]  # PIPELINE=off == today's sync path
        srt_config.set_flag("PIPELINE", str(depth))
        piped_cold_s, piped_outs = piped_stream()
        # reset so the entry's metrics block and the pipeline numbers
        # cover only WARM pipelined passes (no compile-phase noise);
        # the snapshot is taken AFTER all warm reps so overlap_ms and
        # the wall clock it is divided by cover the same passes
        srt_metrics.reset()
        warm_times = [piped_stream()[0] for _ in range(warm_reps)]
        pipeline_mod.drain()
        snap = _metrics_snapshot() or {}
        piped_warm_s = min(warm_times)
        piped_total_s = sum(warm_times)
    finally:
        srt_config.clear_flag("PIPELINE")
    assert off_outs == sync_outs, "stream entry changed sync results"
    assert piped_outs == sync_outs, "pipelined stream changed results"
    ctr = snap.get("counters", {})
    hists = snap.get("histograms", {})
    overlap_ms = float(hists.get("pipeline.overlap_ms", {}).get("sum", 0))
    stall_ms = float(hists.get("pipeline.stall_ms", {}).get("sum", 0))
    rows = sum(b[4] for b in batches)
    return {
        "config": "dispatch",
        "name": f"pipelined_stream_{n_batches}x{len(chain)}op_d{depth}",
        "string_width": str_width,
        "rows": rows,
        "distinct_batch_sizes": len(set(sizes)),
        "host_cpus": _os.cpu_count(),
        "sync_cold_seconds": round(sync_cold_s, 4),
        "sync_warm_seconds": round(sync_warm_s, 4),
        "sync_plan_warm_seconds": round(plan_warm_s, 4),
        "pipelined_cold_seconds": round(piped_cold_s, 4),
        "pipelined_warm_seconds": round(piped_warm_s, 4),
        "warm_speedup": round(sync_warm_s / piped_warm_s, 2),
        "vs_plan_sync": round(plan_warm_s / piped_warm_s, 2),
        "rows_per_s": round(rows / piped_warm_s, 1),
        "pipeline": {
            "depth": depth,
            "batches": n_batches,
            "overlap_ms": round(overlap_ms, 2),
            # overlap and wall cover the SAME warm passes (all of them)
            "overlap_fraction": round(
                overlap_ms / max(piped_total_s * 1e3, 1e-9), 3
            ),
            "stall_ms": round(stall_ms, 2),
            "stalls": int(ctr.get("pipeline.stalls", 0)),
            "replays": int(ctr.get("pipeline.replays", 0)),
            "enqueued": int(ctr.get("pipeline.enqueued", 0)),
            "donated_bytes": int(
                snap.get("bytes", {}).get("hbm.donated_bytes", 0)
            ),
            "donations": int(ctr.get("hbm.donations", 0)),
            "uploads_batched": int(
                ctr.get("wire.upload.batched", 0)
            ),
        },
        "platform": platform,
    }


def bench_serving_multiquery(platform, n_sessions=3, n_batches=5):
    """Serving-daemon bench (ISSUE 9 tentpole): TPC-DS-shaped plan
    mixes (the q5 / q23 / q64 silhouettes: filter->agg,
    filter->sort->agg, filter->cast->sort->agg) served as CONCURRENT
    tenant sessions through one long-lived daemon.

    Three phases:

      serial    every mix over its batch stream via ``table_plan_wire``
                — the parity reference and the no-daemon baseline.
      warm      ONE daemon session streams all mixes against a cleared
                compile cache: it pays every compile (the recorded
                ``warm_misses``).
      served    ``n_sessions`` NEW sessions stream the same mixes
                concurrently. Their compiled-executable lookups land in
                the process-global ``buckets.cached_jit`` the warm
                session populated — the ``cross_session_hits`` /
                ``hit_rate`` headline (misses here stay ~0: tenant B
                never re-pays tenant A's compiles).

    Byte parity of every served result against the serial reference is
    asserted, as is zero leaked resident tables after shutdown. The
    structured ``serving`` block carries sessions, shed count, merged
    p50/p95 queue wait, and the cross-session cache-hit rate.
    SRT_BENCH_SERVE_ROWS shrinks the shape for smoke runs
    (ci/smoke-observability.sh drives this config)."""
    import os as _os
    import threading as _threading
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu import serving
    from spark_rapids_jni_tpu.utils import buckets as srt_buckets
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics

    _metrics_enable()  # the cache/shed/wait counters ARE the story
    base = int(_os.environ.get("SRT_BENCH_SERVE_ROWS", 60_000))
    rng = np.random.default_rng(59)
    sizes = sorted(
        int(s)
        for s in rng.integers(base // 2, base * 3 // 2 + 2, n_batches)
    )
    i64 = int(dt.TypeId.INT64)
    b8 = int(dt.TypeId.BOOL8)
    mixes = {
        # q5 silhouette: scan -> filter -> aggregate
        "q5": [
            {"op": "filter", "mask": 2},
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "sum"}]},
        ],
        # q23 silhouette: filter -> order -> aggregate
        "q23": [
            {"op": "filter", "mask": 2},
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "sum"}]},
        ],
        # q64 silhouette: filter -> project(cast) -> order -> aggregate
        "q64": [
            {"op": "filter", "mask": 2},
            {"op": "cast", "column": 1,
             "type_id": int(dt.TypeId.FLOAT64)},
            {"op": "sort_by", "keys": [{"column": 0}]},
            {"op": "groupby", "by": [0],
             "aggs": [{"column": 1, "agg": "sum"}]},
        ],
    }
    batches = []
    for nn in sizes:
        kk = rng.integers(0, 1000, nn, dtype=np.int64)
        vv = rng.integers(-100, 100, nn, dtype=np.int64)
        mm = (vv > 0).astype(np.uint8)
        batches.append((
            [i64, i64, b8], [0, 0, 0],
            [kk.tobytes(), vv.tobytes(), mm.tobytes()],
            [None, None, None], nn,
        ))

    def serial_pass():
        t0 = _time.perf_counter()
        outs = {
            name: [
                rb.table_plan_wire(json.dumps(ops), *b) for b in batches
            ]
            for name, ops in mixes.items()
        }
        return _time.perf_counter() - t0, outs

    serial_cold_s, serial_outs = serial_pass()
    serial_warm_s = serial_pass()[0]

    got = {}
    errs = []
    with serving.serve() as srv:
        # warm phase: ONE session pays every compile against a cleared
        # cache, so the served phase's hits are strictly CROSS-session
        srt_buckets.cache_clear()
        srt_metrics.reset()
        with serving.Client(srv.port, name="warm") as w:
            for name, ops in mixes.items():
                w.stream(ops, batches)
        warm_snap = _metrics_snapshot() or {}
        warm_misses = int(
            warm_snap.get("counters", {}).get("compile_cache.miss", 0)
        )

        srt_metrics.reset()
        clients = [
            serving.Client(
                srv.port, name=f"tenant-{i}-{list(mixes)[i % 3]}"
            ).connect()
            for i in range(n_sessions)
        ]

        def run(i):
            try:
                got[i] = {
                    name: clients[i].stream(ops, batches)
                    for name, ops in mixes.items()
                }
            except BaseException as e:  # pragma: no cover
                errs.append(e)

        t0 = _time.perf_counter()
        threads = [
            _threading.Thread(target=run, args=(i,))
            for i in range(n_sessions)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        served_s = _time.perf_counter() - t0
        snap = _metrics_snapshot() or {}
        # merged queue-wait percentiles over every live tenant session
        # (in-process peek at the raw wait samples: exact, not a
        # percentile-of-percentiles)
        waits = sorted(
            wt
            for s in srv._sessions.values()
            for wt in list(s._waits)
        )
        stats_doc = srv.stats()
        docs = stats_doc["sessions"]
        durability = stats_doc.get("durability", {})
        for c in clients:
            c.close()
    if errs:
        raise errs[0]
    for i in range(n_sessions):
        assert got[i] == serial_outs, (
            f"served results for tenant {i} diverge from serial"
        )
    leaked = rb.resident_table_count()
    assert leaked == 0, f"{leaked} resident table(s) leaked"

    def pct(p):
        if not waits:
            return 0.0
        i = min(int(p * (len(waits) - 1) + 0.5), len(waits) - 1)
        return round(waits[i] * 1e3, 3)

    ctr = snap.get("counters", {})
    hits = int(ctr.get("compile_cache.hit", 0))
    misses = int(ctr.get("compile_cache.miss", 0))
    rows = sum(b[4] for b in batches) * len(mixes)
    return {
        "config": "serving",
        "name": f"serving_multiquery_{n_sessions}x{len(mixes)}mix",
        "rows": rows,
        "host_cpus": _os.cpu_count(),
        "serial_cold_seconds": round(serial_cold_s, 4),
        "serial_warm_seconds": round(serial_warm_s, 4),
        "served_seconds": round(served_s, 4),
        "rows_per_s": round(rows * n_sessions / served_s, 1),
        "serving": {
            "sessions": n_sessions,
            "mixes": sorted(mixes),
            "batches_per_mix": n_batches,
            "requests": int(ctr.get("serving.requests", 0)),
            "shed": int(ctr.get("serving.shed", 0)),
            "queue_wait_ms_p50": pct(0.50),
            "queue_wait_ms_p95": pct(0.95),
            "warm_misses": warm_misses,
            "cross_session_hits": hits,
            "cross_session_misses": misses,
            "cross_session_hit_rate": round(
                hits / max(hits + misses, 1), 3
            ),
            "sessions_detail": [
                {
                    "name": d["name"],
                    "requests": d["requests"],
                    "shed": d["shed"],
                    "queue_wait": d["queue_wait"],
                    "donated_credit_bytes": d["donated_credit_bytes"],
                }
                for d in docs
            ],
            "leaked_tables": leaked,
            # the durable-plane doc (ISSUE 14): checkpoint/restore
            # counters when SPARK_RAPIDS_TPU_DURABLE=on, and proof the
            # default run carries no journaling cost (enabled: False)
            "durability": durability,
        },
        "platform": platform,
    }


def bench_resident_chain(platform, n=None):
    """VERDICT item 4 bench: a 3-op chain (filter -> sort -> groupby)
    through device-RESIDENT table handles vs the bytes-wire path that
    round-trips every op's inputs/outputs through host memory.
    SRT_BENCH_RESIDENT_ROWS shrinks the shape for smoke runs
    (ci/smoke-observability.sh drives this config to produce trace +
    flight artifacts in seconds, not minutes)."""
    import os as _os
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb

    if n is None:
        n = int(_os.environ.get("SRT_BENCH_RESIDENT_ROWS", 4_000_000))

    rng = np.random.default_rng(9)
    k = rng.integers(0, 1000, n, dtype=np.int64)
    v = rng.integers(-100, 100, n, dtype=np.int64)
    mask = (v > 0).astype(np.uint8)
    i64 = int(dt.TypeId.INT64)
    b8 = int(dt.TypeId.BOOL8)
    op_filter = json.dumps({"op": "filter", "mask": 2})
    op_sort = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
    op_group = json.dumps(
        {"op": "groupby", "by": [0],
         "aggs": [{"column": 1, "agg": "sum"}]}
    )

    def wire_chain():
        t1 = rb.table_op_wire(
            op_filter, [i64, i64, b8], [0, 0, 0],
            [k.tobytes(), v.tobytes(), mask.tobytes()],
            [None, None, None], n,
        )
        t2 = rb.table_op_wire(op_sort, t1[0], t1[1], t1[2], t1[3], t1[4])
        t3 = rb.table_op_wire(op_group, t2[0], t2[1], t2[2], t2[3], t2[4])
        return t3

    def resident_chain():
        tid = rb.table_upload_wire(
            [i64, i64, b8], [0, 0, 0],
            [k.tobytes(), v.tobytes(), mask.tobytes()],
            [None, None, None], n,
        )
        f = rb.table_op_resident(op_filter, [tid])
        s = rb.table_op_resident(op_sort, [f])
        g = rb.table_op_resident(op_group, [s])
        out = rb.table_download_wire(g)
        for t in (tid, f, s, g):
            rb.table_free(t)
        return out

    def best_of(fn, reps=3):
        out = fn()  # warm/compile
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            out = fn()
            best = min(best, _time.perf_counter() - t0)
        return best, out

    wire_s, wire_out = best_of(wire_chain)
    res_s, res_out = best_of(resident_chain)
    assert wire_out[4] == res_out[4], "chain row counts differ"
    assert wire_out[2][1] == res_out[2][1], "chain sums differ"
    return {
        "config": "resident-chain",
        "name": "filter_sort_groupby_3op_chain",
        "rows": n,
        "wire_seconds": round(wire_s, 4),
        "resident_seconds": round(res_s, 4),
        "speedup": round(wire_s / res_s, 2),
        "platform": platform,
    }


def bench_parquet_pipeline(platform, n_groups=4, rows_per_group=1_500_000):
    """Config-5 shape: Parquet scan -> predicate pushdown -> filter ->
    groupby-agg, streamed per row group, with and without the
    decode/compute prefetch overlap (round-3 VERDICT item 10)."""
    import tempfile
    import time as _time

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.io.parquet import scan_parquet
    from spark_rapids_jni_tpu.io.predicates import col as pred_col
    from spark_rapids_jni_tpu.ops.groupby import (
        GroupbyAgg,
        groupby_aggregate,
    )

    rng = np.random.default_rng(21)
    n = n_groups * rows_per_group
    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/bench.parquet"
        pq.write_table(
            pa.table({
                "k": rng.integers(0, 1000, n),
                "v": rng.standard_normal(n),
                "q": rng.integers(0, 100, n),
            }),
            path,
            row_group_size=rows_per_group,
        )
        predicate = pred_col("q") > 19  # ~80% selectivity

        def pipeline(prefetch):
            t0 = _time.perf_counter()
            total = 0
            for batch in scan_parquet(
                path, filters=predicate, prefetch=prefetch
            ):
                agg = groupby_aggregate(
                    batch, ["k"], [GroupbyAgg("v", "sum")]
                )
                total += int(agg.row_count)
            return _time.perf_counter() - t0, total

        pipeline(0)  # compile warmup: both timed runs reuse the cache
        serial_s, t1 = pipeline(0)
        overlap_s, t2 = pipeline(2)
        assert t1 == t2
    return {
        "config": 5,
        # workload size in the name: the r3 shrink from 6x2M to 4x1.5M
        # silently broke round-over-round comparability (ADVICE r3)
        "name": f"parquet_scan_filter_agg_{n_groups}x{rows_per_group // 1000}k",
        "rows": n,
        "serial_seconds": round(serial_s, 3),
        "prefetch_seconds": round(overlap_s, 3),
        "overlap_speedup": round(serial_s / overlap_s, 2),
        "rows_per_s": round(n / overlap_s, 1),
        "platform": platform,
    }


def bench_chunk_sort_ab(platform, total_rows=16_777_216, t=8192):
    """Pallas VMEM bitonic sort vs XLA batched lax.sort on the chunked-
    groupby phase-1 shape — the measurement that decides whether the
    chunked design's 'batched small sorts stay in VMEM' bet needs the
    explicit kernel (kernels/bitonic_sort.py) or XLA already delivers."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_jni_tpu.kernels.bitonic_sort import batched_sort_u64

    c = total_rows // t
    rng = np.random.default_rng(29)
    key = jnp.asarray(rng.integers(0, 1 << 40, (c, t)).astype(np.uint64))
    val = jnp.asarray(rng.integers(-1000, 1000, (c, t)))
    jax.block_until_ready(key)

    def xla_sort(k, v):
        iota = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (c, t))
        return jax.lax.sort((k, iota, v), num_keys=1, is_stable=True)

    xla_fn = jax.jit(xla_sort)
    med_x, mn_x, std_x, out_x = _timeit(xla_fn, [(key, val)], reps_per_input=3)

    # Mosaic on the chip; the interpreter tier only exists so a CPU
    # smoke of this config runs the same code (its timing is meaningless)
    interp = platform == "cpu"
    pl_fn = jax.jit(
        lambda k, v: batched_sort_u64(k, v, interpret=interp)
    )
    med_p, mn_p, std_p, out_p = _timeit(pl_fn, [(key, val)], reps_per_input=3)
    # equality spot check on one chunk
    assert np.array_equal(
        np.asarray(out_x[0][0]), np.asarray(out_p[0][0])
    ), "pallas sort diverges from lax.sort"
    bytes_moved = total_rows * 20 * 2
    e1 = _entry("chunk-sort", f"lax_sort_{c}x{t}", total_rows, med_x,
                mn_x, std_x, bytes_moved, platform)
    e2 = _entry("chunk-sort", f"pallas_bitonic_{c}x{t}", total_rows,
                med_p, mn_p, std_p, bytes_moved, platform)
    e2["vs_lax"] = round(med_x / med_p, 2)

    # u32 single-word arm: the packed-word contract (distinct keys,
    # permutation in the embedded iota, values follow by gather)
    from spark_rapids_jni_tpu.kernels.bitonic_sort import batched_sort_u32

    iota_bits = (t - 1).bit_length()
    key32 = jnp.asarray(
        (
            (rng.integers(0, 1 << (32 - iota_bits), (c, t),
                          dtype=np.uint64) << iota_bits)
            | np.arange(t, dtype=np.uint64)[None, :]
        ).astype(np.uint32)
    )
    jax.block_until_ready(key32)

    def u32_sort(k, v):
        s = batched_sort_u32(k, interpret=interp)[0]
        perm = (s & jnp.uint32(t - 1)).astype(jnp.int32)
        return s, jnp.take_along_axis(v, perm, axis=1)

    u32_fn = jax.jit(u32_sort)
    med_u, mn_u, std_u, out_u = _timeit(
        u32_fn, [(key32, val)], reps_per_input=3
    )
    assert np.array_equal(
        np.asarray(out_u[0][0]), np.sort(np.asarray(key32[0]))
    ), "u32 pallas sort diverges from np.sort"
    bytes_u32 = total_rows * 12 * 2  # u32 word + i64 value in/out
    e3 = _entry("chunk-sort", f"pallas_u32_gather_{c}x{t}", total_rows,
                med_u, mn_u, std_u, bytes_u32, platform)
    e3["vs_lax"] = round(med_x / med_u, 2)
    return [e1, e2, e3]


def bench_strings(platform, n=10_000_000, pad=128):
    """Round-4 VERDICT item 5 bench: literal contains at pad=128 via the
    shift-or scan, and a 10M x 10M string-key join through automatic
    dictionary encoding."""
    import jax

    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops import strings as strings_mod
    from spark_rapids_jni_tpu.ops.join import inner_join

    from spark_rapids_jni_tpu import dtype as dt_mod

    rng = np.random.default_rng(17)
    # contains: random a-z bytes, lengths ~uniform(0, pad)
    lens = rng.integers(0, pad + 1, n).astype(np.int32)
    mat = rng.integers(97, 123, (n, pad), dtype=np.uint8)
    mat[np.arange(pad)[None, :] >= lens[:, None]] = 0
    col = Column(
        jax.numpy.asarray(mat), dt_mod.STRING, None,
        jax.numpy.asarray(lens),
    )
    jax.block_until_ready(col.data)
    fn = jax.jit(lambda c: strings_mod.contains(c, "qzx"))
    med, mn, std, out = _timeit(fn, [(col,)], reps_per_input=3)
    e1 = _entry(
        "strings", f"contains_{n // 1_000_000}M_pad{pad}", n, med, mn,
        std, n * pad, platform,
    )

    # string-key join: nj distinct 12-byte keys, each side drawing nj
    # rows from them, so the expected output is ~nj rows (~1 match/row).
    # The previous 100k-unique pool made E[matches] ~ nj^2/100k ~ 1e9
    # rows — a 30-50 GB materialization that would OOM the 16 GiB chip
    # (ADVICE r4, medium). Byte matrix built vectorized host-side: 10M
    # python strings would dominate the setup.
    nj = n
    klen = 12

    def key_matrix(ids):
        m = np.empty((ids.size, klen), np.uint8)
        m[:, 0] = ord("k")
        x = ids.astype(np.int64)
        for j in range(klen - 1, 0, -1):
            m[:, j] = ord("0") + (x % 10)
            x //= 10
        return m

    def str_table(idx, name):
        return Table(
            [
                Column(
                    jax.numpy.asarray(key_matrix(idx)), dt_mod.STRING,
                    None,
                    jax.numpy.full((nj,), klen, jax.numpy.int32),
                ),
                Column.from_numpy(np.arange(nj, dtype=np.int64)),
            ],
            ["k", name],
        )

    lt = str_table(rng.integers(0, nj, nj), "lv")
    rt = str_table(rng.integers(0, nj, nj), "rv")
    jax.block_until_ready(lt.columns[0].data)
    t0 = time.perf_counter()
    out = inner_join(lt, rt, ["k"])
    np.asarray(out.columns[1].data.ravel()[-1:])
    join_s = time.perf_counter() - t0
    e2 = {
        "config": "strings",
        # uniques pool in the name: changing it changes E[matches]
        "name": (
            f"string_key_join_{nj // 1_000_000}Mx{nj // 1_000_000}M"
            f"_u{nj // 1_000_000}M"
        ),
        "rows": 2 * nj,
        "seconds_median": round(join_s, 4),
        "matches": out.row_count,
        "platform": platform,
    }
    return [e1, e2]


def bench_parquet_device(platform, n_groups=4, rows_per_group=1_500_000):
    """Round-4 VERDICT item 4 A/B: scan throughput of the device page
    decoder (host parses headers, uploads ENCODED bytes, chip expands)
    vs the host-Arrow-decode + upload path, on the config-5 shape."""
    import tempfile
    import time as _time

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_jni_tpu.io.parquet import scan_parquet

    rng = np.random.default_rng(23)
    n = n_groups * rows_per_group
    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/bench_dev.parquet"
        pq.write_table(
            pa.table({
                "k": rng.integers(0, 1000, n),          # dict-encodable
                "v": rng.standard_normal(n),            # PLAIN doubles
                "q": rng.integers(0, 100, n).astype(np.int32),
            }),
            path,
            row_group_size=rows_per_group,
        )

        def scan(device):
            t0 = _time.perf_counter()
            total = 0
            checksum = 0.0
            for batch in scan_parquet(path, device_decode=device):
                # force materialization on device: a reduction + fetch
                total += batch.row_count
                checksum += float(
                    np.asarray(batch["q"].data.astype(np.int64).sum())
                )
            return _time.perf_counter() - t0, total, checksum

        scan(False)  # warm compile + page cache
        scan(True)
        host_s, t1, c1 = scan(False)
        dev_s, t2, c2 = scan(True)
        assert t1 == t2 and c1 == c2, "device decode changed the data"
    return {
        "config": 5,
        "name": f"parquet_device_decode_{n_groups}x{rows_per_group // 1000}k",
        "rows": n,
        "host_decode_seconds": round(host_s, 3),
        "device_decode_seconds": round(dev_s, 3),
        "speedup": round(host_s / dev_s, 2),
        "platform": platform,
    }


def bench_tpcds(platform, scale=None):
    """Configs 4-5 with REAL data (round-4 VERDICT item 6): seeded
    Parquet star schema at SRT_TPCDS_SCALE (default SF1: 2.88M
    store_sales rows), streamed scan->join->agg q5/q23/q64 with pandas
    oracle verdicts recorded per query."""
    import os

    from benchmarks import tpcds

    if scale is None:
        scale = float(os.environ.get("SRT_TPCDS_SCALE", "1.0"))
    cache = f"/tmp/srt_tpcds_sf{scale}"
    if not os.path.exists(os.path.join(cache, "store_sales.parquet")):
        _progress(f"generating TPC-DS parquet at scale {scale} -> {cache}")
        tpcds.generate_parquet(cache, scale=scale, seed=0)
    entries = tpcds.run_all(cache, prefetch=2)
    for e in entries:
        e.update({"config": 5, "scale": scale, "platform": platform})
    return entries


def bench_tpcds_distributed(devices: int = 8, scale: float = 0.05,
                            timeout_s: float = 1800.0):
    """Config 4: the same Parquet files through the mesh-distributed
    q5/q23/q64 DAGs on the virtual CPU mesh (simulation wall-clock).

    ``timeout_s`` bounds the WHOLE arm (parquet generation + the mesh
    subprocess); overrunning raises subprocess.TimeoutExpired, which
    the ``_guard`` caller turns into a structured ``{type:"timeout"}``
    failure record — the r04 rc=124 postmortem: this arm used to start
    with minutes of budget left and run unbounded to the driver's
    kill."""
    import os
    import subprocess

    t0 = time.time()
    cache = f"/tmp/srt_tpcds_sf{scale}"
    if not os.path.exists(os.path.join(cache, "store_sales.parquet")):
        from benchmarks import tpcds

        tpcds.generate_parquet(cache, scale=scale, seed=0)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    code = (
        "import jax, json; jax.config.update('jax_platforms','cpu'); "
        "from benchmarks import tpcds; "
        f"print('TPCDS_DIST ' + json.dumps(tpcds.run_distributed({cache!r}, {devices})))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=max(timeout_s - (time.time() - t0), 60.0), env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in out.stdout.splitlines():
        if line.startswith("TPCDS_DIST "):
            got = json.loads(line[len("TPCDS_DIST "):])
            for e in got:
                e.update({"config": 4, "scale": scale, "platform": "cpu-mesh"})
            return got
    _progress(f"tpcds distributed produced no JSON: {out.stderr[-400:]}")
    return None


def _arm_cap(default_s: float) -> float:
    """Per-arm wall-clock slice for the CPU-mesh tail stages.

    SRT_BENCH_ARM_TIMEOUT_S overrides the default so a smoke run can
    bound every tail arm tightly — the arm dies to its own subprocess
    timeout (a structured {type:"timeout"} entry) instead of running
    into the driver's rc=124 kill and eating the headline emit."""
    raw = os.environ.get("SRT_BENCH_ARM_TIMEOUT_S", "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            _progress(f"ignoring bad SRT_BENCH_ARM_TIMEOUT_S={raw!r}")
    return default_s


def _skew_child(timeout_s: float, rows: int = 10_000_000,
                skew_split=None):
    """One benchmarks.run zipf-skew child on the 8-device CPU mesh;
    returns its parsed JSON entry (or None). ``skew_split`` pins the
    adaptive splitter via the child's env for the A/B arm."""
    import subprocess

    env = dict(os.environ)
    # benchmarks.run sees the host-device-count flag + --devices and
    # forces jax_platforms=cpu through the config API itself (env
    # JAX_PLATFORMS alone is ineffective against the axon plugin)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    if skew_split is not None:
        env["SPARK_RAPIDS_TPU_SKEW_SPLIT"] = "1" if skew_split else "0"
    out = subprocess.run(
        [sys.executable, "-m", "benchmarks.run", "--configs", "skew",
         "--devices", "8", "--rows", str(rows)],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    _progress(f"skew run produced no JSON: {out.stderr[-500:]}")
    return None


def bench_distributed_skew(timeout_s: float = 900.0):
    """Config 4 shape at 1e7 rows: zipf-skew distributed groupby through
    the ragged-compact exchange on the virtual 8-device CPU mesh (the
    multi-chip path; numbers are CPU-simulation, labeled as such).

    An overrun of ``timeout_s`` raises subprocess.TimeoutExpired out to
    ``_guard``'s structured ``{type:"timeout"}`` record — this used to
    be swallowed into a bare progress line, leaving the headline JSON
    with no trace of the arm at all."""
    import subprocess

    try:
        return _skew_child(timeout_s)
    except subprocess.TimeoutExpired:
        raise
    except Exception as e:  # pragma: no cover
        _progress(f"skew run failed: {e}")
    return None


def bench_mesh_skew_adaptive(timeout_s: float = 900.0):
    """The adaptive-skew A/B (ISSUE 17): the BENCH_r04 zipf config run
    twice on the 8-device CPU mesh — splitting off (the r04 behaviour:
    exchange capacity sized from the raw hot-destination counts) vs on
    (hot keys salted across sub-partitions with partial-agg before the
    exchange). Emits one entry whose structured ``skew`` block carries
    both arms' seconds / recv_buffer_rows / peak_rss plus the deltas.

    Each child gets half the slice; an overrun raises TimeoutExpired
    out to _guard's typed record so the headline line survives."""
    half = max(timeout_s / 2.0, 1.0)
    t0 = time.time()
    off = _skew_child(half, skew_split=False)
    rest = max(timeout_s - (time.time() - t0), 1.0)
    on = _skew_child(min(half, rest), skew_split=True)
    if off is None or on is None:
        _progress("skew A/B incomplete: "
                  f"off={'ok' if off else 'lost'} "
                  f"on={'ok' if on else 'lost'}")
        return None

    def _arm(e):
        return {
            "seconds": e.get("seconds"),
            "recv_buffer_rows": e.get("recv_buffer_rows_per_device"),
            "peak_rss_mb": e.get("peak_rss_mb"),
            "max_over_mean": e.get("max_over_mean"),
            "skew_splits": e.get("skew_splits", 0),
        }

    def _delta(key):
        a, b = off.get(key), on.get(key)
        if a is None or b is None:
            return None
        return round(a - b, 4)

    from spark_rapids_jni_tpu.utils import config as srt_config

    return {
        "config": "4-skew-adaptive",
        "name": "mesh_skew_adaptive",
        "rows": on.get("rows"),
        "devices": on.get("devices"),
        "platform": on.get("platform"),
        "skew": {
            "factor": float(srt_config.get_flag("SKEW_SPLIT_FACTOR")),
            "splits": on.get("skew_splits", 0),
            "off": _arm(off),
            "on": _arm(on),
            "deltas": {
                "seconds": _delta("seconds"),
                "recv_buffer_rows": _delta(
                    "recv_buffer_rows_per_device"),
                "peak_rss_mb": _delta("peak_rss_mb"),
            },
        },
    }


def _guard(entries, name, fn):
    """Run one config; a failure records a structured failure entry
    instead of killing the whole ladder (the driver needs the JSON
    line). An arm that overruns its own wall-clock slice
    (subprocess.TimeoutExpired) records the typed ``{type:"timeout"}``
    failure — the arm is sacrificed, the headline line survives."""
    import subprocess

    _progress(name)
    t0 = time.time()
    try:
        out = fn()
    except subprocess.TimeoutExpired as e:
        slice_s = float(e.timeout or 0.0)
        _progress(f"  TIMEOUT after {slice_s:.0f}s")
        entries.append(_failure_record(
            name, f"timeout {slice_s:.0f}s", exc_type="timeout",
            elapsed_s=time.time() - t0,
        ))
        return None
    except Exception as e:  # pragma: no cover
        _progress(f"  FAILED: {e}")
        entries.append(
            _failure_record(name, e, elapsed_s=time.time() - t0)
        )
        return None
    if out is None:
        return None
    got = out if isinstance(out, list) else [out]
    # snapshot-then-RESET: the registry is process-wide, so without the
    # reset a second in-process config's block would also carry the
    # first config's counters (the subprocess path is per-config by
    # virtue of the fresh process)
    snap = _metrics_snapshot(reset=True)
    prof = _profile_block(reset=True)
    for g in got:
        _progress(f"  {g}")  # progress line WITHOUT the bulky block
        if snap is not None:
            g.setdefault("metrics", snap)
        if prof is not None:
            g.setdefault("profile", prof)
    entries.extend(got)
    return out


def bench_spill_stream(platform, tables=12, rows=1 << 15):
    """Config: tiered-memory degradation (utils/spill.py). A resident
    working set ~2x an artificially SHRUNK HBM budget streams a sort
    over every table for two full passes — the second pass repages what
    the first pass spilled, so the LRU cycles the whole set through
    host/disk — and must come back byte-identical to the unconstrained
    run: the RAPIDS plugin's spill-instead-of-die contract, priced.
    Reported: slowdown vs unconstrained plus the spill counters that
    prove the constrained run actually spilled."""
    import time as _time

    import numpy as np

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu.utils import config as srt_config
    from spark_rapids_jni_tpu.utils import hbm as hbm_mod
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics
    from spark_rapids_jni_tpu.utils import spill as spill_mod

    _metrics_enable()
    rng = np.random.default_rng(53)
    i64 = int(dt.TypeId.INT64)
    op_sort = json.dumps({"op": "sort_by", "keys": [{"column": 0}]})
    batches = [
        rng.integers(-(1 << 40), 1 << 40, rows, dtype=np.int64)
        for _ in range(tables)
    ]

    def upload(arr):
        return rb.table_upload_wire(
            [i64], [0], [arr.tobytes()], [None], rows
        )

    def run_stream():
        """Upload the whole working set, then two round-robin sort
        passes over it (each keeps its input resident); returns
        (seconds, downloads) with everything freed again."""
        ids = [upload(a) for a in batches]
        t0 = _time.perf_counter()
        outs = []
        for _ in range(2):
            for tid in ids:
                res = rb.table_op_resident(op_sort, [tid])
                outs.append(rb.table_download_wire(res))
                rb.table_free(res)
        dt_s = _time.perf_counter() - t0
        for tid in ids:
            rb.table_free(tid)
        return dt_s, outs

    def norm(outs):
        return [
            tuple(bytes(d) for d in o[2] if d is not None) for o in outs
        ]

    # unconstrained reference first (spill off, default budget)
    srt_config.set_flag("SPILL", False)
    srt_metrics.reset()
    base_s, base_outs = run_stream()
    base_s = min(base_s, run_stream()[0])

    # shrink the budget to HALF the resident working set and turn the
    # spill tier on: the stream must now degrade, not die
    working_set = tables * rows * 8
    gib = 1 << 30
    shrunk_gb = (working_set / 2) / (1.0 - hbm_mod.RESERVE_FRACTION) / gib
    srt_config.set_flag("HBM_BUDGET_GB", shrunk_gb)
    srt_config.set_flag("SPILL", "on")
    try:
        srt_metrics.reset()
        spill_s, spill_outs = run_stream()
        snap = _metrics_snapshot() or {}
    finally:
        srt_config.set_flag("SPILL", False)
        srt_config.set_flag("HBM_BUDGET_GB", 0)
    ctr = snap.get("counters", {})
    byt = snap.get("bytes", {})
    assert norm(spill_outs) == norm(base_outs), (
        "spilled stream changed results"
    )
    assert rb.resident_table_count() == 0, "spill arm leaked tables"
    assert spill_mod.spill_file_count() == 0, "spill arm leaked files"
    evictions = int(ctr.get("spill.evictions", 0))
    assert evictions > 0, (
        f"working set {working_set} B under budget "
        f"{int(shrunk_gb * gib)} B never spilled"
    )
    return {
        "config": "spill",
        "name": f"spill_stream_{tables}x{rows}",
        "rows": tables * rows,
        "working_set_bytes": working_set,
        "budget_bytes": int(shrunk_gb * gib * (1.0 - hbm_mod.RESERVE_FRACTION)),
        "unconstrained_seconds": round(base_s, 4),
        "spill_seconds": round(spill_s, 4),
        "slowdown": round(spill_s / base_s, 2) if base_s else None,
        "byte_identical": True,
        "spill": {
            "evictions": evictions,
            "repages": int(ctr.get("spill.repages", 0)),
            "demotions": int(ctr.get("spill.demotions", 0)),
            "bytes_out": int(byt.get("spill.bytes_out", 0)),
            "bytes_in": int(byt.get("spill.bytes_in", 0)),
        },
        "platform": platform,
    }


def bench_kernel_ab(platform, workload, total_rows=2_097_152,
                    batch_rows=None):
    """Config: the Pallas kernel tier A/B (kernels/registry.py) — the
    SAME resident dispatch stream with SPARK_RAPIDS_TPU_KERNELS=on vs
    off. Batches sit inside the kernel predicates' envelope (pow2
    bucket, within the VMEM bounds) so the ON arm actually launches;
    the entry carries the kernel.launches/declines/fallbacks counters
    that prove it, and a clean run must report ZERO fallbacks (the
    tier's never-changes-bytes contract, byte-checked here on the last
    batch and exhaustively by tests/test_kernel_tier.py).
    SRT_BENCH_KERNEL_ROWS scales total_rows for smoke runs."""
    import os as _os
    import time as _time

    from spark_rapids_jni_tpu import dtype as dt
    from spark_rapids_jni_tpu import runtime_bridge as rb
    from spark_rapids_jni_tpu.utils import buckets as buckets_mod
    from spark_rapids_jni_tpu.utils import config as srt_config
    from spark_rapids_jni_tpu.utils import metrics as srt_metrics

    _metrics_enable()  # the kernel.* counters ARE this config's story
    if batch_rows is None:
        # each workload's largest pow2 batch inside its kernel's VMEM
        # predicate: packed_sort carries (3 + 4 payload) u32 words/row
        # against SORT_MAX_WORDS, the hash kernels bound rows directly
        batch_rows = (1 << 14) if workload == "sort" else (1 << 16)
    raw = _os.environ.get("SRT_BENCH_KERNEL_ROWS", "").strip()
    if raw:
        total_rows = max(batch_rows, int(raw))
    nb = max(1, total_rows // batch_rows)
    rng = np.random.default_rng(61)
    i64 = int(dt.TypeId.INT64)

    ids = []
    rest_ids = []
    if workload == "sort":
        chain = [{"op": "sort_by", "keys": [{"column": 0}]}]
        for _ in range(nb):
            k = rng.integers(-(1 << 40), 1 << 40, batch_rows,
                             dtype=np.int64)
            v = rng.integers(-1000, 1000, batch_rows, dtype=np.int64)
            ids.append(rb.table_upload_wire(
                [i64, i64], [0, 0], [k.tobytes(), v.tobytes()],
                [None, None], batch_rows,
            ))
    elif workload == "groupby":
        chain = [{"op": "groupby", "by": [0],
                  "aggs": [{"column": 1, "agg": "sum"},
                           {"column": 1, "agg": "count"}]}]
        for _ in range(nb):
            k = rng.integers(0, 50_000, batch_rows, dtype=np.int64)
            v = rng.integers(-1000, 1000, batch_rows, dtype=np.int64)
            ids.append(rb.table_upload_wire(
                [i64, i64], [0, 0], [k.tobytes(), v.tobytes()],
                [None, None], batch_rows,
            ))
    elif workload == "transpose":
        schema = [dt.INT64, dt.FLOAT64, dt.INT32, dt.BOOL8]
        chain = [
            {"op": "to_rows"},
            {"op": "from_rows",
             "type_ids": [int(d.id) for d in schema],
             "scales": [0] * len(schema)},
        ]
        for _ in range(nb):
            datas = [
                rng.integers(-(1 << 40), 1 << 40, batch_rows,
                             dtype=np.int64).tobytes(),
                rng.standard_normal(batch_rows).tobytes(),
                rng.integers(-(1 << 30), 1 << 30, batch_rows,
                             dtype=np.int32).tobytes(),
                rng.integers(0, 2, batch_rows).astype(np.bool_).tobytes(),
            ]
            ids.append(rb.table_upload_wire(
                [int(d.id) for d in schema], [0] * len(schema), datas,
                [None] * len(schema), batch_rows,
            ))
    elif workload == "join":
        # existing batched-join sizing: a resident unique-key build
        # side probed by every stream batch (the kernel's sweet spot —
        # duplicate build keys decline to the exact path)
        chain = [{"op": "join", "on": [0], "how": "inner"}]
        build_n = 1 << 16
        bk = rng.permutation(2 * build_n)[:build_n].astype(np.int64)
        bv = rng.integers(-1000, 1000, build_n, dtype=np.int64)
        rest_ids = [rb.table_upload_wire(
            [i64, i64], [0, 0], [bk.tobytes(), bv.tobytes()],
            [None, None], build_n,
        )]
        for _ in range(nb):
            k = rng.integers(0, 2 * build_n, batch_rows, dtype=np.int64)
            v = rng.integers(-1000, 1000, batch_rows, dtype=np.int64)
            ids.append(rb.table_upload_wire(
                [i64, i64], [0, 0], [k.tobytes(), v.tobytes()],
                [None, None], batch_rows,
            ))
    else:
        raise ValueError(f"unknown kernel A/B workload {workload!r}")

    def stream():
        """One full pass: every batch through the chain; the last
        output is downloaded (the completion barrier) and returned for
        the parity check."""
        t0 = _time.perf_counter()
        out = None
        for tid in ids:
            cur, owned = tid, False
            for op in chain:
                nxt = rb.table_op_resident(json.dumps(op),
                                           [cur] + rest_ids)
                if owned:
                    rb.table_free(cur)
                cur, owned = nxt, True
            out = rb.table_download_wire(cur)
            rb.table_free(cur)
        return _time.perf_counter() - t0, out

    warm_reps = 3

    def run_mode(mode):
        srt_config.set_flag("KERNELS", mode)
        try:
            buckets_mod.cache_clear()
            cold_s, _ = stream()
            srt_metrics.reset()
            warm_s, out = stream()
            for _ in range(warm_reps - 1):
                warm_s = min(warm_s, stream()[0])
            snap = _metrics_snapshot() or {}
        finally:
            srt_config.clear_flag("KERNELS")
        return cold_s, warm_s, out, snap

    try:
        off_cold_s, off_warm_s, off_out, _ = run_mode("off")
        on_cold_s, on_warm_s, on_out, snap = run_mode("on")
    finally:
        for tid in ids + rest_ids:
            rb.table_free(tid)
    assert off_out == on_out, (
        f"kernel tier changed bytes on {workload}"
    )
    ctr = snap.get("counters", {})
    launches = int(ctr.get("kernel.launches", 0))
    fallbacks = int(ctr.get("kernel.fallbacks", 0))
    assert launches > 0, f"kernel ON arm never launched ({workload})"
    assert fallbacks == 0, (
        f"clean kernel run reported {fallbacks} fallback(s) ({workload})"
    )
    return {
        "config": "kernel",
        "name": f"kernel_{workload}_ab_{nb}x{batch_rows}",
        "rows": nb * batch_rows,
        "batches": nb,
        "batch_rows": batch_rows,
        "kernel_off_cold_seconds": round(off_cold_s, 4),
        "kernel_off_warm_seconds": round(off_warm_s, 4),
        "kernel_on_cold_seconds": round(on_cold_s, 4),
        "kernel_on_warm_seconds": round(on_warm_s, 4),
        "warm_speedup": round(off_warm_s / on_warm_s, 3)
        if on_warm_s else None,
        "kernel": {
            "launches": launches,
            "declines": int(ctr.get("kernel.declines", 0)),
            "fallbacks": fallbacks,
        },
        "platform": platform,
    }


# Each device config runs in its OWN subprocess: a TPU worker crash or a
# tunnel hang inside one config must cost that one entry, not every
# config after it (observed: the r3 100M-join crash killed the client
# and the three remaining configs all failed with UNAVAILABLE).
_SUBPROCESS_CONFIGS = {
    "groupby1m": lambda p: bench_groupby(p, 1_000_000)[0],
    "groupby16m": lambda p: bench_groupby(p, 16_000_000)[0],
    "groupby100m": lambda p: bench_groupby(p, 100_000_000)[0],
    "groupby100m_chunked": bench_groupby_chunked,
    "groupby100m_packed": bench_groupby_packed,
    "groupby_highcard": bench_groupby_highcard,
    "groupby16m_packed": lambda p: bench_groupby_packed(p, 16_000_000),
    "groupby16m_chunked": lambda p: bench_groupby_chunked(p, 16_000_000),
    # flat single-level packing: values as sort payloads vs word-only
    # sort + permutation gather
    "groupby16m_gather": lambda p: bench_groupby(
        p, 16_000_000, values_via="gather"
    )[0],
    "groupby100m_gather": lambda p: bench_groupby(
        p, 100_000_000, values_via="gather"
    )[0],
    "groupby16m_flat_sort": lambda p: bench_groupby_flat(
        p, 16_000_000, "sort"
    ),
    "groupby16m_flat_gather": lambda p: bench_groupby_flat(
        p, 16_000_000, "gather"
    ),
    "groupby100m_flat_gather": lambda p: bench_groupby_flat(
        p, 100_000_000, "gather"
    ),
    # VMEM bitonic phase-1 engines (u32 word + value gather): the A/B
    # that decides whether the packed formulation wins its sort back
    "groupby16m_packed_pallas32": lambda p: bench_groupby_packed(
        p, 16_000_000, engine="pallas32", chunk_rows=1 << 17,
        chunk_segments=1 << 14,
    ),
    "groupby100m_packed_pallas32": lambda p: bench_groupby_packed(
        p, 100_000_000, engine="pallas32", chunk_rows=1 << 17,
        chunk_segments=1 << 14,
    ),
    "transpose": bench_transpose,
    "transpose_pallas": bench_transpose_pallas,
    "join": bench_join,
    "join_batched": bench_join_batched,
    "join_batched_packed": bench_join_batched_packed,
    "sort": bench_sort,
    "sort_gather": bench_sort_gather,
    "sort_packed": bench_sort_packed,
    "sort_packed_gather": bench_sort_packed_gather,
    "chunk_sort_ab": bench_chunk_sort_ab,
    # kernel tier A/Bs (kernels/registry.py): dispatch stream with
    # SPARK_RAPIDS_TPU_KERNELS on vs off, byte-parity asserted
    "kernel_sort_ab": lambda p: bench_kernel_ab(p, "sort"),
    "kernel_groupby_ab": lambda p: bench_kernel_ab(p, "groupby"),
    "kernel_transpose_ab": lambda p: bench_kernel_ab(p, "transpose"),
    "kernel_join_ab": lambda p: bench_kernel_ab(p, "join", 8_388_608),
    "kernel_sort100m_ab": lambda p: bench_kernel_ab(p, "sort", 100_007_936),
    "kernel_groupby100m_ab": lambda p: bench_kernel_ab(
        p, "groupby", 100_007_936
    ),
    "kernel_transpose100m_ab": lambda p: bench_kernel_ab(
        p, "transpose", 100_007_936
    ),
    "strings": bench_strings,
    "resident": bench_resident_chain,
    "bucketed_stream": bench_bucketed_stream,
    "fused_plan": bench_fused_plan,
    "pipelined_stream": bench_pipelined_stream,
    "serving_multiquery": bench_serving_multiquery,
    "spill_stream": bench_spill_stream,
    "parquet": bench_parquet_pipeline,
    "parquet_device": bench_parquet_device,
    "tpcds": bench_tpcds,
    # SF10 rung (round-4 VERDICT item 5: scale past SF1): 28.8M-row
    # store_sales star schema, streamed q5/q23/q64 on the chip
    "tpcds10": lambda p: bench_tpcds(p, scale=10.0),
}

# Every arm declares its ladder tier HERE — one table, walk order
# preserved by dict insertion order, statically verified by srt-check
# SRT007 against _SUBPROCESS_CONFIGS (an un-tiered arm fails lint:
# r04/r05 postmortem — both rounds ended rc=124 with parsed=null
# because the flat cheap-first walk spent its whole budget on A/B arms
# before the headline 100M groupby ever ran).
#
#   headline — tier 1: the cheapest arm of each workload that feeds
#              the published line plus one proof arm per subsystem;
#              walks first under the full budget.
#   extended — tier 2: refinement A/Bs; each needs _EXTENDED_FLOOR_S
#              of budget left to start, so a slow extended arm can no
#              longer eat the flush/Arrow-baseline window at the end.
#   manual   — runnable via `--config <arm>` only; never in the
#              budgeted walk (superseded by a batched/packed variant
#              but kept for one-off comparison runs).
_ARM_TIERS = {
    "groupby1m": "headline",
    "groupby16m_packed": "headline",
    "groupby16m_chunked": "headline",
    # the headline metric itself (cheapest winning 100M formulation)
    "groupby100m_flat_gather": "headline",
    # one proof arm per subsystem: fusion, serving, tiered memory
    "fused_plan": "headline",
    "serving_multiquery": "headline",
    "spill_stream": "headline",
    # kernel tier: the three cheapest A/B pairs prove the headline
    # claim (on vs off wall time + launch counters); the 100M variants
    # and the join pair refine in the extended tier
    "kernel_sort_ab": "headline",
    "kernel_groupby_ab": "headline",
    "kernel_transpose_ab": "headline",
    "groupby16m": "extended",
    # decisive cheap A/Bs first: plain-XLA gather arms compile fast,
    # the Pallas engines (slow Mosaic compiles) right after
    "groupby16m_flat_gather": "extended",
    "groupby16m_flat_sort": "extended",
    "groupby16m_gather": "extended",
    "chunk_sort_ab": "extended",
    "kernel_join_ab": "extended",
    "strings": "extended",
    "transpose": "extended",
    "resident": "extended",
    "bucketed_stream": "extended",
    "pipelined_stream": "extended",
    "parquet": "extended",
    "parquet_device": "extended",
    # 100M tier: likely winners first
    "kernel_groupby100m_ab": "extended",
    "kernel_sort100m_ab": "extended",
    "kernel_transpose100m_ab": "extended",
    "groupby100m_gather": "extended",
    "groupby100m": "extended",
    "groupby_highcard": "extended",
    "sort": "extended",
    "sort_packed_gather": "extended",
    "sort_packed": "extended",
    "sort_gather": "extended",
    "join_batched": "extended",
    "join_batched_packed": "extended",
    "tpcds": "extended",
    "tpcds10": "extended",
    # unbatched join: superseded in the walk by join_batched[_packed]
    "join": "manual",
    # slow Mosaic-compile / superseded formulations: each lost its A/B
    # to the gather arms above and alone costs most of the budget tail
    # (rc=124 postmortem: the walk ran flush to the deadline and the
    # mesh+Arrow tail never got a window). `--config <arm>` still runs
    # them for one-off comparisons.
    "groupby16m_packed_pallas32": "manual",
    "groupby100m_packed_pallas32": "manual",
    "groupby100m_packed": "manual",
    "groupby100m_chunked": "manual",
    # superseded by kernel_transpose_ab: the kernel tier runs the same
    # Pallas transpose pair through the dispatch plane with counters
    # and byte parity; the ad-hoc arm stays for one-off comparisons
    "transpose_pallas": "manual",
}
_HEADLINE_LADDER = tuple(
    a for a, t in _ARM_TIERS.items() if t == "headline"
)
_EXTENDED_LADDER = tuple(
    a for a, t in _ARM_TIERS.items() if t == "extended"
)
_LADDER = _HEADLINE_LADDER + _EXTENDED_LADDER

# the static pass catches a missing tier at lint time; this catches it
# the moment someone runs the bench instead
assert set(_ARM_TIERS) == set(_SUBPROCESS_CONFIGS), (
    "bench arms and _ARM_TIERS disagree: "
    f"{set(_ARM_TIERS) ^ set(_SUBPROCESS_CONFIGS)}"
)

_CONFIG_TIMEOUT_S = 1800
_EXTENDED_FLOOR_S = 300.0  # budget an extended arm needs left to start
# The ladder walk stops _TAIL_RESERVE_S before the budget deadline so
# the post-walk tail (two CPU-mesh stages + the Arrow denominator)
# always has a window: those stages are unbounded once started, and a
# walk that ran flush to the deadline left the driver's kill to land
# mid-stage (rc=124 with the headline stuck on the pre-tail emit).
# Each tail stage additionally needs its own floor of budget left to
# start at all.
_TAIL_RESERVE_S = 480.0
_MESH_STAGE_FLOOR_S = 150.0  # a CPU-mesh stage needs this left to start
_ARROW_FLOOR_S = 120.0       # the Arrow 100M baseline likewise
# TPC-DS-from-parquet mesh arm: opt-in AND capped to the same slice as
# the skew arms (it previously ran ~30min worst case under an 1800s cap
# and ate the whole budget tail — the r04 rc=124 postmortem)
_TPCDS_ARM_CAP_S = 900.0

# the chosen budget split, published as headline JSON "budget" so a
# postmortem of a skipped/killed arm can see the split the run chose
# without reverse-engineering it from env + source; set once in main()
_BUDGET_DOC = None


def _budget_doc(budget_s: float, source: str) -> dict:
    return {
        "budget_s": budget_s,
        "source": source,
        "tail_reserve_s": _TAIL_RESERVE_S,
        "config_timeout_s": _CONFIG_TIMEOUT_S,
        "extended_floor_s": _EXTENDED_FLOOR_S,
        "mesh_stage_floor_s": _MESH_STAGE_FLOOR_S,
        "arrow_floor_s": _ARROW_FLOOR_S,
        "mesh_arm_caps_s": {
            "skew_adaptive_ab": _arm_cap(900.0),
            "skew_zipf": _arm_cap(900.0),
            "tpcds": _arm_cap(_TPCDS_ARM_CAP_S),
        },
        "tpcds_opt_in": os.environ.get(
            "SRT_BENCH_MESH_TPCDS", ""
        ).strip().lower() in ("1", "true", "yes", "on"),
    }


def _run_one(name: str) -> None:
    """Child-process entry: run one config, print its JSON entries.

    Metrics collection is forced on so every entry carries a
    per-config "metrics" block (op counts, wire bytes, timers) that
    tools/analyze_bench.py correlates with the throughput numbers."""
    import jax

    _metrics_enable()
    platform = jax.devices()[0].platform
    out = _SUBPROCESS_CONFIGS[name](platform)
    got = out if isinstance(out, list) else [out]
    snap = _metrics_snapshot()
    prof = _profile_block()
    for g in got:
        g.setdefault("platform", platform)
        if snap is not None:
            g["metrics"] = snap
        if prof is not None:
            g["profile"] = prof
        print("BENCH_ENTRY " + json.dumps(g), flush=True)


def _spawn_config(entries, name: str, timeout_s: float = None):
    """Run one config in a fresh process (fresh TPU client)."""
    import os
    import subprocess

    timeout_s = timeout_s or _CONFIG_TIMEOUT_S
    _progress(f"config subprocess: {name}")
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", name],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        _progress(f"  TIMEOUT after {timeout_s:.0f}s")
        entries.append(_failure_record(
            name, f"timeout {timeout_s:.0f}s", exc_type="timeout",
            elapsed_s=time.time() - t0, retries=_failure_count(name),
        ))
        return None
    got = []
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_ENTRY "):
            got.append(json.loads(line[len("BENCH_ENTRY "):]))
    if not got:
        tail = (proc.stderr or "")[-400:]
        _progress(f"  FAILED rc={proc.returncode}: {tail}")
        entries.append(_failure_record(
            name, tail or f"rc={proc.returncode}",
            exc_type="SubprocessFailed", elapsed_s=time.time() - t0,
            retries=_failure_count(name),
        ))
        return None
    for g in got:
        _progress(f"  {g}")
    entries.extend(got)
    return got


# ---------------------------------------------------------------------------
# Self-healing state (round-4 VERDICT item 2): every successful config
# run is merged into a state file the moment it finishes, and a daemon
# mode keeps re-probing the flaky tunnel until a deadline. One outage
# can then no longer blank a round: the round-end main() reuses any
# entry the daemon captured while the chip was up.
# ---------------------------------------------------------------------------

import os

_STATE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "benchmarks",
    "bench_state.json",
)
_DAEMON_PID_PATH = _STATE_PATH + ".pid"


def _load_state() -> dict:
    try:
        with open(_STATE_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"entries": {}}


def _merge_state(config: str, got: list) -> None:
    """Merge one config's entries into the state file atomically
    (tmp+rename: a reader never sees a half-written file)."""
    state = _load_state()
    state["entries"][config] = {
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "results": got,
    }
    os.makedirs(os.path.dirname(_STATE_PATH), exist_ok=True)
    tmp = _STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, _STATE_PATH)


def _note_failure(config: str) -> None:
    state = _load_state()
    fails = state.setdefault("failures", {})
    fails[config] = fails.get(config, 0) + 1
    os.makedirs(os.path.dirname(_STATE_PATH), exist_ok=True)
    tmp = _STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, _STATE_PATH)


def _failure_count(config: str) -> int:
    return _load_state().get("failures", {}).get(config, 0)


def _state_results(config: str):
    got = _load_state()["entries"].get(config)
    if not got:
        return None
    results = [dict(r) for r in got["results"]]
    for r in results:
        r["source"] = "daemon_retry_loop"
        r["measured_at"] = got["measured_at"]
    return results


def _stop_daemon() -> None:
    """Kill a live daemon before a foreground ladder run: two processes
    contending for the single tunneled chip corrupt both timings."""
    import signal

    try:
        with open(_DAEMON_PID_PATH) as f:
            pid = int(f.read().strip())
        os.kill(pid, signal.SIGTERM)
        _progress(f"stopped bench daemon pid {pid}")
        time.sleep(2)
    except (OSError, ValueError):
        pass


def daemon(deadline_s: float, probe_every_s: float = 300.0) -> None:
    """Retry-until-deadline loop: probe the tunnel, run every ladder
    config that has no successful state entry yet (one subprocess each,
    merged into the state file as it lands), sleep, repeat. Exits at the
    deadline or when the ladder is complete."""
    deadline = time.time() + deadline_s
    os.makedirs(os.path.dirname(_STATE_PATH), exist_ok=True)
    with open(_DAEMON_PID_PATH, "w") as f:
        f.write(str(os.getpid()))
    try:
        while time.time() < deadline:
            pending = [c for c in _LADDER if not _state_results(c)]
            if not pending:
                _progress("daemon: ladder complete")
                return
            if not _probe_device():
                _progress(
                    f"daemon: device down; {len(pending)} pending; "
                    f"sleeping {probe_every_s:.0f}s"
                )
                time.sleep(min(probe_every_s, max(deadline - time.time(), 0)))
                continue
            progressed = False
            for cfg in pending:
                if time.time() >= deadline:
                    return
                if _failure_count(cfg) >= 3:
                    continue  # deterministic failure: stop burning chip time
                entries: list = []
                got = _spawn_config(entries, cfg)
                if got:
                    _merge_state(cfg, got)
                    progressed = True
                else:
                    _note_failure(cfg)
                    # crash/timeout with the device up: re-probe before
                    # trying anything else (the worker may be poisoned)
                    break
            if not progressed:
                time.sleep(min(probe_every_s, max(deadline - time.time(), 0)))
    finally:
        try:
            os.remove(_DAEMON_PID_PATH)
        except OSError:
            pass


def _tunnel_log(level: str, msg: str, **fields) -> None:
    """Tunnel events on the observability plane (utils/log.py `tunnel`
    channel, gated by SPARK_RAPIDS_TPU_LOG_LEVEL) — lazy import so the
    bench stays runnable from a checkout without the package installed."""
    try:
        from spark_rapids_jni_tpu.utils import log as _srt_log

        _srt_log.log(level, "tunnel", msg, **fields)
    except Exception:
        pass


def _probe_device(timeout_s: int = 150) -> bool:
    """Cheap liveness check: the axon tunnel sometimes hangs jax.devices()
    forever — probe in a killable subprocess before paying per-config
    timeouts."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        up = out.returncode == 0 and bool(out.stdout.strip())
        _tunnel_log(
            "INFO" if up else "WARN",
            "probe_up" if up else "probe_failed",
            rc=out.returncode,
        )
        _flight_note(
            "tunnel.probe_up" if up else "tunnel.probe_failed",
            out.returncode,
        )
        return up
    except subprocess.TimeoutExpired:
        _tunnel_log("WARN", "probe_timeout", timeout_s=timeout_s)
        _flight_note("tunnel.probe_timeout", timeout_s)
        return False


_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
)


def _published_headline():
    """Last round's published config-1 numbers: the fallback headline
    when this run is killed before (or without) measuring anything."""
    try:
        with open(_BASELINE_PATH) as f:
            pub = json.load(f).get("published", {})
        c1 = pub.get("config1_groupby", {})
        if "rows_per_s" in c1:
            return {
                "rows_per_s": float(c1["rows_per_s"]),
                "vs_arrow": float(c1.get("vs_arrow_cpu_same_shape", 0) or 0),
                "round": pub.get("round"),
            }
    except (OSError, json.JSONDecodeError, ValueError, TypeError):
        pass
    return None


# last headline line printed: the SIGTERM handler re-prints it so the
# FINAL stdout line is parseable JSON even when the driver's timeout
# fires mid-config (rounds ended rc=124, parsed=null twice because the
# kill landed between a progress line and the next emit)
_LAST_LINE = None


def _install_exit_handlers():
    """`timeout -k` sends SIGTERM before SIGKILL: use the grace window
    to flush the telemetry dumps (METRICS_DUMP + FLIGHT_DUMP — atexit
    never runs past os._exit) and re-print the last headline JSON as
    the final stdout line."""
    import signal

    def _on_term(signum, frame):  # pragma: no cover - signal path
        _flight_note("bench.sigterm", signum)
        line = _LAST_LINE
        if not line:
            # killed before the first emit (daemon stop / state read /
            # device probe can all hang into the kill window): the
            # final stdout line must STILL be parseable JSON
            line = json.dumps({
                "metric": "groupby_sum_100M_int64", "value": None,
                "unit": "rows/s", "vs_baseline": None,
                "platform": "unreachable",
                "headline_source": "sigterm_before_first_emit",
                "configs": [],
            })
        # headline FIRST, telemetry second: the re-printed line is
        # the one deliverable the driver parses, so nothing that
        # could conceivably block (file IO, lock acquisition in the
        # dump path) may run before it. Leading newline: the kill
        # may land mid-write of a large emit, and appending to a
        # torn partial line would make the final line unparseable.
        print("\n" + line, flush=True)
        _flush_telemetry()
        os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # pragma: no cover - non-main thread
        pass


def _emit(entries, platform, arrow_rows_per_s=None):
    """Print the ONE headline JSON line, complete with everything
    measured so far, and flush. Called once up front and again after
    every config lands (round-4 postmortem: the r4 run was SIGKILLed
    before its single end-of-run print, publishing nothing although
    per-config results existed — a kill at any instant must still
    leave the last flushed line parseable)."""
    med_big = None
    big_entry = None
    for e in entries:
        if (
            str(e.get("name", "")).startswith("groupby_sum_100M")
            and "seconds_median" in e
        ):
            s = e["seconds_median"]
            if med_big is None or s < med_big:
                med_big, big_entry = s, e
    pub = _published_headline()
    if med_big:
        rows_per_s = 100_000_000 / med_big
        # denominator: freshly measured Arrow if available, else the
        # one implied by last round's published numbers (same shape)
        if arrow_rows_per_s is None and pub and pub["vs_arrow"]:
            arrow_rows_per_s = pub["rows_per_s"] / pub["vs_arrow"]
        vs = rows_per_s / arrow_rows_per_s if arrow_rows_per_s else float("nan")
        # provenance must distinguish a this-run measurement from a
        # daemon-state entry captured at an earlier (possibly stale) time
        if big_entry.get("source") == "daemon_retry_loop":
            source = f"daemon_retry_loop({big_entry.get('measured_at')})"
        else:
            source = "measured"
    elif pub:
        rows_per_s, vs = pub["rows_per_s"], pub["vs_arrow"]
        source = f"published_round{pub['round']}"
    else:
        rows_per_s = vs = float("nan")
        source = "none"

    def _num(x, nd):
        # null, not NaN: json.dumps would emit the bare token `NaN`,
        # which strict parsers (jq, JSON.parse) reject
        return round(x, nd) if x == x else None

    global _LAST_LINE
    _LAST_LINE = json.dumps(
        {
            "metric": "groupby_sum_100M_int64",
            "value": _num(rows_per_s, 1),
            "unit": "rows/s",
            "vs_baseline": _num(vs, 3),
            "platform": platform,
            "headline_source": source,
            "drift": _drift_block(),
            "budget": _BUDGET_DOC,
            "configs": entries,
            "note": (
                "Line re-printed after every config (take the LAST "
                "parseable line): a timeout kill mid-ladder must not "
                "blank already-measured work. headline_source="
                "published_round{N} means no 100M groupby landed "
                "this run and value/vs_baseline echo BASELINE.json's "
                "published numbers. All device timings sync by host "
                "fetch (block_until_ready returns early on the "
                "tunneled platform); vs_baseline is CPU Arrow on "
                "the same 100M shape; configs[] carries the ladder "
                "with achieved GB/s vs HBM peak."
            ),
        }
    )
    print(_LAST_LINE, flush=True)


def main():
    # wall-clock budget (SRT_BENCH_BUDGET_S, default below the driver's
    # kill timeout; SRT_BENCH_DEADLINE_S kept as the legacy alias):
    # when exceeded, remaining configs are SKIPPED with structured
    # records and the headline line is still the last thing printed
    if "SRT_BENCH_BUDGET_S" in os.environ:
        budget_src = "env:SRT_BENCH_BUDGET_S"
    elif "SRT_BENCH_DEADLINE_S" in os.environ:
        budget_src = "env:SRT_BENCH_DEADLINE_S"
    else:
        budget_src = "default"
    budget_s = float(
        os.environ.get(
            "SRT_BENCH_BUDGET_S",
            os.environ.get("SRT_BENCH_DEADLINE_S", 3300),
        )
    )
    global _BUDGET_DOC
    _BUDGET_DOC = _budget_doc(budget_s, budget_src)
    t_start = time.time()
    deadline = t_start + budget_s
    # the arm walk's own deadline: earlier than the budget deadline by
    # the tail reserve, so the mesh stages and Arrow baseline always
    # get their window (see _TAIL_RESERVE_S)
    walk_deadline = deadline - _TAIL_RESERVE_S
    entries = []
    platform = "unreachable"
    _install_exit_handlers()  # SIGTERM re-prints the headline JSON
    _metrics_enable()  # every measured entry carries a "metrics" block
    # first emit BEFORE anything that can block (daemon stop sleeps,
    # state reads hit disk): from here on a kill at any instant leaves
    # a parseable headline as the last stdout line
    _emit(entries, platform)

    # Stop the daemon BEFORE reading state: a merge landing between the
    # prefill read and a later kill would otherwise be invisible here
    # while also suppressing the error entry for that config below.
    _stop_daemon()  # no chip contention with a live retry loop

    # Before anything that can hang (device probe, CPU-mesh subprocess,
    # Arrow baseline): publish the best line we can assemble from the
    # daemon state file + last round's published numbers.
    for key in _LADDER:
        got = _state_results(key)
        if got:
            entries.extend(got)
            if platform == "unreachable":
                platform = got[0].get("platform", platform)
    _emit(entries, platform)

    t_probe = time.time()
    probe_retries = 0
    probe_backoff_ms = 0.0
    alive = _probe_device()
    if not alive:
        # jittered backoff from the shared retry plane before the one
        # re-probe: a tunnel mid-restart often answers a beat later
        try:
            from spark_rapids_jni_tpu.utils import faults as _faults

            probe_backoff_ms = _faults.backoff_ms(1, "bench.probe")
        except Exception:
            probe_backoff_ms = 0.0
        _progress(
            "device probe failed (tunnel down/hung): retrying once "
            f"after {probe_backoff_ms:.0f}ms"
        )
        _flight_note("tunnel.probe_retry")
        time.sleep(probe_backoff_ms / 1e3)
        probe_retries = 1
        alive = _probe_device()
    probe_elapsed = time.time() - t_probe
    if alive:
        for i, key in enumerate(_LADDER):
            # headline arms may run to the walk deadline; extended arms
            # need a further reserve so cheap arms behind them survive
            floor = (
                0.0 if key in _HEADLINE_LADDER else _EXTENDED_FLOOR_S
            )
            if time.time() > walk_deadline - floor:
                # budget exhausted: skip the rest with structured
                # records instead of letting each one eat its own
                # timeout past the driver's kill deadline
                _progress(
                    f"bench budget ({budget_s:.0f}s) exhausted at tier "
                    f"{'1' if floor == 0.0 else '2'}; "
                    f"skipping {len(_LADDER) - i} remaining configs"
                )
                for later in _LADDER[i:]:
                    if not _state_results(later):
                        entries.append(_failure_record(
                            later, f"skipped: budget {budget_s:.0f}s "
                            "exhausted", exc_type="BudgetExceeded",
                            elapsed_s=time.time() - t_start, skipped=True,
                        ))
                break
            # drop the daemon-captured entries for this CONFIG KEY (by
            # the state file's own names — a rename of the workload
            # must not let a stale-shape entry survive the supersede)
            stale_names = {
                e.get("name") for e in (_state_results(key) or [])
            }
            fresh: list = []
            got = _spawn_config(
                fresh, key,
                timeout_s=min(_CONFIG_TIMEOUT_S,
                              max(walk_deadline - time.time(), 60)),
            )
            if got:
                _merge_state(key, got)
                entries = [
                    e for e in entries
                    if e.get("source") != "daemon_retry_loop"
                    or e.get("name") not in stale_names
                ]
                entries.extend(got)
                platform = got[0].get("platform", platform)
            elif not _state_results(key):
                entries.extend(fresh)  # the error entry
                # fast-fail ladder: an unreachable-smelling failure +
                # a failed re-probe means the tunnel is down — mark
                # every remaining device config skipped-unreachable
                # instead of timing each one out serially
                if (
                    fresh
                    and _unreachable_failure(fresh[-1])
                    and not _probe_device()
                ):
                    _progress(
                        "device lost mid-ladder; fast-failing "
                        f"{len(_LADDER) - i - 1} remaining configs"
                    )
                    _flight_note("device.unreachable", key)
                    for later in _LADDER[i + 1:]:
                        if not _state_results(later):
                            entries.append(_failure_record(
                                later,
                                "skipped: device unreachable "
                                f"(fast-fail after {key})",
                                exc_type="DeviceUnreachable",
                                elapsed_s=time.time() - t_start,
                                skipped=True,
                            ))
                    _emit(entries, platform)
                    break
            _emit(entries, platform)
    else:
        for key in _LADDER:
            if not _state_results(key):
                entries.append(_failure_record(
                    key, "device unreachable",
                    exc_type="DeviceUnreachable",
                    elapsed_s=probe_elapsed, retries=probe_retries,
                    backoff_ms=probe_backoff_ms, skipped=True,
                ))
        _emit(entries, platform)

    # CPU-mesh configs. Each arm gets its OWN wall-clock slice, clamped
    # to the budget remaining minus the Arrow reserve: an arm that
    # overruns is killed by its subprocess timeout and recorded as a
    # structured {type:"timeout"} failure — never again the r04 rc=124
    # where a stage started with minutes left and ran unbounded past
    # the driver's kill, leaving parsed=null. The TPC-DS-from-parquet
    # arm is additionally opt-in (SRT_BENCH_MESH_TPCDS=1) AND trimmed
    # to the same 900s slice as the skew arms (_TPCDS_ARM_CAP_S): under
    # its old 1800s cap it could eat the whole tail even when opted in,
    # and the skew arm already exercises the distributed exchange for
    # the headline. The split the run chose is published as the
    # headline's "budget" block.
    mesh_arms = [
        # the adaptive-skew A/B first: it carries the headline skew
        # block (seconds / recv-buffer / RSS deltas, splitting on vs
        # off), so it must land before any budget-tail exhaustion
        ("config 4: adaptive skew split A/B, 8-device CPU mesh",
         bench_mesh_skew_adaptive, _arm_cap(900.0)),
        ("config 4: distributed zipf skew, 8-device CPU mesh",
         bench_distributed_skew, _arm_cap(900.0)),
    ]
    tpcds_name = "config 4: TPC-DS q5/q23/q64 from parquet, 8-dev mesh"
    if os.environ.get("SRT_BENCH_MESH_TPCDS", "").strip().lower() in (
        "1", "true", "yes", "on"
    ):
        mesh_arms.append((tpcds_name, bench_tpcds_distributed,
                          _arm_cap(_TPCDS_ARM_CAP_S)))
    else:
        _progress(
            f"skipping {tpcds_name}: opt-in arm "
            "(set SRT_BENCH_MESH_TPCDS=1)"
        )
        entries.append(_failure_record(
            tpcds_name,
            "skipped: opt-in arm (SRT_BENCH_MESH_TPCDS unset)",
            exc_type="OptInSkipped", skipped=True,
        ))
    for mesh_name, mesh_fn, arm_cap_s in mesh_arms:
        slice_s = min(arm_cap_s, deadline - time.time() - _ARROW_FLOOR_S)
        if slice_s < _MESH_STAGE_FLOOR_S:
            _progress(f"skipping {mesh_name}: budget tail exhausted")
            entries.append(_failure_record(
                mesh_name,
                f"skipped: budget {budget_s:.0f}s exhausted",
                exc_type="BudgetExceeded",
                elapsed_s=time.time() - t_start, skipped=True,
            ))
            _emit(entries, platform)
            continue
        _guard(
            entries, mesh_name,
            lambda fn=mesh_fn, s=slice_s: fn(timeout_s=s),
        )
        _emit(entries, platform)

    # fresh Arrow denominator last: it only refines vs_baseline
    arrow = None
    if time.time() < deadline - _ARROW_FLOOR_S:
        _progress("arrow baseline 100M")
        try:
            arrow = arrow_baseline(100_000_000)
        except Exception:  # pragma: no cover
            arrow = None
    else:
        _progress("skipping arrow baseline: budget tail exhausted")
    _emit(entries, platform, arrow_rows_per_s=arrow)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--one":
        _run_one(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--daemon":
        # python bench.py --daemon <deadline_seconds> [probe_every_s]
        dl = float(sys.argv[2]) if len(sys.argv) >= 3 else 6 * 3600
        every = float(sys.argv[3]) if len(sys.argv) >= 4 else 300.0
        daemon(dl, every)
    else:
        try:
            main()
        except Exception:
            # exit-clean guarantee: tracebacks go to stderr and the
            # FINAL stdout line stays the last headline JSON
            import traceback

            traceback.print_exc()
            if _LAST_LINE:
                print(_LAST_LINE, flush=True)
            sys.exit(1)
