"""Benchmark: hash groupby-sum, 1M int64 rows (BASELINE.json config 1).

Measures the device groupby (sort-based, jitted, capped variant — no host
syncs inside the timed region) against the CPU Arrow reference
(pyarrow.Table.group_by), the baseline named in BASELINE.json. Prints one
JSON line:
  {"metric": ..., "value": rows/sec on device, "unit": "rows/s",
   "vs_baseline": device_throughput / arrow_throughput}
"""

import json
import time

import numpy as np


def main():
    import jax

    import spark_rapids_jni_tpu as srt
    from spark_rapids_jni_tpu.column import Column, Table
    from spark_rapids_jni_tpu.ops.groupby import (
        GroupbyAgg,
        groupby_aggregate_capped,
    )

    n = 1_000_000
    n_keys = 10_000
    rng = np.random.default_rng(42)
    k = rng.integers(0, n_keys, n, dtype=np.int64)
    v = rng.integers(-1000, 1000, n, dtype=np.int64)

    table = Table(
        [Column.from_numpy(k), Column.from_numpy(v)], ["k", "v"]
    )
    # materialize on device before timing
    jax.block_until_ready(table.columns[0].data)

    step = jax.jit(
        lambda t: groupby_aggregate_capped(
            t,
            ["k"],
            [GroupbyAgg("v", "sum"), GroupbyAgg("v", "count")],
            num_segments=n_keys,
        )
    )
    # warmup/compile
    out = step(table)
    jax.block_until_ready(out)

    reps = 10
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step(table)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    device_rows_per_s = n / best

    # CPU Arrow baseline
    try:
        import pyarrow as pa

        atbl = pa.table({"k": k, "v": v})
        # warmup
        atbl.group_by("k").aggregate([("v", "sum"), ("v", "count")])
        abest = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            atbl.group_by("k").aggregate([("v", "sum"), ("v", "count")])
            abest = min(abest, time.perf_counter() - t0)
        arrow_rows_per_s = n / abest
        vs = device_rows_per_s / arrow_rows_per_s
    except ImportError:  # pragma: no cover
        vs = float("nan")

    # sanity: totals must agree
    agg, ngroups = out
    total = int(np.asarray(agg["sum_v"].data)[: int(ngroups)].sum())
    assert total == int(v.sum()), "groupby-sum mismatch vs numpy"

    print(
        json.dumps(
            {
                "metric": "groupby_sum_1M_int64",
                "value": round(device_rows_per_s, 1),
                "unit": "rows/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
