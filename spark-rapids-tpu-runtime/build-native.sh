#!/usr/bin/env bash
# Configure-once native build — the build-libcudf.xml discipline
# (build-libcudf.xml:23-30): only rerun CMake configure when no
# CMakeCache.txt exists or NATIVE_BUILD_CONFIGURE=true, so incremental
# `mvn verify` runs reuse the build tree (CONTRIBUTING.md:46-55
# rationale in the reference).
set -euo pipefail

here="$(cd "$(dirname "$0")" && pwd)"
repo="$(cd "$here/.." && pwd)"
build="$repo/build"

if [[ ! -f "$build/CMakeCache.txt" || "${NATIVE_BUILD_CONFIGURE:-false}" == "true" ]]; then
  cmake -S "$repo/src" -B "$build" \
    -DSRT_WERROR="${SRT_WERROR:-ON}" \
    -DCMAKE_BUILD_TYPE=Release
fi
cmake --build "$build" --parallel "${CPP_PARALLEL_LEVEL:-4}"
