"""Automated minimizer for the fused-join TPU fault (XLA_BUG_REPORT.md).

Runs each graph variant in its OWN subprocess (a worker crash poisons
the whole PJRT client, so in-process bisection is impossible) and
appends a results table to the bug report. Designed to run unattended
when the flaky tunnel is up:

    python tools/xla_fault_minimize.py            # full matrix
    python tools/xla_fault_minimize.py --variant single_word 32000000
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

VARIANTS = {
    # name -> python source run in a fresh process; prints PASS total
    "full": """
lw, rw = words()
perm = jnp.lexsort([jnp.ones_like(rw), rw][::-1])
sw = [jnp.ones_like(rw)[perm], rw[perm]]
qw = [jnp.ones_like(lw), lw]
lo = _lex_searchsorted(sw, qw, "left")
hi = _lex_searchsorted(sw, qw, "right")
out = jnp.where(jnp.ones_like(lw, dtype=bool), hi - lo, 0).sum()
""",
    "single_word": """
lw, rw = words()
srt = jax.lax.sort((rw,), num_keys=1)[0]
lo = _lex_searchsorted([srt], [lw], "left")
hi = _lex_searchsorted([srt], [lw], "right")
out = (hi - lo).sum()
""",
    "one_search": """
lw, rw = words()
perm = jnp.lexsort([jnp.ones_like(rw), rw][::-1])
sw = [jnp.ones_like(rw)[perm], rw[perm]]
qw = [jnp.ones_like(lw), lw]
lo = _lex_searchsorted(sw, qw, "left")
out = lo.sum()
""",
    "jnp_searchsorted": """
lw, rw = words()
srt = jax.lax.sort((rw,), num_keys=1)[0]
lo = jnp.searchsorted(srt, lw, side="left")
hi = jnp.searchsorted(srt, lw, side="right")
out = (hi - lo).sum()
""",
    "no_perm_gather": """
lw, rw = words()
srt = jax.lax.sort((jnp.ones_like(rw), rw), num_keys=2)[1]
lo = _lex_searchsorted([jnp.ones_like(srt), srt],
                       [jnp.ones_like(lw), lw], "left")
hi = _lex_searchsorted([jnp.ones_like(srt), srt],
                       [jnp.ones_like(lw), lw], "right")
out = (hi - lo).sum()
""",
}

_TEMPLATE = """
import spark_rapids_jni_tpu  # x64 on before arrays exist
import jax, jax.numpy as jnp, numpy as np
from spark_rapids_jni_tpu.ops.join import _lex_searchsorted

n = {n}
def words():
    rng = np.random.default_rng(11)
    sign = jnp.uint64(0x8000000000000000)
    kl = jnp.asarray(rng.integers(0, n, n, dtype=np.int64))
    kr = jnp.asarray(rng.integers(0, n, n, dtype=np.int64))
    return kl.astype(jnp.uint64) ^ sign, kr.astype(jnp.uint64) ^ sign

def graph():
{body}
    return out

val = jax.jit(graph)()
print("PASS", int(np.asarray(val.ravel()[-1:])[0]))
"""


def run_variant(name: str, n: int, timeout_s: int = 900) -> dict:
    body = "\n".join(
        "    " + line for line in VARIANTS[name].strip().splitlines()
    )
    code = _TEMPLATE.format(n=n, body=body)
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
        status = (
            "pass"
            if out.returncode == 0 and "PASS" in out.stdout
            else "CRASH"
        )
        detail = (out.stderr or "")[-200:] if status == "CRASH" else ""
    except subprocess.TimeoutExpired:
        status, detail = "timeout", ""
    return {
        "variant": name, "n": n, "status": status,
        "seconds": round(time.time() - t0, 1), "detail": detail,
    }


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--variant":
        print(json.dumps(run_variant(sys.argv[2], int(sys.argv[3]))))
        return
    results = []
    # the matrix: each variant at the faulting size, then a threshold
    # bisection on whichever smallest variant still crashes
    for name in VARIANTS:
        r = run_variant(name, 32_000_000)
        print(json.dumps(r), flush=True)
        results.append(r)
    crashing = [r["variant"] for r in results if r["status"] == "CRASH"]
    if crashing:
        name = crashing[-1]  # most-minimized crashing variant
        lo, hi = 16_000_000, 32_000_000
        while hi - lo > 2_000_000:
            mid = (lo + hi) // 2
            r = run_variant(name, mid)
            print(json.dumps(r), flush=True)
            results.append(r)
            if r["status"] == "CRASH":
                hi = mid
            else:
                lo = mid
    with open(__file__.replace(
        "xla_fault_minimize.py", "XLA_BUG_REPORT.md"
    ), "a") as f:
        f.write(
            "\n## Automated minimize run "
            + time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
            + "\n\n| variant | n | status | s |\n|---|---|---|---|\n"
        )
        for r in results:
            f.write(
                f"| {r['variant']} | {r['n']} | {r['status']} "
                f"| {r['seconds']} |\n"
            )


if __name__ == "__main__":
    main()
