"""Standalone repro: TPU worker kernel fault in the fused join-count graph.

On the tunneled v5e ('axon') platform, ONE jit containing
  64-bit key normalization -> jnp.lexsort -> two lex-searchsorted
  binary-search loops -> masked sum
crashes the TPU worker ("TPU worker process crashed or restarted...
kernel fault") at n >= 32M rows. Each piece is fine in isolation at the
same or larger sizes (lexsort alone passes at 100M, the searchsorted
loop alone passes at 32M, and the identical graph passes at 16M or with
32-bit keys), so this is an XLA TPU codegen/runtime fault of the fused
graph, not HBM exhaustion.

Consequence for the framework: ops/join.py:inner_join_batched sorts the
build side in its own jit and probes in 16M-row chunks — the same
batching discipline the reference applies at INT_MAX bytes
(row_conversion.cu:505-511) — and bench.py uses it for the 100M config.

Run: python tools/xla_join_fault_repro.py 32000000   # crashes the worker
     python tools/xla_join_fault_repro.py 16000000   # passes
"""

import sys

import spark_rapids_jni_tpu  # noqa: F401  (enables x64 before array creation)
import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.ops.join import _lex_searchsorted


def main(n: int) -> None:
    rng = np.random.default_rng(11)
    sign = jnp.uint64(0x8000000000000000)
    kl = jnp.asarray(rng.integers(0, n, n, dtype=np.int64))
    kr = jnp.asarray(rng.integers(0, n, n, dtype=np.int64))
    jax.block_until_ready(kr)

    def count(kld, krd):
        lw = kld.astype(jnp.uint64) ^ sign
        rw = krd.astype(jnp.uint64) ^ sign
        ones_r = jnp.ones_like(rw)
        perm = jnp.lexsort([ones_r, rw][::-1])
        sw = [ones_r[perm], rw[perm]]
        qw = [jnp.ones_like(lw), lw]
        lo = _lex_searchsorted(sw, qw, "left")
        hi = _lex_searchsorted(sw, qw, "right")
        return jnp.where(jnp.ones_like(lw, dtype=bool), hi - lo, 0).sum()

    print("total:", int(jax.jit(count)(kl, kr)))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 32_000_000)
