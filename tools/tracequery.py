"""Merge per-process flight dumps and render ONE request's trace.

The trace-context plane (utils/tracing.py) stamps every span the
serving daemon, the scheduler executors, the pipeline workers and the
mesh tier record with the request's W3C-style trace id; each process
involved writes its own ``SPARK_RAPIDS_TPU_FLIGHT_DUMP``. This tool is
the read side: give it the dumps, and it

* ``--list``           enumerates the trace ids present across all
                       dumps (process count, span count, wall span);
* ``--trace <id>``     renders that request's span tree — every span /
                       instant from every process, aligned onto one
                       clock and indented by nesting, so queue wait,
                       admission, compile, per-segment execute and
                       exchange launches read top-to-bottom;
* ``--chrome out.json`` (with ``--trace``) writes a Chrome-trace /
                       Perfetto JSON filtered to that one trace id,
                       one process track per dump.

Clock alignment reuses the flight dump's wall-clock anchors
(``epoch_ns`` + ``anchor_perf_ns``): each dump's monotonic timestamps
shift to wall time and the earliest event across all dumps becomes the
shared origin — the ``tracing.merge_chrome_traces`` discipline. Trace
attribution is per dump (thread ids and seq numbers are process-local,
so :func:`assign_trace_ids` must run before any merge). A ``<id>``
prefix is accepted anywhere a full 32-hex trace id is expected.

Usage:
    python tools/tracequery.py --list server.json worker*.json
    python tools/tracequery.py --trace 4bf92f35 server.json worker.json
    python tools/tracequery.py --trace 4bf92f35 --chrome req.trace.json \
        server.json worker.json

Tolerates older flight formats the way the exporter does: non-dict
rows are dropped, missing keys degrade, dumps without anchors merge
unshifted.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
# pure-stdlib analysis: keep the import off the accelerator plugin
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spark_rapids_jni_tpu.utils.tracing import (  # noqa: E402
    assign_trace_ids,
    merge_chrome_traces,
    trace_span_records,
)


def load_dump(path: str) -> dict:
    """One flight dump, parsed whole or line-wise (the trace2chrome
    discipline: a dump embedded in log output still loads)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
        for line in text.splitlines():
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a flight dump (expected object)")
    return doc


def _proc_label(d: dict) -> str:
    name = f"{d.get('host', '?')}:{d.get('pid', '?')}"
    sid = d.get("session_id")
    if sid:
        name = f"{name} [{str(sid)[:8]}]"
    return name


def _shift_ns(d: dict) -> int:
    """perf-counter -> wall-clock shift for one dump (0 when the dump
    predates the anchors — it merges unshifted rather than failing)."""
    epoch, anchor = d.get("epoch_ns"), d.get("anchor_perf_ns")
    if epoch is None or anchor is None:
        return 0
    return int(epoch) - int(anchor)


def _dump_events(d: dict) -> list:
    return [
        e for e in (d.get("events") or [])
        if isinstance(e, dict) and "t_ns" in e
    ]


def collect(paths) -> list:
    """[(dump, trace-tagged events)] — attribution runs PER DUMP:
    thread ids and seq numbers are process-local."""
    out = []
    for p in paths:
        d = load_dump(p)
        d["_path"] = p
        out.append((d, assign_trace_ids(_dump_events(d))))
    return out


def resolve_trace_id(tagged_dumps, prefix: str) -> str:
    """Expand a trace-id prefix to the unique full id it names."""
    want = prefix.strip().lower()
    hits = sorted({
        e["trace_id"]
        for _, evs in tagged_dumps
        for e in evs
        if e.get("trace_id", "").startswith(want)
    })
    if not hits:
        raise SystemExit(
            f"tracequery: no trace matching {prefix!r} in the given "
            "dumps (was SPARK_RAPIDS_TPU_TRACE/FLIGHT on end to end?)"
        )
    if len(hits) > 1:
        raise SystemExit(
            f"tracequery: trace prefix {prefix!r} is ambiguous: "
            + ", ".join(h[:12] for h in hits)
        )
    return hits[0]


def list_traces(tagged_dumps) -> list:
    """Summaries of every trace across the dumps, earliest first."""
    traces: dict = {}
    for d, evs in tagged_dumps:
        shift = _shift_ns(d)
        proc = _proc_label(d)
        for e in evs:
            tid_ = e.get("trace_id")
            if not tid_:
                continue
            t = traces.setdefault(tid_, {
                "trace_id": tid_, "procs": set(), "events": 0,
                "first_ns": None, "last_ns": None, "names": set(),
            })
            t["procs"].add(proc)
            t["events"] += 1
            w = e.get("t_ns", 0) + shift
            t["first_ns"] = w if t["first_ns"] is None else min(
                t["first_ns"], w
            )
            t["last_ns"] = w if t["last_ns"] is None else max(
                t["last_ns"], w
            )
            t["names"].add(e.get("name", "?"))
    out = []
    for t in sorted(traces.values(), key=lambda t: t["first_ns"] or 0):
        out.append({
            "trace_id": t["trace_id"],
            "processes": sorted(t["procs"]),
            "events": t["events"],
            "wall_ms": round((t["last_ns"] - t["first_ns"]) / 1e6, 3),
            "names": sorted(t["names"]),
        })
    return out


def merged_records(tagged_dumps, trace_id: str) -> list:
    """One trace's span/instant records from every dump, on the shared
    wall clock, sorted by start time."""
    recs = []
    for d, _ in tagged_dumps:
        shift = _shift_ns(d)
        proc = _proc_label(d)
        for r in trace_span_records(_dump_events(d), trace_id):
            r = dict(r)
            r["proc"] = proc
            r["t_ns"] = r.get("t_ns", 0) + shift
            recs.append(r)
    recs.sort(key=lambda r: (r.get("t_ns", 0), r.get("proc", "")))
    return recs


def render_tree(recs, trace_id: str) -> str:
    """The span tree: indentation = interval containment per
    (process, thread) lane; offsets are ms from the trace origin."""
    if not recs:
        return f"trace {trace_id}: no spans"
    origin = min(r.get("t_ns", 0) for r in recs)
    lines = [f"trace {trace_id}"]
    stacks: dict = {}  # (proc, tid) -> [end_ns, ...] of open spans
    for r in recs:
        key = (r.get("proc"), r.get("tid"))
        stack = stacks.setdefault(key, [])
        t = r.get("t_ns", 0)
        while stack and stack[-1] <= t:
            stack.pop()
        depth = len(stack)
        off = (t - origin) / 1e6
        if r.get("instant"):
            tail = "· " + str(r.get("name", "?"))
            if r.get("arg") is not None:
                tail += f" [{r['arg']}]"
        elif r.get("unterminated"):
            tail = f"{r.get('name', '?')} (unterminated)"
        else:
            dur = r.get("dur_ms")
            tail = str(r.get("name", "?"))
            if dur is not None:
                tail += f" ({dur:.3f} ms)"
                stack.append(t + int(dur * 1e6))
            if r.get("error") is not None:
                tail += f" !{r['error']}"
        lines.append(
            f"{off:>12.3f} ms  {r.get('proc', '?'):<28} "
            f"{'  ' * depth}{tail}"
        )
    return "\n".join(lines)


def chrome_for_trace(tagged_dumps, trace_id: str) -> dict:
    """Merged Chrome trace filtered to one trace id (per-dump filter
    BEFORE the merge, so B/E pairing and process tracks stay intact)."""
    filtered = []
    for d, evs in tagged_dumps:
        keep = [
            {k: v for k, v in e.items() if k != "trace_id"}
            for e in evs
            if e.get("trace_id") == trace_id
        ]
        if keep:
            filtered.append(dict(d, events=keep))
    return merge_chrome_traces(filtered)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge flight dumps; render one request's trace"
    )
    ap.add_argument("dumps", nargs="+", help="flight dump files")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="list the trace ids across all dumps")
    ap.add_argument("--trace", help="trace id (or unique prefix)")
    ap.add_argument("--chrome", metavar="OUT",
                    help="with --trace: write a filtered Chrome trace")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output instead of the tree")
    args = ap.parse_args(argv)
    tagged = collect(args.dumps)
    if not any(evs for _, evs in tagged):
        print(
            "tracequery: no flight events in the given dumps "
            "(was SPARK_RAPIDS_TPU_FLIGHT_DUMP enabled?)",
            file=sys.stderr,
        )
        return 1
    if args.list_ or not args.trace:
        for t in list_traces(tagged):
            if args.json:
                print(json.dumps(t, sort_keys=True))
            else:
                print(
                    f"{t['trace_id']}  procs={len(t['processes'])} "
                    f"events={t['events']} wall={t['wall_ms']}ms  "
                    + " ".join(t["names"][:6])
                )
        return 0
    trace_id = resolve_trace_id(tagged, args.trace)
    if args.chrome:
        trace = chrome_for_trace(tagged, trace_id)
        with open(args.chrome, "w") as f:
            json.dump(trace, f, indent=1, sort_keys=True)
            f.write("\n")
        n = sum(
            1 for e in trace["traceEvents"] if e.get("ph") == "X"
        )
        print(
            f"wrote {args.chrome}: {n} spans of trace {trace_id[:12]} "
            "— open at https://ui.perfetto.dev"
        )
        return 0
    recs = merged_records(tagged, trace_id)
    if args.json:
        for r in recs:
            print(json.dumps(r, sort_keys=True))
    else:
        print(render_tree(recs, trace_id))
    return 0


if __name__ == "__main__":
    sys.exit(main())
